"""crypto::, parse::, encoding::, geo::, bytes::, session::, sequence::,
value::, search::, http::, api:: families (reference: core/src/fnc/)."""

from __future__ import annotations

import hashlib
import hmac as _hmac
import math
import secrets

from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import _arr, _num, _str, register
from surrealdb_tpu.val import NONE, Geometry, RecordId, render, to_json


# -- crypto -------------------------------------------------------------------


@register("crypto::md5")
def _md5(args, ctx):
    return hashlib.md5(_str(args[0], "crypto::md5", 1).encode()).hexdigest()


@register("crypto::sha1")
def _sha1(args, ctx):
    return hashlib.sha1(_str(args[0], "crypto::sha1", 1).encode()).hexdigest()


@register("crypto::sha256")
def _sha256(args, ctx):
    return hashlib.sha256(_str(args[0], "crypto::sha256", 1).encode()).hexdigest()


@register("crypto::joaat")
def _joaat(args, ctx):
    """Jenkins one-at-a-time hash (u32 decimal, reference fnc/crypto)."""
    data = _str(args[0], "crypto::joaat", 1).encode()
    h = 0
    for b in data:
        h = (h + b) & 0xFFFFFFFF
        h = (h + (h << 10)) & 0xFFFFFFFF
        h ^= h >> 6
    h = (h + (h << 3)) & 0xFFFFFFFF
    h ^= h >> 11
    h = (h + (h << 15)) & 0xFFFFFFFF
    return h


@register("crypto::sha512")
def _sha512(args, ctx):
    return hashlib.sha512(_str(args[0], "crypto::sha512", 1).encode()).hexdigest()


@register("crypto::blake3")
def _blake3(args, ctx):
    from surrealdb_tpu.utils.blake3 import blake3_hex

    return blake3_hex(_str(args[0], "crypto::blake3", 1).encode())


# password hashing: argon2id (via the argon2 package, like the reference's
# user passhashes), pbkdf2 and scrypt; bcrypt falls back to pbkdf2


def _pbkdf2_hash(pw: str, rounds=600_000) -> str:
    salt = secrets.token_bytes(16)
    dk = hashlib.pbkdf2_hmac("sha256", pw.encode(), salt, rounds)
    return f"$pbkdf2-sha256$i={rounds}${salt.hex()}${dk.hex()}"


def _pbkdf2_compare(h: str, pw: str) -> bool:
    try:
        _, alg, iters, salt, dk = h.split("$")
        rounds = int(iters.split("=")[1])
        got = hashlib.pbkdf2_hmac("sha256", pw.encode(), bytes.fromhex(salt), rounds)
        return _hmac.compare_digest(got.hex(), dk)
    except (ValueError, IndexError):
        return False


def _scrypt_hash(pw: str) -> str:
    salt = secrets.token_bytes(16)
    dk = hashlib.scrypt(pw.encode(), salt=salt, n=2**14, r=8, p=1)
    return f"$scrypt$n=16384,r=8,p=1${salt.hex()}${dk.hex()}"


def _scrypt_compare(h: str, pw: str) -> bool:
    try:
        parts = h.split("$")
        salt, dk = parts[3], parts[4]
        got = hashlib.scrypt(pw.encode(), salt=bytes.fromhex(salt), n=2**14, r=8, p=1)
        return _hmac.compare_digest(got.hex(), dk)
    except (ValueError, IndexError):
        return False


@register("crypto::pbkdf2::generate")
def _pbkdf2_gen(args, ctx):
    return _pbkdf2_hash(_str(args[0], "f", 1))


@register("crypto::pbkdf2::compare")
def _pbkdf2_cmp(args, ctx):
    return _pbkdf2_compare(_str(args[0], "f", 1), _str(args[1], "f", 2))


@register("crypto::scrypt::generate")
def _scrypt_gen(args, ctx):
    return _scrypt_hash(_str(args[0], "f", 1))


@register("crypto::scrypt::compare")
def _scrypt_cmp(args, ctx):
    return _scrypt_compare(_str(args[0], "f", 1), _str(args[1], "f", 2))


def _argon2_hash(pw: str) -> str:
    from argon2 import PasswordHasher

    return PasswordHasher().hash(pw)


def _argon2_compare(h: str, pw: str) -> bool:
    from argon2 import PasswordHasher
    from argon2.exceptions import (
        InvalidHashError,
        VerificationError,
        VerifyMismatchError,
    )

    try:
        return PasswordHasher().verify(h, pw)
    except (VerifyMismatchError, VerificationError, InvalidHashError):
        return False


@register("crypto::argon2::generate")
def _argon2_gen(args, ctx):
    return _argon2_hash(_str(args[0], "f", 1))


@register("crypto::argon2::compare")
def _argon2_cmp(args, ctx):
    return _argon2_compare(_str(args[0], "f", 1), _str(args[1], "f", 2))


@register("crypto::bcrypt::generate")
def _bcrypt_gen(args, ctx):
    return _pbkdf2_hash(_str(args[0], "f", 1))


@register("crypto::bcrypt::compare")
def _bcrypt_cmp(args, ctx):
    return _pbkdf2_compare(_str(args[0], "f", 1), _str(args[1], "f", 2))


def password_hash(pw: str) -> str:
    # user passhashes are argon2id, like the reference (iam user defs)
    return _argon2_hash(pw)


def password_compare(h: str, pw: str) -> bool:
    if h.startswith("$argon2"):
        return _argon2_compare(h, pw)
    if h.startswith("$pbkdf2"):
        return _pbkdf2_compare(h, pw)
    if h.startswith("$scrypt"):
        return _scrypt_compare(h, pw)
    return False


# -- parse --------------------------------------------------------------------


def _email_parts(s):
    """RFC-style address validation (reference addr crate): returns
    (local, host) or None when the address is invalid."""
    import re as _re

    in_q = False
    at = -1
    for i, ch in enumerate(s):
        if ch == '"':
            in_q = not in_q
        elif ch == "@" and not in_q:
            at = i
    if in_q or at <= 0 or at == len(s) - 1:
        return None
    local, dom = s[:at], s[at + 1:]
    if local.startswith('"'):
        if not (local.endswith('"') and len(local) >= 2):
            return None
    else:
        t = local
        if not t or t[0] == "." or t[-1] == "." or ".." in t:
            return None
        if not _re.fullmatch(r"[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+", t):
            return None
    if dom.startswith("[") and dom.endswith("]"):
        host = dom[1:-1]
        # only IPv4 address literals are accepted
        if not _re.fullmatch(
            r"(25[0-5]|2[0-4]\d|1\d\d|[1-9]?\d)"
            r"(\.(25[0-5]|2[0-4]\d|1\d\d|[1-9]?\d)){3}", host
        ):
            return None
        return local, host
    labels = dom.split(".")
    for lb in labels:
        if not lb or lb[0] == "-" or lb[-1] == "-":
            return None
        if not _re.fullmatch(r"[A-Za-z0-9-]+", lb):
            return None
    return local, dom


@register("parse::email::host")
def _email_host(args, ctx):
    parts = _email_parts(_str(args[0], "parse::email::host", 1))
    return parts[1] if parts else NONE


@register("parse::email::user")
def _email_user(args, ctx):
    parts = _email_parts(_str(args[0], "parse::email::user", 1))
    return parts[0] if parts else NONE


class _UrlNone:
    """Unparseable URL: every component reads NONE."""

    hostname = None
    fragment = ""
    path = ""
    query = ""
    scheme = ""
    port = None


def _url(args, fname):
    from urllib.parse import quote, urlparse

    from surrealdb_tpu.val import render as _r

    v = args[0]
    if not isinstance(v, str):
        raise SdbError(
            f"Incorrect arguments for function {fname}(). Argument 1 was "
            f"the wrong type. Expected `string` but found `{_r(v)}`"
        )
    try:
        u = urlparse(v)
    except ValueError:
        return _UrlNone()
    if not u.scheme or not (u.netloc or u.path):
        return _UrlNone()

    class _U:
        hostname = u.hostname
        fragment = u.fragment
        scheme = u.scheme
        # WHATWG: special schemes normalize an empty path to "/" and
        # resolve . / .. segments
        def _norm_path(pth):
            if not pth:
                return ""
            out = []
            segs = pth.split("/")
            for i, seg in enumerate(segs):
                if seg == ".":
                    if i == len(segs) - 1:
                        out.append("")
                    continue
                if seg == "..":
                    if len(out) > 1:
                        out.pop()
                    if i == len(segs) - 1:
                        out.append("")
                    continue
                out.append(seg)
            return "/".join(out)

        path = _norm_path(u.path) or (
            "/" if u.scheme in ("http", "https", "ws", "wss", "ftp", "file")
            else ""
        )
        # query serializes percent-encoded; existing %XX escapes are
        # preserved (url crate form serialization)
        query = quote(u.query, safe="=&,-._~!$*+;:@/?%")

        try:
            port = u.port
        except ValueError:
            port = None

    return _U()


@register("parse::url::domain")
def _url_domain(args, ctx):
    h = _url(args, "parse::url::domain").hostname
    return h if h else NONE


@register("parse::url::host")
def _url_host(args, ctx):
    h = _url(args, "parse::url::host").hostname
    return h if h else NONE


@register("parse::url::fragment")
def _url_fragment(args, ctx):
    f = _url(args, "parse::url::fragment").fragment
    return f if f else NONE


@register("parse::url::path")
def _url_path(args, ctx):
    return _url(args, "parse::url::path").path or NONE


@register("parse::url::port")
def _url_port(args, ctx):
    p = _url(args, "parse::url::port").port
    return p if p is not None else NONE


@register("parse::url::query")
def _url_query(args, ctx):
    q = _url(args, "parse::url::query").query
    return q if q else NONE


@register("parse::url::scheme")
def _url_scheme(args, ctx):
    s = _url(args, "parse::url::scheme").scheme
    return s if s else NONE


# -- encoding -----------------------------------------------------------------


@register("encoding::base64::encode")
def _b64_encode(args, ctx):
    import base64

    v = args[0]
    data = v if isinstance(v, (bytes, bytearray)) else _str(v, "f").encode()
    out = base64.b64encode(bytes(data)).decode()
    padded = len(args) > 1 and args[1] is True
    return out if padded else out.rstrip("=")


def _to_jsonable(v):
    from surrealdb_tpu.exec.operators import to_string
    from surrealdb_tpu.val import SSet

    if v is NONE or v is None:
        return None
    if isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, list):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, SSet):
        return [_to_jsonable(x) for x in v.items]
    if isinstance(v, dict):
        return {k: _to_jsonable(x) for k, x in v.items()}
    return to_string(v)


def _from_jsonable(v):
    if v is None:
        return None
    if isinstance(v, list):
        return [_from_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _from_jsonable(x) for k, x in v.items()}
    return v


@register("encoding::json::encode")
def _json_encode(args, ctx):
    import json

    return json.dumps(
        _to_jsonable(args[0]), separators=(",", ":"), ensure_ascii=False
    )


@register("encoding::json::decode")
def _json_decode(args, ctx):
    import json

    s2 = _str(args[0], "encoding::json::decode", 1)
    try:
        return _from_jsonable(json.loads(s2))
    except ValueError:
        raise SdbError(
            "Incorrect arguments for function encoding::json::decode(). "
            "Invalid JSON"
        )


def _cbor_encode_val(v, out: bytearray):
    import struct

    from surrealdb_tpu.val import SSet

    def head(major, n):
        if n < 24:
            out.append((major << 5) | n)
        elif n < 0x100:
            out.append((major << 5) | 24)
            out.append(n)
        elif n < 0x10000:
            out.append((major << 5) | 25)
            out.extend(n.to_bytes(2, "big"))
        elif n < 0x100000000:
            out.append((major << 5) | 26)
            out.extend(n.to_bytes(4, "big"))
        else:
            out.append((major << 5) | 27)
            out.extend(n.to_bytes(8, "big"))

    if v is NONE:
        # NONE is tagged null (tag 6); plain null is SQL NULL
        out.append(0xC6)
        out.append(0xF6)
    elif v is None:
        out.append(0xF6)
    elif isinstance(v, bool):
        out.append(0xF5 if v else 0xF4)
    elif isinstance(v, int):
        if v >= 0:
            head(0, v)
        else:
            head(1, -1 - v)
    elif isinstance(v, float):
        out.append(0xFB)
        out.extend(struct.pack(">d", v))
    elif isinstance(v, str):
        b = v.encode("utf-8")
        head(3, len(b))
        out.extend(b)
    elif isinstance(v, (bytes, bytearray)):
        head(2, len(v))
        out.extend(v)
    elif isinstance(v, (list, SSet)):
        items = v.items if isinstance(v, SSet) else v
        head(4, len(items))
        for x in items:
            _cbor_encode_val(x, out)
    elif isinstance(v, dict):
        head(5, len(v))
        for k, x in v.items():
            _cbor_encode_val(k, out)
            _cbor_encode_val(x, out)
    else:
        from surrealdb_tpu.exec.operators import to_string

        _cbor_encode_val(to_string(v), out)


def _cbor_invalid():
    return SdbError(
        "Incorrect arguments for function encoding::cbor::decode(). "
        "Invalid CBOR input"
    )


def _cbor_decode_val(b: bytes, pos: int):
    import struct

    def take(k):
        if pos + k > len(b):
            raise _cbor_invalid()

    if pos >= len(b):
        raise _cbor_invalid()
    ib = b[pos]
    major, info = ib >> 5, ib & 0x1F
    pos += 1
    if info < 24:
        n = info
    elif info == 24:
        take(1)
        n = b[pos]
        pos += 1
    elif info == 25:
        take(2)
        n = int.from_bytes(b[pos:pos + 2], "big")
        pos += 2
    elif info == 26:
        take(4)
        n = int.from_bytes(b[pos:pos + 4], "big")
        pos += 4
    elif info == 27:
        take(8)
        n = int.from_bytes(b[pos:pos + 8], "big")
        pos += 8
    else:
        # indefinite lengths / reserved additional-info are unsupported
        raise SdbError(
            "Incorrect arguments for function encoding::cbor::decode(). "
            "Invalid CBOR input"
        )
    if major == 0:
        return n, pos
    if major == 1:
        return -1 - n, pos
    if major == 2:
        take(n)
        return bytes(b[pos:pos + n]), pos + n
    if major == 3:
        take(n)
        return b[pos:pos + n].decode("utf-8"), pos + n
    if major == 4:
        out = []
        for _ in range(n):
            v, pos = _cbor_decode_val(b, pos)
            out.append(v)
        return out, pos
    if major == 5:
        out = {}
        for _ in range(n):
            k, pos = _cbor_decode_val(b, pos)
            v, pos = _cbor_decode_val(b, pos)
            out[k if isinstance(k, str) else str(k)] = v
        return out, pos
    if major == 6:
        v, pos = _cbor_decode_val(b, pos)
        if n == 6:
            return NONE, pos
        return v, pos
    # major 7: simple / float
    if info == 20:
        return False, pos
    if info == 21:
        return True, pos
    if info in (22, 23):
        return None, pos
    if info == 27:
        return struct.unpack(">d", b[pos - 8:pos])[0], pos
    if info == 26:
        return struct.unpack(">f", b[pos - 4:pos])[0], pos
    raise SdbError(
        "Incorrect arguments for function encoding::cbor::decode(). "
        "Invalid CBOR input"
    )


@register("encoding::cbor::encode")
def _cbor_encode(args, ctx):
    out = bytearray()
    _cbor_encode_val(args[0], out)
    return bytes(out)


@register("encoding::cbor::decode")
def _cbor_decode(args, ctx):
    v = args[0]
    if not isinstance(v, (bytes, bytearray)):
        from surrealdb_tpu.val import render as _r

        raise SdbError(
            "Incorrect arguments for function encoding::cbor::decode(). "
            f"Argument 1 was the wrong type. Expected `bytes` but found "
            f"`{_r(v)}`"
        )
    try:
        out, _pos = _cbor_decode_val(bytes(v), 0)
        return out
    except (IndexError, UnicodeDecodeError):
        raise SdbError(
            "Incorrect arguments for function encoding::cbor::decode(). "
            "Invalid CBOR input"
        )


@register("encoding::base64::decode")
def _b64_decode(args, ctx):
    import base64

    s = _str(args[0], "f", 1)
    pad = "=" * (-len(s) % 4)
    return base64.b64decode(s + pad)


@register("string::base64_encode")
def _b64e2(args, ctx):
    return _b64_encode(args, ctx)


# -- bytes --------------------------------------------------------------------


@register("bytes::len")
def _bytes_len(args, ctx):
    v = args[0]
    if not isinstance(v, (bytes, bytearray)):
        from surrealdb_tpu.fnc import ArgError

        raise ArgError(1, "bytes", v)
    return len(v)


# -- geo ----------------------------------------------------------------------

_EARTH_R = 6371008.8  # meters (mean earth radius)


def _as_geom(v):
    """GeoJSON-shaped objects coerce to geometries in geo:: functions."""
    if isinstance(v, Geometry):
        return v
    if isinstance(v, dict) and isinstance(v.get("type"), str) and \
            "coordinates" in v:
        def tup(c):
            if isinstance(c, list):
                return tuple(tup(x) for x in c)
            return c

        return Geometry(v["type"], tup(v["coordinates"]))
    return v


def _pt(v, fname, argn=1):
    from surrealdb_tpu.val import render

    v = _as_geom(v)
    if isinstance(v, Geometry) and v.kind == "Point":
        return float(v.coords[0]), float(v.coords[1])
    if isinstance(v, Geometry) or isinstance(v, dict):
        return None  # a geometry, just not a point -> NONE result
    raise SdbError(
        f"Incorrect arguments for function {fname}(). Argument {argn} was "
        f"the wrong type. Expected `geometry` but found `{render(v)}`"
    )


@register("geo::distance")
def _geo_distance(args, ctx):
    a = _pt(args[0], "geo::distance", 1)
    b = _pt(args[1], "geo::distance", 2)
    if a is None or b is None:
        return NONE
    (lon1, lat1) = a
    (lon2, lat2) = b
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = math.radians(lat2 - lat1)
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return _EARTH_R * 2 * math.atan2(math.sqrt(a), math.sqrt(1 - a))


@register("geo::bearing")
def _geo_bearing(args, ctx):
    a = _pt(args[0], "geo::bearing", 1)
    b = _pt(args[1], "geo::bearing", 2)
    if a is None or b is None:
        return NONE
    (lon1, lat1) = a
    (lon2, lat2) = b
    # geo crate Haversine::bearing op order: radians per coordinate,
    # delta in radians, then rem_euclid(360) — the reference folds
    # values > 180 back to the [-180, 180] range
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dl = math.radians(lon2) - math.radians(lon1)
    x = math.sin(dl) * math.cos(p2)
    y = math.cos(p1) * math.sin(p2) - math.sin(p1) * math.cos(p2) * math.cos(dl)
    deg = math.degrees(math.atan2(x, y)) % 360.0
    return deg - 360.0 if deg > 180.0 else deg


def _ring_centroid(ring):
    """Polygon ring centroid: triangle fan translated to the first vertex
    (geo crate Centroid — the translation keeps float bits identical)."""
    pts = [(float(p[0]), float(p[1])) for p in ring]
    if len(pts) > 1 and pts[0] == pts[-1]:
        pts = pts[:-1]
    if len(pts) < 3:
        return None
    x0, y0 = pts[0]
    area = cx = cy = 0.0
    for i in range(1, len(pts) - 1):
        dx1, dy1 = pts[i][0] - x0, pts[i][1] - y0
        dx2, dy2 = pts[i + 1][0] - x0, pts[i + 1][1] - y0
        a = dx1 * dy2 - dx2 * dy1
        area += a
        cx += a * (dx1 + dx2)
        cy += a * (dy1 + dy2)
    if area == 0.0:
        return None
    return x0 + cx / (3.0 * area), y0 + cy / (3.0 * area)


@register("geo::centroid")
def _geo_centroid(args, ctx):
    from surrealdb_tpu.exec.operators import _points_of

    from surrealdb_tpu.val import render as _r

    v = _as_geom(args[0])
    if not isinstance(v, Geometry):
        raise SdbError(
            "Incorrect arguments for function geo::centroid(). Argument 1 "
            f"was the wrong type. Expected `geometry` but found `{_r(v)}`"
        )
    if v.kind == "Polygon" and v.coords:
        c = _ring_centroid(v.coords[0])
        if c is not None:
            return Geometry("Point", c)
    pts = _points_of(v)
    if not pts:
        return NONE
    xs = sum(float(p[0]) for p in pts) / len(pts)
    ys = sum(float(p[1]) for p in pts) / len(pts)
    return Geometry("Point", (xs, ys))


@register("geo::area")
def _geo_area(args, ctx):
    from surrealdb_tpu.val import render as _r

    v = _as_geom(args[0])
    if not isinstance(v, Geometry):
        raise SdbError(
            "Incorrect arguments for function geo::area(). Argument 1 was "
            f"the wrong type. Expected `geometry` but found `{_r(v)}`"
        )

    def ring_area(ring):
        # chamberlain-duquette (geo crate): sum over vertices of
        # rad(x_next - x_prev) * sin(rad(y)), WGS84 equatorial radius
        pts = [(float(p[0]), float(p[1])) for p in ring]
        if len(pts) > 1 and pts[0] == pts[-1]:
            pts = pts[:-1]
        n = len(pts)
        if n < 3:
            return 0.0
        s = 0.0
        for i in range(n):
            x_prev = pts[i - 1][0]
            x_next = pts[(i + 1) % n][0]
            s += math.radians(x_next - x_prev) * math.sin(
                math.radians(pts[i][1])
            )
        return abs(s) * 6378137.0 * 6378137.0 / 2

    if v.kind == "Polygon":
        area = ring_area(v.coords[0]) if v.coords else 0.0
        for hole in v.coords[1:]:
            area -= ring_area(hole)
        return area
    if v.kind == "MultiPolygon":
        return sum(
            _geo_area([Geometry("Polygon", p)], ctx) for p in v.coords
        )
    return 0.0


_GH32 = "0123456789bcdefghjkmnpqrstuvwxyz"


@register("geo::hash::encode")
def _geohash_encode(args, ctx):
    a = _pt(args[0], "geo::hash::encode", 1)
    if a is None:
        return NONE
    lon, lat = a
    precision = int(args[1]) if len(args) > 1 else 12
    if not 1 <= precision <= 12:
        raise SdbError(
            "Incorrect arguments for function geo::hash::encode(). The "
            "second argument must be an integer greater than 0 and less "
            "than or equal to 12."
        )
    lat_r, lon_r = [-90.0, 90.0], [-180.0, 180.0]
    bits, bit, ch = 0, 0, 0
    even = True
    out = []
    while len(out) < precision:
        if even:
            mid = (lon_r[0] + lon_r[1]) / 2
            if lon > mid:
                ch |= 1 << (4 - bit)
                lon_r[0] = mid
            else:
                lon_r[1] = mid
        else:
            mid = (lat_r[0] + lat_r[1]) / 2
            if lat > mid:
                ch |= 1 << (4 - bit)
                lat_r[0] = mid
            else:
                lat_r[1] = mid
        even = not even
        if bit < 4:
            bit += 1
        else:
            out.append(_GH32[ch])
            bit, ch = 0, 0
    return "".join(out)


@register("geo::hash::decode")
def _geohash_decode(args, ctx):
    if not isinstance(args[0], str):
        return NONE
    s = args[0]
    lat_r, lon_r = [-90.0, 90.0], [-180.0, 180.0]
    even = True
    for c in s:
        cd = _GH32.index(c)
        for mask in (16, 8, 4, 2, 1):
            r = lon_r if even else lat_r
            mid = (r[0] + r[1]) / 2
            if cd & mask:
                r[0] = mid
            else:
                r[1] = mid
            even = not even
    return Geometry("Point", ((lon_r[0] + lon_r[1]) / 2, (lat_r[0] + lat_r[1]) / 2))


@register("geo::is::valid")
def _geo_valid(args, ctx):
    v = args[0]
    if not isinstance(v, Geometry):
        return False
    from surrealdb_tpu.exec.operators import _points_of

    return all(
        -180 <= float(p[0]) <= 180 and -90 <= float(p[1]) <= 90
        for p in _points_of(v)
    )


# -- session ------------------------------------------------------------------


@register("session::ac")
def _s_ac(args, ctx):
    return ctx.session.ac if ctx.session.ac else NONE


@register("session::db")
def _s_db(args, ctx):
    return ctx.session.db if ctx.session.db else NONE


@register("session::ns")
def _s_ns(args, ctx):
    return ctx.session.ns if ctx.session.ns else NONE


@register("session::id")
def _s_id(args, ctx):
    return NONE


@register("session::ip")
def _s_ip(args, ctx):
    return NONE


@register("session::origin")
def _s_origin(args, ctx):
    return NONE


@register("session::rd")
def _s_rd(args, ctx):
    return ctx.session.rid if ctx.session.rid else NONE


@register("session::token")
def _s_token(args, ctx):
    return ctx.vars.get("token", NONE)


# -- sequence -----------------------------------------------------------------


@register("sequence::nextval")
def _nextval(args, ctx):
    """Batch-allocated distributed sequences (kvs/sequences.rs:1-20):
    each node transactionally claims a BATCH-sized id range from the KV
    state row in its OWN transaction, then hands ids out locally — so
    concurrent nodes contend once per batch, not once per id, and ids
    survive the calling statement's rollback (reference semantics)."""
    from surrealdb_tpu import key as K
    from surrealdb_tpu.kvs.mem import CONFLICT_MSG

    name = _str(args[0], "sequence::nextval", 1)
    ns, db = ctx.need_ns_db()
    kdef = K.seq_state(ns, db, name)
    skey = (ns, db, name)
    with ctx.ds.lock:
        rng = ctx.ds.sequences.get(skey)
        if rng is not None and rng[0] < rng[1]:
            v = rng[0]
            rng[0] += 1
            return v
    st = ctx.txn.get_val(kdef)
    if st is None:
        raise SdbError(f"The sequence '{name}' does not exist")
    tmo = getattr(st[0], "timeout", None)
    deadline = None
    if tmo is not None and getattr(tmo, "ns", None) is not None:
        import time as _time

        # batch allocation respects the sequence's TIMEOUT (reference
        # kvs/sequences.rs; a 0ns timeout can never allocate)
        if tmo.ns == 0:
            raise SdbError(
                "The query was not executed because it exceeded the "
                f"timeout: {tmo.render()}"
            )
        deadline = _time.monotonic() + tmo.ns / 1e9
    for _ in range(16):
        if deadline is not None:
            import time as _time

            if _time.monotonic() > deadline:
                raise SdbError(
                    "The query was not executed because it exceeded the "
                    f"timeout: {tmo.render()}"
                )
        txn = ctx.ds.transaction(write=True)
        try:
            st2 = txn.get_val(kdef)
            if st2 is None:
                # defined inside the caller's still-uncommitted txn:
                # allocate through that txn (single-node bootstrap case)
                txn.cancel()
                sd, current = st
                ctx.txn.set_val(kdef, (sd, current + 1))
                return current
            sd, current = st2
            batch = max(int(getattr(sd, "batch", 1000) or 1), 1)
            txn.set_val(kdef, (sd, current + batch))
            txn.commit()
            with ctx.ds.lock:
                ctx.ds.sequences[skey] = [current + 1, current + batch]
            return current
        except SdbError as e:
            txn.cancel()
            if str(e) != CONFLICT_MSG:
                raise
    raise SdbError(f"sequence '{name}' allocation contention")


# -- value / search / http stubs ---------------------------------------------


@register("value::chain")
def _vchain(args, ctx):
    # value.chain(|$v| ...) — apply a closure to any value (fnc/value.rs)
    from surrealdb_tpu.exec.eval import call_closure
    from surrealdb_tpu.val import Closure

    if len(args) != 2 or not isinstance(args[1], Closure):
        raise SdbError(
            "Incorrect arguments for function value::chain(). "
            "Expected a closure"
        )
    return call_closure(args[1], [args[0]], ctx)


@register("value::diff")
def _vdiff(args, ctx):
    from surrealdb_tpu.utils.patch import diff

    return diff(args[0], args[1])


@register("value::patch")
def _vpatch(args, ctx):
    from surrealdb_tpu.utils.patch import apply_patch

    return apply_patch(args[0], args[1])


@register("search::score")
def _search_score(args, ctx):
    from surrealdb_tpu.idx.fulltext import search_score

    return search_score(int(args[0]) if args else 0, ctx)


@register("search::highlight")
def _search_highlight(args, ctx):
    from surrealdb_tpu.idx.fulltext import search_highlight

    return search_highlight(args, ctx)


@register("search::offsets")
def _search_offsets(args, ctx):
    from surrealdb_tpu.idx.fulltext import search_offsets

    return search_offsets(args, ctx)


@register("search::analyze")
def _search_analyze(args, ctx):
    from surrealdb_tpu.idx.fulltext import analyze_text

    az = _str(args[0], "search::analyze", 1)
    return analyze_text(az, _str(args[1], "search::analyze", 2), ctx)


@register("search::rrf")
def _search_rrf(args, ctx):
    """Reciprocal-rank fusion of result-object arrays keyed on `id`
    (reference fnc search::rrf: merged fields + rrf_score)."""
    lists = args[0] if args else []
    limit = args[1] if len(args) > 1 else None
    k = args[2] if len(args) > 2 else 60
    if not isinstance(limit, int) or isinstance(limit, bool) or limit < 1:
        raise SdbError(
            "Incorrect arguments for function search::rrf(). "
            "limit must be at least 1"
        )
    if not isinstance(k, (int, float)) or isinstance(k, bool) or k < 0:
        raise SdbError(
            "Incorrect arguments for function search::rrf(). "
            "RRF constant must be at least 0"
        )
    from surrealdb_tpu.val import hashable

    scores: dict = {}
    merged: dict = {}
    order: list = []
    for lst in lists or []:
        if not isinstance(lst, list):
            continue
        for rank, item in enumerate(lst):
            if not isinstance(item, dict):
                continue
            h = hashable(item.get("id", rank))
            if h not in merged:
                merged[h] = dict(item)
                order.append(h)
            else:
                merged[h].update(item)
            scores[h] = scores.get(h, 0.0) + 1.0 / (k + rank + 1)
    out = sorted(order, key=lambda h: -scores[h])[: int(limit)]
    res = []
    for h in out:
        row = merged[h]
        row["rrf_score"] = scores[h]
        res.append(row)
    return res


@register("search::linear")
def _search_linear(args, ctx):
    """Weighted linear fusion with per-list score normalization
    (reference fnc search::linear: minmax/zscore + linear_score)."""
    lists = args[0] if args else []
    weights = args[1] if len(args) > 1 else []
    limit = args[2] if len(args) > 2 else None
    norm = args[3] if len(args) > 3 else "minmax"
    if not isinstance(limit, int) or isinstance(limit, bool) or limit < 1:
        raise SdbError(
            "Incorrect arguments for function search::linear(). "
            "Limit must be at least 1"
        )
    if norm not in ("minmax", "zscore"):
        raise SdbError(
            "Incorrect arguments for function search::linear(). "
            "Norm must be 'minmax' or 'zscore'"
        )
    if not isinstance(lists, list) or not isinstance(weights, list) or \
            len(lists) != len(weights):
        raise SdbError(
            "Incorrect arguments for function search::linear(). "
            "The results and the weights array should have the same length"
        )
    for i, w in enumerate(weights):
        if isinstance(w, bool) or not isinstance(w, (int, float, Decimal)):
            raise SdbError(
                "Incorrect arguments for function search::linear(). "
                f"Weight at index {i} must be a number"
            )
    from surrealdb_tpu.val import hashable

    # mirrors the reference's exact float op order (fnc/search.rs:380-537)
    # so normalized scores match bit-for-bit: per-doc raw score is
    # distance→1/(1+d) | ft_score | score | rank fallback 1/(1+count);
    # params per list, then weighted combination over score>0 entries
    n_lists = len(lists)
    documents: dict = {}  # h -> [scores_per_list, merged_obj]
    order: list = []
    count = 0
    for list_idx, lst in enumerate(lists):
        if not isinstance(lst, list):
            continue
        for item in lst:
            if not isinstance(item, dict) or "id" not in item:
                continue
            d = item.get("distance")
            fts = item.get("ft_score")
            sc = item.get("score")
            if isinstance(d, (int, float, Decimal)) and \
                    not isinstance(d, bool):
                score = 1.0 / (1.0 + float(d))
            elif isinstance(fts, (int, float, Decimal)) and \
                    not isinstance(fts, bool):
                score = float(fts)
            elif isinstance(sc, (int, float, Decimal)) and \
                    not isinstance(sc, bool):
                score = float(sc)
            else:
                score = 1.0 / (1.0 + count)
            h = hashable(item.get("id"))
            if h not in documents:
                documents[h] = [[0.0] * n_lists, dict(item)]
                order.append(h)
            else:
                documents[h][1].update(item)
            documents[h][0][list_idx] = score
            count += 1
    # per-list normalization params over scores > 0
    params = []
    for list_idx in range(n_lists):
        vals = [doc[0][list_idx] for doc in documents.values()
                if doc[0][list_idx] > 0.0]
        if not vals:
            params.append((0.0, 1.0))
            continue
        if norm == "minmax":
            lo = min(vals)
            rng = max(vals) - lo
            params.append((lo, rng if rng > 0.0 else 1.0))
        else:
            mean = sum(vals) / len(vals)
            var = sum((x - mean) ** 2 for x in vals) / len(vals)
            sd = var ** 0.5
            params.append((mean, sd if sd > 0.0 else 1.0))
    combined: dict = {}
    for h in order:
        scores_l, _obj = documents[h]
        total = 0.0
        for list_idx, score in enumerate(scores_l):
            if score > 0.0:
                w = weights[list_idx] if list_idx < len(weights) else 1.0
                a, b = params[list_idx]
                total += float(w) * ((score - a) / b)
        combined[h] = total
    out = sorted(order, key=lambda h: -combined[h])[: int(limit)]
    res = []
    for h in out:
        row = documents[h][1]
        row["linear_score"] = combined[h]
        res.append(row)
    return res


def _http_call(method):
    def call(args, ctx):
        from urllib.parse import urlparse

        url = _str(args[0], f"http::{method}", 1)
        parsed = urlparse(url)
        host = parsed.hostname or ""
        target = f"{host}:{parsed.port}" if parsed.port else host
        caps = getattr(ctx.ds, "capabilities", None)
        # network access is deny-by-default (reference capability gate)
        if caps is None or not caps.allows_net(target):
            raise SdbError(
                f"Access to network target '{target}' is not allowed"
            )
        import json as _json
        import urllib.request

        body = args[1] if len(args) > 1 else None
        headers = args[2] if len(args) > 2 else {}
        data = None
        req_headers = dict(headers) if isinstance(headers, dict) else {}
        if body is not None and body is not NONE and method in (
            "put", "post", "patch"
        ):
            if isinstance(body, (dict, list)):
                data = _json.dumps(to_json(body)).encode()
                req_headers.setdefault("Content-Type", "application/json")
            elif isinstance(body, bytes):
                data = body
            else:
                data = str(body).encode()
        req = urllib.request.Request(
            url, method=method.upper(), data=data, headers=req_headers
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                raw = resp.read()
                if method == "head":
                    return NONE
                ctype = resp.headers.get("Content-Type", "")
                if "json" in ctype:
                    try:
                        return _json.loads(raw)
                    except ValueError:
                        pass
                try:
                    return raw.decode()
                except UnicodeDecodeError:
                    return raw
        except Exception as e:
            raise SdbError(f"There was an error processing a remote HTTP request: {e}")

    return call


for _m in ("head", "get", "put", "post", "patch", "delete"):
    register(f"http::{_m}")(_http_call(_m))


@register("api::invoke")
def _api_invoke(args, ctx):
    """Invoke a DEFINE API endpoint through the full middleware engine
    (reference core/src/api/mod.rs)."""
    from surrealdb_tpu.api import invoke as _invoke

    path = _str(args[0], "api::invoke", 1)
    opts = args[1] if len(args) > 1 and isinstance(args[1], dict) else {}
    return _invoke(ctx, path, opts)


@register("file::bucket")
def _file_bucket(args, ctx):
    from surrealdb_tpu.val import File

    v = args[0]
    if isinstance(v, File):
        return v.bucket
    raise SdbError("Incorrect arguments for function file::bucket(). Expected a file")


@register("file::key")
def _file_key(args, ctx):
    from surrealdb_tpu.val import File

    v = args[0]
    if isinstance(v, File):
        return v.key
    raise SdbError("Incorrect arguments for function file::key(). Expected a file")


# -- file:: bucket operations (reference core/src/buc/ + fnc file ops) ------


def _file_arg(args, fname):
    from surrealdb_tpu.val import File

    v = args[0] if args else NONE
    if not isinstance(v, File):
        raise SdbError(
            f"Incorrect arguments for function file::{fname}(). Expected a file"
        )
    return v


def _as_bytes(v, fname):
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if isinstance(v, str):
        return v.encode("utf-8")
    if isinstance(v, list) and all(
        isinstance(x, int) and not isinstance(x, bool) and 0 <= x < 256
        for x in v
    ):
        return bytes(v)  # int arrays coerce to bytes (reference file ops)
    raise SdbError(
        f"Incorrect arguments for function file::{fname}(). "
        f"Expected bytes or string data"
    )


@register("file::put")
def _file_put(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    f = _file_arg(args, "put")
    get_bucket(f.bucket, ctx, for_write=True).put(
        f.key, _as_bytes(args[1] if len(args) > 1 else NONE, "put")
    )
    return NONE


@register("file::put_if_not_exists")
def _file_put_ine(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    f = _file_arg(args, "put_if_not_exists")
    get_bucket(f.bucket, ctx, for_write=True).put_if_not_exists(
        f.key, _as_bytes(args[1] if len(args) > 1 else NONE,
                         "put_if_not_exists")
    )
    return NONE


@register("file::get")
def _file_get(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    f = _file_arg(args, "get")
    data = get_bucket(f.bucket, ctx).get(f.key)
    return NONE if data is None else data


@register("file::head")
def _file_head(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    f = _file_arg(args, "head")
    meta = get_bucket(f.bucket, ctx).head(f.key)
    return NONE if meta is None else meta


@register("file::exists")
def _file_exists(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    f = _file_arg(args, "exists")
    return get_bucket(f.bucket, ctx).exists(f.key)


@register("file::delete")
def _file_delete(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    f = _file_arg(args, "delete")
    get_bucket(f.bucket, ctx, for_write=True).delete(f.key)
    return NONE


def _dst_target(args, fname):
    """Destination (bucket|None, key): a string stays in the source bucket,
    a File may point into another bucket (cross-bucket copy/rename)."""
    v = args[1] if len(args) > 1 else NONE
    from surrealdb_tpu.val import File as _File

    if isinstance(v, _File):
        return v.bucket, v.key
    if isinstance(v, str):
        return None, (v if v.startswith("/") else "/" + v)
    raise SdbError(
        f"Incorrect arguments for function file::{fname}(). Expected a key"
    )


def _copy_like(ctx, f, args, fname, if_not_exists=False,
               idempotent_missing=False, remove_src=False):
    from surrealdb_tpu.buc import get_bucket

    src = get_bucket(f.bucket, ctx, for_write=remove_src)
    dbucket, dkey = _dst_target(args, fname)
    if dbucket is None or dbucket == f.bucket:
        if remove_src:
            src.rename(f.key, dkey, if_not_exists=if_not_exists)
        else:
            src.copy(f.key, dkey, if_not_exists=if_not_exists,
                     idempotent_missing=idempotent_missing)
        return
    dst = get_bucket(dbucket, ctx, for_write=True)
    data = src.get(f.key)
    if data is None:
        if idempotent_missing:
            return
        src._missing_source(f.key)
    if if_not_exists and dst.exists(dkey):
        return
    dst.put(dkey, data)
    if remove_src:
        src.delete(f.key)


@register("file::copy")
def _file_copy(args, ctx):
    f = _file_arg(args, "copy")
    _copy_like(ctx, f, args, "copy")
    return NONE


@register("file::copy_if_not_exists")
def _file_copy_ine(args, ctx):
    f = _file_arg(args, "copy_if_not_exists")
    _copy_like(ctx, f, args, "copy_if_not_exists", if_not_exists=True,
               idempotent_missing=True)
    return NONE


@register("file::rename")
def _file_rename(args, ctx):
    f = _file_arg(args, "rename")
    _copy_like(ctx, f, args, "rename", remove_src=True)
    return NONE


@register("file::rename_if_not_exists")
def _file_rename_ine(args, ctx):
    f = _file_arg(args, "rename_if_not_exists")
    _copy_like(ctx, f, args, "rename_if_not_exists", if_not_exists=True,
               remove_src=True)
    return NONE


@register("file::list")
def _file_list(args, ctx):
    from surrealdb_tpu.buc import get_bucket

    name = args[0] if args else NONE
    if not isinstance(name, str):
        raise SdbError(
            "Incorrect arguments for function file::list(). Expected a "
            "bucket name"
        )
    opts = args[1] if len(args) > 1 and isinstance(args[1], dict) else None
    return get_bucket(name, ctx).list(opts)
