"""math:: functions incl. stats (reference: core/src/fnc/math.rs + util/math)."""

from __future__ import annotations

import math
from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import _arr, _num, register
from surrealdb_tpu.val import NONE, sort_key


def _nums(a, fname, keep=False):
    out = []
    for x in _arr(a, fname):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            continue
        out.append(x if keep else float(x))
    return out


def _num_elems(a, fname):
    """Array argument coerced to numbers; non-numeric elements error
    (reference Vec<Number> argument coercion)."""
    from surrealdb_tpu.val import render

    out = []
    for x in _arr(a, fname, 1):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            raise SdbError(
                f"Incorrect arguments for function {fname}(). Argument 1 "
                f"was the wrong type. Expected `number` but found "
                f"`{render(x)}` when coercing an element of `array<number>`"
            )
        out.append(x)
    return out


class _RustHeap:
    """Rust std BinaryHeap layout emulation (push sift-up; pop moves the
    last element to the root, walks the hole to the bottom along greatest
    children, then sifts up) so into_vec order matches the reference."""

    def __init__(self, gt):
        self.a = []
        self.gt = gt  # strict greater-than in heap order

    def push(self, v):
        a = self.a
        a.append(v)
        i = len(a) - 1
        while i > 0:
            p = (i - 1) // 2
            if self.gt(a[i], a[p]):
                a[i], a[p] = a[p], a[i]
                i = p
            else:
                break

    def pop(self):
        a = self.a
        if not a:
            return None
        top = a[0]
        last = a.pop()
        if not a:
            return top
        # hole starts at root and descends along greatest children
        hole = 0
        n = len(a)
        while 2 * hole + 1 < n:
            c = 2 * hole + 1
            if c + 1 < n and self.gt(a[c + 1], a[c]):
                c += 1
            a[hole] = a[c]
            hole = c
        # place the displaced element and sift it up
        i = hole
        a[i] = last
        while i > 0:
            p = (i - 1) // 2
            if self.gt(a[i], a[p]):
                a[i], a[p] = a[p], a[i]
                i = p
            else:
                break
        return top


def _unary(name, fn):
    @register(f"math::{name}")
    def _f(args, ctx, fn=fn, name=name):
        v = _num(args[0], f"math::{name}")
        try:
            return fn(v)
        except (ValueError, OverflowError):
            return float("nan")


def _abs_checked(v):
    if isinstance(v, int) and v == -(1 << 63):
        raise SdbError(
            'Failed to compute: "math::abs(-9223372036854775808)", as the '
            "operation results in an arithmetic overflow."
        )
    return abs(v)


_unary("abs", _abs_checked)
_unary("acos", lambda v: math.acos(v))
_unary("acot", lambda v: math.atan(1 / v) if v != 0 else math.pi / 2)
_unary("asin", lambda v: math.asin(v))
_unary("atan", lambda v: math.atan(v))
_unary("cos", lambda v: math.cos(v))
_unary("cot", lambda v: 1 / math.tan(v))
_unary("deg2rad", lambda v: math.radians(v))
def _logf(fn):
    def inner(v):
        v = float(v)
        if v == 0.0:
            return float("-inf")
        if v < 0.0:
            return float("nan")
        return fn(v)

    return inner


_unary("ln", _logf(math.log))
_unary("log10", _logf(math.log10))
_unary("log2", _logf(math.log2))
_unary("rad2deg", lambda v: math.degrees(v))
def _signum(v):
    # floats use f64::signum (reference Number::sign): +-0.0 keep their
    # sign bit, NaN stays NaN
    if isinstance(v, float):
        if math.isnan(v):
            return v
        return math.copysign(1.0, v)
    return (v > 0) - (v < 0)


_unary("sign", _signum)
_unary("sin", lambda v: math.sin(v))
_unary("sqrt", lambda v: math.sqrt(v))
_unary("tan", lambda v: math.tan(v))


@register("math::ceil")
def _ceil(args, ctx):
    v = _num(args[0], "math::ceil", 1)
    if isinstance(v, int):
        return v
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return v
    if isinstance(v, Decimal):
        return v.to_integral_value(rounding="ROUND_CEILING")
    return float(math.ceil(v))


@register("math::floor")
def _floor(args, ctx):
    v = _num(args[0], "math::floor", 1)
    if isinstance(v, int):
        return v
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return v
    if isinstance(v, Decimal):
        return v.to_integral_value(rounding="ROUND_FLOOR")
    return float(math.floor(v))


@register("math::round")
def _round(args, ctx):
    v = _num(args[0], "math::round", 1)
    if isinstance(v, int):
        return v
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return v
    # half-away-from-zero like Rust's round(); floats stay floats
    r = math.floor(v + 0.5) if v >= 0 else math.ceil(v - 0.5)
    return float(r) if isinstance(v, float) else r


@register("math::fixed")
def _fixed(args, ctx):
    v = _num(args[0], "math::fixed", 1)
    p = int(_num(args[1], "math::fixed", 2))
    if p <= 0:
        raise SdbError("Incorrect arguments for function math::fixed(). The second argument must be an integer greater than 0.")
    if isinstance(v, int):
        return v
    return round(float(v), p)


@register("math::clamp")
def _clamp(args, ctx):
    v = _num(args[0], "math::clamp", 1)
    lo = _num(args[1], "math::clamp", 2)
    hi = _num(args[2], "math::clamp", 3)
    if lo > hi:
        raise SdbError(
            "Incorrect arguments for function math::clamp(). Lowerbound "
            "for clamp must be smaller than the upperbound"
        )
    out = max(lo, min(hi, v))
    if isinstance(v, float) and not isinstance(out, float):
        return float(out)
    return out


@register("math::lerp")
def _lerp(args, ctx):
    a = float(_num(args[0], "math::lerp", 1))
    b = float(_num(args[1], "math::lerp", 2))
    t = float(_num(args[2], "math::lerp", 3))
    return a + (b - a) * t


@register("math::lerpangle")
def _lerpangle(args, ctx):
    a = float(_num(args[0], "math::lerpangle", 1))
    b = float(_num(args[1], "math::lerpangle", 2))
    t = float(_num(args[2], "math::lerpangle", 3))
    d = (b - a) % 360.0
    if d > 180.0:
        d -= 360.0
    return a + d * t


@register("math::log")
def _log(args, ctx):
    v = float(_num(args[0], "math::log", 1))
    base = float(_num(args[1], "math::log", 2))
    if v == 0.0:
        return float("-inf")
    try:
        return math.log(v, base)
    except (ValueError, ZeroDivisionError):
        return float("nan")


@register("math::pow")
def _pow(args, ctx):
    from surrealdb_tpu.exec.operators import pow_

    return pow_(args[0], args[1])


@register("math::max")
def _mmax(args, ctx):
    a = _num_elems(args[0], "math::max")
    return max(a, key=sort_key) if a else float("-inf")


@register("math::min")
def _mmin(args, ctx):
    a = _num_elems(args[0], "math::min")
    return min(a, key=sort_key) if a else float("inf")


@register("math::sum")
def _sum(args, ctx):
    total = 0
    for x in _arr(args[0], "math::sum", 1):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            continue
        if isinstance(x, Decimal) and not isinstance(total, Decimal):
            total = Decimal(str(total))
        total = total + x
    return total


@register("math::product")
def _product(args, ctx):
    total = 1
    for x in _arr(args[0], "math::product", 1):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            continue
        total = total * x
    return total


@register("math::mean")
def _mean(args, ctx):
    ns = _nums(args[0], "math::mean", keep=True)
    if not ns:
        return float("nan")
    # try_float_div semantics: int sum / int count stays int when exact
    # (reference fnc/util/math/mean — view rolling means surface this)
    from surrealdb_tpu.exec.operators import float_div

    return float_div(sum(ns), len(ns))


@register("math::median")
def _median(args, ctx):
    ns = sorted(_nums(args[0], "math::median"))
    if not ns:
        return NONE
    n = len(ns)
    if n % 2:
        return float(ns[n // 2])
    return (ns[n // 2 - 1] + ns[n // 2]) / 2


@register("math::mode")
def _mode(args, ctx):
    ns = _nums(args[0], "math::mode")
    if not ns:
        return float("nan")
    from collections import Counter

    c = Counter(ns)
    best = max(c.items(), key=lambda kv: (kv[1], kv[0]))
    v = best[0]
    return int(v) if v == int(v) else v


@register("math::variance")
def _variance(args, ctx):
    ns = _nums(args[0], "math::variance")
    if len(ns) < 2:
        return float("nan")
    m = sum(ns) / len(ns)
    return sum((x - m) ** 2 for x in ns) / (len(ns) - 1)


@register("math::stddev")
def _stddev(args, ctx):
    v = _variance(args, ctx)
    return math.sqrt(v) if not math.isnan(v) else v


@register("math::spread")
def _spread(args, ctx):
    ns = _nums(args[0], "math::spread", keep=True)
    if not ns:
        return float("nan")
    from surrealdb_tpu.exec.operators import sub

    return sub(max(ns), min(ns))


@register("math::percentile")
def _percentile(args, ctx):
    ns = sorted(_nums(args[0], "math::percentile"))
    p = float(_num(args[1], "math::percentile", 2))
    if not ns or p < 0.0 or p > 100.0:
        return float("nan")
    if len(ns) == 1:
        return ns[0]
    rank = (p / 100.0) * (len(ns) - 1)
    lo = int(math.floor(rank))
    hi = int(math.ceil(rank))
    if lo == hi:
        return ns[lo]
    return ns[lo] + (ns[hi] - ns[lo]) * (rank - lo)


@register("math::nearestrank")
def _nearestrank(args, ctx):
    ns = sorted(_nums(args[0], "math::nearestrank", keep=True))
    p = float(_num(args[1], "math::nearestrank", 2))
    if not ns:
        return float("nan")
    rank = int(math.ceil((p / 100.0) * len(ns)))
    rank = max(1, min(rank, len(ns)))
    return ns[rank - 1]


@register("math::interquartile")
def _interquartile(args, ctx):
    return _percentile([args[0], 75], ctx) - _percentile([args[0], 25], ctx)


@register("math::midhinge")
def _midhinge(args, ctx):
    return (_percentile([args[0], 75], ctx) + _percentile([args[0], 25], ctx)) / 2


@register("math::trimean")
def _trimean(args, ctx):
    return (
        _percentile([args[0], 25], ctx)
        + 2 * _percentile([args[0], 50], ctx)
        + _percentile([args[0], 75], ctx)
    ) / 4


@register("math::top")
def _top(args, ctx):
    n = int(_num(args[1], "math::top", 2))
    if n < 1:
        raise SdbError("Incorrect arguments for function math::top(). The second argument must be an integer greater than 0.")
    a = _num_elems(args[0], "math::top")
    # min-heap of the k largest (Reverse ordering), reference heap layout
    h = _RustHeap(lambda x, y: sort_key(x) < sort_key(y))
    for i, v in enumerate(a):
        h.push(v)
        if i >= n:
            h.pop()
    return h.a


@register("math::bottom")
def _bottom(args, ctx):
    n = int(_num(args[1], "math::bottom", 2))
    if n < 1:
        raise SdbError("Incorrect arguments for function math::bottom(). The second argument must be an integer greater than 0.")
    a = _num_elems(args[0], "math::bottom")
    h = _RustHeap(lambda x, y: sort_key(x) > sort_key(y))
    for i, v in enumerate(a):
        h.push(v)
        if i >= n:
            h.pop()
    return h.a
