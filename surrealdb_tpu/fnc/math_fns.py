"""math:: functions incl. stats (reference: core/src/fnc/math.rs + util/math)."""

from __future__ import annotations

import math
from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import _arr, _num, register
from surrealdb_tpu.val import NONE, sort_key


def _nums(a, fname, keep=False):
    out = []
    for x in _arr(a, fname):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            continue
        out.append(x if keep else float(x))
    return out


def _unary(name, fn):
    @register(f"math::{name}")
    def _f(args, ctx, fn=fn, name=name):
        v = _num(args[0], f"math::{name}")
        try:
            return fn(v)
        except (ValueError, OverflowError):
            return float("nan")


def _abs_checked(v):
    if isinstance(v, int) and v == -(1 << 63):
        raise SdbError("Cannot calculate the absolute value of this number")
    return abs(v)


_unary("abs", _abs_checked)
_unary("acos", lambda v: math.acos(v))
_unary("acot", lambda v: math.atan(1 / v) if v != 0 else math.pi / 2)
_unary("asin", lambda v: math.asin(v))
_unary("atan", lambda v: math.atan(v))
_unary("cos", lambda v: math.cos(v))
_unary("cot", lambda v: 1 / math.tan(v))
_unary("deg2rad", lambda v: math.radians(v))
_unary("ln", lambda v: math.log(v))
_unary("log10", lambda v: math.log10(v))
_unary("log2", lambda v: math.log2(v))
_unary("rad2deg", lambda v: math.degrees(v))
_unary("sign", lambda v: (v > 0) - (v < 0))
_unary("sin", lambda v: math.sin(v))
_unary("sqrt", lambda v: math.sqrt(v))
_unary("tan", lambda v: math.tan(v))


@register("math::ceil")
def _ceil(args, ctx):
    v = _num(args[0], "math::ceil", 1)
    if isinstance(v, int):
        return v
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return v
    return float(math.ceil(v)) if isinstance(v, float) else math.ceil(v)


@register("math::floor")
def _floor(args, ctx):
    v = _num(args[0], "math::floor", 1)
    if isinstance(v, int):
        return v
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return v
    return float(math.floor(v)) if isinstance(v, float) else math.floor(v)


@register("math::round")
def _round(args, ctx):
    v = _num(args[0], "math::round", 1)
    if isinstance(v, int):
        return v
    if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
        return v
    # half-away-from-zero like Rust's round(); floats stay floats
    r = math.floor(v + 0.5) if v >= 0 else math.ceil(v - 0.5)
    return float(r) if isinstance(v, float) else r


@register("math::fixed")
def _fixed(args, ctx):
    v = _num(args[0], "math::fixed", 1)
    p = int(_num(args[1], "math::fixed", 2))
    if p <= 0:
        raise SdbError("Incorrect arguments for function math::fixed(). The second argument must be an integer greater than 0.")
    if isinstance(v, int):
        return v
    return round(float(v), p)


@register("math::clamp")
def _clamp(args, ctx):
    v = _num(args[0], "math::clamp", 1)
    lo = _num(args[1], "math::clamp", 2)
    hi = _num(args[2], "math::clamp", 3)
    out = max(lo, min(hi, v))
    if isinstance(v, float) and not isinstance(out, float):
        return float(out)
    return out


@register("math::lerp")
def _lerp(args, ctx):
    a = float(_num(args[0], "math::lerp", 1))
    b = float(_num(args[1], "math::lerp", 2))
    t = float(_num(args[2], "math::lerp", 3))
    return a + (b - a) * t


@register("math::lerpangle")
def _lerpangle(args, ctx):
    a = float(_num(args[0], "math::lerpangle", 1))
    b = float(_num(args[1], "math::lerpangle", 2))
    t = float(_num(args[2], "math::lerpangle", 3))
    d = (b - a) % 360.0
    if d > 180.0:
        d -= 360.0
    return a + d * t


@register("math::log")
def _log(args, ctx):
    v = float(_num(args[0], "math::log", 1))
    base = float(_num(args[1], "math::log", 2))
    try:
        return math.log(v, base)
    except (ValueError, ZeroDivisionError):
        return float("nan")


@register("math::pow")
def _pow(args, ctx):
    from surrealdb_tpu.exec.operators import pow_

    return pow_(args[0], args[1])


@register("math::max")
def _mmax(args, ctx):
    a = _arr(args[0], "math::max", 1)
    return max(a, key=sort_key) if a else NONE


@register("math::min")
def _mmin(args, ctx):
    a = _arr(args[0], "math::min", 1)
    return min(a, key=sort_key) if a else NONE


@register("math::sum")
def _sum(args, ctx):
    total = 0
    for x in _arr(args[0], "math::sum", 1):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            continue
        if isinstance(x, Decimal) and not isinstance(total, Decimal):
            total = Decimal(str(total))
        total = total + x
    return total


@register("math::product")
def _product(args, ctx):
    total = 1
    for x in _arr(args[0], "math::product", 1):
        if isinstance(x, bool) or not isinstance(x, (int, float, Decimal)):
            continue
        total = total * x
    return total


@register("math::mean")
def _mean(args, ctx):
    ns = _nums(args[0], "math::mean")
    if not ns:
        return float("nan")
    return sum(ns) / len(ns)


@register("math::median")
def _median(args, ctx):
    ns = sorted(_nums(args[0], "math::median"))
    if not ns:
        return float("nan")
    n = len(ns)
    if n % 2:
        return ns[n // 2]
    return (ns[n // 2 - 1] + ns[n // 2]) / 2


@register("math::mode")
def _mode(args, ctx):
    ns = _nums(args[0], "math::mode")
    if not ns:
        return float("nan")
    from collections import Counter

    c = Counter(ns)
    best = max(c.items(), key=lambda kv: (kv[1], kv[0]))
    v = best[0]
    return int(v) if v == int(v) else v


@register("math::variance")
def _variance(args, ctx):
    ns = _nums(args[0], "math::variance")
    if len(ns) < 2:
        return float("nan")
    m = sum(ns) / len(ns)
    return sum((x - m) ** 2 for x in ns) / (len(ns) - 1)


@register("math::stddev")
def _stddev(args, ctx):
    v = _variance(args, ctx)
    return math.sqrt(v) if not math.isnan(v) else v


@register("math::spread")
def _spread(args, ctx):
    ns = _nums(args[0], "math::spread")
    if not ns:
        return float("nan")
    return max(ns) - min(ns)


@register("math::percentile")
def _percentile(args, ctx):
    ns = sorted(_nums(args[0], "math::percentile"))
    p = float(_num(args[1], "math::percentile", 2))
    if not ns:
        return float("nan")
    if len(ns) == 1:
        return ns[0]
    rank = (p / 100.0) * (len(ns) - 1)
    lo = int(math.floor(rank))
    hi = int(math.ceil(rank))
    if lo == hi:
        return ns[lo]
    return ns[lo] + (ns[hi] - ns[lo]) * (rank - lo)


@register("math::nearestrank")
def _nearestrank(args, ctx):
    ns = sorted(_nums(args[0], "math::nearestrank"))
    p = float(_num(args[1], "math::nearestrank", 2))
    if not ns:
        return float("nan")
    rank = int(math.ceil((p / 100.0) * len(ns)))
    rank = max(1, min(rank, len(ns)))
    return ns[rank - 1]


@register("math::interquartile")
def _interquartile(args, ctx):
    return _percentile([args[0], 75], ctx) - _percentile([args[0], 25], ctx)


@register("math::midhinge")
def _midhinge(args, ctx):
    return (_percentile([args[0], 75], ctx) + _percentile([args[0], 25], ctx)) / 2


@register("math::trimean")
def _trimean(args, ctx):
    return (
        _percentile([args[0], 25], ctx)
        + 2 * _percentile([args[0], 50], ctx)
        + _percentile([args[0], 75], ctx)
    ) / 4


@register("math::top")
def _top(args, ctx):
    a = _arr(args[0], "math::top", 1)
    n = int(_num(args[1], "math::top", 2))
    if n < 1:
        raise SdbError("Incorrect arguments for function math::top(). The second argument must be an integer greater than 0.")
    return sorted(a, key=sort_key)[-n:]


@register("math::bottom")
def _bottom(args, ctx):
    a = _arr(args[0], "math::bottom", 1)
    n = int(_num(args[1], "math::bottom", 2))
    if n < 1:
        raise SdbError("Incorrect arguments for function math::bottom(). The second argument must be an integer greater than 0.")
    return sorted(a, key=sort_key)[:n][::-1]
