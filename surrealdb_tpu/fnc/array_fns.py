"""array:: and set:: functions (reference: core/src/fnc/array.rs)."""

from __future__ import annotations

import random as _random

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import _arr, _num, register
from surrealdb_tpu.val import (
    NONE,
    Closure,
    Range,
    is_truthy,
    sort_key,
    value_cmp,
    value_eq,
)


def _call(clo, args, ctx):
    from surrealdb_tpu.exec.eval import call_closure

    if not isinstance(clo, Closure):
        raise SdbError("Expected a closure argument")
    return call_closure(clo, args, ctx)


def _dedup(items):
    out = []
    for x in items:
        if not any(value_eq(x, y) for y in out):
            out.append(x)
    return out


@register("array::add")
def _add(args, ctx):
    a = _arr(args[0], "array::add", 1)[:]
    v = args[1]
    vs = v if isinstance(v, list) else [v]
    for x in vs:
        if not any(value_eq(x, y) for y in a):
            a.append(x)
    return a


@register("array::all")
def _all(args, ctx):
    a = _arr(args[0], "array::all", 1)
    if len(args) > 1:
        if isinstance(args[1], Closure):
            return all(is_truthy(_call(args[1], [x], ctx)) for x in a)
        return all(value_eq(x, args[1]) for x in a)
    return all(is_truthy(x) for x in a)


@register("array::any")
def _any(args, ctx):
    a = _arr(args[0], "array::any", 1)
    if len(args) > 1:
        if isinstance(args[1], Closure):
            return any(is_truthy(_call(args[1], [x], ctx)) for x in a)
        return any(value_eq(x, args[1]) for x in a)
    return any(is_truthy(x) for x in a)


@register("array::append")
def _append(args, ctx):
    return _arr(args[0], "array::append", 1)[:] + [args[1]]


@register("array::at")
def _at(args, ctx):
    from surrealdb_tpu.fnc import _int

    a = _arr(args[0], "array::at", 1)
    i = _int(args[1], "array::at", 2)
    if -len(a) <= i < len(a):
        return a[i]
    return NONE


@register("array::boolean_and")
def _band(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    n = max(len(a), len(b))
    ga = a + [NONE] * (n - len(a))
    gb = b + [NONE] * (n - len(b))
    return [is_truthy(x) and is_truthy(y) for x, y in zip(ga, gb)]


@register("array::boolean_or")
def _bor(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    n = max(len(a), len(b))
    ga = a + [NONE] * (n - len(a))
    gb = b + [NONE] * (n - len(b))
    return [is_truthy(x) or is_truthy(y) for x, y in zip(ga, gb)]


@register("array::boolean_xor")
def _bxor(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    n = max(len(a), len(b))
    ga = a + [NONE] * (n - len(a))
    gb = b + [NONE] * (n - len(b))
    return [is_truthy(x) != is_truthy(y) for x, y in zip(ga, gb)]


@register("array::boolean_not")
def _bnot(args, ctx):
    return [not is_truthy(x) for x in _arr(args[0], "f", 1)]


@register("array::clump")
def _clump(args, ctx):
    a = _arr(args[0], "array::clump", 1)
    n = int(_num(args[1], "array::clump", 2))
    if n < 1:
        raise SdbError("Incorrect arguments for function array::clump(). The second argument must be an integer greater than 0")
    return [a[i : i + n] for i in range(0, len(a), n)]


@register("array::combine")
def _combine(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    return [[x, y] for x in a for y in b]


@register("array::complement")
def _complement(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    return [x for x in a if not any(value_eq(x, y) for y in b)]


@register("array::concat")
def _concat(args, ctx):
    out = []
    for i, a in enumerate(args):
        out.extend(_arr(a, "array::concat", i + 1))
    return out


@register("array::difference")
def _difference(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    out = [x for x in a if not any(value_eq(x, y) for y in b)]
    out += [y for y in b if not any(value_eq(y, x) for x in a)]
    return out


@register("array::distinct")
def _distinct(args, ctx):
    return _dedup(_arr(args[0], "array::distinct", 1))


@register("array::fill")
def _fill(args, ctx):
    a = _arr(args[0], "array::fill", 1)[:]
    v = args[1]
    n = len(a)
    beg = int(args[2]) if len(args) > 2 else 0
    end = int(args[3]) if len(args) > 3 else n
    if beg < 0:
        beg += n
    if len(args) > 3 and end < 0:
        end += n
    for i in range(max(beg, 0), min(end, n)):
        a[i] = v
    return a


@register("array::filter")
def _filter(args, ctx):
    a = _arr(args[0], "array::filter", 1)
    p = args[1]
    if isinstance(p, Closure):
        return [x for x in a if is_truthy(_call(p, [x], ctx))]
    return [x for x in a if value_eq(x, p)]


@register("array::filter_index")
def _filter_index(args, ctx):
    a = _arr(args[0], "array::filter_index", 1)
    p = args[1]
    if isinstance(p, Closure):
        return [i for i, x in enumerate(a) if is_truthy(_call(p, [x], ctx))]
    return [i for i, x in enumerate(a) if value_eq(x, p)]


@register("array::find")
def _find(args, ctx):
    a = _arr(args[0], "array::find", 1)
    p = args[1]
    if isinstance(p, Closure):
        for x in a:
            if is_truthy(_call(p, [x], ctx)):
                return x
        return NONE
    for x in a:
        if value_eq(x, p):
            return x
    return NONE


@register("array::find_index")
def _find_index(args, ctx):
    a = _arr(args[0], "array::find_index", 1)
    p = args[1]
    for i, x in enumerate(a):
        if isinstance(p, Closure):
            if is_truthy(_call(p, [x], ctx)):
                return i
        elif value_eq(x, p):
            return i
    return NONE


@register("array::first")
def _first(args, ctx):
    a = _arr(args[0], "array::first", 1)
    return a[0] if a else NONE


@register("array::flatten")
def _flatten(args, ctx):
    out = []
    for x in _arr(args[0], "array::flatten", 1):
        if isinstance(x, list):
            out.extend(x)
        else:
            out.append(x)
    return out


@register("array::fold")
def _fold(args, ctx):
    a = _arr(args[0], "array::fold", 1)
    acc = args[1]
    clo = args[2]
    for i, x in enumerate(a):
        acc = _call(clo, [acc, x, i], ctx)
    return acc


@register("array::group")
def _group(args, ctx):
    out = []
    for x in _arr(args[0], "array::group", 1):
        items = x if isinstance(x, list) else [x]
        for y in items:
            if not any(value_eq(y, z) for z in out):
                out.append(y)
    return out


@register("array::insert")
def _insert(args, ctx):
    a = _arr(args[0], "array::insert", 1)[:]
    v = args[1]
    i = int(args[2]) if len(args) > 2 else len(a)
    if i < 0:
        i += len(a)
    if not 0 <= i <= len(a):
        return a  # out-of-bounds insert is a no-op (reference)
    a.insert(i, v)
    return a


@register("array::intersect")
def _intersect(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    return [x for x in _dedup(a) if any(value_eq(x, y) for y in b)]


@register("array::is_empty")
def _is_empty(args, ctx):
    return len(_arr(args[0], "array::is_empty", 1)) == 0


@register("array::join")
def _join(args, ctx):
    from surrealdb_tpu.exec.operators import to_string

    sep = args[1] if len(args) > 1 else ""
    return sep.join(to_string(x) for x in _arr(args[0], "array::join", 1))


@register("array::last")
def _last(args, ctx):
    a = _arr(args[0], "array::last", 1)
    return a[-1] if a else NONE


@register("array::len")
def _len(args, ctx):
    return len(_arr(args[0], "array::len", 1))


@register("array::logical_and")
def _land(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    n = max(len(a), len(b))
    out = []
    for i in range(n):
        x = a[i] if i < len(a) else None
        y = b[i] if i < len(b) else None
        out.append(y if is_truthy(x) else x)
    return out


@register("array::logical_or")
def _lor(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    n = max(len(a), len(b))
    out = []
    for i in range(n):
        x = a[i] if i < len(a) else None
        y = b[i] if i < len(b) else None
        out.append(x if is_truthy(x) else y)
    return out


@register("array::logical_xor")
def _lxor(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    n = max(len(a), len(b))
    out = []
    # xor: exactly one truthy -> that value; both truthy -> false;
    # both falsy -> the first operand's value; a missing side yields
    # the other side's value (reference logical_xor)
    for i in range(n):
        if i >= len(a):
            y = b[i]
            out.append(y if is_truthy(y) else None)
            continue
        if i >= len(b):
            out.append(a[i])
            continue
        x, y = a[i], b[i]
        tx, ty = is_truthy(x), is_truthy(y)
        if tx and not ty:
            out.append(x)
        elif ty and not tx:
            out.append(y)
        elif tx and ty:
            out.append(False)
        else:
            out.append(x)
    return out


@register("array::map")
def _map(args, ctx):
    a = _arr(args[0], "array::map", 1)
    clo = args[1]
    return [_call(clo, [x, i], ctx) for i, x in enumerate(a)]


@register("array::matches")
def _matches(args, ctx):
    a = _arr(args[0], "array::matches", 1)
    return [value_eq(x, args[1]) for x in a]


@register("array::max")
def _max(args, ctx):
    a = _arr(args[0], "array::max", 1)
    return max(a, key=sort_key) if a else NONE


@register("array::min")
def _min(args, ctx):
    a = _arr(args[0], "array::min", 1)
    return min(a, key=sort_key) if a else NONE


@register("array::pop")
def _pop(args, ctx):
    a = _arr(args[0], "array::pop", 1)
    return a[-1] if a else NONE


@register("array::prepend")
def _prepend(args, ctx):
    return [args[1]] + _arr(args[0], "array::prepend", 1)


@register("array::push")
def _push(args, ctx):
    return _arr(args[0], "array::push", 1)[:] + [args[1]]


@register("array::range")
def _range(args, ctx):
    from surrealdb_tpu.val import Range as _Rng

    if len(args) == 1 and isinstance(args[0], _Rng):
        r = args[0]
        if not isinstance(r.beg, int) or not isinstance(r.end, int) or \
                isinstance(r.beg, bool) or isinstance(r.end, bool):
            from surrealdb_tpu.val import render as _r2

            raise SdbError(
                "Incorrect arguments for function array::range(). "
                "Argument 1 was the wrong type. Expected `range<int>` "
                f"but found `{_r2(r)}`"
            )
        beg = int(r.beg) + (0 if r.beg_incl else 1)
        end = int(r.end) + (1 if r.end_incl else 0)
        if end - beg > 1048576:
            raise SdbError(
                "Incorrect arguments for function array::range(). Output "
                "must not exceed 1048576 bytes."
            )
        return list(range(beg, end))
    beg = int(_num(args[0], "array::range", 1))
    end = int(_num(args[1], "array::range", 2))
    if end - beg > 1048576:
        raise SdbError(
            "Incorrect arguments for function array::range(). Output "
            "must not exceed 1048576 bytes."
        )
    return list(range(beg, end))


@register("array::reduce")
def _reduce(args, ctx):
    a = _arr(args[0], "array::reduce", 1)
    clo = args[1]
    if not a:
        return NONE
    acc = a[0]
    for i, x in enumerate(a[1:]):
        acc = _call(clo, [acc, x, i], ctx)
    return acc


@register("array::remove")
def _remove(args, ctx):
    a = _arr(args[0], "array::remove", 1)[:]
    i = int(_num(args[1], "array::remove", 2))
    if -len(a) <= i < len(a):
        a.pop(i)
    return a


@register("array::repeat")
def _repeat(args, ctx):
    n = int(_num(args[1], "array::repeat", 2))
    if n < 0:
        raise SdbError(
            "Incorrect arguments for function array::repeat(). Expected "
            "argument 2 to be a positive number"
        )
    if n > 1048576:
        raise SdbError(
            "Incorrect arguments for function array::repeat(). Output "
            "must not exceed 1048576 bytes."
        )
    return [args[0]] * n


@register("array::sequence")
def _sequence(args, ctx):
    if len(args) > 1:
        beg = int(_num(args[0], "array::sequence", 1))
        cnt = int(_num(args[1], "array::sequence", 2))
    else:
        beg = 0
        cnt = int(_num(args[0], "array::sequence", 1))
    if cnt <= 0:
        return []
    if cnt > 1048576:
        raise SdbError(
            "Incorrect arguments for function array::sequence(). Output "
            "must not exceed 1048576 bytes."
        )
    return list(range(beg, beg + cnt))


@register("array::reverse")
def _reverse(args, ctx):
    return list(reversed(_arr(args[0], "array::reverse", 1)))


@register("array::shuffle")
def _shuffle(args, ctx):
    a = _arr(args[0], "array::shuffle", 1)[:]
    _random.shuffle(a)
    return a


@register("array::slice")
def _slice(args, ctx):
    a = _arr(args[0], "array::slice", 1)
    if len(args) > 1 and isinstance(args[1], Range):
        # range syntax: slice(a, 1..4) / slice(a, 1..=4)
        rg = args[1]
        beg = int(rg.beg) if rg.beg is not NONE and rg.beg is not None else 0
        if rg.end is NONE or rg.end is None:
            return a[beg:]
        end = int(rg.end) + (1 if rg.end_incl else 0)
        return a[beg:end]
    beg = int(args[1]) if len(args) > 1 else 0
    n = int(args[2]) if len(args) > 2 else None
    if beg < 0:
        beg = max(len(a) + beg, 0)
    if beg > len(a):
        return []
    if n is None:
        return a[beg:]
    if n < 0:
        return a[beg : len(a) + n]
    return a[beg:n]


@register("array::sort")
def _sort(args, ctx):
    a = _arr(args[0], "array::sort", 1)[:]
    asc = True
    if len(args) > 1:
        v = args[1]
        if v is False or (isinstance(v, str) and v.lower() == "desc"):
            asc = False
    a.sort(key=sort_key, reverse=not asc)
    return a


@register("array::sort::asc")
def _sort_asc(args, ctx):
    return _sort([args[0]], ctx)


@register("array::sort::desc")
def _sort_desc(args, ctx):
    return _sort([args[0], False], ctx)


def _natural_key(s):
    """Numeric-aware segmentation: '11' sorts after '2'."""
    import re as _re

    return [
        (0, int(t)) if t.isdigit() else (1, t)
        for t in _re.split(r"(\d+)", s)
        if t != ""
    ]


def _lexical_fold(s):
    """Case/accent-insensitive collation (lexical_sort crate)."""
    import unicodedata

    return "".join(
        c for c in unicodedata.normalize("NFD", s.casefold())
        if not unicodedata.combining(c)
    )


def _sort_variant(args, ctx, keyfn, name):
    a = _arr(args[0], name, 1)[:]
    asc = True
    if len(args) > 1:
        v = args[1]
        if v is False or (isinstance(v, str) and v.lower() == "desc"):
            asc = False
    import functools

    from surrealdb_tpu.val import value_cmp

    def cmp(x, y):
        # string pairs use the variant collation; any other pair falls
        # back to value order (reference natural_cmp partial_cmp)
        if isinstance(x, str) and isinstance(y, str):
            kx, ky = keyfn(x), keyfn(y)
            return -1 if kx < ky else (1 if kx > ky else 0)
        return value_cmp(x, y)

    a.sort(key=functools.cmp_to_key(cmp), reverse=not asc)
    return a


@register("array::sort_natural")
def _sort_natural(args, ctx):
    return _sort_variant(args, ctx, _natural_key, "array::sort_natural")


@register("array::sort_lexical")
def _sort_lexical(args, ctx):
    return _sort_variant(args, ctx, _lexical_fold, "array::sort_lexical")


@register("array::sort_natural_lexical")
def _sort_nl(args, ctx):
    return _sort_variant(
        args, ctx,
        lambda x: _natural_key(_lexical_fold(x)),
        "array::sort_natural_lexical",
    )


@register("array::swap")
def _swap(args, ctx):
    a = _arr(args[0], "array::swap", 1)[:]
    i, j = int(args[1]), int(args[2])
    n = len(a)
    i0, j0 = i, j
    if i < 0:
        i += n
    if j < 0:
        j += n
    if not 0 <= i < n:
        raise SdbError(
            "Incorrect arguments for function array::swap(). Argument 1 "
            f"is out of range. Expected a number between -{n} and {n}"
        )
    if not 0 <= j < n:
        raise SdbError(
            "Incorrect arguments for function array::swap(). Argument 2 "
            f"is out of range. Expected a number between -{n} and {n}"
        )
    a[i], a[j] = a[j], a[i]
    return a


@register("array::transpose")
def _transpose(args, ctx):
    a = _arr(args[0], "array::transpose", 1)
    if not a:
        return []
    n = max(len(x) if isinstance(x, list) else 1 for x in a)
    out = []
    for i in range(n):
        row = []
        for x in a:
            if isinstance(x, list):
                row.append(x[i] if i < len(x) else NONE)
            else:
                row.append(x if i == 0 else NONE)
        out.append(row)
    return out


@register("array::union")
def _union(args, ctx):
    a, b = _arr(args[0], "f", 1), _arr(args[1], "f", 2)
    return _dedup(a + b)


@register("array::windows")
def _windows(args, ctx):
    a = _arr(args[0], "array::windows", 1)
    n = int(_num(args[1], "array::windows", 2))
    if n < 1:
        raise SdbError("Incorrect arguments for function array::windows(). The second argument must be an integer greater than 0")
    return [a[i : i + n] for i in range(0, len(a) - n + 1)]


# ---------------------------------------------------------------------------
# set:: family — SSet in, SSet out where the reference returns a set
# (reference fnc/set.rs over val/set.rs BTreeSet)
# ---------------------------------------------------------------------------

from surrealdb_tpu.fnc import ARITY, FUNCS as _F, ArgError  # noqa: E402
from surrealdb_tpu.val import SSet  # noqa: E402


def _set(v, idx=1):
    if not isinstance(v, SSet):
        raise ArgError(idx, "set", v)
    return v


def _set_wrap(arr_name, returns_set=True, set_args=(1,), value_args=()):
    inner = _F[arr_name]

    def fn(args, ctx):
        conv = list(args)
        for i in set_args:
            if i <= len(conv):
                conv[i - 1] = list(_set(conv[i - 1], i))
        # second set/array arguments are accepted as arrays too — except
        # value positions (set::all's needle compares as a VALUE: a set
        # element that IS a set must equal a set, not a list)
        for i, v in enumerate(conv):
            if isinstance(v, SSet) and (i + 1) not in set_args                     and (i + 1) not in value_args:
                conv[i] = list(v)
        out = inner(conv, ctx)
        if returns_set and isinstance(out, list):
            return SSet(out)
        return out

    return fn


_SET_FNS = {
    # name -> (array impl, returns_set[, value-arg positions])
    "add": ("array::add", True), "all": ("array::all", False, (2,)),
    "any": ("array::any", False, (2,)), "at": ("array::at", False),
    "complement": ("array::complement", True),
    "difference": ("array::difference", True),
    "filter": ("array::filter", True),
    "find": ("array::find", False, (2,)),
    "first": ("array::first", False), "flatten": ("array::flatten", True),
    "fold": ("array::fold", False), "intersect": ("array::intersect", True),
    "is_empty": ("array::is_empty", False), "join": ("array::join", False),
    "last": ("array::last", False), "len": ("array::len", False),
    "map": ("array::map", True), "max": ("array::max", False),
    "min": ("array::min", False), "reduce": ("array::reduce", False),
    "remove": ("array::remove", True), "slice": ("array::slice", True),
    "union": ("array::union", True),
}

for _n, _spec in _SET_FNS.items():
    _impl, _ret = _spec[0], _spec[1]
    _vargs = _spec[2] if len(_spec) > 2 else ()
    _F[f"set::{_n}"] = _set_wrap(_impl, _ret, value_args=_vargs)
    if _impl in ARITY:
        ARITY[f"set::{_n}"] = ARITY[_impl]


def _set_contains(args, ctx):
    return args[1] in _set(args[0], 1)


_F["set::contains"] = _set_contains


def _set_insert(args, ctx):
    s = _set(args[0], 1)
    return SSet(s.items + [args[1]])


_F["set::insert"] = _set_insert


def _set_remove(args, ctx):
    """set::remove removes by VALUE (reference fnc/set.rs), unlike
    array::remove's index semantics; an array/set argument removes each
    of its members."""
    s = _set(args[0], 1)
    v = args[1]
    gone = list(v) if isinstance(v, (list, SSet)) else [v]
    return SSet([
        x for x in s.items if not any(value_eq(x, g) for g in gone)
    ])


_F["set::remove"] = _set_remove


def _set_flatten(args, ctx):
    s = _set(args[0], 1)
    out = []
    for x in s:
        if isinstance(x, (SSet, list)):
            out.extend(list(x))
        else:
            out.append(x)
    return SSet(out)


_F["set::flatten"] = _set_flatten
