"""Builtin function library (reference: core/src/fnc/, 14.9k LoC).

Registry maps "family::name" -> callable(args, ctx). The vector:: family's
batched forms live in surrealdb_tpu.ops (JAX); the scalar forms here are the
per-row fallback the executor uses outside index scans.
"""

from __future__ import annotations

import hashlib
import math
import random as _random
import secrets
from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import (
    NONE,
    Closure,
    Datetime,
    Duration,
    Geometry,
    Range,
    RecordId,
    Regex,
    Table,
    Uuid,
    is_truthy,
    render,
    sort_key,
    value_cmp,
    value_eq,
)

FUNCS: dict = {}
_NUM = (int, float, Decimal)


def register(name):
    def deco(fn):
        FUNCS[name] = fn
        return fn

    return deco


def _num(v, fname):
    if isinstance(v, bool) or not isinstance(v, _NUM):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected a number, got {render(v)}")
    return v


def _arr(v, fname):
    if not isinstance(v, list):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected an array, got {render(v)}")
    return v


def _str(v, fname):
    if not isinstance(v, str):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected a string, got {render(v)}")
    return v


def _f(v):
    return float(v)


# ---------------------------------------------------------------------------
# dispatch entry points
# ---------------------------------------------------------------------------


def call_function(node, ctx):
    """Evaluate a FunctionCall AST node."""
    from surrealdb_tpu.exec.eval import evaluate

    name = node.name.lower()
    if name.startswith("fn::"):
        return call_custom(node.name[4:], [evaluate(a, ctx) for a in node.args], ctx)
    if name.startswith("ml::"):
        raise SdbError("ML model execution requires the surrealml sidecar (not configured)")
    if name == "__future__":
        # futures evaluate lazily; this build evaluates at read time
        return evaluate(node.args[0], ctx)
    if name == "__point__":
        a = evaluate(node.args[0], ctx)
        b = evaluate(node.args[1], ctx)
        return Geometry("Point", (float(a), float(b)))
    fn = FUNCS.get(name)
    if fn is None:
        raise SdbError(f"The function '{node.name}' does not exist")
    # closure-taking functions get raw AST access via ctx
    args = [evaluate(a, ctx) for a in node.args]
    return fn(args, ctx)


def call_custom(name, args, ctx):
    """fn::name(...) — user-defined function from the catalog."""
    from surrealdb_tpu import key as K
    from surrealdb_tpu.catalog import FunctionDef
    from surrealdb_tpu.exec.coerce import coerce
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.err import ReturnException

    ns, db = ctx.need_ns_db()
    fd = ctx.txn.get_val(K.fc_def(ns, db, name))
    if not isinstance(fd, FunctionDef):
        raise SdbError(f"The function 'fn::{name}' does not exist")
    c = ctx.child()
    for i, (pname, pkind) in enumerate(fd.args):
        v = args[i] if i < len(args) else NONE
        if pkind is not None:
            v = coerce(v, pkind)
        c.vars[pname] = v
    try:
        out = evaluate(fd.block, c)
    except ReturnException as r:
        out = r.value
    if fd.returns is not None:
        try:
            out = coerce(out, fd.returns)
        except SdbError as e:
            raise SdbError(
                f"Couldn't coerce return value from function `fn::{name}`: {e}"
            )
    return out


_METHOD_FAMILIES = [
    (list, "array"),
    (str, "string"),
    (dict, "object"),
    (RecordId, "record"),
    ((bytes, bytearray), "bytes"),
    (Duration, "duration"),
    (Datetime, "time"),
    (Geometry, "geo"),
    ((int, float, Decimal), "math"),
    (Uuid, "string"),
    (Range, "range"),
    (Closure, "function"),
]


def method_call(val, name, args, ctx):
    """value.method(args) — resolve to family::method(val, ...)."""
    name = name.lower()
    candidates = []
    for typ, fam in _METHOD_FAMILIES:
        if isinstance(val, typ):
            candidates.append(f"{fam}::{name}")
            break
    candidates += [f"type::{name}", f"value::{name}", name]
    # .is_string() style -> type::is::string
    if name.startswith("is_"):
        candidates.insert(0, f"type::is::{name[3:]}")
    if name.startswith("to_"):
        candidates.insert(0, f"type::{name[3:]}")
    for cand in candidates:
        fn = FUNCS.get(cand)
        if fn is not None:
            return fn([val] + args, ctx)
    # chained custom function: .fn::foo()
    raise SdbError(f"The method '{name}' does not exist for {render(val)}")


# ---------------------------------------------------------------------------
# count / not / sleep / rand
# ---------------------------------------------------------------------------


@register("count")
def _count(args, ctx):
    if not args:
        return 1
    v = args[0]
    if isinstance(v, list):
        return len(v)
    return 1 if is_truthy(v) else 0


@register("not")
def _not(args, ctx):
    return not is_truthy(args[0])


@register("sleep")
def _sleep(args, ctx):
    import time as _t

    d = args[0]
    if isinstance(d, Duration):
        _t.sleep(min(d.to_seconds(), 30))
    return NONE


@register("rand")
def _rand(args, ctx):
    return _random.random()


@register("rand::bool")
def _rand_bool(args, ctx):
    return _random.random() < 0.5


@register("rand::enum")
def _rand_enum(args, ctx):
    if len(args) == 1 and isinstance(args[0], list):
        return _random.choice(args[0]) if args[0] else NONE
    return _random.choice(args) if args else NONE


@register("rand::float")
def _rand_float(args, ctx):
    if len(args) == 2:
        return _random.uniform(_f(args[0]), _f(args[1]))
    return _random.random()


@register("rand::guid")
def _rand_guid(args, ctx):
    n = args[0] if args else 20
    return "".join(_random.choices("0123456789abcdefghijklmnopqrstuvwxyz", k=int(n)))


@register("rand::int")
def _rand_int(args, ctx):
    if len(args) == 2:
        return _random.randint(int(args[0]), int(args[1]))
    return _random.randint(-(2**63), 2**63 - 1)


@register("rand::string")
def _rand_string(args, ctx):
    import string as _s

    chars = _s.ascii_letters + _s.digits
    if len(args) == 2:
        n = _random.randint(int(args[0]), int(args[1]))
    elif len(args) == 1:
        n = int(args[0])
    else:
        n = 32
    return "".join(_random.choices(chars, k=n))


@register("rand::time")
def _rand_time(args, ctx):
    import datetime as _dt

    if len(args) == 2 and isinstance(args[0], Datetime):
        lo, hi = args[0].epoch_ns() // 10**9, args[1].epoch_ns() // 10**9
    elif len(args) == 2:
        lo, hi = int(args[0]), int(args[1])
    else:
        lo, hi = 0, 2**31 - 1
    s = _random.randint(lo, hi)
    return Datetime(_dt.datetime.fromtimestamp(s, _dt.timezone.utc))


@register("rand::uuid")
def _rand_uuid(args, ctx):
    return Uuid.new_v4()


@register("rand::uuid::v4")
def _rand_uuid4(args, ctx):
    return Uuid.new_v4()


@register("rand::uuid::v7")
def _rand_uuid7(args, ctx):
    return Uuid.new_v7()


@register("rand::ulid")
def _rand_ulid(args, ctx):
    from surrealdb_tpu.exec.eval import generate_record_key

    return generate_record_key("__gen_ulid__")


# family modules register themselves on import
from surrealdb_tpu.fnc import (  # noqa: E402,F401
    array_fns,
    misc_fns,
    math_fns,
    string_fns,
    time_fns,
    type_fns,
    vector_fns,
)
