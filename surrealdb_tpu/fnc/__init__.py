"""Builtin function library (reference: core/src/fnc/, 14.9k LoC).

Registry maps "family::name" -> callable(args, ctx). The vector:: family's
batched forms live in surrealdb_tpu.ops (JAX); the scalar forms here are the
per-row fallback the executor uses outside index scans.
"""

from __future__ import annotations

import hashlib
import math
import random as _random
import secrets
from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import (
    NONE,
    Closure,
    Datetime,
    Duration,
    Geometry,
    Range,
    RecordId,
    Regex,
    Table,
    Uuid,
    is_truthy,
    render,
    sort_key,
    value_cmp,
    value_eq,
)

FUNCS: dict = {}
ARITY: dict = {}  # name -> (lo, hi|None) or (lo1, lo2) exact alternatives
_NUM = (int, float, Decimal)


class ArgError(Exception):
    """Wrong-typed argument; formatted with the function name by the
    dispatcher (reference fnc/args.rs: 'Argument {idx} was the wrong
    type. Expected `{kind}` but found `{value}`')."""

    def __init__(self, idx, kind, value):
        self.idx = idx
        self.kind = kind
        self.value = value


def register(name, arity=None):
    def deco(fn):
        FUNCS[name] = fn
        if arity is not None:
            ARITY[name] = arity
        return fn

    return deco


def _arity_msg(spec) -> str:
    lo, hi = spec
    if hi is None:
        return f"Expected {lo} or more arguments"
    if lo == hi:
        if lo == 0:
            return "Expected no arguments"
        if lo == 1:
            return "Expected 1 argument"
        return f"Expected {lo} arguments"
    return f"Expected {lo} to {hi} arguments"


def check_args(name: str, args: list):
    spec = ARITY.get(name)
    if spec is None:
        return
    lo, hi = spec
    if len(args) < lo or (hi is not None and len(args) > hi):
        raise SdbError(
            f"Incorrect arguments for function {name}(). {_arity_msg(spec)}"
        )


def _num(v, fname=None, idx=1):
    if isinstance(v, bool) or not isinstance(v, _NUM):
        raise ArgError(idx, "number", v)
    return v


def _int(v, fname=None, idx=1):
    from decimal import Decimal as _D

    if isinstance(v, bool) or not isinstance(v, int):
        if isinstance(v, float) and v.is_integer():
            return int(v)
        if isinstance(v, _D) and v == v.to_integral_value():
            return int(v)
        raise ArgError(idx, "int", v)
    return v


def _arr(v, fname=None, idx=1):
    if not isinstance(v, list):
        raise ArgError(idx, "array", v)
    return v


def _str(v, fname=None, idx=1):
    if not isinstance(v, str):
        raise ArgError(idx, "string", v)
    return v


def _f(v):
    return float(v)


# ---------------------------------------------------------------------------
# dispatch entry points
# ---------------------------------------------------------------------------


def call_function(node, ctx):
    """Evaluate a FunctionCall AST node."""
    from surrealdb_tpu.exec.eval import evaluate

    name = node.name.lower()
    if name.startswith("fn::"):
        return call_custom(node.name[4:], [evaluate(a, ctx) for a in node.args], ctx)
    if name.startswith("mod::"):
        from surrealdb_tpu.surrealism import call_module

        return call_module(
            node.name[5:], [evaluate(a, ctx) for a in node.args], ctx
        )
    if name.startswith("ml::"):
        caps = getattr(ctx.ds, "capabilities", None)
        if caps is None or not caps.allows_experimental("ml"):
            # the reference's default build compiles without the `ml`
            # feature — the language suite expects this exact error
            raise SdbError(
                "Problem with machine learning computation. "
                "Machine learning computation is not enabled."
            )
        from surrealdb_tpu.ml import compute_model

        version = getattr(node, "version", None)
        if not version:
            raise SdbError(
                f"Incorrect arguments for function {name}(). "
                f"A model version is required: {name}<1.0.0>(...)"
            )
        # model names are case-sensitive (unlike builtin fn paths)
        return compute_model(
            node.name[4:], version,
            [evaluate(a, ctx) for a in node.args], ctx,
        )
    if name == "__future__":
        # futures evaluate lazily; this build evaluates at read time
        return evaluate(node.args[0], ctx)
    if name == "__point__":
        a = evaluate(node.args[0], ctx)
        b = evaluate(node.args[1], ctx)
        return Geometry("Point", (float(a), float(b)))
    fn = FUNCS.get(name)
    if fn is None:
        raise SdbError(f"The function '{node.name}' does not exist")
    caps = getattr(ctx.ds, "capabilities", None)
    if caps is not None and not caps.allows_function(name):
        raise SdbError(f"Function '{name}' is not allowed to be executed")
    args = [evaluate(a, ctx) for a in node.args]
    return invoke(name, fn, args, ctx)


def invoke(name, fn, args, ctx):
    check_args(name, args)
    try:
        return fn(args, ctx)
    except ArgError as e:
        from surrealdb_tpu.val import render as _render

        raise SdbError(
            f"Incorrect arguments for function {name}(). Argument {e.idx} "
            f"was the wrong type. Expected `{e.kind}` but found `{_render(e.value)}`"
        )
    except IndexError:
        spec = ARITY.get(name)
        if spec is not None:
            raise SdbError(
                f"Incorrect arguments for function {name}(). {_arity_msg(spec)}"
            )
        raise SdbError(
            f"Incorrect arguments for function {name}(). Not enough arguments"
        )


def call_custom(name, args, ctx):
    """fn::name(...) — user-defined function from the catalog."""
    from surrealdb_tpu import key as K
    from surrealdb_tpu.catalog import FunctionDef
    from surrealdb_tpu.exec.coerce import coerce
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.err import ReturnException

    ns, db = ctx.need_ns_db()
    fd = ctx.txn.get_val(K.fc_def(ns, db, name))
    if not isinstance(fd, FunctionDef):
        raise SdbError(f"The function 'fn::{name}' does not exist")
    # PERMISSIONS gate record/anonymous sessions (reference fnc/mod.rs
    # checks the function permission before invocation)
    if getattr(ctx.session, "auth_level", "owner") in ("record", "none"):
        perm = getattr(fd, "permissions", True)
        # no PERMISSIONS clause defaults to FULL (reference define/function)
        allowed = perm is True or perm is None
        if perm not in (True, False, None):
            from surrealdb_tpu.val import is_truthy

            # the clause evaluates with row permissions disabled, like
            # table PERMISSIONS (reference new_with_perms(false)); real
            # evaluation errors propagate rather than read as denials
            c0 = ctx.child()
            c0.vars["auth"] = getattr(ctx.session, "rid", None) or NONE
            c0._in_perm_check = True
            allowed = is_truthy(evaluate(perm, c0))
        if not allowed:
            raise SdbError(
                f"You don't have permission to run the fn::{name} function"
            )
    # arity: trailing option<>/any params are optional (reference fnc
    # custom: custom_optional_args.surql — a middle optional still makes
    # every later position mandatory)
    total = len(fd.args)
    required = total
    for _pname, pkind in reversed(fd.args):
        if pkind is not None and getattr(pkind, "name", None) in (
                "option", "any"):
            required -= 1
        else:
            break
    if len(args) > total or len(args) < required:
        if required == total:
            expects = (
                f"{total} argument" if total == 1 else f"{total} arguments"
            )
        else:
            expects = f"{required} to {total} arguments"
        raise SdbError(
            f"Incorrect arguments for function fn::{name}(). "
            f"The function expects {expects}."
        )
    c = ctx.child()
    for i, (pname, pkind) in enumerate(fd.args):
        v = args[i] if i < len(args) else NONE
        if pkind is not None:
            try:
                v = coerce(v, pkind)
            except SdbError as e:
                raise SdbError(
                    f"Incorrect arguments for function fn::{name}(). "
                    f"Failed to coerce argument `${pname}`: {e}"
                )
        c.vars[pname] = v
    try:
        out = evaluate(fd.block, c)
    except ReturnException as r:
        out = r.value
    except Exception as e:
        from surrealdb_tpu.err import BreakException, ContinueException

        if isinstance(e, (BreakException, ContinueException)):
            raise SdbError(
                "Invalid control flow statement, break or continue "
                "statement found outside of loop."
            )
        raise
    if fd.returns is not None:
        try:
            out = coerce(out, fd.returns)
        except SdbError as e:
            raise SdbError(
                f"Couldn't coerce return value from function `fn::{name}`: {e}"
            )
    return out


from surrealdb_tpu.val import SSet as _SSet  # noqa: E402

from surrealdb_tpu.val import File as _File  # noqa: E402

_METHOD_FAMILIES = [
    (_File, "file"),
    (_SSet, "set"),
    (list, "array"),
    (str, "string"),
    (dict, "object"),
    (RecordId, "record"),
    ((bytes, bytearray), "bytes"),
    (Duration, "duration"),
    (Datetime, "time"),
    (Geometry, "geo"),
    ((int, float, Decimal), "math"),
    (Uuid, "string"),
    (Range, "range"),
    (Closure, "function"),
]


_METHOD_ALIASES = {
    # reference exec/function/method.rs register_alias
    "every": "all", "includes": "any", "some": "any",
    "index_of": "find_index",
}


def method_call(val, name, args, ctx):
    """value.method(args) — resolve to family::method(val, ...)."""
    name = name.lower()
    name = _METHOD_ALIASES.get(name, name)
    candidates = []
    for typ, fam in _METHOD_FAMILIES:
        if isinstance(val, typ):
            candidates.append(f"{fam}::{name}")
            if "_" in name:
                # nested families: .distance_damerau_levenshtein() ->
                # string::distance::damerau_levenshtein, .semver_inc_major()
                # -> string::semver::inc::major (reference method
                # registration maps leading '_'s to submodules)
                candidates.append(f"{fam}::{name.replace('_', '::', 1)}")
                candidates.append(f"{fam}::{name.replace('_', '::', 2)}")
            break
    candidates += [f"type::{name}", f"value::{name}", name]
    if "_" in name:
        # bare namespaced methods: .vector_add() -> vector::add
        candidates.append(name.replace("_", "::", 1))
        candidates.append(name.replace("_", "::", 2))
    if name == "type_of":
        candidates.insert(0, "type::of")
    # .is_string() style -> type::is::string
    if name.startswith("is_"):
        candidates.insert(0, f"type::is::{name[3:]}")
    if name.startswith("to_"):
        candidates.insert(0, f"type::{name[3:]}")
    for cand in candidates:
        fn = FUNCS.get(cand)
        if fn is not None:
            return invoke(cand, fn, [val] + args, ctx)
    # ranges materialize to arrays for array methods: (0..10).map(...)
    if isinstance(val, Range):
        try:
            items = list(val.iter_ints())
        except TypeError:
            items = None
        if items is not None:
            fn = FUNCS.get(f"array::{name}")
            if fn is not None:
                return invoke(f"array::{name}", fn, [items] + args, ctx)
    if isinstance(val, _SSet):
        fn = FUNCS.get(f"array::{name}")
        if fn is not None:
            out = invoke(f"array::{name}", fn, [list(val)] + args, ctx)
            return _SSet(out) if isinstance(out, list) else out
    # chained custom function: .fn::foo()
    raise SdbError(f"The method '{name}' does not exist for {render(val)}")


# ---------------------------------------------------------------------------
# count / not / sleep / rand
# ---------------------------------------------------------------------------


@register("count")
def _count(args, ctx):
    if not args:
        return 1
    v = args[0]
    if isinstance(v, list):
        return len(v)
    from surrealdb_tpu.val import Range as _Rng, SSet as _SS

    if isinstance(v, _SS):
        return len(v)
    # every other value counts by truthiness — a Range is NOT expanded
    # (reference fnc count.rs: only Array/Set have cardinality)
    return 1 if is_truthy(v) else 0


@register("not")
def _not(args, ctx):
    return not is_truthy(args[0])


@register("sleep")
def _sleep(args, ctx):
    import time as _t

    d = args[0]
    if isinstance(d, Duration):
        _t.sleep(min(d.to_seconds(), 30))
    return NONE


@register("rand")
def _rand(args, ctx):
    return _random.random()


@register("rand::bool")
def _rand_bool(args, ctx):
    return _random.random() < 0.5


@register("rand::enum")
def _rand_enum(args, ctx):
    if len(args) == 1 and isinstance(args[0], list):
        return _random.choice(args[0]) if args[0] else NONE
    return _random.choice(args) if args else NONE


@register("rand::float")
def _rand_float(args, ctx):
    if len(args) == 2:
        return _random.uniform(_f(args[0]), _f(args[1]))
    return _random.random()


@register("rand::guid")
def _rand_guid(args, ctx):
    n = args[0] if args else 20
    return "".join(_random.choices("0123456789abcdefghijklmnopqrstuvwxyz", k=int(n)))


@register("rand::int")
def _rand_int(args, ctx):
    if len(args) == 1:
        raise SdbError(
            "Incorrect arguments for function rand::int(). Expected 0 or "
            "2 arguments"
        )
    if len(args) == 2:
        lo = _int(args[0], "rand::int", 1)
        hi = _int(args[1], "rand::int", 2)
        if lo > hi:
            lo, hi = hi, lo
        return _random.randint(lo, hi)
    return _random.randint(-(2**63), 2**63 - 1)


@register("rand::string")
def _rand_string(args, ctx):
    import string as _s

    chars = _s.ascii_letters + _s.digits
    if len(args) == 2:
        lo = _int(args[0], "rand::string", 1)
        hi = _int(args[1], "rand::string", 2)
        if lo > hi:
            raise SdbError(
                "Incorrect arguments for function rand::string(). "
                "Lowerbound of number of characters must be less then "
                "the upperbound."
            )
        n = _random.randint(lo, hi)
    elif len(args) == 1:
        n = _int(args[0], "rand::string", 1)
    else:
        n = 32
    if n > 65536:
        raise SdbError(
            "Incorrect arguments for function rand::string(). Number of "
            "characters must not exceed 65536."
        )
    return "".join(_random.choices(chars, k=max(n, 0)))


@register("rand::time")
def _rand_time(args, ctx):
    import datetime as _dt

    def secs(v, i):
        if isinstance(v, Datetime):
            return v.epoch_ns() // 10**9
        return _int(v, "rand::time", i)

    if len(args) == 2:
        lo, hi = secs(args[0], 1), secs(args[1], 2)
        if lo > hi:
            lo, hi = hi, lo
    else:
        # reference default spans years 0000-9999
        lo, hi = -62167219200, 253402300799
    s2 = _random.randint(lo, hi)
    return Datetime(_dt.datetime.fromtimestamp(s2, _dt.timezone.utc))


@register("rand::uuid")
def _rand_uuid(args, ctx):
    return Uuid.new_v4()


@register("rand::uuid::v4")
def _rand_uuid4(args, ctx):
    return Uuid.new_v4()


@register("rand::uuid::v7", arity=(0, 1))
def _rand_uuid7(args, ctx):
    if args and isinstance(args[0], Datetime):
        import os as _os
        import uuid as _uuid

        ts = args[0].epoch_ns() // 1_000_000
        b = bytearray(ts.to_bytes(6, "big") + _os.urandom(10))
        b[6] = (b[6] & 0x0F) | 0x70
        b[8] = (b[8] & 0x3F) | 0x80
        return Uuid(_uuid.UUID(bytes=bytes(b)))
    return Uuid.new_v7()


@register("rand::duration", arity=(0, 2))
def _rand_duration(args, ctx):
    from surrealdb_tpu.val import Duration as _D

    if len(args) == 2:
        for i, a in enumerate(args):
            if not isinstance(a, _D):
                raise ArgError(i + 1, "duration", a)
        lo, hi = args[0].ns, args[1].ns
    else:
        lo, hi = 0, 10**12
    return _D(_random.randint(min(lo, hi), max(lo, hi)))


@register("rand::id", arity=(0, 2))
def _rand_id(args, ctx):
    """rand::id() / rand::id(len) / rand::id(lo, hi) (reference fnc/rand.rs:85)."""
    if len(args) == 2:
        lo, hi = _int(args[0], idx=1), _int(args[1], idx=2)
        if lo > hi:
            lo, hi = hi, lo
        n = _random.randint(lo, min(hi, 64))
    elif len(args) == 1:
        n = min(_int(args[0], idx=1), 64)
    else:
        n = 20
    return "".join(
        _random.choices("0123456789abcdefghijklmnopqrstuvwxyz", k=max(n, 0))
    )


@register("rand::ulid")
def _rand_ulid(args, ctx):
    from surrealdb_tpu.exec.eval import generate_record_key

    if args and isinstance(args[0], Datetime):
        import os as _os

        t = args[0].epoch_ns() // 1_000_000
        rand = int.from_bytes(_os.urandom(10), "big")
        alph = "0123456789ABCDEFGHJKMNPQRSTVWXYZ"
        out = []
        for shift in range(45, -5, -5):
            out.append(alph[(t >> shift) & 31])
        for shift in range(75, -5, -5):
            out.append(alph[(rand >> shift) & 31])
        return "".join(out)
    return generate_record_key("__gen_ulid__")


# family modules register themselves on import
from surrealdb_tpu.fnc import (  # noqa: E402,F401
    array_fns,
    misc_fns,
    math_fns,
    string_fns,
    time_fns,
    type_fns,
    vector_fns,
)

# underscore aliases: family::is_X / family::from_X mirror family::is::X /
# family::from::X (both spellings exist in the reference surface)
for _pname in list(FUNCS):
    if "::is::" in _pname:
        FUNCS[_pname.replace("::is::", "::is_")] = FUNCS[_pname]
    if "::from::" in _pname:
        FUNCS[_pname.replace("::from::", "::from_")] = FUNCS[_pname]

# arity table (reference fnc signatures; (lo, hi) with hi=None = unbounded)
ARITY.update({
    "count": (0, 1), "not": (1, 1), "sleep": (1, 1), "rand": (0, 0),
    # array
    "array::add": (2, 2), "array::all": (1, 2), "array::any": (1, 2),
    "array::append": (2, 2), "array::at": (2, 2),
    "array::boolean_and": (2, 2), "array::boolean_or": (2, 2),
    "array::boolean_xor": (2, 2), "array::boolean_not": (1, 1),
    "array::clump": (2, 2), "array::combine": (2, 2),
    "array::complement": (2, 2), "array::concat": (0, None),
    "array::difference": (2, 2), "array::distinct": (1, 1),
    "array::fill": (2, 4), "array::filter": (2, 2),
    "array::filter_index": (2, 2), "array::find": (2, 2),
    "array::find_index": (2, 2), "array::first": (1, 1),
    "array::flatten": (1, 1), "array::fold": (3, 3), "array::group": (1, 1),
    "array::insert": (2, 3), "array::intersect": (2, 2),
    "array::is_empty": (1, 1), "array::join": (2, 2), "array::last": (1, 1),
    "array::len": (1, 1), "array::logical_and": (2, 2),
    "array::logical_or": (2, 2), "array::logical_xor": (2, 2),
    "array::map": (2, 2), "array::matches": (2, 2), "array::max": (1, 1),
    "array::min": (1, 1), "array::pop": (1, 1), "array::prepend": (2, 2),
    "array::push": (2, 2), "array::range": (1, 2), "array::reduce": (2, 2),
    "array::remove": (2, 2), "array::repeat": (2, 2),
    "array::reverse": (1, 1), "array::shuffle": (1, 1),
    "array::slice": (1, 3), "array::sort": (1, 2),
    "array::sort::asc": (1, 1), "array::sort::desc": (1, 1),
    "array::swap": (3, 3), "array::transpose": (1, 1),
    "array::union": (2, 2), "array::windows": (2, 2),
    # set
    "set::add": (2, 2), "set::complement": (2, 2), "set::contains": (2, 2),
    "set::difference": (2, 2), "set::intersect": (2, 2), "set::len": (1, 1),
    "set::union": (2, 2),
    # string
    "string::contains": (2, 2), "string::ends_with": (2, 2),
    "string::len": (1, 1), "string::lowercase": (1, 1),
    "string::matches": (2, 2), "string::repeat": (2, 2),
    "string::replace": (3, 3), "string::reverse": (1, 1),
    "string::slice": (1, 3), "string::slug": (1, 1),
    "string::split": (2, 2), "string::starts_with": (2, 2),
    "string::trim": (1, 1), "string::uppercase": (1, 1),
    "string::words": (1, 1),
    "string::distance::hamming": (2, 2),
    "string::distance::levenshtein": (2, 2),
    "string::distance::damerau_levenshtein": (2, 2),
    "string::similarity::fuzzy": (2, 2), "string::similarity::jaro": (2, 2),
    "string::similarity::jaro_winkler": (2, 2),
    "string::similarity::smithwaterman": (2, 2),
    # math
    "math::abs": (1, 1), "math::acos": (1, 1), "math::asin": (1, 1),
    "math::atan": (1, 1), "math::ceil": (1, 1), "math::cos": (1, 1),
    "math::fixed": (2, 2), "math::floor": (1, 1), "math::ln": (1, 1),
    "math::log": (2, 2), "math::log10": (1, 1), "math::log2": (1, 1),
    "math::max": (1, 1), "math::mean": (1, 1), "math::median": (1, 1),
    "math::min": (1, 1), "math::mode": (1, 1), "math::pow": (2, 2),
    "math::product": (1, 1), "math::round": (1, 1), "math::sign": (1, 1),
    "math::sin": (1, 1), "math::sqrt": (1, 1), "math::stddev": (1, 1),
    "math::sum": (1, 1), "math::tan": (1, 1), "math::variance": (1, 1),
    "math::spread": (1, 1), "math::percentile": (2, 2),
    "math::nearestrank": (2, 2), "math::top": (2, 2), "math::bottom": (2, 2),
    "math::interquartile": (1, 1), "math::midhinge": (1, 1),
    "math::trimean": (1, 1), "math::clamp": (3, 3), "math::lerp": (3, 3),
    "math::lerpangle": (3, 3), "math::deg2rad": (1, 1),
    "math::rad2deg": (1, 1),
    # time / duration
    "time::now": (0, 0), "time::floor": (2, 2), "time::ceil": (2, 2),
    "time::round": (2, 2), "time::group": (2, 2), "time::format": (2, 2),
    # type
    "type::bool": (1, 1), "type::datetime": (1, 1), "type::decimal": (1, 1),
    "type::duration": (1, 1), "type::float": (1, 1), "type::int": (1, 1),
    "type::number": (1, 1), "type::string": (1, 1), "type::table": (1, 1),
    "type::record": (1, 2), "type::uuid": (1, 1),
    "type::point": (1, 2), "type::field": (1, 1), "type::fields": (1, 1),
    "type::range": (1, 1), "type::array": (1, 1), "type::bytes": (1, 1),
    # vector
    "vector::add": (2, 2), "vector::subtract": (2, 2),
    "vector::multiply": (2, 2), "vector::divide": (2, 2),
    "vector::cross": (2, 2), "vector::dot": (2, 2), "vector::scale": (2, 2),
    "vector::magnitude": (1, 1), "vector::normalize": (1, 1),
    "vector::project": (2, 2), "vector::angle": (2, 2),
    "vector::distance::euclidean": (2, 2),
    "vector::distance::manhattan": (2, 2),
    "vector::distance::chebyshev": (2, 2),
    "vector::distance::hamming": (2, 2),
    "vector::distance::minkowski": (3, 3),
    "vector::distance::knn": (0, 1),
    "vector::similarity::cosine": (2, 2),
    "vector::similarity::jaccard": (2, 2),
    "vector::similarity::pearson": (2, 2),
    "vector::similarity::spearman": (2, 2),
    # crypto / parse / encoding
    "crypto::md5": (1, 1), "crypto::sha1": (1, 1), "crypto::sha256": (1, 1),
    "crypto::sha512": (1, 1),
    "parse::email::host": (1, 1), "parse::email::user": (1, 1),
    "encoding::base64::encode": (1, 2), "encoding::base64::decode": (1, 1),
    # rand
    "rand::bool": (0, 0), "rand::float": (0, 2), "rand::guid": (0, 2),
    "rand::int": (0, 2), "rand::string": (0, 2), "rand::time": (0, 2),
    "rand::uuid": (0, 1), "rand::ulid": (0, 1), "rand::enum": (1, None),
    # record
    "record::exists": (1, 1), "record::id": (1, 1), "record::tb": (1, 1),
    "record::table": (1, 1), "record::refs": (1, 3),
})
