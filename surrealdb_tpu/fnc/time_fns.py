"""time:: and duration:: functions (reference: core/src/fnc/time.rs)."""

from __future__ import annotations

import datetime as _dt

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import _arr, _num, register
from surrealdb_tpu.val import NONE, Datetime, Duration, sort_key


def _dtm(v, fname) -> Datetime:
    if not isinstance(v, Datetime):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected a datetime")
    return v


@register("time::now")
def _now(args, ctx):
    return Datetime.now()


@register("time::day")
def _day(args, ctx):
    d = _dtm(args[0], "time::day") if args else Datetime.now()
    return d.dt.day


@register("time::hour")
def _hour(args, ctx):
    d = _dtm(args[0], "time::hour") if args else Datetime.now()
    return d.dt.hour

@register("time::minute")
def _minute(args, ctx):
    d = _dtm(args[0], "time::minute") if args else Datetime.now()
    return d.dt.minute


@register("time::second")
def _second(args, ctx):
    d = _dtm(args[0], "time::second") if args else Datetime.now()
    return d.dt.second


@register("time::month")
def _month(args, ctx):
    d = _dtm(args[0], "time::month") if args else Datetime.now()
    return d.dt.month


@register("time::year")
def _year(args, ctx):
    d = _dtm(args[0], "time::year") if args else Datetime.now()
    return d.year


@register("time::wday")
def _wday(args, ctx):
    d = _dtm(args[0], "time::wday") if args else Datetime.now()
    return d.dt.isoweekday()


@register("time::week")
def _week(args, ctx):
    d = _dtm(args[0], "time::week") if args else Datetime.now()
    return d.dt.isocalendar()[1]


@register("time::yday")
def _yday(args, ctx):
    d = _dtm(args[0], "time::yday") if args else Datetime.now()
    return d.dt.timetuple().tm_yday


@register("time::unix")
def _unix(args, ctx):
    d = _dtm(args[0], "time::unix") if args else Datetime.now()
    return d.epoch_ns() // 1_000_000_000


@register("time::micros")
def _micros(args, ctx):
    d = _dtm(args[0], "time::micros") if args else Datetime.now()
    return d.epoch_ns() // 1_000


@register("time::millis")
def _millis(args, ctx):
    d = _dtm(args[0], "time::millis") if args else Datetime.now()
    return d.epoch_ns() // 1_000_000


@register("time::nano")
def _nano(args, ctx):
    d = _dtm(args[0], "time::nano") if args else Datetime.now()
    return d.epoch_ns()


def _set_component(args, which, fname):
    d = _dtm(args[0], fname)
    v = int(args[1])
    if which == "year":
        # chrono's settable year range (MIN_UTC..=MAX_UTC years)
        if not -262143 <= v <= 262142:
            raise SdbError(f"Unable to set datetime to year {v}")
        try:
            return Datetime.from_parts(
                v, d.dt.month, d.dt.day, d.dt.hour, d.dt.minute,
                d.dt.second, d.ns_frac,
            )
        except ValueError:
            raise SdbError(f"Unable to set datetime to year {v}")
    if not 0 <= v < (1 << 32):
        # reference converts through u32 before chrono sees the value
        raise SdbError("out of range integral type conversion attempted")
    try:
        return Datetime(d.dt.replace(**{which: v}), d.ns_frac,
                        d.year_shift)
    except ValueError:
        raise SdbError(f"Unable to set datetime to {which} {v}")


for _comp in ("year", "month", "day", "hour", "minute", "second"):
    def _mk_set(comp):
        @register(f"time::set_{comp}", arity=(2, 2))
        def _f(args, ctx):
            return _set_component(args, comp, f"time::set_{comp}")

    _mk_set(_comp)


@register("time::set_nanosecond", arity=(2, 2))
def _set_nanosecond(args, ctx):
    """Replace the sub-second component (reference time.rs set_nanosecond:
    whole-second part kept, fraction replaced by `nanos`)."""
    d = _dtm(args[0], "time::set_nanosecond")
    v = int(args[1])
    if v < 0 or v >= (1 << 32):
        raise SdbError("out of range integral type conversion attempted")
    if v >= 1_000_000_000:
        raise SdbError(f"Unable to set datetime to nanosecond {v}")
    return Datetime(d.dt.replace(microsecond=0), v, d.year_shift)


@register("time::timezone")
def _timezone(args, ctx):
    return "UTC"


@register("time::max")
def _tmax(args, ctx):
    a = _arr(args[0], "time::max", 1)
    return max(a, key=sort_key) if a else NONE


@register("time::min")
def _tmin(args, ctx):
    a = _arr(args[0], "time::min", 1)
    return min(a, key=sort_key) if a else NONE


def _floor_to(d: Datetime, dur: Duration) -> Datetime:
    if dur.ns <= 0:
        raise SdbError("Incorrect arguments for function time::floor(). Expected a positive duration")
    ns = d.epoch_ns()
    f = (ns // dur.ns) * dur.ns
    # rebuild inside Python's year range, re-attaching the cycle shift
    # (shifted years would otherwise crash fromtimestamp)
    from surrealdb_tpu.val import _GREGORIAN_CYCLE_NS

    f -= (d.year_shift // 400) * _GREGORIAN_CYCLE_NS
    secs, frac = divmod(f, 1_000_000_000)
    return Datetime(_dt.datetime.fromtimestamp(secs, _dt.timezone.utc),
                    frac, d.year_shift)


@register("time::floor")
def _floor(args, ctx):
    return _floor_to(_dtm(args[0], "time::floor"), args[1])


@register("time::ceil")
def _ceil(args, ctx):
    d = _dtm(args[0], "time::ceil")
    dur = args[1]
    f = _floor_to(d, dur)
    if f.epoch_ns() == d.epoch_ns():
        return f
    secs, frac = divmod(f.epoch_ns() + dur.ns, 1_000_000_000)
    return Datetime(_dt.datetime.fromtimestamp(secs, _dt.timezone.utc), frac)


@register("time::round")
def _round(args, ctx):
    d = _dtm(args[0], "time::round")
    dur = args[1]
    f = _floor_to(d, dur)
    if d.epoch_ns() - f.epoch_ns() >= dur.ns / 2:
        secs, frac = divmod(f.epoch_ns() + dur.ns, 1_000_000_000)
        return Datetime(_dt.datetime.fromtimestamp(secs, _dt.timezone.utc), frac)
    return f


@register("time::group")
def _group(args, ctx):
    d = _dtm(args[0], "time::group")
    unit = args[1]
    units = {
        "year": Duration.UNITS["y"], "month": None, "day": Duration.UNITS["d"],
        "hour": Duration.UNITS["h"], "minute": Duration.UNITS["m"],
        "second": Duration.UNITS["s"], "week": Duration.UNITS["w"],
    }
    if unit not in units:
        raise SdbError("Incorrect arguments for function time::group(). Expected a unit")
    if unit == "year":
        return Datetime.from_parts(d.year, 1, 1)
    if unit == "month":
        return Datetime.from_parts(d.year, d.dt.month, 1)
    return _floor_to(d, Duration(units[unit]))


# chrono strftime specifiers (reference uses chrono::format; Python's
# strftime silently passes unknown sequences through, chrono errors)
_CHRONO_SPECS = set("YCyqmbBhdeaAwuUWGgVjDxFvHkIlPpMSfRTXrZzstn%c+")


def _validate_chrono_fmt(fmt: str, fname: str):
    i, n = 0, len(fmt)
    while i < n:
        if fmt[i] != "%":
            i += 1
            continue
        i += 1
        if i < n and fmt[i] in "-_0":  # padding modifiers
            i += 1
        if i < n and fmt[i] == ".":
            i += 1
            if i < n and fmt[i] in "369":
                i += 1
        elif i < n and fmt[i] in "369" and i + 1 < n and fmt[i + 1] == "f":
            i += 1
        if i < n and fmt[i] == ":":
            while i < n and fmt[i] == ":":
                i += 1
            if i < n and fmt[i] == "z":
                i += 1
                continue
            i -= 1
        if i >= n or fmt[i] not in _CHRONO_SPECS:
            raise SdbError(
                f"Incorrect arguments for method {fname}(). `{fmt}` is "
                f"not a valid time formatting string"
            )
        i += 1


@register("time::format")
def _format(args, ctx):
    d = _dtm(args[0], "time::format")
    fmt = args[1]
    _validate_chrono_fmt(fmt, "time::format")
    if d.year_shift:
        # logical-year directives can't ride the shifted proxy datetime
        y = d.year
        fmt = (fmt.replace("%Y", str(y))
                  .replace("%y", f"{y % 100:02d}")
                  .replace("%C", str(y // 100)))
    return d.dt.strftime(fmt)


@register("time::is::leap_year")
def _leap(args, ctx):
    d = _dtm(args[0], "time::is::leap_year") if args else Datetime.now()
    y = d.year
    return y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)


def _from_epoch(v, scale):
    ns = int(v) * scale
    secs, frac = divmod(ns, 1_000_000_000)
    return Datetime(_dt.datetime.fromtimestamp(secs, _dt.timezone.utc), frac)


@register("time::from::nanos")
def _from_nanos(args, ctx):
    return _from_epoch(args[0], 1)


@register("time::from::micros")
def _from_micros(args, ctx):
    return _from_epoch(args[0], 1_000)


@register("time::from::millis")
def _from_millis(args, ctx):
    return _from_epoch(args[0], 1_000_000)


@register("time::from::secs")
def _from_secs(args, ctx):
    return _from_epoch(args[0], 1_000_000_000)


@register("time::from::unix")
def _from_unix(args, ctx):
    return _from_epoch(args[0], 1_000_000_000)


@register("time::from::ulid")
def _from_ulid(args, ctx):
    s = args[0]
    alph = "0123456789ABCDEFGHJKMNPQRSTVWXYZ"
    t = 0
    for c in s[:10]:
        t = t * 32 + alph.index(c)
    return _from_epoch(t, 1_000_000)


@register("time::from::uuid")
def _from_uuid(args, ctx):
    u = args[0]
    b = u.u.bytes
    if (b[6] >> 4) == 7:
        ms = int.from_bytes(b[:6], "big")
        return _from_epoch(ms, 1_000_000)
    raise SdbError("Incorrect arguments for function time::from::uuid(). Expected a version 7 UUID")


# -- duration:: ----------------------------------------------------------------


def _dur(v, fname) -> Duration:
    if not isinstance(v, Duration):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected a duration")
    return v


_U64 = 1 << 64
_I64 = 1 << 63
_MAX_DUR_NS = (_U64 - 1) * 1_000_000_000 + 999_999_999


def _wrap_i64(v: int) -> int:
    """Reference getters cast through `as i64`: two's-complement wrap."""
    return ((v + _I64) % _U64) - _I64


for _name, _unit in (
    ("nanos", 1), ("micros", 1_000), ("millis", 1_000_000),
    ("secs", 1_000_000_000), ("mins", 60 * 1_000_000_000),
    ("hours", 3600 * 1_000_000_000), ("days", 86400 * 1_000_000_000),
    ("weeks", 7 * 86400 * 1_000_000_000), ("years", 365 * 86400 * 1_000_000_000),
):
    def _mk(unit, name):
        @register(f"duration::{name}")
        def _g(args, ctx):
            return _wrap_i64(_dur(args[0], f"duration::{name}").ns // unit)

        @register(f"duration::from::{name}")
        def _h(args, ctx):
            # argument coerces through u64 (negative ints wrap); the
            # resulting duration must fit u64 seconds
            v = int(args[0]) % _U64
            ns = v * unit
            if ns > _MAX_DUR_NS:
                raise SdbError(
                    f'Failed to compute: "duration::from_{name}({v})", as '
                    "the operation results in an arithmetic overflow."
                )
            return Duration(ns)

    _mk(_unit, _name)
