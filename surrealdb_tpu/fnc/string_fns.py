"""string:: functions (reference: core/src/fnc/string.rs)."""

from __future__ import annotations

import re as _re

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import _arr, _num, _str, register
from surrealdb_tpu.val import NONE, Datetime, RecordId, Regex, Uuid


@register("string::capitalize")
def _capitalize(args, ctx):
    s = _str(args[0], "string::capitalize", 1)
    out = []
    prev_ws = True
    for ch in s:
        if prev_ws and ch.islower():
            out.append(ch.upper())
        else:
            out.append(ch)
        prev_ws = ch.isspace()
    return "".join(out)


@register("string::concat")
def _concat(args, ctx):
    from surrealdb_tpu.exec.operators import to_string

    return "".join(to_string(a) for a in args)


@register("string::contains")
def _contains(args, ctx):
    return _str(args[1], "string::contains", 2) in _str(args[0], "string::contains", 1)


@register("string::ends_with")
def _ends(args, ctx):
    return _str(args[0], "f", 1).endswith(_str(args[1], "f", 2))


FUNCS_endsWith = _ends


@register("string::starts_with")
def _starts(args, ctx):
    return _str(args[0], "f", 1).startswith(_str(args[1], "f", 2))


@register("string::join")
def _join(args, ctx):
    from surrealdb_tpu.exec.operators import to_string

    sep = _str(args[0], "string::join", 1)
    return sep.join(to_string(a) for a in args[1:])


@register("string::len")
def _len(args, ctx):
    return len(_str(args[0], "string::len", 1))


@register("string::lowercase")
def _lower(args, ctx):
    return _str(args[0], "string::lowercase", 1).lower()


@register("string::uppercase")
def _upper(args, ctx):
    return _str(args[0], "string::uppercase", 1).upper()


@register("string::matches")
def _matches(args, ctx):
    s = _str(args[0], "string::matches", 1)
    p = args[1]
    if isinstance(p, Regex):
        return p.rx.search(s) is not None
    return _re.search(p, s) is not None


@register("string::repeat")
def _repeat(args, ctx):
    return _str(args[0], "string::repeat", 1) * int(_num(args[1], "string::repeat", 2))


@register("string::replace")
def _replace(args, ctx):
    s = _str(args[0], "string::replace", 1)
    old = args[1]
    new = _str(args[2], "string::replace", 3) if len(args) > 2 else ""
    if isinstance(old, Regex):
        out = old.rx.sub(new, s)
    else:
        out = s.replace(_str(old, "string::replace"), new)
    if len(out.encode()) > 1048576 and len(out) > len(s):
        raise SdbError(
            "Incorrect arguments for function string::replace(). Output "
            "must not exceed 1048576 bytes."
        )
    return out


@register("string::reverse")
def _reverse(args, ctx):
    return _str(args[0], "string::reverse", 1)[::-1]


@register("string::slice")
def _slice(args, ctx):
    s = _str(args[0], "string::slice", 1)
    beg = int(args[1]) if len(args) > 1 else 0
    n = int(args[2]) if len(args) > 2 else None
    if beg < 0:
        beg += len(s)
    if n is None:
        return s[beg:]
    if n < 0:
        return s[beg : len(s) + n]
    return s[beg : beg + n]


@register("string::slug")
def _slug(args, ctx):
    s = _str(args[0], "string::slug", 1).lower()
    s = _re.sub(r"[^a-z0-9]+", "-", s)
    return s.strip("-")


@register("string::split")
def _split(args, ctx):
    s = _str(args[0], "string::split", 1)
    sep = _str(args[1], "string::split", 2)
    if sep == "":
        return list(s)
    return s.split(sep)


@register("string::trim")
def _trim(args, ctx):
    return _str(args[0], "string::trim", 1).strip()


@register("string::words")
def _words(args, ctx):
    return _str(args[0], "string::words", 1).split()


_HTML_ENC = {
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;",
    "'": "&#39;", "`": "&#96;", "/": "&#47;", "=": "&#61;",
    " ": "&#32;", "\n": "&#10;", "\r": "&#13;", "\t": "&#9;",
}


@register("string::html::encode")
def _html_encode(args, ctx):
    # reference: ammonia::clean_text — named entities for markup chars,
    # numeric references for separators/attribute-breaking chars
    return "".join(
        _HTML_ENC.get(c, c) for c in _str(args[0], "f", 1)
    )


@register("string::html::sanitize")
def _html_sanitize(args, ctx):
    return _re.sub(r"<[^>]*script[^>]*>.*?</[^>]*script[^>]*>", "",
                   _str(args[0], "f", 1), flags=_re.S | _re.I)


# -- is:: ---------------------------------------------------------------------

_EMAIL_RX = _re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")
_HEX_RX = _re.compile(r"^(0x)?[0-9a-fA-F]+$")
_NUMERIC_RX = _re.compile(r"^[+-]?\d+(\.\d+)?$")
_SEMVER_RX = _re.compile(
    r"^(0|[1-9]\d*)\.(0|[1-9]\d*)\.(0|[1-9]\d*)"
    r"(?:-((?:0|[1-9]\d*|\d*[a-zA-Z-][0-9a-zA-Z-]*)"
    r"(?:\.(?:0|[1-9]\d*|\d*[a-zA-Z-][0-9a-zA-Z-]*))*))?"
    r"(?:\+([0-9a-zA-Z-]+(?:\.[0-9a-zA-Z-]+)*))?$"
)
_ULID_RX = _re.compile(r"^[0-7][0-9A-HJKMNP-TV-Z]{25}$")
_UUID_RX = _re.compile(
    r"^[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$"
)


def _is(name, fn):
    @register(f"string::is::{name}")
    def _f(args, ctx, fn=fn):
        v = args[0]
        if not isinstance(v, str) or v == "":
            return False
        return fn(v)


_is("alphanum", lambda s: bool(s) and s.isalnum())
_is("alpha", lambda s: bool(s) and s.isalpha())
_is("ascii", lambda s: s.isascii())
_is("hexadecimal", lambda s: bool(_HEX_RX.match(s)))
_is("numeric", lambda s: bool(_NUMERIC_RX.match(s)))
_ATEXT = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    "!#$%&'*+-/=?^_`{|}~"
)


def _is_email_addr(s: str) -> bool:
    """RFC 5321 addr-spec shape (reference links the `addr` crate):
    dot-atom local part, dot-atom domain or [address literal]."""
    at = s.rfind("@")
    if at <= 0 or at == len(s) - 1:
        return False
    local, domain = s[:at], s[at + 1:]
    for seg in local.split("."):
        if not seg or any(c not in _ATEXT for c in seg):
            return False
    if domain.startswith("[") and domain.endswith("]"):
        return len(domain) > 2  # address literal (IPv6: / IPv4)
    for seg in domain.split("."):
        if not seg or seg.startswith("-") or seg.endswith("-"):
            return False
        if not all(c.isalnum() or c == "-" for c in seg):
            return False
    return True


_is("email", _is_email_addr)
_is("semver", lambda s: bool(_SEMVER_RX.match(s)))
_is("ulid", lambda s: bool(_ULID_RX.match(s)))
_is("uuid", lambda s: bool(_UUID_RX.match(s)))
_is("url", lambda s: bool(_re.match(r"^[a-zA-Z][a-zA-Z0-9+.-]*://[^\s]+$", s)))
def _is_domain(s):
    # internationalized labels validate through their punycode form
    if not s.isascii():
        try:
            s = s.encode("idna").decode()
        except UnicodeError:
            return False
    return bool(_re.match(
        r"^([a-zA-Z0-9]([a-zA-Z0-9-]{0,61}[a-zA-Z0-9])?\.)+[a-zA-Z0-9-]{2,}$",
        s,
    ))


_is("domain", _is_domain)
_is("ip", lambda s: _is_ip(s))
_is("ipv4", lambda s: _is_ipv4(s))
_is("ipv6", lambda s: _is_ipv6(s))
_is("latitude", lambda s: _is_float_in(s, -90, 90))
_is("longitude", lambda s: _is_float_in(s, -180, 180))


def _is_ipv4(s):
    import ipaddress

    try:
        ipaddress.IPv4Address(s)
        return True
    except ValueError:
        return False


def _is_ipv6(s):
    import ipaddress

    try:
        ipaddress.IPv6Address(s)
        return True
    except ValueError:
        return False


def _is_ip(s):
    return _is_ipv4(s) or _is_ipv6(s)


def _is_float_in(s, lo, hi):
    try:
        return lo <= float(s) <= hi
    except ValueError:
        return False


@register("string::is::datetime")
def _is_datetime(args, ctx):
    s = args[0]
    fmt = args[1] if len(args) > 1 else None
    if not isinstance(s, str):
        return False
    if fmt:
        import datetime as _dt

        try:
            _dt.datetime.strptime(s, _strftime_of(fmt))
            return True
        except ValueError:
            return False
    try:
        Datetime.parse(s)
        return True
    except ValueError:
        return False


@register("string::is::record")
def _is_record(args, ctx):
    s = args[0]
    if isinstance(s, RecordId):
        return True
    if not isinstance(s, str):
        return False
    try:
        from surrealdb_tpu.exec.static_eval import static_value
        from surrealdb_tpu.syn.parser import parse_record_literal

        v = static_value(parse_record_literal(s))
        if len(args) > 1:
            want = args[1]
            tb = want.name if hasattr(want, "name") else want
            return v.tb == tb
        return True
    except Exception:
        return False


# -- similarity / distance ----------------------------------------------------


def _check_similarity_len(fname, a, b):
    """O(n*m) guard (reference fnc/string.rs check_similarity_input_length)."""
    from surrealdb_tpu import cnf

    mx = cnf.FUNCTION_SIMILARITY_MAX_LENGTH
    if len(a) > mx or len(b) > mx:
        raise SdbError(
            f"Incorrect arguments for function {fname}(). Input strings "
            f"must not exceed {mx} bytes (got {len(a)} and {len(b)})."
        )


def _levenshtein(a, b):
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


@register("string::distance::levenshtein")
def _lev(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    _check_similarity_len("string::distance::levenshtein", a, b)
    return _levenshtein(a, b)


@register("string::distance::damerau_levenshtein")
def _dlev(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    _check_similarity_len("string::distance::damerau_levenshtein", a, b)
    da = {}
    maxdist = len(a) + len(b)
    d = [[maxdist] * (len(b) + 2) for _ in range(len(a) + 2)]
    for i in range(len(a) + 1):
        d[i + 1][1] = i
        d[i + 1][0] = maxdist
    for j in range(len(b) + 1):
        d[1][j + 1] = j
        d[0][j + 1] = maxdist
    for i in range(1, len(a) + 1):
        db = 0
        for j in range(1, len(b) + 1):
            k = da.get(b[j - 1], 0)
            l = db
            if a[i - 1] == b[j - 1]:
                cost = 0
                db = j
            else:
                cost = 1
            d[i + 1][j + 1] = min(
                d[i][j] + cost,
                d[i + 1][j] + 1,
                d[i][j + 1] + 1,
                d[k][l] + (i - k - 1) + 1 + (j - l - 1),
            )
        da[a[i - 1]] = i
    return d[len(a) + 1][len(b) + 1]


@register("string::distance::normalized_levenshtein")
def _nlev(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    m = max(len(a), len(b))
    return 1.0 - (_levenshtein(a, b) / m if m else 0.0)


@register("string::distance::normalized_damerau_levenshtein")
def _ndlev(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    m = max(len(a), len(b))
    if not m:
        return 1.0
    return 1.0 - _dlev(args, ctx) / m


@register("string::distance::osa_distance")
def _osa(args, ctx):
    """Optimal string alignment (restricted Damerau-Levenshtein,
    strsim::osa_distance)."""
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    la, lb = len(a), len(b)
    d = [[0] * (lb + 1) for _ in range(la + 1)]
    for i in range(la + 1):
        d[i][0] = i
    for j in range(lb + 1):
        d[0][j] = j
    for i in range(1, la + 1):
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d[i][j] = min(d[i - 1][j] + 1, d[i][j - 1] + 1,
                          d[i - 1][j - 1] + cost)
            if i > 1 and j > 1 and a[i - 1] == b[j - 2] \
                    and a[i - 2] == b[j - 1]:
                d[i][j] = min(d[i][j], d[i - 2][j - 2] + 1)
    return d[la][lb]


@register("string::distance::hamming")
def _hamming(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    if len(a) != len(b):
        raise SdbError("Incorrect arguments for function string::distance::hamming(). Strings must be of equal length")
    return sum(x != y for x, y in zip(a, b))


def _jaro(a, b):
    if a == b:
        return 1.0
    la, lb = len(a), len(b)
    if not la or not lb:
        return 0.0
    match_dist = max(la, lb) // 2 - 1
    a_matches = [False] * la
    b_matches = [False] * lb
    matches = 0
    for i in range(la):
        lo = max(0, i - match_dist)
        hi = min(lb, i + match_dist + 1)
        for j in range(lo, hi):
            if b_matches[j] or a[i] != b[j]:
                continue
            a_matches[i] = b_matches[j] = True
            matches += 1
            break
    if not matches:
        return 0.0
    t = 0
    k = 0
    for i in range(la):
        if a_matches[i]:
            while not b_matches[k]:
                k += 1
            if a[i] != b[k]:
                t += 1
            k += 1
    t /= 2
    return (matches / la + matches / lb + (matches - t) / matches) / 3


@register("string::similarity::jaro")
def _jaro_fn(args, ctx):
    return _jaro(_str(args[0], "f", 1), _str(args[1], "f", 2))


@register("string::similarity::jaro_winkler")
def _jw(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    j = _jaro(a, b)
    prefix = 0
    for x, y in zip(a, b):
        if x == y and prefix < 4:
            prefix += 1
        else:
            break
    return j + prefix * 0.1 * (1 - j)


@register("string::similarity::fuzzy")
def _fuzzy_sim(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    # fuzzy match score similar to the reference's fuzzy matcher: 0 if no
    # subsequence match, else a positive score
    from surrealdb_tpu.exec.operators import _fuzzy

    if not _fuzzy(b.lower(), a.lower()):
        return 0
    return len(b)


@register("string::similarity::sorensen_dice")
def _sdice(args, ctx):
    """Sørensen–Dice coefficient over character bigrams
    (strsim::sorensen_dice)."""
    a = _str(args[0], "f", 1).replace(" ", "")
    b = _str(args[1], "f", 2).replace(" ", "")
    if a == b:
        return 1.0
    if len(a) < 2 or len(b) < 2:
        return 0.0
    from collections import Counter

    ba = Counter(a[i:i + 2] for i in range(len(a) - 1))
    bb = Counter(b[i:i + 2] for i in range(len(b) - 1))
    inter = sum((ba & bb).values())
    return 2.0 * inter / (sum(ba.values()) + sum(bb.values()))


@register("string::similarity::smithwaterman")
def _sw(args, ctx):
    a, b = _str(args[0], "f", 1), _str(args[1], "f", 2)
    prev = [0] * (len(b) + 1)
    best = 0
    for ca in a:
        cur = [0]
        for j, cb in enumerate(b, 1):
            score = max(
                0,
                prev[j - 1] + (2 if ca == cb else -1),
                prev[j] - 1,
                cur[j - 1] - 1,
            )
            cur.append(score)
            best = max(best, score)
        prev = cur
    return best


# -- semver -------------------------------------------------------------------


def _parse_semver(s):
    m = _SEMVER_RX.match(s)
    if not m:
        raise SdbError(f"Invalid semantic version: {s}")
    return m


@register("string::semver::compare")
def _semver_cmp(args, ctx):
    a = _parse_semver(_str(args[0], "f", 1))
    b = _parse_semver(_str(args[1], "f", 2))
    ka = (int(a[1]), int(a[2]), int(a[3]))
    kb = (int(b[1]), int(b[2]), int(b[3]))
    if ka != kb:
        return -1 if ka < kb else 1
    pa, pb = a[4], b[4]
    if pa == pb:
        return 0
    if pa is None:
        return 1
    if pb is None:
        return -1
    return -1 if pa < pb else 1


@register("string::semver::major")
def _semver_major(args, ctx):
    return int(_parse_semver(_str(args[0], "f", 1))[1])


@register("string::semver::minor")
def _semver_minor(args, ctx):
    return int(_parse_semver(_str(args[0], "f", 1))[2])


@register("string::semver::patch")
def _semver_patch(args, ctx):
    return int(_parse_semver(_str(args[0], "f", 1))[3])


@register("string::semver::inc::major")
def _semver_inc_major(args, ctx):
    m = _parse_semver(_str(args[0], "f", 1))
    return f"{int(m[1]) + 1}.0.0"


@register("string::semver::inc::minor")
def _semver_inc_minor(args, ctx):
    m = _parse_semver(_str(args[0], "f", 1))
    return f"{m[1]}.{int(m[2]) + 1}.0"


@register("string::semver::inc::patch")
def _semver_inc_patch(args, ctx):
    m = _parse_semver(_str(args[0], "f", 1))
    return f"{m[1]}.{m[2]}.{int(m[3]) + 1}"


@register("string::semver::set::major")
def _semver_set_major(args, ctx):
    m = _parse_semver(_str(args[0], "f", 1))
    return f"{int(args[1])}.{m[2]}.{m[3]}"


@register("string::semver::set::minor")
def _semver_set_minor(args, ctx):
    m = _parse_semver(_str(args[0], "f", 1))
    return f"{m[1]}.{int(args[1])}.{m[3]}"


@register("string::semver::set::patch")
def _semver_set_patch(args, ctx):
    m = _parse_semver(_str(args[0], "f", 1))
    return f"{m[1]}.{m[2]}.{int(args[1])}"


def _strftime_of(fmt: str) -> str:
    """Convert chrono-style format to strftime (common specifiers match)."""
    return fmt
