"""vector:: functions (reference: core/src/fnc/vector.rs:9-141,
fnc/util/math/vector.rs).

Scalar (per-call) forms using numpy. The batched forms used by index scans
live in surrealdb_tpu.ops.distance (JAX on TPU); these must agree numerically
with those kernels — tests assert parity.
"""

from __future__ import annotations

import math

import numpy as np

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.fnc import register
from surrealdb_tpu.val import NONE


def _vec(v, fname):
    if not isinstance(v, (list, tuple)):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected a vector")
    try:
        return np.asarray(v, dtype=np.float64)
    except (TypeError, ValueError):
        raise SdbError(f"Incorrect arguments for function {fname}(). Expected a numeric vector")


def _pair(a, b, fname):
    va, vb = _vec(a, fname), _vec(b, fname)
    if va.shape != vb.shape:
        raise SdbError(f"Incorrect arguments for function {fname}(). The two vectors must be of the same dimension")
    return va, vb


def _out(arr):
    return [float(x) if not float(x).is_integer() else int(x) for x in arr]


def _outf(arr):
    return [float(x) for x in arr]


@register("vector::add")
def _add(args, ctx):
    a, b = _pair(args[0], args[1], "vector::add")
    return _out(a + b)


@register("vector::subtract")
def _subtract(args, ctx):
    a, b = _pair(args[0], args[1], "vector::subtract")
    return _out(a - b)


@register("vector::multiply")
def _multiply(args, ctx):
    a, b = _pair(args[0], args[1], "vector::multiply")
    return _out(a * b)


@register("vector::divide")
def _divide(args, ctx):
    a, b = _pair(args[0], args[1], "vector::divide")
    with np.errstate(divide="ignore", invalid="ignore"):
        return _outf(a / b)


@register("vector::scale")
def _scale(args, ctx):
    a = _vec(args[0], "vector::scale")
    return _out(a * float(args[1]))


@register("vector::dot")
def _dot(args, ctx):
    a, b = _pair(args[0], args[1], "vector::dot")
    v = float(np.dot(a, b))
    return int(v) if v.is_integer() else v


@register("vector::cross")
def _cross(args, ctx):
    a, b = _pair(args[0], args[1], "vector::cross")
    if a.shape != (3,):
        raise SdbError("Incorrect arguments for function vector::cross(). The two vectors must be of dimension 3")
    return _out(np.cross(a, b))


@register("vector::magnitude")
def _magnitude(args, ctx):
    a = _vec(args[0], "vector::magnitude")
    return float(np.linalg.norm(a))


@register("vector::normalize")
def _normalize(args, ctx):
    a = _vec(args[0], "vector::normalize")
    n = np.linalg.norm(a)
    if n == 0:
        return _outf(a)
    return _outf(a / n)


@register("vector::project")
def _project(args, ctx):
    a, b = _pair(args[0], args[1], "vector::project")
    denom = float(np.dot(b, b))
    if denom == 0:
        raise SdbError("Incorrect arguments for function vector::project(). Cannot project onto a zero vector")
    return _outf(b * (float(np.dot(a, b)) / denom))


@register("vector::angle")
def _angle(args, ctx):
    a, b = _pair(args[0], args[1], "vector::angle")
    na, nb = np.linalg.norm(a), np.linalg.norm(b)
    if na == 0 or nb == 0:
        raise SdbError("Incorrect arguments for function vector::angle(). Cannot compute the angle of a zero vector")
    c = float(np.dot(a, b) / (na * nb))
    return math.acos(max(-1.0, min(1.0, c)))


# -- distances ----------------------------------------------------------------


@register("vector::distance::euclidean")
def _euclidean(args, ctx):
    a, b = _pair(args[0], args[1], "vector::distance::euclidean")
    return float(np.linalg.norm(a - b))


@register("vector::distance::manhattan")
def _manhattan(args, ctx):
    a, b = _pair(args[0], args[1], "vector::distance::manhattan")
    v = float(np.abs(a - b).sum())
    return int(v) if v.is_integer() else v


@register("vector::distance::chebyshev")
def _chebyshev(args, ctx):
    a, b = _pair(args[0], args[1], "vector::distance::chebyshev")
    return float(np.abs(a - b).max()) if a.size else 0.0


@register("vector::distance::hamming")
def _hamming(args, ctx):
    a, b = _pair(args[0], args[1], "vector::distance::hamming")
    return int((a != b).sum())


@register("vector::distance::minkowski")
def _minkowski(args, ctx):
    a, b = _pair(args[0], args[1], "vector::distance::minkowski")
    p = float(args[2])
    if p <= 0:
        raise SdbError("Incorrect arguments for function vector::distance::minkowski(). The order must be positive")
    return float(np.power(np.power(np.abs(a - b), p).sum(), 1.0 / p))


@register("vector::distance::mahalanobis")
def _mahalanobis(args, ctx):
    raise SdbError("The function 'vector::distance::mahalanobis' is not yet implemented")


@register("vector::distance::knn")
def _knn_dist(args, ctx):
    """Distance computed by the KNN operator for the current record
    (reference: exec/function/index.rs:289 KnnContext)."""
    if ctx.knn is None or ctx.doc_id is None:
        return NONE
    from surrealdb_tpu.val import hashable

    ref = int(args[0]) if args else 0
    d = ctx.knn.get(hashable(ctx.doc_id))
    return d if d is not None else NONE


# -- similarity ---------------------------------------------------------------


@register("vector::similarity::cosine")
def _cosine(args, ctx):
    a, b = _pair(args[0], args[1], "vector::similarity::cosine")
    na, nb = np.linalg.norm(a), np.linalg.norm(b)
    if na == 0 or nb == 0:
        return float("nan")
    return float(np.dot(a, b) / (na * nb))


@register("vector::distance::cosine")
def _cosine_dist(args, ctx):
    return 1.0 - _cosine(args, ctx)


@register("vector::similarity::jaccard")
def _jaccard(args, ctx):
    a = set(map(float, _vec(args[0], "f")))
    b = set(map(float, _vec(args[1], "f")))
    if not a and not b:
        return 1.0
    return len(a & b) / len(a | b)


@register("vector::similarity::pearson")
def _pearson(args, ctx):
    a, b = _pair(args[0], args[1], "vector::similarity::pearson")
    if a.size < 2:
        return float("nan")
    sa, sb = a.std(), b.std()
    if sa == 0 or sb == 0:
        return float("nan")
    return float(((a - a.mean()) * (b - b.mean())).mean() / (sa * sb))


@register("vector::similarity::spearman")
def _spearman(args, ctx):
    a, b = _pair(args[0], args[1], "vector::similarity::spearman")

    def rank(x):
        order = np.argsort(x)
        r = np.empty_like(order, dtype=np.float64)
        r[order] = np.arange(1, len(x) + 1)
        # average ties
        vals, inv, counts = np.unique(x, return_inverse=True, return_counts=True)
        sums = np.zeros(len(vals))
        np.add.at(sums, inv, r)
        return sums[inv] / counts[inv]

    ra, rb = rank(a), rank(b)
    return _pearson([list(ra), list(rb)], ctx)
