"""type:: conversion & predicate functions, object:: and record:: families."""

from __future__ import annotations

from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.expr.ast import Kind
from surrealdb_tpu.fnc import _arr, _str, register
from surrealdb_tpu.val import (
    NONE,
    SSet,
    Datetime,
    Duration,
    File,
    Geometry,
    Range,
    RecordId,
    Regex,
    Table,
    Uuid,
)


def _cast_to(name):
    from surrealdb_tpu.exec.coerce import cast

    def fn(args, ctx):
        return cast(args[0], Kind(name))

    return fn


for _n in ("bool", "bytes", "datetime", "decimal", "duration", "float", "int",
           "number", "string", "uuid", "regex", "array", "geometry"):
    register(f"type::{_n}")(_cast_to(_n))


@register("type::set")
def _type_set(args, ctx):
    from surrealdb_tpu.exec.coerce import cast

    try:
        return cast(args[0], Kind("set"))
    except SdbError:
        # the FUNCTION's failure names `set` (functions/type/set.surql),
        # unlike the <set> cast which converts through `array`
        from surrealdb_tpu.val import render

        raise SdbError(
            f"Could not cast into `set` using input `{render(args[0])}`"
        )


@register("type::string_lossy")
def _string_lossy(args, ctx):
    from surrealdb_tpu.exec.coerce import cast

    return cast(args[0], Kind("string"))


@register("type::point")
def _point(args, ctx):
    if len(args) == 2:
        return Geometry("Point", (float(args[0]), float(args[1])))
    v = args[0]
    if isinstance(v, Geometry) and v.kind == "Point":
        return v
    if isinstance(v, list) and len(v) == 2:
        return Geometry("Point", (float(v[0]), float(v[1])))
    raise SdbError("Incorrect arguments for function type::point()")


@register("type::table")
def _table(args, ctx):
    v = args[0]
    if isinstance(v, Table):
        return v
    if isinstance(v, RecordId):
        return Table(v.tb)
    from surrealdb_tpu.exec.operators import to_string

    return Table(to_string(v))


def _thing(args, ctx):
    """2.x type::thing — kept callable for internal use; the parser
    rejects the path with a `type::record` hint (path_hints suite)."""
    tb = args[0]
    tbname = tb.name if isinstance(tb, Table) else tb
    if isinstance(tb, RecordId) and len(args) == 1:
        return tb
    if len(args) == 1:
        if isinstance(tb, str):
            from surrealdb_tpu.exec.static_eval import static_value
            from surrealdb_tpu.syn.parser import parse_record_literal

            return static_value(parse_record_literal(tb))
        raise SdbError("Incorrect arguments for function type::thing()")
    idv = args[1]
    if isinstance(idv, RecordId):
        idv = idv.id
    if isinstance(idv, float) and idv.is_integer():
        idv = int(idv)
    return RecordId(str(tbname), idv)


@register("type::record")
def _record(args, ctx):
    """type::record(value) parses; type::record(tb, key) builds
    (reference fnc/type.rs:139)."""
    v = args[0]
    if len(args) > 1:
        tb = v.name if isinstance(v, Table) else v
        if not isinstance(tb, str) or not tb:
            raise SdbError("Incorrect arguments for function type::record()")
        key = args[1]
        if isinstance(key, RecordId):
            key = key.id
        elif isinstance(key, float):
            key = str(key) if not key.is_integer() else int(key)
        from surrealdb_tpu.exec.document import record_id_key

        return RecordId(tb, record_id_key(key))
    if isinstance(v, RecordId):
        return v
    if isinstance(v, str):
        from surrealdb_tpu.exec.static_eval import static_value
        from surrealdb_tpu.syn.parser import parse_record_literal

        return static_value(parse_record_literal(v))
    raise SdbError("Incorrect arguments for function type::record()")


@register("type::range")
def _range(args, ctx):
    v = args[0]
    if isinstance(v, Range):
        return v
    if isinstance(v, list):
        if len(v) == 2:
            return Range(v[0], v[1], True, False)
    raise SdbError("Incorrect arguments for function type::range()")


@register("type::field")
def _field(args, ctx):
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.syn.parser import Parser

    path = _str(args[0], "type::field", 1)
    node = Parser(path).parse_expr()
    return evaluate(node, ctx)


@register("type::fields")
def _fields(args, ctx):
    return [_field([p], ctx) for p in _arr(args[0], "type::fields", 1)]


@register("type::file")
def _file(args, ctx):
    return File(_str(args[0], "f", 1), _str(args[1], "f", 2) if len(args) > 1 else "")


# -- predicates ---------------------------------------------------------------

_PRED = {
    "array": lambda v: isinstance(v, list),
    "bool": lambda v: isinstance(v, bool),
    "bytes": lambda v: isinstance(v, (bytes, bytearray)),
    "collection": lambda v: isinstance(v, Geometry) and v.kind == "GeometryCollection",
    "datetime": lambda v: isinstance(v, Datetime),
    "decimal": lambda v: isinstance(v, Decimal),
    "duration": lambda v: isinstance(v, Duration),
    "float": lambda v: isinstance(v, float),
    "geometry": lambda v: isinstance(v, Geometry),
    "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "line": lambda v: isinstance(v, Geometry) and v.kind == "LineString",
    "none": lambda v: v is NONE,
    "null": lambda v: v is None,
    "multiline": lambda v: isinstance(v, Geometry) and v.kind == "MultiLineString",
    "multipoint": lambda v: isinstance(v, Geometry) and v.kind == "MultiPoint",
    "multipolygon": lambda v: isinstance(v, Geometry) and v.kind == "MultiPolygon",
    "number": lambda v: isinstance(v, (int, float, Decimal)) and not isinstance(v, bool),
    "object": lambda v: isinstance(v, dict),
    "point": lambda v: isinstance(v, Geometry) and v.kind == "Point",
    "polygon": lambda v: isinstance(v, Geometry) and v.kind == "Polygon",
    "string": lambda v: isinstance(v, str),
    "uuid": lambda v: isinstance(v, Uuid),
    "range": lambda v: isinstance(v, Range),
    "set": lambda v: isinstance(v, SSet),
}

for _name, _fn in _PRED.items():
    def _mk(fn):
        def g(args, ctx):
            return fn(args[0])

        return g

    register(f"type::is::{_name}")(_mk(_fn))


@register("type::is::record")
def _is_record(args, ctx):
    v = args[0]
    if not isinstance(v, RecordId):
        return False
    if len(args) > 1:
        want = args[1]
        tbname = want.name if isinstance(want, Table) else want
        return v.tb == tbname
    return True


@register("type::of")
def _type_of(args, ctx):
    from surrealdb_tpu.exec.coerce import _type_name

    return _type_name(args[0])


# -- object:: -----------------------------------------------------------------


def _obj(v, fname, idx=1):
    if not isinstance(v, dict):
        from surrealdb_tpu.val import render

        raise SdbError(
            f"Incorrect arguments for function {fname}(). Argument {idx} "
            f"was the wrong type. Expected `object` but found `{render(v)}`"
        )
    return v


@register("object::entries")
def _entries(args, ctx):
    return [[k, v] for k, v in _obj(args[0], "object::entries").items()]


@register("object::from_entries")
def _from_entries(args, ctx):
    out = {}
    for it in _arr(args[0], "object::from_entries", 1):
        if isinstance(it, list) and len(it) == 2:
            out[str(it[0])] = it[1]
    return out


@register("object::keys")
def _keys(args, ctx):
    return list(_obj(args[0], "object::keys").keys())


@register("object::values")
def _values(args, ctx):
    return list(_obj(args[0], "object::values").values())


@register("object::len")
def _olen(args, ctx):
    return len(_obj(args[0], "object::len"))


@register("object::is_empty")
def _oempty(args, ctx):
    return len(_obj(args[0], "object::is_empty")) == 0


@register("object::extend")
def _oextend(args, ctx):
    out = dict(_obj(args[0], "object::extend"))
    out.update(_obj(args[1], "object::extend"))
    return out


@register("object::remove")
def _oremove(args, ctx):
    from surrealdb_tpu.val import render

    out = dict(_obj(args[0], "object::remove"))
    keys = args[1] if isinstance(args[1], list) else [args[1]]
    for k in keys:
        if not isinstance(k, str):
            raise SdbError(
                f"Incorrect arguments for function object::remove(). "
                f"{render(k)!r} cannot be used as a key. "
                f"Please use a string instead.".replace('"', "'")
            )
        out.pop(k, None)
    return out


# -- record:: -----------------------------------------------------------------


@register("record::is_edge")
def _ris_edge(args, ctx):
    from surrealdb_tpu.exec.eval import fetch_record
    from surrealdb_tpu.val import NONE as _N

    v = args[0]
    if isinstance(v, str):
        # string record ids coerce (reference fnc/record.rs is_edge takes
        # a Thing conversion)
        from surrealdb_tpu.exec.eval import evaluate
        from surrealdb_tpu.syn.parser import parse_record_literal

        try:
            v = evaluate(parse_record_literal(v), ctx)
        except (SdbError, ValueError):
            v = None
    if not isinstance(v, RecordId):
        raise SdbError(
            "Incorrect arguments for function record::is_edge(). "
            "Expected a record ID"
        )
    doc = fetch_record(ctx, v)
    return (
        isinstance(doc, dict)
        and isinstance(doc.get("in"), RecordId)
        and isinstance(doc.get("out"), RecordId)
    )


@register("schema::table::exists")
def _schema_tb_exists(args, ctx):
    from surrealdb_tpu import key as K2

    tb = args[0]
    if not isinstance(tb, str):
        raise SdbError(
            "Incorrect arguments for function schema::table::exists(). "
            "Expected a string"
        )
    ns, db = ctx.need_ns_db()
    return ctx.txn.get(K2.tb_def(ns, db, tb)) is not None


@register("record::exists")
def _rexists(args, ctx):
    from surrealdb_tpu.exec.eval import fetch_record

    v = args[0]
    if not isinstance(v, RecordId):
        raise SdbError("Incorrect arguments for function record::exists(). Expected a record")
    return fetch_record(ctx, v) is not NONE


@register("record::id")
def _rid(args, ctx):
    v = args[0]
    if not isinstance(v, RecordId):
        raise SdbError("Incorrect arguments for function record::id(). Expected a record")
    return v.id


@register("record::tb")
def _rtb(args, ctx):
    v = args[0]
    if not isinstance(v, RecordId):
        raise SdbError("Incorrect arguments for function record::tb(). Expected a record")
    return v.tb


from surrealdb_tpu.fnc import FUNCS as _F  # noqa: E402

_F["record::table"] = _F["record::tb"]
_F["meta::id"] = _F["record::id"]
_F["meta::tb"] = _F["record::tb"]


@register("record::refs")
def _refs(args, ctx):
    """Records referencing this one (reverse record-link lookup)."""
    v = args[0]
    if not isinstance(v, RecordId):
        raise SdbError("Incorrect arguments for function record::refs(). Expected a record")
    from surrealdb_tpu.graph import find_references

    tb = args[1] if len(args) > 1 else None
    ff = args[2] if len(args) > 2 else None
    return find_references(v, ctx, tb, ff)
