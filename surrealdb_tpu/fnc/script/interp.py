"""A small tree-walking ECMAScript-subset interpreter.

Original design (tokenizer → Pratt parser → environment-chain evaluator);
implements the slice of JS the reference's embedded scripts use. Scripts
are synchronous here, so `await x` evaluates to x (the host query API
returns values directly).
"""

from __future__ import annotations

import math
import re as _re
from decimal import Decimal

from surrealdb_tpu.val import (
    NONE,
    Datetime,
    Duration,
    Geometry,
    RecordId,
    Uuid,
)


class JSError(Exception):
    def __init__(self, message):
        super().__init__(message)
        self.message = message


class JSUndefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"


UNDEF = JSUndefined()


class BigInt(int):
    """A JS BigInt — distinct type so 1n !== 1 and values round-trip."""


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RX = _re.compile(
    r"""
    (?P<ws>[\s]+)
  | (?P<comment>//[^\n]*|/\*.*?\*/)
  | (?P<template>`(?:[^`\\]|\\.)*`)
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<bigint>\d+n)
  | (?P<number>0[xX][0-9a-fA-F]+|\d+\.\d+(?:[eE][+-]?\d+)?|\.\d+|\d+(?:[eE][+-]?\d+)?)
  | (?P<ident>[A-Za-z_$][A-Za-z0-9_$]*)
  | (?P<punct>=>|\.\.\.|===|!==|==|!=|<=|>=|&&|\|\||\*\*|\+\+|--|\+=|-=|\*=|/=|%=|\?\.|[{}()\[\];,.<>+\-*/%!?:=&|^~])
    """,
    _re.X | _re.S,
)

_KEYWORDS = {
    "function", "return", "if", "else", "for", "while", "do", "let",
    "const", "var", "new", "typeof", "throw", "try", "catch", "finally",
    "true", "false", "null", "undefined", "await", "async", "of", "in",
    "break", "continue", "delete", "instanceof",
}


def tokenize(src: str):
    toks = []
    i = 0
    n = len(src)
    while i < n:
        m = _TOKEN_RX.match(src, i)
        if m is None:
            raise JSError(f"Unexpected token at position {i}")
        i = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind in ("ws", "comment"):
            continue
        toks.append((kind, text))
    toks.append(("eof", ""))
    return toks


# ---------------------------------------------------------------------------
# parser — produces tuple-based AST nodes
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self, off=0):
        return self.toks[min(self.i + off, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def at(self, text):
        return self.peek()[1] == text and self.peek()[0] in ("punct", "ident")

    def eat(self, text):
        if self.at(text):
            self.next()
            return True
        return False

    def expect(self, text):
        if not self.eat(text):
            raise JSError(f"Expected '{text}' but found '{self.peek()[1]}'")

    # -- statements ---------------------------------------------------------
    def parse_block(self):
        self.expect("{")
        stmts = []
        while not self.at("}"):
            stmts.append(self.parse_stmt())
        self.expect("}")
        return ("block", stmts)

    def parse_stmt(self):
        k, t = self.peek()
        if t == "{":
            return self.parse_block()
        if t in ("let", "const", "var"):
            self.next()
            decls = []
            while True:
                name = self.next()[1]
                init = None
                if self.eat("="):
                    init = self.parse_assign()
                decls.append((name, init))
                if not self.eat(","):
                    break
            self.eat(";")
            return ("decl", decls)
        if t == "return":
            self.next()
            if self.at(";") or self.at("}"):
                self.eat(";")
                return ("return", None)
            e = self.parse_expr()
            self.eat(";")
            return ("return", e)
        if t == "throw":
            self.next()
            e = self.parse_expr()
            self.eat(";")
            return ("throw", e)
        if t == "if":
            self.next()
            self.expect("(")
            cond = self.parse_expr()
            self.expect(")")
            then = self.parse_stmt()
            other = None
            if self.eat("else"):
                other = self.parse_stmt()
            return ("if", cond, then, other)
        if t == "while":
            self.next()
            self.expect("(")
            cond = self.parse_expr()
            self.expect(")")
            body = self.parse_stmt()
            return ("while", cond, body)
        if t == "for":
            return self.parse_for()
        if t == "try":
            self.next()
            block = self.parse_block()
            param = None
            handler = None
            final = None
            if self.eat("catch"):
                if self.eat("("):
                    param = self.next()[1]
                    self.expect(")")
                handler = self.parse_block()
            if self.eat("finally"):
                final = self.parse_block()
            return ("try", block, param, handler, final)
        if t == "break":
            self.next()
            self.eat(";")
            return ("break",)
        if t == "continue":
            self.next()
            self.eat(";")
            return ("continue",)
        if t == ";":
            self.next()
            return ("empty",)
        e = self.parse_expr()
        self.eat(";")
        return ("expr", e)

    def parse_for(self):
        self.expect("for")
        self.expect("(")
        if self.peek()[1] in ("let", "const", "var") and \
                self.peek(2)[1] == "of":
            self.next()
            name = self.next()[1]
            self.expect("of")
            it = self.parse_expr()
            self.expect(")")
            body = self.parse_stmt()
            return ("forof", name, it, body)
        init = None
        if not self.at(";"):
            init = self.parse_stmt()
        else:
            self.next()
        cond = None
        if not self.at(";"):
            cond = self.parse_expr()
        self.expect(";")
        step = None
        if not self.at(")"):
            step = self.parse_expr()
        self.expect(")")
        body = self.parse_stmt()
        return ("for", init, cond, step, body)

    # -- expressions (Pratt) -------------------------------------------------
    def parse_expr(self):
        e = self.parse_assign()
        while self.eat(","):
            e2 = self.parse_assign()
            e = ("seq", e, e2)
        return e

    def parse_assign(self):
        # arrow functions: ident => ... | (a, b) => ...
        save = self.i
        arrow = self._try_arrow()
        if arrow is not None:
            return arrow
        self.i = save
        left = self.parse_ternary()
        k, t = self.peek()
        if t in ("=", "+=", "-=", "*=", "/=", "%="):
            self.next()
            right = self.parse_assign()
            return ("assign", t, left, right)
        return left

    def _try_arrow(self):
        params = None
        k, t = self.peek()
        if k == "ident" and t not in _KEYWORDS and self.peek(1)[1] == "=>":
            params = [t]
            self.next()
        elif t == "(":
            j = self.i
            depth = 0
            while j < len(self.toks):
                tt = self.toks[j][1]
                if tt == "(":
                    depth += 1
                elif tt == ")":
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            if j + 1 < len(self.toks) and self.toks[j + 1][1] == "=>":
                self.next()
                params = []
                while not self.at(")"):
                    if self.eat("..."):
                        params.append(("rest", self.next()[1]))
                    else:
                        params.append(self.next()[1])
                    self.eat(",")
                self.expect(")")
            else:
                return None
        else:
            return None
        if params is None:
            return None
        self.expect("=>")
        if self.at("{"):
            body = self.parse_block()
            return ("func", params, body, True)
        body = self.parse_assign()
        return ("func", params, ("return", body), True)

    def parse_ternary(self):
        cond = self.parse_binary(0)
        if self.eat("?"):
            a = self.parse_assign()
            self.expect(":")
            b = self.parse_assign()
            return ("ternary", cond, a, b)
        return cond

    _BIN_PREC = {
        "||": 1, "&&": 2, "|": 3, "^": 4, "&": 5,
        "===": 6, "!==": 6, "==": 6, "!=": 6,
        "<": 7, ">": 7, "<=": 7, ">=": 7, "instanceof": 7, "in": 7,
        "+": 9, "-": 9, "*": 10, "/": 10, "%": 10, "**": 11,
    }

    def parse_binary(self, minp):
        left = self.parse_unary()
        while True:
            t = self.peek()[1]
            prec = self._BIN_PREC.get(t)
            if prec is None or prec < minp:
                return left
            self.next()
            right = self.parse_binary(prec + 1)
            left = ("bin", t, left, right)

    def parse_unary(self):
        k, t = self.peek()
        if t in ("!", "-", "+", "~", "typeof", "await", "delete"):
            self.next()
            return ("unary", t, self.parse_unary())
        if t in ("++", "--"):
            self.next()
            tgt = self.parse_unary()
            return ("update", t, tgt, True)
        e = self.parse_postfix()
        t = self.peek()[1]
        if t in ("++", "--"):
            self.next()
            return ("update", t, e, False)
        return e

    def parse_postfix(self):
        k, t = self.peek()
        if t == "new":
            self.next()
            callee = self.parse_member_chain(self.parse_primary(), no_call=True)
            args = []
            if self.eat("("):
                while not self.at(")"):
                    args.append(self.parse_assign())
                    self.eat(",")
                self.expect(")")
            e = ("new", callee, args)
            return self.parse_member_chain(e)
        return self.parse_member_chain(self.parse_primary())

    def parse_member_chain(self, e, no_call=False):
        while True:
            t = self.peek()[1]
            if t == ".":
                self.next()
                name = self.next()[1]
                e = ("member", e, name, False)
            elif t == "?.":
                self.next()
                name = self.next()[1]
                e = ("member", e, name, True)
            elif t == "[":
                self.next()
                idx = self.parse_expr()
                self.expect("]")
                e = ("index", e, idx)
            elif t == "(" and not no_call:
                self.next()
                args = []
                while not self.at(")"):
                    if self.eat("..."):
                        args.append(("spread", self.parse_assign()))
                    else:
                        args.append(self.parse_assign())
                    self.eat(",")
                self.expect(")")
                e = ("call", e, args)
            elif self.peek()[0] == "template":
                # tagged templates unsupported; stop
                return e
            else:
                return e

    def parse_primary(self):
        k, t = self.next()
        if k == "number":
            if t.startswith(("0x", "0X")):
                return ("lit", int(t, 16))
            if "." in t or "e" in t or "E" in t:
                return ("lit", float(t))
            return ("lit", int(t))
        if k == "bigint":
            return ("lit", BigInt(t[:-1]))
        if k == "string":
            return ("lit", _unescape(t[1:-1]))
        if k == "template":
            return self._template(t[1:-1])
        if k == "ident":
            if t == "true":
                return ("lit", True)
            if t == "false":
                return ("lit", False)
            if t == "null":
                return ("lit", None)
            if t == "undefined":
                return ("lit", UNDEF)
            if t == "function":
                return self._function_expr()
            if t == "async":
                if self.peek()[1] == "function":
                    self.next()
                    return self._function_expr()
                # async arrow
                save = self.i
                arrow = self._try_arrow()
                if arrow is not None:
                    return arrow
                self.i = save
                return ("var", t)
            return ("var", t)
        if t == "(":
            e = self.parse_expr()
            self.expect(")")
            return e
        if t == "[":
            items = []
            while not self.at("]"):
                if self.eat("..."):
                    items.append(("spread", self.parse_assign()))
                else:
                    items.append(self.parse_assign())
                self.eat(",")
            self.expect("]")
            return ("array", items)
        if t == "{":
            props = []
            while not self.at("}"):
                if self.eat("..."):
                    props.append(("spread", self.parse_assign()))
                else:
                    pk, pt = self.next()
                    if pk == "string":
                        key = _unescape(pt[1:-1])
                    elif pk in ("number",):
                        key = pt
                    elif pt == "[":
                        key = ("computed", self.parse_expr())
                        self.expect("]")
                    else:
                        key = pt
                    if self.eat(":"):
                        props.append((key, self.parse_assign()))
                    elif self.peek()[1] == "(":
                        # method shorthand
                        fn = self._method_shorthand()
                        props.append((key, fn))
                    else:
                        props.append((key, ("var", key)))
                self.eat(",")
            self.expect("}")
            return ("object", props)
        raise JSError(f"Unexpected token '{t}'")

    def _method_shorthand(self):
        self.expect("(")
        params = []
        while not self.at(")"):
            if self.eat("..."):
                params.append(("rest", self.next()[1]))
            else:
                params.append(self.next()[1])
            self.eat(",")
        self.expect(")")
        body = self.parse_block()
        return ("func", params, body, False)

    def _function_expr(self):
        if self.peek()[0] == "ident" and self.peek()[1] not in _KEYWORDS \
                and self.peek()[1] != "(":
            self.next()  # optional name
        return ("func", *self._fn_tail())

    def _fn_tail(self):
        self.expect("(")
        params = []
        while not self.at(")"):
            if self.eat("..."):
                params.append(("rest", self.next()[1]))
            else:
                params.append(self.next()[1])
            self.eat(",")
        self.expect(")")
        body = self.parse_block()
        return params, body, False

    def _template(self, raw):
        parts = []
        i = 0
        buf = []
        while i < len(raw):
            c = raw[i]
            if c == "\\" and i + 1 < len(raw):
                buf.append(_unescape(raw[i : i + 2]))
                i += 2
                continue
            if c == "$" and i + 1 < len(raw) and raw[i + 1] == "{":
                depth = 1
                j = i + 2
                while j < len(raw) and depth:
                    if raw[j] == "{":
                        depth += 1
                    elif raw[j] == "}":
                        depth -= 1
                    j += 1
                if buf:
                    parts.append(("lit", "".join(buf)))
                    buf = []
                inner = raw[i + 2 : j - 1]
                sub = Parser(tokenize(inner)).parse_expr()
                parts.append(sub)
                i = j
                continue
            buf.append(c)
            i += 1
        if buf:
            parts.append(("lit", "".join(buf)))
        return ("template", parts)


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            n = s[i + 1]
            mapping = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\",
                       "'": "'", '"': '"', "`": "`", "0": "\0", "$": "$",
                       "b": "\b", "f": "\f", "v": "\v", "/": "/"}
            if n == "u" and i + 5 < len(s):
                out.append(chr(int(s[i + 2 : i + 6], 16)))
                i += 6
                continue
            out.append(mapping.get(n, n))
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


# ---------------------------------------------------------------------------
# runtime values
# ---------------------------------------------------------------------------


class JSFunction:
    def __init__(self, params, body, env, interp, is_arrow, this=None):
        self.params = params
        self.body = body
        self.env = env
        self.interp = interp
        self.is_arrow = is_arrow
        self.this = this

    def call(self, this, args):
        env = Env(self.env)
        use_this = self.this if self.is_arrow else this
        env.declare("this", use_this)
        env.declare("arguments", list(args))
        i = 0
        for p in self.params:
            if isinstance(p, tuple) and p[0] == "rest":
                env.declare(p[1], list(args[i:]))
                break
            env.declare(p, args[i] if i < len(args) else UNDEF)
            i += 1
        try:
            self.interp.exec_stmt(self.body, env)
        except _Return as r:
            return r.value
        return UNDEF


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None):
        self.vars = {}
        self.parent = parent

    def declare(self, name, value):
        self.vars[name] = value

    def get(self, name):
        e = self
        while e is not None:
            if name in e.vars:
                return e.vars[name]
            e = e.parent
        raise JSError(f"'{name}' is not defined")

    def has(self, name):
        e = self
        while e is not None:
            if name in e.vars:
                return True
            e = e.parent
        return False

    def set(self, name, value):
        e = self
        while e is not None:
            if name in e.vars:
                e.vars[name] = value
                return
            e = e.parent
        self.vars[name] = value


class JSErrorObj:
    def __init__(self, message):
        self.message = message


# ---------------------------------------------------------------------------
# interpreter
# ---------------------------------------------------------------------------


def _max_ops():
    from surrealdb_tpu import cnf

    return cnf.SCRIPTING_MAX_OPS


class Interpreter:
    def __init__(self, ctx):
        self.ctx = ctx
        self.ops = 0

    # -- entry ---------------------------------------------------------------
    def run_function(self, source: str, args):
        toks = tokenize(source.strip())
        p = Parser(toks)
        # strip leading `function` / `async function`
        if p.peek()[1] == "async":
            p.next()
        p.expect("function")
        params, body, _ = p._fn_tail()
        genv = Env()
        self._install_globals(genv)
        fn = JSFunction(params, body, genv, self, False)
        this = self._doc_this()
        out = fn.call(this, [sql_to_js(a) for a in args])
        return js_to_sql(out)

    def _doc_this(self):
        doc = self.ctx.doc
        if doc is None or doc is NONE:
            return UNDEF
        return sql_to_js(doc)

    # -- globals / host API --------------------------------------------------
    def _install_globals(self, env):
        env.declare("Math", _MATH)
        env.declare("JSON", _JSON)
        env.declare("Object", _OBJECT)
        env.declare("Array", _ARRAY)
        env.declare("Number", _NUMBER)
        env.declare("String", _STRING)
        env.declare("BigInt", ("native", lambda this, a: BigInt(int(a[0]))))
        env.declare("NaN", float("nan"))
        env.declare("Infinity", float("inf"))
        env.declare("Error", ("class_error",))
        env.declare("TypeError", ("class_error",))
        env.declare("RangeError", ("class_error",))
        env.declare("Date", ("class_date",))
        env.declare("Duration", ("class_duration",))
        env.declare("Record", ("class_record",))
        env.declare("Uuid", ("class_uuid",))
        env.declare("Uint8Array", ("class_u8",))
        env.declare("parseInt", ("native", lambda this, a: int(float(a[0]))))
        env.declare("parseFloat", ("native", lambda this, a: float(a[0])))
        env.declare("Promise", {
            "all": ("native", lambda this, a: list(a[0]) if a else []),
            "resolve": ("native", lambda this, a: a[0] if a else UNDEF),
        })
        env.declare("surrealdb", {
            "query": ("native", self._host_query),
            "value": ("native", self._host_value),
            "Query": ("class_query",),
            "functions": self._functions_tree(),
        })
        # script-visible session params: every SurrealQL $var
        for name, val in self.ctx.vars.items():
            if isinstance(name, str) and name.isidentifier():
                if not env.has(name):
                    env.declare(name, sql_to_js(val))

    def _functions_tree(self):
        """surrealdb.functions.<family>.<name>(...) — every registered SQL
        function as a nested host object (reference fnc/script surrealdb
        module bindings)."""
        from surrealdb_tpu.fnc import FUNCS, invoke

        def mk(fname, fn):
            def call(this, args):
                out = invoke(fname, fn, [js_to_sql(a) for a in args],
                             self.ctx)
                return sql_to_js(out)

            return ("native", call)

        tree: dict = {}
        for fname, fn in FUNCS.items():
            if fname.startswith("__"):
                continue
            segs = fname.split("::")
            cur = tree
            ok = True
            for s in segs[:-1]:
                nxt = cur.setdefault(s, {})
                if not isinstance(nxt, dict):
                    ok = False  # name collides with a leaf (e.g. count)
                    break
                cur = nxt
            if ok and isinstance(cur, dict) and not isinstance(
                cur.get(segs[-1]), dict
            ):
                cur[segs[-1]] = mk(fname, fn)
        return tree

    def _host_query(self, this, args):
        q = args[0] if args else ""
        binds = {}
        if isinstance(q, dict) and q.get("__query__") is not None:
            binds.update(q.get("binds") or {})
            q = q["__query__"]
        if len(args) > 1 and isinstance(args[1], dict):
            binds.update(args[1])
        from surrealdb_tpu.syn import parse

        c = self.ctx.child()
        for k, v in binds.items():
            c.vars[k] = js_to_sql(v)
        from surrealdb_tpu.exec.statements import eval_statement

        stmts = parse(str(q))
        out = NONE
        for st in stmts:
            out = eval_statement(st, c)
        return sql_to_js(out)

    def _host_value(self, this, args):
        from surrealdb_tpu.exec.eval import evaluate
        from surrealdb_tpu.syn import parse_value_expr

        src = str(args[0]) if args else ""
        node = parse_value_expr(src)
        return sql_to_js(evaluate(node, self.ctx))

    # -- statements ----------------------------------------------------------
    def exec_stmt(self, node, env):
        self.ops += 1
        if self.ops > _max_ops():
            raise JSError("Max script execution time exceeded")
        tag = node[0]
        if tag == "block":
            benv = Env(env)
            for st in node[1]:
                self.exec_stmt(st, benv)
        elif tag == "decl":
            for name, init in node[1]:
                env.declare(
                    name, self.eval(init, env) if init is not None else UNDEF
                )
        elif tag == "return":
            raise _Return(
                self.eval(node[1], env) if node[1] is not None else UNDEF
            )
        elif tag == "throw":
            v = self.eval(node[1], env)
            if isinstance(v, JSErrorObj):
                raise JSError(v.message)
            raise JSError(js_display(v))
        elif tag == "if":
            if js_truthy(self.eval(node[1], env)):
                self.exec_stmt(node[2], env)
            elif node[3] is not None:
                self.exec_stmt(node[3], env)
        elif tag == "while":
            while js_truthy(self.eval(node[1], env)):
                try:
                    self.exec_stmt(node[2], env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif tag == "for":
            fenv = Env(env)
            if node[1] is not None:
                self.exec_stmt(node[1], fenv)
            while node[2] is None or js_truthy(self.eval(node[2], fenv)):
                try:
                    self.exec_stmt(node[4], fenv)
                except _Break:
                    break
                except _Continue:
                    pass
                if node[3] is not None:
                    self.eval(node[3], fenv)
        elif tag == "forof":
            it = self.eval(node[2], env)
            if isinstance(it, dict):
                it = list(it.values())
            if isinstance(it, str):
                it = list(it)
            for v in it or []:
                fenv = Env(env)
                fenv.declare(node[1], v)
                try:
                    self.exec_stmt(node[3], fenv)
                except _Break:
                    break
                except _Continue:
                    continue
        elif tag == "try":
            try:
                self.exec_stmt(node[1], env)
            except JSError as e:
                if node[3] is not None:
                    henv = Env(env)
                    if node[2]:
                        henv.declare(node[2], JSErrorObj(e.message))
                    self.exec_stmt(node[3], henv)
            finally:
                if node[4] is not None:
                    self.exec_stmt(node[4], env)
        elif tag == "break":
            raise _Break()
        elif tag == "continue":
            raise _Continue()
        elif tag == "empty":
            pass
        elif tag == "expr":
            self.eval(node[1], env)
        else:
            raise JSError(f"Unsupported statement {tag}")

    # -- expressions ---------------------------------------------------------
    def eval(self, node, env):
        self.ops += 1
        if self.ops > _max_ops():
            raise JSError("Max script execution time exceeded")
        tag = node[0]
        if tag == "lit":
            return node[1]
        if tag == "var":
            return env.get(node[1])
        if tag == "template":
            out = []
            for p in node[1]:
                v = self.eval(p, env)
                out.append(v if isinstance(v, str) else js_display(v))
            return "".join(out)
        if tag == "array":
            out = []
            for it in node[1]:
                if it[0] == "spread":
                    sv = self.eval(it[1], env)
                    out.extend(sv if isinstance(sv, list) else list(sv))
                else:
                    out.append(self.eval(it, env))
            return out
        if tag == "object":
            out = {}
            for key, vexpr in node[1]:
                if key == "spread":
                    sv = self.eval(vexpr, env)
                    if isinstance(sv, dict):
                        out.update(sv)
                    continue
                if isinstance(key, tuple) and key[0] == "computed":
                    key = js_display(self.eval(key[1], env))
                out[key] = self.eval(vexpr, env)
            return out
        if tag == "func":
            return JSFunction(
                node[1], node[2], env, self, node[3],
                this=env.get("this") if env.has("this") else UNDEF,
            )
        if tag == "seq":
            self.eval(node[1], env)
            return self.eval(node[2], env)
        if tag == "ternary":
            return (
                self.eval(node[2], env)
                if js_truthy(self.eval(node[1], env))
                else self.eval(node[3], env)
            )
        if tag == "unary":
            op = node[1]
            if op == "await":
                return self.eval(node[2], env)
            if op == "typeof":
                try:
                    v = self.eval(node[2], env)
                except JSError:
                    return "undefined"
                return js_typeof(v)
            v = self.eval(node[2], env)
            if op == "!":
                return not js_truthy(v)
            if op == "-":
                if isinstance(v, BigInt):
                    return BigInt(-int(v))
                return -js_num(v)
            if op == "+":
                return js_num(v)
            if op == "~":
                return ~int(js_num(v))
            if op == "delete":
                return True
            raise JSError(f"Unsupported unary {op}")
        if tag == "update":
            op, target, prefix = node[1], node[2], node[3]
            cur = js_num(self.eval(target, env))
            new = cur + 1 if op == "++" else cur - 1
            self._assign_to(target, new, env)
            return new if prefix else cur
        if tag == "bin":
            return self._binop(node[1], node[2], node[3], env)
        if tag == "assign":
            op = node[1]
            if op == "=":
                v = self.eval(node[3], env)
            else:
                cur = self.eval(node[2], env)
                rhs = self.eval(node[3], env)
                v = self._arith(op[0], cur, rhs)
            self._assign_to(node[2], v, env)
            return v
        if tag == "member":
            obj = self.eval(node[1], env)
            if node[3] and (obj is UNDEF or obj is None):
                return UNDEF
            return self._member(obj, node[2])
        if tag == "index":
            obj = self.eval(node[1], env)
            idx = self.eval(node[2], env)
            return self._index(obj, idx)
        if tag == "call":
            return self._call(node, env)
        if tag == "new":
            return self._new(node, env)
        if tag == "spread":
            return self.eval(node[1], env)
        raise JSError(f"Unsupported expression {tag}")

    def _assign_to(self, target, value, env):
        tag = target[0]
        if tag == "var":
            env.set(target[1], value)
        elif tag == "member":
            obj = self.eval(target[1], env)
            if isinstance(obj, dict):
                obj[target[2]] = value
            else:
                setattr(obj, target[2], value)
        elif tag == "index":
            obj = self.eval(target[1], env)
            idx = self.eval(target[2], env)
            if isinstance(obj, list):
                i = int(js_num(idx))
                while len(obj) <= i:
                    obj.append(UNDEF)
                obj[i] = value
            elif isinstance(obj, dict):
                obj[js_display(idx)] = value
        else:
            raise JSError("Invalid assignment target")

    def _binop(self, op, le, re_, env):
        if op == "&&":
            lv = self.eval(le, env)
            return self.eval(re_, env) if js_truthy(lv) else lv
        if op == "||":
            lv = self.eval(le, env)
            return lv if js_truthy(lv) else self.eval(re_, env)
        lv = self.eval(le, env)
        rv = self.eval(re_, env)
        if op in ("+", "-", "*", "/", "%", "**"):
            return self._arith(op, lv, rv)
        if op == "===":
            return js_strict_eq(lv, rv)
        if op == "!==":
            return not js_strict_eq(lv, rv)
        if op == "==":
            return js_loose_eq(lv, rv)
        if op == "!=":
            return not js_loose_eq(lv, rv)
        if op in ("<", ">", "<=", ">="):
            if isinstance(lv, str) and isinstance(rv, str):
                a, b = lv, rv
            else:
                a, b = js_num(lv), js_num(rv)
            return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[op]
        if op == "instanceof":
            if isinstance(rv, tuple) and rv:
                kind = rv[0]
                if kind == "class_u8":
                    return isinstance(lv, (bytes, bytearray))
                if kind == "class_error":
                    return isinstance(lv, JSErrorObj)
                if kind == "class_date":
                    return isinstance(lv, _HostValue) and isinstance(
                        lv.value, Datetime
                    )
                if kind == "class_duration":
                    return isinstance(lv, _HostValue) and isinstance(
                        lv.value, Duration
                    )
                if kind == "class_record":
                    return isinstance(lv, _HostValue) and isinstance(
                        lv.value, RecordId
                    )
                if kind == "class_uuid":
                    return isinstance(lv, _HostValue) and isinstance(
                        lv.value, Uuid
                    )
            if rv is _ARRAY or (isinstance(rv, dict) and rv is _ARRAY):
                return isinstance(lv, list)
            return False
        if op == "in":
            return js_display(lv) in rv if isinstance(rv, dict) else False
        if op in ("&", "|", "^"):
            a, b = int(js_num(lv)), int(js_num(rv))
            return {"&": a & b, "|": a | b, "^": a ^ b}[op]
        raise JSError(f"Unsupported operator {op}")

    def _arith(self, op, lv, rv):
        if op == "+" and (isinstance(lv, str) or isinstance(rv, str)):
            return (lv if isinstance(lv, str) else js_display(lv)) + (
                rv if isinstance(rv, str) else js_display(rv)
            )
        if isinstance(lv, BigInt) and isinstance(rv, BigInt):
            a, b = int(lv), int(rv)
            out = {
                "+": a + b, "-": a - b, "*": a * b,
                "/": a // b if b else 0, "%": a % b if b else 0,
                "**": a ** b,
            }[op]
            return BigInt(out)
        a, b = js_num(lv), js_num(rv)
        if op == "+":
            r = a + b
        elif op == "-":
            r = a - b
        elif op == "*":
            r = a * b
        elif op == "/":
            if b == 0:
                return float("nan") if a == 0 else math.copysign(
                    float("inf"), a * (1 if b >= 0 else -1)
                )
            r = a / b
        elif op == "%":
            if b == 0:
                return float("nan")
            r = math.fmod(a, b)
        elif op == "**":
            r = a ** b
        else:
            raise JSError(f"Unsupported operator {op}")
        if isinstance(a, int) and isinstance(b, int) and isinstance(r, int):
            return r
        if isinstance(r, float) and r.is_integer() and op != "/":
            return r
        return r

    # -- member access / methods ---------------------------------------------
    def _member(self, obj, name):
        if isinstance(obj, dict):
            if name in obj:
                return obj[name]
            meth = _object_method(obj, name)
            if meth is not None:
                return meth
            return UNDEF
        if isinstance(obj, list):
            if name == "length":
                return len(obj)
            meth = _array_method(obj, name, self)
            if meth is not None:
                return meth
            return UNDEF
        if isinstance(obj, str):
            if name == "length":
                return len(obj)
            meth = _string_method(obj, name)
            if meth is not None:
                return meth
            return UNDEF
        if isinstance(obj, (bytes, bytearray)):
            if name == "length":
                return len(obj)
            return UNDEF
        if isinstance(obj, JSErrorObj):
            if name == "message":
                return obj.message
            return UNDEF
        if isinstance(obj, _HostValue):
            return obj.member(name)
        if obj is UNDEF or obj is None:
            raise JSError(
                f"Cannot read properties of "
                f"{'undefined' if obj is UNDEF else 'null'} "
                f"(reading '{name}')"
            )
        return UNDEF

    def _index(self, obj, idx):
        if isinstance(obj, list):
            i = int(js_num(idx))
            if 0 <= i < len(obj):
                return obj[i]
            return UNDEF
        if isinstance(obj, str):
            i = int(js_num(idx))
            if 0 <= i < len(obj):
                return obj[i]
            return UNDEF
        if isinstance(obj, (bytes, bytearray)):
            i = int(js_num(idx))
            if 0 <= i < len(obj):
                return obj[i]
            return UNDEF
        if isinstance(obj, dict):
            return obj.get(js_display(idx), UNDEF)
        return UNDEF

    def _call(self, node, env):
        callee = node[1]
        args = []
        for a in node[2]:
            if a[0] == "spread":
                sv = self.eval(a[1], env)
                args.extend(sv if isinstance(sv, list) else list(sv))
            else:
                args.append(self.eval(a, env))
        if callee[0] in ("member", "index"):
            obj = self.eval(callee[1], env)
            if callee[0] == "member":
                if callee[3] and (obj is UNDEF or obj is None):
                    return UNDEF
                fn = self._member(obj, callee[2])
            else:
                fn = self._index(obj, self.eval(callee[2], env))
            return self._invoke(fn, obj, args, callee)
        fn = self.eval(callee, env)
        return self._invoke(fn, UNDEF, args, callee)

    def _invoke(self, fn, this, args, callee=None):
        if isinstance(fn, JSFunction):
            return fn.call(this, args)
        if isinstance(fn, tuple) and fn and fn[0] == "native":
            return fn[1](this, args)
        if callable(fn) and not isinstance(fn, tuple):
            return fn(this, args)
        name = ""
        if callee is not None and callee[0] == "member":
            name = callee[2]
        raise JSError(f"'{name or js_display(fn)}' is not a function")

    def _new(self, node, env):
        cls = self.eval(node[1], env)
        args = [self.eval(a, env) for a in node[2]]
        if isinstance(cls, tuple):
            kind = cls[0]
            if kind == "class_error":
                return JSErrorObj(js_display(args[0]) if args else "")
            if kind == "class_date":
                if args and isinstance(args[0], str):
                    return _HostValue(Datetime.parse(args[0]))
                if args and isinstance(args[0], _HostValue) and isinstance(
                    args[0].value, Datetime
                ):
                    return args[0]
                return _HostValue(Datetime.now())
            if kind == "class_duration":
                return _HostValue(Duration.parse(str(args[0])))
            if kind == "class_record":
                tb = str(args[0])
                key = js_to_sql(args[1]) if len(args) > 1 else None
                return _HostValue(RecordId(tb, key))
            if kind == "class_uuid":
                return _HostValue(Uuid(str(args[0])))
            if kind == "class_u8":
                if args and isinstance(args[0], list):
                    return bytes(int(js_num(x)) & 0xFF for x in args[0])
                if args and isinstance(args[0], (int, float)):
                    return bytes(int(args[0]))
                return b""
            if kind == "class_query":
                return {
                    "__query__": str(args[0]) if args else "",
                    "binds": {},
                    "bind": ("native", _query_bind),
                }
        if isinstance(cls, JSFunction):
            this = {}
            out = cls.call(this, args)
            return out if isinstance(out, dict) else this
        raise JSError("not a constructor")


def _query_bind(this, args):
    if isinstance(this, dict):
        this.setdefault("binds", {})[js_display(args[0])] = (
            args[1] if len(args) > 1 else UNDEF
        )
    return this


class _HostValue:
    """A SurrealQL value passed through JS untouched (Datetime, Duration,
    RecordId, Uuid, Geometry...)."""

    def __init__(self, value):
        self.value = value

    def member(self, name):
        v = self.value
        if isinstance(v, RecordId):
            if name == "tb":
                return v.tb
            if name == "id":
                return sql_to_js(v.id)
        if isinstance(v, Datetime):
            if name == "getTime":
                return ("native", lambda this, a: v.epoch_ns() // 1_000_000)
            if name == "toISOString":
                return ("native", lambda this, a: v.render()[2:-1])
        if name == "toString":
            from surrealdb_tpu.val import render

            return ("native", lambda this, a: render(v))
        return UNDEF


# ---------------------------------------------------------------------------
# value bridge + helpers
# ---------------------------------------------------------------------------


def sql_to_js(v):
    if v is NONE or v is None:
        return None if v is None else UNDEF
    if isinstance(v, Decimal):
        return float(v)
    if isinstance(v, Geometry):
        # geometries surface as GeoJSON objects in scripts
        return sql_to_js(_geo_obj(v))
    if isinstance(v, (Datetime, Duration, RecordId, Uuid)):
        return _HostValue(v)
    if isinstance(v, list):
        return [sql_to_js(x) for x in v]
    if isinstance(v, dict):
        return {k: sql_to_js(x) for k, x in v.items()}
    from surrealdb_tpu.val import SSet

    if isinstance(v, SSet):
        return [sql_to_js(x) for x in v]
    if isinstance(v, int) and not isinstance(v, bool) and (
        v > 9007199254740991 or v < -9007199254740992
    ):
        return BigInt(v)
    return v


def _geo_obj(g):
    o = g.to_object()
    return o


def js_to_sql(v):
    if v is UNDEF:
        return NONE
    if v is None:
        return None
    if isinstance(v, _HostValue):
        return v.value
    if isinstance(v, JSFunction) or (isinstance(v, tuple) and v):
        return NONE
    if isinstance(v, BigInt):
        return int(v)
    if isinstance(v, float) and v.is_integer() and abs(v) < 2**53:
        # JS numbers are doubles; integral results surface as ints
        return int(v)
    if isinstance(v, list):
        return [js_to_sql(x) for x in v]
    if isinstance(v, dict):
        out = {}
        for k, x in v.items():
            if k in ("__query__", "binds", "bind"):
                continue
            if isinstance(x, (JSFunction, tuple)):
                continue
            out[k] = js_to_sql(x)
        # GeoJSON-shaped objects become geometry, like eval's object path
        if len(out) == 2 and "type" in out and (
            "coordinates" in out or "geometries" in out
        ):
            from surrealdb_tpu.exec.coerce import object_to_geometry

            g = object_to_geometry(out)
            if g is not None:
                return g
        return out
    if isinstance(v, JSErrorObj):
        return str(v.message)
    return v


def js_truthy(v):
    if v is UNDEF or v is None or v is False:
        return False
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v != 0 and v == v
    if isinstance(v, str):
        return len(v) > 0
    return True


def js_num(v):
    if isinstance(v, bool):
        return 1 if v else 0
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return int(v) if v.strip().isdigit() else float(v)
        except ValueError:
            return float("nan")
    if v is None:
        return 0
    return float("nan")


def js_typeof(v):
    if v is UNDEF:
        return "undefined"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, BigInt):
        return "bigint"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, JSFunction) or (
        isinstance(v, tuple) and v and v[0] == "native"
    ):
        return "function"
    return "object"


def js_strict_eq(a, b):
    if isinstance(a, BigInt) != isinstance(b, BigInt):
        return False
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    num = (int, float)
    if isinstance(a, num) and isinstance(b, num):
        return a == b
    if type(a) is not type(b):
        if a is UNDEF or b is UNDEF or a is None or b is None:
            return a is b
    if isinstance(a, (list, dict)):
        return a is b
    return a == b


def js_loose_eq(a, b):
    if a is UNDEF or a is None:
        return b is UNDEF or b is None
    num = (int, float)
    if isinstance(a, num) and isinstance(b, str):
        return a == js_num(b)
    if isinstance(a, str) and isinstance(b, num):
        return js_num(a) == b
    return js_strict_eq(a, b)


def js_display(v):
    if v is UNDEF:
        return "undefined"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v.is_integer() and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    if isinstance(v, BigInt):
        return str(int(v))
    if isinstance(v, list):
        return ",".join(js_display(x) for x in v)
    if isinstance(v, dict):
        return "[object Object]"
    if isinstance(v, JSErrorObj):
        return f"Error: {v.message}"
    if isinstance(v, _HostValue):
        from surrealdb_tpu.val import render

        return render(v.value)
    return str(v)


# -- built-in namespaces -----------------------------------------------------


def _n(fn):
    return ("native", fn)


_MATH = {
    "round": _n(lambda t, a: int(math.floor(js_num(a[0]) + 0.5))),
    "floor": _n(lambda t, a: int(math.floor(js_num(a[0])))),
    "ceil": _n(lambda t, a: int(math.ceil(js_num(a[0])))),
    "abs": _n(lambda t, a: abs(js_num(a[0]))),
    "sqrt": _n(lambda t, a: math.sqrt(js_num(a[0]))),
    "pow": _n(lambda t, a: js_num(a[0]) ** js_num(a[1])),
    "min": _n(lambda t, a: min(js_num(x) for x in a)),
    "max": _n(lambda t, a: max(js_num(x) for x in a)),
    "trunc": _n(lambda t, a: int(js_num(a[0]))),
    "random": _n(lambda t, a: __import__("random").random()),
    "PI": math.pi,
    "E": math.e,
}


def _json_stringify(t, a):
    import json as _j

    def conv(v):
        if v is UNDEF:
            return None
        if isinstance(v, list):
            return [conv(x) for x in v]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, BigInt):
            raise JSError("Do not know how to serialize a BigInt")
        if isinstance(v, _HostValue):
            return js_display(v)
        return v

    return _j.dumps(conv(a[0] if a else None))


_JSON = {
    "stringify": _n(_json_stringify),
    "parse": _n(lambda t, a: __import__("json").loads(a[0])),
}

_OBJECT = {
    "keys": _n(lambda t, a: list(a[0].keys()) if isinstance(a[0], dict) else []),
    "values": _n(
        lambda t, a: list(a[0].values()) if isinstance(a[0], dict) else []
    ),
    "entries": _n(
        lambda t, a: [[k, v] for k, v in a[0].items()]
        if isinstance(a[0], dict) else []
    ),
    "assign": _n(lambda t, a: _obj_assign(a)),
    "fromEntries": _n(
        lambda t, a: {js_display(k): v for k, v in (a[0] or [])}
    ),
}


def _obj_assign(a):
    out = a[0] if a and isinstance(a[0], dict) else {}
    for src in a[1:]:
        if isinstance(src, dict):
            out.update(src)
    return out


_ARRAY = {
    "isArray": _n(lambda t, a: isinstance(a[0] if a else None, list)),
    "from": _n(lambda t, a: list(a[0]) if a else []),
    "of": _n(lambda t, a: list(a)),
}

_NUMBER = {
    "isInteger": _n(
        lambda t, a: isinstance(a[0], int) and not isinstance(a[0], bool)
        or (isinstance(a[0], float) and a[0].is_integer())
    ),
    "isFinite": _n(
        lambda t, a: isinstance(a[0], (int, float))
        and not isinstance(a[0], bool) and math.isfinite(a[0])
    ),
    "isNaN": _n(lambda t, a: isinstance(a[0], float) and a[0] != a[0]),
    "parseFloat": _n(lambda t, a: float(a[0])),
    "parseInt": _n(lambda t, a: int(float(a[0]))),
    "MAX_SAFE_INTEGER": 9007199254740991,
    "MIN_SAFE_INTEGER": -9007199254740991,
}

_STRING = {
    "fromCharCode": _n(lambda t, a: "".join(chr(int(js_num(x))) for x in a)),
}


def _array_method(arr, name, interp):
    def call(fn, *args):
        return interp._invoke(fn, UNDEF, list(args))

    if name == "map":
        return _n(lambda t, a: [
            call(a[0], v, i, arr) for i, v in enumerate(arr)
        ])
    if name == "filter":
        return _n(lambda t, a: [
            v for i, v in enumerate(arr) if js_truthy(call(a[0], v, i, arr))
        ])
    if name == "forEach":
        def _fe(t, a):
            for i, v in enumerate(arr):
                call(a[0], v, i, arr)
            return UNDEF
        return _n(_fe)
    if name == "join":
        return _n(lambda t, a: (
            js_display(a[0]) if a else ","
        ).join(js_display(x) if not isinstance(x, str) else x for x in arr))
    if name == "push":
        def _push(t, a):
            arr.extend(a)
            return len(arr)
        return _n(_push)
    if name == "pop":
        return _n(lambda t, a: arr.pop() if arr else UNDEF)
    if name == "shift":
        return _n(lambda t, a: arr.pop(0) if arr else UNDEF)
    if name == "unshift":
        def _unshift(t, a):
            arr[:0] = a
            return len(arr)
        return _n(_unshift)
    if name == "includes":
        return _n(lambda t, a: any(js_strict_eq(x, a[0]) for x in arr))
    if name == "indexOf":
        def _io(t, a):
            for i, x in enumerate(arr):
                if js_strict_eq(x, a[0]):
                    return i
            return -1
        return _n(_io)
    if name == "find":
        def _find(t, a):
            for i, v in enumerate(arr):
                if js_truthy(call(a[0], v, i, arr)):
                    return v
            return UNDEF
        return _n(_find)
    if name == "findIndex":
        def _fi(t, a):
            for i, v in enumerate(arr):
                if js_truthy(call(a[0], v, i, arr)):
                    return i
            return -1
        return _n(_fi)
    if name == "some":
        return _n(lambda t, a: any(
            js_truthy(call(a[0], v, i, arr)) for i, v in enumerate(arr)
        ))
    if name == "every":
        return _n(lambda t, a: all(
            js_truthy(call(a[0], v, i, arr)) for i, v in enumerate(arr)
        ))
    if name == "reduce":
        def _red(t, a):
            acc = a[1] if len(a) > 1 else None
            items = list(enumerate(arr))
            if acc is None:
                if not items:
                    raise JSError("Reduce of empty array with no initial value")
                acc = items[0][1]
                items = items[1:]
            for i, v in items:
                acc = call(a[0], acc, v, i, arr)
            return acc
        return _n(_red)
    if name == "slice":
        def _slice(t, a):
            s = int(js_num(a[0])) if a else 0
            e = int(js_num(a[1])) if len(a) > 1 else len(arr)
            return arr[s:e]
        return _n(_slice)
    if name == "concat":
        def _concat(t, a):
            out = list(arr)
            for x in a:
                out.extend(x if isinstance(x, list) else [x])
            return out
        return _n(_concat)
    if name == "flat":
        def _flat(t, a):
            out = []
            for x in arr:
                out.extend(x if isinstance(x, list) else [x])
            return out
        return _n(_flat)
    if name == "reverse":
        def _rev(t, a):
            arr.reverse()
            return arr
        return _n(_rev)
    if name == "sort":
        def _sort(t, a):
            import functools

            if a:
                arr.sort(key=functools.cmp_to_key(
                    lambda x, y: js_num(call(a[0], x, y)) or 0
                ))
            else:
                arr.sort(key=js_display)
            return arr
        return _n(_sort)
    return None


def _string_method(s, name):
    if name == "toUpperCase":
        return _n(lambda t, a: s.upper())
    if name == "toLowerCase":
        return _n(lambda t, a: s.lower())
    if name == "trim":
        return _n(lambda t, a: s.strip())
    if name == "split":
        return _n(lambda t, a: s.split(a[0]) if a and a[0] != "" else list(s))
    if name == "includes":
        return _n(lambda t, a: (a[0] in s) if a else False)
    if name == "startsWith":
        return _n(lambda t, a: s.startswith(a[0]) if a else False)
    if name == "endsWith":
        return _n(lambda t, a: s.endswith(a[0]) if a else False)
    if name == "indexOf":
        return _n(lambda t, a: s.find(a[0]) if a else -1)
    if name == "slice":
        def _sl(t, a):
            b = int(js_num(a[0])) if a else 0
            e = int(js_num(a[1])) if len(a) > 1 else len(s)
            return s[b:e]
        return _n(_sl)
    if name == "substring":
        def _ss(t, a):
            b = max(int(js_num(a[0])) if a else 0, 0)
            e = max(int(js_num(a[1])) if len(a) > 1 else len(s), 0)
            if b > e:
                b, e = e, b
            return s[b:e]
        return _n(_ss)
    if name == "replace":
        return _n(lambda t, a: s.replace(a[0], a[1], 1))
    if name == "replaceAll":
        return _n(lambda t, a: s.replace(a[0], a[1]))
    if name == "repeat":
        return _n(lambda t, a: s * int(js_num(a[0])))
    if name == "charCodeAt":
        return _n(lambda t, a: ord(s[int(js_num(a[0])) if a else 0]))
    if name == "charAt":
        def _ca(t, a):
            i = int(js_num(a[0])) if a else 0
            return s[i] if 0 <= i < len(s) else ""
        return _n(_ca)
    if name == "padStart":
        return _n(lambda t, a: s.rjust(
            int(js_num(a[0])), a[1] if len(a) > 1 else " "
        ))
    if name == "concat":
        return _n(lambda t, a: s + "".join(js_display(x) for x in a))
    if name == "toString":
        return _n(lambda t, a: s)
    return None


def _object_method(obj, name):
    if name == "hasOwnProperty":
        return _n(lambda t, a: js_display(a[0]) in obj if a else False)
    if name == "toString":
        return _n(lambda t, a: "[object Object]")
    return None
