"""Embedded scripting runtime for `function() { ... }` blocks.

The reference embeds QuickJS (core/src/fnc/script/mod.rs, rquickjs); this
build ships a self-contained ECMAScript-subset interpreter (no external JS
engine exists in the image) covering the scripted surface the language
tests exercise: closures/arrow functions, template literals, spread,
BigInt literals, exceptions, async/await (scripts run to completion
synchronously, so await is value passthrough), the host `surrealdb`
query/value API, and the Value bridge classes (Date/Duration/Record/Uuid/
Uint8Array).
"""

from __future__ import annotations

from surrealdb_tpu.err import SdbError


def run_script(source: str, args, ctx):
    """Execute a `function(...) { body }` script; returns a SurrealQL value.

    `args`: evaluated SurrealQL argument values; `this` binds the current
    document (reference fnc/script: script functions receive the doc ctx).
    """
    from surrealdb_tpu.fnc.script.interp import Interpreter, JSError

    try:
        interp = Interpreter(ctx)
        return interp.run_function(source, args)
    except JSError as e:
        raise SdbError(
            f"Problem with embedded script function. An exception occurred: {e.message}"
        )
    except RecursionError:
        raise SdbError(
            "Problem with embedded script function. An exception occurred: "
            "Reached excessive computation depth due to functions, "
            "subqueries, or computed values"
        )
