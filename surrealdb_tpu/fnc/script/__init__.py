"""Embedded scripting runtime for `function() { ... }` blocks.

The reference embeds QuickJS (core/src/fnc/script/mod.rs, rquickjs); this
build ships a self-contained ECMAScript-subset interpreter (no external JS
engine exists in the image) covering the scripted surface the language
tests exercise: closures/arrow functions, template literals, spread,
BigInt literals, exceptions, async/await (scripts run to completion
synchronously, so await is value passthrough), the host `surrealdb`
query/value API, and the Value bridge classes (Date/Duration/Record/Uuid/
Uint8Array).
"""

from __future__ import annotations

from surrealdb_tpu.err import SdbError


def run_script(source: str, args, ctx):
    """Execute a `function(...) { body }` script; returns a SurrealQL value.

    `args`: evaluated SurrealQL argument values; `this` binds the current
    document (reference fnc/script: script functions receive the doc ctx).
    """
    from surrealdb_tpu.fnc.script.interp import Interpreter, JSError

    # script recursion budget: the reference's 120-unit computation depth
    # admits 15 nested script frames (language/script/massive_parallel);
    # the counter is a Ctx field inherited by child contexts — not a
    # user-visible variable
    depth = ctx._script_depth
    if depth >= 15:
        raise SdbError(
            "Reached excessive computation depth due to functions, "
            "subqueries, or computed values"
        )
    ctx._script_depth = depth + 1
    try:
        interp = Interpreter(ctx)
        return interp.run_function(source, args)
    except JSError as e:
        raise SdbError(
            f"Problem with embedded script function. An exception occurred: {e.message}"
        )
    except SdbError as e:
        # errors crossing a script boundary wrap once per frame
        raise SdbError(
            f"Problem with embedded script function. An exception occurred: {e}"
        )
    except RecursionError:
        raise SdbError(
            "Problem with embedded script function. An exception occurred: "
            "Reached excessive computation depth due to functions, "
            "subqueries, or computed values"
        )
    finally:
        ctx._script_depth = depth
