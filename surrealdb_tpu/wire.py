"""CBOR wire format for SurrealQL values (reference: core/src/rpc/format/
cbor/convert.rs — same semantic tag numbers, so SDKs speaking the
reference's CBOR dialect interoperate).

Pure-Python RFC 8949 subset codec plus the SurrealDB value tags:
NONE(6), Table(7), RecordId(8), string-decimal(10), custom-datetime(12
[secs, nanos]), custom-duration(14 [secs, nanos]), UUID(37 bytes),
Range(49) with Included(50)/Excluded(51) bounds, File(55), Set(56), and
the geometry tags 88-94.
"""

from __future__ import annotations

import struct
from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import (
    NONE,
    Datetime,
    Duration,
    File,
    Geometry,
    Range,
    RecordId,
    SSet,
    Table,
    Uuid,
)

TAG_NONE = 6
TAG_TABLE = 7
TAG_RECORDID = 8
TAG_STRING_DECIMAL = 10
TAG_CUSTOM_DATETIME = 12
TAG_STRING_DURATION = 13
TAG_CUSTOM_DURATION = 14
TAG_SPEC_UUID = 37
TAG_RANGE = 49
TAG_BOUND_INCLUDED = 50
TAG_BOUND_EXCLUDED = 51
TAG_FILE = 55
TAG_SET = 56
TAG_GEOMETRY = {
    "Point": 88, "LineString": 89, "Polygon": 90, "MultiPoint": 91,
    "MultiLineString": 92, "MultiPolygon": 93, "GeometryCollection": 94,
}
_GEO_BY_TAG = {v: k for k, v in TAG_GEOMETRY.items()}


# ---------------------------------------------------------------------------
# encoder
# ---------------------------------------------------------------------------


def _head(out: bytearray, major: int, arg: int):
    if arg < 24:
        out.append((major << 5) | arg)
    elif arg < 0x100:
        out.append((major << 5) | 24)
        out.append(arg)
    elif arg < 0x10000:
        out.append((major << 5) | 25)
        out += arg.to_bytes(2, "big")
    elif arg < 0x100000000:
        out.append((major << 5) | 26)
        out += arg.to_bytes(4, "big")
    else:
        out.append((major << 5) | 27)
        out += arg.to_bytes(8, "big")


def _encode(v, out: bytearray):
    if v is NONE:
        _head(out, 6, TAG_NONE)
        out.append(0xF6)  # null
        return
    if v is None:
        out.append(0xF6)
        return
    if isinstance(v, bool):
        out.append(0xF5 if v else 0xF4)
        return
    if isinstance(v, int):
        if v >= 0:
            _head(out, 0, v)
        else:
            _head(out, 1, -1 - v)
        return
    if isinstance(v, float):
        out.append(0xFB)
        out += struct.pack(">d", v)
        return
    if isinstance(v, Decimal):
        _head(out, 6, TAG_STRING_DECIMAL)
        _encode(str(v), out)
        return
    if isinstance(v, str):
        b = v.encode("utf-8")
        _head(out, 3, len(b))
        out += b
        return
    if isinstance(v, (bytes, bytearray)):
        _head(out, 2, len(v))
        out += bytes(v)
        return
    if isinstance(v, Datetime):
        _head(out, 6, TAG_CUSTOM_DATETIME)
        total = v.epoch_ns()
        secs, nanos = divmod(total, 1_000_000_000)
        _encode([secs, nanos], out)
        return
    if isinstance(v, Duration):
        _head(out, 6, TAG_CUSTOM_DURATION)
        secs, nanos = divmod(v.ns, 1_000_000_000)
        _encode([secs, nanos], out)
        return
    if isinstance(v, Uuid):
        _head(out, 6, TAG_SPEC_UUID)
        _encode(v.u.bytes, out)
        return
    if isinstance(v, RecordId):
        _head(out, 6, TAG_RECORDID)
        _encode([v.tb, v.id], out)
        return
    if isinstance(v, Table):
        _head(out, 6, TAG_TABLE)
        _encode(v.name, out)
        return
    if isinstance(v, File):
        _head(out, 6, TAG_FILE)
        _encode([v.bucket, v.key], out)
        return
    if isinstance(v, Range):
        _head(out, 6, TAG_RANGE)
        beg = _bound(v.beg, v.beg_incl, out=None)
        end = _bound(v.end, v.end_incl, out=None)
        _encode([beg, end], out)
        return
    if isinstance(v, _Bound):
        _head(out, 6, TAG_BOUND_INCLUDED if v.incl else TAG_BOUND_EXCLUDED)
        _encode(v.value, out)
        return
    if isinstance(v, SSet):
        _head(out, 6, TAG_SET)
        _encode(list(v), out)
        return
    if isinstance(v, Geometry):
        _head(out, 6, TAG_GEOMETRY[v.kind])
        if v.kind == "GeometryCollection":
            _encode(list(v.coords), out)
        else:
            _encode(_coords_to_lists(v.coords), out)
        return
    if isinstance(v, list):
        _head(out, 4, len(v))
        for x in v:
            _encode(x, out)
        return
    if isinstance(v, dict):
        _head(out, 5, len(v))
        for k, x in v.items():
            _encode(str(k), out)
            _encode(x, out)
        return
    raise SdbError(f"Cannot encode value of type {type(v).__name__} as CBOR")


class _Bound:
    __slots__ = ("value", "incl")

    def __init__(self, value, incl):
        self.value = value
        self.incl = incl


def _bound(value, incl, out):
    if value is NONE or value is None:
        return None
    return _Bound(value, incl)


def _coords_to_lists(c):
    if isinstance(c, tuple):
        return [_coords_to_lists(x) for x in c]
    return c


def encode(v) -> bytes:
    out = bytearray()
    _encode(v, out)
    return bytes(out)


# ---------------------------------------------------------------------------
# decoder
# ---------------------------------------------------------------------------


class _Dec:
    def __init__(self, data: bytes):
        self.b = data
        self.i = 0

    def u8(self):
        v = self.b[self.i]
        self.i += 1
        return v

    def take(self, n):
        v = self.b[self.i : self.i + n]
        if len(v) < n:
            raise SdbError("truncated CBOR input")
        self.i += n
        return v

    def arg(self, info):
        if info < 24:
            return info
        if info == 24:
            return self.u8()
        if info == 25:
            return int.from_bytes(self.take(2), "big")
        if info == 26:
            return int.from_bytes(self.take(4), "big")
        if info == 27:
            return int.from_bytes(self.take(8), "big")
        raise SdbError("unsupported CBOR length encoding")

    def value(self):
        ib = self.u8()
        major, info = ib >> 5, ib & 0x1F
        if major == 0:
            return self.arg(info)
        if major == 1:
            return -1 - self.arg(info)
        if major == 2:
            return bytes(self.take(self.arg(info)))
        if major == 3:
            return self.take(self.arg(info)).decode("utf-8")
        if major == 4:
            n = self.arg(info)
            return [self.value() for _ in range(n)]
        if major == 5:
            n = self.arg(info)
            out = {}
            for _ in range(n):
                k = self.value()
                out[k if isinstance(k, str) else str(k)] = self.value()
            return out
        if major == 6:
            return self.tagged(self.arg(info))
        # major 7: simple / floats
        if info == 20:
            return False
        if info == 21:
            return True
        if info == 22:
            return None
        if info == 23:
            return NONE  # undefined maps to NONE
        if info == 25:
            raw = self.take(2)
            return _half_to_float(int.from_bytes(raw, "big"))
        if info == 26:
            return struct.unpack(">f", self.take(4))[0]
        if info == 27:
            return struct.unpack(">d", self.take(8))[0]
        raise SdbError(f"unsupported CBOR simple value {info}")

    def tagged(self, tag):
        v = self.value()
        if tag == TAG_NONE:
            return NONE
        if tag == TAG_TABLE:
            return Table(v)
        if tag == TAG_RECORDID:
            if isinstance(v, list) and len(v) == 2:
                return RecordId(v[0], v[1])
            if isinstance(v, str) and ":" in v:
                tb, idv = v.split(":", 1)
                return RecordId(tb, idv)
            raise SdbError("invalid CBOR record id")
        if tag == TAG_STRING_DECIMAL:
            return Decimal(v)
        if tag in (TAG_CUSTOM_DATETIME, 0):
            if isinstance(v, list) and len(v) == 2:
                import datetime as _dt

                secs, nanos = v
                return Datetime(
                    _dt.datetime.fromtimestamp(secs, _dt.timezone.utc), nanos
                )
            return Datetime.parse(v)
        if tag == TAG_STRING_DURATION:
            return Duration.parse(v)
        if tag == TAG_CUSTOM_DURATION:
            secs = v[0] if len(v) > 0 else 0
            nanos = v[1] if len(v) > 1 else 0
            return Duration(secs * 1_000_000_000 + nanos)
        if tag in (TAG_SPEC_UUID, 9):
            if isinstance(v, bytes):
                import uuid as _uuid

                return Uuid(_uuid.UUID(bytes=v))
            return Uuid(v)
        if tag == TAG_FILE:
            return File(v[0], v[1])
        if tag == TAG_SET:
            return SSet(v)
        if tag == TAG_BOUND_INCLUDED:
            return _Bound(v, True)
        if tag == TAG_BOUND_EXCLUDED:
            return _Bound(v, False)
        if tag == TAG_RANGE:
            beg, end = v
            bv = beg.value if isinstance(beg, _Bound) else NONE
            ev = end.value if isinstance(end, _Bound) else NONE
            return Range(
                bv, ev,
                beg.incl if isinstance(beg, _Bound) else True,
                end.incl if isinstance(end, _Bound) else False,
            )
        if tag in _GEO_BY_TAG:
            kind = _GEO_BY_TAG[tag]
            if kind == "GeometryCollection":
                return Geometry(kind, list(v))
            return Geometry(kind, _lists_to_coords(v))
        # unknown tags pass the inner value through
        return v


def _lists_to_coords(c):
    if isinstance(c, list):
        return tuple(_lists_to_coords(x) for x in c)
    return float(c) if isinstance(c, (int, float, Decimal)) else c


def _half_to_float(h: int) -> float:
    sign = -1.0 if h & 0x8000 else 1.0
    exp = (h >> 10) & 0x1F
    frac = h & 0x3FF
    if exp == 0:
        return sign * frac * 2.0 ** -24
    if exp == 31:
        return sign * (float("inf") if frac == 0 else float("nan"))
    return sign * (1 + frac / 1024.0) * 2.0 ** (exp - 15)


def decode(data: bytes):
    d = _Dec(data)
    v = d.value()
    if d.i != len(data):
        raise SdbError("trailing bytes after CBOR value")
    return v


# ---------------------------------------------------------------------------
# partial decode — project named top-level fields without materializing
# the rest of the record (exec/batch.py column extraction: an analytics
# scan over wide documents decodes only the columns it needs)
# ---------------------------------------------------------------------------


def _skip(d: _Dec):
    """Advance the cursor past one encoded value without building it."""
    ib = d.u8()
    major, info = ib >> 5, ib & 0x1F
    if major in (0, 1):
        d.arg(info)
        return
    if major in (2, 3):
        d.take(d.arg(info))
        return
    if major == 4:
        for _ in range(d.arg(info)):
            _skip(d)
        return
    if major == 5:
        for _ in range(d.arg(info)):
            _skip(d)
            _skip(d)
        return
    if major == 6:
        d.arg(info)
        _skip(d)
        return
    # major 7: simple values / floats — fail closed exactly where the
    # full decoder would (info 24 and 28+ are rejected by value() too),
    # never desynchronize the cursor on foreign bytes
    if info == 25:
        d.take(2)
    elif info == 26:
        d.take(4)
    elif info == 27:
        d.take(8)
    elif info == 24 or info >= 28:
        raise SdbError(f"unsupported CBOR simple value {info}")


def decode_fields(data: bytes, wanted) -> "dict | None":
    """Decode only the `wanted` top-level keys of an encoded map; values
    of other keys are length-skipped, never materialized. Returns None
    when the top-level value is not a plain map (tagged/object-like
    records fall back to a full decode at the caller)."""
    d = _Dec(data)
    ib = d.u8()
    major, info = ib >> 5, ib & 0x1F
    if major != 5:
        return None
    out = {}
    remaining = len(wanted)
    for _ in range(d.arg(info)):
        kb = d.u8()
        kmajor, kinfo = kb >> 5, kb & 0x1F
        if kmajor != 3:
            return None  # non-string key: not a record-shaped map
        k = d.take(d.arg(kinfo)).decode("utf-8")
        if remaining and k in wanted and k not in out:
            out[k] = d.value()
            remaining -= 1
        else:
            _skip(d)
    return out
