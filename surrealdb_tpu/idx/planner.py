"""Index access-path selection (reference: idx/planner/{mod,tree,plan}.rs +
exec/index/access_path.rs).

`plan_scan` inspects the WHERE tree for: a KNN operator (vector index /
brute-force top-k), a MATCHES operator (full-text), or indexable predicates
(= / IN / range on indexed columns). Returns a Source generator or None for
a full table scan. Distances are published through ctx.knn (the KnnContext,
exec/function/index.rs:289) for `vector::distance::knn()` projections.
"""

from __future__ import annotations

import numpy as np

from surrealdb_tpu import key as K
from surrealdb_tpu.expr.ast import (
    Binary,
    Idiom,
    Knn,
    Literal,
    Param,
    PField,
    RangeExpr,
)
from surrealdb_tpu.val import NONE, Range, RecordId, hashable, value_cmp, \
    value_eq

from surrealdb_tpu.err import SdbError


def _field_path(expr):
    from surrealdb_tpu.expr.ast import PAll, PFlatten, PIndex, PMethod

    def _ok(p):
        if isinstance(p, (PAll, PFlatten)):
            return True
        if isinstance(p, PField):
            return True
        # argument-free method parts (id.id().r) are deterministic
        # per-document, so they name stable index column paths
        if isinstance(p, PMethod) and not p.args:
            return True
        # literal integer index parts (id[1]) are stable column paths
        return isinstance(p, PIndex) and isinstance(p.expr, Literal) \
            and isinstance(p.expr.value, int)

    if isinstance(expr, Idiom) and expr.parts and all(
        _ok(p) for p in expr.parts
    ) and isinstance(expr.parts[0], PField):
        from surrealdb_tpu.exec.statements import expr_name

        return expr_name(expr)
    return None


def _split_ands(cond, out):
    if isinstance(cond, Binary) and cond.op == "&&":
        _split_ands(cond.lhs, out)
        _split_ands(cond.rhs, out)
    else:
        out.append(cond)


def _find_knn(cond):
    if isinstance(cond, Knn):
        return cond
    if isinstance(cond, Binary) and cond.op == "&&":
        return _find_knn(cond.lhs) or _find_knn(cond.rhs)
    return None


def _find_matches(cond):
    """All Matches nodes in the AND-tree."""
    from surrealdb_tpu.expr.ast import Matches

    out = []

    def rec(c):
        if isinstance(c, Matches):
            out.append(c)
        elif isinstance(c, Binary) and c.op == "&&":
            rec(c.lhs)
            rec(c.rhs)

    rec(cond)
    return out


def _split_ors(cond, out):
    if isinstance(cond, Binary) and cond.op == "||":
        _split_ors(cond.lhs, out)
        _split_ors(cond.rhs, out)
    else:
        out.append(cond)


def _ft_index_for(d, indexes):
    path = _field_path(d.lhs)
    return next(
        (x for x in indexes
         if x.fulltext is not None and x.cols_str
         and (path is None or x.cols_str[0] == path)),
        None,
    )


def or_union_branches(tb, cond, indexes, ctx, value_idioms=True):
    """Streaming multi-index OR (reference UnionIndexScan): when the WHERE
    tree is a top-level OR and EVERY disjunct is servable by ONE index
    access (eq/IN/range on an indexed column, or a full-text MATCHES),
    return per-branch descriptors in cond order; else None — e.g. when
    WITH INDEX excludes a branch's index, the whole query falls back to
    a table scan."""
    from surrealdb_tpu.expr.ast import Matches

    if not (isinstance(cond, Binary) and cond.op == "||"):
        return None
    disj = []
    _split_ors(cond, disj)
    if len(disj) < 2:
        return None
    array_paths = _array_like_paths(tb, ctx)
    branches = []
    for d in disj:
        if isinstance(d, Matches):
            idef = _ft_index_for(d, indexes)
            if idef is None:
                return None
            branches.append({"kind": "ft", "idef": idef, "mt": d})
            continue
        eqs, ins, rngs = _classify_preds(d, array_paths, value_idioms)
        chosen = _choose_index(indexes, eqs, ins, rngs) if (
            eqs or ins or rngs
        ) else None
        # a MATCHES inside the disjunct's AND tree is also a candidate
        # access (scored 800, losing only to unique full-equality)
        mts_d = _find_matches(d)
        ft_idef = _ft_index_for(mts_d[0], indexes) if mts_d else None
        if ft_idef is not None and (chosen is None or chosen[3] <= 800):
            branches.append({"kind": "ft", "idef": ft_idef, "mt": mts_d[0]})
            continue
        if chosen is None:
            return None
        idef, nmatch, tail, _score = chosen
        if tail is not None and tail[0] == "range" and nmatch == 0:
            branches.append({"kind": "range", "idef": idef, "tail": tail})
        elif tail is not None and tail[0] == "in" and nmatch == 0:
            branches.append({"kind": "in", "idef": idef, "tail": tail})
        else:
            branches.append({
                "kind": "idx", "idef": idef, "nmatch": nmatch,
                "tail": tail, "eqs": eqs,
            })
    return branches


def multi_index_leaves(tb, cond, indexes, ctx, value_idioms=True):
    """Legacy multi-index analysis (reference tree.rs leaf walk +
    Plan::MultiIndex, plan.rs:164-177): when the WHERE tree contains at
    least one OR and EVERY leaf predicate is servable by an index access,
    return one branch per leaf — non-range leaves first (DFS cond order),
    then range leaves grouped by index (plan.rs renders
    `non_range_indexes` then `ranges`); else None."""
    from surrealdb_tpu.expr.ast import Matches

    leaves = []
    saw_or = [False]

    def walk(node):
        if isinstance(node, Binary) and node.op in ("&&", "||"):
            if node.op == "||":
                saw_or[0] = True
            return walk(node.lhs) and walk(node.rhs)
        leaves.append(node)
        return True

    if not walk(cond) or not saw_or[0] or len(leaves) < 2:
        return None
    array_paths = _array_like_paths(tb, ctx)
    non_range = []
    ranges = []
    for leaf in leaves:
        if isinstance(leaf, Matches):
            idef = _ft_index_for(leaf, indexes)
            if idef is None:
                return None
            non_range.append({"kind": "ft", "idef": idef, "mt": leaf})
            continue
        eqs, ins, rngs = _classify_preds(leaf, array_paths, value_idioms)
        if len(eqs) + len(ins) + len(rngs) != 1:
            return None
        chosen = _choose_index(indexes, eqs, ins, rngs)
        if chosen is None:
            return None
        idef, nmatch, tail, _score = chosen
        if tail is not None and tail[0] == "range" and nmatch == 0:
            ranges.append({"kind": "range", "idef": idef, "tail": tail})
        elif tail is not None and tail[0] == "in" and nmatch == 0:
            non_range.append({"kind": "in", "idef": idef, "tail": tail})
        elif nmatch and tail is None:
            non_range.append({
                "kind": "idx", "idef": idef, "nmatch": nmatch,
                "tail": None, "eqs": eqs,
            })
        else:
            return None
    # ranges grouped by index in first-seen order, leaf order within
    seen_ix = []
    for br in ranges:
        if br["idef"].name not in seen_ix:
            seen_ix.append(br["idef"].name)
    ranges.sort(key=lambda br: seen_ix.index(br["idef"].name))
    return non_range + ranges


def _ft_branch_scan(tb, br, ctx):
    """One full-text branch of a multi-index union: run the search,
    publish the score/offset context (so the re-applied OR filter's
    MATCHES evaluates by membership), and yield the hits."""
    from surrealdb_tpu.exec.eval import evaluate, fetch_record
    from surrealdb_tpu.exec.statements import Source
    from surrealdb_tpu.idx.fulltext import ft_result

    mt = br["mt"]
    idef = br["idef"]
    q = evaluate(mt.rhs, ctx)
    pre = (ctx.vars.get("__ft__") or {}).get(("node", id(mt)))
    if pre is not None and pre["idef"].name == idef.name \
            and pre["query"] == str(q) and pre.get("res") is not None:
        res = pre["res"]
    else:
        res = ft_result(idef, str(q), ctx, boolean=mt.boolean)
    hits = res.hits
    ft_ctx = dict(ctx.vars.get("__ft__") or {})
    ctx.vars["__ft__"] = ft_ctx
    ref = mt.ref if mt.ref is not None else 0
    entry = {
        "scores": res.scores,
        "offsets": res.offsets,
        "idef": idef,
        "query": str(q),
        "res": res,
    }
    ft_ctx[ref] = entry
    # per-node key: two OR branches may share the default ref 0 (the AND
    # path rejects that as a duplicate, fulltext.py plan_matches); the
    # re-applied filter's membership check must not see the other
    # branch's hits, so matches_operator prefers this node-keyed entry
    ft_ctx[("node", id(mt))] = entry
    for rid, _s in hits:
        doc = fetch_record(ctx, rid)
        if doc is NONE:
            continue
        yield Source(rid=rid, doc=doc)


def union_branch_scan(tb, br, ctx):
    """Execute ONE multi-index union branch — the single dispatch point
    shared by _union_scan and the streaming explain's row counting, so
    explain output can't drift from what actually runs."""
    from surrealdb_tpu.exec.eval import evaluate

    if br["kind"] == "ft":
        return _ft_branch_scan(tb, br, ctx)
    if br["kind"] in ("range", "in"):
        return _index_scan(tb, br["idef"], [], br["tail"], ctx)
    idef = br["idef"]
    eq_vals = [
        evaluate(br["eqs"][c], ctx) for c in idef.cols_str[:br["nmatch"]]
    ]
    return _index_scan(tb, idef, eq_vals, br["tail"], ctx)


def _union_scan(tb, branches, ctx):
    """Concatenate per-branch index scans, deduping by record id. The
    SELECT loop re-applies the full OR cond (cond NOT consumed), so each
    branch may safely over-approximate its disjunct."""

    def gen():
        seen = set()
        for br in branches:
            for src in union_branch_scan(tb, br, ctx):
                h = hashable(src.rid) if src.rid is not None else None
                if h is not None and h in seen:
                    continue
                if h is not None:
                    seen.add(h)
                yield src

    return gen()


def _remove_node(cond, node):
    """Drop `node` from an AND-tree; returns remaining cond or None."""
    if cond is node:
        return None
    if isinstance(cond, Binary) and cond.op == "&&":
        l = _remove_node(cond.lhs, node)
        r = _remove_node(cond.rhs, node)
        if l is None:
            return r
        if r is None:
            return l
        return Binary("&&", l, r)
    return cond


def get_indexes_for(tb, ctx):
    """Read-path index enumeration: PREPARE REMOVE decommissioned indexes
    are invisible to the planner (writes still maintain them — the write
    side scans the catalog directly, exec/document.py)."""
    ns, db = ctx.need_ns_db()
    return [
        d for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ix_prefix(ns, db, tb)))
        if not getattr(d, "prepare_remove", False)
    ]



def _array_like_paths(tb, ctx) -> set:
    """Field paths declared array/set (their index entries are unnested, so
    CONTAINS-family predicates can ride the index)."""
    from surrealdb_tpu.exec.document import get_fields

    out = set()
    try:
        for fd in get_fields(tb, ctx):
            if fd.kind is not None and fd.kind.name in ("array", "set"):
                out.add(fd.name_str)
    except Exception:
        pass
    try:
        for idef in get_indexes_for(tb, ctx):
            for col in idef.cols_str:
                if col.endswith("[*]"):
                    out.add(col[:-3])
                elif col.endswith(".*"):
                    out.add(col[:-2])
    except Exception:
        pass
    return out


def _find_link_join(tb, cond, indexes, ctx):
    """Record-link index join (reference idx/planner/tree.rs remote-index
    resolution; plan.rs renders `operator: 'join'` with a `joins` list):
    a predicate `link.rest OP v` where the local table has a single-column
    plain index on `link`, the field is a typed `record<rt>` link, and
    `rt` serves `rest OP v` from one of its own indexes. Returns
    {lidef, ridef, rt, op, vexpr, mt} or None."""
    from surrealdb_tpu.exec.document import get_fields
    from surrealdb_tpu.expr.ast import Matches

    preds = []
    _split_ands(cond, preds)
    for pred in preds:
        mt = None
        if isinstance(pred, Matches):
            lp = _field_path(pred.lhs)
            op, vexpr, mt = "matches", pred.rhs, pred
        elif isinstance(pred, Binary) and pred.op in ("=", "==", "∈"):
            lp = _field_path(pred.lhs)
            if lp is None or _field_path(pred.rhs) is not None:
                continue
            op = "in" if pred.op == "∈" else "="
            vexpr = pred.rhs
        else:
            continue
        if lp is None or "." not in lp or ".*" in lp or "…" in lp:
            continue
        first, _, rest = lp.partition(".")
        lidef = next(
            (i for i in indexes
             if list(i.cols_str) == [first] and i.hnsw is None
             and i.fulltext is None and not i.count),
            None,
        )
        if lidef is None:
            continue
        try:
            fd = next(
                (f for f in get_fields(tb, ctx) if f.name_str == first), None
            )
        except SdbError:
            continue
        kind = getattr(fd, "kind", None)
        if kind is None or kind.name != "record" or \
                len(kind.inner or []) != 1:
            continue
        rt = kind.inner[0]
        rindexes = get_indexes_for(rt, ctx)
        if op == "matches":
            ridef = next(
                (x for x in rindexes
                 if x.fulltext is not None and x.cols_str
                 and x.cols_str[0] == rest),
                None,
            )
        else:
            ridef = next(
                (x for x in rindexes
                 if list(x.cols_str) == [rest] and x.hnsw is None
                 and x.fulltext is None and not x.count),
                None,
            )
        if ridef is None:
            continue
        return {"lidef": lidef, "ridef": ridef, "rt": rt, "op": op,
                "vexpr": vexpr, "mt": mt}
    return None


def _link_join_scan(tb, jn, ctx):
    """Execute a link join: remote index access -> remote record ids ->
    local equality scans on the link index. The WHERE clause re-applies
    row-wise afterwards (cond is NOT consumed)."""
    from surrealdb_tpu.exec.eval import evaluate

    def gen():
        rt, ridef = jn["rt"], jn["ridef"]
        if jn["op"] == "matches":
            from surrealdb_tpu.idx.fulltext import ft_search

            q = evaluate(jn["vexpr"], ctx)
            hits, _offsets = ft_search(
                ridef, str(q), ctx, boolean=jn["mt"].boolean
            )
            remote_ids = [r for r, _s in hits]
        elif jn["op"] == "in":
            vals = evaluate(jn["vexpr"], ctx)
            vals = vals if isinstance(vals, list) else [vals]
            remote_ids = [
                s.rid
                for v in vals
                for s in _index_scan(rt, ridef, [v], None, ctx)
            ]
        else:
            remote_ids = [
                s.rid
                for s in _index_scan(
                    rt, ridef, [evaluate(jn["vexpr"], ctx)], None, ctx
                )
            ]
        seen = set()
        for rid in remote_ids:
            h = hashable(rid)
            if h in seen:
                continue
            seen.add(h)
            yield from _index_scan(tb, jn["lidef"], [rid], None, ctx)

    return gen()


def _link_join_explain(tb, jn, ctx):
    from surrealdb_tpu.exec.eval import evaluate

    if jn["op"] == "matches":
        mt = jn["mt"]
        rop = f"@{mt.ref}@" if mt.ref is not None else "@@"
        val = evaluate(jn["vexpr"], ctx)
    elif jn["op"] == "in":
        rop = "union"
        val = evaluate(jn["vexpr"], ctx)
    else:
        rop = "="
        val = evaluate(jn["vexpr"], ctx)
    return {
        "detail": {
            "plan": {
                "index": jn["lidef"].name,
                "joins": [
                    {"index": jn["ridef"].name, "operator": rop,
                     "value": val}
                ],
                "operator": "join",
            },
            "table": tb,
        },
        "operation": "Iterate Index",
    }


def _is_array_value(e) -> bool:
    """Plan-time is_array() check (reference tree.rs requires a computed
    array before a union access applies)."""
    from surrealdb_tpu.expr.ast import ArrayExpr, Literal

    if isinstance(e, ArrayExpr):
        return True
    return isinstance(e, Literal) and isinstance(e.value, list)


def _classify_preds(cond, array_paths=frozenset(), value_idioms=True):
    """WHERE-tree analysis shared by plan_scan and explain_plan: returns
    (eqs, ins, rngs) keyed by field path. value_idioms=False (streaming
    executor) rejects idiom-valued rhs like $obj.name entirely."""
    preds = []
    _split_ands(cond, preds)
    eqs: dict = {}
    ins: dict = {}
    rngs: dict = {}
    for pred in preds:
        if not isinstance(pred, Binary):
            continue
        if pred.op not in ("=", "==", "∈", "<", "<=", ">", ">=", "∋", "⊇",
                           "containsany", "anyinside", "allinside"):
            continue
        lp = _field_path(pred.lhs)
        rp = _field_path(pred.rhs)
        path = op = valexpr = None
        contain_alias = False
        if lp is not None and rp is None:
            op = pred.op
            if op == "∋":
                # CONTAINS only matches index entries when the column is
                # array-shaped (unnested entries — via a .*/… path, a
                # declared array/set field, or an explicit `col[*]` index
                # column); string fields use substring semantics and
                # can't ride the index
                if not _array_shaped(lp, array_paths):
                    continue
                op = "="  # per-element entries, equality lookup
                contain_alias = True
            elif op in ("⊇", "containsany"):
                # CONTAINSANY/CONTAINSALL [..] become a union of
                # per-element equality scans. Legacy tree planner: any
                # array value qualifies (tree.rs:651-664). Streaming
                # analyzer: only a `.*`-shaped column (Part::All) matches
                # (analysis.rs idiom_matches_containment).
                if not _is_array_value(pred.rhs):
                    continue
                if not value_idioms and not (".*" in lp or "…" in lp):
                    continue
                op = "in"
            elif op in ("anyinside", "allinside"):
                continue  # value op field handled in the rhs-path case
            elif op == "∈":
                op = "in"
            path, valexpr = lp, pred.rhs
            # idiom-valued rhs: allowed only when it starts from a value
            # (e.g. $obj.name) and the caller permits them (the legacy
            # planner computes them; the streaming executor does not)
            from surrealdb_tpu.expr.ast import Idiom as _Idiom

            if isinstance(valexpr, _Idiom):
                if not value_idioms or not _doc_free_idiom(valexpr):
                    continue
        elif rp is not None and lp is None:
            if pred.op == "∈":
                if not _array_shaped(rp, array_paths):
                    continue
                path, op, valexpr = rp, "=", pred.lhs
                contain_alias = True
            elif pred.op in ("anyinside", "allinside"):
                # [..] ANYINSIDE/ALLINSIDE field -> union access
                # (reference tree.rs AnyInside|AllInside, IdiomPosition::Right;
                # same per-planner gates as ContainAny)
                if not _is_array_value(pred.lhs):
                    continue
                if not value_idioms and not (".*" in rp or "…" in rp):
                    continue
                path, op, valexpr = rp, "in", pred.lhs
            elif pred.op in ("⊇", "containsany", "∋"):
                continue  # field op value handled in the lhs-path case
            else:
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                path, op, valexpr = rp, flip.get(pred.op, pred.op), pred.lhs
            from surrealdb_tpu.expr.ast import Idiom as _Idiom

            if isinstance(valexpr, _Idiom):
                if not value_idioms or not _doc_free_idiom(valexpr):
                    continue
        if path is None or path == "id":
            continue
        if not value_idioms and (".*" in path or "…" in path) and \
                pred.op in ("=", "==", "<", "<=", ">", ">="):
            # the streaming analyzer's plain equality/range access needs a
            # plain column idiom; Part::All columns serve only the
            # CONTAINS/INSIDE per-element accesses
            # (create_with_std_index_with_flattened_field)
            continue
        if op in ("=", "=="):
            eqs.setdefault(path, valexpr)
            if contain_alias:
                # `DEFINE INDEX ... FIELDS col[*]` / `col.*` columns hold
                # the unnested entries a containment access scans
                eqs.setdefault(path + "[*]", valexpr)
                eqs.setdefault(path + ".*", valexpr)
        elif op == "in":
            ins.setdefault(path, valexpr)
        else:
            rngs.setdefault(path, []).append((op, valexpr))
    return eqs, ins, rngs


def _doc_free_idiom(expr) -> bool:
    """True when an idiom starts from a self-contained value (a param or
    literal), so it can be computed once without a document."""
    from surrealdb_tpu.expr.ast import ArrayExpr, ObjectExpr

    p0 = expr.parts[0] if expr.parts else None
    if not (isinstance(p0, tuple) and len(p0) == 2 and p0[0] == "start"):
        return False
    return isinstance(p0[1], (Param, Literal, ObjectExpr, ArrayExpr))


def _array_shaped(path: str, array_paths) -> bool:
    return ".*" in path or "…" in path or path in array_paths


def _choose_index(indexes, eqs, ins, rngs, model="streaming"):
    """Pick the best access path over the candidate indexes; returns
    (idef, nmatch, tail) or None.

    `model="streaming"` mirrors the reference's streaming planner
    (exec/index/analysis.rs IndexCandidate::score): single-column
    equality scores 1000 unique / 500 non-unique; a compound prefix
    scores 400 + 50·prefix (+25 with a narrowing range); a pure range
    scores 300 bounded / 200 half-bounded. Ties prefer the narrower
    index (the reference appends single-column candidates after compound
    ones and max_by_key keeps the last maximum), then the LATER-defined
    index (max_by_key keeps the last of equal maxima).

    `model="legacy"` mirrors the legacy tree planner (idx/planner/tree.rs):
    the longest run of leading eq columns wins, an IN/range tail counts
    extra, first-defined index wins ties."""
    best = None
    for pos, idef in enumerate(indexes):
        if idef.hnsw is not None or idef.fulltext is not None or idef.count:
            continue
        cols = idef.cols_str
        if not cols:
            continue
        nmatch = 0
        tail = None  # ('range', [(op, vx)]) | ('in', vx)
        for i, col in enumerate(cols):
            if col in eqs:
                nmatch += 1
                continue
            if i == nmatch and col in rngs:
                tail = ("range", rngs[col])
            elif i == nmatch and col in ins:
                tail = ("in", ins[col])
            break
        if nmatch == 0 and tail is None:
            continue
        if model == "legacy":
            key = (nmatch * 2 + (1 if tail else 0), 0, -pos)
        elif nmatch == len(cols) and tail is None and len(cols) == 1:
            key = (1000 if idef.unique else 500, -1, pos)
        elif tail is not None and tail[0] == "in" and nmatch == 0:
            from surrealdb_tpu.expr.ast import ArrayExpr as _AE

            if isinstance(tail[1], _AE) and len(tail[1].items) == 1:
                # `x IN [v]` collapses to an equality access and scores
                # like one (the streaming planner's single-value
                # rewrite) — beats a range candidate on another column
                key = (1000 if idef.unique else 500, -len(cols), pos)
            else:
                # IN-expansion union is a FALLBACK path in the streaming
                # planner (analysis.rs try_in_expansion): it only applies
                # when no eq/range candidate exists, and prefers the
                # narrowest index whose FIRST column is the IN column
                key = (10, -len(cols), pos)
        elif nmatch:
            # compound access: prefix of equalities, optionally narrowed
            # by a range on the next column (IN tails are NOT pushed by
            # the streaming executor — prefix-only access)
            score = 400 + 50 * nmatch + (
                25 if tail is not None and tail[0] == "range" else 0
            )
            key = (score, -len(cols), pos)
        else:
            ops = {op for op, _vx in tail[1]}
            lower = any(o in (">", ">=") for o in ops)
            upper = any(o in ("<", "<=") for o in ops)
            key = (300 if (lower and upper) else 200, -len(cols), pos)
        if best is None or key > best[0]:
            best = (key, idef, nmatch, tail)
    if best is None:
        return None
    return best[1], best[2], best[3], best[0][0]


def _register_match_contexts(tb, cond, ctx):
    """The reference's QueryExecutor registers score/offset contexts for
    every indexed MATCHES in the cond even when the plan falls back to a
    table iterator (idx/planner/executor.rs QueryExecutor::new walks all
    matches expressions) — so search::score(ref)/highlight work without
    the full-text index driving the scan."""
    from surrealdb_tpu.expr.ast import Matches

    nodes = []

    def rec(c):
        if isinstance(c, Matches):
            nodes.append(c)
        elif isinstance(c, Binary) and c.op in ("&&", "||"):
            rec(c.lhs)
            rec(c.rhs)

    rec(cond)
    if not nodes:
        return
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.idx.fulltext import ft_result

    indexes = get_indexes_for(tb, ctx)
    ft_ctx = dict(ctx.vars.get("__ft__") or {})
    registered: dict = {}
    for mt in nodes:
        idef = _ft_index_for(mt, indexes)
        if idef is None:
            continue  # no index: the filter evaluates it ad-hoc
        q = str(evaluate(mt.rhs, ctx))
        ref = mt.ref if mt.ref is not None else 0
        prev = registered.get(ref)
        if prev is not None:
            if prev == (idef.name, q):
                # same expression repeated: share the entry
                ft_ctx[("node", id(mt))] = ft_ctx[ref]
                continue
            # colliding refs (e.g. two implicit @@ in one cond): the
            # ref-keyed entry stays first-wins for the score functions;
            # the node-keyed entry below keeps membership exact per node
            # (plan_matches still rejects duplicates among AND-planned
            # matches, matching the reference's executor error)
        res = ft_result(idef, q, ctx, boolean=mt.boolean)
        entry = {
            "scores": res.scores,
            "offsets": res.offsets,
            "idef": idef,
            "query": q,
            "res": res,
        }
        if prev is None:
            ft_ctx[ref] = entry
            registered[ref] = (idef.name, q)
        ft_ctx[("node", id(mt))] = entry
    ctx.vars["__ft__"] = ft_ctx


def plan_scan(tb: str, cond, ctx, stmt):
    """Return a Source generator when an index path applies, else None
    (table scan). Indexed MATCHES in the cond get their score contexts
    registered regardless of which plan wins (the reference's
    QueryExecutor does this for every matches expression), so
    search::score/highlight work under table scans, eq-index scans,
    and union branches alike."""
    import time as _time

    from surrealdb_tpu.telemetry import stage_record

    t0 = _time.perf_counter_ns()
    if cond is not None:
        with_index = getattr(stmt, "with_index", None) \
            if stmt is not None else None
        if with_index != []:
            _register_match_contexts(tb, cond, ctx)
    try:
        return _plan_scan(tb, cond, ctx, stmt)
    finally:
        # note: a KNN plan executes its index search eagerly in here,
        # so `plan` CONTAINS `index_knn` — the profile tool subtracts
        stage_record("plan", _time.perf_counter_ns() - t0)


def _plan_scan(tb: str, cond, ctx, stmt):
    if cond is None:
        return None
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.statements import Source, _resolve_type_fields

    # plan-time rewrite: `type::field($param)` with a statically-known
    # argument becomes the named column idiom, so parameterized
    # (schemaless OData-style) predicates match index access paths; the
    # rewrite is semantics-preserving, so downstream residual filters
    # may evaluate either tree
    cond = _resolve_type_fields(cond, ctx)

    with_index = getattr(stmt, "with_index", None) if stmt is not None else None
    if with_index == []:  # WITH NOINDEX: no index access paths...
        indexes = []
    else:
        indexes = get_indexes_for(tb, ctx)
        if with_index:
            indexes = [i for i in indexes if i.name in with_index]

    # ---- KNN --------------------------------------------------------------
    # ...but brute-force KNN is a scan operator (KnnTopK), not an index, so
    # it still applies under WITH NOINDEX (reference: exec/operators/knn_topk.rs)
    knn = _find_knn(cond)
    if knn is not None:
        return _plan_knn(tb, cond, knn, indexes, ctx, stmt)
    if with_index == []:
        return None

    # ---- multi-index OR (Plan::MultiIndex / UnionIndexScan) ---------------
    # the access shape must match the engine being run: the streaming
    # planner unions ONE access per top-level disjunct, the legacy tree
    # planner unions EVERY indexable leaf (plan.rs Plan::MultiIndex)
    if getattr(ctx.session, "planner_strategy", None) == "all-ro":
        union = or_union_branches(tb, cond, indexes, ctx, value_idioms=False)
    else:
        union = multi_index_leaves(tb, cond, indexes, ctx)
        if union is None:
            # OR-with-AND-tails: not a leaf union, but one access per
            # disjunct still beats a table scan — branches safely
            # over-approximate (the full cond filters above the union)
            union = or_union_branches(tb, cond, indexes, ctx)
    if union is not None:
        return _union_scan(tb, union, ctx)

    # ---- MATCHES ----------------------------------------------------------
    mts = _find_matches(cond)
    if mts:
        use_ft = True
        if getattr(ctx.session, "planner_strategy", None) == "all-ro":
            # multi-part idioms (`t.name @@ …`) may traverse record links;
            # MatchesOp only evaluates against the source table's fulltext
            # index (reference exec/planner.rs:525-537 PlannerUnimplemented)
            from surrealdb_tpu.expr.ast import Idiom as _Idiom

            for m in mts:
                if isinstance(m.lhs, _Idiom) and len(m.lhs.parts) > 1:
                    raise SdbError(
                        "Invalid query: New executor does not support: "
                        "MATCHES with multi-part field path not yet "
                        "supported in streaming executor"
                    )
            # the streaming planner scores the MATCHES access at 800
            # (exec/index/analysis.rs:1281): a unique full-equality
            # candidate outranks it and the MATCHES drops to the filter
            eqs0, ins0, rngs0 = _classify_preds(
                cond, _array_like_paths(tb, ctx), value_idioms=False
            )
            ch0 = _choose_index(indexes, eqs0, ins0, rngs0) if (
                eqs0 or ins0 or rngs0
            ) else None
            if ch0 is not None and ch0[3] > 800:
                use_ft = False
        if use_ft:
            # a MATCHES on a multi-part link path can't use a LOCAL ft
            # index — try the remote-index join before plan_matches
            # raises (single-part un-indexed matches keep the error)
            if not all(_ft_index_for(m, indexes) for m in mts):
                jn = _find_link_join(tb, cond, indexes, ctx) if getattr(
                    ctx.session, "planner_strategy", None
                ) != "all-ro" else None
                if jn is not None:
                    return _link_join_scan(tb, jn, ctx)
                from surrealdb_tpu.expr.ast import Idiom as _Idiom2

                if all(
                    isinstance(m.lhs, _Idiom2) and len(m.lhs.parts) > 1
                    for m in mts
                ):
                    return None  # link-path matches: row-wise ad hoc eval
            from surrealdb_tpu.idx.fulltext import plan_matches

            return plan_matches(tb, cond, mts, indexes, ctx, stmt)

    # ---- equality / range / contains on indexed columns --------------------
    array_paths = _array_like_paths(tb, ctx)
    eqs, ins, rngs = _classify_preds(cond, array_paths)
    legacy = getattr(ctx.session, "planner_strategy", None) != "all-ro"
    if not eqs and not rngs and not ins:
        jn = _find_link_join(tb, cond, indexes, ctx) if legacy else None
        return _link_join_scan(tb, jn, ctx) if jn is not None else None
    chosen = _choose_index(indexes, eqs, ins, rngs)
    if chosen is None:
        jn = _find_link_join(tb, cond, indexes, ctx) if legacy else None
        return _link_join_scan(tb, jn, ctx) if jn is not None else None
    idef, nmatch, tail, _score = chosen
    eq_vals = [evaluate(eqs[c], ctx) for c in idef.cols_str[:nmatch]]
    prefilter = _index_prefilter(idef, nmatch, tail, eqs, ins, rngs, ctx,
                                 array_paths)
    scan = _index_scan(tb, idef, eq_vals, tail, ctx, prefilter=prefilter)
    order = getattr(stmt, "order", None) if stmt is not None else None
    if order and order != "rand" and len(order) == 1 and \
            order[0][1] == "desc":
        from surrealdb_tpu.exec.statements import expr_name

        if expr_name(order[0][0]) == idef.cols_str[0]:
            # ORDER BY <first index column> DESC rides the reverse index
            # iterator: emit in reverse key order so equal-key rows keep
            # reverse-scan relative order (the later stable sort preserves
            # it; reference ReverseOrder / backward range iterators)
            def rev(inner=scan):
                yield from reversed(list(inner))

            return rev()
    return scan


def _index_prefilter(idef, nmatch, tail, eqs, ins, rngs, ctx,
                     array_paths=frozenset()):
    """Sargable residual predicates on the index's OWN columns, compiled
    to (col_pos, test(decoded_value)) pairs — evaluated on the decoded
    index-key fields BEFORE the record fetch/deserialization, so rows
    the WHERE clause would drop anyway never pay the document decode.
    Purely an access-path optimization: the residual cond still
    re-applies row-wise above the scan (never consumed), so this may
    only skip rows the index key itself proves non-matching."""
    from surrealdb_tpu.exec.eval import evaluate

    tail_col = idef.cols_str[nmatch] if (
        tail is not None and nmatch < len(idef.cols_str)
    ) else None
    tests = []
    for pos, col in enumerate(idef.cols_str):
        if pos < nmatch or "*" in col or \
                _array_shaped(col, array_paths):
            # consumed by the eq prefix, or an array/set column whose
            # index entries are UNNESTED per-element values — a whole-
            # array predicate must never test against single elements
            continue
        preds = []
        if col in eqs and col != tail_col:
            v = evaluate(eqs[col], ctx)
            preds.append(lambda f, v=v: value_eq(f, v))
        if col in rngs:
            bounds = rngs[col]
            if col == tail_col and tail is not None and tail[0] == "range":
                # composite scans push exactly ONE bound into the key
                # range (_index_scan bounds=payload[:1]); the rest of
                # the same column's bounds prefilter here
                pushed = tail[1][:1] if nmatch else tail[1]
                bounds = [b for b in bounds if b not in pushed]
            for op, vx in bounds:
                v = evaluate(vx, ctx)
                if op == "<":
                    preds.append(lambda f, v=v: value_cmp(f, v) < 0)
                elif op == "<=":
                    preds.append(lambda f, v=v: value_cmp(f, v) <= 0)
                elif op == ">":
                    preds.append(lambda f, v=v: value_cmp(f, v) > 0)
                elif op == ">=":
                    preds.append(lambda f, v=v: value_cmp(f, v) >= 0)
        if col in ins and col != tail_col:
            vals = evaluate(ins[col], ctx)
            vals = vals if isinstance(vals, list) else [vals]
            preds.append(
                lambda f, vals=vals: any(value_eq(f, x) for x in vals)
            )
        for p in preds:
            tests.append((pos, p))
    return tests or None


def _dec_unique_fields(k: bytes, base: bytes, ncols: int):
    """Decode the field values of a unique-index entry key (fields only,
    no trailing rid); None on any decode wrinkle."""
    try:
        pos = len(base)
        fields = []
        for _ in range(ncols):
            f, pos = K.dec_value(k, pos)
            fields.append(f)
        return fields
    except Exception:
        return None


def _index_scan(tb, idef, eq_vals, tail, ctx, prefilter=None):
    """Scan an index: equality prefix on leading columns, then an optional
    range / IN-list on the next column. `prefilter` tests decoded key
    fields before the record fetch (sargable-residual pushdown)."""
    from surrealdb_tpu.exec.eval import evaluate, fetch_record
    from surrealdb_tpu.exec.statements import Source

    ns, db = ctx.need_ns_db()
    seen = set()
    unique = idef.unique
    base = (
        K.index_unique_prefix(ns, db, tb, idef.name)
        if unique
        else K.index_prefix(ns, db, tb, idef.name)
    )

    def _fetch(rid):
        h = hashable(rid)
        if h in seen:
            return None
        seen.add(h)
        doc = fetch_record(ctx, rid)
        if doc is NONE:
            return None
        return Source(rid=rid, doc=doc)

    def _fields_pass(fields) -> bool:
        if prefilter is None:
            return True
        for pos, test in prefilter:
            if pos >= len(fields):
                continue
            try:
                if not test(fields[pos]):
                    from surrealdb_tpu.exec.batch import _count

                    _count(ctx.ds, "pushdown_rows_pruned")
                    return False
            except Exception:
                return True  # never drop a row on a comparator wrinkle
        return True

    nonuniq_base = K.index_prefix(ns, db, tb, idef.name)

    def _emit_range(beg, end):
        ncols = len(idef.cols_str)
        if unique:
            # all-NONE rows of unique indexes live in the non-unique
            # keyspace (duplicates allowed); rebase the bounds there.
            # NONE sorts below every value, so those rows come FIRST in
            # index order (reference range scans interleave by key).
            nb = nonuniq_base + beg[len(base):]
            if end.startswith(base):
                ne = nonuniq_base + end[len(base):]
            else:
                # end was a whole-prefix bump: bump the rebased prefix
                ne = K.prefix_range(nb)[1]
            for k in ctx.txn.keys(nb, ne):
                _fields, idv = K.decode_index(k, ns, db, tb, idef.name, ncols)
                if not _fields_pass(_fields):
                    continue
                s = _fetch(RecordId(tb, idv))
                if s:
                    yield s
            for _k, rid in ctx.txn.scan_vals(beg, end):
                # unique entries key by field values under a different
                # prefix; the prefilter reads them via the shared codec
                if prefilter is not None:
                    _fields = _dec_unique_fields(_k, base, ncols)
                    if _fields is not None and not _fields_pass(_fields):
                        continue
                s = _fetch(rid)
                if s:
                    yield s
        else:
            for k in ctx.txn.keys(beg, end):
                _fields, idv = K.decode_index(k, ns, db, tb, idef.name, ncols)
                if not _fields_pass(_fields):
                    continue
                s = _fetch(RecordId(tb, idv))
                if s:
                    yield s

    def gen():
        prefix = base + K.index_fields_enc(eq_vals)
        if tail is None:
            if len(eq_vals) == len(idef.cols_str) and unique:
                rid = ctx.txn.get_val(
                    K.index_unique(ns, db, tb, idef.name, eq_vals)
                )
                if rid is not None:
                    s = _fetch(rid)
                    if s:
                        yield s
                elif any(x is NONE or x is None for x in eq_vals):
                    # all-NONE rows are stored without the unique
                    # constraint; scan the rebased non-unique range
                    yield from _emit_range(*K.prefix_range(prefix))
                return
            yield from _emit_range(*K.prefix_range(prefix))
            return
        kind, payload = tail
        if kind == "in":
            vals = evaluate(payload, ctx)
            if not isinstance(vals, list):
                vals = [vals]
            for v in vals:
                pre = prefix + K.enc_value(v)
                yield from _emit_range(*K.prefix_range(pre))
            return
        # range bounds on the next column. Composite scans (eq prefix)
        # push exactly ONE bound into the key range — the rest re-filter
        # via the residual WHERE (mirrors the streaming IndexScan access);
        # single-column scans combine all bounds as before.
        bounds = payload[:1] if eq_vals else payload
        lo = hi = None
        lo_incl = hi_incl = True
        for op, vx in bounds:
            v = evaluate(vx, ctx)
            if op in (">", ">="):
                lo, lo_incl = v, op == ">="
            else:
                hi, hi_incl = v, op == "<="
        beg, end = K.prefix_range(prefix)
        if lo is not None:
            beg = prefix + K.enc_value(lo)
            if not lo_incl:
                beg += b"\xff"
        if hi is not None:
            end = prefix + K.enc_value(hi)
            if hi_incl:
                end += b"\xff"
        yield from _emit_range(beg, end)

    return gen()


def _knn_safe_expr(expr) -> bool:
    if _field_path(expr) == "id":
        return True
    from surrealdb_tpu.expr.ast import FunctionCall

    # knn-distance pseudo-functions read ctx.knn, not the document
    return isinstance(expr, FunctionCall) and expr.name in (
        "vector::distance::knn",
    ) and not expr.args


def _pseudo_only_projection(stmt, ctx, safe_expr, allow_order=False) -> bool:
    """True when a SELECT's output is derivable from an index result
    alone (rids + per-rid pseudo-function contexts): every projection is
    `id` or a `safe_expr` pseudo-function. Lets the scan skip per-row
    record fetches — the dominant host cost for high-QPS index serving.
    With `allow_order`, ORDER BY keys may be safe expressions or
    projection aliases (aliases re-evaluate their — safe — expressions
    against the keys-only row, exec/statements._apply_order_sources)."""
    from surrealdb_tpu.expr.ast import SelectStmt

    if not isinstance(stmt, SelectStmt) or not ctx.session.is_owner:
        return False
    if (stmt.group is not None or stmt.split or stmt.fetch or stmt.omit
            or stmt.version is not None or stmt.explain):
        return False
    if stmt.order:  # ORDER BY may reference arbitrary fields
        if not allow_order or stmt.order == "rand":
            return False
        from surrealdb_tpu.exec.statements import expr_name

        aliases = set()
        for e, a in (stmt.exprs or []):
            if e != "*":
                aliases.add(a or expr_name(e))
        for item in stmt.order:
            oexpr = item[0]
            if safe_expr(oexpr) or expr_name(oexpr) in aliases:
                continue
            return False
    if stmt.value is not None:
        return not stmt.exprs and safe_expr(stmt.value)
    if not stmt.exprs:
        return False
    return all(safe_expr(e) for e, _a in stmt.exprs)


def _id_only_projection(stmt, ctx) -> bool:
    """The KNN shape of `_pseudo_only_projection`: `SELECT id` /
    `SELECT VALUE id`, optionally with vector::distance::knn()."""
    return _pseudo_only_projection(stmt, ctx, _knn_safe_expr)


def _plan_knn(tb, cond, knn: Knn, indexes, ctx, stmt):
    from surrealdb_tpu.exec.eval import evaluate, fetch_record
    from surrealdb_tpu.exec.statements import Source

    path = _field_path(knn.lhs)
    qv = evaluate(knn.rhs, ctx)
    rest = _remove_node(cond, knn)
    results = None
    if path is not None:
        # indexed ANN: `<|k,ef|>` / `<|k|>`, or `<|k,DIST|>` when DIST
        # matches the index distance (reference routes those to HNSW too)
        for idef in indexes:
            if idef.hnsw is None or not idef.cols_str or \
                    idef.cols_str[0] != path:
                continue
            if knn.dist is not None and knn.dist.lower() != \
                    idef.hnsw.get("distance", "euclidean"):
                continue
            from surrealdb_tpu.idx.vector import get_vector_index

            eng = get_vector_index(idef, ctx)
            ef = knn.ef
            if ef is None and knn.dist is not None:
                ef = idef.hnsw.get("ef_construction", 150)
            results = eng.knn(
                qv, knn.k, ctx,
                ef=ef,
                cond=rest,
                cond_ctx=ctx if rest is not None else None,
            )
            break
        if results is None and knn.ef is not None:
            raise SdbError(
                f"There was no suitable index found for the provided KNN expression"
            )
    if results is None:
        # brute-force top-k over the table scan (KnnTopK operator,
        # exec/operators/knn_topk.rs)
        results = _brute_knn(tb, knn, qv, rest, ctx)
        rest_after = rest
        # the KnnTopK aggregate is global across all FROM sources: record k
        # so the SELECT loop trims the union of per-table top-ks back to k
        ctx._brute_knn_k = knn.k
    else:
        rest_after = None  # index path already applied the residual cond
    if getattr(ctx, "knn", None) is None:
        ctx.knn = {}

    def gen():
        from surrealdb_tpu.exec.eval import fetch_record

        if _id_only_projection(stmt, ctx):
            # projection touches only `id` (plus knn-distance pseudo-
            # functions): the index result IS the answer — skip the
            # per-row record fetch entirely (keys-only KNN scan)
            for rid, dist in results:
                ctx.knn[hashable(rid)] = dist
                yield Source(rid=rid, doc={"id": rid})
            return
        for rid, dist in results:
            ctx.knn[hashable(rid)] = dist
            doc = fetch_record(ctx, rid)
            if doc is NONE:
                continue
            yield Source(rid=rid, doc=doc)

    ctx._cond_consumed = True
    if rest_after is not None:
        # brute path: still need residual filter; leave it to re-filter
        ctx._cond_consumed = True

        def gen2():
            from surrealdb_tpu.exec.eval import evaluate as ev, fetch_record
            from surrealdb_tpu.val import is_truthy

            for rid, dist in results:
                ctx.knn[hashable(rid)] = dist
                doc = fetch_record(ctx, rid)
                if doc is NONE:
                    continue
                yield Source(rid=rid, doc=doc)

        return gen2()
    return gen()


def _brute_knn(tb, knn: Knn, qv, rest, ctx):
    """Exact top-k over the table: batched on device for big tables
    (replaces KnnTopK's bounded max-heap with jax top_k)."""
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.statements import _scan_table
    from surrealdb_tpu.ops.metrics import normalize_metric
    from surrealdb_tpu.val import is_truthy

    metric, p = normalize_metric(knn.dist or "euclidean")
    # fused columnar path: the residual predicate evaluates vectorized
    # over the table column store and only surviving candidates ship —
    # (mask, qvec, k) — through the cross-query batcher (exec/vops.py);
    # any wrinkle (exotic rows, overlay, non-conforming vectors) keeps
    # the exact row-at-a-time scan below
    from surrealdb_tpu.exec.vops import fused_brute_knn

    fused = fused_brute_knn(tb, knn, qv, rest, ctx)
    if fused is not None:
        return fused
    path_expr = knn.lhs
    rows = []
    vecs = []
    dim = None
    for src in _scan_table(tb, ctx, None, None):
        c = ctx.with_doc(src.doc, src.rid)
        if rest is not None and not is_truthy(evaluate(rest, c)):
            continue
        v = evaluate(path_expr, c)
        if not isinstance(v, list):
            continue
        try:
            arr = np.asarray(v, dtype=np.float32)
        except (TypeError, ValueError):
            continue
        if arr.ndim != 1:
            continue
        if dim is None:
            dim = arr.shape[0]
        if arr.shape[0] != dim:
            continue
        rows.append(src.rid)
        vecs.append(arr)
    if not rows:
        return []
    xs = np.stack(vecs)
    q = np.asarray(qv, dtype=np.float32)
    n = len(rows)
    if n >= 4096:
        # big unindexed scans rank on device via the supervisor (the
        # rows are ephemeral — shipped with the call, nothing cached);
        # any device trouble degrades to the exact numpy path below
        from surrealdb_tpu.device import (
            DeviceOpError, DeviceUnavailable, get_supervisor,
        )

        sup = get_supervisor()
        if sup.fast_path():
            try:
                _t, _m, bufs = sup.call(
                    "brute_knn",
                    {"k": min(knn.k, n), "metric": metric, "p": p},
                    [xs, q[None, :].astype(np.float32)],
                )
                d, i = bufs[0][0], bufs[1][0]
                return [(rows[int(ii)], float(dd))
                        for dd, ii in zip(d, i) if ii >= 0]
            except (DeviceUnavailable, DeviceOpError):
                sup.note_fallback()
        else:
            sup.note_fallback()  # same accounting as the vector path
    # host path
    from surrealdb_tpu.idx.vector import TpuVectorIndex

    tmp = TpuVectorIndex.__new__(TpuVectorIndex)
    tmp.vecs = xs
    tmp.metric = metric
    tmp.mink_p = p
    d = tmp._host_distances(q)
    k = min(knn.k, n)
    idx = np.argpartition(d, k - 1)[:k]
    idx = idx[np.argsort(d[idx], kind="stable")]
    return [(rows[int(ii)], float(d[ii])) for ii in idx]


def _unsupported_expr(cond):
    """First planner-unsupported subexpression (unary ops) in an AND tree,
    rendered compactly for the Fallback explain entry."""
    from surrealdb_tpu.expr.ast import Prefix as _Pfx

    preds = []
    _split_ands(cond, preds)
    for p in preds:
        if isinstance(p, _Pfx):
            from surrealdb_tpu.exec.render_def import _expr_sql

            inner = _expr_sql(p.expr)
            return f"{p.op}{inner}"
    return None


def explain_plan(tb, cond, ctx, stmt):
    """EXPLAIN output (reference dbs/plan.rs Explanation)."""
    with_index = getattr(stmt, "with_index", None) if stmt is not None else None
    orig_cond = cond
    if with_index == []:
        cond = None  # WITH NOINDEX: always a table scan
    # record strategy (idx/planner/mod.rs check_record_strategy): a
    # count()-only selection over a bare table needs no document values —
    # GROUP ALL counts keys (Count), ungrouped iterates keys (KeysOnly)
    if orig_cond is None and stmt is not None and             not getattr(stmt, "order", None) and             getattr(stmt, "exprs", None):
        from surrealdb_tpu.expr.ast import FunctionCall as _FC3

        if (
            len(stmt.exprs) == 1
            and isinstance(stmt.exprs[0][0], _FC3)
            and stmt.exprs[0][0].name.lower() == "count"
            and not stmt.exprs[0][0].args
        ):
            group = getattr(stmt, "group", None)
            if group == []:
                # a live COUNT index serves the whole-table count directly
                # (reference count_exists_rewriter.rs; decommissioned
                # PREPARE REMOVE indexes are skipped)
                idxs0 = get_indexes_for(tb, ctx)
                if with_index:
                    idxs0 = [i for i in idxs0 if i.name in with_index]
                cidx = next(
                    (i for i in idxs0 if i.count
                     and getattr(i, "count_cond", None) is None
                     and not getattr(i, "prepare_remove", False)),
                    None,
                )
                if cidx is not None:
                    return {
                        "detail": {
                            "plan": {"index": cidx.name, "operator": "Count"},
                            "table": tb,
                        },
                        "operation": "Iterate Index Count",
                    }
                return {
                    "detail": {"direction": "forward", "table": tb},
                    "operation": "Iterate Table Count",
                }
            if group is None:
                return {
                    "detail": {"direction": "forward", "table": tb},
                    "operation": "Iterate Table Keys",
                }
    if cond is not None:
        from surrealdb_tpu.exec.statements import _resolve_type_fields

        cond = _resolve_type_fields(cond, ctx)
        knn = _find_knn(cond)
        indexes = get_indexes_for(tb, ctx)
        if with_index:
            indexes = [i for i in indexes if i.name in with_index]
        if knn is not None:
            path = _field_path(knn.lhs)
            for idef in indexes:
                if idef.hnsw is not None and idef.cols_str and \
                        idef.cols_str[0] == path and (
                            knn.dist is None
                            or knn.dist.lower() == idef.hnsw.get(
                                "distance", "euclidean")
                        ):
                    from surrealdb_tpu.exec.eval import evaluate

                    try:
                        qval = evaluate(knn.rhs, ctx)
                    except Exception:
                        qval = None
                    ef = knn.ef
                    if ef is None and knn.dist is not None:
                        ef = idef.hnsw.get("ef_construction", 150)
                    from surrealdb_tpu.idx.vector import get_vector_index

                    eng = get_vector_index(idef, ctx)
                    plan = {
                        "index": idef.name,
                        "operator": f"<|{knn.k},{ef or 40}|>",
                        "value": qval,
                    }
                    ann_plan = eng.ann_plan(knn.k)
                    if ann_plan is not None:
                        # the size/metric gate routed this store off
                        # the brute scan: "graph" = whole-store CAGRA
                        # (int8 descent + exact re-rank), "segmented" =
                        # LSM-style sealed-segment fan-out with
                        # per-segment graphs (idx/segments.py); the
                        # segment/ready counts surface the lifecycle
                        plan.update(ann_plan)
                    refresh = getattr(eng, "refresh_parts", None)
                    if refresh is not None:
                        # sharded store: the search scatter-gathers
                        # across this many index shards (idx/shardvec)
                        try:
                            plan["shards"] = len(refresh())
                        except SdbError:
                            pass  # map unreadable: plan stays useful
                    return {
                        "detail": {"plan": plan, "table": tb},
                        "operation": "Iterate Index",
                    }
            return {
                "detail": {"direction": "forward", "table": tb},
                "operation": "Iterate Table",
            }
        union = multi_index_leaves(tb, cond, indexes, ctx)
        if union is not None:
            from surrealdb_tpu.exec.eval import evaluate

            entries = []
            for br in union:
                if br["kind"] == "range":
                    frm = {"inclusive": False, "value": NONE}
                    to = {"inclusive": False, "value": NONE}
                    for rop, rexpr in br["tail"][1]:
                        rv = evaluate(rexpr, ctx)
                        if rop in (">", ">="):
                            frm = {"inclusive": rop == ">=", "value": rv}
                        else:
                            to = {"inclusive": rop == "<=", "value": rv}
                    entries.append({
                        "detail": {
                            "plan": {
                                "direction": "forward",
                                "from": frm,
                                "index": br["idef"].name,
                                "to": to,
                            },
                            "table": tb,
                        },
                        "operation": "Iterate Index",
                    })
                    continue
                if br["kind"] == "ft":
                    mt = br["mt"]
                    op = f"@{mt.ref}@" if mt.ref is not None else "@@"
                    try:
                        val = evaluate(mt.rhs, ctx)
                    except Exception:
                        val = None
                elif br["kind"] == "in":
                    op = "union"
                    iv = evaluate(br["tail"][1], ctx)
                    val = iv if isinstance(iv, list) else [iv]
                else:
                    idef = br["idef"]
                    op = "="
                    vals = [
                        evaluate(br["eqs"][c], ctx)
                        for c in idef.cols_str[:br["nmatch"]]
                    ]
                    val = vals[0] if len(vals) == 1 else vals
                entries.append({
                    "detail": {
                        "plan": {
                            "index": br["idef"].name,
                            "operator": op,
                            "value": val,
                        },
                        "table": tb,
                    },
                    "operation": "Iterate Index",
                })
            return entries
        # a top-level OR whose disjuncts each carry an AND tail is not a
        # leaf union (multi_index_leaves rejects it) but still unions one
        # access per disjunct — render it as a single UnionIndexScan
        # plan object (reference exec/operators/scan/union.rs JSON)
        orb = or_union_branches(tb, cond, indexes, ctx)
        if orb is not None:
            from surrealdb_tpu.exec.eval import evaluate

            plans = []
            for br in orb:
                if br["kind"] == "range":
                    frm = {"inclusive": False, "value": NONE}
                    to = {"inclusive": False, "value": NONE}
                    for rop, rexpr in br["tail"][1]:
                        rv = evaluate(rexpr, ctx)
                        if rop in (">", ">="):
                            frm = {"inclusive": rop == ">=", "value": rv}
                        else:
                            to = {"inclusive": rop == "<=", "value": rv}
                    plans.append({
                        "direction": "forward", "from": frm,
                        "index": br["idef"].name, "to": to,
                    })
                    continue
                if br["kind"] == "ft":
                    mt = br["mt"]
                    op = f"@{mt.ref}@" if mt.ref is not None else "@@"
                    try:
                        val = evaluate(mt.rhs, ctx)
                    except Exception:
                        val = None
                elif br["kind"] == "in":
                    op = "union"
                    iv = evaluate(br["tail"][1], ctx)
                    val = iv if isinstance(iv, list) else [iv]
                else:
                    idef = br["idef"]
                    op = "="
                    vals = [
                        evaluate(br["eqs"][c], ctx)
                        for c in idef.cols_str[:br["nmatch"]]
                    ]
                    val = vals[0] if len(vals) == 1 else vals
                plans.append({
                    "index": br["idef"].name,
                    "operator": op,
                    "value": val,
                })
            return {
                "detail": {
                    "plan": {
                        "operator": "UnionIndexScan",
                        "branches": plans,
                    },
                    "table": tb,
                },
                "operation": "Iterate Index Union",
            }
        mts = _find_matches(cond)
        if mts:
            from surrealdb_tpu.exec.eval import evaluate

            mt = mts[0]
            path = _field_path(mt.lhs)
            for idef in indexes:
                if idef.fulltext is not None and (
                    path is None or (idef.cols_str and idef.cols_str[0] == path)
                ):
                    op = f"@{mt.ref}@" if mt.ref is not None else "@@"
                    try:
                        val = evaluate(mt.rhs, ctx)
                    except Exception:
                        val = None
                    return {
                        "detail": {
                            "plan": {
                                "index": idef.name,
                                "operator": op,
                                "value": val,
                            },
                            "table": tb,
                        },
                        "operation": "Iterate Index",
                    }
        from surrealdb_tpu.exec.eval import evaluate

        eqs, ins, rngs = _classify_preds(cond, _array_like_paths(tb, ctx))
        best = None
        chosen = _choose_index(indexes, eqs, ins, rngs, model="legacy")
        if chosen is None:
            jn = _find_link_join(tb, cond, indexes, ctx)
            if jn is not None:
                return _link_join_explain(tb, jn, ctx)
        count_only = False
        if stmt is not None and getattr(stmt, "group", None) == [] and \
                getattr(stmt, "exprs", None):
            from surrealdb_tpu.expr.ast import FunctionCall as _FC2

            count_only = (
                len(stmt.exprs) == 1
                and isinstance(stmt.exprs[0][0], _FC2)
                and stmt.exprs[0][0].name.lower() == "count"
                and not stmt.exprs[0][0].args
            )
        if chosen is not None:
            idef, nmatch, tail, _score = chosen
            if count_only:
                # a count-only scan requires the index to cover the whole
                # WHERE clause; residual predicates need real documents
                covered = set(idef.cols_str[:nmatch])
                if tail is not None:
                    covered.add(idef.cols_str[nmatch])
                preds = []
                _split_ands(cond, preds)
                classified = set(eqs) | set(ins) | set(rngs)
                _IDXOPS = ("=", "==", "\u2208", "<", "<=", ">", ">=",
                           "\u220b", "\u2287", "containsany")
                for pred in preds:
                    pth = None
                    servable = False
                    if isinstance(pred, Binary) and pred.op in _IDXOPS:
                        lp2 = _field_path(pred.lhs)
                        rp2 = _field_path(pred.rhs)
                        # exactly one side is the column; the other side
                        # must be a computable value
                        if (lp2 is None) != (rp2 is None):
                            pth = lp2 or rp2
                            servable = True
                    if not servable or pth not in covered or \
                            pth not in classified:
                        count_only = False
                        break
            vals = [evaluate(eqs[c], ctx) for c in idef.cols_str[:nmatch]]
            op = "="
            if tail is not None and tail[0] == "in":
                op = "union"
                iv = evaluate(tail[1], ctx)
                iv = iv if isinstance(iv, list) else [iv]
                if nmatch:
                    # composite: one [prefix..., v] branch per IN value
                    vals = [list(vals) + [x] for x in iv]
                else:
                    vals = vals + [iv]
            elif tail is not None and tail[0] == "range" and not nmatch \
                    and not count_only:
                frm = {"inclusive": False, "value": NONE}
                to = {"inclusive": False, "value": NONE}
                for rop2, rexpr2 in tail[1]:
                    rv2 = evaluate(rexpr2, ctx)
                    if rop2 in (">", ">="):
                        frm = {"inclusive": rop2 == ">=", "value": rv2}
                    else:
                        to = {"inclusive": rop2 == "<=", "value": rv2}
                direction = "forward"
                order_consumed = False
                order = getattr(stmt, "order", None) if stmt is not None                     else None
                if order and order != "rand" and len(order) == 1:
                    from surrealdb_tpu.exec.statements import expr_name

                    oexpr, odir = order[0][0], order[0][1]
                    if expr_name(oexpr) == idef.cols_str[0]:
                        # the scan streams in index order: ASC rides the
                        # forward iterator, DESC the reverse iterator
                        order_consumed = True
                        if odir == "desc":
                            direction = "backward"
                detail = {
                    "plan": {
                        "direction": direction,
                        "from": frm,
                        "index": idef.name,
                        "to": to,
                    },
                    "table": tb,
                }
                if order_consumed:
                    detail["_order_consumed"] = True
                return {
                    "detail": detail,
                    "operation": "Iterate Index",
                }
            elif tail is not None and tail[0] == "range" and nmatch and \
                    not count_only:
                # composite eq-prefix + range tail: the reference renders
                # the prefix values and each range bound in cond order
                # (exe/lookup compound plans)
                return {
                    "detail": {
                        "plan": {
                            "index": idef.name,
                            "prefix": vals,
                            "ranges": [
                                {"operator": rop, "value": evaluate(rexpr, ctx)}
                                for rop, rexpr in tail[1]
                            ],
                        },
                        "table": tb,
                    },
                    "operation": "Iterate Index",
                }
            elif tail is not None:
                op = {">": "MoreThan", ">=": "MoreThanOrEqual",
                      "<": "LessThan", "<=": "LessThanOrEqual"}.get(
                          tail[1][0][0], "range")
                vals = vals + [evaluate(tail[1][0][1], ctx)]
            value = vals[0] if len(vals) == 1 else vals
            if op == "union" and len(vals) == 1:
                value = vals[0]
            if count_only and tail is not None and tail[0] == "range":
                frm = {"inclusive": True, "value": NONE}
                to = {"inclusive": False, "value": NONE}
                for rop, rexpr in tail[1]:
                    rv = evaluate(rexpr, ctx)
                    if rop in (">", ">="):
                        frm = {"inclusive": rop == ">=", "value": rv}
                    else:
                        to = {"inclusive": rop == "<=", "value": rv}
                return {
                    "detail": {
                        "plan": {
                            "direction": "forward",
                            "from": frm,
                            "index": idef.name,
                            "to": to,
                        },
                        "table": tb,
                    },
                    "operation": "Iterate Index Count",
                }
            return {
                "detail": {
                    "plan": {
                        "index": idef.name,
                        "operator": op,
                        "value": value,
                    },
                    "table": tb,
                },
                "operation": "Iterate Index Count" if count_only
                else "Iterate Index",
            }
    if cond is None and stmt is not None and with_index != []:
        # no WHERE, but a single-key ORDER BY over an indexed column:
        # stream the index in (reverse) order (reference Plan::SingleIndex
        # with Order/ReverseOrder iterators)
        order = getattr(stmt, "order", None)
        if order and order != "rand" and len(order) == 1:
            from surrealdb_tpu.exec.statements import expr_name

            oexpr, odir = order[0][0], order[0][1]
            opath = expr_name(oexpr)
            idxs = get_indexes_for(tb, ctx)
            if with_index:
                idxs = [i for i in idxs if i.name in with_index]
            idef3 = next(
                (d for d in idxs
                 if d.cols_str and d.cols_str[0] == opath
                 and d.hnsw is None and d.fulltext is None and not d.count),
                None,
            )
            if idef3 is not None:
                return {
                    "detail": {
                        "plan": {
                            "index": idef3.name,
                            "operator": "ReverseOrder" if odir == "desc"
                            else "Order",
                        },
                        "table": tb,
                        "_order_consumed": True,
                    },
                    "operation": "Iterate Index",
                }
    base = {
        "detail": {"direction": "forward", "table": tb},
        "operation": "Iterate Table",
    }
    if cond is not None:
        reason = _unsupported_expr(cond)
        if reason is not None:
            # the planner analyzer bailed on an unsupported expression
            # shape: the explain carries a Fallback entry (dbs/plan.rs)
            return [base, {
                "detail": {"reason": f"Unsupported expression: {reason}"},
                "operation": "Fallback",
            }]
    return base
