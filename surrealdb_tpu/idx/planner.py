"""Index access-path selection (reference: idx/planner/{mod,tree,plan}.rs +
exec/index/access_path.rs).

`plan_scan` inspects the WHERE tree for: a KNN operator (vector index /
brute-force top-k), a MATCHES operator (full-text), or indexable predicates
(= / IN / range on indexed columns). Returns a Source generator or None for
a full table scan. Distances are published through ctx.knn (the KnnContext,
exec/function/index.rs:289) for `vector::distance::knn()` projections.
"""

from __future__ import annotations

import numpy as np

from surrealdb_tpu import key as K
from surrealdb_tpu.expr.ast import (
    Binary,
    Idiom,
    Knn,
    Literal,
    Param,
    PField,
    RangeExpr,
)
from surrealdb_tpu.val import NONE, Range, RecordId, hashable, value_eq

from surrealdb_tpu.err import SdbError


def _field_path(expr):
    from surrealdb_tpu.expr.ast import PAll, PFlatten

    if isinstance(expr, Idiom) and expr.parts and all(
        isinstance(p, (PField, PAll, PFlatten)) for p in expr.parts
    ) and isinstance(expr.parts[0], PField):
        from surrealdb_tpu.exec.statements import expr_name

        return expr_name(expr)
    return None


def _split_ands(cond, out):
    if isinstance(cond, Binary) and cond.op == "&&":
        _split_ands(cond.lhs, out)
        _split_ands(cond.rhs, out)
    else:
        out.append(cond)


def _find_knn(cond):
    if isinstance(cond, Knn):
        return cond
    if isinstance(cond, Binary) and cond.op == "&&":
        return _find_knn(cond.lhs) or _find_knn(cond.rhs)
    return None


def _find_matches(cond):
    if isinstance(cond, Binary) and cond.op == "@@":
        return cond
    if isinstance(cond, Binary) and cond.op == "&&":
        return _find_matches(cond.lhs) or _find_matches(cond.rhs)
    return None


def _remove_node(cond, node):
    """Drop `node` from an AND-tree; returns remaining cond or None."""
    if cond is node:
        return None
    if isinstance(cond, Binary) and cond.op == "&&":
        l = _remove_node(cond.lhs, node)
        r = _remove_node(cond.rhs, node)
        if l is None:
            return r
        if r is None:
            return l
        return Binary("&&", l, r)
    return cond


def get_indexes_for(tb, ctx):
    ns, db = ctx.need_ns_db()
    return [
        d for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ix_prefix(ns, db, tb)))
    ]


def plan_scan(tb: str, cond, ctx, stmt):
    """Return a Source generator when an index path applies, else None."""
    if cond is None:
        return None
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.statements import Source

    with_index = getattr(stmt, "with_index", None) if stmt is not None else None
    if with_index == []:  # WITH NOINDEX
        return None
    indexes = get_indexes_for(tb, ctx)
    if with_index:
        indexes = [i for i in indexes if i.name in with_index]

    # ---- KNN --------------------------------------------------------------
    knn = _find_knn(cond)
    if knn is not None:
        return _plan_knn(tb, cond, knn, indexes, ctx, stmt)

    # ---- MATCHES ----------------------------------------------------------
    mt = _find_matches(cond)
    if mt is not None:
        from surrealdb_tpu.idx.fulltext import plan_matches

        return plan_matches(tb, cond, mt, indexes, ctx, stmt)

    # ---- equality / range on an indexed column ----------------------------
    preds = []
    _split_ands(cond, preds)
    for pred in preds:
        if not isinstance(pred, Binary):
            continue
        path = op = valexpr = None
        if pred.op in ("=", "==", "∈", "<", "<=", ">", ">=", "∋", "⊇",
                       "containsany"):
            lp = _field_path(pred.lhs)
            rp = _field_path(pred.rhs)
            if lp is not None and rp is None:
                # field CONTAINS v  -> per-element entries, equality lookup
                op = {"∋": "="}.get(pred.op, pred.op)
                if pred.op in ("⊇", "containsany"):
                    op = "∈"  # lookup each element of the rhs array
                path, valexpr = lp, pred.rhs
            elif rp is not None and lp is None:
                if pred.op == "∈":
                    # v INSIDE field -> same as field CONTAINS v
                    path, op, valexpr = rp, "=", pred.lhs
                else:
                    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                    path, op, valexpr = rp, flip.get(pred.op, pred.op), pred.lhs
        if path is None or path == "id":
            continue
        for idef in indexes:
            if idef.hnsw is not None or idef.fulltext is not None or idef.count:
                continue
            if not idef.cols_str or idef.cols_str[0] != path:
                continue
            if len(idef.cols_str) > 1 and op != "=":
                continue
            v = evaluate(valexpr, ctx)
            return _index_lookup(tb, idef, op, v, cond, ctx)
    return None


def _plan_knn(tb, cond, knn: Knn, indexes, ctx, stmt):
    from surrealdb_tpu.exec.eval import evaluate, fetch_record
    from surrealdb_tpu.exec.statements import Source

    path = _field_path(knn.lhs)
    qv = evaluate(knn.rhs, ctx)
    rest = _remove_node(cond, knn)
    results = None
    if knn.dist is None and path is not None:
        # indexed ANN (ef given or not — we search the index either way)
        for idef in indexes:
            if idef.hnsw is not None and idef.cols_str and idef.cols_str[0] == path:
                from surrealdb_tpu.idx.vector import get_vector_index

                eng = get_vector_index(idef, ctx)
                results = eng.knn(
                    qv, knn.k, ctx,
                    ef=knn.ef,
                    cond=rest,
                    cond_ctx=ctx if rest is not None else None,
                )
                break
        if results is None and knn.ef is not None:
            raise SdbError(
                f"There was no suitable index found for the provided KNN expression"
            )
    if results is None:
        # brute-force top-k over the table scan (KnnTopK operator,
        # exec/operators/knn_topk.rs)
        results = _brute_knn(tb, knn, qv, rest, ctx)
        rest_after = rest
    else:
        rest_after = None  # index path already applied the residual cond
    ctx.knn = {}

    def gen():
        from surrealdb_tpu.exec.eval import fetch_record

        for rid, dist in results:
            ctx.knn[hashable(rid)] = dist
            doc = fetch_record(ctx, rid)
            if doc is NONE:
                continue
            yield Source(rid=rid, doc=doc)

    ctx._cond_consumed = True
    if rest_after is not None:
        # brute path: still need residual filter; leave it to re-filter
        ctx._cond_consumed = True

        def gen2():
            from surrealdb_tpu.exec.eval import evaluate as ev, fetch_record
            from surrealdb_tpu.val import is_truthy

            for rid, dist in results:
                ctx.knn[hashable(rid)] = dist
                doc = fetch_record(ctx, rid)
                if doc is NONE:
                    continue
                yield Source(rid=rid, doc=doc)

        return gen2()
    return gen()


def _brute_knn(tb, knn: Knn, qv, rest, ctx):
    """Exact top-k over the table: batched on device for big tables
    (replaces KnnTopK's bounded max-heap with jax top_k)."""
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.statements import _scan_table
    from surrealdb_tpu.ops.distance import normalize_metric
    from surrealdb_tpu.val import is_truthy

    metric, p = normalize_metric(knn.dist or "euclidean")
    path_expr = knn.lhs
    rows = []
    vecs = []
    dim = None
    for src in _scan_table(tb, ctx, None, None):
        c = ctx.with_doc(src.doc, src.rid)
        if rest is not None and not is_truthy(evaluate(rest, c)):
            continue
        v = evaluate(path_expr, c)
        if not isinstance(v, list):
            continue
        try:
            arr = np.asarray(v, dtype=np.float32)
        except (TypeError, ValueError):
            continue
        if arr.ndim != 1:
            continue
        if dim is None:
            dim = arr.shape[0]
        if arr.shape[0] != dim:
            continue
        rows.append(src.rid)
        vecs.append(arr)
    if not rows:
        return []
    xs = np.stack(vecs)
    q = np.asarray(qv, dtype=np.float32)
    n = len(rows)
    if n >= 4096:
        from surrealdb_tpu.ops.topk import knn_search
        import jax.numpy as jnp

        d, i = knn_search(jnp.asarray(xs), jnp.asarray(q[None, :]),
                          min(knn.k, n), metric, p)
        d = np.asarray(d[0])
        i = np.asarray(i[0])
        return [(rows[int(ii)], float(dd)) for dd, ii in zip(d, i) if ii >= 0]
    # host path
    from surrealdb_tpu.idx.vector import TpuVectorIndex

    tmp = TpuVectorIndex.__new__(TpuVectorIndex)
    tmp.vecs = xs
    tmp.metric = metric
    tmp.mink_p = p
    d = tmp._host_distances(q)
    k = min(knn.k, n)
    idx = np.argpartition(d, k - 1)[:k]
    idx = idx[np.argsort(d[idx], kind="stable")]
    return [(rows[int(ii)], float(d[ii])) for ii in idx]


def _index_lookup(tb, idef, op, v, cond, ctx):
    from surrealdb_tpu.exec.eval import fetch_record
    from surrealdb_tpu.exec.statements import Source
    from surrealdb_tpu.kvs.api import deserialize

    ns, db = ctx.need_ns_db()
    seen = set()

    def _fetch(rid):
        h = hashable(rid)
        if h in seen:
            return None
        seen.add(h)
        doc = fetch_record(ctx, rid)
        if doc is NONE:
            return None
        return Source(rid=rid, doc=doc)

    def gen():
        if idef.unique:
            if op in ("=", "=="):
                rid = ctx.txn.get_val(K.index_unique(ns, db, tb, idef.name, [v]))
                if rid is not None:
                    s = _fetch(rid)
                    if s:
                        yield s
            elif op == "∈" and isinstance(v, list):
                for x in v:
                    rid = ctx.txn.get_val(
                        K.index_unique(ns, db, tb, idef.name, [x])
                    )
                    if rid is not None:
                        s = _fetch(rid)
                        if s:
                            yield s
            else:
                # range over unique index entries
                yield from _range_scan_unique()
            return
        if op in ("=", "=="):
            pre = K.index_prefix(ns, db, tb, idef.name) + K.enc_value([v])
            for k in ctx.txn.keys(*K.prefix_range(pre)):
                _fields, idv = K.decode_index(k, ns, db, tb, idef.name)
                s = _fetch(RecordId(tb, idv))
                if s:
                    yield s
        elif op == "∈" and isinstance(v, list):
            for x in v:
                pre = K.index_prefix(ns, db, tb, idef.name) + K.enc_value([x])
                for k in ctx.txn.keys(*K.prefix_range(pre)):
                    _fields, idv = K.decode_index(k, ns, db, tb, idef.name)
                    s = _fetch(RecordId(tb, idv))
                    if s:
                        yield s
        else:
            yield from _range_scan()

    def _range_bounds(make_key, tag_open, tag_close):
        base = make_key
        if op in (">", ">="):
            beg = base + K.enc_value([v])
            if op == ">":
                beg += b"\xff"
            end = base + b"\xff\xff\xff\xff\xff\xff\xff\xff"
        else:
            beg = base
            end = base + K.enc_value([v])
            if op == "<=":
                end += b"\xff"
        return beg, end

    def _range_scan():
        base = K.index_prefix(ns, db, tb, idef.name)
        beg, end = _range_bounds(base, None, None)
        for k in ctx.txn.keys(beg, end):
            _fields, idv = K.decode_index(k, ns, db, tb, idef.name)
            s = _fetch(RecordId(tb, idv))
            if s:
                yield s

    def _range_scan_unique():
        base = K.index_unique_prefix(ns, db, tb, idef.name)
        beg, end = _range_bounds(base, None, None)
        for k, rid in ctx.txn.scan_vals(beg, end):
            s = _fetch(rid)
            if s:
                yield s

    return gen()


def explain_plan(tb, cond, ctx, stmt):
    """EXPLAIN output (reference dbs/plan.rs Explanation)."""
    if cond is not None:
        knn = _find_knn(cond)
        indexes = get_indexes_for(tb, ctx)
        if knn is not None:
            path = _field_path(knn.lhs)
            for idef in indexes:
                if idef.hnsw is not None and idef.cols_str and \
                        idef.cols_str[0] == path and knn.dist is None:
                    return {
                        "detail": {
                            "plan": {
                                "index": idef.name,
                                "operator": f"<|{knn.k},{knn.ef or 40}|>",
                            },
                            "table": tb,
                        },
                        "operation": "Iterate Index",
                    }
            return {
                "detail": {"table": tb},
                "operation": "Iterate Table",
            }
        mt = _find_matches(cond)
        if mt is not None:
            for idef in indexes:
                if idef.fulltext is not None:
                    return {
                        "detail": {
                            "plan": {"index": idef.name, "operator": "@@"},
                            "table": tb,
                        },
                        "operation": "Iterate Index",
                    }
        preds = []
        _split_ands(cond, preds)
        for pred in preds:
            if isinstance(pred, Binary) and pred.op in (
                "=", "==", "∈", "∋", "<", "<=", ">", ">="
            ):
                lp = _field_path(pred.lhs)
                rp = _field_path(pred.rhs)
                path = lp or rp
                valexpr = pred.rhs if lp else pred.lhs
                op = pred.op
                if op in ("∋",) or (op == "∈" and rp is not None):
                    op = "="
                elif op == "∈":
                    op = "union"
                for idef in indexes:
                    if idef.cols_str and idef.cols_str[0] == path and \
                            idef.hnsw is None and idef.fulltext is None:
                        from surrealdb_tpu.exec.eval import evaluate

                        try:
                            val = evaluate(valexpr, ctx)
                        except Exception:
                            val = None
                        return {
                            "detail": {
                                "plan": {
                                    "index": idef.name,
                                    "operator": op,
                                    "value": val,
                                },
                                "table": tb,
                            },
                            "operation": "Iterate Index",
                        }
    return {"detail": {"table": tb}, "operation": "Iterate Table"}
