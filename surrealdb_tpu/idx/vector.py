"""TPU-resident vector index.

Replaces the reference's HNSW graph walk (idx/trees/hnsw/, hot loop
layer.rs:184-223: per-neighbor async KV fetch + scalar distance) with a
device-resident flat store: batched distance (`einsum` on the MXU) +
`jax.lax.top_k`, blockwise for big stores, mesh-sharded for multi-chip
(SURVEY.md §7 step 4). Exact search ⇒ recall@10 = 1.0 ≥ the 0.95 target.

Consistency model mirrors hnsw/index.rs's two-phase design: the KV `he` keys
(rid→vector) written inside the caller's transaction are the source of
truth; the device block cache is an overlay rebuilt/extended when a search
observes a newer KV version — "device blocks are a cache rebuilt from KV"
(SURVEY.md §5 checkpoint/resume).

Fault isolation: this module NEVER imports jax. Device execution goes
through the supervised DeviceRunner subprocess (surrealdb_tpu.device):
the search path ships raw row blocks + query batches over the
supervisor's RPC, and degrades to the exact numpy host path whenever
the device is cold, degraded, or out of budget — a wedged TPU can stall
the runner process, never a query worker thread.
"""

from __future__ import annotations

import threading
import uuid

import numpy as np

from surrealdb_tpu import key as K
from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import NONE, RecordId, is_truthy

from surrealdb_tpu import cnf

# device-search threshold: below this, numpy on host beats dispatch overhead
DEVICE_MIN_ROWS = cnf.KNN_DEVICE_MIN_ROWS
# blockwise scan threshold (rows) to bound [B, N] materialization
BLOCK_ROWS = cnf.KNN_BLOCK_ROWS


def _vec_dtype(params) -> type:
    # the index vector type governs storage precision; the reference's
    # parser defaults to F32 (syn define.rs:1107 VectorType::F32)
    vt = (params or {}).get("vector_type", "f32")
    return np.float32 if str(vt).lower() in ("f32", "i16", "i32") else np.float64


def _as_vector(v, dim, what, dtype=np.float64):
    if not isinstance(v, (list, tuple)):
        raise SdbError(f"Incorrect vector value for {what}")
    try:
        arr = np.asarray(v, dtype=dtype)
    except (TypeError, ValueError):
        raise SdbError(f"Incorrect vector value for {what}")
    if arr.ndim != 1 or arr.shape[0] != dim:
        raise SdbError(
            f"Incorrect vector dimension ({arr.shape[0] if arr.ndim == 1 else '?'}). Expected a vector of {dim} dimension."
        )
    return arr


def vector_index_update(idef, rid: RecordId, before, after, ctx):
    """Write-side maintenance: persist rid→vector under `he` state keys
    (reference hnsw/elements.rs) inside the caller's transaction."""
    ns, db = ctx.need_ns_db()
    dim = idef.hnsw["dimension"]
    col = idef.cols[0]
    from surrealdb_tpu.exec.eval import evaluate

    dtype = _vec_dtype(idef.hnsw)
    key = K.ix_state(ns, db, rid.tb, idef.name, b"he", K.enc_value(rid.id))
    vkey = K.ix_state(ns, db, rid.tb, idef.name, b"vn")
    old_vec = None
    new_vec = None
    if isinstance(before, dict):
        v = evaluate(col, ctx.with_doc(before, rid))
        if v is not NONE and v is not None:
            old_vec = v
    if isinstance(after, dict):
        v = evaluate(col, ctx.with_doc(after, rid))
        if v is not NONE and v is not None:
            new_vec = _as_vector(v, dim, f"index {idef.name}", dtype)
    if new_vec is None and old_vec is None:
        return
    # version allocation is process-atomic (ds.lock): concurrent writers
    # can't collide on a log slot; a cancelled txn burns a version, which
    # sync() detects as a log gap and resolves with a rebuild
    with ctx.ds.lock:
        counters = getattr(ctx.ds, "_ix_versions", None)
        if counters is None:
            counters = {}
            ctx.ds._ix_versions = counters
        ckey = (ns, db, rid.tb, idef.name)
        stored = ctx.txn.get_val(vkey) or 0
        ver = max(counters.get(ckey, 0), stored) + 1
        counters[ckey] = ver
    log_key = K.ix_state(ns, db, rid.tb, idef.name, b"hl", K.enc_u64(ver))
    if new_vec is not None:
        ctx.txn.set_val(key, new_vec.tobytes())
        ctx.txn.set_val(log_key, ("set", rid.id, new_vec.tobytes()))
    else:
        ctx.txn.delete(key)
        ctx.txn.set_val(log_key, ("del", rid.id, None))
    ctx.txn.set_val(vkey, ver)


def _exact_mxu_distances(metric: str, xs, q):
    """Exact f64 distances for the device-rankable metrics, shared by the
    single-query host path and the batched rescore. `xs` is [..., D] and
    `q` broadcasts against it; reduction is over the last axis. The
    reference computes distances in f64 regardless of stored type
    (trees/vector.rs)."""
    if metric == "euclidean":
        return np.linalg.norm(xs - q, axis=-1)
    if metric == "cosine":
        dots = (xs * q).sum(axis=-1)
        denom = np.maximum(
            np.linalg.norm(xs, axis=-1) * np.linalg.norm(q, axis=-1), 1e-300
        )
        return 1.0 - dots / denom
    if metric == "dot":
        return -(xs * q).sum(axis=-1)
    raise SdbError(f"unsupported device metric {metric}")


class _Coalescer:
    """Self-clocking cross-query dynamic batcher.

    The first searcher dispatches immediately (no added latency when
    idle); searches arriving while a device call is in flight queue up
    and ride the NEXT dispatch as one batched kernel call — so device
    batch size grows with client concurrency, inference-server style.
    This is how concurrent `SELECT … <|k|>` statements (e.g. from the
    threaded HTTP/WS server) share MXU work instead of serializing
    per-query dispatches. Reference contrast: hnsw/index.rs walks the
    graph per query under an RwLock; here concurrency *increases*
    device efficiency.
    """

    def __init__(self, index):
        self.index = index
        self.cond = threading.Condition()
        self.queue: list = []
        self.running = False

    def search(self, qv: np.ndarray, k: int):
        # slot: [result, exception, done]. Waiters are signalled by the
        # dispatching thread at batch completion (cond.notify_all) — no
        # polling interval, queued queries wake immediately. The wait is
        # capped by the calling query's remaining deadline (inflight
        # thread-local): a nearly-expired query must not park behind a
        # long batch it can no longer use.
        from surrealdb_tpu.err import QueryCancelled, QueryTimeout
        from surrealdb_tpu.inflight import cancelled as _q_cancelled
        from surrealdb_tpu.inflight import current as _q_current
        from surrealdb_tpu.inflight import remaining as _q_remaining

        slot = [None, None, False]
        entry = (qv, k, slot)
        with self.cond:
            self.queue.append(entry)
            while not slot[2] and self.running:
                if _q_cancelled():
                    # KILL / disconnect / drain while parked: withdraw
                    # and unwind — nothing signals this condition on
                    # cancel, so the wait below is sliced at 50ms
                    try:
                        self.queue.remove(entry)
                    except ValueError:
                        pass
                    h = _q_current()
                    if h is not None:
                        h.mark_cancelled()
                    raise QueryCancelled("The query was cancelled")
                budget = _q_remaining()
                if budget is not None and budget <= 0:
                    # expired while queued: withdraw if the batch hasn't
                    # picked us up; either way stop waiting — a late
                    # result written into the slot is simply discarded
                    try:
                        self.queue.remove(entry)
                    except ValueError:
                        pass
                    h = _q_current()
                    if h is not None:
                        h.mark_timed_out()
                    raise QueryTimeout(
                        "The query was not executed because it "
                        "exceeded the timeout"
                    )
                # completion still wakes riders immediately via
                # notify_all; the 50ms slice exists only so a KILL is
                # noticed while parked (nothing signals the condition on
                # cancel). Riders outside any query context keep the
                # pure event-driven wait.
                if _q_current() is not None:
                    self.cond.wait(0.05 if budget is None
                                   else min(budget, 0.05))
                else:
                    self.cond.wait()
            if not slot[2]:
                # no dispatch in flight: THIS thread becomes the
                # dispatcher for everything queued so far
                batch, self.queue = self.queue, []
                self.running = True
        if slot[2]:
            # our query rode a previous dispatch
            if slot[1] is not None:
                raise slot[1]
            return slot[0]
        try:
            self._run(batch)
        finally:
            with self.cond:
                self.running = False
                self.cond.notify_all()
        if slot[1] is not None:
            raise slot[1]
        return slot[0]

    def _run(self, batch):
        index = self.index
        try:
            kmax = max(k for _q, k, _s in batch)
            qvs = np.stack([q for q, _k, _s in batch])
            with index.lock:  # exclude cache sync while the kernel reads
                results = index._device_knn_batch(qvs, kmax)
            for (_q, k, slot), pairs in zip(batch, results):
                slot[0] = pairs[:k]
                slot[2] = True
            return
        except BaseException as e:
            from surrealdb_tpu.device import (
                DeviceOpError, DeviceUnavailable, get_supervisor,
            )

            if not isinstance(e, (DeviceUnavailable, DeviceOpError)):
                # a shared non-device failure (OOM, bug): attribute it
                # to every rider still waiting — nothing to degrade to
                for _q, _k, slot in batch:
                    if not slot[2]:
                        slot[1] = e
                        slot[2] = True
                return
            get_supervisor().note_fallback()
        # Degrade-and-recover: the device couldn't serve this batch, so
        # every rider is answered from the exact numpy host path — each
        # computed (and attributed) INDIVIDUALLY, so one rider's failure
        # can never poison the rest of the batch.
        for q, k, slot in batch:
            if slot[2]:
                continue
            try:
                with index.lock:
                    slot[0] = index._host_knn_single(q, k)
            except BaseException as e2:
                slot[1] = e2
            slot[2] = True


class TpuVectorIndex:
    """Per-(ns,db,tb,ix) device block cache + search engine."""

    def __init__(self, ns, db, tb, ix, params: dict):
        self.key = (ns, db, tb, ix)
        self.params = params
        self.dim = params["dimension"]
        from surrealdb_tpu.ops.metrics import normalize_metric

        self.metric, self.mink_p = normalize_metric(
            params.get("distance", "euclidean")
        )
        self.dtype = _vec_dtype(params)
        self.lock = threading.RLock()
        self.version = -1
        self.rids: list = []  # row -> RecordId
        self.row_index: dict = {}  # enc(id) -> row
        self.vecs = np.zeros((0, self.dim), dtype=self.dtype)
        self.valid = np.zeros(0, dtype=bool)  # tombstone mask
        # device blocks live in the supervised DeviceRunner, addressed
        # by (cache key, [version, epoch]); a runner restart or an epoch
        # bump re-ships them from the host arrays (KV truth)
        self._dev_key = f"vec/{uuid.uuid4().hex[:16]}"
        self._dev_epoch = 0
        self.rank_mode = None  # last runner-reported ranking mode
        self.coalescer = _Coalescer(self)

    # -- cache sync ---------------------------------------------------------
    def sync(self, ctx):
        """Bring the device block cache up to the KV truth: small gaps apply
        the op log incrementally (append + tombstone); big gaps or heavy
        fragmentation trigger a full repack (the reference's two-phase
        pending/compaction design, hnsw/index.rs)."""
        ns, db, tb, ix = self.key
        vkey = K.ix_state(ns, db, tb, ix, b"vn")
        ver = ctx.txn.get_val(vkey) or 0
        if ver == self.version:
            return
        with self.lock:
            if ver == self.version:
                return
            gap = ver - self.version
            n = len(self.rids)
            if self.version >= 0 and 0 < gap <= max(4096, n // 4):
                if self._apply_log(ctx, self.version, ver):
                    self.version = ver
                    frag = (
                        1.0 - (self.valid.sum() / max(len(self.valid), 1))
                        if len(self.valid)
                        else 0.0
                    )
                    if frag <= 0.25:
                        return
            self._rebuild(ctx)
            self.version = ver

    def _apply_log(self, ctx, from_ver, to_ver) -> bool:
        ns, db, tb, ix = self.key
        beg = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(from_ver + 1))
        end = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(to_ver)) + b"\x00"
        entries = list(ctx.txn.scan_vals(beg, end))
        if len(entries) != to_ver - from_ver:
            return False  # log incomplete (e.g. trimmed) — rebuild instead
        add_rows = []
        add_rids = []
        for _k, (op, idv, raw) in entries:
            h = K.enc_value(idv)
            row = self.row_index.get(h)
            if op == "del":
                if row is not None and row < len(self.valid):
                    self.valid[row] = False
                continue
            vec = np.frombuffer(raw, dtype=self.dtype)
            if row is not None and row < len(self.vecs):
                self.vecs[row] = vec
                self.valid[row] = True
            else:
                self.row_index[h] = len(self.rids) + len(add_rids)
                add_rids.append(RecordId(tb, idv))
                add_rows.append(vec)
        if add_rows:
            self.vecs = (
                np.vstack([self.vecs, np.stack(add_rows)])
                if len(self.vecs)
                else np.stack(add_rows)
            )
            self.valid = np.concatenate(
                [self.valid, np.ones(len(add_rows), bool)]
            )
            self.rids.extend(add_rids)
        self._drop_device()
        return True

    def _drop_device(self):
        """Invalidate the device-resident cache (host arrays are truth):
        bumping the epoch makes the runner's copy stale, so the next
        dispatch re-ships the blocks."""
        self._dev_epoch += 1
        self.rank_mode = None

    def _rebuild(self, ctx):
        ns, db, tb, ix = self.key
        pre = K.ix_state(ns, db, tb, ix, b"he")
        beg, end = K.prefix_range(pre)
        rids = []
        rows = []
        index = {}
        plen = len(pre)
        from surrealdb_tpu.kvs.api import deserialize

        for k, raw in ctx.txn.scan(beg, end):
            idv, _pos = K.dec_value(k, plen)
            index[K.enc_value(idv)] = len(rids)
            rids.append(RecordId(tb, idv))
            rows.append(np.frombuffer(deserialize(raw), dtype=self.dtype))
        self.rids = rids
        self.row_index = index
        self.vecs = (
            np.stack(rows) if rows else np.zeros((0, self.dim), self.dtype)
        )
        self.valid = np.ones(len(rids), dtype=bool)
        self._drop_device()
        # trim the consumed op log when we can write (bounds log growth)
        if getattr(ctx.txn, "write", False):
            ver = ctx.txn.get_val(K.ix_state(ns, db, tb, ix, b"vn")) or 0
            beg = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(0))
            end = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(ver)) + b"\x00"
            ctx.txn.delete_range(beg, end)

    # -- search -------------------------------------------------------------
    def knn(self, q, k: int, ctx, ef=None, cond=None, cond_ctx=None):
        """Top-k nearest records. `cond`: optional per-record predicate —
        handled by oversample + host truthiness check + refill
        (SURVEY.md hard-parts: cond-filtered KNN)."""
        self.sync(ctx)
        n = int(self.valid.sum())
        if n == 0:
            return []
        qv = _as_vector(q, self.dim, "knn query", self.dtype)
        if cond is None:
            pairs = self._raw_knn(qv, min(k, n))
            return pairs[:k]
        # predicate pushdown: oversample and refill
        want = k
        fetch = min(max(4 * k, 64), n)
        checked: set = set()
        out = []
        while True:
            pairs = self._raw_knn(qv, min(fetch, n))
            for rid, dist in pairs:
                hkey = K.enc_value(rid.id)
                if hkey in checked:
                    continue
                checked.add(hkey)
                if self._check_cond(rid, cond, cond_ctx):
                    out.append((rid, dist))
                    if len(out) >= want:
                        return out
            if fetch >= n:
                return out
            fetch = min(fetch * 4, n)

    def _check_cond(self, rid, cond, ctx):
        from surrealdb_tpu.exec.eval import evaluate, fetch_record

        doc = fetch_record(ctx, rid)
        if doc is NONE:
            return False
        c = ctx.with_doc(doc, rid)
        return is_truthy(evaluate(cond, c))

    def _raw_knn(self, qv: np.ndarray, k: int):
        from surrealdb_tpu.device import get_supervisor

        n = len(self.rids)
        if n < DEVICE_MIN_ROWS:
            return self._host_knn_single(qv, k)
        if not get_supervisor().fast_path():
            # circuit open / device cold / disabled: serve exact from
            # host immediately — no coalescer wait, no device dispatch
            get_supervisor().note_fallback()
            return self._host_knn_single(qv, k)
        return self.coalescer.search(qv, k)

    def _host_knn_single(self, qv: np.ndarray, k: int):
        """Exact numpy top-k over the host arrays — the degraded path
        and the small-store fast path (identical results to device)."""
        n = len(self.rids)
        if n == 0:
            return []
        d = self._host_distances(qv)
        d = np.where(self.valid, d, np.inf)
        k_eff = min(k, n)
        idx = np.argpartition(d, k_eff - 1)[:k_eff]
        idx = idx[np.argsort(d[idx], kind="stable")]
        return [
            (self.rids[i], float(d[i]))
            for i in idx
            if np.isfinite(d[i])
        ]

    def _device_cfg(self) -> dict:
        """Kernel budgets shipped per dispatch (read at call time so the
        serving process's configuration governs the runner)."""
        return {
            "hbm_budget": cnf.KNN_HBM_BUDGET_BYTES,
            "score_budget": cnf.KNN_SCORE_BUDGET_ELEMS,
            "query_chunk": cnf.KNN_QUERY_CHUNK,
            "int8_oversample": cnf.KNN_INT8_OVERSAMPLE,
            "block_rows": BLOCK_ROWS,
        }

    def _device_knn_batch(self, qvs: np.ndarray, k: int):
        """Batched search through the device supervisor: [B, D] queries
        -> per-query (rid, dist) lists. The runner ranks (bf16/int8/
        sharded) and rescores where it holds f32 rows; the int8 path
        returns candidates that are EXACTLY rescored here from the
        full-precision host rows. Raises DeviceUnavailable for the
        coalescer to degrade to the host path."""
        from surrealdb_tpu.device import DeviceUnavailable, get_supervisor

        sup = get_supervisor()
        n = len(self.rids)
        tag = [int(self.version), int(self._dev_epoch)]

        def loader():
            return "vec_load", {
                "metric": self.metric,
                "mink_p": self.mink_p,
                "cfg": self._device_cfg(),
            }, [
                np.ascontiguousarray(self.vecs),
                np.ascontiguousarray(self.valid.astype(np.uint8)),
            ]

        qs32 = np.ascontiguousarray(qvs, dtype=np.float32)
        meta = bufs = None
        for _attempt in (0, 1):
            sup.ensure_loaded(self._dev_key, tag, loader)
            t, meta, bufs = sup.call(
                "vec_knn",
                {"key": self._dev_key, "tag": tag, "k": int(k)},
                [qs32],
            )
            if t == "stale":
                # runner evicted/restarted between load and query
                sup.forget(self._dev_key)
                continue
            break
        else:
            # sup.unavailable: SdbError in require mode (the query must
            # fail loudly), DeviceUnavailable (degrade to host) in auto
            raise sup.unavailable("vec cache thrashing")
        self.rank_mode = meta.get("rank_mode")
        if meta.get("mode") == "cand":
            # int8 ranking candidates: exact host rescore from the
            # full-precision rows (kc rows per query — tiny next to the
            # store); per-query loop bounds the gather to [kc, D]
            cand = bufs[0]
            out = []
            for b in range(cand.shape[0]):
                ids_b = cand[b]
                ids_b = ids_b[(ids_b >= 0) & (ids_b < n)]
                rows = self.vecs[ids_b]
                d = self._host_distances(qvs[b], xs=rows)
                d = np.where(self.valid[ids_b], d, np.inf)
                k_eff = min(k, len(ids_b))
                if k_eff == 0:
                    out.append([])
                    continue
                sel = np.argpartition(d, k_eff - 1)[:k_eff]
                sel = sel[np.argsort(d[sel], kind="stable")]
                out.append([
                    (self.rids[int(ids_b[j])], float(d[j]))
                    for j in sel
                    if np.isfinite(d[j])
                ])
            return out
        dists, ids = bufs
        return [
            [
                (self.rids[int(i)], float(d))
                for d, i in zip(drow, irow)
                if 0 <= i < n and np.isfinite(d)
            ]
            for drow, irow in zip(dists, ids)
        ]

    def _host_distances(self, qv, xs=None):
        # the reference accumulates in f64 for most metrics regardless of
        # stored type (trees/vector.rs generic impls use to_float), but
        # cosine has an F32 specialization (cosine_distance_f32): f32
        # dot/norm sums combined in f64 — match it for TYPE F32 stores
        raw = self.vecs if xs is None else xs
        m = self.metric
        if m == "cosine" and raw.dtype == np.float32:
            x32 = raw
            q32 = np.asarray(qv, dtype=np.float32)
            dots = (x32 * q32[None, :]).sum(axis=1).astype(np.float64)
            na = np.sqrt((x32 * x32).sum(axis=1).astype(np.float64))
            nb = np.sqrt(np.float64((q32 * q32).sum()))
            return 1.0 - dots / np.maximum(na * nb, 1e-300)
        xs = raw.astype(np.float64)
        qv = np.asarray(qv, dtype=np.float64)
        if m in ("euclidean", "cosine", "dot"):
            return _exact_mxu_distances(m, xs, qv[None, :])
        if m == "manhattan":
            return np.abs(xs - qv[None, :]).sum(axis=1)
        if m == "chebyshev":
            return np.abs(xs - qv[None, :]).max(axis=1) if xs.size else np.zeros(0)
        if m == "hamming":
            return (xs != qv[None, :]).sum(axis=1).astype(np.float64)
        if m == "minkowski":
            return np.power(
                np.power(np.abs(xs - qv[None, :]), self.mink_p).sum(axis=1),
                1.0 / self.mink_p,
            )
        if m == "pearson":
            xc = xs - xs.mean(axis=1, keepdims=True)
            qc = qv - qv.mean()
            xn = xc / np.maximum(np.linalg.norm(xc, axis=1, keepdims=True), 1e-30)
            qn = qc / max(np.linalg.norm(qc), 1e-30)
            return 1.0 - xn @ qn
        if m == "jaccard":
            mn = np.minimum(xs, qv[None, :]).sum(axis=1)
            mx = np.maximum(xs, qv[None, :]).sum(axis=1)
            return 1.0 - mn / np.maximum(mx, 1e-30)
        raise SdbError(f"unsupported metric {m}")


def get_vector_index(idef, ctx) -> TpuVectorIndex:
    ns, db = ctx.need_ns_db()
    key = (ns, db, idef.tb, idef.name)
    eng = ctx.ds.vector_indexes.get(key)
    if eng is None:
        eng = TpuVectorIndex(ns, db, idef.tb, idef.name, idef.hnsw)
        ctx.ds.vector_indexes[key] = eng
    return eng
