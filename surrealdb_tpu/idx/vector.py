"""TPU-resident vector index.

Replaces the reference's HNSW graph walk (idx/trees/hnsw/, hot loop
layer.rs:184-223: per-neighbor async KV fetch + scalar distance) with a
device-resident flat store: batched distance (`einsum` on the MXU) +
`jax.lax.top_k`, blockwise for big stores, mesh-sharded for multi-chip
(SURVEY.md §7 step 4). Exact search ⇒ recall@10 = 1.0 ≥ the 0.95 target.

Consistency model mirrors hnsw/index.rs's two-phase design: the KV `he` keys
(rid→vector) written inside the caller's transaction are the source of
truth; the device block cache is an overlay rebuilt/extended when a search
observes a newer KV version — "device blocks are a cache rebuilt from KV"
(SURVEY.md §5 checkpoint/resume).

Fault isolation: this module NEVER imports jax. Device execution goes
through the supervised DeviceRunner subprocess (surrealdb_tpu.device):
the search path ships raw row blocks + query batches over the
supervisor's RPC, and degrades to the exact numpy host path whenever
the device is cold, degraded, or out of budget — a wedged TPU can stall
the runner process, never a query worker thread.
"""

from __future__ import annotations

import threading
import uuid

import numpy as np

from surrealdb_tpu import key as K
from surrealdb_tpu import resource
from surrealdb_tpu.device.batcher import DeviceBatcher
from surrealdb_tpu.err import SdbError
from surrealdb_tpu.utils.rwlock import RWLock
from surrealdb_tpu.val import NONE, RecordId, is_truthy

from surrealdb_tpu import cnf

# device-search threshold: below this, numpy on host beats dispatch overhead
DEVICE_MIN_ROWS = cnf.KNN_DEVICE_MIN_ROWS
# blockwise scan threshold (rows) to bound [B, N] materialization
BLOCK_ROWS = cnf.KNN_BLOCK_ROWS


def _vec_dtype(params) -> type:
    # the index vector type governs storage precision; the reference's
    # parser defaults to F32 (syn define.rs:1107 VectorType::F32)
    vt = (params or {}).get("vector_type", "f32")
    return np.float32 if str(vt).lower() in ("f32", "i16", "i32") else np.float64


def _as_vector(v, dim, what, dtype=np.float64):
    if not isinstance(v, (list, tuple)):
        raise SdbError(f"Incorrect vector value for {what}")
    try:
        arr = np.asarray(v, dtype=dtype)
    except (TypeError, ValueError):
        raise SdbError(f"Incorrect vector value for {what}")
    if arr.ndim != 1 or arr.shape[0] != dim:
        raise SdbError(
            f"Incorrect vector dimension ({arr.shape[0] if arr.ndim == 1 else '?'}). Expected a vector of {dim} dimension."
        )
    return arr


def vector_index_update(idef, rid: RecordId, before, after, ctx):
    """Write-side maintenance: persist rid→vector under `he` state keys
    (reference hnsw/elements.rs) inside the caller's transaction."""
    ns, db = ctx.need_ns_db()
    dim = idef.hnsw["dimension"]
    col = idef.cols[0]
    from surrealdb_tpu.exec.eval import evaluate

    dtype = _vec_dtype(idef.hnsw)
    key = K.ix_state(ns, db, rid.tb, idef.name, b"he", K.enc_value(rid.id))
    vkey = K.ix_state(ns, db, rid.tb, idef.name, b"vn")
    old_vec = None
    new_vec = None
    if isinstance(before, dict):
        v = evaluate(col, ctx.with_doc(before, rid))
        if v is not NONE and v is not None:
            old_vec = v
    if isinstance(after, dict):
        v = evaluate(col, ctx.with_doc(after, rid))
        if v is not NONE and v is not None:
            new_vec = _as_vector(v, dim, f"index {idef.name}", dtype)
    if new_vec is None and old_vec is None:
        return
    # version allocation is process-atomic (ds.lock): concurrent writers
    # can't collide on a log slot; a cancelled txn burns a version, which
    # sync() detects as a log gap and resolves with a rebuild. The KV
    # read happens BEFORE the lock — on a sharded store it is a remote
    # round trip, and ds.lock must never be held across one.
    stored = ctx.txn.get_val(vkey) or 0
    with ctx.ds.lock:
        counters = getattr(ctx.ds, "_ix_versions", None)
        if counters is None:
            counters = {}
            ctx.ds._ix_versions = counters
        ckey = (ns, db, rid.tb, idef.name)
        ver = max(counters.get(ckey, 0), stored) + 1
        counters[ckey] = ver
    log_key = K.ix_state(ns, db, rid.tb, idef.name, b"hl", K.enc_u64(ver))
    if new_vec is not None:
        ctx.txn.set_val(key, new_vec.tobytes())
        ctx.txn.set_val(log_key, ("set", rid.id, new_vec.tobytes()))
    else:
        ctx.txn.delete(key)
        ctx.txn.set_val(log_key, ("del", rid.id, None))
    ctx.txn.set_val(vkey, ver)


def _exact_mxu_distances(metric: str, xs, q):
    """Exact f64 distances for the device-rankable metrics, shared by the
    single-query host path and the batched rescore. `xs` is [..., D] and
    `q` broadcasts against it; reduction is over the last axis. The
    reference computes distances in f64 regardless of stored type
    (trees/vector.rs)."""
    if metric == "euclidean":
        return np.linalg.norm(xs - q, axis=-1)
    if metric == "cosine":
        dots = (xs * q).sum(axis=-1)
        denom = np.maximum(
            np.linalg.norm(xs, axis=-1) * np.linalg.norm(q, axis=-1), 1e-300
        )
        return 1.0 - dots / denom
    if metric == "dot":
        return -(xs * q).sum(axis=-1)
    raise SdbError(f"unsupported device metric {metric}")


class _Coalescer(DeviceBatcher):
    """Self-clocking cross-query dynamic batcher over one vector index.

    The first searcher dispatches immediately (no added latency when
    idle); searches arriving while a device call is in flight queue up
    and ride the NEXT dispatch as one batched kernel call — so device
    batch size grows with client concurrency, inference-server style.
    This is how concurrent `SELECT … <|k|>` statements (e.g. from the
    threaded HTTP/WS server) share MXU work instead of serializing
    per-query dispatches. Reference contrast: hnsw/index.rs walks the
    graph per query under an RwLock; here concurrency *increases*
    device efficiency.

    The batching mechanics (pipelined dispatch, deadline withdrawal,
    per-rider attribution) live in `device/batcher.py`; this class
    binds them to one index's engine entry: batch kernel =
    `index.knn_batch` (device or batched host, routed by platform),
    first fallback = the SAME batched host kernel, last-resort
    fallback = per-rider host single search (one poisoned rider can
    never fail its batchmates)."""

    def __init__(self, index):
        from surrealdb_tpu.device import DeviceOpError, DeviceUnavailable

        self.index = index
        super().__init__(
            dispatch=self._dispatch,
            fallback_batch=self._fallback_batch,
            fallback=self._fallback_one,
            retryable=(DeviceUnavailable, DeviceOpError),
        )

    def search(self, qv: np.ndarray, k: int):
        return self.submit((qv, k))

    def _read_lock(self):
        # TpuVectorIndex carries a reader-writer lock so pipelined
        # dispatches can score concurrently while cache sync stays
        # exclusive; test doubles may only have the legacy RLock
        rw = getattr(self.index, "rw", None)
        if rw is not None:
            return rw.read()
        return self.index.lock

    def _dispatch(self, payloads):
        kmax = max(k for _q, k in payloads)
        qvs = np.stack([q for q, _k in payloads])
        # the routed engine entry when the index has one; test doubles
        # expose only the raw device kernel
        fn = getattr(self.index, "knn_batch", None) \
            or self.index._device_knn_batch
        with self._read_lock():
            results = fn(qvs, kmax)
        return [pairs[:k] for (_q, k), pairs in zip(payloads, results)]

    def _fallback_batch(self, payloads):
        # the device couldn't serve this batch: answer the WHOLE batch
        # from one batched exact host kernel (a [B, N] BLAS pass still
        # beats B single passes — the degraded path batches too)
        from surrealdb_tpu.device import get_supervisor

        get_supervisor().note_fallback()
        kmax = max(k for _q, k in payloads)
        qvs = np.stack([q for q, _k in payloads])
        with self._read_lock():
            results = self.index._host_knn_multi(qvs, kmax)
        return [pairs[:k] for (_q, k), pairs in zip(payloads, results)]

    def _fallback_one(self, payload):
        q, k = payload
        with self._read_lock():
            return self.index._host_knn_single(q, k)


class TpuVectorIndex:
    """Per-(ns,db,tb,ix) device block cache + search engine."""

    def __init__(self, ns, db, tb, ix, params: dict, key_range=None,
                 label: str = ""):
        self.key = (ns, db, tb, ix)
        self.params = params
        self.dim = params["dimension"]
        # optional [lo, hi) clamp over the `he` element keyspace: a
        # shard-partitioned index (idx/shardvec.py) builds one engine
        # per shard range, each covering only its slice of the rows
        self.key_range = (
            None if key_range is None
            else (bytes(key_range[0]), bytes(key_range[1]))
        )
        self.label = label  # display name for residency/partial reports
        # directory for persisted CAGRA build artifacts (set by
        # get_vector_index from the datastore; None = never persist)
        self.snapshot_dir = None
        from surrealdb_tpu.ops.metrics import normalize_metric

        self.metric, self.mink_p = normalize_metric(
            params.get("distance", "euclidean")
        )
        self.dtype = _vec_dtype(params)
        self.lock = threading.RLock()
        # reader-writer lock over the host arrays: pipelined dispatches
        # score concurrently under read; cache sync mutates under write
        self.rw = RWLock()
        self.version = -1
        self.rids: list = []  # row -> RecordId
        self.row_index: dict = {}  # enc(id) -> row
        self.vecs = np.zeros((0, self.dim), dtype=self.dtype)
        self.valid = np.zeros(0, dtype=bool)  # tombstone mask
        # device blocks live in the supervised DeviceRunner, addressed
        # by (cache key, [version, epoch]); a runner restart or an epoch
        # bump re-ships them from the host arrays (KV truth)
        self._dev_key = f"vec/{uuid.uuid4().hex[:16]}"
        self._dev_epoch = 0
        self.rank_mode = None  # last runner-reported ranking mode
        # widest mesh the runner reported serving this engine's blocks
        # on (device/mesh.py; 1 or 0 = legacy single-device stores)
        self._dev_mesh = 0
        self._dev_mesh_ann = 0
        # per-epoch host scoring stats (row norms / squared norms) for
        # the batched BLAS host path; rebuilt lazily after cache sync
        self._host_stats = None
        # quantized graph-ANN overlay (idx/cagra.py): built from a host
        # snapshot for stores past cnf.KNN_ANN_MIN_ROWS, searched by
        # int8 greedy descent + exact re-rank. The flat graph + int8
        # arrays ship to the runner under their own (key, tag) blocks.
        self._ann = None           # built cagra.AnnIndex
        self._ann_state = "idle"   # idle | building | ready
        # rows overwritten since the graph snapshot, stamped with the
        # mutation counter at overwrite time: a build only un-dirties
        # rows whose stamp predates its snapshot (a row overwritten
        # AGAIN mid-build keeps brute-merging)
        self._ann_dirty: dict = {}
        self._ann_mut = 0          # overwrite stamp counter
        # tombstones since the snapshot: deletions poison graph slots
        # (the re-rank filters them), so they count toward staleness
        # like appends/overwrites do
        self._ann_dead = 0
        self._ann_dead_base = 0
        self._ann_gen = 0          # bumped on full repack (row remap)
        self._ann_seq = 0          # device block tag for shipped builds
        self._ann_lock = threading.Lock()
        self._ann_dev_key = f"ann/{uuid.uuid4().hex[:16]}"
        # segmented LSM-style serving (idx/segments.py): lazily created
        # once the store crosses the segmentation floor; None until
        # then (small stores keep the legacy single-graph overlay)
        self._segs = None
        # whole-index ANN rebuilds THIS engine scheduled (the legacy
        # drift treadmill); engine-scoped so churn gates can assert 0
        # without cross-datastore pollution (a module-level aggregate
        # lives in idx/segments.py)
        self.ann_full_rebuilds = 0
        self.coalescer = _Coalescer(self)
        # queries in flight on this engine (between sync and the end of
        # their scoring pass): a pinned engine's host arrays are not
        # evictable — freeing state out from under an active search
        # would silently change its answer, the one degradation the
        # governance layer must never produce
        self._pins = 0
        # resource governance: every byte this engine derives from KV
        # truth is a tracked, evictable account — the host rows
        # (rebuild = one range scan on the next sync), the CAGRA
        # build (rebuild in the background / reload from a persisted
        # artifact; brute force serves meanwhile), and the per-epoch
        # rank stats (a trivial recompute). Bound methods: the
        # accountant holds them weakly, so a discarded engine is
        # pruned, never pinned.
        acct_label = f"{tb}.{ix}" + (f"[{label}]" if label else "")
        # shard-part engines (key_range set) are TRACKED but their host
        # rows are not byte-evictable: the scatter router syncs and
        # searches a part in separate steps, and a background eviction
        # between them could merge a silently short answer — the one
        # wrongness this layer forbids. Their ann/rank-stats overlays
        # (safe to drop mid-flight) stay evictable; the unsharded
        # engine keeps full evictability behind the pin guard.
        self._mem_vec = resource.register(
            "vec", acct_label, self._vec_mem_bytes,
            evict=self._mem_evict_vec if key_range is None else None,
            owner=self,
        )
        self._mem_ann = resource.register(
            "ann", acct_label, self._ann_mem_bytes,
            evict=self._mem_evict_ann, owner=self,
        )
        self._mem_stats = resource.register(
            "rank_stats", acct_label, self._stats_mem_bytes,
            evict=self._mem_evict_stats, owner=self,
        )

    # -- resource accounting ------------------------------------------------

    def _vec_mem_bytes(self) -> int:
        return int(self.vecs.nbytes) + int(self.valid.nbytes)

    def _ann_mem_bytes(self) -> int:
        ann = self._ann
        return int(ann.nbytes()) if ann is not None else 0

    def _stats_mem_bytes(self) -> int:
        st = self._host_stats
        if st is None:
            return 0
        return sum(int(a.nbytes) for a in st
                   if a is not None and hasattr(a, "nbytes"))

    def _mem_evict_stats(self):
        # per-epoch scoring stats: recomputed lazily by the next BLAS
        # ranking pass — the cheapest possible degrade
        self._host_stats = None

    def _mem_evict_ann(self):
        # drop the built graph; brute force serves (exactly) until the
        # background build — possibly a fast artifact reload — returns.
        # The dirty-row map survives: an in-flight query that captured
        # the old AnnIndex still needs it for its exact tail merge, and
        # row numbers stay valid until a repack.
        with self._ann_lock:
            self._ann = None
            self._ann_gen += 1  # voids a build racing this eviction
            if self._ann_state == "ready":
                self._ann_state = "idle"

    def _mem_evict_vec(self):
        # degrade the host arrays to rebuild-on-touch: version -1 makes
        # the next sync() re-scan this engine's KV range (the exact
        # PR-9 fresh-node discipline); the ANN snapshot's row numbering
        # dies with the arrays. PINNED engines are skipped: a query
        # between its sync() and its read-locked scoring pass must
        # never observe the arrays vanish — eviction degrades speed,
        # NEVER answers. Called only from checkpoint sites that hold
        # none of this engine's locks.
        with self.lock:
            if self._pins > 0:
                return  # actively serving: not evictable right now
            with self.rw.write():
                self.version = -1
                self.rids = []
                self.row_index = {}
                self.vecs = np.zeros((0, self.dim), dtype=self.dtype)
                self.valid = np.zeros(0, dtype=bool)
                self._drop_device()
                with self._ann_lock:
                    self._ann = None
                    self._ann_dirty = {}
                    self._ann_dead = 0
                    self._ann_dead_base = 0
                    self._ann_gen += 1
                    if self._ann_state == "ready":
                        self._ann_state = "idle"
                if self._segs is not None:
                    self._segs.reset()

    # -- cache sync ---------------------------------------------------------
    def sync(self, ctx):
        """Bring the device block cache up to the KV truth: small gaps apply
        the op log incrementally (append + tombstone); big gaps or heavy
        fragmentation trigger a full repack (the reference's two-phase
        pending/compaction design, hnsw/index.rs). A store that crossed
        the ANN threshold (or whose graph went stale) kicks a background
        graph build afterwards — brute force serves until it lands."""
        # pressure checkpoint BEFORE taking any index lock: past the
        # soft watermark this may evict cold accounts (possibly this
        # engine's own — the rebuild below then runs from KV truth)
        self._mem_vec.touch()
        resource.checkpoint()
        ver0 = self.version
        try:
            self._sync_impl(ctx)
        finally:
            if self.version != ver0:
                # the sync grew state (log apply / rebuild): settle
                # with a fresh poll, same step-jump rationale as the
                # ANN install
                resource.checkpoint(fresh=True)
            self._maybe_maintain()

    def _sync_impl(self, ctx):
        ns, db, tb, ix = self.key
        vkey = K.ix_state(ns, db, tb, ix, b"vn")
        ver = ctx.txn.get_val(vkey) or 0
        if ver == self.version:
            return
        with self.lock, self.rw.write():
            if ver == self.version:
                return
            gap = ver - self.version
            n = len(self.rids)
            if self.version >= 0 and 0 < gap <= max(4096, n // 4):
                if self._apply_log(ctx, self.version, ver):
                    self.version = ver
                    frag = (
                        1.0 - (self.valid.sum() / max(len(self.valid), 1))
                        if len(self.valid)
                        else 0.0
                    )
                    if frag <= 0.25:
                        return
            self._rebuild(ctx)
            self.version = ver

    def _apply_log(self, ctx, from_ver, to_ver) -> bool:
        ns, db, tb, ix = self.key
        beg = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(from_ver + 1))
        end = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(to_ver)) + b"\x00"
        entries = list(ctx.txn.scan_vals(beg, end))
        if len(entries) != to_ver - from_ver:
            return False  # log incomplete (e.g. trimmed) — rebuild instead
        self._apply_entries([e for _k, e in entries])
        return True

    def _apply_entries(self, entries):
        """Apply pre-fetched op-log entries [(op, idv, raw), ...] to the
        host arrays. Pure in-memory — the caller holds the index locks
        and has already fetched the log slice (the shard router fetches
        ONCE and fans the ops out to its parts by key range)."""
        tb = self.key[2]
        add_rows = []
        add_rids = []
        add_valid = []
        for op, idv, raw in entries:
            h = K.enc_value(idv)
            row = self.row_index.get(h)
            if op == "del":
                if row is None:
                    continue
                if row < len(self.valid):
                    if self.valid[row]:
                        self._ann_dead += 1
                    self.valid[row] = False
                else:
                    # the row was appended EARLIER IN THIS BATCH and is
                    # still in the pending buffers — dropping the
                    # tombstone here would resurrect it forever
                    ai = row - len(self.rids)
                    if 0 <= ai < len(add_valid):
                        add_valid[ai] = False
                continue
            vec = np.frombuffer(raw, dtype=self.dtype)
            if row is not None and row < len(self.vecs):
                self.vecs[row] = vec
                self.valid[row] = True
                # the ANN graph/int8 snapshot no longer matches this
                # row: brute-merge it at query time until a rebuild
                self._ann_mut += 1
                self._ann_dirty[row] = self._ann_mut
            elif row is not None:
                # overwrite of a same-batch append: update the pending
                # buffer in place (a second append would leave a stale
                # duplicate row permanently valid)
                ai = row - len(self.rids)
                add_rows[ai] = vec
                add_valid[ai] = True
            else:
                self.row_index[h] = len(self.rids) + len(add_rids)
                add_rids.append(RecordId(tb, idv))
                add_rows.append(vec)
                add_valid.append(True)
        if add_rows:
            self.vecs = (
                np.vstack([self.vecs, np.stack(add_rows)])
                if len(self.vecs)
                else np.stack(add_rows)
            )
            self.valid = np.concatenate(
                [self.valid, np.asarray(add_valid, bool)]
            )
            self.rids.extend(add_rids)
        self._drop_device()

    def _drop_device(self):
        """Invalidate the device-resident cache (host arrays are truth):
        bumping the epoch makes the runner's copy stale, so the next
        dispatch re-ships the blocks. The host scoring stats are derived
        from the same arrays and invalidate with it."""
        self._dev_epoch += 1
        self.rank_mode = None
        self._host_stats = None

    def _he_range(self) -> tuple[bytes, bytes, bytes]:
        """(prefix, begin, end) of this engine's element keyspace —
        clamped to `key_range` for a shard part."""
        ns, db, tb, ix = self.key
        pre = K.ix_state(ns, db, tb, ix, b"he")
        beg, end = K.prefix_range(pre)
        if self.key_range is not None:
            beg = max(beg, self.key_range[0])
            end = min(end, self.key_range[1])
        return pre, beg, end

    def _scan_rows(self, ctx):
        """Read this engine's rows from KV truth (range-clamped). Pure
        I/O — takes NO index locks, so the scatter paths can park on a
        remote scan without wedging concurrent searchers; the caller
        installs the snapshot afterwards under the write lock."""
        pre, beg, end = self._he_range()
        tb = self.key[2]
        rids = []
        rows = []
        index = {}
        plen = len(pre)
        from surrealdb_tpu.kvs.api import deserialize

        for k, raw in ctx.txn.scan(beg, end):
            idv, _pos = K.dec_value(k, plen)
            index[K.enc_value(idv)] = len(rids)
            rids.append(RecordId(tb, idv))
            rows.append(np.frombuffer(deserialize(raw), dtype=self.dtype))
            if len(rids) % 65536 == 0:
                # chunk-boundary pause point: a rebuild under memory
                # pressure evicts colder state before allocating more
                resource.throttle("index_rebuild")
        return rids, rows, index

    def _install_rows(self, rids, rows, index):
        """Install a freshly scanned snapshot (caller holds the locks)."""
        self.rids = rids
        self.row_index = index
        self.vecs = (
            np.stack(rows) if rows else np.zeros((0, self.dim), self.dtype)
        )
        self.valid = np.ones(len(rids), dtype=bool)
        self._drop_device()
        # a repack remaps row ids: the ANN snapshot (graph ids, dirty
        # rows, any build in flight) is void — discard and re-trigger;
        # the segment table (spans of the old numbering) dies with it
        with self._ann_lock:
            self._ann = None
            self._ann_dirty = {}
            self._ann_dead = 0
            self._ann_dead_base = 0
            self._ann_gen += 1
            if self._ann_state == "ready":
                self._ann_state = "idle"
        if self._segs is not None:
            self._segs.reset()

    def _rebuild(self, ctx):
        ns, db, tb, ix = self.key
        self._install_rows(*self._scan_rows(ctx))
        # trim the consumed op log when we can write (bounds log growth);
        # shard parts never trim — the router owns the shared log
        if self.key_range is None and getattr(ctx.txn, "write", False):
            ver = ctx.txn.get_val(K.ix_state(ns, db, tb, ix, b"vn")) or 0
            beg = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(0))
            end = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(ver)) + b"\x00"
            ctx.txn.delete_range(beg, end)

    # -- shard-part serving (driven by idx/shardvec.py) ---------------------

    def part_sync(self, ctx, ver: int, entries):
        """Bring ONE shard part up to global mutation version `ver`.

        The router read `vn` once and fetched the shared op log once;
        `entries` is this part's share — ascending `(gver, op, idv,
        raw)` tuples — or None when the log cannot cover the gap (full
        range rebuild). Lock discipline differs from the unsharded
        `sync`: all KV I/O (the rebuild scan) runs OUTSIDE the index
        locks, so a scatter attempt parked on a sick shard's scan never
        wedges searchers of the healthy parts; installs re-check the
        version under the lock, so two racing syncs converge instead of
        regressing."""
        if ver <= self.version:
            return
        if entries is not None and self.version >= 0:
            frag = 0.0
            with self.lock, self.rw.write():
                if ver > self.version:
                    self._apply_entries([
                        (op, idv, raw) for g, op, idv, raw in entries
                        if g > self.version
                    ])
                    self.version = ver
                if len(self.valid):
                    frag = 1.0 - (self.valid.sum() / len(self.valid))
            if frag <= 0.25:
                self._maybe_maintain()
                return
        rids, rows, index = self._scan_rows(ctx)  # KV I/O: no locks held
        with self.lock, self.rw.write():
            if ver >= self.version:
                self._install_rows(rids, rows, index)
                self.version = ver
        self._maybe_maintain()

    def search_topk(self, qv: np.ndarray, k: int):
        """Per-part scatter entry: top-k over this part's (already
        synced) rows — exact, or CAGRA descent + exact re-rank when the
        part grew past the ANN floor. Pure compute: by the lock
        discipline above it can never block on a remote shard.

        Routing: device-bound parts ride the cross-query coalescer
        (concurrent queries share one batched kernel per part block);
        host-routed parts call the batched engine entry directly —
        paying the coalescer's condition dance per part per query
        measurably loses to one BLAS pass on CPU-routed stores."""
        with self.lock:
            self._pins += 1  # pin: eviction must not race this search
        try:
            n = int(self.valid.sum()) if len(self.valid) else 0
            if n == 0:
                return []
            k = min(k, n)
            if len(self.rids) < DEVICE_MIN_ROWS:
                # tiny part: the exact host ladder, bit-for-bit the
                # unsharded small-store path
                with self.rw.read():
                    return self._host_knn_single(qv, k)
            if self._use_device():
                return self.coalescer.search(qv, k)
            # lint: lock-held(read-side hold is the array-swap guard vs sync's rw.write; a device dispatch inside is bounded by the supervisor call timeout + degrade circuit, and eviction is already pin-gated)
            with self.rw.read():
                return self.knn_batch(np.asarray(qv)[None, :], k)[0]
        finally:
            with self.lock:
                self._pins -= 1

    def residency(self) -> dict:
        """Index-serving residency for INFO FOR SYSTEM / /metrics."""
        out = {
            "rows": int(self.valid.sum()) if len(self.valid) else 0,
            "bytes": int(self.vecs.nbytes),
            "version": int(self.version),
            "ann": self._ann_state,
        }
        ann = self._ann
        if ann is not None:
            out["ann_bytes"] = ann.nbytes()
        mesh_nd = max(int(self._dev_mesh), int(self._dev_mesh_ann))
        if mesh_nd > 1:
            # devices this engine's runner blocks actually served on
            # (device/mesh.py row-sharding); absent = single-device
            out["device_sharded"] = mesh_nd
        segs = self._segs
        if segs is not None and segs.active():
            st = segs.status()
            out["ann"] = "segmented"
            out["segments"] = st["segments"]
            out["segments_ready"] = st["ready"]
            out["tail_rows"] = st["tail_rows"]
        if self.label:
            out["range"] = self.label
        return out

    # -- segmented LSM-style serving (idx/segments.py) ----------------------

    def _segments(self):
        """The segment coordinator, created on first touch."""
        if self._segs is None:
            from surrealdb_tpu.idx.segments import SegmentedAnn

            with self.lock:
                if self._segs is None:
                    self._segs = SegmentedAnn(self)
        return self._segs

    def _seg_engaged(self) -> bool:
        """True when segmented serving governs this engine (mode +
        metric + size gates, idx/segments.py policy)."""
        segs = self._segs
        if segs is not None:
            return segs.engaged()
        from surrealdb_tpu import cnf as _cnf

        if str(_cnf.KNN_SEG_MODE).lower() == "off":
            return False
        return self._segments().engaged()

    def _maybe_maintain(self):
        """Post-sync index maintenance: segmented engines seal / build
        / merge in the background (idx/segments.py); everything else
        keeps the legacy whole-store graph schedule."""
        if self._seg_engaged():
            self._segments().maybe_maintain()
            return
        self._maybe_build_ann()

    # -- quantized graph-ANN overlay (idx/cagra.py) -------------------------

    def _ann_floor(self):
        """Row floor above which a graph build is scheduled, or None
        when the ANN path is disabled for this index (mode off, or a
        metric the MXU scoring recipe doesn't cover)."""
        mode = cnf.KNN_ANN_MODE
        if mode == "off" or self.metric not in (
            "euclidean", "cosine", "dot"
        ):
            return None
        if mode == "force":
            return 256
        return cnf.KNN_ANN_MIN_ROWS

    def _ann_stale(self, ann, n) -> bool:
        """Appended-tail + overwritten-row fraction past which the
        graph is rebuilt. Until the rebuild lands those rows are
        brute-ranked and merged per query, so results stay exact-
        re-ranked either way — staleness is a throughput concern."""
        drift = (n - ann.built_n) + len(self._ann_dirty) \
            + max(self._ann_dead - self._ann_dead_base, 0)
        return drift / max(n, 1) > cnf.KNN_ANN_TAIL_FRAC

    def _maybe_build_ann(self):
        floor = self._ann_floor()
        if floor is None:
            return
        n = len(self.rids)
        if n < floor:
            return
        ann = self._ann
        if ann is not None and not self._ann_stale(ann, n):
            return
        with self._ann_lock:
            if self._ann_state == "building":
                return
            self._ann_state = "building"
        if ann is not None:
            # drift past KNN_ANN_TAIL_FRAC is re-deriving the WHOLE
            # graph — the rebuild treadmill the segmented path
            # (idx/segments.py) exists to eliminate; counted so the
            # knn_churn gate can assert it never happens there
            from surrealdb_tpu.idx import segments as _segments

            self.ann_full_rebuilds += 1
            _segments.count("ann_full_rebuilds")
        threading.Thread(target=self._build_ann, daemon=True,
                         name="ann-build").start()

    def ensure_ann(self) -> bool:
        """Synchronous build entry (bench/tests): returns True when a
        ready, non-stale graph (or, on a segmented engine, a fully
        built segment set) serves searches of this store."""
        import time as _time

        if self._seg_engaged():
            return self._segments().drain()
        floor = self._ann_floor()
        n = len(self.rids)
        if floor is None or n < floor:
            return False
        while True:
            ann = self._ann
            if ann is not None and not self._ann_stale(ann, n):
                return True
            with self._ann_lock:
                if self._ann_state != "building":
                    if ann is not None:
                        from surrealdb_tpu.idx import segments as _sg

                        self.ann_full_rebuilds += 1
                        _sg.count("ann_full_rebuilds")
                    self._ann_state = "building"
                    break
            _time.sleep(0.05)  # a background build is running: wait
        self._build_ann()
        ann = self._ann
        # honest answer: a failed rebuild leaves the old (stale) graph
        # serving, which is NOT the fresh build this entry promises
        return ann is not None and not self._ann_stale(ann, len(self.rids))

    def _build_ann(self):
        """Build the CAGRA graph + int8 arrays from a host snapshot.
        Runs WITHOUT the index lock held through the build: the host
        arrays are append-stable (the log applier grows them by
        reallocation, so a captured reference keeps its length), and a
        concurrent in-place overwrite lands in `_ann_dirty`, whose rows
        are brute-merged at query time — a torn snapshot can never
        surface a wrong distance, only a slightly worse candidate set.
        A full repack bumps `_ann_gen`; a build that raced one is
        discarded.

        With a `snapshot_dir`, a persisted artifact whose mutation
        stamp (the `vn` version) AND row-identity digest match the
        current snapshot loads in seconds instead of redoing the build;
        a fresh build persists on the way out (idx/cagra.py
        save_index/load_index, SKVCRC01 frame idiom)."""
        from surrealdb_tpu.idx import cagra

        with self.rw.read():
            gen = self._ann_gen
            xs = self.vecs
            rids = self.rids
            version, epoch = self.version, self._dev_epoch
            mut_cut = self._ann_mut
            dead0 = self._ann_dead
        ann = self._load_ann_snapshot(xs, rids, version)
        loaded = ann is not None
        if ann is None:
            try:
                ann = cagra.build_index(xs, self.metric, version, epoch)
            except Exception:
                with self._ann_lock:
                    self._ann_state = "idle"
                return
        installed = False
        with self._ann_lock:
            if self._ann_gen != gen:
                self._ann_state = "idle"  # repack raced: discard
                return
            installed = True
            self._ann = ann
            self._ann_seq += 1
            # rows dirtied BEFORE the snapshot hold their new values in
            # xs (writers exclude the capture via the rw lock, so the
            # build covered them); rows stamped after — overwritten
            # DURING the build, possibly half-captured — stay dirty and
            # keep brute-merging
            self._ann_dirty = {
                r: g for r, g in self._ann_dirty.items() if g > mut_cut
            }
            # deletions known at snapshot time are as absorbed as an
            # ANN rebuild can make them (the rows leave the arrays only
            # at the next full repack) — stop counting them as drift
            self._ann_dead_base = dead0
            self._ann_state = "ready"
        if installed:
            self._mem_ann.touch()
            # the install just grew accounted bytes by a step: settle
            # pressure NOW with a fresh poll — the gated hot-path
            # checkpoint could reuse a stale low reading
            resource.checkpoint(fresh=True)
        if installed and not loaded:
            self._save_ann_snapshot(ann, xs, rids)

    # -- persisted build artifacts ------------------------------------------

    def _ann_snap_path(self):
        if not self.snapshot_dir:
            return None
        import hashlib
        import os

        ns, db, tb, ix = self.key
        # filename: readable stem + a collision-proof tag (names may
        # contain bytes a filesystem rejects; parts add their range)
        ident = repr((ns, db, tb, ix, self.label))
        tag = hashlib.sha256(ident.encode()).hexdigest()[:16]
        stem = "".join(
            c if c.isalnum() else "_" for c in f"{ns}.{db}.{tb}.{ix}"
        )[:48]
        return os.path.join(self.snapshot_dir, f"{stem}-{tag}.annsnap")

    @staticmethod
    def _row_digest(rids, n: int) -> str:
        """Row-identity digest over the first `n` rows IN ORDER: graph
        node ids are row numbers, so a reloaded artifact is only valid
        when the numbering — not just the row set — matches."""
        import hashlib

        h = hashlib.sha256()
        for r in rids[:n]:
            h.update(K.enc_value(r.id))
            h.update(b";")
        return h.hexdigest()

    def _load_ann_snapshot(self, xs, rids, version):
        path = self._ann_snap_path()
        if path is None or not len(xs):
            return None
        import os
        import sys

        from surrealdb_tpu.idx import cagra

        try:
            ann, meta = cagra.load_index(path)
        except OSError:
            return None  # no snapshot (or unreadable dir): just build
        except Exception as e:
            # corrupt/torn snapshot: warn + rebuild, NEVER serve it
            print(
                f"[surrealdb-tpu] ann snapshot {path} rejected "
                f"({e}); rebuilding from rows",
                file=sys.stderr, flush=True,
            )
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        if (ann.metric != self.metric
                or ann.built_n != len(xs)
                or ann.built_version != int(version)
                or meta.get("dim") != int(xs.shape[1])
                or meta.get("rows") != self._row_digest(rids, len(xs))):
            return None  # stale stamp: rows changed since the save
        return ann

    def _save_ann_snapshot(self, ann, xs, rids):
        path = self._ann_snap_path()
        if path is None:
            return
        import os
        import sys

        from surrealdb_tpu.idx import cagra

        try:
            os.makedirs(self.snapshot_dir, exist_ok=True)
            cagra.save_index(ann, path, extra={
                "dim": int(xs.shape[1]),
                "rows": self._row_digest(rids, ann.built_n),
            })
        except OSError as e:
            print(
                f"[surrealdb-tpu] ann snapshot save failed ({path}): "
                f"{e}", file=sys.stderr, flush=True,
            )

    def _ann_route(self, k: int):
        """The ready AnnIndex when a k-NN search of `k` should ride the
        graph path, else None (brute force — bit-for-bit the legacy
        results). A stale-but-built graph keeps serving while its
        replacement builds; the tail merge keeps results exact."""
        if cnf.KNN_ANN_MODE == "off" or k > cnf.KNN_ANN_MAX_K:
            return None
        return self._ann

    def _seg_route(self, k: int):
        """The segment coordinator when a k-NN search of `k` should fan
        over sealed segments, else None. Same k gate as the graph
        route; exact-only segment sets still fan out (each span scans
        exactly — the merge stays byte-identical to brute)."""
        if k > cnf.KNN_ANN_MAX_K:
            return None
        segs = self._segs
        if segs is not None and segs.active():
            return segs
        return None

    def ann_plan(self, k: int):
        """EXPLAIN surface: how a k-NN of `k` over this engine is
        served — None (brute scan), {"ann": "graph"} (legacy
        whole-store graph), or {"ann": "segmented", ...} with the
        segment fan-out shape."""
        segs = self._seg_route(k)
        if segs is not None:
            st = segs.status()
            return {
                "ann": "segmented",
                "segments": st["segments"],
                "ready": st["ready"],
                "tail_rows": st["tail_rows"],
            }
        if self._ann_route(k) is not None:
            return {"ann": "graph"}
        return None

    def _ann_search_cfg(self) -> dict:
        w = max(int(cnf.KNN_ANN_SEARCH_WIDTH), 1)
        width = 1
        while width < w:
            width *= 2  # pow2: descent kernel shapes stay a ladder
        return {
            "width": width,
            "iters": max(int(cnf.KNN_ANN_ITERS), 1),
            "expand": max(int(cnf.KNN_ANN_EXPAND), 1),
        }

    def _ann_device_search(self, ann, qs32: np.ndarray, kc: int,
                           dev_key=None, tag=None):
        """Descent candidates from the runner's AnnStore blocks; ships
        the build snapshot on first use / after a runner restart via
        the same (key, tag) protocol as the vector blocks — PR-4
        crash/reship and the post-ship prewarm apply unchanged.
        Segmented engines pass a per-SEGMENT `dev_key`/`tag`
        (idx/segments.py), making every sealed segment an independently
        shippable/evictable runner block."""
        from surrealdb_tpu.device import get_supervisor

        sup = get_supervisor()
        if dev_key is None:
            dev_key = self._ann_dev_key
        if tag is None:
            tag = [int(self._ann_seq), int(ann.built_version),
                   int(ann.built_epoch)]

        def loader():
            return "ann_load", {
                "metric": ann.metric,
                "cfg": self._ann_search_cfg(),
            }, [
                np.ascontiguousarray(ann.graph),
                np.ascontiguousarray(ann.x8),
                np.ascontiguousarray(ann.arow),
                np.ascontiguousarray(ann.x2),
            ]

        for _attempt in (0, 1):
            sup.ensure_loaded(dev_key, tag, loader)
            t, meta, bufs = sup.call(
                "ann_search",
                {"key": dev_key, "tag": tag, "kc": int(kc)},
                [qs32],
            )
            if t == "stale":
                sup.forget(dev_key)
                continue
            break
        else:
            raise sup.unavailable("ann cache thrashing")
        nd = int(meta.get("mesh_ndev", 1) or 1)
        if nd > self._dev_mesh_ann:
            self._dev_mesh_ann = nd
        return bufs[0]

    def _ann_extra_topk(self, ann, qvs, k: int, n: int):
        """Per-query top-k ids over rows the graph snapshot can't see
        (appended tail + overwritten rows), exact-scored; None when the
        snapshot covers the store. Bounded by KNN_ANN_TAIL_FRAC — past
        it `_ann_stale` schedules a rebuild."""
        dirty = [r for r in list(self._ann_dirty) if r < ann.built_n]
        if n <= ann.built_n and not dirty:
            return None
        extra = np.arange(ann.built_n, n, dtype=np.int64)
        if dirty:
            extra = np.concatenate(
                [np.asarray(sorted(dirty), np.int64), extra]
            )
        # tombstoned rows must not crowd valid ones out of the top-k
        # (the final re-rank would drop them, silently shrinking the
        # exact tail coverage)
        extra = extra[self.valid[extra]]
        if not len(extra):
            return None
        rows = self.vecs[extra]
        k_eff = min(k, len(extra))
        out = []
        for qv in qvs:
            d = self._host_distances(qv, xs=rows)
            if k_eff < len(extra):
                sel = np.argpartition(d, k_eff - 1)[:k_eff]
            else:
                sel = np.arange(len(extra))
            out.append(extra[sel])
        return out

    def _ann_knn_batch(self, ann, qvs: np.ndarray, k: int):
        """Graph-ANN search: int8 greedy descent (the runner's jax
        kernel, or its numpy mirror when the device is cold/degraded/
        host-routed) proposes an oversampled candidate set per query;
        rows outside the build snapshot are brute-ranked and merged;
        the final top-k comes from the exact `_host_distances` ladder
        over the union — every reported distance is exact, and the
        quantized descent only decides which kc candidates get
        considered (the AQR-style multi-stage re-rank)."""
        from surrealdb_tpu.device import DeviceOpError, DeviceUnavailable
        from surrealdb_tpu.idx import cagra

        n = len(self.rids)
        b = len(qvs)
        kc = min(ann.built_n, max(cnf.KNN_ANN_OVERSAMPLE * k, 32))
        qs32 = np.ascontiguousarray(np.asarray(qvs, np.float32))
        cand = None
        if self._use_device():
            try:
                cand = self._ann_device_search(ann, qs32, kc)
            except (DeviceUnavailable, DeviceOpError):
                cand = None  # degrade to the numpy descent below
        if cand is None:
            cfg = self._ann_search_cfg()
            width = min(max(cfg["width"], kc), ann.built_n)
            fn, probe_fn = cagra.int8_score_fn(ann, qs32)
            cand = cagra.descend(
                ann.graph, ann.built_n, fn, b, width, cfg["iters"],
                min(cfg["expand"], width), kc, probe_fn=probe_fn,
            )
        extra_top = self._ann_extra_topk(ann, qvs, k, n)
        out = []
        for i in range(b):
            ids_b = cand[i].astype(np.int64)
            ids_b = ids_b[(ids_b >= 0) & (ids_b < n)]
            if extra_top is not None:
                ids_b = np.concatenate([ids_b, extra_top[i]])
            ids_b = np.unique(ids_b)
            d = self._host_distances(qvs[i], xs=self.vecs[ids_b])
            d = np.where(self.valid[ids_b], d, np.inf)
            k_eff = min(k, len(ids_b))
            if k_eff == 0:
                out.append([])
                continue
            sel = np.argpartition(d, k_eff - 1)[:k_eff]
            sel = sel[np.argsort(d[sel], kind="stable")]
            res_i = [
                (self.rids[int(ids_b[j])], float(d[j]))
                for j in sel
                if np.isfinite(d[j])
            ]
            if len(res_i) < k:
                # tombstone-dense neighborhood (e.g. a fully deleted
                # cluster): graph candidates can underfill k while the
                # store still holds enough valid rows — answer that
                # query exactly rather than short (rare path; the
                # staleness counter is already scheduling a rebuild
                # when deletions accumulate)
                if len(res_i) < min(k, int(self.valid.sum())):
                    res_i = self._host_knn_single(qvs[i], k)
            out.append(res_i)
        return out

    # -- search -------------------------------------------------------------
    def knn(self, q, k: int, ctx, ef=None, cond=None, cond_ctx=None):
        """Top-k nearest records. `cond`: optional per-record predicate —
        handled by oversample + host truthiness check + refill
        (SURVEY.md hard-parts: cond-filtered KNN)."""
        import time as _time

        from surrealdb_tpu.telemetry import stage_record

        t0 = _time.perf_counter_ns()
        with self.lock:
            self._pins += 1  # pin: eviction must not race this query
        try:
            return self._knn(q, k, ctx, ef=ef, cond=cond,
                             cond_ctx=cond_ctx)
        finally:
            with self.lock:
                self._pins -= 1
            # wall time inside the index: cache sync + batcher wait +
            # kernel (device RPC time shows separately as device_rpc)
            stage_record("index_knn", _time.perf_counter_ns() - t0)

    def _knn(self, q, k: int, ctx, ef=None, cond=None, cond_ctx=None):
        self.sync(ctx)
        n = int(self.valid.sum())
        if n == 0:
            return []
        qv = _as_vector(q, self.dim, "knn query", self.dtype)
        if cond is None:
            pairs = self._raw_knn(qv, min(k, n))
            return pairs[:k]
        # predicate pushdown: oversample and refill
        want = k
        fetch = min(max(4 * k, 64), n)
        checked: set = set()
        out = []
        while True:
            pairs = self._raw_knn(qv, min(fetch, n))
            for rid, dist in pairs:
                hkey = K.enc_value(rid.id)
                if hkey in checked:
                    continue
                checked.add(hkey)
                if self._check_cond(rid, cond, cond_ctx):
                    out.append((rid, dist))
                    if len(out) >= want:
                        return out
            if fetch >= n:
                return out
            fetch = min(fetch * 4, n)

    def _check_cond(self, rid, cond, ctx):
        from surrealdb_tpu.exec.eval import evaluate, fetch_record

        doc = fetch_record(ctx, rid)
        if doc is NONE:
            return False
        c = ctx.with_doc(doc, rid)
        return is_truthy(evaluate(cond, c))

    def _raw_knn(self, qv: np.ndarray, k: int):
        n = len(self.rids)
        if n < DEVICE_MIN_ROWS:
            # tiny store: a single exact pass beats any batching overhead
            return self._host_knn_single(qv, k)
        # Everything else rides the cross-query batcher — including the
        # degraded/CPU-only paths, which coalesce into one batched host
        # kernel instead of N single passes (PR 6: the batcher must win
        # on CPU-only boxes too).
        return self.coalescer.search(qv, k)

    def _use_device(self) -> bool:
        """Routing policy for the scoring engine (SURREAL_KNN_HOST_BATCH):
        dispatch to the device runner on real accelerators; when the
        "device" IS this host's CPU, the batched BLAS host path wins —
        offloading numpy-speed kernels through jax only adds dispatch
        overhead. `device` forces the old always-dispatch behavior,
        `host` forces host scoring."""
        from surrealdb_tpu.device import get_supervisor

        mode = cnf.KNN_HOST_BATCH
        if mode == "host":
            return False
        sup = get_supervisor()
        if not sup.fast_path():
            if sup.mode != "off":
                # device wanted but cold/degraded/disabled: host serves
                sup.note_fallback()
            return False
        if mode == "device":
            return True
        if sup.platform == "cpu":
            # the "accelerator" is this host's own CPU (inline debug
            # mode or a CPU-platform runner): one BLAS pass here beats
            # shipping numpy-speed work through jax/IPC
            sup.counters["device_host_routed"] = (
                sup.counters.get("device_host_routed", 0) + 1
            )
            return False
        return True

    def knn_batch(self, qvs: np.ndarray, k: int):
        """The raw batched engine entry: [B, D] queries -> per-query
        (rid, dist) lists. A store with a built CAGRA graph routes
        through int8 descent + exact re-rank (`_ann_knn_batch`);
        everything else goes to the device runner or the batched exact
        host kernel by `_use_device`. This is the path the cross-query
        batcher dispatches AND what bench.py measures as
        `index_engine_qps` — the serving stack above it is pure tax.
        Device trouble raises DeviceUnavailable/DeviceOpError for the
        batcher's per-rider degrade ladder (the ANN path degrades
        internally to its numpy descent instead — falling back to a
        brute scan would forfeit the graph's 10× at the worst moment)."""
        segs = self._seg_route(k)
        if segs is not None:
            return segs.knn_batch(qvs, k)
        ann = self._ann_route(k)
        if ann is not None:
            return self._ann_knn_batch(ann, qvs, k)
        if self._use_device():
            return self._device_knn_batch(qvs, k)
        return self._host_knn_multi(qvs, k)

    def _host_knn_single(self, qv: np.ndarray, k: int):
        """Exact numpy top-k over the host arrays — the degraded path
        and the small-store fast path (identical results to device).
        Delegates to the batched kernel so sequential and batched
        results are byte-identical by construction."""
        return self._host_knn_multi(
            np.asarray(qv)[None, :], k
        )[0]

    def _host_knn_multi(self, qvs: np.ndarray, k: int):
        """Batched exact host KNN: [B, D] queries -> per-query
        (rid, dist) lists. Large stores with MXU metrics run the same
        two-stage discipline as the device kernels — ONE gemm ranking
        pass over the whole store in store precision, then an exact
        distance-ladder rescore of the oversampled candidates — so the
        [B, N] block is touched once, in f32, and every reported
        distance comes from the same per-metric ladder the legacy host
        path used. Small stores and exotic metrics keep the legacy
        per-query ladder bit-for-bit (the conformance oracle's path)."""
        n = len(self.rids)
        if n == 0:
            return [[] for _ in range(len(qvs))]
        if n < DEVICE_MIN_ROWS or self.metric not in (
            "euclidean", "cosine", "dot"
        ):
            return self._host_knn_multi_exact(qvs, k)
        return self._host_knn_multi_blas(qvs, k)

    def _host_knn_multi_exact(self, qvs: np.ndarray, k: int):
        """Legacy full-ladder search, one query at a time — byte-
        identical to the pre-batcher `_host_knn_single`."""
        n = len(self.rids)
        k_eff = min(k, n)
        out = []
        for qv in qvs:
            d = self._host_distances(qv)
            d = np.where(self.valid, d, np.inf)
            idx = np.argpartition(d, k_eff - 1)[:k_eff]
            idx = idx[np.argsort(d[idx], kind="stable")]
            out.append([
                (self.rids[i], float(d[i]))
                for i in idx
                if np.isfinite(d[i])
            ])
        return out

    def _host_stats_cached(self):
        """Per-epoch ranking stats for the BLAS path: f32 squared row
        norms (euclidean scores), f32 inverse row norms (cosine
        scores), and the invalid-row index list (None when the store
        has no tombstones — the common case skips the mask pass).
        Computed blockwise; never materializes an [N, D] copy."""
        st = self._host_stats
        if st is not None:
            return st
        xs = self.vecs
        n = xs.shape[0]
        x2 = np.empty(n, np.float64)
        step = max(1, (64 << 20) // max(xs.shape[1] * 8, 1))
        for s in range(0, n, step):
            blk = xs[s:s + step].astype(np.float64)
            x2[s:s + step] = (blk * blk).sum(axis=1)
        inv_norms = (
            1.0 / np.maximum(np.sqrt(x2), 1e-300)
        ).astype(np.float32)
        invalid = None
        if not self.valid.all():
            invalid = np.nonzero(~self.valid)[0]
        st = (x2.astype(np.float32), inv_norms, invalid)
        self._host_stats = st
        return st

    def _host_knn_multi_blas(self, qvs: np.ndarray, k: int):
        """Stage 1: rank every query against the whole store with one
        gemm per chunk (store precision; per-row results are bitwise
        stable across batch sizes >= 2, single queries pad to 2 rows —
        so batched and sequential searches return identical bytes).
        Stage 2: exact rescore of the kc oversampled candidates through
        `_host_distances` — the reported distances use the SAME ladder
        (and the same f32-cosine specialization) as the legacy path."""
        xs = self.vecs
        n = xs.shape[0]
        m = self.metric
        x2_32, inv_norms32, invalid = self._host_stats_cached()
        k_eff = min(k, n)
        kc = min(n, max(2 * k, k + 16))
        # bound the [chunk, N] f32 score block
        step = max(1, (cnf.KNN_SCORE_BUDGET_ELEMS // 2) // max(n, 1))
        out = []
        for s in range(0, len(qvs), step):
            qc = qvs[s:s + step]
            qb = np.ascontiguousarray(np.asarray(qc, dtype=xs.dtype))
            pad1 = qb.shape[0] == 1
            if pad1:
                # gemv and gemm round differently; a 2-row gemm keeps
                # single-query results bit-identical to batched ones
                qb = np.concatenate([qb, qb], axis=0)
            dots = qb @ xs.T  # [B, N] store precision
            if pad1:
                dots = dots[:1]
            if m == "euclidean":
                score = x2_32[None, :] - 2.0 * dots
            elif m == "cosine":
                score = dots * inv_norms32[None, :]
                np.negative(score, out=score)
            else:  # dot
                score = -dots
            if invalid is not None and len(invalid):
                score[:, invalid] = np.inf
            cand = np.argpartition(score, kc - 1, axis=1)[:, :kc]
            for b in range(cand.shape[0]):
                ids_b = cand[b]
                rows = xs[ids_b]
                d = self._host_distances(qc[b], xs=rows)
                d = np.where(self.valid[ids_b], d, np.inf)
                sel = np.argpartition(d, min(k_eff, kc) - 1)[:k_eff]
                sel = sel[np.argsort(d[sel], kind="stable")]
                out.append([
                    (self.rids[int(ids_b[j])], float(d[j]))
                    for j in sel
                    if np.isfinite(d[j])
                ])
        return out

    def _device_cfg(self) -> dict:
        """Kernel budgets shipped per dispatch (read at call time so the
        serving process's configuration governs the runner)."""
        return {
            "hbm_budget": cnf.KNN_HBM_BUDGET_BYTES,
            "score_budget": cnf.KNN_SCORE_BUDGET_ELEMS,
            "query_chunk": cnf.KNN_QUERY_CHUNK,
            "int8_oversample": cnf.KNN_INT8_OVERSAMPLE,
            "block_rows": BLOCK_ROWS,
        }

    def _device_knn_batch(self, qvs: np.ndarray, k: int):
        """Batched search through the device supervisor: [B, D] queries
        -> per-query (rid, dist) lists. The runner ranks (bf16/int8/
        sharded) and rescores where it holds f32 rows; the int8 path
        returns candidates that are EXACTLY rescored here from the
        full-precision host rows. Raises DeviceUnavailable for the
        coalescer to degrade to the host path."""
        from surrealdb_tpu.device import DeviceUnavailable, get_supervisor

        sup = get_supervisor()
        n = len(self.rids)
        tag = [int(self.version), int(self._dev_epoch)]

        def loader():
            return "vec_load", {
                "metric": self.metric,
                "mink_p": self.mink_p,
                "cfg": self._device_cfg(),
            }, [
                np.ascontiguousarray(self.vecs),
                np.ascontiguousarray(self.valid.astype(np.uint8)),
            ]

        qs32 = np.ascontiguousarray(qvs, dtype=np.float32)
        meta = bufs = None
        for _attempt in (0, 1):
            sup.ensure_loaded(self._dev_key, tag, loader)
            t, meta, bufs = sup.call(
                "vec_knn",
                {"key": self._dev_key, "tag": tag, "k": int(k)},
                [qs32],
            )
            if t == "stale":
                # runner evicted/restarted between load and query
                sup.forget(self._dev_key)
                continue
            break
        else:
            # sup.unavailable: SdbError in require mode (the query must
            # fail loudly), DeviceUnavailable (degrade to host) in auto
            raise sup.unavailable("vec cache thrashing")
        self.rank_mode = meta.get("rank_mode")
        nd = int(meta.get("mesh_ndev", 1) or 1)
        if nd > self._dev_mesh:
            self._dev_mesh = nd
        if meta.get("mode") == "cand":
            # int8 ranking candidates: exact host rescore from the
            # full-precision rows (kc rows per query — tiny next to the
            # store); per-query loop bounds the gather to [kc, D]
            cand = bufs[0]
            out = []
            for b in range(cand.shape[0]):
                ids_b = cand[b]
                ids_b = ids_b[(ids_b >= 0) & (ids_b < n)]
                rows = self.vecs[ids_b]
                d = self._host_distances(qvs[b], xs=rows)
                d = np.where(self.valid[ids_b], d, np.inf)
                k_eff = min(k, len(ids_b))
                if k_eff == 0:
                    out.append([])
                    continue
                sel = np.argpartition(d, k_eff - 1)[:k_eff]
                sel = sel[np.argsort(d[sel], kind="stable")]
                out.append([
                    (self.rids[int(ids_b[j])], float(d[j]))
                    for j in sel
                    if np.isfinite(d[j])
                ])
            return out
        dists, ids = bufs
        return [
            [
                (self.rids[int(i)], float(d))
                for d, i in zip(drow, irow)
                if 0 <= i < n and np.isfinite(d)
            ]
            for drow, irow in zip(dists, ids)
        ]

    def _host_distances(self, qv, xs=None):
        # the reference accumulates in f64 for most metrics regardless of
        # stored type (trees/vector.rs generic impls use to_float), but
        # cosine has an F32 specialization (cosine_distance_f32): f32
        # dot/norm sums combined in f64 — match it for TYPE F32 stores
        raw = self.vecs if xs is None else xs
        m = self.metric
        if m == "cosine" and raw.dtype == np.float32:
            x32 = raw
            q32 = np.asarray(qv, dtype=np.float32)
            dots = (x32 * q32[None, :]).sum(axis=1).astype(np.float64)
            na = np.sqrt((x32 * x32).sum(axis=1).astype(np.float64))
            nb = np.sqrt(np.float64((q32 * q32).sum()))
            return 1.0 - dots / np.maximum(na * nb, 1e-300)
        xs = raw.astype(np.float64)
        qv = np.asarray(qv, dtype=np.float64)
        if m in ("euclidean", "cosine", "dot"):
            return _exact_mxu_distances(m, xs, qv[None, :])
        if m == "manhattan":
            return np.abs(xs - qv[None, :]).sum(axis=1)
        if m == "chebyshev":
            return np.abs(xs - qv[None, :]).max(axis=1) if xs.size else np.zeros(0)
        if m == "hamming":
            return (xs != qv[None, :]).sum(axis=1).astype(np.float64)
        if m == "minkowski":
            return np.power(
                np.power(np.abs(xs - qv[None, :]), self.mink_p).sum(axis=1),
                1.0 / self.mink_p,
            )
        if m == "pearson":
            xc = xs - xs.mean(axis=1, keepdims=True)
            qc = qv - qv.mean()
            xn = xc / np.maximum(np.linalg.norm(xc, axis=1, keepdims=True), 1e-30)
            qn = qc / max(np.linalg.norm(qc), 1e-30)
            return 1.0 - xn @ qn
        if m == "jaccard":
            mn = np.minimum(xs, qv[None, :]).sum(axis=1)
            mx = np.maximum(xs, qv[None, :]).sum(axis=1)
            return 1.0 - mn / np.maximum(mx, 1e-30)
        raise SdbError(f"unsupported metric {m}")


def get_vector_index(idef, ctx):
    """The serving engine for one vector index: a node-local
    TpuVectorIndex, or — on a range-sharded store — the scatter-gather
    router (idx/shardvec.py) that partitions the index along the shard
    map and merges per-shard top-k."""
    ns, db = ctx.need_ns_db()
    key = (ns, db, idef.tb, idef.name)
    eng = ctx.ds.vector_indexes.get(key)
    if eng is None:
        from surrealdb_tpu.kvs.shard import ShardedBackend

        if isinstance(ctx.ds.backend, ShardedBackend):
            from surrealdb_tpu.idx.shardvec import ShardedVectorIndex

            eng = ShardedVectorIndex(ns, db, idef.tb, idef.name,
                                     idef.hnsw, ctx.ds.backend,
                                     telemetry=ctx.ds.telemetry)
        else:
            eng = TpuVectorIndex(ns, db, idef.tb, idef.name, idef.hnsw)
        eng.snapshot_dir = getattr(ctx.ds, "ann_snapshot_dir", None)
        ctx.ds.vector_indexes[key] = eng
    return eng
