"""Index & search layer (reference: core/src/idx/ — SURVEY.md §2.7, the
north-star target). Vector ANN runs on TPU (idx/vector.py), full-text BM25 is
host-side postings (idx/fulltext.py), plan selection in idx/planner.py."""
