"""Segmented LSM-style ANN: continuous ingest without rebuild stalls.

The single-graph overlay (idx/vector.py + idx/cagra.py) rebuilds the
WHOLE index once drift passes KNN_ANN_TAIL_FRAC and brute-merges the
dirty tail per query — at sustained write traffic that is a rebuild
treadmill with a growing exact-scan tax. This module restructures the
overlay into the Lucene/DiskANN-fresh idiom over the engine's existing
host arrays:

- **Mutable tail.** Writes land in the un-sealed suffix of the host
  arrays (rows `[sealed_hi, n)`), served exact/brute — a committed row
  is searchable on the very next sync, no build in the ingest path.
- **Sealed segments.** A seal policy (row count / byte size / age,
  `SURREAL_KNN_SEG_*`) freezes the tail into an immutable row span;
  a background job builds that span's own CAGRA graph at chunk
  boundaries riding `resource.throttle`. Segment graphs are built over
  the rows VALID at snapshot time (`row_map`), so sealing already
  compacts tombstones out of the graph.
- **Tiered merges.** When `KNN_SEG_FANOUT` adjacent segments share a
  geometric size tier, a background job builds one graph over their
  combined span and atomically splices it in — LSM tiers bound both
  the segment count (O(log n)) and the amortized per-row build work;
  merge compaction is where accumulated tombstones leave the graphs.
- **Per-segment tombstone bitmaps.** Deletes flip the engine's `valid`
  slice; a SEGMENT whose dead+overwritten fraction passes
  `KNN_SEG_TOMB_FRAC` gets ITS graph rebuilt (bounded work) — there is
  no global drift threshold and `ann_full_rebuilds` stays 0 forever.
- **Exact fan-out.** A query runs per-segment top-k (graph descent +
  exact re-rank where a graph is ready, exact scan otherwise, with
  per-segment oversampling scaled by tombstone density so a dense
  segment cannot underfill k) and k-way merges through the PR-9
  `merge_topk` — segments partition the rows, every per-segment list
  is exact over its rows, so the merge is exact (the PR-9 proof).

Reuse, by construction: per-segment artifacts persist through the
PR-9 `SKVANN01` CRC-framed format keyed by segment identity (content
hash, not version stamps — a sealed span is immutable); device
shipping rides the PR-4 `(key, tag)` block protocol with one
independently shippable/evictable key per segment; every sealed graph
registers an `ann`-class account with the PR-10 accountant (the
mutable tail is covered by the engine's existing `vec` account, the
bitmaps are slices of it). This module NEVER imports jax
(check_robustness rule 5) — device descent goes through the engine's
supervised-runner entry.

Lock order: engine locks (lock / rw / _ann_lock) are always taken
BEFORE the segment-table lock, never after it; maintenance jobs
capture array snapshots under `rw.read()`, release, and only then
touch the table lock — a seal/merge can never wedge a searcher.
"""

from __future__ import annotations

import threading
import time
import uuid

import numpy as np

from surrealdb_tpu import cnf, resource

# process-wide AGGREGATE counters (fixed keys, trivially bounded).
# Gates that must be isolated from other engines/datastores in the
# process assert on the ENGINE-scoped views instead: SegmentedAnn.stats
# (per coordinator) and TpuVectorIndex.ann_full_rebuilds — the PR-14
# datastore-scoped counter discipline, one level lower.
# lint: mem-account(fixed-key int counters, not derived state)
_COUNTERS = {
    "ann_full_rebuilds": 0,
    "seg_seals": 0,
    "seg_builds": 0,
    "seg_merges": 0,
    "seg_rebuilds": 0,
}
_COUNTER_LOCK = threading.Lock()


def count(name: str, by: int = 1):
    with _COUNTER_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + by


def counters() -> dict:
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def reset_counters():
    with _COUNTER_LOCK:
        for k in _COUNTERS:
            _COUNTERS[k] = 0


class _NoDeadline:
    """merge_topk ctx shim for engine-internal merges (the statement
    deadline is enforced by the serving layers above knn_batch)."""

    __slots__ = ()

    def check_deadline(self):
        pass


_NOCTX = _NoDeadline()


def _seg_mode() -> str:
    return str(cnf.KNN_SEG_MODE).lower()


class SealedSegment:
    """One immutable row span `[lo, hi)` of the engine's host arrays,
    plus the CAGRA graph built over the rows valid at its snapshot.

    The built graph lives in `graph`, ONE tuple `(ann, row_map)`
    assigned atomically (a searcher captures the pair together — a
    concurrent rebuild installing a new graph can never tear a query
    into old node ids against a new row map). `row_map` maps graph
    node ids to GLOBAL row numbers; None means the identity
    `lo + node` (the all-valid fast path — the graph was built straight
    over the array slice, no gather copy). `state`: `pending` (no
    graph yet — served exact), `ready` (graph serving), `empty` (no
    valid rows at snapshot — skipped). A segment never mutates rows;
    engine-side tombstones/overwrites are observed through `valid` /
    `_ann_dirty` at query time."""

    __slots__ = ("lo", "hi", "sid", "state", "graph", "_tlock",
                 "dev_key", "seq", "acct", "__weakref__")

    def __init__(self, lo: int, hi: int, sid: int, label: str,
                 tlock: threading.Lock):
        self.lo = int(lo)
        self.hi = int(hi)
        self.sid = int(sid)
        self.state = "pending"
        self.graph = None  # (AnnIndex, row_map | None), set atomically
        # the coordinator's table lock: graph installs happen under it,
        # so the accountant's evict callback takes it too — an eviction
        # can never discard a graph installed concurrently (or report
        # bytes freed for an install that landed just after)
        self._tlock = tlock
        # one independently shippable/evictable device block per
        # segment — the PR-4 (key, tag) protocol applies unchanged
        self.dev_key = f"ann/seg-{uuid.uuid4().hex[:16]}"
        self.seq = 0
        # PR-10 accounting: the sealed graph is ann-class derived
        # state; eviction degrades this ONE segment to exact scans
        # until the background rebuild returns
        self.acct = resource.register(
            "ann", f"{label}/seg{self.sid}", self._ann_bytes,
            evict=self._evict_graph, owner=self,
        )

    def span(self) -> int:
        return self.hi - self.lo

    def _ann_bytes(self) -> int:
        g = self.graph
        if g is None:
            return 0
        ann, rm = g
        b = int(ann.nbytes())
        if rm is not None:
            b += int(rm.nbytes)
        return b

    def _evict_graph(self):
        # drop this segment's graph only: exact scans serve the span
        # (answers stay exact, just slower) until a rebuild lands.
        # Under the table lock so a concurrent install can't be
        # discarded the instant it lands (evict callbacks run from
        # checkpoint sites that hold no segment/engine locks)
        with self._tlock:
            if self.state == "ready":
                self.graph = None
                self.state = "pending"

    def close(self):
        with self._tlock:
            self.graph = None
            self.state = "closed"
        self.acct.close()

    def status(self) -> dict:
        out = {"lo": self.lo, "hi": self.hi, "state": self.state}
        g = self.graph
        if g is not None:
            out["graph_rows"] = int(g[0].built_n)
            out["bytes"] = int(g[0].nbytes())
        return out


class SegmentedAnn:
    """Segment coordinator for one TpuVectorIndex: the seal / build /
    merge policies, the background maintenance worker, and the
    per-segment search fan-out. Created lazily by the engine; `reset()`
    voids everything on a repack/eviction (row numbering died)."""

    def __init__(self, engine):
        self.engine = engine
        # segment-table lock: pure bookkeeping — never held across a
        # build, a KV op, or any engine-lock acquisition (lock order:
        # engine locks strictly before this one)
        self.lock = threading.Lock()
        # ascending, contiguous-from-0 sealed spans
        # lint: mem-account(bookkeeping list; each segment's graph owns its own ann account)
        self.segs: list[SealedSegment] = []
        self.gen = 0            # bumped on reset: voids in-flight jobs
        self._sid = 0
        self._maint_running = False
        # engine-scoped counter view (same keys as the module
        # aggregate): what the churn gates assert on — counts from
        # OTHER engines/datastores in the process can never leak in
        # lint: mem-account(fixed-key int counters, not derived state)
        self.stats = {k: 0 for k in _COUNTERS}
        self._tail_born = None  # monotonic stamp for the age seal
        # change detection so per-sync maintenance stays O(1) when idle
        self._seen_mut = -1
        self._seen_dead = -1

    def _count(self, name: str, by: int = 1):
        # single-writer per key in practice (seals under the table
        # lock, installs on the one maintenance worker); the module
        # aggregate keeps its own lock
        self.stats[name] = self.stats.get(name, 0) + by
        count(name, by)

    # -- policy -------------------------------------------------------------

    def engaged(self) -> bool:
        """Whether segmented serving governs this engine right now."""
        mode = _seg_mode()
        if mode == "off":
            return False
        eng = self.engine
        if cnf.KNN_ANN_MODE == "off" or eng.metric not in (
            "euclidean", "cosine", "dot"
        ):
            return False
        if self.segs:
            return True
        n = len(eng.rids)
        if mode == "force":
            return n >= 16
        return n >= int(cnf.KNN_SEG_MIN_ROWS)

    def active(self) -> bool:
        """Whether queries should fan over segments (at least one
        sealed span exists and the mode still allows it)."""
        return bool(self.segs) and _seg_mode() != "off"

    def _seal_rows(self) -> int:
        return max(int(cnf.KNN_SEG_ROWS), 16)

    def _sealed_hi(self) -> int:
        return self.segs[-1].hi if self.segs else 0

    def _tier(self, rows: int) -> int:
        f = max(int(cnf.KNN_SEG_FANOUT), 2)
        base = self._seal_rows()
        t = 0
        while rows >= base * (f ** (t + 1)) and t < 32:
            t += 1
        return t

    # -- maintenance entry (post-sync, no engine locks held) ----------------

    def maybe_maintain(self):
        """Cheap per-sync policy check; kicks the background worker
        when there is sealing, building, or merging to do."""
        if not self.engaged():
            return
        self._adopt_legacy()
        dirty = self._dirty_snapshot()  # engine lock BEFORE table lock
        with self.lock:
            work = self._seal_locked() or self._has_jobs_locked(dirty)
        if work:
            self._kick()

    def _adopt_legacy(self):
        """An engine crossing into segmented mode with a legacy
        whole-store graph already built keeps serving it: the graph
        becomes the first sealed segment (rows it covered), and the
        leftover suffix becomes the mutable tail — no rebuild, no
        serving gap."""
        eng = self.engine
        if self.segs or eng._ann is None:
            return
        with eng._ann_lock:
            ann = eng._ann
            if ann is None or ann.metric != eng.metric:
                return
            if ann.built_n <= 0 or ann.built_n > len(eng.rids):
                return
            eng._ann = None  # the segment's account covers it now
            if eng._ann_state == "ready":
                eng._ann_state = "idle"
        with self.lock:
            if self.segs:
                return
            seg = self._new_seg_locked(0, ann.built_n)
            # the legacy graph includes rows already dead at its build;
            # counting them all as staleness just schedules one bounded
            # segment rebuild that compacts them out — never a stall
            seg.graph = (ann, None)
            seg.seq = 1
            seg.state = "ready"
            self.segs.append(seg)
        # the whole-store block the legacy path shipped is orphaned
        # now (the segment ships under its own key on first use)
        self._drop_dev_blocks([eng._ann_dev_key])

    def _new_seg_locked(self, lo: int, hi: int) -> SealedSegment:
        self._sid += 1
        eng = self.engine
        label = f"{eng.key[2]}.{eng.key[3]}" + (
            f"[{eng.label}]" if eng.label else ""
        )
        return SealedSegment(lo, hi, self._sid, label, self.lock)

    def _seal_locked(self) -> bool:
        """Apply the seal policy (caller holds the table lock). The
        FIRST seal takes the whole tail as one segment (a bulk load
        builds one big graph, exactly like the legacy path); steady
        ingest afterwards seals in `KNN_SEG_ROWS` chunks."""
        eng = self.engine
        n = len(eng.rids)
        hi = self._sealed_hi()
        tail = n - hi
        if tail <= 0:
            self._tail_born = None
            return False
        if self._tail_born is None:
            self._tail_born = time.monotonic()
        rows_floor = self._seal_rows()
        itemsize = np.dtype(eng.dtype).itemsize
        bytes_hit = tail * eng.dim * itemsize >= max(
            int(cnf.KNN_SEG_BYTES), 1 << 20
        )
        age = float(cnf.KNN_SEG_AGE_S)
        age_hit = age > 0 and (time.monotonic() - self._tail_born) >= age
        sealed = False
        if not self.segs and (tail >= rows_floor or bytes_hit or age_hit):
            self.segs.append(self._new_seg_locked(0, n))
            sealed = True
        else:
            while self.segs and n - self._sealed_hi() >= rows_floor:
                lo = self._sealed_hi()
                self.segs.append(
                    self._new_seg_locked(lo, lo + rows_floor)
                )
                sealed = True
            if self.segs and (bytes_hit or age_hit) \
                    and n > self._sealed_hi():
                lo = self._sealed_hi()
                self.segs.append(self._new_seg_locked(lo, n))
                sealed = True
        if sealed:
            self._count("seg_seals")
            self._tail_born = None if n == self._sealed_hi() else \
                time.monotonic()
        return sealed

    def _dirty_snapshot(self) -> list:
        """Stable copy of the engine's dirty-row keys, taken under the
        engine's ann lock and BEFORE any table-lock acquisition — the
        log applier mutates the dict concurrently, and the module's
        lock order forbids taking engine locks inside the table lock."""
        with self.engine._ann_lock:
            return list(self.engine._ann_dirty)

    def _stale_locked(self, seg: SealedSegment, dirty_keys) -> bool:
        """Segment-local staleness: dead graph rows + overwritten rows
        in the span, over the graph size — past KNN_SEG_TOMB_FRAC the
        segment's graph is rebuilt (and its dead rows compacted out)."""
        g = seg.graph
        if g is None or seg.state != "ready":
            return False
        ann, row_map = g
        eng = self.engine
        valid = eng.valid
        if seg.hi > len(valid):
            return False  # racing a reset; the next pass re-checks
        if row_map is not None:
            dead = int(np.count_nonzero(~valid[row_map]))
        else:
            # identity graphs are only built over all-valid spans (and
            # the adopted legacy graph counts its build-time dead rows
            # as staleness on purpose — one bounded rebuild compacts
            # them out), so every invalid row in the span is drift
            dead = int(np.count_nonzero(~valid[seg.lo:seg.hi]))
        dirty = sum(1 for r in dirty_keys if seg.lo <= r < seg.hi)
        frac = max(float(cnf.KNN_SEG_TOMB_FRAC), 0.01)
        return (max(dead, 0) + dirty) / max(ann.built_n, 1) > frac

    def _merge_run_locked(self):
        """First adjacent same-tier run of KNN_SEG_FANOUT ready/pending
        segments, lowest tier preferred (cheapest compaction first)."""
        f = max(int(cnf.KNN_SEG_FANOUT), 2)
        best = None
        tiers = [self._tier(s.span()) for s in self.segs]
        i = 0
        while i < len(self.segs):
            j = i
            while (
                j < len(self.segs)
                and tiers[j] == tiers[i]
                and self.segs[j].state in ("pending", "ready", "empty")
            ):
                j += 1
            if j - i >= f and (best is None or tiers[i] < best[0]):
                best = (tiers[i], i, i + f)
            i = max(j, i + 1)
        if best is None:
            return None
        _t, a, b = best
        return list(self.segs[a:b])

    def _has_jobs_locked(self, dirty_keys) -> bool:
        if any(s.state == "pending" for s in self.segs):
            return True
        eng = self.engine
        # capture the counters BEFORE the sweep: a mutation landing
        # mid-sweep must leave them unequal so the next sync re-checks
        # the staleness it may have just created
        mut, dead = eng._ann_mut, eng._ann_dead
        if (mut, dead) == (self._seen_mut, self._seen_dead):
            # nothing mutated since the last staleness sweep and no
            # pending builds: the only remaining job source is a merge
            return self._merge_run_locked() is not None
        if any(self._stale_locked(s, dirty_keys) for s in self.segs):
            # do NOT advance the seen counters: if this kick races the
            # worker's exit, the next sync re-detects the stale segment
            # instead of stranding it until the next mutation
            return True
        self._seen_mut, self._seen_dead = mut, dead
        return self._merge_run_locked() is not None

    # -- background worker --------------------------------------------------

    def _kick(self):
        with self.lock:
            if self._maint_running:
                return
            self._maint_running = True
        threading.Thread(
            target=self._maint_loop, daemon=True, name="seg-maint"
        ).start()

    def _maint_loop(self):
        try:
            while True:
                job = self._next_job()
                if job is None:
                    return
                if not self._run_job(job):
                    # a failed job (build error, snapshot race) is
                    # retried at SYNC cadence, not in a hot loop: exit
                    # and let the next maybe_maintain re-kick — exact
                    # scans serve the span meanwhile
                    return
        finally:
            with self.lock:
                self._maint_running = False

    def _next_job(self):
        """(kind, payload, gen) or None; picked under the table lock.
        Seal-builds first (ingest freshness), then stale-segment
        rebuilds, then tier merges (throughput)."""
        dirty = self._dirty_snapshot()  # engine lock BEFORE table lock
        with self.lock:
            gen = self.gen
            for s in self.segs:
                if s.state == "pending":
                    return ("build", s, gen)
            for s in self.segs:
                if self._stale_locked(s, dirty):
                    return ("rebuild", s, gen)
            run = self._merge_run_locked()
            if run is not None:
                return ("merge", run, gen)
        return None

    def _run_job(self, job) -> bool:
        """Run one job; False = it failed (caller stops draining the
        queue — the next sync retries instead of a hot loop)."""
        kind, payload, gen = job
        if kind in ("build", "rebuild"):
            return self._build_segment(payload, gen,
                                       rebuild=(kind == "rebuild"))
        return self._merge_segments(payload, gen)

    # -- builds -------------------------------------------------------------

    def _capture(self, lo: int, hi: int):
        """Snapshot the span under the read lock: the arrays are
        append-stable (a captured reference keeps its length) and the
        valid slice is copied, so the build never observes a torn
        bitmap; rows overwritten after `mut_cut` stay dirty and keep
        brute-merging (the legacy snapshot discipline, per segment)."""
        eng = self.engine
        with eng.rw.read():
            if hi > len(eng.rids):
                return None
            xs = eng.vecs
            vmask = eng.valid[lo:hi].copy()
            mut_cut = eng._ann_mut
        return xs, vmask, mut_cut

    def _build_ann_for(self, xs, vmask, lo: int, hi: int):
        """(ann, row_map) over the span's valid rows. All-valid spans
        build straight over the array slice (no copy); otherwise the
        valid rows gather through an explicit row_map — which is
        exactly how tombstones compact out of a graph."""
        from surrealdb_tpu.idx import cagra

        span = hi - lo
        live = int(np.count_nonzero(vmask))
        if live == 0:
            return None, None
        if live == span:
            row_map = None
            xs_b = xs[lo:hi]
        else:
            row_map = (np.flatnonzero(vmask) + lo).astype(np.int64)
            resource.throttle("seg_build")  # before the gather copy
            xs_b = np.ascontiguousarray(xs[row_map])
        # hash the span bytes ONCE: load and save share the path
        path = self._snap_path(xs_b)
        ann = self._load_snapshot(path, xs_b)
        if ann is None:
            ann = cagra.build_index(xs_b, self.engine.metric, 0, 0)
            self._save_snapshot(path, ann, xs_b)
        return ann, row_map

    def _build_segment(self, seg: SealedSegment, gen: int,
                       rebuild: bool = False) -> bool:
        cap = self._capture(seg.lo, seg.hi)
        if cap is None:
            return False  # reset raced the job: retry at sync cadence
        xs, vmask, mut_cut = cap
        try:
            ann, row_map = self._build_ann_for(
                xs, vmask, seg.lo, seg.hi
            )
        except Exception:
            # exact scans keep serving; the next sync retries (the
            # worker exits rather than hot-looping on a sick build)
            return False
        with self.lock:
            if self.gen != gen or seg not in self.segs \
                    or seg.state == "closed":
                return True  # obsolete job, not a failure
            if ann is None:
                seg.graph = None
                seg.state = "empty"
            else:
                seg.graph = (ann, row_map)
                seg.seq += 1
                seg.state = "ready"
        self._prune_dirty(seg.lo, seg.hi, mut_cut)
        self._count("seg_rebuilds" if rebuild else "seg_builds")
        seg.acct.touch()
        # the install grew accounted bytes by a step: settle pressure
        # NOW with a fresh poll (the legacy ANN-install discipline)
        resource.checkpoint(fresh=True)
        return True

    def _merge_segments(self, run: list, gen: int) -> bool:
        lo, hi = run[0].lo, run[-1].hi
        cap = self._capture(lo, hi)
        if cap is None:
            return False
        xs, vmask, mut_cut = cap
        try:
            ann, row_map = self._build_ann_for(xs, vmask, lo, hi)
        except Exception:
            return False
        with self.lock:
            if self.gen != gen:
                return True  # obsolete job, not a failure
            try:
                a = self.segs.index(run[0])
            except ValueError:
                return True  # the run was re-cut under us: drop it
            if self.segs[a:a + len(run)] != run:
                return True
            merged = self._new_seg_locked(lo, hi)
            merged.graph = (ann, row_map) if ann is not None else None
            merged.seq = 1
            merged.state = "ready" if ann is not None else "empty"
            self.segs[a:a + len(run)] = [merged]
        # in-flight queries hold their captured segment list: the old
        # graphs stay alive (and correct) until those queries finish
        for s in run:
            s.close()
        # the retired segments' runner blocks are dead weight now:
        # release them (best-effort, worker thread, no engine locks)
        self._drop_dev_blocks([s.dev_key for s in run])
        self._prune_dirty(lo, hi, mut_cut)
        self._count("seg_merges")
        merged.acct.touch()
        resource.checkpoint(fresh=True)
        return True

    def _drop_dev_blocks(self, keys):
        """Best-effort release of retired segments' device blocks so
        dead graphs stop competing with live ones for runner memory.
        Only when the runner is actively serving — a cold/degraded
        supervisor holds no blocks worth a spawn, and the runner's own
        LRU + byte budget reclaims anything this misses."""
        from surrealdb_tpu.device import get_supervisor

        try:
            sup = get_supervisor()
            if not sup.fast_path():
                return
            for k in keys:
                sup.forget(k)
                try:
                    sup.call("ann_drop", {"key": k, "tag": []}, [])
                except Exception:
                    pass  # reclaimed by the runner budget eventually
        except Exception:
            pass

    def _prune_dirty(self, lo: int, hi: int, mut_cut: int):
        """Rows in the span overwritten BEFORE the snapshot hold their
        new values in the build (writers exclude the capture via the
        rw lock); rows stamped after stay dirty and keep brute-merging."""
        eng = self.engine
        with eng._ann_lock:
            eng._ann_dirty = {
                r: g for r, g in eng._ann_dirty.items()
                if g > mut_cut or not (lo <= r < hi)
            }

    # -- lifecycle ----------------------------------------------------------

    def reset(self):
        """Void every segment (repack / vec eviction: the global row
        numbering died). Caller may hold engine locks — this only takes
        the table lock (engine-before-table order)."""
        with self.lock:
            self.gen += 1
            old, self.segs = self.segs, []
            self._tail_born = None
            self._seen_mut = -1
            self._seen_dead = -1
        for s in old:
            s.close()

    def drain(self, timeout_s: float = 600.0) -> bool:
        """Synchronous maintenance to quiescence (bench/tests): run
        jobs inline until none remain, then report whether every
        segment serves from a graph."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            with self.lock:
                busy = self._maint_running
                if not busy:
                    self._maint_running = True
            if busy:
                time.sleep(0.01)
                continue
            try:
                with self.lock:
                    self._seal_locked()
                job = self._next_job()
                if job is None:
                    break
                if not self._run_job(job):
                    break  # sick job: report un-drained, don't spin
            finally:
                with self.lock:
                    self._maint_running = False
        with self.lock:
            return bool(self.segs) and all(
                s.state in ("ready", "empty") for s in self.segs
            )

    def status(self) -> dict:
        with self.lock:
            segs = list(self.segs)
        n = len(self.engine.rids)
        hi = segs[-1].hi if segs else 0
        out = {
            "segments": len(segs),
            "ready": sum(1 for s in segs if s.state == "ready"),
            "tail_rows": max(n - hi, 0),
            "stats": dict(self.stats),
            "spans": [s.status() for s in segs],
        }
        # segment descents ride the engine's ann blocks, so the mesh
        # width the runner reported for them is the segment truth too
        nd = int(getattr(self.engine, "_dev_mesh_ann", 0) or 0)
        if nd > 1:
            out["device_sharded"] = nd
        return out

    # -- search fan-out -----------------------------------------------------

    def knn_batch(self, qvs: np.ndarray, k: int):
        """Per-query top-k over the segment fan-out: one exact list per
        sealed span (graph descent + exact re-rank when ready, exact
        scan otherwise), one for the mutable tail, k-way merged through
        the PR-9 `merge_topk`. Caller holds the engine read lock (the
        knn_batch contract), so the arrays are stable throughout."""
        from surrealdb_tpu.idx.shardvec import merge_topk

        eng = self.engine
        with self.lock:
            segs = list(self.segs)
        n = len(eng.rids)
        b = len(qvs)
        with eng._ann_lock:
            dirty = list(eng._ann_dirty)
        lists = []  # one [per-query results] entry per span
        for seg in segs:
            lo, hi = seg.lo, min(seg.hi, n)
            if lo >= hi:
                continue
            g = seg.graph  # atomic capture: (ann, row_map) together
            if g is None:
                if seg.state == "empty" and not any(
                    lo <= r < hi for r in dirty
                ):
                    continue
                lists.append(self._exact_span(qvs, k, lo, hi))
            else:
                lists.append(
                    self._graph_span(qvs, k, seg, g[0], g[1], dirty)
                )
        hi = segs[-1].hi if segs else 0
        if hi < n:
            lists.append(self._exact_span(qvs, k, hi, n))
        out = []
        for i in range(b):
            out.append(merge_topk(_NOCTX, [l[i] for l in lists], k))
        return out

    def _exact_span(self, qvs, k: int, lo: int, hi: int):
        """Exact per-span top-k. Reported distances always come from
        the engine's f64 ladder; big MXU-metric spans rank through the
        engine's two-stage BLAS discipline first (one f32 gemm over
        the slice, exact rescore of the oversampled candidates) —
        exactly how the whole-store brute path serves them — while
        small spans and exotic metrics run the ladder directly."""
        eng = self.engine
        from surrealdb_tpu.idx import vector as _vector

        span = hi - lo
        vmask = eng.valid[lo:hi]
        nvalid = int(np.count_nonzero(vmask))
        if nvalid == 0:
            return [[] for _ in range(len(qvs))]
        k_eff = min(k, nvalid)
        if span >= _vector.DEVICE_MIN_ROWS and eng.metric in (
            "euclidean", "cosine", "dot"
        ):
            return self._exact_span_blas(qvs, k_eff, lo, hi, vmask)
        xs = eng.vecs[lo:hi]
        out = []
        for qv in qvs:
            d = eng._host_distances(qv, xs=xs)
            d = np.where(vmask, d, np.inf)
            sel = np.argpartition(d, k_eff - 1)[:k_eff]
            sel = sel[np.argsort(d[sel], kind="stable")]
            out.append([
                (eng.rids[lo + int(j)], float(d[j]))
                for j in sel
                if np.isfinite(d[j])
            ])
        return out

    def _exact_span_blas(self, qvs, k_eff: int, lo: int, hi: int,
                         vmask):
        """Two-stage exact scan of one span: stage 1 ranks the slice
        with one f32 gemm per query chunk (the engine's per-epoch rank
        stats, sliced); stage 2 rescores the kc oversampled candidates
        through the exact f64 ladder — the same discipline (and the
        same single-query 2-row-gemm padding for bitwise stability) as
        `_host_knn_multi_blas`, scoped to the span."""
        eng = self.engine
        xs = eng.vecs
        m = eng.metric
        x2_32, inv_norms32, _invalid = eng._host_stats_cached()
        span = hi - lo
        kc = min(span, max(2 * k_eff, k_eff + 16))
        invalid = None
        if not vmask.all():
            invalid = np.flatnonzero(~vmask)
        xs_s = xs[lo:hi]
        step = max(1, (cnf.KNN_SCORE_BUDGET_ELEMS // 2) // max(span, 1))
        out = []
        for s in range(0, len(qvs), step):
            qc = qvs[s:s + step]
            qb = np.ascontiguousarray(np.asarray(qc, dtype=xs.dtype))
            pad1 = qb.shape[0] == 1
            if pad1:
                qb = np.concatenate([qb, qb], axis=0)
            dots = qb @ xs_s.T
            if pad1:
                dots = dots[:1]
            if m == "euclidean":
                score = x2_32[lo:hi][None, :] - 2.0 * dots
            elif m == "cosine":
                score = dots * inv_norms32[lo:hi][None, :]
                np.negative(score, out=score)
            else:  # dot
                score = -dots
            if invalid is not None and len(invalid):
                score[:, invalid] = np.inf
            cand = np.argpartition(score, kc - 1, axis=1)[:, :kc]
            for b in range(cand.shape[0]):
                ids_b = cand[b]
                d = eng._host_distances(qc[b], xs=xs_s[ids_b])
                d = np.where(vmask[ids_b], d, np.inf)
                sel = np.argpartition(d, min(k_eff, kc) - 1)[:k_eff]
                sel = sel[np.argsort(d[sel], kind="stable")]
                out.append([
                    (eng.rids[lo + int(ids_b[j])], float(d[j]))
                    for j in sel
                    if np.isfinite(d[j])
                ])
        return out

    def _graph_span(self, qvs, k: int, seg: SealedSegment, ann,
                    row_map, dirty):
        """Graph-served span: int8 descent (device kernel or its numpy
        mirror) proposes candidates, dirty/overwritten rows in the span
        brute-merge in, the final list is exact-re-ranked from the f32
        host rows. Oversampling scales with the span's tombstone
        density so a delete-heavy segment cannot underfill k; if it
        still would (pathological), the span is answered exactly."""
        from surrealdb_tpu.device import DeviceOpError, DeviceUnavailable
        from surrealdb_tpu.idx import cagra

        eng = self.engine
        lo, hi = seg.lo, seg.hi
        valid = eng.valid
        m = ann.built_n
        if row_map is not None:
            live_graph = int(np.count_nonzero(valid[row_map]))
        else:
            live_graph = int(np.count_nonzero(valid[lo:lo + m]))
        valid_span = int(np.count_nonzero(valid[lo:hi]))
        if valid_span == 0:
            return [[] for _ in range(len(qvs))]
        # per-segment oversampling: a tombstone-dense graph must
        # propose enough live candidates to fill k after the mask
        density = max(live_graph, 1) / max(m, 1)
        factor = min(int(np.ceil(1.0 / max(density, 1.0 / 64))), 64)
        kc = min(m, max(int(cnf.KNN_ANN_OVERSAMPLE) * k * factor, 32))
        qs32 = np.ascontiguousarray(np.asarray(qvs, np.float32))
        b = len(qvs)
        cand = None
        if eng._use_device():
            try:
                cand = eng._ann_device_search(
                    ann, qs32, kc, dev_key=seg.dev_key,
                    tag=[int(seg.seq), int(lo), int(hi)],
                )
            except (DeviceUnavailable, DeviceOpError):
                cand = None  # numpy mirror below
        if cand is None:
            cfg = eng._ann_search_cfg()
            width = min(max(cfg["width"], kc), m)
            fn, probe_fn = cagra.int8_score_fn(ann, qs32)
            cand = cagra.descend(
                ann.graph, m, fn, b, width, cfg["iters"],
                min(cfg["expand"], width), kc, probe_fn=probe_fn,
            )
        extra = np.asarray(
            sorted(r for r in dirty if lo <= r < hi), np.int64
        )
        if len(extra):
            extra = extra[valid[extra]]
        out = []
        for i in range(b):
            ids = cand[i].astype(np.int64)
            ids = ids[(ids >= 0) & (ids < m)]
            if row_map is not None:
                ids = row_map[ids]
            else:
                ids = ids + lo
            if len(extra):
                ids = np.concatenate([ids, extra])
            ids = np.unique(ids)
            d = eng._host_distances(qvs[i], xs=eng.vecs[ids])
            d = np.where(valid[ids], d, np.inf)
            k_eff = min(k, len(ids))
            if k_eff == 0:
                out.append([])
                continue
            sel = np.argpartition(d, k_eff - 1)[:k_eff]
            sel = sel[np.argsort(d[sel], kind="stable")]
            res = [
                (eng.rids[int(ids[j])], float(d[j]))
                for j in sel
                if np.isfinite(d[j])
            ]
            if len(res) < min(k, valid_span):
                # tombstone-dense neighborhood underfilled even after
                # oversampling: answer THIS span exactly (bounded by
                # the segment size, never the store)
                res = self._exact_span(
                    qvs[i:i + 1], k, lo, min(hi, len(eng.rids))
                )[0]
            out.append(res)
        return out

    # -- persisted per-segment artifacts ------------------------------------

    def _snap_path(self, xs_b: np.ndarray):
        """Artifact path keyed by SEGMENT IDENTITY: the content hash of
        the exact rows the graph covers (a sealed span is immutable, so
        the hash — not a version stamp — proves validity; an overwrite
        since the save changes the bytes and misses the artifact)."""
        eng = self.engine
        if not eng.snapshot_dir:
            return None
        import hashlib
        import os

        h = hashlib.sha256()
        h.update(repr((eng.key, eng.label, eng.metric,
                       xs_b.shape, str(xs_b.dtype))).encode())
        # zero-copy: xs_b is contiguous on both _build_ann_for branches
        # (a row slice of the C-order store, or an explicit gather) —
        # tobytes() would clone gigabytes mid-merge just to hash them
        h.update(memoryview(np.ascontiguousarray(xs_b)).cast("B"))
        ns, db, tb, ix = eng.key
        stem = "".join(
            c if c.isalnum() else "_" for c in f"{tb}.{ix}"
        )[:32]
        return os.path.join(
            eng.snapshot_dir, f"{stem}-seg-{h.hexdigest()[:24]}.annsnap"
        )

    def _load_snapshot(self, path, xs_b: np.ndarray):
        if path is None:
            return None
        import os
        import sys

        from surrealdb_tpu.idx import cagra

        try:
            ann, meta = cagra.load_index(path)
        except OSError:
            return None
        except Exception as e:
            print(
                f"[surrealdb-tpu] seg snapshot {path} rejected ({e}); "
                f"rebuilding from rows", file=sys.stderr, flush=True,
            )
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        if (ann.metric != self.engine.metric
                or ann.built_n != len(xs_b)
                or meta.get("dim") != int(xs_b.shape[1])):
            return None
        return ann

    def _save_snapshot(self, path, ann, xs_b: np.ndarray):
        if path is None:
            return
        import os
        import sys

        from surrealdb_tpu.idx import cagra

        try:
            os.makedirs(self.engine.snapshot_dir, exist_ok=True)
            cagra.save_index(ann, path, extra={
                "dim": int(xs_b.shape[1]), "segment": True,
            })
        except OSError as e:
            print(
                f"[surrealdb-tpu] seg snapshot save failed ({path}): "
                f"{e}", file=sys.stderr, flush=True,
            )
