"""CAGRA-style quantized graph-ANN index: host-side construction and
the numpy half of the search.

The device path is a brute-force scan and the host fallback is CPU
HNSW; neither survives 10M×768 vectors (30 GB at f32). This module
builds the compressed index that does:

- **Fixed-out-degree flat graph** (`build_graph`): a kNN-graph init
  (random-projection partition trees — exact kNN inside each leaf via
  one gemm — merged across trees, optional NN-descent refine), then
  CAGRA's rank-based reordering + reverse-edge merge (arXiv:2308.15136)
  into a dense `[N, D_out]` int32 array. Pure gather + top-k search is
  a perfect fit for the device runner's padded-array discipline.
- **int8 quantization** (`quantize_int8`): per-row scale with
  density-aware clipping (scale from a |x| quantile instead of the max,
  so one outlier coordinate cannot crush a row's resolution). 4× less
  HBM than f32; the exact f32 re-rank restores accuracy à la AQR-HNSW
  (arXiv:2602.21600).
- **Batched greedy descent** (`descend`): the fixed-iteration,
  static-shape frontier search shared (algorithmically) with the jax
  kernel in `device/annstore.py`; here it runs on numpy for the host
  fallback path. Both return an OVERSAMPLED candidate set — the exact
  re-rank from the serving side's full-precision rows happens in
  `idx/vector.py`.

Metric handling: euclidean searches raw rows; cosine searches
pre-normalized rows (monotonic); dot builds the graph over
norm-augmented rows (the MIPS→L2 reduction: x' = [x, sqrt(M²-|x|²)])
and scores with plain -dot at search time.

This module NEVER imports jax (check_robustness rule 5) — the jax
descent kernel lives runner-side in `device/annstore.py`.
"""

from __future__ import annotations

import time

import numpy as np

from surrealdb_tpu import cnf

MXU_METRICS = ("euclidean", "cosine", "dot")


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------


def row_stats(xs: np.ndarray, block_elems: int = 16 << 20):
    """f64-accurate per-row stats as f32: (x2 squared norms, norms).
    Blockwise — never materializes an [N, D] copy."""
    n, dim = xs.shape
    x2 = np.empty(n, np.float32)
    step = max(1, block_elems // max(dim, 1))
    for s in range(0, n, step):
        blk = xs[s:s + step].astype(np.float64)
        x2[s:s + step] = (blk * blk).sum(axis=1).astype(np.float32)
    norms = np.sqrt(x2, dtype=np.float32)
    return x2, norms


def quantize_int8(xs: np.ndarray, metric: str = "euclidean",
                  clip_q: float = None, norms: np.ndarray = None):
    """Per-row int8 with density-aware clipping: row r stores
    `round(clip(x_r, ±m_r) * 127 / m_r)` where `m_r` is the row's
    |x| quantile at `clip_q` (1.0 = exact max — bit-compatible with the
    legacy VecStore int8 path). Cosine quantizes the pre-normalized
    rows. Returns (x8 [N, D] int8, arow [N] f32 dequant scale)."""
    if clip_q is None:
        clip_q = cnf.KNN_ANN_CLIP_Q
    n, dim = xs.shape
    x8 = np.empty((n, dim), np.int8)
    arow = np.empty(n, np.float32)
    kth = min(max(int(clip_q * (dim - 1)), 0), dim - 1)
    step = max(1, (64 << 20) // max(dim * 4, 1))
    for s in range(0, n, step):
        blk = xs[s:s + step].astype(np.float32)
        if metric == "cosine":
            nb = norms[s:s + step] if norms is not None else np.maximum(
                np.linalg.norm(blk.astype(np.float64), axis=1), 1e-30
            ).astype(np.float32)
            blk = blk / np.maximum(nb, 1e-30)[:, None]
        a = np.abs(blk)
        if kth >= dim - 1:
            m = a.max(axis=1)
        else:
            m = np.partition(a, kth, axis=1)[:, kth]
            # a clipped row must still resolve: all-outlier rows (the
            # quantile lands on 0 while the max doesn't) fall back to max
            zero = m <= 0
            if zero.any():
                m[zero] = a[zero].max(axis=1)
        m = np.maximum(m, 1e-30)
        x8[s:s + step] = np.clip(
            np.rint(blk * (127.0 / m)[:, None]), -127, 127
        ).astype(np.int8)
        arow[s:s + step] = m / 127.0
    return x8, arow


def dequantize(x8: np.ndarray, arow: np.ndarray) -> np.ndarray:
    """Round-trip helper (tests): the f32 rows the int8 store encodes."""
    return x8.astype(np.float32) * arow[:, None]


# ---------------------------------------------------------------------------
# graph construction
# ---------------------------------------------------------------------------


class _Space:
    """Metric-transformed row access for the BUILD distance (squared
    euclidean in the transformed space — monotone with the metric).
    Never materializes a transformed [N, D] copy; gathers transform
    on the fly."""

    def __init__(self, xs, metric, x2, norms):
        self.xs = xs
        self.metric = metric
        self.dim = xs.shape[1] + (1 if metric == "dot" else 0)
        if metric == "cosine":
            self.inv = (1.0 / np.maximum(norms, 1e-30)).astype(np.float32)
            self.aug = None
        elif metric == "dot":
            self.inv = None
            m2 = float(x2.max()) if len(x2) else 0.0
            self.aug = np.sqrt(np.maximum(m2 - x2, 0.0)).astype(np.float32)
        else:
            self.inv = None
            self.aug = None

    def gather(self, ids) -> np.ndarray:
        """Transformed f32 rows for (possibly multi-dim) id arrays."""
        rows = self.xs[ids].astype(np.float32, copy=False)
        if self.inv is not None:
            rows = rows * self.inv[ids][..., None]
        elif self.aug is not None:
            rows = np.concatenate(
                [rows, self.aug[ids][..., None]], axis=-1
            )
        return rows

    def project(self, ids, r: np.ndarray) -> np.ndarray:
        """Projection of transformed rows onto direction r [dim]."""
        p = self.xs[ids].astype(np.float32, copy=False) @ r[:self.xs.shape[1]]
        if self.inv is not None:
            p = p * self.inv[ids]
        elif self.aug is not None:
            p = p + self.aug[ids] * r[-1]
        return p


def _merge_into(best_i, best_d, rows, new_i, new_d, keep: int):
    """Merge candidate (id, dist) lists into the running per-node best,
    deduping by id (min dist wins) — one lexsort per block, no Python
    per-row loops."""
    ci = np.concatenate([best_i[rows], new_i], axis=1)
    cd = np.concatenate([best_d[rows], new_d], axis=1)
    order = np.lexsort((cd, ci), axis=1)  # by id, then dist
    ci = np.take_along_axis(ci, order, 1)
    cd = np.take_along_axis(cd, order, 1)
    dup = np.zeros(ci.shape, bool)
    dup[:, 1:] = ci[:, 1:] == ci[:, :-1]
    cd[dup] = np.inf
    cd[ci < 0] = np.inf
    sel = np.argpartition(cd, keep - 1, axis=1)[:, :keep]
    best_i[rows] = np.take_along_axis(ci, sel, 1)
    best_d[rows] = np.take_along_axis(cd, sel, 1)


def _leaf_pass(space: _Space, best_i, best_d, keep, leaf, rng):
    """One random-projection partition tree: recursively median-split on
    random directions until leaves ≤ `leaf`, then exact kNN inside each
    leaf via one gemm — every node collects `keep`-bounded candidates."""
    n = len(best_i)
    k = min(keep // 2, leaf - 1)
    stack = [np.arange(n, dtype=np.int64)]
    while stack:
        idx = stack.pop()
        if len(idx) > leaf:
            r = rng.standard_normal(space.dim).astype(np.float32)
            p = space.project(idx, r)
            med = np.median(p)
            left = idx[p < med]
            right = idx[p >= med]
            if len(left) == 0 or len(right) == 0:
                # degenerate projection (constant rows): random halves
                perm = rng.permutation(len(idx))
                half = len(idx) // 2
                left, right = idx[perm[:half]], idx[perm[half:]]
            stack.append(left)
            stack.append(right)
            continue
        if len(idx) < 2:
            continue
        rows = space.gather(idx)
        x2 = (rows * rows).sum(axis=1)
        g = x2[:, None] + x2[None, :] - 2.0 * (rows @ rows.T)
        np.fill_diagonal(g, np.inf)
        kk = min(k, len(idx) - 1)
        sel = np.argpartition(g, kk - 1, axis=1)[:, :kk]
        d = np.take_along_axis(g, sel, axis=1)
        _merge_into(best_i, best_d, idx, idx[sel], d, keep)


def _refine_pass(space: _Space, best_i, best_d, keep, d_out, rng):
    """One NN-descent round: each node scores its neighbors' neighbors
    (sampled) — repairs partition-boundary misses from the tree init."""
    n = len(best_i)
    order = np.argsort(best_d, axis=1, kind="stable")[:, :d_out]
    fwd = np.take_along_axis(best_i, order, 1)
    fwd = np.where(fwd < 0, np.arange(n, dtype=np.int64)[:, None], fwd)
    s = min(4, d_out)
    step = max(1, (256 << 20) // max(s * d_out * space.dim * 4, 1))
    for lo in range(0, n, step):
        rows = np.arange(lo, min(lo + step, n), dtype=np.int64)
        cand = fwd[fwd[rows, :s]].reshape(len(rows), s * d_out)
        base = space.gather(rows)          # [B, D]
        crows = space.gather(cand)         # [B, C, D]
        d = (
            (base * base).sum(axis=1)[:, None]
            + (crows * crows).sum(axis=2)
            - 2.0 * np.einsum("bcd,bd->bc", crows, base)
        ).astype(np.float32)
        d[cand == rows[:, None]] = np.inf  # never link to self
        _merge_into(best_i, best_d, rows, cand, d, keep)


def build_graph(xs: np.ndarray, metric: str = "euclidean",
                d_out: int = None, leaf: int = None, trees: int = None,
                refine: int = None, seed: int = 7,
                x2: np.ndarray = None, norms: np.ndarray = None):
    """Fixed-out-degree search graph [N, d_out] int32: kNN-graph init
    (RP-trees + optional NN-descent), then CAGRA rank-based reordering
    with reverse-edge merge. Rows with fewer than d_out distinct
    neighbors (tiny stores) pad with self-loops (harmless: an already-
    visited node is never re-expanded)."""
    if d_out is None:
        d_out = cnf.KNN_ANN_DEGREE
    if leaf is None:
        leaf = cnf.KNN_ANN_LEAF
    if trees is None:
        trees = cnf.KNN_ANN_TREES
    if refine is None:
        refine = cnf.KNN_ANN_REFINE
    n = xs.shape[0]
    if refine < 0:
        refine = 1 if n <= 200_000 else 0
    if x2 is None or norms is None:
        x2, norms = row_stats(xs)
    space = _Space(xs, metric, x2, norms)
    rng = np.random.default_rng(seed)
    keep = 2 * d_out
    best_i = np.full((n, keep), -1, np.int64)
    best_d = np.full((n, keep), np.inf, np.float32)
    from surrealdb_tpu import resource

    for _t in range(max(trees, 1)):
        # chunk-boundary pause point (resource governance): under hard
        # memory pressure the build evicts colder node state — or
        # waits, when SURREAL_MEM_PAUSE_S is set — before allocating
        # the next tree pass's scratch
        resource.throttle("ann_build")
        _leaf_pass(space, best_i, best_d, keep, max(leaf, d_out + 1), rng)
    for _r in range(max(refine, 0)):
        resource.throttle("ann_build")
        _refine_pass(space, best_i, best_d, keep, d_out, rng)
    # forward edges in rank order (CAGRA "reordering": rank = closeness
    # position, which the merge below prefers over raw distance)
    order = np.argsort(best_d, axis=1, kind="stable")[:, :d_out]
    fwd = np.take_along_axis(best_i, order, 1)
    fwd_d = np.take_along_axis(best_d, order, 1)
    self_col = np.arange(n, dtype=np.int64)[:, None]
    fwd = np.where(np.isinf(fwd_d) | (fwd < 0), self_col, fwd)
    # reverse edges, rank-ordered per destination: flatten the forward
    # edge list RANK-major so the CSR pack's stable sort preserves rank
    # order inside each destination's segment
    from surrealdb_tpu.graph.csr import pack_csr

    rev_rows = fwd.T.reshape(-1).astype(np.int64)   # destinations
    rev_cols = np.tile(np.arange(n, dtype=np.int64), d_out)  # sources
    indptr, rev_sorted, _ = pack_csr(rev_rows, rev_cols, n)
    # bounded gather of each node's first d_out reverse edges
    counts = np.minimum(indptr[1:] - indptr[:-1], d_out).astype(np.int64)
    rev = np.full((n, d_out), -1, np.int64)
    pos = np.nonzero(counts)[0]
    if len(pos):
        starts = indptr[:-1][pos]
        cts = counts[pos]
        # rank of each kept reverse edge within its destination segment
        rcol = (
            np.arange(cts.sum()) - np.repeat(np.cumsum(cts) - cts, cts)
        )
        flat = np.repeat(starts, cts) + rcol
        rev[np.repeat(pos, cts), rcol] = rev_sorted[flat]
    # merge: forward rank r at priority 2r, reverse rank r at 2r+1 —
    # interleaves the two lists by rank, dedupes by id (min priority
    # wins), truncates to d_out
    cand = np.concatenate([fwd, rev], axis=1)
    pri = np.empty((n, 2 * d_out), np.float32)
    pri[:, :d_out] = 2.0 * np.arange(d_out, dtype=np.float32)
    pri[:, d_out:] = 2.0 * np.arange(d_out, dtype=np.float32) + 1.0
    pri[cand < 0] = np.inf
    pri[cand == self_col] = np.inf
    order = np.lexsort((pri, cand), axis=1)
    ci = np.take_along_axis(cand, order, 1)
    cp = np.take_along_axis(pri, order, 1)
    dup = np.zeros(ci.shape, bool)
    dup[:, 1:] = ci[:, 1:] == ci[:, :-1]
    cp[dup] = np.inf
    sel = np.argsort(cp, axis=1, kind="stable")[:, :d_out]
    graph = np.take_along_axis(ci, sel, 1)
    gp = np.take_along_axis(cp, sel, 1)
    graph = np.where(np.isinf(gp), self_col, graph)
    return np.ascontiguousarray(graph, np.int32)


# ---------------------------------------------------------------------------
# batched greedy descent (numpy — the host mirror of device/annstore)
# ---------------------------------------------------------------------------


def entry_ids(n: int, width: int) -> np.ndarray:
    """Deterministic strided sample ids (same formula as the device
    kernel — byte-stable across restarts by construction)."""
    return ((np.arange(width, dtype=np.int64) * n) // width)


def probe_count(n: int, width: int) -> int:
    """Size of the strided routing probe brute-scored per query batch
    to seed the descent: the frontier starts from the best `width` of
    these, so isolated clusters (which a pure graph walk from fixed
    entries can never reach — the kNN graph has no inter-cluster
    edges) are still discovered. One [B, probe] matmul — negligible
    next to a brute scan as long as probe ≪ n. The floor matters: a
    cluster of s rows is missed with p ≈ e^(-P·s/n) and recall
    plateaus at exactly 1-p (measured), so the probe scales BOTH with
    an absolute floor (small stores: cover everything) and as a
    fraction of n (large stores: constant per-cluster expectation —
    a fixed P=4096 measured 1.0 recall at 50k but 0.80 at 250k)."""
    return min(n, max(4 * width, cnf.KNN_ANN_PROBE,
                      int(n * cnf.KNN_ANN_PROBE_FRAC)))


def descend(graph: np.ndarray, n: int, score_fn, batch: int,
            width: int, iters: int, expand: int, kc: int,
            probe_fn=None) -> np.ndarray:
    """Fixed-iteration batched greedy graph descent. `score_fn(ids)`
    maps an int64 id array [B, C] to f32 scores (lower = closer; any
    monotone transform of the metric works — the exact re-rank
    restores true distances). `probe_fn(ids [P]) -> [B, P]` scores the
    shared routing probe with ONE gemm — without it the probe would
    gather a [B, P, D] block (hundreds of MB at 1M×768). Returns
    candidate ids [B, kc], unique per row, best-first."""
    W = max(width, kc)
    probe = entry_ids(n, probe_count(n, W))
    if probe_fn is not None:
        pd = probe_fn(probe).astype(np.float32, copy=False)
    else:
        pd = score_fn(
            np.broadcast_to(probe[None, :], (batch, len(probe)))
        ).astype(np.float32, copy=False)
    sel0 = np.argpartition(pd, W - 1, axis=1)[:, :W]
    ids = probe[sel0]
    dist = np.take_along_axis(pd, sel0, 1).copy()
    expanded = np.zeros((batch, W), bool)
    for _it in range(iters):
        key = np.where(expanded, np.inf, dist)
        sel = np.argpartition(key, expand - 1, axis=1)[:, :expand]
        if not np.isfinite(
            np.take_along_axis(key, sel, 1)
        ).any():
            break  # every frontier slot expanded: converged
        np.put_along_axis(expanded, sel, True, axis=1)
        src = np.take_along_axis(ids, sel, 1)          # [B, E]
        nb = graph[src].reshape(batch, -1).astype(np.int64)  # [B, E*D]
        # drop duplicates: vs the current list, and inside nb itself
        dup = (nb[:, :, None] == ids[:, None, :]).any(axis=2)
        eq = nb[:, :, None] == nb[:, None, :]
        inner = (np.tril(eq, k=-1)).any(axis=2)
        nd = score_fn(nb).astype(np.float32, copy=False)
        nd = np.where(dup | inner, np.inf, nd)
        mi = np.concatenate([ids, nb], axis=1)
        md = np.concatenate([dist, nd], axis=1)
        me = np.concatenate([expanded, dup | inner], axis=1)
        keep = np.argpartition(md, W - 1, axis=1)[:, :W]
        ids = np.take_along_axis(mi, keep, 1)
        dist = np.take_along_axis(md, keep, 1)
        expanded = np.take_along_axis(me, keep, 1)
    order = np.argsort(dist, axis=1, kind="stable")[:, :kc]
    return np.take_along_axis(ids, order, 1)


# ---------------------------------------------------------------------------
# built artifact
# ---------------------------------------------------------------------------


class AnnIndex:
    """One built CAGRA index over a snapshot of the host rows: the flat
    graph + the int8 ranking arrays the device store ships, plus the
    (version, epoch) the snapshot was taken at — the device cache tag,
    so crash/reship and prewarm ride the existing block protocol."""

    __slots__ = ("metric", "graph", "x8", "arow", "x2", "d_out",
                 "built_n", "built_version", "built_epoch", "build_s",
                 "inv_norms")

    def __init__(self, metric, graph, x8, arow, x2, inv_norms,
                 built_n, built_version, built_epoch, build_s):
        self.metric = metric
        self.graph = graph
        self.x8 = x8
        self.arow = arow
        self.x2 = x2
        self.inv_norms = inv_norms
        self.d_out = int(graph.shape[1]) if graph.ndim == 2 else 0
        self.built_n = int(built_n)
        self.built_version = int(built_version)
        self.built_epoch = int(built_epoch)
        self.build_s = float(build_s)

    def nbytes(self) -> int:
        return int(self.graph.nbytes + self.x8.nbytes + self.arow.nbytes
                   + self.x2.nbytes)


def build_index(xs: np.ndarray, metric: str, version: int, epoch: int,
                seed: int = 7, **kw) -> AnnIndex:
    """Snapshot build: graph + int8 arrays from the f32/f64 host rows.
    `version`/`epoch` stamp the snapshot for the device cache tag."""
    t0 = time.perf_counter()
    n = xs.shape[0]
    x2, norms = row_stats(xs)
    graph = build_graph(xs, metric, seed=seed, x2=x2, norms=norms, **kw)
    from surrealdb_tpu import resource

    resource.throttle("ann_build")  # before the int8 store allocates
    x8, arow = quantize_int8(xs, metric, norms=norms)
    if metric == "euclidean":
        # squared norms of the DEQUANTIZED rows: the int8 descent
        # (host mirror and device kernel alike) scores x2q - 2·q·x̂,
        # which is only monotone-consistent against x̂ = x8·arow.
        # Blockwise — never an [N, D] f32 copy of the int8 store.
        x2q = np.empty(n, np.float32)
        step = max(1, (64 << 20) // max(xs.shape[1] * 4, 1))
        for s in range(0, n, step):
            blk = x8[s:s + step].astype(np.float32)
            x2q[s:s + step] = (blk * blk).sum(axis=1)
        x2q *= arow * arow
    else:
        x2q = np.zeros(n, np.float32)
    inv_norms = (1.0 / np.maximum(norms, 1e-30)).astype(np.float32)
    return AnnIndex(
        metric, graph, x8, arow, x2q,
        inv_norms, n, version, epoch, time.perf_counter() - t0,
    )


def host_score_fn(xs: np.ndarray, metric: str, qs: np.ndarray,
                  x2: np.ndarray = None, inv_norms: np.ndarray = None):
    """Descent scoring against the full-precision host rows (the
    degraded/CPU path — strictly better than the int8 scores the device
    uses, same monotone-score contract). Returns (score_fn, probe_fn):
    per-candidate gather scoring and one-gemm probe scoring."""
    qs32 = np.ascontiguousarray(qs, np.float32)

    def fn(ids):
        rows = xs[ids].astype(np.float32, copy=False)  # [B, C, D]
        dots = np.einsum("bcd,bd->bc", rows, qs32)
        if metric == "euclidean":
            return x2[ids] - 2.0 * dots
        if metric == "cosine":
            return -(dots * inv_norms[ids])
        return -dots

    def probe(ids):
        rows = xs[ids].astype(np.float32, copy=False)  # [P, D]
        dots = qs32 @ rows.T                           # [B, P]
        if metric == "euclidean":
            return x2[ids][None, :] - 2.0 * dots
        if metric == "cosine":
            return -(dots * inv_norms[ids][None, :])
        return -dots

    return fn, probe


def int8_score_fn(ann: "AnnIndex", qs: np.ndarray):
    """Descent scoring against the DEQUANTIZED int8 ranking rows — the
    numpy mirror of the device kernel's scoring (same rows, f32 query,
    no query quantization), used by the degraded/CPU ANN path so host
    and device descents walk the same landscape. Returns
    (score_fn, probe_fn)."""
    qs32 = np.ascontiguousarray(qs, np.float32)
    x8, arow, x2q = ann.x8, ann.arow, ann.x2
    metric = ann.metric

    def fn(ids):
        rows = x8[ids].astype(np.float32)              # [B, C, D]
        dots = np.einsum("bcd,bd->bc", rows, qs32) * arow[ids]
        if metric == "euclidean":
            return x2q[ids] - 2.0 * dots
        return -dots  # cosine quantized pre-normalized rows; dot raw

    def probe(ids):
        rows = x8[ids].astype(np.float32)              # [P, D]
        dots = (qs32 @ rows.T) * arow[ids][None, :]    # [B, P]
        if metric == "euclidean":
            return x2q[ids][None, :] - 2.0 * dots
        return -dots

    return fn, probe


# ---------------------------------------------------------------------------
# persisted build artifacts
# ---------------------------------------------------------------------------
# The ~300 s 1M×768 build is pure recomputation of state already implied
# by the KV rows, so it persists to the datastore dir and a restart
# reloads in seconds. On-disk format follows the WAL's `SKVCRC01` frame
# idiom (kvs/remote.py): an 8-byte magic, then `u32 len | u32 crc32 |
# body` frames — one JSON header frame, then one frame per array. Any
# mismatch (magic, torn frame, crc) raises ValueError and the caller
# warns + rebuilds: a corrupt snapshot is never served.

_SNAP_MAGIC = b"SKVANN01"
_SNAP_ARRAYS = ("graph", "x8", "arow", "x2", "inv_norms")


def _write_frame(f, body: bytes):
    import struct
    import zlib

    f.write(struct.pack(">I", len(body)))
    f.write(struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF))
    f.write(body)


def _read_frame(f) -> bytes:
    import struct
    import zlib

    hdr = f.read(8)
    if len(hdr) != 8:
        raise ValueError("ann snapshot: truncated frame header")
    (n,) = struct.unpack(">I", hdr[:4])
    (crc,) = struct.unpack(">I", hdr[4:])
    body = f.read(n)
    if len(body) != n:
        raise ValueError("ann snapshot: torn frame")
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ValueError("ann snapshot: crc mismatch")
    return body


def save_index(ann: "AnnIndex", path: str, extra: dict = None):
    """Persist a built index atomically (tmp + rename). `extra` lands in
    the header frame — the serving side stamps the row-identity digest
    there so a reload can prove the row NUMBERING still matches."""
    import json
    import os

    meta = {
        "metric": ann.metric,
        "built_n": ann.built_n,
        "built_version": ann.built_version,
        "built_epoch": ann.built_epoch,
        "build_s": ann.build_s,
        "arrays": {
            name: [getattr(ann, name).dtype.str,
                   list(getattr(ann, name).shape)]
            for name in _SNAP_ARRAYS
        },
    }
    if extra:
        meta.update(extra)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(_SNAP_MAGIC)
            _write_frame(f, json.dumps(meta, sort_keys=True).encode())
            for name in _SNAP_ARRAYS:
                _write_frame(
                    f, np.ascontiguousarray(getattr(ann, name)).tobytes()
                )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load_index(path: str) -> tuple["AnnIndex", dict]:
    """Load a persisted index -> (AnnIndex, header meta). Raises
    OSError when absent/unreadable and ValueError on any corruption —
    the caller decides between silence (no snapshot) and warn+rebuild
    (corrupt snapshot)."""
    import json

    with open(path, "rb") as f:
        if f.read(len(_SNAP_MAGIC)) != _SNAP_MAGIC:
            raise ValueError("ann snapshot: bad magic")
        meta = json.loads(_read_frame(f).decode())
        arrays = {}
        for name in _SNAP_ARRAYS:
            try:
                dt, shape = meta["arrays"][name]
            except (KeyError, TypeError, ValueError):
                raise ValueError(f"ann snapshot: header missing {name}")
            body = _read_frame(f)
            arr = np.frombuffer(body, dtype=np.dtype(dt))
            want = 1
            for s in shape:
                want *= int(s)
            if arr.size != want:
                raise ValueError(f"ann snapshot: {name} size mismatch")
            arrays[name] = arr.reshape([int(s) for s in shape])
    return AnnIndex(
        meta["metric"], arrays["graph"], arrays["x8"], arrays["arow"],
        arrays["x2"], arrays["inv_norms"], meta["built_n"],
        meta["built_version"], meta["built_epoch"],
        meta.get("build_s", 0.0),
    ), meta
