"""Full-text search: analyzers + BM25 postings (reference: core/src/idx/ft/
fulltext.rs Bm25Params/Scorer, analyzer/ tokenizers+filters).

Postings live in KV under index-state keys: per-term doc maps with term
frequencies and offsets; doc lengths and corpus stats alongside. BM25 at
query time; hybrid rerank composes with the vector engine via search::rrf.
"""

from __future__ import annotations

import math
import re as _re

from surrealdb_tpu import key as K
from surrealdb_tpu.catalog import AnalyzerDef
from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import NONE, RecordId, hashable, is_truthy

# ---------------------------------------------------------------------------
# analyzers
# ---------------------------------------------------------------------------

_CAMEL_RX = _re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _tokenize(text: str, tokenizers: list) -> list[tuple[str, int, int]]:
    """Returns (token, start, end) triples."""
    if not tokenizers:
        tokenizers = ["blank"]
    spans = [(text, 0)]
    for tk in tokenizers:
        out = []
        for s, base in spans:
            if tk == "blank":
                for m in _re.finditer(r"\S+", s):
                    out.append((m.group(), base + m.start()))
            elif tk == "punct":
                for m in _re.finditer(r"[^\s\W]+|\w+", s):
                    out.append((m.group(), base + m.start()))
            elif tk == "class":
                for m in _re.finditer(r"[a-zA-Z]+|\d+|[^\w\s]+", s):
                    out.append((m.group(), base + m.start()))
            elif tk == "camel":
                pos = 0
                for part in _CAMEL_RX.split(s):
                    idx = s.find(part, pos)
                    out.append((part, base + idx))
                    pos = idx + len(part)
            else:
                out.append((s, base))
        spans = [(t, p) for t, p in out]
    return [(t, p, p + len(t)) for t, p in spans]


_STOP_SUFFIXES = [
    "ational", "tional", "iveness", "fulness", "ousness", "ization", "ement",
    "ments", "ment", "ings", "ing", "edly", "ed", "ies", "ly", "es", "s",
]


def _stem(word: str) -> str:
    """Lightweight english stemmer (snowball-lite)."""
    if len(word) <= 3:
        return word
    for suf in _STOP_SUFFIXES:
        if word.endswith(suf) and len(word) - len(suf) >= 3:
            return word[: -len(suf)]
    return word


def _apply_filters(tokens, filters):
    out = tokens
    for f in filters:
        name = f[0]
        nxt = []
        if name == "lowercase":
            nxt = [(t.lower(), a, b) for t, a, b in out]
        elif name == "uppercase":
            nxt = [(t.upper(), a, b) for t, a, b in out]
        elif name == "ascii":
            import unicodedata

            nxt = [
                (
                    unicodedata.normalize("NFKD", t)
                    .encode("ascii", "ignore")
                    .decode(),
                    a,
                    b,
                )
                for t, a, b in out
            ]
        elif name == "snowball":
            nxt = [(_stem(t.lower()), a, b) for t, a, b in out]
        elif name == "edgengram":
            lo, hi = int(f[1]), int(f[2])
            for t, a, b in out:
                for n in range(lo, min(hi, len(t)) + 1):
                    nxt.append((t[:n], a, b))
        elif name == "ngram":
            lo, hi = int(f[1]), int(f[2])
            for t, a, b in out:
                for n in range(lo, hi + 1):
                    for i in range(0, max(len(t) - n + 1, 0)):
                        nxt.append((t[i : i + n], a, b))
        else:
            nxt = out
        out = nxt
    return out


def get_analyzer(name, ctx) -> AnalyzerDef:
    if name is None:
        return AnalyzerDef("like", ["blank"], [("lowercase",)])
    ns, db = ctx.need_ns_db()
    az = ctx.txn.get_val(K.az_def(ns, db, name))
    if az is None:
        raise SdbError(f"The analyzer '{name}' does not exist")
    return az


def analyze(az: AnalyzerDef, text: str):
    return _apply_filters(_tokenize(text, az.tokenizers), az.filters)


def analyze_text(az_name, text, ctx):
    az = get_analyzer(az_name, ctx)
    return [t for t, _a, _b in analyze(az, text)]


# ---------------------------------------------------------------------------
# index maintenance
# ---------------------------------------------------------------------------


def _doc_terms(idef, doc, ctx, rid):
    from surrealdb_tpu.exec.eval import evaluate

    az = get_analyzer(idef.fulltext.get("analyzer"), ctx)
    c = ctx.with_doc(doc, rid)
    terms: dict = {}
    length = 0
    for col in idef.cols:
        v = evaluate(col, c)
        texts = []
        if isinstance(v, str):
            texts = [v]
        elif isinstance(v, list):
            texts = [x for x in v if isinstance(x, str)]
        for text in texts:
            for t, a, b in analyze(az, text):
                if not t:
                    continue
                length += 1
                tf, offs = terms.get(t, (0, []))
                terms[t] = (tf + 1, offs + [(a, b)])
    return terms, length


def _post_key(ns, db, tb, ix, term):
    return K.ix_state(ns, db, tb, ix, b"bf", K.enc_str(term))


def _len_key(ns, db, tb, ix, rid_id):
    return K.ix_state(ns, db, tb, ix, b"bl", K.enc_value(rid_id))


def _stats_key(ns, db, tb, ix):
    return K.ix_state(ns, db, tb, ix, b"bs")


def fulltext_index_update(idef, rid: RecordId, before, after, ctx):
    ns, db = ctx.need_ns_db()
    tb = rid.tb
    ix = idef.name
    ridk = K.enc_value(rid.id)
    old_terms = {}
    if isinstance(before, dict):
        old_terms, old_len = _doc_terms(idef, before, ctx, rid)
    new_terms, new_len = ({}, 0)
    if isinstance(after, dict):
        new_terms, new_len = _doc_terms(idef, after, ctx, rid)
    stats = ctx.txn.get_val(_stats_key(ns, db, tb, ix)) or {
        "docs": 0,
        "total_len": 0,
    }
    had = ctx.txn.get_val(_len_key(ns, db, tb, ix, rid.id))
    if had is not None:
        stats["docs"] -= 1
        stats["total_len"] -= had
        ctx.txn.delete(_len_key(ns, db, tb, ix, rid.id))
    for t in old_terms:
        pk = _post_key(ns, db, tb, ix, t)
        post = ctx.txn.get_val(pk) or {}
        post.pop(ridk, None)
        if post:
            ctx.txn.set_val(pk, post)
        else:
            ctx.txn.delete(pk)
    if new_terms:
        for t, (tf, offs) in new_terms.items():
            pk = _post_key(ns, db, tb, ix, t)
            post = ctx.txn.get_val(pk) or {}
            post[ridk] = (tf, offs, rid.id)
            ctx.txn.set_val(pk, post)
        ctx.txn.set_val(_len_key(ns, db, tb, ix, rid.id), new_len)
        stats["docs"] += 1
        stats["total_len"] += new_len
    ctx.txn.set_val(_stats_key(ns, db, tb, ix), stats)


# ---------------------------------------------------------------------------
# search (BM25)
# ---------------------------------------------------------------------------


def ft_search(idef, query: str, ctx):
    """Returns ordered [(rid, score)] plus per-term match offsets."""
    ns, db = ctx.need_ns_db()
    tb, ix = idef.tb, idef.name
    az = get_analyzer(idef.fulltext.get("analyzer"), ctx)
    terms = [t for t, _a, _b in analyze(az, query) if t]
    if not terms:
        return [], {}
    k1, b = idef.fulltext.get("bm25", (1.2, 0.75))
    stats = ctx.txn.get_val(_stats_key(ns, db, tb, ix)) or {
        "docs": 0,
        "total_len": 0,
    }
    n_docs = max(stats["docs"], 1)
    avg_len = stats["total_len"] / n_docs if n_docs else 1.0
    scores: dict = {}
    rids: dict = {}
    offsets: dict = {}
    matched_all: dict = {}
    for t in dict.fromkeys(terms):
        post = ctx.txn.get_val(_post_key(ns, db, tb, ix, t)) or {}
        df = len(post)
        if df == 0:
            continue
        idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
        for ridk, (tf, offs, rid_id) in post.items():
            dl = ctx.txn.get_val(_len_key(ns, db, tb, ix, rid_id)) or 1
            denom = tf + k1 * (1 - b + b * dl / max(avg_len, 1e-9))
            s = idf * tf * (k1 + 1) / max(denom, 1e-9)
            scores[ridk] = scores.get(ridk, 0.0) + s
            rids[ridk] = RecordId(tb, rid_id)
            offsets.setdefault(ridk, []).extend(offs)
            matched_all.setdefault(ridk, set()).add(t)
    want = set(dict.fromkeys(terms))
    # AND semantics: docs must match every query term (reference MATCHES)
    hits = [
        (rids[rk], sc)
        for rk, sc in scores.items()
        if matched_all.get(rk) == want
    ]
    if not hits:
        # fall back to OR ranking when no doc has all terms? reference
        # returns only full matches — keep strict AND.
        pass
    hits.sort(key=lambda p: -p[1])
    return hits, offsets


def plan_matches(tb, cond, mt, indexes, ctx, stmt):
    """Planner entry for `field @@ query` — index scan + score context."""
    from surrealdb_tpu.exec.eval import evaluate, fetch_record
    from surrealdb_tpu.exec.statements import Source
    from surrealdb_tpu.idx.planner import _field_path, _remove_node
    from surrealdb_tpu.val import is_truthy

    path = _field_path(mt.lhs)
    idef = None
    for d in indexes:
        if d.fulltext is not None and d.cols_str and (
            path is None or d.cols_str[0] == path
        ):
            idef = d
            break
    if idef is None:
        raise SdbError(
            "Unable to perform the MATCHES operator without a full-text index"
        )
    q = evaluate(mt.rhs, ctx)
    hits, offsets = ft_search(idef, str(q), ctx)
    rest = _remove_node(cond, mt)
    ctx.vars["__ft_scores__"] = {hashable(r): s for r, s in hits}
    ctx.vars["__ft_offsets__"] = offsets
    ctx.vars["__ft_index__"] = idef
    ctx.vars["__ft_query__"] = str(q)
    ctx._cond_consumed = rest is None

    def gen():
        for rid, _score in hits:
            doc = fetch_record(ctx, rid)
            if doc is NONE:
                continue
            if rest is not None:
                c = ctx.with_doc(doc, rid)
                if not is_truthy(evaluate(rest, c)):
                    continue
            yield Source(rid=rid, doc=doc)

    # mark consumed either way: rest applied inside the generator
    ctx._cond_consumed = True
    return gen()


def matches_operator(n, ctx):
    """Row-wise @@ evaluation (post-planner membership, or ad-hoc)."""
    scores = ctx.vars.get("__ft_scores__")
    if scores is not None and ctx.doc_id is not None:
        return hashable(ctx.doc_id) in scores
    # ad-hoc: analyze both sides with the default analyzer
    from surrealdb_tpu.exec.eval import evaluate

    lhs = evaluate(n.lhs, ctx)
    rhs = evaluate(n.rhs, ctx)
    if not isinstance(lhs, str) or not isinstance(rhs, str):
        return False
    az = AnalyzerDef("like", ["blank"], [("lowercase",)])
    doc_terms = {t for t, _a, _b in analyze(az, lhs)}
    q_terms = {t for t, _a, _b in analyze(az, rhs)}
    return bool(q_terms) and q_terms <= doc_terms


def search_score(ref, ctx):
    scores = ctx.vars.get("__ft_scores__")
    if scores is None or ctx.doc_id is None:
        return NONE
    return scores.get(hashable(ctx.doc_id), NONE)


def search_highlight(args, ctx):
    """search::highlight(open, close, ref) — wrap matched terms."""
    if len(args) < 3:
        raise SdbError("Incorrect arguments for function search::highlight()")
    open_t, close_t = str(args[0]), str(args[1])
    idef = ctx.vars.get("__ft_index__")
    offsets = ctx.vars.get("__ft_offsets__")
    if idef is None or ctx.doc_id is None or ctx.doc is None:
        return NONE
    from surrealdb_tpu import key as K2
    from surrealdb_tpu.exec.eval import evaluate

    ridk = K2.enc_value(ctx.doc_id.id)
    offs = sorted(set((a, b) for a, b in (offsets or {}).get(ridk, [])))
    c = ctx.with_doc(ctx.doc, ctx.doc_id)
    text = evaluate(idef.cols[0], c)
    if not isinstance(text, str):
        return text
    out = []
    last = 0
    for a, b in offs:
        if a < last or b > len(text):
            continue
        out.append(text[last:a])
        out.append(open_t + text[a:b] + close_t)
        last = b
    out.append(text[last:])
    return "".join(out)


def search_offsets(args, ctx):
    offsets = ctx.vars.get("__ft_offsets__")
    if offsets is None or ctx.doc_id is None:
        return NONE
    from surrealdb_tpu import key as K2

    ridk = K2.enc_value(ctx.doc_id.id)
    offs = sorted(set((a, b) for a, b in (offsets or {}).get(ridk, [])))
    return {"0": [{"e": b, "s": a} for a, b in offs]}
