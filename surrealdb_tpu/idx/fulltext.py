"""Full-text search: analyzers + BM25 postings (reference: core/src/idx/ft/
fulltext.rs Bm25Params/Scorer, analyzer/ tokenizers+filters).

Postings live in KV under index-state keys: per-term doc maps with term
frequencies and offsets; doc lengths and corpus stats alongside. BM25 at
query time; hybrid rerank composes with the vector engine via search::rrf.
"""

from __future__ import annotations

import math
import re as _re
import time

from surrealdb_tpu import key as K
from surrealdb_tpu.catalog import AnalyzerDef
from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import NONE, RecordId, hashable, is_truthy

# ---------------------------------------------------------------------------
# analyzers
# ---------------------------------------------------------------------------

_CAMEL_RX = _re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _tokenize(text: str, tokenizers: list) -> list[tuple[str, int, int]]:
    """Returns (token, start, end) triples."""
    if not tokenizers:
        tokenizers = ["blank"]
    spans = [(text, 0)]
    for tk in tokenizers:
        out = []
        for s, base in spans:
            if tk == "blank":
                for m in _re.finditer(r"\S+", s):
                    out.append((m.group(), base + m.start()))
            elif tk == "punct":
                # punctuation chars are tokens of their own (they count
                # toward BM25 doc length, like the reference tokenizer)
                for m in _re.finditer(r"\w+|[^\w\s]", s):
                    out.append((m.group(), base + m.start()))
            elif tk == "class":
                # split on unicode character-class changes (letter/digit/other)
                cur = []
                cstart = 0

                def _cls(ch):
                    if ch.isalpha():
                        return "a"
                    if ch.isdigit():
                        return "d"
                    if ch.isspace():
                        return "s"
                    return "p"

                prev = None
                for ci, ch in enumerate(s):
                    c = _cls(ch)
                    if c != prev and cur:
                        if prev != "s":
                            out.append(("".join(cur), base + cstart))
                        cur = []
                    if c != prev:
                        cstart = ci
                    prev = c
                    cur.append(ch)
                if cur and prev != "s":
                    out.append(("".join(cur), base + cstart))
            elif tk == "camel":
                pos = 0
                for part in _CAMEL_RX.split(s):
                    idx = s.find(part, pos)
                    out.append((part, base + idx))
                    pos = idx + len(part)
            else:
                out.append((s, base))
        spans = [(t, p) for t, p in out]
    return [(t, p, p + len(t), p, p + len(t)) for t, p in spans]


_STOP_SUFFIXES = [
    "ational", "tional", "iveness", "fulness", "ousness", "ization", "ement",
    "ments", "ment", "ings", "ing", "edly", "ed", "ies", "ly", "es", "s",
]


def _stem(word: str) -> str:
    """Lightweight english stemmer (snowball-lite)."""
    if len(word) <= 3:
        return word
    for suf in _STOP_SUFFIXES:
        if word.endswith(suf) and len(word) - len(suf) >= 3:
            return word[: -len(suf)]
    return word


def _apply_filters(tokens, filters, stage="index"):
    out = tokens
    for f in filters:
        name = f[0]
        # ngram family generates index-time grams only; query text keeps
        # its whole tokens (reference filter.rs is_stage FilteringStage)
        if stage == "query" and name in ("ngram", "edgengram"):
            continue
        nxt = []
        if name == "lowercase":
            nxt = [(t.lower(), a, b, oa, ob) for t, a, b, oa, ob in out]
        elif name == "uppercase":
            nxt = [(t.upper(), a, b, oa, ob) for t, a, b, oa, ob in out]
        elif name == "ascii":
            import unicodedata

            nxt = [
                (
                    unicodedata.normalize("NFKD", t)
                    .encode("ascii", "ignore")
                    .decode(),
                    a,
                    b,
                    oa,
                    ob,
                )
                for t, a, b, oa, ob in out
            ]
        elif name == "snowball":
            nxt = [(_stem(t.lower()), a, b, oa, ob) for t, a, b, oa, ob in out]
        elif name == "edgengram":
            lo, hi = int(f[1]), int(f[2])
            for t, a, b, oa, ob in out:
                for n in range(lo, min(hi, len(t)) + 1):
                    nxt.append((t[:n], a, a + n, oa, ob))
        elif name == "ngram":
            lo, hi = int(f[1]), int(f[2])
            for t, a, b, oa, ob in out:
                for n in range(lo, hi + 1):
                    for i in range(0, max(len(t) - n + 1, 0)):
                        nxt.append((t[i : i + n], a + i, a + i + n, oa, ob))
        else:
            nxt = out
        out = nxt
    return out


def get_analyzer(name, ctx) -> AnalyzerDef:
    if name is None:
        return AnalyzerDef("like", ["blank"], [("lowercase",)])
    ns, db = ctx.need_ns_db()
    az = ctx.txn.get_val(K.az_def(ns, db, name))
    if az is None:
        raise SdbError(f"The analyzer '{name}' does not exist")
    return az


def analyze(az: AnalyzerDef, text: str, ctx=None, stage="index"):
    # FUNCTION analyzers preprocess the text through a custom function
    # that must return a string (reference ft/analyzer mapper)
    if getattr(az, "function", None) and ctx is not None:
        from surrealdb_tpu.fnc import call_custom

        name = az.function
        if name.startswith("fn::"):
            name = name[4:]
        out = call_custom(name, [text], ctx)
        if not isinstance(out, str):
            from surrealdb_tpu.err import SdbError

            raise SdbError(
                f"There was a problem running the {name}() function. "
                f"The function should return a string."
            )
        text = out
    return _apply_filters(_tokenize(text, az.tokenizers), az.filters, stage)


def analyze_text(az_name, text, ctx):
    az = get_analyzer(az_name, ctx)
    return [tok[0] for tok in analyze(az, text, ctx)]


# ---------------------------------------------------------------------------
# index maintenance
# ---------------------------------------------------------------------------


def _flatten_strings(v):
    """All strings in a value, depth-first; objects iterate in sorted key
    order (the reference's Object is a BTreeMap, so the analyzer visits
    nested strings lexicographically by key)."""
    if isinstance(v, str):
        return [v]
    out = []
    if isinstance(v, list):
        for x in v:
            out.extend(_flatten_strings(x))
    elif isinstance(v, dict):
        for k in sorted(v):
            out.extend(_flatten_strings(v[k]))
    return out


def _doc_terms(idef, doc, ctx, rid):
    from surrealdb_tpu.exec.eval import evaluate

    az = get_analyzer(idef.fulltext.get("analyzer"), ctx)
    c = ctx.with_doc(doc, rid)
    terms: dict = {}
    length = 0
    for col in idef.cols:
        v = evaluate(col, c)
        texts = _flatten_strings(v)
        for vi, text in enumerate(texts):
            for t, a, b, oa, ob in analyze(az, text):
                if not t:
                    continue
                length += 1
                tf, offs = terms.get(t, (0, []))
                terms[t] = (tf + 1, offs + [(vi, a, b, oa, ob)])
    return terms, length


def _post_key(ns, db, tb, ix, term):
    return K.ix_state(ns, db, tb, ix, b"bf", K.enc_str(term))


def _len_key(ns, db, tb, ix, rid_id):
    return K.ix_state(ns, db, tb, ix, b"bl", K.enc_value(rid_id))


def _stats_key(ns, db, tb, ix):
    return K.ix_state(ns, db, tb, ix, b"bs")


def _ver_key(ns, db, tb, ix):
    # monotone write counter: the search-result cache's invalidation
    # token (read through the caller's txn, so an uncommitted write in
    # the SAME txn already misses the cache)
    return K.ix_state(ns, db, tb, ix, b"bv")


def fulltext_index_update(idef, rid: RecordId, before, after, ctx):
    ns, db = ctx.need_ns_db()
    tb = rid.tb
    ix = idef.name
    ridk = K.enc_value(rid.id)
    old_terms = {}
    if isinstance(before, dict):
        old_terms, old_len = _doc_terms(idef, before, ctx, rid)
    new_terms, new_len = ({}, 0)
    if isinstance(after, dict):
        new_terms, new_len = _doc_terms(idef, after, ctx, rid)
    stats = ctx.txn.get_val(_stats_key(ns, db, tb, ix)) or {
        "docs": 0,
        "total_len": 0,
    }
    had = ctx.txn.get_val(_len_key(ns, db, tb, ix, rid.id))
    if had is not None:
        stats["docs"] -= 1
        stats["total_len"] -= had
        ctx.txn.delete(_len_key(ns, db, tb, ix, rid.id))
    for t in old_terms:
        pk = _post_key(ns, db, tb, ix, t)
        post = ctx.txn.get_val(pk) or {}
        post.pop(ridk, None)
        if post:
            ctx.txn.set_val(pk, post)
        else:
            ctx.txn.delete(pk)
    if new_terms:
        for t, (tf, offs) in new_terms.items():
            pk = _post_key(ns, db, tb, ix, t)
            post = ctx.txn.get_val(pk) or {}
            post[ridk] = (tf, offs, rid.id)
            ctx.txn.set_val(pk, post)
        ctx.txn.set_val(_len_key(ns, db, tb, ix, rid.id), new_len)
        stats["docs"] += 1
        stats["total_len"] += new_len
    ctx.txn.set_val(_stats_key(ns, db, tb, ix), stats)
    cur = ctx.txn.get_val(_ver_key(ns, db, tb, ix))
    if cur is None:
        # generation base, not 0: REMOVE INDEX + DEFINE INDEX wipes this
        # key, and a plain counter could climb back to a previously
        # cached value — a wall-clock base makes versions from different
        # index generations disjoint, on every node that shares the KV
        cur = time.time_ns()
    ctx.txn.set_val(_ver_key(ns, db, tb, ix), cur + 1)


# ---------------------------------------------------------------------------
# search (BM25)
# ---------------------------------------------------------------------------


class FtResult:
    """One search's shared, read-only result: hits/offsets plus lazily
    derived lookup structures (score map, rid map, ordered rid list)
    that the match planner and the score pseudo-functions reuse —
    consumers MUST NOT mutate any of these."""

    __slots__ = ("hits", "offsets", "_scores", "_rid_map", "_ordered")

    def __init__(self, hits, offsets):
        self.hits = hits
        self.offsets = offsets
        self._scores = None
        self._rid_map = None
        self._ordered = None

    @property
    def scores(self) -> dict:
        s = self._scores
        if s is None:
            s = self._scores = {hashable(r): sc for r, sc in self.hits}
        return s

    @property
    def rid_map(self) -> dict:
        m = self._rid_map
        if m is None:
            m = self._rid_map = {hashable(r): r for r, _s in self.hits}
        return m

    @property
    def ordered(self) -> list:
        o = self._ordered
        if o is None:
            o = self._ordered = [r for r, _s in self.hits]
        return o

    def cost_bytes(self) -> int:
        """Cheap cache-cost estimate (no object-graph traversal): each
        hit carries a rid + score + map slots across the three derived
        views; each offset tuple is a handful of small ints."""
        n_offs = sum(len(v) for v in self.offsets.values()) \
            if self.offsets else 0
        return 256 + 160 * len(self.hits) + 96 * n_offs


def _txn_wrote(txn, key: bytes) -> bool:
    """Whether this transaction's OWN write set touches `key`.

    Every FT index mutation writes the `bv` version key in the same
    call that writes the postings (fulltext_index_update), so an
    untouched `bv` proves the txn's view of this index is the
    committed snapshot — safe to share through the datastore cache. An
    engine whose write buffer we cannot see answers True
    (conservative: never populate from an unknowable view)."""
    btx = getattr(txn, "btx", None)
    w = getattr(btx, "writes", None)
    if w is not None:
        return key in w
    subs = getattr(btx, "_subs", None)  # ShardTx: per-shard buffers
    if subs is not None:
        try:
            return any(key in sub.writes for sub in subs.values())
        except AttributeError:
            return True
    return True


def ft_result(idef, query: str, ctx, boolean: str = "AND") -> FtResult:
    """The memoized search. Two levels: per statement
    (ctx.record_cache) — the planner's match-context registration, the
    access-path analysis, and the scan itself all ask for the same
    search, one execution serves all three; and per datastore, keyed by
    the index's write-version counter plus the index definition's
    scoring fingerprint — repeated identical queries (the hybrid-RRF
    serving shape) skip the posting walk entirely until the next index
    write."""
    ck = ("__ft__", idef.tb, idef.name, query, boolean)
    hit = ctx.record_cache.get(ck)
    if hit is not None:
        return hit
    ns, db = ctx.need_ns_db()
    tb, ix = idef.tb, idef.name
    ver = ctx.txn.get_val(_ver_key(ns, db, tb, ix)) or 0
    cache = getattr(ctx.ds, "_ft_cache", None)
    if cache is None:
        # bounded LRU (entry count + byte cap): on a hot mixed
        # read/write table every write bumps `bv`, so an unbounded map
        # keyed by (query, version) grows one dead entry per write
        # forever. Normally created (and registered with the memory
        # accountant) by Datastore.__init__; this is the duck-typed-ds
        # fallback.
        from surrealdb_tpu.resource import BudgetedLRU

        from surrealdb_tpu import cnf as _cnf

        cache = ctx.ds._ft_cache = BudgetedLRU(
            _cnf.FT_CACHE_ENTRIES, _cnf.FT_CACHE_BYTES
        )
    ftp = idef.fulltext or {}
    # fingerprint the analyzer DEFINITION, not its name: DEFINE
    # ANALYZER ... OVERWRITE changes tokenization without touching the
    # index write-version, and a name-keyed entry would serve the old
    # generation's hits
    az = get_analyzer(ftp.get("analyzer"), ctx)
    az_fp = (tuple(az.tokenizers or ()),
             tuple(tuple(f) if isinstance(f, (list, tuple)) else f
                   for f in (az.filters or ())),
             az.function)
    fp = (az_fp, tuple(ftp.get("bm25") or ()),
          tuple(idef.cols_str or ()))
    gk = (ns, db, tb, ix, query, boolean, fp)
    ent = cache.get(gk)
    if ent is not None and ent[0] == ver:
        res = ent[1]
    else:
        res = FtResult(*_ft_search_impl(idef, query, ctx, boolean))
        # never populate an UNCOMMITTED view: a write txn that touched
        # this index read `ver` from its own write set — a version it
        # might never commit, which a later committed writer could
        # alias. A write txn that did NOT touch the index saw exactly
        # the committed snapshot at `ver` (every index mutation bumps
        # `bv` in the same call as its postings), so its result is as
        # shareable as a read txn's — which matters, because the
        # embedded executor runs every statement in a write txn.
        if not getattr(ctx.txn, "write", False) \
                or not _txn_wrote(ctx.txn, _ver_key(ns, db, tb, ix)):
            cache.put(gk, (ver, res), cost=res.cost_bytes())
    ctx.record_cache[ck] = res
    return res


def ft_search(idef, query: str, ctx, boolean: str = "AND"):
    """Compatibility surface: ordered [(rid, score)] + match offsets."""
    res = ft_result(idef, query, ctx, boolean)
    return res.hits, res.offsets


def _doc_lengths(ctx, ns, db, tb, ix) -> dict:
    """enc(rid_id) -> BM25 doc length for the whole index, loaded with
    ONE prefix scan and memoized per statement (ctx.record_cache). The
    old per-(term, doc) `get_val` pattern dominated hybrid-query
    latency: a 300-match posting paid 300 key encodes + tree lookups
    per query."""
    ck = ("__ftdl__", tb, ix)
    hit = ctx.record_cache.get(ck)
    if hit is not None:
        return hit
    pre = K.ix_state(ns, db, tb, ix, b"bl")
    beg, end = K.prefix_range(pre)
    plen = len(pre)
    out = {bytes(k[plen:]): v for k, v in ctx.txn.scan_vals(beg, end)}
    ctx.record_cache[ck] = out
    return out


def _ft_search_impl(idef, query: str, ctx, boolean: str = "AND"):
    ns, db = ctx.need_ns_db()
    tb, ix = idef.tb, idef.name
    az = get_analyzer(idef.fulltext.get("analyzer"), ctx)
    terms = [tok[0] for tok in analyze(az, query, stage="query") if tok[0]]
    if not terms:
        return [], {}
    import numpy as _np

    k1, b = idef.fulltext.get("bm25", (1.2, 0.75))
    k1, b = float(_np.float32(k1)), float(_np.float32(b))
    stats = ctx.txn.get_val(_stats_key(ns, db, tb, ix)) or {
        "docs": 0,
        "total_len": 0,
    }
    n_docs = max(stats["docs"], 1)
    avg_len = stats["total_len"] / n_docs if n_docs else 1.0
    # peek: the posting maps are read-only here, and the fresh-copy
    # contract of get_val costs a full copy of every entry per query
    posts = {
        t: ctx.txn.peek_val(_post_key(ns, db, tb, ix, t)) or {}
        for t in dict.fromkeys(terms)
    }
    total_matches = sum(len(p) for p in posts.values())
    if total_matches >= 512 or total_matches * 8 >= n_docs:
        # broad result set: ONE prefix scan of the doc-length keyspace
        # amortizes across the matches
        dls = _doc_lengths(ctx, ns, db, tb, ix)

        def dl_get(ridk, rid_id):
            return dls.get(ridk) or 0
    else:
        # selective query (rare terms on a big index): O(matches)
        # point reads beat an O(n_docs) scan
        _dl_memo: dict = {}

        def dl_get(ridk, rid_id):
            v = _dl_memo.get(ridk)
            if v is None:
                v = _dl_memo[ridk] = (
                    ctx.txn.get_val(_len_key(ns, db, tb, ix, rid_id))
                    or 0
                )
            return v

    scores: dict = {}
    rids: dict = {}
    offsets: dict = {}
    matched_all: dict = {}
    for t, post in posts.items():
        df = len(post)
        if df == 0:
            continue
        # reference scorer (ft/fulltext.rs compute_bm25_score): clamped idf,
        # lower-bounded tf' = 1 + ln(tf)
        idf = max(math.log((n_docs - df + 0.5) / (df + 0.5)), 0.0)
        for ridk, (tf, offs, rid_id) in post.items():
            dl = dl_get(ridk, rid_id)
            if idf == 0.0 or tf <= 0:
                s = 0.0
            else:
                tf_prime = 1.0 + math.log(tf)
                length_norm = (1 - b) + (b / max(avg_len, 1e-9)) * dl
                s = idf * (k1 + 1) * tf_prime / (tf_prime + k1 * length_norm)
            scores[ridk] = scores.get(ridk, 0.0) + s
            rids[ridk] = RecordId(tb, rid_id)
            offsets.setdefault(ridk, []).extend(offs)
            matched_all.setdefault(ridk, set()).add(t)
    want = set(dict.fromkeys(terms))
    if boolean == "OR":
        hits = [(rids[rk], sc) for rk, sc in scores.items()]
    else:
        # AND semantics: docs must match every query term (reference MATCHES)
        hits = [
            (rids[rk], sc)
            for rk, sc in scores.items()
            if matched_all.get(rk) == want
        ]
    hits = [(r, float(_np.float32(sc))) for r, sc in hits]
    hits.sort(key=lambda p: -p[1])
    return hits, offsets


def plan_matches(tb, cond, mts, indexes, ctx, stmt):
    """Planner entry for one or more `field @ref@ query` predicates: each
    resolves to a full-text index; results intersect (AND across
    predicates); per-ref score/offset contexts feed search::score etc."""
    from surrealdb_tpu.exec.eval import evaluate, fetch_record
    from surrealdb_tpu.exec.statements import Source
    from surrealdb_tpu.idx.planner import _field_path, _remove_node
    from surrealdb_tpu.val import is_truthy

    # rebind a fresh dict: children share vars-dict values by reference, so
    # mutating in place would leak subquery match contexts into the parent
    ft_ctx = dict(ctx.vars.get("__ft__") or {})
    ctx.vars["__ft__"] = ft_ctx
    seen_refs = set()
    results = []
    rest = cond
    for mt in mts:
        path = _field_path(mt.lhs)
        idef = None
        for d in indexes:
            if d.fulltext is not None and d.cols_str and (
                path is None or d.cols_str[0] == path
            ):
                idef = d
                break
        if idef is None:
            raise SdbError(
                "Unable to perform the MATCHES operator without a full-text index"
            )
        q = evaluate(mt.rhs, ctx)
        pre = (ctx.vars.get("__ft__") or {}).get(("node", id(mt)))
        if pre is not None and pre["idef"].name == idef.name \
                and pre["query"] == str(q) and pre.get("res") is not None:
            # plan_scan pre-registered this node's search (planner
            # _register_match_contexts) — reuse instead of re-searching
            res = pre["res"]
        else:
            res = ft_result(idef, str(q), ctx, boolean=mt.boolean)
        ref = mt.ref if mt.ref is not None else 0
        if ref in seen_refs:
            raise SdbError(f"Duplicated Match reference: {ref}")
        seen_refs.add(ref)
        ft_ctx[ref] = {
            "scores": res.scores,
            "offsets": res.offsets,
            "idef": idef,
            "query": str(q),
            "res": res,
        }
        results.append(res)
        rest = _remove_node(rest, mt)
    if len(results) == 1:
        # the common case pays zero set/dict building: the shared
        # result's ordered rid list IS the scan order (score-desc)
        ordered = results[0].ordered
    else:
        common = None
        for res in results:
            common = (set(res.scores.keys()) if common is None
                      else common & res.scores.keys())
        ordered = []
        seen = set()
        # h ∈ common ⇒ present in every current result, so rid objects
        # always resolve through the first result's map
        rid_map = results[0].rid_map
        # node-keyed tuple entries are aliases for filter evaluation;
        # the ordered result union walks the numeric ref entries only
        for ref in sorted(k for k in ft_ctx if isinstance(k, int)):
            entry = ft_ctx[ref]
            for h in entry["scores"]:
                if h in common and h not in seen:
                    seen.add(h)
                    ordered.append(rid_map[h])

    if rest is None and _score_only_projection(stmt, ctx):
        # projection (and ORDER BY) touch only `id` + search::* pseudo-
        # functions, which read the match context, not the document:
        # skip the per-row record fetch entirely (keys-only FT scan —
        # the dominant host cost of the hybrid RRF shape, where a
        # 300-match leg paid 300 record fetches per query)
        lim = _ft_order_limit(stmt, mts, ctx)
        if lim is not None:
            # ORDER BY <that score> DESC LIMIT n over a single MATCHES
            # re-sorts the order the search already produced (hits are
            # score-descending, the scores dict preserves it): truncate
            # BEFORE projection so only n rows pay the pipeline, not
            # every match. The pipeline still sorts/limits the survivors
            # (a stable no-op).
            ordered = ordered[:lim]

        def gen_keys():
            for rid in ordered:
                yield Source(rid=rid, doc={"id": rid})

        ctx._cond_consumed = True
        return gen_keys()

    def gen():
        for rid in ordered:
            doc = fetch_record(ctx, rid)
            if doc is NONE:
                continue
            if rest is not None:
                c = ctx.with_doc(doc, rid)
                if not is_truthy(evaluate(rest, c)):
                    continue
            yield Source(rid=rid, doc=doc)

    ctx._cond_consumed = True
    return gen()


def _ft_order_limit(stmt, mts, ctx):
    """LIMIT value when `ORDER BY <score> DESC LIMIT n` (no START) can
    be absorbed into the single-MATCHES scan order, else None. Valid
    only when the one ORDER key is search::score(ref) — directly or via
    its projection alias — for the statement's single match predicate:
    the scan already yields score-descending rows, so the sort is a
    stable no-op and the limit can truncate before projection."""
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.statements import expr_name
    from surrealdb_tpu.expr.ast import FunctionCall

    if (stmt is None or len(mts) != 1 or getattr(stmt, "start", None)
            is not None or getattr(stmt, "limit", None) is None):
        return None
    order = getattr(stmt, "order", None)
    if not order or order == "rand" or len(order) != 1:
        return None
    oexpr, d, collate, numeric = order[0]
    if d != "desc" or collate or numeric:
        return None
    target = oexpr
    if not isinstance(target, FunctionCall):
        # resolve a projection alias to its expression
        name = expr_name(oexpr)
        target = None
        for e, a in (stmt.exprs or []):
            if e != "*" and (a or expr_name(e)) == name:
                target = e
                break
        if stmt.value is not None and getattr(stmt, "value_alias", None) \
                == name:
            target = stmt.value
    if not (isinstance(target, FunctionCall)
            and target.name == "search::score"):
        return None
    try:
        ref = int(evaluate(target.args[0], ctx)) if target.args else 0
    except (SdbError, TypeError, ValueError, IndexError):
        return None
    if ref != (mts[0].ref if mts[0].ref is not None else 0):
        return None
    try:
        lim = evaluate(stmt.limit, ctx)
        lim = int(lim)
    except (SdbError, TypeError, ValueError):
        return None
    return lim if lim >= 0 else None


def _ft_safe_expr(expr) -> bool:
    """Projections derivable from the match context alone: `id` and the
    search::score pseudo-function (reads ctx __ft__, not the doc)."""
    from surrealdb_tpu.expr.ast import FunctionCall
    from surrealdb_tpu.idx.planner import _field_path

    if _field_path(expr) == "id":
        return True
    return isinstance(expr, FunctionCall) and expr.name == "search::score"


def _score_only_projection(stmt, ctx) -> bool:
    from surrealdb_tpu.idx.planner import _pseudo_only_projection

    return _pseudo_only_projection(stmt, ctx, _ft_safe_expr,
                                   allow_order=True)


def matches_operator(n, ctx):
    """Row-wise matches evaluation (post-planner membership, or ad-hoc)."""
    ft_ctx = ctx.vars.get("__ft__")
    ref = n.ref if n.ref is not None else 0
    if ft_ctx is not None and ctx.doc_id is not None:
        # node-keyed entries disambiguate OR-union branches that share
        # the default ref (planner _ft_branch_scan)
        entry = ft_ctx.get(("node", id(n))) or ft_ctx.get(ref)
        if entry is not None:
            return hashable(ctx.doc_id) in entry["scores"]
    # ad-hoc: analyze both sides — with the field's full-text analyzer
    # when one is defined (so an index access path that outranked the
    # MATCHES keeps the index's stemming/ngram semantics in the filter),
    # else the default blank+lowercase analyzer
    from surrealdb_tpu.exec.eval import evaluate

    lhs = evaluate(n.lhs, ctx)
    rhs = evaluate(n.rhs, ctx)
    if not isinstance(lhs, str) or not isinstance(rhs, str):
        return False
    az = None
    if ctx.doc_id is not None:
        from surrealdb_tpu.idx.planner import _field_path, get_indexes_for

        path = _field_path(n.lhs)
        try:
            for d in get_indexes_for(ctx.doc_id.tb, ctx):
                if d.fulltext is not None and d.cols_str and (
                    path is None or d.cols_str[0] == path
                ):
                    az = get_analyzer(d.fulltext.get("analyzer"), ctx)
                    break
        except Exception:
            az = None
    if az is None:
        az = AnalyzerDef("like", ["blank"], [("lowercase",)])
    doc_terms = {tok[0] for tok in analyze(az, lhs)}
    q_terms = {tok[0] for tok in analyze(az, rhs, stage="query")}
    if not q_terms:
        return False
    if getattr(n, "boolean", "AND") == "OR":
        return bool(q_terms & doc_terms)
    return q_terms <= doc_terms


def _ft_entry(ctx, ref):
    ft_ctx = ctx.vars.get("__ft__")
    if ft_ctx is None:
        return None
    return ft_ctx.get(ref if ref is not None else 0)


def search_score(ref, ctx):
    entry = _ft_entry(ctx, ref or 0)
    if entry is None or ctx.doc_id is None:
        # matched without an index scoring context: score is 0 (reference
        # select_where_matches_without_complex_query)
        return 0.0 if ctx.doc_id is not None else NONE
    return entry["scores"].get(hashable(ctx.doc_id), 0.0)


def search_highlight(args, ctx):
    """search::highlight(open, close, ref[, partial]) — wrap matched spans;
    partial=true marks the matched grams, default marks whole tokens."""
    if len(args) < 3:
        raise SdbError("Incorrect arguments for function search::highlight()")
    open_t, close_t = str(args[0]), str(args[1])
    try:
        ref = int(args[2]) if not isinstance(args[2], bool) else 0
    except (TypeError, ValueError):
        raise SdbError("Incorrect arguments for function search::highlight()")
    partial = bool(args[3]) if len(args) > 3 else False
    entry = _ft_entry(ctx, ref)
    if entry is None or ctx.doc_id is None or ctx.doc is None:
        return NONE
    from surrealdb_tpu import key as K2
    from surrealdb_tpu.exec.eval import evaluate

    idef = entry["idef"]
    ridk = K2.enc_value(ctx.doc_id.id)
    spans = _spans_by_value(entry, ridk, partial)
    c = ctx.with_doc(ctx.doc, ctx.doc_id)
    text = evaluate(idef.cols[0], c)

    def mark(t, vi):
        if not isinstance(t, str):
            return t
        out = []
        last = 0
        for a, b in spans.get(vi, []):
            if a < last or b > len(t):
                continue
            out.append(t[last:a])
            out.append(open_t + t[a:b] + close_t)
            last = b
        out.append(t[last:])
        return "".join(out)

    if isinstance(text, dict):
        # object fields highlight their flattened strings (same value
        # order the indexer used)
        return [
            mark(t, vi) for vi, t in enumerate(_flatten_strings(text))
        ]
    if isinstance(text, list):
        return [mark(t, vi) for vi, t in enumerate(text)]
    return mark(text, 0)


def _spans_by_value(entry, ridk, partial):
    """vi -> merged sorted spans for this record's matches."""
    by_vi: dict = {}
    for off in (entry["offsets"] or {}).get(ridk, []):
        if len(off) == 5:
            vi, a, b, oa, ob = off
        else:  # legacy 2-tuple
            vi, (a, b, oa, ob) = 0, (*off, *off)
        span = (a, b) if partial else (oa, ob)
        by_vi.setdefault(vi, set()).add(span)
    out = {}
    for vi, spans in by_vi.items():
        merged = []
        for a, b in sorted(spans):
            if merged and a <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(b, merged[-1][1]))
            else:
                merged.append((a, b))
        out[vi] = merged
    return out


def search_offsets(args, ctx):
    """search::offsets(ref[, partial]) -> { "<value idx>": [{s, e}] }."""
    ref = 0
    if args and not isinstance(args[0], bool):
        try:
            ref = int(args[0])
        except (TypeError, ValueError):
            ref = 0
    partial = bool(args[1]) if len(args) > 1 else False
    entry = _ft_entry(ctx, ref)
    if entry is None or ctx.doc_id is None:
        return NONE
    from surrealdb_tpu import key as K2

    ridk = K2.enc_value(ctx.doc_id.id)
    spans = _spans_by_value(entry, ridk, partial)
    return {
        str(vi): [{"e": b, "s": a} for a, b in merged]
        for vi, merged in sorted(spans.items())
    }
