"""Shard-partitioned vector serving: failure-tolerant scatter-gather KNN.

On a range-sharded store (kvs/shard.py) the vector index is no longer
one node-local blob: the element keyspace (`he` state keys) is cut
along the SAME shard map that partitions the data, and each shard range
gets its own part engine — a `TpuVectorIndex` clamped to that range.
Every part owns its slice end to end: host arrays rebuilt from ITS
range, device blocks shipped to the runner under the existing
`(key, tag)` protocol, a CAGRA graph once the part crosses the ANN
floor — and, past the segmentation floor, its own LSM-style sealed
segments (idx/segments.py): segment fan-out nests INSIDE the shard
scatter-gather, so a part under continuous ingest seals/merges in the
background while the router's exact k-way merge stays exact (each
part's list is exact over its rows whether it came from one graph, a
segment fan-out, or a brute scan). Index size and query fan-out both
scale with shard count (ROADMAP open item 3, the SHINE direction).

A query scatter-gathers: one `vn` read establishes the freshness
point, the shared op log is fetched ONCE and routed to stale parts by
key range (or a part range-rebuilds), every part answers its local
top-k (oversampled by SURREAL_KNN_SHARD_OVERSAMPLE), and the
coordinator k-way merges — mirroring the cross-shard scan stitching
the router already does for ordered scans.

The robustness spine is the point (built like PRs 1-5, failure-first):

- **Per-shard budgets.** Each scatter attempt runs under a budget
  carved from the query's remaining inflight deadline
  (SURREAL_KNN_SHARD_TIMEOUT_S, enforced through the inflight
  thread-local so the KV retry policy inherits it) — one sick shard
  can burn its slice of the query, never the whole deadline.
- **Bounded hedged retry.** A failed part gets up to
  SURREAL_KNN_SHARD_HEDGES re-dispatches against a refreshed shard
  map, through the group's failover-following pool — a promoted
  replica answers the hedge (`knn_hedged_dispatches`).
- **Typed partial results.** What still fails is governed by
  SURREAL_KNN_PARTIAL: `error` (default) raises KnnShardUnavailable
  naming the missing shard(s); `partial` answers from the healthy
  parts, flags the response (QueryResult.partial) with the missing
  shard names, and counts `knn_partial_results`. Never silently wrong.
- **Splits behind the epoch fence.** A shard split re-cuts the
  partition table at the next query; the moved slice's fresh part
  rebuilds from KV truth and serves brute-exact until its graph
  rebuilds — a mid-split query is answered exactly.
- **Crash/promotion recovery for free.** Parts sync from KV truth
  through the routing client, so a promoted replica repopulates
  index-serving state exactly like PR-4 crash/reship.
- **Follower reads ride through.** Every KV read here goes through the
  query's transaction (`ctx.txn`), so a `SELECT ... <|k|> ... READ AT
  <bound>` statement scatter-gathers over each group's REPLICAS via
  the closed-timestamp proof (kvs/remote.py): the freshness `vn` read,
  the op-log fetch, and every part's range sync all serve from a
  provably-bounded-stale snapshot, and KNN read capacity scales with
  replicas instead of serializing on each group's primary.

Lock discipline (tools/check_robustness.py rule 8): `scatter_gather`
and `merge_topk` check the query deadline, and NO lock is held across
a remote dispatch — the partition lock guards pure bookkeeping, and
part engines do their KV I/O outside their index locks (`part_sync`).
"""

from __future__ import annotations

import threading

import numpy as np

from surrealdb_tpu import cnf
from surrealdb_tpu import key as K
from surrealdb_tpu.err import (
    KnnShardUnavailable,
    QueryCancelled,
    QueryTimeout,
    RetryableKvError,
    SdbError,
)
from surrealdb_tpu.idx.vector import TpuVectorIndex, _as_vector, _vec_dtype
from surrealdb_tpu.kvs import net
from surrealdb_tpu.val import NONE, is_truthy

# exceptions a scatter attempt absorbs into a per-shard failure record;
# query-lifecycle signals (cancel/timeout) always propagate
_SHARD_ERRS = (RetryableKvError, SdbError, OSError)

# consumed op-log entries that must accumulate before the router trims
# the shared log (bursty trims keep the steady-state query free of
# delete traffic; the log shard pays one range delete per burst)
TRIM_LOG_ENTRIES = 1024


class _NeverCancel:
    __slots__ = ()

    def is_set(self) -> bool:
        return False


_NEVER_CANCEL = _NeverCancel()


class _ShardBudget:
    """Duck-typed inflight handle activated around ONE per-shard
    scatter attempt: `remaining()` is the per-shard budget capped by
    the real query budget, so the KV retry policy
    (`RetryPolicy.effective_deadline_s`) — which reads the thread-local
    — bounds its retries to the SHARD's slice of the deadline without
    any plumbing. The clock is the seam's (`kvs/net.py`), so the
    deterministic simulator virtualizes these budgets too."""

    __slots__ = ("cancel", "_end", "_parent")

    def __init__(self, parent, budget_s: float):
        self._parent = parent
        self.cancel = parent.cancel if parent is not None \
            else _NEVER_CANCEL
        self._end = net.mono() + budget_s

    def remaining(self) -> float:
        rem = self._end - net.mono()
        if self._parent is not None:
            p = self._parent.remaining()
            if p is not None:
                rem = min(rem, p)
        return rem

    def mark_timed_out(self):
        # a shard attempt running out its budget is NOT the query
        # timing out — the hedge/partial machinery owns what follows
        pass

    def mark_cancelled(self):
        if self._parent is not None:
            self._parent.mark_cancelled()


class _Part:
    """One contiguous slice of the element keyspace: the shard range
    serving it and the range-clamped engine holding its rows."""

    __slots__ = ("lo", "hi", "addrs", "label", "engine")

    def __init__(self, parent: "ShardedVectorIndex", lo: bytes,
                 hi: bytes, addrs):
        self.lo = bytes(lo)
        self.hi = bytes(hi)
        self.addrs = tuple(addrs)
        self.label = parent.range_label(self.lo, self.hi)
        ns, db, tb, ix = parent.key
        self.engine = TpuVectorIndex(
            ns, db, tb, ix, parent.params,
            key_range=(self.lo, self.hi), label=self.label,
        )
        self.engine.snapshot_dir = parent.snapshot_dir

    def span(self) -> tuple[bytes, bytes]:
        return (self.lo, self.hi)

    def shard_name(self) -> str:
        """How a partial answer / typed error names this shard: the
        range label plus the replica addresses an operator can act on."""
        return f"{self.label}@{','.join(self.addrs)}"


class ShardedVectorIndex:
    """Scatter-gather router for one vector index over a sharded store.

    Implements the same `knn(q, k, ctx, ...)` contract as
    TpuVectorIndex (the planner cannot tell them apart); internally it
    maintains one part engine per shard range intersecting the index's
    element keyspace and re-cuts that partition whenever the shard map
    epoch moves."""

    def __init__(self, ns, db, tb, ix, params: dict, backend,
                 telemetry=None):
        from surrealdb_tpu.ops.metrics import normalize_metric

        self.key = (ns, db, tb, ix)
        self.params = params
        self.dim = params["dimension"]
        self.backend = backend
        self.telemetry = telemetry
        self.metric, self.mink_p = normalize_metric(
            params.get("distance", "euclidean")
        )
        self.dtype = _vec_dtype(params)
        self.snapshot_dir = None
        pre = K.ix_state(ns, db, tb, ix, b"he")
        self.he_pre = pre
        self.he_beg, self.he_end = K.prefix_range(pre)
        self.vn_key = K.ix_state(ns, db, tb, ix, b"vn")
        # partition-table lock: pure in-memory bookkeeping ONLY — rule 8
        # forbids holding it across any remote dispatch
        self.lock = threading.Lock()
        self.parts: list[_Part] = []
        self.map_epoch = -1
        # version below which the shared op log was last trimmed (this
        # node's view); the router trims in bursts — see _maybe_trim_log
        self._trimmed_ver = 0

    # -- bookkeeping --------------------------------------------------------

    def _count(self, name: str, by: int = 1):
        if self.telemetry is not None:
            self.telemetry.inc(name, by)

    def range_label(self, lo: bytes, hi: bytes) -> str:
        """Short printable label for a slice of the element keyspace
        (the he prefix stripped, boundaries hex-trimmed)."""

        def _p(b):
            if b <= self.he_beg:
                return "-inf"
            if b >= self.he_end:
                return "+inf"
            return b[len(self.he_pre):][:8].hex() or "-inf"

        return f"[{_p(lo)}..{_p(hi)})"

    def refresh_parts(self) -> list:
        """The partition table synced to the backend's CURRENT shard
        map. `shard_map()` may refresh over the network when marked
        stale — called BEFORE the partition lock is taken."""
        m = self.backend.shard_map()
        with self.lock:
            if m.epoch != self.map_epoch or not self.parts:
                self._repartition(m)
            return list(self.parts)

    def _repartition(self, m):
        """Re-cut the partition along shard map `m` (caller holds the
        partition lock; in-memory only). Engines whose range is
        unchanged are kept — their device blocks and ANN graphs stay
        warm; a changed range (split/merge) gets a fresh engine that
        rebuilds from KV truth behind the epoch fence and serves
        brute-exact until its graph rebuilds."""
        old = {p.span(): p for p in self.parts}
        parts = []
        for i in m.covering(self.he_beg, self.he_end):
            s = m.shards[i]
            lo = max(self.he_beg, s.beg)
            hi = self.he_end if s.end is None else min(self.he_end, s.end)
            if lo >= hi:
                continue
            p = old.get((lo, hi))
            if p is None:
                p = _Part(self, lo, hi, s.addrs)
            else:
                # same range, possibly new replica set (failover/move):
                # the warm engine survives, only the address book moves
                p.addrs = tuple(s.addrs)
            parts.append(p)
        self.parts = parts
        self.map_epoch = m.epoch

    def shards_status(self) -> list[dict]:
        """Per-shard index residency (INFO FOR SYSTEM / /metrics):
        rows, host bytes, ANN state, sync version, replica addresses.
        `device_sharded` (device/mesh.py mesh width) rides through each
        part's engine residency when its blocks served on >1 device."""
        with self.lock:
            parts = list(self.parts)
        out = []
        for p in parts:
            d = p.engine.residency()
            d["addrs"] = list(p.addrs)
            out.append(d)
        return out

    def _ann_route(self, k: int):
        """EXPLAIN support: non-None when ANY part serves k-NN of `k`
        from its CAGRA graph (mirrors TpuVectorIndex._ann_route)."""
        with self.lock:
            parts = list(self.parts)
        for p in parts:
            r = p.engine._ann_route(k)
            if r is not None:
                return r
        return None

    def ann_plan(self, k: int):
        """EXPLAIN surface across the parts: segmented wins over the
        legacy graph marker when any part fans over sealed segments
        (each part engine runs its own seal/build/merge lifecycle —
        segment fan-out nests inside the shard scatter-gather)."""
        with self.lock:
            parts = list(self.parts)
        plan = None
        seg_total = ready_total = 0
        for p in parts:
            pp = p.engine.ann_plan(k)
            if pp is None:
                continue
            if pp.get("ann") == "segmented":
                seg_total += pp.get("segments", 0)
                ready_total += pp.get("ready", 0)
                plan = "segmented"
            elif plan is None:
                plan = "graph"
        if plan == "segmented":
            return {"ann": "segmented", "segments": seg_total,
                    "ready": ready_total}
        if plan == "graph":
            return {"ann": "graph"}
        return None

    def ensure_ann(self) -> bool:
        """Synchronous per-part graph builds (bench/tests)."""
        with self.lock:
            parts = list(self.parts)
        return bool(parts) and all(p.engine.ensure_ann() for p in parts)

    # -- search -------------------------------------------------------------

    def knn(self, q, k: int, ctx, ef=None, cond=None, cond_ctx=None):
        """Top-k nearest records across every shard part (same contract
        as TpuVectorIndex.knn; `ef` is advisory, as there)."""
        import time as _time

        from surrealdb_tpu.telemetry import stage_record

        t0 = _time.perf_counter_ns()
        try:
            return self._knn(q, k, ctx, cond=cond, cond_ctx=cond_ctx)
        finally:
            stage_record("index_knn", _time.perf_counter_ns() - t0)

    def _knn(self, q, k: int, ctx, cond=None, cond_ctx=None):
        # pressure checkpoint before the scatter (no router/part locks
        # held here — rule 8): part engines register their own vec/ann
        # accounts, so eviction degrades a cold part to rebuild-on-touch
        from surrealdb_tpu import resource as _resource

        _resource.checkpoint()
        qv = _as_vector(q, self.dim, "knn query", self.dtype)
        over = max(float(cnf.KNN_SHARD_OVERSAMPLE), 1.0)
        fetch0 = max(k, int(np.ceil(k * over)))
        # per-query memo: shards that failed once in this query are not
        # re-dispatched by cond-refill rounds (each re-attempt would
        # burn another budget x hedges against a known-dead shard), and
        # a partial answer is counted ONCE per query however many
        # refill rounds flag it
        memo = {"failed": None, "counted": False}
        if cond is None:
            return self._search(qv, fetch0, ctx, memo)[:k]
        # predicate pushdown: oversample + refill (mirrors the
        # node-local engine's cond loop)
        want = k
        fetch = max(4 * k, 64, fetch0)
        checked: set = set()
        out = []
        while True:
            pairs = self._search(qv, fetch, ctx, memo)
            exhausted = len(pairs) < fetch  # every part fully drained
            for rid, dist in pairs:
                h = K.enc_value(rid.id)
                if h in checked:
                    continue
                checked.add(h)
                if self._check_cond(rid, cond, cond_ctx):
                    out.append((rid, dist))
                    if len(out) >= want:
                        return out
            if exhausted:
                return out
            fetch *= 4

    def _check_cond(self, rid, cond, ctx):
        from surrealdb_tpu.exec.eval import evaluate, fetch_record

        doc = fetch_record(ctx, rid)
        if doc is NONE:
            return False
        c = ctx.with_doc(doc, rid)
        return is_truthy(evaluate(cond, c))

    def _search(self, qv: np.ndarray, fetch: int, ctx, memo=None):
        """One scatter-gather round trip, with the partial-result
        policy applied: `error` raises the typed KnnShardUnavailable;
        `partial` serves the healthy parts' merge, flags the statement
        response (executor mailbox -> QueryResult.partial) and counts
        knn_partial_results (once per query — `memo` carries the
        failed-shard set and the counted flag across refill rounds)."""
        known = memo.get("failed") if memo else None
        pairs, failures = scatter_gather(self, qv, fetch, ctx,
                                         known_failed=known)
        if memo is not None:
            memo["failed"] = {f["span"] for f in failures}
        if failures:
            names = sorted({f["shard"] for f in failures})
            detail = "; ".join(
                f"{f['shard']}: {f['error']}" for f in failures
            )
            if str(cnf.KNN_PARTIAL).lower() != "partial":
                raise KnnShardUnavailable(
                    f"knn shard(s) unavailable "
                    f"(SURREAL_KNN_PARTIAL=error): {detail}",
                    shards=names,
                )
            if memo is None or not memo.get("counted"):
                self._count("knn_partial_results")
                if memo is not None:
                    memo["counted"] = True
            ex = getattr(ctx, "executor", None)
            if ex is not None:
                prev = getattr(ex, "_knn_partial", None) or []
                ex._knn_partial = sorted(set(prev) | set(names))
        return pairs


# ---------------------------------------------------------------------------
# scatter / gather (free functions: tools/check_robustness.py rule 8
# asserts these exist, call check_deadline, and never hold a lock
# across a remote dispatch)
# ---------------------------------------------------------------------------


def scatter_gather(idx: ShardedVectorIndex, qv: np.ndarray, fetch: int,
                   ctx, known_failed=None):
    """Scatter one KNN query across the index's shard parts, gather
    per-part top-`fetch`, and k-way merge. Returns
    `(pairs, failures)` where `failures` is a list of
    `{"span", "shard", "error"}` records for parts that could not be
    brought to the query's freshness point within their budgets — the
    caller applies the partial policy. Parts whose span is in
    `known_failed` (they already failed earlier in THIS query) are
    not re-dispatched — a cond-refill round must not burn another
    budget x hedges against a known-dead shard. Every pair in `pairs`
    carries an exact distance computed from full-precision rows."""
    from surrealdb_tpu import inflight

    ctx.check_deadline()
    # 1. freshness point: ONE vn read through the query's transaction
    # (per-shard MVCC snapshot — the same consistency the unsharded
    # engine gets from its sync). Budgeted like any shard attempt; if
    # even this is unreachable, no part can prove freshness: fail them
    # all, naming the state shard.
    budget = max(float(cnf.KNN_SHARD_TIMEOUT_S), 0.05)
    try:
        with inflight.activate(_ShardBudget(inflight.current(), budget)):
            ver = ctx.txn.get_val(idx.vn_key) or 0
    except (QueryCancelled, QueryTimeout):
        raise
    except _SHARD_ERRS as e:
        idx._count("knn_shard_fanout")
        shard = _state_shard_name(idx)
        return [], [{"shard": shard, "error": str(e)[:160]}]
    # 2. partition table against the current shard map (in-memory)
    parts = idx.refresh_parts()
    idx._count("knn_shard_fanout", max(len(parts), 1))
    known_failed = known_failed or set()
    skipped = [p for p in parts if p.span() in known_failed]
    live = [p for p in parts if p.span() not in known_failed]
    # 3. sync plan: fetch the shared op log ONCE, route ops per part
    pending = [p for p in live if p.engine.version < ver]
    synced_any = bool(pending)
    routed = _route_log(idx, ctx, ver, pending) if pending else {}
    failures: list[dict] = []
    hedges = max(int(cnf.KNN_SHARD_HEDGES), 0)
    for round_i in range(1 + hedges):
        ctx.check_deadline()
        if round_i > 0:
            if not pending:
                break
            # bounded hedged retry: the failure may be a failover or a
            # split — refresh the map, re-cut the partition, and
            # re-dispatch only what is still stale. The group pool
            # follows promotions, so a promoted replica answers this.
            # The map refresh runs under a shard budget too — a sick
            # meta shard must not eat the query either.
            idx._count("knn_hedged_dispatches", len(pending))
            try:
                with inflight.activate(
                    _ShardBudget(inflight.current(), budget)
                ):
                    idx.backend.refresh_map()
            except _SHARD_ERRS:
                pass  # hedge against the stale map, better than nothing
            parts = idx.refresh_parts()
            skipped = [p for p in parts if p.span() in known_failed]
            live = [p for p in parts if p.span() not in known_failed]
            pending = [p for p in live if p.engine.version < ver]
            routed = _route_log(idx, ctx, ver, pending) if pending else {}
        failures = _scatter_round(idx, ctx, ver, pending, routed)
        pending = [p for p in live
                   if any(f["span"] == p.span() for f in failures)]
        if not pending:
            break
    for p in skipped:
        failures.append(_failure(
            p, "unavailable earlier in this query (not re-dispatched)"
        ))
    if not failures and synced_any:
        _maybe_trim_log(idx, ctx, parts, ver)
    # 4. per-part local top-k (pure compute — by part_sync's lock
    # discipline nothing here can block on a remote shard)
    failed_spans = {f["span"] for f in failures}
    serving = [p for p in parts if p.span() not in failed_spans]
    ctx.check_deadline()
    lists = _search_parts(idx, ctx, serving, qv, fetch)
    pairs = merge_topk(ctx, lists, fetch)
    return pairs, failures


def _search_parts(idx, ctx, serving, qv, fetch):
    """Per-part top-k, in part order. Sequential by default: the
    local searches are one BLAS/kernel call each, and on a GIL-bound
    host extra worker threads per query measurably LOSE to the
    straight loop (concurrency comes from the per-part cross-query
    batchers instead). SURREAL_KNN_SCATTER=threads opts into a
    thread fan-out for many-core hosts where each part's gemm
    genuinely parallelizes."""
    mode = str(cnf.KNN_SCATTER).lower()
    parallel = len(serving) > 1 and mode == "threads"
    if not parallel:
        out = []
        for p in serving:
            ctx.check_deadline()
            out.append(p.engine.search_topk(qv, fetch))
        return out
    slots = [None] * len(serving)
    errs = []

    def work(i, p):
        try:
            slots[i] = p.engine.search_topk(qv, fetch)
        except BaseException as e:
            # a local-search crash is a BUG, not a shard failure —
            # swallowing it would be exactly the silent loss this
            # module exists to forbid: surface it to the query
            errs.append(e)

    threads = [
        threading.Thread(target=work, args=(i, p), daemon=True,
                         name=f"knn-search-{i}")
        for i, p in enumerate(serving[1:], start=1)
    ]
    for t in threads:
        t.start()
    work(0, serving[0])  # this thread takes the first part
    for t in threads:
        t.join()
    if errs:
        raise errs[0]
    return [s if s is not None else [] for s in slots]


def merge_topk(ctx, lists: list, k: int):
    """K-way merge of per-shard ascending `(rid, dist)` lists into the
    global top-k. Exact parts make the merge exact: each list is that
    part's true top-k, the parts partition the rows, so the k smallest
    of the union ARE the global top-k. Ties keep shard order (stable)."""
    import heapq

    ctx.check_deadline()
    out = []
    for item in heapq.merge(*lists, key=lambda pair: pair[1]):
        out.append(item)
        if len(out) >= k:
            break
    return out


def _scatter_round(idx, ctx, ver, pending, routed) -> list[dict]:
    """Dispatch one sync round over the stale parts; returns the
    failure records (span + shard name + error). Parallel worker
    threads on real transports for read-only queries; sequential
    otherwise (the deterministic simulator must own all interleaving)."""
    if not pending:
        return []
    failures = []
    mode = str(cnf.KNN_SCATTER).lower()
    parallel = len(pending) > 1 and mode != "seq" and (
        mode == "threads"
        or (mode == "auto" and idx.backend.transport is None)
    )
    if parallel:
        # shared-transaction safety: lazy sub-txn creation and
        # wrong-shard re-routing both mutate ShardTx state — pre-pin
        # every involved shard from THIS thread, and only fan out when
        # the transaction holds no writes (a write txn re-routes by
        # aborting, which must stay single-threaded)
        shard_tx = getattr(ctx.txn, "btx", None)
        prepin = getattr(shard_tx, "prepin", None)
        if prepin is None or shard_tx._any_writes():
            parallel = False
        else:
            pinned = []
            for p in pending:
                try:
                    prepin(p.lo)
                    pinned.append(p)
                except (QueryCancelled, QueryTimeout):
                    raise
                except _SHARD_ERRS as e:
                    failures.append(_failure(p, e))
            pending = pinned
    if parallel and len(pending) > 1:
        slots: dict = {}

        def work(p):
            try:
                slots[p.span()] = _sync_part(
                    idx, ctx, p, ver, routed.get(p.span())
                )
            except (QueryCancelled, QueryTimeout):
                slots[p.span()] = "query cancelled/timed out mid-scatter"
            except BaseException as e:
                slots[p.span()] = f"{type(e).__name__}: {e}"[:160]

        threads = [
            threading.Thread(target=work, args=(p,), daemon=True,
                             name=f"knn-scatter-{i}")
            for i, p in enumerate(pending)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()  # bounded: every KV op inside runs under the
            #           part's _ShardBudget via the inflight seam
        for p in pending:
            err = slots.get(p.span())
            if err is not None:
                failures.append(_failure(p, err))
        return failures
    for p in pending:
        ctx.check_deadline()
        try:
            err = _sync_part(idx, ctx, p, ver, routed.get(p.span()))
        except (QueryCancelled, QueryTimeout):
            raise
        if err is not None:
            failures.append(_failure(p, err))
    return failures


def _failure(part, err) -> dict:
    return {
        "span": part.span(),
        "shard": part.shard_name(),
        "error": str(err)[:160],
    }


def _sync_part(idx, ctx, part, ver, entries):
    """One per-shard scatter attempt: bring `part` to version `ver`
    under its own budget (carved from the query's remaining deadline
    through the inflight thread-local — the KV retry policy then
    bounds itself to the shard's slice). Returns None on success, the
    error string on failure."""
    from surrealdb_tpu import inflight

    budget = max(float(cnf.KNN_SHARD_TIMEOUT_S), 0.05)
    try:
        with inflight.activate(
            _ShardBudget(inflight.current(), budget)
        ):
            part.engine.part_sync(ctx, ver, entries)
        return None
    except (QueryCancelled, QueryTimeout):
        raise
    except _SHARD_ERRS as e:
        return str(e)[:160]


def _route_log(idx, ctx, ver, pending) -> dict:
    """Fetch the shared op log once and route its entries to the stale
    parts by element-key range. Returns `{span: entries | None}` —
    None means that part must range-rebuild (fresh part, or the log
    no longer covers its gap). Log trouble is NOT a failure here:
    every part just falls back to its own range rebuild."""
    out: dict = {p.span(): None for p in pending}
    floors = [p.engine.version for p in pending if p.engine.version >= 0]
    if not floors:
        return out
    base = min(floors)
    gap = ver - base
    total = sum(len(p.engine.rids) for p in pending)
    if gap <= 0 or gap > max(4096, total // 4):
        return out
    from surrealdb_tpu import inflight

    ns, db, tb, ix = idx.key
    beg = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(base + 1))
    end = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(ver)) + b"\x00"
    try:
        # budgeted like every other shard attempt: a sick log shard
        # burns at most one shard budget here, then the parts fall
        # back to their own (individually budgeted) range rebuilds
        with inflight.activate(_ShardBudget(
            inflight.current(), max(float(cnf.KNN_SHARD_TIMEOUT_S),
                                    0.05)
        )):
            entries = list(ctx.txn.scan_vals(beg, end))
    except (QueryCancelled, QueryTimeout):
        raise
    except _SHARD_ERRS:
        return out
    if len(entries) != gap:
        return out  # trimmed/gappy log: rebuild instead
    routed: dict = {
        p.span(): [] for p in pending if p.engine.version >= 0
    }
    spans = [(p.span(), p.lo, p.hi) for p in pending
             if p.engine.version >= 0]
    for i, (_k, (op, idv, raw)) in enumerate(entries):
        gver = base + 1 + i
        hk = idx.he_pre + K.enc_value(idv)
        for span, lo, hi in spans:
            if lo <= hk < hi:
                routed[span].append((gver, op, idv, raw))
                break
    out.update(routed)
    return out


def _maybe_trim_log(idx, ctx, parts, ver):
    """Trim the shared op log once every part has consumed it. Part
    engines never trim (idx/vector.py gates the unsharded trim on
    `key_range is None`), so the ROUTER owns log growth: when every
    part reached `ver`, the query's transaction can write, and at
    least 1024 entries accumulated since the last trim, buffer a
    delete of the consumed range into this transaction (TRIM_LOG_
    ENTRIES bounds the burst size). Another serving node mid-catch-up
    simply finds the gap and range-rebuilds — the same discipline as
    the unsharded multi-node trim."""
    if not getattr(ctx.txn, "write", False):
        return
    if ver - idx._trimmed_ver < TRIM_LOG_ENTRIES:
        return
    if any(p.engine.version < ver for p in parts):
        return
    ns, db, tb, ix = idx.key
    beg = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(0))
    end = K.ix_state(ns, db, tb, ix, b"hl", K.enc_u64(ver)) + b"\x00"
    try:
        ctx.txn.delete_range(beg, end)
    except (QueryCancelled, QueryTimeout):
        raise
    except _SHARD_ERRS:
        return  # trimming is best-effort; the next burst retries
    idx._trimmed_ver = ver


def _state_shard_name(idx) -> str:
    """Name the shard holding the index's version/log state keys (what
    a partial answer blames when even freshness is unprovable)."""
    try:
        m = idx.backend.shard_map()
        s = m.shards[m.locate(idx.vn_key)]
        return (f"{idx.range_label(max(idx.he_beg, s.beg), idx.he_end)}"
                f"@{','.join(s.addrs)}")
    except _SHARD_ERRS:
        return "index-state shard (map unavailable)"
