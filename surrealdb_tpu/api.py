"""DEFINE API invocation engine.

Reference: core/src/api/mod.rs:1-11 (middleware chain, body handling,
response shaping), core/src/expr/statements/define/api.rs (definition
surface), core/src/api/path.rs (path grammar: static segments, `:param`
dynamic segments with optional `<type>` coercion, `*rest` catch-alls,
`\\:`/`\\*` escapes), core/src/api/middleware (api::timeout,
api::req::body, api::res::{body,status,header,headers} built-ins plus
user `fn::` middleware with the ($req, $next, ...args) calling
convention), core/src/api/invocation.rs (permission evaluation order:
method -> route -> global config).

The chain runs entirely inside the executor — api::invoke() is an
ordinary function call, and the HTTP /api/:ns/:db/* route drives the
same code path.
"""

from __future__ import annotations

import json as _json
import re
import time as _time

from surrealdb_tpu.err import ReturnException, SdbError
from surrealdb_tpu.val import NONE, Closure

__all__ = ["invoke", "validate_define_path"]


class _ApiError(Exception):
    """A shaped API failure: becomes {status, body: message} directly."""

    def __init__(self, status: int, body):
        super().__init__(str(body))
        self.status = status
        self.body = body


# ---------------------------------------------------------------------------
# Path grammar
# ---------------------------------------------------------------------------

_HEADER_NAME_RE = re.compile(r"^[!#$%&'*+\-.^_`|~0-9A-Za-z]+$")


def validate_define_path(path: str) -> None:
    """DEFINE-time validation with the reference's exact error strings."""
    if path == "":
        raise SdbError(
            "The string could not be parsed into a path: Path cannot be empty"
        )
    if not path.startswith("/"):
        raise SdbError(
            "The string could not be parsed into a path: "
            "Segment should start with /"
        )


def _parse_segments(path: str) -> list:
    """-> [("static", text) | ("param", name, type|None) | ("rest", name)].

    Escapes: `\\:` and `\\*` make the next char literal. A `*name`
    segment must be last and captures one-or-more remaining segments.
    """
    segs = []
    for raw in path.split("/"):
        if raw == "":
            continue
        if raw.startswith("\\:") or raw.startswith("\\*"):
            segs.append(("static", raw[1:]))
        elif raw.startswith(":"):
            name = raw[1:]
            typ = None
            m = re.match(r"^([^<]*)<([^>]*)>$", name)
            if m:
                name, typ = m.group(1), m.group(2)
            segs.append(("param", name, typ))
        elif raw.startswith("*"):
            segs.append(("rest", raw[1:]))
        else:
            segs.append(("static", raw.replace("\\:", ":").replace(
                "\\*", "*")))
    return segs


def _coerce_segment(value: str, typ):
    """Typed dynamic segment (`:id<number>`): coerce or fail the match."""
    if typ in (None, "", "string"):
        return value
    if typ in ("number", "int", "float", "decimal"):
        try:
            return int(value)
        except ValueError:
            pass
        try:
            return float(value)
        except ValueError:
            raise ValueError(value)
    if typ == "bool":
        if value in ("true", "false"):
            return value == "true"
        raise ValueError(value)
    if typ == "uuid":
        from surrealdb_tpu.val import Uuid

        return Uuid(value)
    return value


def _match_segments(defsegs: list, reqsegs: list):
    """-> (params dict, specificity tuple) or None.

    Specificity per segment: static=0 < param=1 < rest=2; tuples compare
    lexicographically so `/users/specific` beats `/users/:id` beats
    `/users/*rest`, and a longer static prefix beats an early catch-all.
    """
    params = {}
    spec = []
    i = 0
    for seg in defsegs:
        kind = seg[0]
        if kind == "rest":
            if i >= len(reqsegs):
                return None  # rest requires at least one segment
            params[seg[1]] = list(reqsegs[i:])
            spec.append(2)
            i = len(reqsegs)
            return params, tuple(spec)
        if i >= len(reqsegs):
            return None
        if kind == "static":
            if seg[1] != reqsegs[i]:
                return None
            spec.append(0)
        else:  # param
            try:
                params[seg[1]] = _coerce_segment(reqsegs[i], seg[2])
            except (ValueError, SdbError):
                return None
            spec.append(1)
        i += 1
    if i != len(reqsegs):
        return None
    return params, tuple(spec)


# ---------------------------------------------------------------------------
# Body strategies
# ---------------------------------------------------------------------------

_STRATEGY_CTYPE = {
    "json": "application/json",
    "cbor": "application/cbor",
    "flatbuffers": "application/vnd.surrealdb.flatbuffers",
    "plain": "text/plain",
    "bytes": "application/octet-stream",
    "native": "application/vnd.surrealdb.native",
}
_CTYPE_STRATEGY = {v: k for k, v in _STRATEGY_CTYPE.items()}


def _decode_body(strategy: str, body):
    if strategy == "native":
        return body
    if not isinstance(body, (bytes, bytearray)):
        raise _ApiError(400, "Request body must be binary data")
    data = bytes(body)
    try:
        if strategy == "json":
            return _from_json(_json.loads(data.decode()))
        if strategy == "cbor":
            from surrealdb_tpu.wire import decode as _cbor_dec

            return _cbor_dec(data)
        if strategy == "flatbuffers":
            from surrealdb_tpu.fb import decode as _fb_dec

            return _fb_dec(data)
        if strategy == "plain":
            return data.decode()
        if strategy == "bytes":
            return data
    except _ApiError:
        raise
    except Exception:
        raise _ApiError(400, "Failed to decode the request body")
    raise _ApiError(400, "Failed to decode the request body")


def _from_json(v):
    if isinstance(v, dict):
        return {k: _from_json(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_from_json(x) for x in v]
    if v is None:
        from surrealdb_tpu.val import NULL

        return NULL
    return v


def _apply_req_body(strategy, req):
    headers = req.get("headers") or {}
    ctype = _header_get(headers, "content-type")
    if strategy == "auto":
        if ctype is None:
            return req  # no Content-Type: pass the body through untouched
        target = _CTYPE_STRATEGY.get(str(ctype).split(";")[0].strip())
        if target is None:
            raise _ApiError(415, f"Unsupported Content-Type: {ctype}")
    else:
        target = strategy
        if target != "native":
            expected = _STRATEGY_CTYPE.get(target)
            if expected is None:
                raise _ApiError(400, "Failed to decode the request body")
            if ctype is None or str(ctype).split(";")[0].strip() != expected:
                raise _ApiError(
                    400, f"Expected Content-Type to be {expected}"
                )
    return {**req, "body": _decode_body(target, req.get("body", NONE))}


def _parse_accept(value: str) -> list:
    """-> [(media_type, q)] in preference order (q desc, listed order)."""
    items = []
    for idx, part in enumerate(str(value).split(",")):
        bits = part.strip().split(";")
        mt = bits[0].strip().lower()
        if not mt:
            continue
        q = 1.0
        for p in bits[1:]:
            p = p.strip()
            if p.startswith("q="):
                try:
                    q = float(p[2:])
                except ValueError:
                    q = 1.0
        items.append((mt, q, idx))
    items.sort(key=lambda t: (-t[1], t[2]))
    return [(mt, q) for mt, q, _ in items]


def _negotiate(strategy, req):
    """-> output strategy honouring the Accept header, or 406."""
    accept = _header_get(req.get("headers") or {}, "accept")
    if strategy != "auto":
        ctype = _STRATEGY_CTYPE[strategy]
        if accept is None:
            return strategy
        for mt, _q in _parse_accept(accept):
            if mt in ("*/*", ctype) or (
                mt.endswith("/*") and ctype.startswith(mt[:-1])
            ):
                return strategy
        raise _ApiError(
            406, "No output strategy was possible for this API request"
        )
    if accept is None:
        return "json"
    for mt, _q in _parse_accept(accept):
        if mt == "*/*":
            return "json"
        s = _CTYPE_STRATEGY.get(mt)
        if s is not None:
            return s
        if mt.endswith("/*"):
            for ct, st in _CTYPE_STRATEGY.items():
                if ct.startswith(mt[:-1]):
                    return st
    raise _ApiError(
        406, "No output strategy was possible for this API request"
    )


def _serialize_body(strategy, body) -> bytes:
    from surrealdb_tpu.val import render, to_json

    if strategy == "json":
        return _json.dumps(to_json(body)).encode()
    if strategy == "cbor":
        from surrealdb_tpu.wire import encode as _cbor_enc

        return _cbor_enc(body)
    if strategy == "flatbuffers":
        from surrealdb_tpu.fb import encode as _fb_enc

        return _fb_enc(body)
    if strategy == "plain":
        return (body if isinstance(body, str) else render(body)).encode()
    if strategy == "bytes":
        if isinstance(body, (bytes, bytearray)):
            return bytes(body)
        return (body if isinstance(body, str) else render(body)).encode()
    return _json.dumps(to_json(body)).encode()


def _apply_res_body(strategy, res, req):
    if res.get("raw"):
        return res
    if strategy != "auto" and strategy not in _STRATEGY_CTYPE:
        raise SdbError(f"Unknown response body strategy '{strategy}'")
    out = _negotiate(strategy, req)
    headers = dict(res.get("headers") or {})
    headers["content-type"] = _STRATEGY_CTYPE[out]
    if out == "native":
        # native responses carry the value through unserialized
        return {**res, "headers": headers}
    body = _serialize_body(out, res.get("body", NONE))
    return {**res, "body": body, "headers": headers}


# ---------------------------------------------------------------------------
# Response validation / shaping
# ---------------------------------------------------------------------------


def _validate_status(status):
    # the http crate accepts 100..=999; the message cites the RFC range
    ok = isinstance(status, (int, float)) and not isinstance(status, bool) \
        and float(status).is_integer() and 100 <= int(status) <= 999
    if not ok:
        shown = int(status) if isinstance(status, float) and float(
            status).is_integer() else status
        from surrealdb_tpu.val import render

        shown = shown if isinstance(shown, (int, float)) else render(shown)
        raise _ApiError(
            400,
            f"Invalid HTTP status code: {shown}. Must be between 100 and 599",
        )
    return int(status)


def _validate_header(name, value) -> tuple:
    lname = str(name).lower()
    if not _HEADER_NAME_RE.match(lname):
        raise _ApiError(
            400,
            f"Invalid header name: {name}: invalid HTTP header name",
        )
    sval = value if isinstance(value, str) else None
    if sval is None:
        from surrealdb_tpu.val import render

        sval = render(value)
    if "\r" in sval or "\n" in sval:
        raise _ApiError(
            400,
            f"Invalid header value for {lname}: {sval}: "
            "failed to parse header value",
        )
    return lname, sval


def _normalize_response(out):
    """Handler / custom-middleware output -> response object."""
    if isinstance(out, dict) and ("status" in out or "body" in out
                                  or "headers" in out or "raw" in out
                                  or "context" in out):
        res = dict(out)
        res.setdefault("status", 200)
        res.setdefault("headers", {})
        res.setdefault("body", NONE)
        res.setdefault("context", {})
        return res
    return {"status": 200, "headers": {}, "body": out, "context": {}}


def _finalize(res) -> dict:
    status = _validate_status(res.get("status", 200))
    headers = {}
    for k, v in dict(res.get("headers") or {}).items():
        if v is NONE or v is None:
            continue
        lk, lv = _validate_header(k, v)
        headers[lk] = lv
    return {"status": status, "headers": headers,
            "body": res.get("body", NONE)}


# ---------------------------------------------------------------------------
# Middleware chain
# ---------------------------------------------------------------------------


class _HostNext(Closure):
    """The $next value handed to custom middleware — a host-implemented
    closure that resumes the chain when called as $next($req)."""

    __slots__ = ("py",)

    def __init__(self, py):
        super().__init__([("req", None)], None)
        self.py = py

    def render(self) -> str:
        return "|$req| <api middleware chain>"


def _header_get(headers: dict, name: str):
    for k, v in (headers or {}).items():
        if str(k).lower() == name:
            return v
    return None


def _permission_allows(perm, ctx) -> bool:
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.val import is_truthy

    if perm is True:
        return True
    if perm is False:
        return False
    c = ctx.child()
    c.vars["auth"] = getattr(ctx.session, "rid", None) or NONE
    try:
        return is_truthy(evaluate(perm, c))
    except SdbError:
        return False


def invoke(ctx, path: str, opts: dict):
    """api::invoke(path, opts) — route, authorize, run the chain."""
    from surrealdb_tpu import key as K
    from surrealdb_tpu.catalog import ApiDef, ConfigDef
    from surrealdb_tpu.exec.eval import evaluate

    ns, db = ctx.need_ns_db()
    opts = opts if isinstance(opts, dict) else {}
    reqsegs = [s for s in str(path).split("/") if s != ""]

    best = None  # (spec, ApiDef, params)
    for _k, cand in ctx.txn.scan_vals(
        *K.prefix_range(K.api_prefix(ns, db))
    ):
        if not isinstance(cand, ApiDef):
            continue
        m = _match_segments(_parse_segments(cand.path), reqsegs)
        if m is None:
            continue
        params, spec = m
        if best is None or spec < best[0]:
            best = (spec, cand, params)
    if best is None:
        return {"status": 404, "body": "Not found", "headers": {}}
    _spec, d, path_params = best

    method = str(opts.get("method", "get")).lower()
    method_action = None
    any_action = None
    for a in d.actions:
        if method in a.methods and method_action is None:
            method_action = a
        if "any" in a.methods and any_action is None:
            any_action = a
    action = method_action or any_action
    if action is None or action.then is None:
        return {"status": 404, "body": "Not found", "headers": {}}

    cfg = ctx.txn.get_val(K.cfg_def(ns, db, "API"))
    cfg = cfg if isinstance(cfg, ConfigDef) else None

    # permissions: method -> route -> global, all must allow; system
    # sessions (owner/editor/viewer) bypass like the reference — both
    # record users AND anonymous sessions are gated
    if getattr(ctx.session, "auth_level", "owner") in ("record", "none"):
        levels = [action.permissions]
        if any_action is not None and any_action is not action:
            levels.append(any_action.permissions)
        if cfg is not None:
            levels.append(cfg.permissions)
        for perm in levels:
            if not _permission_allows(perm, ctx):
                return {
                    "status": 403,
                    "body": "Permission denied: You are not allowed to "
                            "access this resource",
                    "headers": {},
                }

    # middleware chain: DB config -> FOR any -> FOR method
    mws = []
    if cfg is not None:
        mws.extend(cfg.middleware or [])
    if any_action is not None and any_action is not action:
        mws.extend(any_action.middleware or [])
    mws.extend(action.middleware or [])

    req = {
        "method": method,
        "path": str(path),
        "params": {**path_params, **(opts.get("params") or {})},
        "query": opts.get("query") if isinstance(opts.get("query"), dict)
        else {},
        "headers": opts.get("headers") if isinstance(
            opts.get("headers"), dict) else {},
        "body": opts.get("body", NONE),
        "context": opts.get("context") if isinstance(
            opts.get("context"), dict) else {},
    }

    def run_handler(req_obj, ectx):
        c = ectx.child()
        c.vars["request"] = req_obj
        try:
            out = evaluate(action.then, c)
        except ReturnException as r:
            out = r.value
        return _normalize_response(out)

    def run(i, req_obj, ectx):
        if i == len(mws):
            return run_handler(req_obj, ectx)
        name, argexprs = mws[i]
        args = [evaluate(a, ectx) for a in argexprs]
        if name in ("api::timeout", "timeout"):
            from surrealdb_tpu.val import Duration

            inner = ectx.child()
            if args and isinstance(args[0], Duration):
                inner.deadline = _time.monotonic() + args[0].ns / 1e9
                inner.timeout_dur = args[0]
            res = run(i + 1, req_obj, inner)
            if inner.deadline is not None and \
                    _time.monotonic() > inner.deadline:
                raise _ApiError(500, "deadline has elapsed")
            return res
        if name == "api::req::body":
            strategy = str(args[0]).lower() if args else "auto"
            return run(i + 1, _apply_req_body(strategy, req_obj), ectx)
        if name == "api::req::max_body":
            from surrealdb_tpu.val import Duration as _D  # noqa: F401

            limit = args[0] if args else None
            body = req_obj.get("body")
            nbytes = None
            if isinstance(body, (bytes, bytearray)):
                nbytes = len(body)
            if limit is not None and nbytes is not None:
                try:
                    lim = int(limit)
                except (TypeError, ValueError):
                    lim = None
                if lim is not None and nbytes > lim:
                    raise _ApiError(413, "Request body too large")
            return run(i + 1, req_obj, ectx)
        if name == "api::res::status":
            res = run(i + 1, req_obj, ectx)
            return {**res, "status": _validate_status(
                args[0] if args else 200)}
        if name == "api::res::header":
            res = run(i + 1, req_obj, ectx)
            if len(args) >= 2:
                lk, lv = _validate_header(args[0], args[1])
                headers = dict(res.get("headers") or {})
                headers[lk] = lv
                res = {**res, "headers": headers}
            return res
        if name == "api::res::headers":
            res = run(i + 1, req_obj, ectx)
            if args and isinstance(args[0], dict):
                headers = dict(res.get("headers") or {})
                for k, v in args[0].items():
                    if v is NONE or v is None:
                        headers.pop(str(k).lower(), None)
                    else:
                        lk, lv = _validate_header(k, v)
                        headers[lk] = lv
                res = {**res, "headers": headers}
            return res
        if name == "api::res::body":
            strategy = str(args[0]).lower() if args else "auto"
            res = run(i + 1, req_obj, ectx)
            return _apply_res_body(strategy, res, req_obj)
        if name.startswith("fn::"):
            from surrealdb_tpu.fnc import call_custom

            nxt = _HostNext(
                lambda a, c, _i=i: _normalize_response(
                    run(_i + 1, a[0] if a else req_obj, ectx)
                )
            )
            out = call_custom(name[4:], [req_obj, nxt] + args, ectx)
            return _normalize_response(out)
        raise SdbError(f"Unknown API middleware '{name}'")

    try:
        res = run(0, req, ctx)
        return _finalize(res)
    except _ApiError as e:
        return {"status": e.status, "body": e.body, "headers": {}}
    except SdbError as e:
        msg = str(e)
        if "exceeded the timeout" in msg:
            return {"status": 500, "body": msg, "headers": {}}
        return {"status": 500, "body": NONE, "headers": {}}
