"""Statement loop + transaction management (reference: dbs/executor.rs).

Each statement outside BEGIN/COMMIT runs in its own transaction; inside an
explicit transaction all statements share one, and a failure poisons the
remainder until COMMIT/CANCEL (reference Executor behaviour)."""

from __future__ import annotations

import time

from surrealdb_tpu.err import (
    BreakException,
    ContinueException,
    ReturnException,
    SdbError,
    ThrownError,
)
from surrealdb_tpu.exec.context import Ctx
from surrealdb_tpu.exec.statements import eval_statement
from surrealdb_tpu.expr.ast import (
    BeginStmt,
    CancelStmt,
    CommitStmt,
    LetStmt,
    OptionStmt,
    UseStmt,
)
from surrealdb_tpu.kvs.ds import QueryResult
from surrealdb_tpu.val import NONE


class Executor:
    def __init__(self, ds, session):
        self.ds = ds
        self.session = session

    def _read_staleness(self, stmt, shared_vars):
        """Bounded-staleness opt-in for ONE auto-transaction statement:
        a SELECT's `READ AT <duration>` clause, else the session-level
        `max_staleness` default. Returns seconds or None (exact read —
        the default, byte-identical to the primary-pinned path)."""
        from surrealdb_tpu.expr.ast import SelectStmt

        if not isinstance(stmt, SelectStmt):
            return None
        expr = getattr(stmt, "read_at", None)
        if expr is None:
            return self.session.max_staleness
        from surrealdb_tpu.exec.eval import evaluate
        from surrealdb_tpu.val import Duration, render

        # READ AT is resolved BEFORE the transaction opens (it decides
        # which kind to open), so it evaluates txn-free: literals and
        # $params only, like the reference's statement-level options —
        # anything that needs the store (a subquery, an idiom) is a
        # TYPED error, not an internal crash on the missing txn
        ctx = Ctx(self.ds, self.session, None, executor=self)
        ctx.vars.update(shared_vars)
        try:
            d = evaluate(expr, ctx)
        except SdbError:
            raise
        except Exception:
            raise SdbError(
                "READ AT expects a literal duration or $param "
                "(subqueries and record access are not allowed here)"
            )
        if isinstance(d, Duration):
            return max(d.to_seconds(), 0.0)
        if isinstance(d, (int, float)) and not isinstance(d, bool):
            return max(float(d), 0.0)
        raise SdbError(
            f"READ AT expects a duration but found {render(d)}"
        )

    def _commit_and_publish(self, txn):
        """Commit, then hand the transaction's captured live events to
        the fan-out dispatch workers (server/fanout.py). A transaction
        with events commits under the hub's commit-order lock: publish
        order must equal commit order, and a GIL handoff between
        commit() and publish() would let a racing writer's later commit
        publish first (subscriber state diverging from the table with
        no OVERFLOW). Unwatched transactions — no captured events —
        commit without the lock. A cancelled transaction publishes
        nothing, so subscribers never see uncommitted mutations."""
        events = getattr(txn, "_live_events", None)
        if not events:
            txn.commit()
            return
        txn._live_events = None
        fanout = self.ds.fanout
        with fanout.commit_order_lock:
            txn.commit()
            fanout.publish(events)

    @staticmethod
    def _truncate_lives(txn, n: int):
        events = getattr(txn, "_live_events", None)
        if events is not None and len(events) > n:
            del events[n:]

    def execute(self, stmts: list, vars: dict) -> list[QueryResult]:
        tel = self.ds.telemetry
        root = tel.start("query", statements=len(stmts))
        try:
            return self._execute(stmts, vars, tel)
        finally:
            tel.end(root)

    def _execute(self, stmts: list, vars: dict, tel) -> list[QueryResult]:
        from surrealdb_tpu import cnf as _cnf
        from surrealdb_tpu import inflight as _inflight
        from surrealdb_tpu.exec.statements import _ensure_ns_db
        from surrealdb_tpu.telemetry import stage_record

        results: list[QueryResult] = []
        self.import_mode = False  # OPTION IMPORT, scoped to this run
        # the edge deadline + cancel flag ride the thread's QueryHandle
        # (kvs/ds.py execute registers it); every statement ctx inherits
        handle = _inflight.current()
        txn = None  # explicit transaction, if open
        ensured_nsdb = False
        failed = False  # explicit txn poisoned
        returned = False  # top-level RETURN inside the txn: skip to COMMIT
        buffered: list[int] = []  # result idxs inside current explicit txn
        shared_vars = dict(self.session.variables)
        shared_vars.update(vars)
        for stmt in stmts:
            t0 = time.perf_counter_ns()
            if isinstance(stmt, BeginStmt):
                if txn is None:
                    txn = self.ds.transaction(write=True)
                    failed = False
                    returned = False
                    buffered = []
                    results.append(QueryResult(result=NONE))
                else:
                    results.append(
                        QueryResult(
                            error="Cannot BEGIN a transaction within a transaction"
                        )
                    )
                continue
            if isinstance(stmt, CommitStmt):
                if txn is not None:
                    if failed:
                        txn.cancel()
                        for i in buffered:
                            if results[i].error is None:
                                results[i] = QueryResult(
                                    error="The query was not executed due to a failed transaction"
                                )
                        results.append(
                            QueryResult(
                                error="Cannot COMMIT: the transaction was aborted due to a prior error"
                            )
                        )
                    else:
                        self._commit_and_publish(txn)
                        results.append(QueryResult(result=NONE))
                    txn = None
                else:
                    results.append(
                        QueryResult(
                            error="Invalid statement: Cannot COMMIT without starting a transaction"
                        )
                    )
                continue
            if isinstance(stmt, CancelStmt):
                if txn is not None:
                    txn.cancel()
                    for i in buffered:
                        results[i] = QueryResult(
                            error="The query was not executed due to a cancelled transaction"
                        )
                    txn = None
                    results.append(QueryResult(result=NONE))
                else:
                    results.append(
                        QueryResult(
                            error="Invalid statement: Cannot CANCEL without starting a transaction"
                        )
                    )
                continue
            if txn is not None and returned:
                # a top-level RETURN ends the transaction's statement run:
                # the rest (until COMMIT/CANCEL) neither executes nor
                # reports (statements/return/breaks_nested_execution)
                continue
            if txn is not None and failed:
                # statements after the failing one report the transaction as
                # cancelled (the failure itself reported the real error)
                results.append(
                    QueryResult(
                        error="The query was not executed due to a cancelled transaction"
                    )
                )
                continue
            if _cnf.MEMORY_THRESHOLD:
                from surrealdb_tpu.mem import check_threshold

                try:
                    check_threshold()
                except SdbError as e:
                    results.append(QueryResult(error=str(e)))
                    continue
            if handle is not None and handle.cancel.is_set():
                # a KILL / disconnect / drain cancels the REMAINING
                # statements too — they never start, and an open explicit
                # transaction is poisoned exactly as if the cancel had
                # landed DURING a statement (COMMIT must not persist a
                # half-done transaction the client was told was cancelled)
                handle.mark_cancelled()
                failed = txn is not None or failed
                results.append(QueryResult(error="The query was cancelled"))
                continue
            if handle is not None and handle.deadline is not None and \
                    time.monotonic() > handle.deadline:
                handle.mark_timed_out()
                failed = txn is not None or failed
                results.append(QueryResult(
                    error="The query was not executed because it "
                          "exceeded the timeout"
                ))
                continue
            own_txn = txn is None
            # pre-statement live-event watermark (savepoint rollback
            # truncates to it; set before the try so an error raised
            # ahead of new_save_point still finds it bound)
            n_lives = len(getattr(txn, "_live_events", None) or ()) \
                if txn is not None else 0
            try:
                if own_txn:
                    t_txn = time.perf_counter_ns()
                    # READ AT / session max_staleness: the statement
                    # runs READ-ONLY and may be served by a replica
                    # that proves the bound (closed-timestamp follower
                    # reads, kvs/remote.py). Exact statements take the
                    # unchanged write=True path.
                    stale_s = self._read_staleness(stmt, shared_vars)
                    if stale_s is not None:
                        cur = self.ds.transaction(
                            write=False, max_staleness=stale_s
                        )
                    else:
                        cur = self.ds.transaction(write=True)
                    stage_record("txn_open",
                                 time.perf_counter_ns() - t_txn)
                else:
                    if getattr(stmt, "read_at", None) is not None:
                        raise SdbError(
                            "READ AT cannot be used inside an "
                            "explicit transaction"
                        )
                    cur = txn
            except SdbError as e:
                # a transaction that cannot OPEN (remote KV unreachable /
                # retry deadline exhausted) is a per-statement error, not
                # a crashed query: the worker thread must be reclaimed
                # and the client must see the typed message
                self.ds.record_statement(
                    False, time.perf_counter_ns() - t0, type(stmt).__name__
                )
                results.append(QueryResult(error=str(e)))
                continue
            ctx = Ctx(self.ds, self.session, cur, executor=self)
            if handle is not None:
                ctx.deadline = handle.deadline
                ctx.cancel = handle.cancel
                ctx.inflight = handle
            ctx.vars.update(shared_vars)
            # per-statement mailbox for the sharded KNN partial flag
            # (idx/shardvec.py writes it; the QueryResult carries it)
            self._knn_partial = None
            try:
                if self.session.ns and self.session.db and not ensured_nsdb:
                    # non-strict mode lazily registers the session ns/db in
                    # the catalog (reference kvs get_or_add_ns/db); once per
                    # run — inside the error envelope: a partitioned KV
                    # must surface as a statement error, not a crash.
                    # A follower-read statement holds a READ-ONLY txn,
                    # so the one-time registration commits separately.
                    if not getattr(cur, "write", True):
                        wtx = self.ds.transaction(write=True)
                        try:
                            _ensure_ns_db(Ctx(self.ds, self.session,
                                              wtx, executor=self))
                            wtx.commit()
                        except BaseException:
                            wtx.cancel()
                            raise
                    else:
                        _ensure_ns_db(ctx)
                if not own_txn:
                    # savepoints only matter inside an explicit
                    # transaction (a failing statement rolls back to the
                    # last one); an auto-commit statement cancels its
                    # whole transaction on error, so the happy path
                    # skips the create/release pair entirely
                    cur.new_save_point()
                sp = tel.start(type(stmt).__name__)
                t_eval = time.perf_counter_ns()
                try:
                    out = eval_statement(stmt, ctx)
                finally:
                    eval_ns = time.perf_counter_ns() - t_eval
                    stage_record("stmt_eval", eval_ns)
                    tel.end(sp)
                if not own_txn:
                    cur.release_last_save_point()
                # persist session-level vars (LET/USE at top level)
                if isinstance(stmt, (LetStmt,)):
                    shared_vars = dict(ctx.vars)
                    self.session.variables[stmt.name] = ctx.vars.get(stmt.name)
                elif isinstance(stmt, UseStmt):
                    pass  # session mutated in place
                if own_txn:
                    self._commit_and_publish(cur)
                ensured_nsdb = True
                dt = time.perf_counter_ns() - t0
                # envelope = statement machinery around the evaluation
                # (txn plumbing, cancel/deadline gates, result wrap)
                stage_record("stmt_envelope", max(dt - eval_ns, 0))
                self.ds.record_statement(True, dt, type(stmt).__name__)
                qr = QueryResult(result=out, time_ns=dt)
                if getattr(self, "_knn_partial", None):
                    # a sharded KNN answered without these index shards
                    # (SURREAL_KNN_PARTIAL=partial): the flag rides the
                    # statement result so no client can mistake a
                    # partial candidate set for a complete one
                    qr.partial = {"missing_shards": self._knn_partial}
                    self._knn_partial = None
                results.append(qr)
                if not own_txn:
                    buffered.append(len(results) - 1)
            except ReturnException as r:
                if own_txn:
                    self._commit_and_publish(cur)
                results.append(
                    QueryResult(result=r.value, time_ns=time.perf_counter_ns() - t0)
                )
                if not own_txn:
                    buffered.append(len(results) - 1)
                    returned = True
            except (BreakException, ContinueException):
                msg = ("Invalid control flow statement, break or continue statement "
                       "found outside of loop.")
                if own_txn:
                    cur.cancel()
                results.append(QueryResult(error=msg))
            except (SdbError, ThrownError) as e:
                if own_txn:
                    cur.cancel()
                else:
                    cur.rollback_to_save_point()
                    self._truncate_lives(cur, n_lives)
                    failed = True
                self.ds.record_statement(
                    False, time.perf_counter_ns() - t0, type(stmt).__name__
                )
                results.append(QueryResult(error=str(e)))
                if not own_txn:
                    buffered.append(len(results) - 1)
            except RecursionError:
                if own_txn:
                    cur.cancel()
                results.append(QueryResult(error="Max computation depth exceeded"))
            except Exception as e:  # internal error — surface, don't crash
                if own_txn:
                    cur.cancel()
                else:
                    cur.rollback_to_save_point()
                    self._truncate_lives(cur, n_lives)
                    failed = True
                results.append(
                    QueryResult(error=f"Internal error: {e.__class__.__name__}: {e}")
                )
                if not own_txn:
                    buffered.append(len(results) - 1)
        if txn is not None:
            # unterminated explicit transaction: cancel
            txn.cancel()
            for i in buffered:
                results[i] = QueryResult(
                    error="The query was not executed due to a cancelled transaction"
                )
        return results
