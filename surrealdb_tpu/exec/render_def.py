"""Canonical SQL text + INFO STRUCTURE forms for catalog definitions.

Formats match the reference's ToSql/InfoStructure impls exactly
(sql/statements/define/*.rs fmt_sql, catalog/*.rs InfoStructure) so INFO FOR
output is byte-compatible and usable as an import script."""

from __future__ import annotations

from surrealdb_tpu.val import NONE, Duration, escape_ident


def _expr_sql(node) -> str:
    """Canonical text of an expression AST (reference CoverStmts rendering)."""
    from surrealdb_tpu.expr.ast import (
        ArrayExpr,
        Binary,
        BlockExpr,
        Cast,
        ClosureExpr,
        Constant,
        FunctionCall,
        Idiom,
        IfElse,
        Knn,
        Literal,
        Matches,
        Mock,
        ObjectExpr,
        Param,
        PField,
        Prefix,
        SetExpr,
        RangeExpr,
        RecordIdLit,
        RegexLit,
        SelectStmt,
        Subquery,
    )
    from surrealdb_tpu.val import render

    if node is None:
        return ""
    if isinstance(node, Literal):
        return render(node.value)
    if isinstance(node, Param):
        return f"${node.name}"
    if isinstance(node, Binary):
        op = {"&&": "AND", "||": "OR", "∈": "INSIDE", "∉": "NOT INSIDE",
              "∋": "CONTAINS", "∌": "CONTAINSNOT", "⊇": "CONTAINSALL",
              "⊆": "ALLINSIDE", "containsany": "CONTAINSANY",
              "containsnone": "CONTAINSNONE", "anyinside": "ANYINSIDE",
              "noneinside": "NONEINSIDE"}.get(node.op, node.op)
        return f"{_expr_sql(node.lhs)} {op} {_expr_sql(node.rhs)}"
    if isinstance(node, Prefix):
        if node.op == "!":
            return f"! {_expr_sql(node.expr)}"
        return f"{node.op}{_expr_sql(node.expr)}"
    if isinstance(node, RegexLit):
        return f"/{node.pattern}/"
    if isinstance(node, Matches):
        op = f"@{node.ref}@" if node.ref is not None else "@@"
        return f"{_expr_sql(node.lhs)} {op} {_expr_sql(node.rhs)}"
    if isinstance(node, Knn):
        if node.ef is not None:
            return f"{_expr_sql(node.lhs)} <|{node.k},{node.ef}|> {_expr_sql(node.rhs)}"
        if node.dist is not None:
            d = node.dist
            ds = f"MINKOWSKI {d[1]}" if isinstance(d, tuple) else d.upper()
            return f"{_expr_sql(node.lhs)} <|{node.k},{ds}|> {_expr_sql(node.rhs)}"
        return f"{_expr_sql(node.lhs)} <|{node.k}|> {_expr_sql(node.rhs)}"
    if isinstance(node, FunctionCall):
        args = ", ".join(_expr_sql(a) for a in node.args)
        return f"{node.name}({args})"
    if isinstance(node, Idiom):
        from surrealdb_tpu.exec.statements import expr_name

        parts = node.parts
        if parts and isinstance(parts[0], tuple) and parts[0][0] == "start":
            head = _expr_sql(parts[0][1])
            rest = (
                expr_name(Idiom(list(parts[1:])), sql=True)
                if len(parts) > 1 else ""
            )
            if not rest:
                return head
            sep = "" if rest.startswith(("[", "-", "<")) else "."
            return head + sep + rest
        return expr_name(node, sql=True)
    if isinstance(node, ArrayExpr):
        return "[" + ", ".join(_expr_sql(x) for x in node.items) + "]"
    if isinstance(node, ObjectExpr):
        if not node.items:
            return "{  }"
        inner = ", ".join(f"{escape_ident(k)}: {_expr_sql(v)}" for k, v in node.items)
        return "{ " + inner + " }"
    if isinstance(node, SetExpr):
        if not node.items:
            return "{,}"
        return "{" + ", ".join(_expr_sql(x) for x in node.items) + "}"
    if isinstance(node, RecordIdLit):
        from surrealdb_tpu.val import render_record_id_key

        idv = node.id
        if isinstance(idv, Literal):
            return f"{escape_ident(node.tb)}:{render_record_id_key(idv.value)}"
        return f"{escape_ident(node.tb)}:{_expr_sql(idv)}"
    if isinstance(node, RangeExpr):
        beg = _expr_sql(node.beg) if node.beg is not None else ""
        end = _expr_sql(node.end) if node.end is not None else ""
        op = "..=" if node.end_incl else ".."
        if not node.beg_incl:
            beg += ">"
        return f"{beg}{op}{end}"
    if isinstance(node, Subquery):
        return f"({_expr_sql(node.stmt)})"
    if isinstance(node, BlockExpr):
        if not node.stmts:
            return "{  }"
        if len(node.stmts) == 1:
            return "{ " + _expr_sql(node.stmts[0]) + " }"
        return "{ " + "; ".join(_expr_sql(s) for s in node.stmts) + "; }"
    if isinstance(node, Constant):
        return node.name
    if isinstance(node, Cast):
        from surrealdb_tpu.exec.coerce import kind_name

        return f"<{kind_name(node.kind)}> {_expr_sql(node.expr)}"
    if isinstance(node, ClosureExpr):
        from surrealdb_tpu.exec.coerce import kind_name

        ps = ", ".join(
            f"${n}: " + (kind_name(k) if k is not None else "any")
            for n, k in node.params
        )
        ret = f" -> {kind_name(node.returns)}" if node.returns else ""
        body = node.body
        if isinstance(body, Subquery):
            from surrealdb_tpu.expr.ast import BlockExpr as _Blk

            if isinstance(body.stmt, _Blk):
                body = body.stmt
        return f"|{ps}|{ret} {_expr_sql(body)}"
    if isinstance(node, IfElse):
        bodies = [b for _c, b in node.branches]
        if node.otherwise is not None:
            bodies.append(node.otherwise)
        blocky = all(
            isinstance(b, BlockExpr)
            or (isinstance(b, Subquery) and isinstance(b.stmt, BlockExpr))
            for b in bodies
        )
        out = []
        for i, (cond, body) in enumerate(node.branches):
            kw = "IF" if i == 0 else "ELSE IF"
            if blocky:
                out.append(f"{kw} {_expr_sql(cond)} {_expr_sql(body)}")
            else:
                out.append(f"{kw} {_expr_sql(cond)} THEN {_expr_sql(body)}")
        if node.otherwise is not None:
            out.append(f"ELSE {_expr_sql(node.otherwise)}")
        if not blocky:
            out.append("END")
        return " ".join(out)
    if isinstance(node, Mock):
        if node.end is not None:
            return f"|{node.tb}:{node.beg}..{node.end}|"
        return f"|{node.tb}:{node.beg}|"
    if isinstance(node, SelectStmt):
        return _select_sql(node)
    # statements in expression position
    from surrealdb_tpu.expr.ast import (
        CreateStmt,
        DeleteStmt,
        LetStmt,
        RelateStmt,
        ReturnStmt,
        UpdateStmt,
        UpsertStmt,
    )

    if isinstance(node, ReturnStmt):
        return f"RETURN {_expr_sql(node.what)}"
    if isinstance(node, LetStmt):
        return f"LET ${node.name} = {_expr_sql(node.what)}"
    if isinstance(node, CreateStmt):
        return "CREATE " + ", ".join(_expr_sql(w) for w in node.what) + _data_sql(node.data)
    if isinstance(node, (UpdateStmt, UpsertStmt)):
        kw = "UPDATE" if isinstance(node, UpdateStmt) else "UPSERT"
        out = f"{kw} " + ", ".join(_expr_sql(w) for w in node.what) + _data_sql(node.data)
        if node.cond is not None:
            out += f" WHERE {_expr_sql(node.cond)}"
        return out
    if isinstance(node, DeleteStmt):
        out = "DELETE " + ", ".join(_expr_sql(w) for w in node.what)
        if node.cond is not None:
            out += f" WHERE {_expr_sql(node.cond)}"
        return out
    if isinstance(node, RelateStmt):
        return (
            f"RELATE {_expr_sql(node.from_)} -> {_expr_sql(node.kind)} -> "
            f"{_expr_sql(node.to)}" + _data_sql(node.data)
        )
    return str(node)


def _data_sql(data) -> str:
    from surrealdb_tpu.expr.ast import (
        ContentData,
        MergeData,
        PatchData,
        ReplaceData,
        SetData,
        UnsetData,
    )

    if data is None:
        return ""
    if isinstance(data, SetData):
        items = ", ".join(
            f"{_expr_sql(t)} {op} {_expr_sql(e)}" for t, op, e in data.items
        )
        return f" SET {items}"
    if isinstance(data, ContentData):
        return f" CONTENT {_expr_sql(data.expr)}"
    if isinstance(data, ReplaceData):
        return f" REPLACE {_expr_sql(data.expr)}"
    if isinstance(data, MergeData):
        return f" MERGE {_expr_sql(data.expr)}"
    if isinstance(data, PatchData):
        return f" PATCH {_expr_sql(data.expr)}"
    if isinstance(data, UnsetData):
        return " UNSET " + ", ".join(_expr_sql(f) for f in data.fields)
    return ""


def _select_sql(node) -> str:
    from surrealdb_tpu.exec.statements import expr_name

    if node.value is not None:
        fields = f"VALUE {_expr_sql(node.value)}"
    else:
        fields = ", ".join(
            "*" if e == "*" else (_expr_sql(e) + (f" AS {a}" if a else ""))
            for e, a in node.exprs
        )
    whats = ", ".join(_expr_sql(w) for w in node.what)
    out = f"SELECT {fields} FROM {whats}"
    if node.cond is not None:
        out += f" WHERE {_expr_sql(node.cond)}"
    if node.split:
        out += " SPLIT " + ", ".join(_expr_sql(s) for s in node.split)
    if node.group is not None:
        if node.group:
            out += " GROUP BY " + ", ".join(_expr_sql(g) for g in node.group)
        else:
            out += " GROUP ALL"
    if node.order:
        if node.order == "rand":
            out += " ORDER BY RAND()"
        else:
            items = []
            for expr, d, collate, numeric in node.order:
                s = _expr_sql(expr)
                if collate:
                    s += " COLLATE"
                if numeric:
                    s += " NUMERIC"
                if d == "desc":
                    s += " DESC"
                items.append(s)
            out += " ORDER BY " + ", ".join(items)
    if node.limit is not None:
        out += f" LIMIT {_expr_sql(node.limit)}"
    if node.start is not None:
        out += f" START {_expr_sql(node.start)}"
    if node.fetch:
        out += " FETCH " + ", ".join(_expr_sql(f) for f in node.fetch)
    return out


def _kind_sql(kind) -> str:
    from surrealdb_tpu.exec.coerce import kind_name

    return kind_name(kind)


# ---------------------------------------------------------------------------
# permissions
# ---------------------------------------------------------------------------

_ACTIONS = ("select", "create", "update", "delete")


def _perm_of(perms, action, default):
    if perms is None:
        return default
    return perms.get(action, default)


def _perms_sql(perms, default=False, field=False) -> str:
    """Reference sql/permission.rs fmt_sql: NONE / FULL / grouped FOR.
    Fields don't track delete (implicitly Full), so all-NONE field perms
    never collapse to the bare NONE form."""
    actions = _ACTIONS[:3] if field else _ACTIONS
    vals = {a: _perm_of(perms, a, default) for a in _ACTIONS}
    considered = [vals[a] for a in actions]
    if field:
        vals["delete"] = True
    if all(v is False for v in considered) and vals["delete"] is False:
        return "PERMISSIONS NONE"
    if all(v is True for v in considered) and vals["delete"] is True:
        return "PERMISSIONS FULL"
    # group kinds by identical permission, order select, create, update, delete
    lines = []
    order = ["select", "create", "update"] + ([] if field else ["delete"])
    for a in order:
        v = vals[a]
        if a == "delete" and v is True:
            continue  # delete Full skipped (catalog fields don't track it)
        placed = False
        for entry in lines:
            if _perm_eq(entry[1], v):
                entry[0].append(a)
                placed = True
                break
        if not placed:
            lines.append(([a], v))
    parts = []
    for kinds, v in lines:
        ks = ", ".join(kinds)
        if v is True:
            parts.append(f"FOR {ks} FULL")
        elif v is False:
            parts.append(f"FOR {ks} NONE")
        else:
            parts.append(f"FOR {ks} WHERE {_expr_sql(v)}")
    return "PERMISSIONS " + ", ".join(parts)


def _perm_eq(a, b):
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    # WHERE permissions group when structurally equal (reference compares
    # the Permission values, not identities)
    return _expr_sql(a) == _expr_sql(b)


def _perm_structure(v):
    if v is True:
        return True
    if v is False:
        return False
    return _expr_sql(v)


def perms_structure(perms, default=False, field=False):
    actions = _ACTIONS[:3] if field else _ACTIONS
    return {
        a: _perm_structure(_perm_of(perms, a, default)) for a in actions
    }


# ---------------------------------------------------------------------------
# canonical DEFINE statements
# ---------------------------------------------------------------------------


def render_ns(d) -> str:
    out = f"DEFINE NAMESPACE {escape_ident(d.name)}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def _str_sql(s) -> str:
    from surrealdb_tpu.val import escape_string

    return escape_string(s)


def render_db(d) -> str:
    out = f"DEFINE DATABASE {escape_ident(d.name)}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    if d.changefeed:
        out += f" CHANGEFEED {Duration(d.changefeed).render()}"
    return out


def render_table(d) -> str:
    out = f"DEFINE TABLE {escape_ident(d.name)} TYPE"
    if d.kind == "any":
        out += " ANY"
    elif d.kind == "relation":
        out += " RELATION"
        if d.relation_from:
            out += " IN " + " | ".join(escape_ident(x) for x in d.relation_from)
        if d.relation_to:
            out += " OUT " + " | ".join(escape_ident(x) for x in d.relation_to)
        if d.enforced:
            out += " ENFORCED"
    else:
        out += " NORMAL"
    if d.drop:
        out += " DROP"
    out += " SCHEMAFULL" if d.full else " SCHEMALESS"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    if d.view is not None:
        out += f" AS {_expr_sql(d.view)}"
    if d.changefeed:
        out += f" CHANGEFEED {Duration(d.changefeed).render()}"
        if d.changefeed_original:
            out += " INCLUDE ORIGINAL"
    out += " " + _perms_sql(d.permissions, default=False)
    return out


def table_structure(d) -> dict:
    out = {
        "id": getattr(d, "table_id", 0),
        "name": d.name,
        "drop": d.drop,
        "schemafull": d.full,
        "kind": _table_kind_structure(d),
        "permissions": perms_structure(d.permissions, default=False),
    }
    if d.view is not None:
        out["view"] = _expr_sql(d.view)
    if d.changefeed:
        out["changefeed"] = {
            "expiry": Duration(d.changefeed).render(),
            "original": d.changefeed_original,
        }
    if d.comment:
        out["comment"] = d.comment
    return out


def _table_kind_structure(d):
    if d.kind == "relation":
        out = {"kind": "RELATION"}
        if d.relation_from:
            out["in"] = d.relation_from
        if d.relation_to:
            out["out"] = d.relation_to
        out["enforced"] = d.enforced
        return out
    return {"kind": d.kind.upper()}


def _field_seg_sql(seg: str, keyish: bool) -> str:
    """One dot-segment of a field name. Bracket suffixes ([1], [*]) and a
    trailing flatten ellipsis stay OUTSIDE the ident escaping (reference
    renders `index[1]` and `flatten…` bare)."""
    import re as _re3

    from surrealdb_tpu.val import escape_rid_table

    m = _re3.match(r"^(.*?)((?:\[[^\]]*\])*)(\u2026?)$", seg)
    base, brackets, flat = m.group(1), m.group(2), m.group(3)
    if base == "*" or (base == "" and (brackets or flat)):
        return seg
    esc = escape_rid_table(base) if keyish else escape_ident(base)
    return esc + brackets + flat


def _field_name_sql(name_str: str) -> str:
    # escape each dot segment independently (`value`.sub stays quoted)
    parts = []
    for seg in name_str.split("."):
        if seg == "*" or seg.startswith("["):
            parts.append(seg)
        else:
            parts.append(_field_seg_sql(seg, keyish=False))
    return ".".join(parts)


def field_name_key(name_str: str) -> str:
    """INFO map key for a field: quote only lexically-invalid segments
    (keywords stay bare — reference EscapeKey, not EscapeIdent)."""
    from surrealdb_tpu.val import escape_rid_table

    parts = []
    for seg in name_str.split("."):
        if seg == "*" or seg.startswith("["):
            parts.append(seg)
        else:
            parts.append(_field_seg_sql(seg, keyish=True))
    return ".".join(parts)


def render_field(d, tb) -> str:
    out = f"DEFINE FIELD {_field_name_sql(d.name_str)} ON {escape_ident(tb)}"
    if d.kind is not None:
        out += f" TYPE {_kind_sql(d.kind)}"
        if d.flex:
            out += " FLEXIBLE"
    if d.default is not None:
        out += " DEFAULT"
        if d.default_always:
            out += " ALWAYS"
        out += f" {_expr_sql(d.default)}"
    if d.readonly:
        out += " READONLY"
    if d.value is not None:
        out += f" VALUE {_expr_sql(d.value)}"
    if d.assert_ is not None:
        out += f" ASSERT {_expr_sql(d.assert_)}"
    if d.computed is not None:
        comp = d.computed
        from surrealdb_tpu.expr.ast import BlockExpr as _Blk2
        from surrealdb_tpu.expr.ast import Subquery as _Sub2

        if isinstance(comp, _Sub2) and isinstance(comp.stmt, _Blk2):
            comp = comp.stmt  # COMPUTED { a } renders without parens
        out += f" COMPUTED {_expr_sql(comp)}"
    if d.reference is not None:
        out += " REFERENCE ON DELETE " + d.reference.get(
            "on_delete", "ignore"
        ).upper()
        if d.reference.get("on_delete") == "then":
            out += f" {_expr_sql(d.reference.get('then'))}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    out += " " + _perms_sql(d.permissions, default=True, field=True)
    return out


def field_structure(d, tb) -> dict:
    out = {"name": d.name_str, "table": tb}
    if d.kind is not None:
        out["kind"] = _kind_sql(d.kind)
    if d.flex:
        out["flexible"] = True
    if d.value is not None:
        out["value"] = _expr_sql(d.value)
    if d.assert_ is not None:
        out["assert"] = _expr_sql(d.assert_)
    if d.computed is not None:
        out["computed"] = _expr_sql(d.computed)
    if d.default is not None:
        out["default_always"] = d.default_always
        out["default"] = _expr_sql(d.default)
    out["readonly"] = d.readonly
    out["permissions"] = perms_structure(d.permissions, default=True, field=True)
    if d.comment:
        out["comment"] = d.comment
    return out


def render_index(d) -> str:
    out = f"DEFINE INDEX {escape_ident(d.name)} ON {escape_ident(d.tb)}"
    if d.cols_str:
        out += " FIELDS " + ", ".join(d.cols_str)
    if d.unique:
        out += " UNIQUE"
    if d.count:
        out += " COUNT"
        if getattr(d, "count_cond", None) is not None:
            out += f" WHERE {_expr_sql(d.count_cond)}"
    if d.fulltext is not None:
        ft = d.fulltext
        out += f" FULLTEXT ANALYZER {ft.get('analyzer')}"
        k1, b = ft.get("bm25", (1.2, 0.75))
        out += f" BM25({k1},{b})"
        if ft.get("highlights"):
            out += " HIGHLIGHTS"
    if d.hnsw is not None:
        h = d.hnsw
        dist = h.get("distance", "euclidean")
        dist_s = (
            f"MINKOWSKI {dist[1]}" if isinstance(dist, tuple) else dist.upper()
        )
        out += (
            f" HNSW DIMENSION {h.get('dimension')} DIST {dist_s}"
            f" TYPE {h.get('vector_type', 'f32').upper()}"
            f" EFC {h.get('ef_construction', 150)} M {h.get('m', 12)}"
            f" M0 {h.get('m0', 24)}"
        )
        import math as _m

        ml = h.get("ml")
        if ml is None:
            ml = 1.0 / _m.log(h.get("m", 12))
        from surrealdb_tpu.val import render as _render

        out += f" LM {_render(float(ml))}"
        if h.get("extend_candidates"):
            out += " EXTEND_CANDIDATES"
        if h.get("keep_pruned_connections"):
            out += " KEEP_PRUNED_CONNECTIONS"
        if h.get("use_hashed_vector"):
            out += " HASHED_VECTOR"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def index_structure(d) -> dict:
    out = {"name": d.name, "table": d.tb, "cols": list(d.cols_str)}
    if d.unique:
        out["index"] = "UNIQUE"
    elif d.count:
        out["index"] = "COUNT"
    elif d.fulltext is not None:
        out["index"] = "FULLTEXT"
    elif d.hnsw is not None:
        out["index"] = "HNSW"
    else:
        out["index"] = "IDX"
    if getattr(d, "prepare_remove", False):
        out["prepare_remove"] = True
    if d.comment:
        out["comment"] = d.comment
    return out


def render_event(d, tb) -> str:
    def wrap(t):
        from surrealdb_tpu.expr.ast import BlockExpr as _Blk, Subquery as _Sub

        if isinstance(t, _Sub) and isinstance(t.stmt, _Blk):
            t = t.stmt
        x = _expr_sql(t)
        from surrealdb_tpu.expr.ast import Idiom as _Idm, Literal as _Lit

        if isinstance(t, (_Lit, _Idm)):
            return x  # plain values/idioms render bare: THEN bla
        return x if x.startswith(("(", "{")) else f"({x})"

    then = ", ".join(wrap(t) for t in d.then)
    attrs = ""
    if getattr(d, "async_", False):
        retry = getattr(d, "retry", None)
        maxdepth = getattr(d, "maxdepth", None)
        attrs = (
            f" ASYNC RETRY {1 if retry is None else retry} "
            f"MAXDEPTH {3 if maxdepth is None else maxdepth}"
        )
    out = (
        f"DEFINE EVENT {escape_ident(d.name)} ON {escape_ident(tb)}{attrs} "
        f"WHEN {_expr_sql(d.when) if d.when is not None else 'true'} THEN {then}"
    )
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def event_structure(d, tb) -> dict:
    return {
        "name": d.name,
        "what": tb,
        "when": _expr_sql(d.when) if d.when is not None else "true",
        "then": [_expr_sql(t) for t in d.then],
    }


def render_param(d) -> str:
    from surrealdb_tpu.val import render as vr

    out = f"DEFINE PARAM ${d.name} VALUE {vr(d.value)}"
    if d.comment is not None:
        out += f" COMMENT {_str_sql(d.comment)}"
    p = d.permissions
    if p is True or p is None:
        out += " PERMISSIONS FULL"
    elif p is False:
        out += " PERMISSIONS NONE"
    else:
        out += f" PERMISSIONS WHERE {_expr_sql(p)}"
    return out


def render_function(d) -> str:
    from surrealdb_tpu.exec.coerce import kind_name

    args = ", ".join(f"${n}: {kind_name(k)}" for n, k in d.args)
    out = f"DEFINE FUNCTION fn::{d.name}({args})"
    if d.returns is not None:
        out += f" -> {kind_name(d.returns)}"
    body = _expr_sql(d.block)
    if body == "{  }":
        body = "{;}"  # reference renders an empty function body as {;}
    out += f" {body}"
    if d.comment is not None:
        out += f" COMMENT {_str_sql(d.comment)}"
    p = d.permissions
    if p is True or p is None:
        out += " PERMISSIONS FULL"
    elif p is False:
        out += " PERMISSIONS NONE"
    else:
        out += f" PERMISSIONS WHERE {_expr_sql(p)}"
    return out


def render_analyzer(d) -> str:
    out = f"DEFINE ANALYZER {escape_ident(d.name)}"
    if d.function:
        out += f" FUNCTION fn::{d.function}"
    if d.tokenizers:
        out += " TOKENIZERS " + ",".join(t.upper() for t in d.tokenizers)
    if d.filters:
        fs = []
        for f in d.filters:
            if len(f) == 1:
                fs.append(f[0].upper())
            elif f[0].lower() == "mapper":
                fs.append(f"MAPPER({_str_sql(str(f[1]))})")
            elif f[0].lower() == "snowball":
                fs.append(
                    f"SNOWBALL({','.join(str(x).upper() for x in f[1:])})"
                )
            else:
                fs.append(f"{f[0].upper()}({','.join(str(x) for x in f[1:])})")
        out += " FILTERS " + ", ".join(fs)
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def render_user(d) -> str:
    roles = ", ".join(r.upper() for r in d.roles)
    base = {"root": "ROOT", "ns": "NAMESPACE", "db": "DATABASE"}.get(
        d.base, d.base.upper()
    )
    out = (
        f"DEFINE USER {escape_ident(d.name)} ON {base} "
        f"PASSHASH {_str_sql(d.passhash)} ROLES {roles}"
    )
    dur = d.duration or {}
    tok = dur.get("token", Duration.parse("1h"))
    ses = dur.get("session")
    tok_s = tok.render() if isinstance(tok, Duration) else (tok or "NONE")
    ses_s = ses.render() if isinstance(ses, Duration) else (ses or "NONE")
    out += f" DURATION FOR TOKEN {tok_s}, FOR SESSION {ses_s}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def render_access(d) -> str:
    from surrealdb_tpu.val import Duration

    base = {"root": "ROOT", "ns": "NAMESPACE", "db": "DATABASE"}.get(
        d.base, d.base.upper()
    )
    cfg = d.config or {}
    out = f"DEFINE ACCESS {escape_ident(d.name)} ON {base} TYPE {d.kind.upper()}"
    if d.kind == "record":
        if cfg.get("signup") is not None:
            out += f" SIGNUP {_expr_sql(cfg['signup'])}"
        if cfg.get("signin") is not None:
            out += f" SIGNIN {_expr_sql(cfg['signin'])}"
        if cfg.get("alg") or cfg.get("key") or cfg.get("url"):
            out += " WITH JWT" + _jwt_sql(cfg)
    elif d.kind == "jwt":
        out += _jwt_sql(cfg)
    elif d.kind == "bearer" and cfg.get("for"):
        out += f" FOR {cfg['for'].upper()}"
    if cfg.get("authenticate") is not None:
        out += f" AUTHENTICATE {_expr_sql(cfg['authenticate'])}"
    # durations always printed (reference: exports stay forward compatible)
    def _dur(v, dflt):
        if v is None and dflt is not None:
            v = dflt
        if v is None:
            return "NONE"
        return v.render() if isinstance(v, Duration) else str(v)

    dur = d.duration or {}

    def slot(name, dflt):
        if name in dur:
            return _dur(dur[name], None)
        return _dur(None, dflt)

    out += " DURATION"
    if d.kind == "bearer":
        out += f" FOR GRANT {slot('grant', Duration.parse('30d'))},"
    if d.kind in ("jwt", "record", "bearer"):
        out += f" FOR TOKEN {slot('token', Duration.parse('1h'))},"
    out += f" FOR SESSION {slot('session', None)}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def _jwt_sql(cfg) -> str:
    """ALGORITHM/KEY clauses; symmetric verify keys and all issuer keys
    render redacted (reference catalog/schema/access.rs redacted())."""
    out = ""
    if cfg.get("url"):
        out += f" URL {_str_sql(cfg['url'])}"
        return out
    alg = (cfg.get("alg") or "HS512").upper()
    sym = alg.startswith("HS")
    key = "[REDACTED]" if sym else cfg.get("key", "")
    out += f" ALGORITHM {alg} KEY {_str_sql(key)}"
    issuer = cfg.get("issuer_key")
    if issuer is None and sym and cfg.get("key") is not None:
        issuer = cfg.get("key")
    ialg = (cfg.get("issuer_alg") or "").upper()
    if issuer is not None or ialg:
        out += " WITH ISSUER"
        if ialg:
            out += f" ALGORITHM {ialg}"
        if issuer is not None:
            out += " KEY '[REDACTED]'"
    return out


def _middleware_sql(mw) -> str:
    return ", ".join(
        f"{name}({', '.join(_expr_sql(a) for a in args)})"
        for name, args in mw
    )


def _perm_value_sql(p) -> str:
    if p is True or p is None:
        return "FULL"
    if p is False:
        return "NONE"
    return f"WHERE {_expr_sql(p)}"


def render_api(d) -> str:
    from surrealdb_tpu.val import escape_string

    out = f"DEFINE API {escape_string(d.path)}"
    from surrealdb_tpu.catalog import ApiActionDef

    actions = list(d.actions or [])
    if not any("any" in a.methods for a in actions):
        actions.insert(0, ApiActionDef(methods=["any"]))
    else:
        # the fallback (FOR any) always renders first
        actions.sort(key=lambda a: 0 if "any" in a.methods else 1)
    for a in actions:
        out += " FOR " + ", ".join(a.methods)
        if a.middleware:
            out += f" MIDDLEWARE {_middleware_sql(a.middleware)}"
        out += f" PERMISSIONS {_perm_value_sql(a.permissions)}"
        if a.then is not None:
            body = a.then
            from surrealdb_tpu.expr.ast import (
                BlockExpr as _Blk,
                Subquery as _Sub,
            )

            if isinstance(body, _Sub) and isinstance(body.stmt, _Blk):
                body = body.stmt
            out += f" THEN {_expr_sql(body)}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def render_bucket(d) -> str:
    out = f"DEFINE BUCKET {escape_ident(d.name)}"
    if d.readonly:
        out += " READONLY"
    if d.backend:
        out += f" BACKEND {_str_sql(d.backend)}"
    out += f" PERMISSIONS {_perm_value_sql(d.permissions)}"
    if d.comment:
        out += f" COMMENT {_str_sql(d.comment)}"
    return out


def render_config(d) -> str:
    if d.what == "API":
        out = "API"
        if d.middleware:
            out += f" MIDDLEWARE {_middleware_sql(d.middleware)}"
        out += f" PERMISSIONS {_perm_value_sql(d.permissions)}"
        return out
    if d.what == "GRAPHQL":
        def part(v):
            if isinstance(v, tuple):
                return f"{v[0]} " + ", ".join(v[1])
            if isinstance(v, list):
                return "INCLUDE " + ", ".join(v)
            return str(v)

        out = f"GRAPHQL TABLES {part(d.tables)} FUNCTIONS {part(d.functions)}"
        if getattr(d, "depth", None) is not None:
            out += f" DEPTH {d.depth}"
        if getattr(d, "complexity", None) is not None:
            out += f" COMPLEXITY {d.complexity}"
        if getattr(d, "introspection", None) == "NONE":
            out += " INTROSPECTION NONE"
        return out
    if d.what == "DEFAULT":
        out = "DEFAULT"
        if getattr(d, "namespace", None):
            out += f" NAMESPACE {d.namespace}"
        if getattr(d, "database", None):
            out += f" DATABASE {d.database}"
        return out
    return d.what


def config_structure(d) -> dict:
    """INFO FOR DB STRUCTURE entry for one config definition."""
    from surrealdb_tpu.val import NONE as _NONE

    def part(v):
        if isinstance(v, tuple):
            return {v[0].lower(): list(v[1])}
        if v == "NONE":
            return _NONE
        return v

    if d.what == "GRAPHQL":
        out = {"tables": part(d.tables), "functions": part(d.functions)}
        if getattr(d, "depth", None) is not None:
            out["depth_limit"] = d.depth
        if getattr(d, "complexity", None) is not None:
            out["complexity_limit"] = d.complexity
        if getattr(d, "introspection", None) == "NONE":
            out["introspection"] = _NONE
        return {"graphql": out}
    if d.what == "API":
        perms = getattr(d, "config", None) or {}
        return {"api": {
            "permissions": perms.get("permissions", True),
        }}
    return {d.what.lower(): {}}


def render_sequence(d) -> str:
    out = f"DEFINE SEQUENCE {escape_ident(d.name)} BATCH {d.batch} START {d.start}"
    if getattr(d, "timeout", None) is not None:
        out += f" TIMEOUT {d.timeout.render()}"
    return out
