"""Canonical SQL text for catalog definitions (INFO FOR output).

Reference renders definitions back to their DEFINE statements; we do the
same so INFO output is usable as an import script (kvs/export.rs)."""

from __future__ import annotations

from surrealdb_tpu.val import Duration, escape_ident


def _expr_sql(node) -> str:
    """Best-effort canonical text of an expression AST."""
    from surrealdb_tpu.expr.ast import (
        ArrayExpr,
        Binary,
        BlockExpr,
        Cast,
        Constant,
        FunctionCall,
        Idiom,
        Knn,
        Literal,
        ObjectExpr,
        Param,
        PField,
        Prefix,
        RangeExpr,
        RecordIdLit,
        SelectStmt,
        Subquery,
    )
    from surrealdb_tpu.val import render

    if node is None:
        return ""
    if isinstance(node, Literal):
        return render(node.value)
    if isinstance(node, Param):
        return f"${node.name}"
    if isinstance(node, Binary):
        op = {"&&": "AND", "||": "OR"}.get(node.op, node.op)
        return f"{_expr_sql(node.lhs)} {op} {_expr_sql(node.rhs)}"
    if isinstance(node, Prefix):
        return f"{node.op}{_expr_sql(node.expr)}"
    if isinstance(node, FunctionCall):
        args = ", ".join(_expr_sql(a) for a in node.args)
        return f"{node.name}({args})"
    if isinstance(node, Idiom):
        from surrealdb_tpu.exec.statements import expr_name

        return expr_name(node)
    if isinstance(node, ArrayExpr):
        return "[" + ", ".join(_expr_sql(x) for x in node.items) + "]"
    if isinstance(node, ObjectExpr):
        inner = ", ".join(f"{k}: {_expr_sql(v)}" for k, v in node.items)
        return "{ " + inner + " }"
    if isinstance(node, RecordIdLit):
        return f"{node.tb}:{_expr_sql(node.id)}"
    if isinstance(node, Subquery):
        return f"({_expr_sql(node.stmt)})"
    if isinstance(node, BlockExpr):
        return "{ " + "; ".join(_expr_sql(s) for s in node.stmts) + " }"
    if isinstance(node, Constant):
        return node.name
    if isinstance(node, Cast):
        return f"<{node.kind.name}> {_expr_sql(node.expr)}"
    if isinstance(node, SelectStmt):
        fields = ", ".join(
            "*" if e == "*" else (_expr_sql(e) + (f" AS {a}" if a else ""))
            for e, a in node.exprs
        )
        whats = ", ".join(_expr_sql(w) for w in node.what)
        out = f"SELECT {fields} FROM {whats}"
        if node.cond is not None:
            out += f" WHERE {_expr_sql(node.cond)}"
        if node.group is not None:
            if node.group:
                out += " GROUP BY " + ", ".join(_expr_sql(g) for g in node.group)
            else:
                out += " GROUP ALL"
        return out
    return str(node)


def _kind_sql(kind) -> str:
    from surrealdb_tpu.exec.coerce import kind_name

    return kind_name(kind)


def _perm_sql(p) -> str:
    if p is True:
        return "FULL"
    if p is False or p is None:
        return "NONE"
    return f"WHERE {_expr_sql(p)}"


def _perms_sql(perms) -> str:
    if perms is None:
        return "NONE"
    parts = []
    for action in ("select", "create", "update", "delete"):
        parts.append(f"FOR {action} {_perm_sql(perms.get(action, False))}")
    return ", ".join(parts)


def render_ns(d) -> str:
    return f"DEFINE NAMESPACE {escape_ident(d.name)}"


def render_db(d) -> str:
    out = f"DEFINE DATABASE {escape_ident(d.name)}"
    if d.changefeed:
        out += f" CHANGEFEED {Duration(d.changefeed).render()}"
    return out


def render_table(d) -> str:
    out = f"DEFINE TABLE {escape_ident(d.name)}"
    if d.drop:
        out += " DROP"
    out += " SCHEMAFULL" if d.full else " SCHEMALESS"
    if d.kind == "relation":
        out += " TYPE RELATION"
        if d.relation_from:
            out += " IN " + " | ".join(d.relation_from)
        if d.relation_to:
            out += " OUT " + " | ".join(d.relation_to)
        if d.enforced:
            out += " ENFORCED"
    elif d.kind == "any":
        out += " TYPE ANY"
    else:
        out += " TYPE NORMAL"
    if d.view is not None:
        out += f" AS {_expr_sql(d.view)}"
    if d.changefeed:
        out += f" CHANGEFEED {Duration(d.changefeed).render()}"
    out += f" PERMISSIONS {_perms_sql(d.permissions)}"
    return out


def render_field(d, tb) -> str:
    out = f"DEFINE FIELD {d.name_str} ON {escape_ident(tb)}"
    if d.flex:
        out += " FLEXIBLE"
    if d.kind is not None:
        out += f" TYPE {_kind_sql(d.kind)}"
    if d.default is not None:
        out += " DEFAULT"
        if d.default_always:
            out += " ALWAYS"
        out += f" {_expr_sql(d.default)}"
    if d.readonly:
        out += " READONLY"
    if d.value is not None:
        out += f" VALUE {_expr_sql(d.value)}"
    if d.assert_ is not None:
        out += f" ASSERT {_expr_sql(d.assert_)}"
    out += f" PERMISSIONS {_perms_sql(d.permissions) if d.permissions is not None else 'FULL'}"
    return out


def render_index(d) -> str:
    out = f"DEFINE INDEX {escape_ident(d.name)} ON {escape_ident(d.tb)}"
    if d.cols_str:
        out += " FIELDS " + ", ".join(d.cols_str)
    if d.unique:
        out += " UNIQUE"
    if d.count:
        out += " COUNT"
    if d.fulltext is not None:
        ft = d.fulltext
        out += f" FULLTEXT ANALYZER {ft.get('analyzer')}"
        k1, b = ft.get("bm25", (1.2, 0.75))
        out += f" BM25({k1},{b})"
        if ft.get("highlights"):
            out += " HIGHLIGHTS"
    if d.hnsw is not None:
        h = d.hnsw
        dist = h.get("distance", "euclidean")
        dist_s = (
            f"MINKOWSKI {dist[1]}" if isinstance(dist, tuple) else dist.upper()
        )
        out += (
            f" HNSW DIMENSION {h.get('dimension')} DIST {dist_s}"
            f" TYPE {h.get('vector_type', 'f64').upper()}"
            f" EFC {h.get('ef_construction', 150)} M {h.get('m', 12)}"
        )
    return out


def render_event(d, tb) -> str:
    then = ", ".join(_expr_sql(t) for t in d.then)
    return (
        f"DEFINE EVENT {escape_ident(d.name)} ON {escape_ident(tb)} "
        f"WHEN {_expr_sql(d.when) if d.when is not None else 'true'} THEN ({then})"
    )


def render_param(d) -> str:
    from surrealdb_tpu.val import render as vr

    return f"DEFINE PARAM ${d.name} VALUE {vr(d.value)} PERMISSIONS {_perm_sql(d.permissions)}"


def render_function(d) -> str:
    args = ", ".join(f"${n}: {_kind_sql(k)}" for n, k in d.args)
    return f"DEFINE FUNCTION fn::{d.name}({args}) {_expr_sql(d.block)}"


def render_analyzer(d) -> str:
    out = f"DEFINE ANALYZER {escape_ident(d.name)}"
    if d.tokenizers:
        out += " TOKENIZERS " + ",".join(t.upper() for t in d.tokenizers)
    if d.filters:
        fs = []
        for f in d.filters:
            if len(f) == 1:
                fs.append(f[0].upper())
            else:
                fs.append(f"{f[0].upper()}({','.join(str(x) for x in f[1:])})")
        out += " FILTERS " + ",".join(fs)
    return out


def render_user(d) -> str:
    roles = ", ".join(r.upper() for r in d.roles)
    return (
        f"DEFINE USER {escape_ident(d.name)} ON {d.base.upper()} "
        f"PASSHASH '{d.passhash}' ROLES {roles}"
    )


def render_access(d) -> str:
    return f"DEFINE ACCESS {escape_ident(d.name)} ON {d.base.upper()} TYPE {d.kind.upper()}"


def render_sequence(d) -> str:
    return f"DEFINE SEQUENCE {escape_ident(d.name)} BATCH {d.batch} START {d.start}"
