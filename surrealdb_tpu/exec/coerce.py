"""Kind coercion & casting (reference: expr/kind.rs + val coercion).

`coerce` implements TYPE-clause semantics (DEFINE FIELD TYPE / LET $x: kind);
`cast` implements `<kind> value` expressions (more lenient conversions).
"""

from __future__ import annotations

import math
from decimal import Decimal

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.expr.ast import Kind
from surrealdb_tpu.val import (
    NONE,
    Datetime,
    Duration,
    File,
    Geometry,
    Range,
    RecordId,
    Regex,
    Table,
    Uuid,
    render,
    value_eq,
)


def kind_name(kind: Kind) -> str:
    if kind.name == "either":
        return " | ".join(kind_name(k) for k in kind.inner)
    if kind.name == "option":
        # option<X> renders as `none | X` (reference kind display)
        if kind.inner:
            return f"none | {kind_name(kind.inner[0])}"
        return "none"
    if kind.name == "record" and kind.inner:
        return f"record<{' | '.join(kind.inner)}>"
    if kind.name in ("table", "geometry") and kind.inner:
        return f"{kind.name}<{'|'.join(str(x) for x in kind.inner)}>"
    if kind.name == "object_literal":
        inner = ", ".join(
            f"{k}: {kind_name(kk)}"
            for k, kk in sorted(kind.inner, key=lambda p: p[0])
        )
        return "{ " + inner + " }"
    if kind.name == "array_literal":
        return "[" + ", ".join(kind_name(k) for k in kind.inner) + "]"
    if kind.name == "literal":
        from surrealdb_tpu.exec.static_eval import static_value_maybe
        from surrealdb_tpu.val import render

        try:
            return render(static_value_maybe(kind.literal))
        except Exception:
            return "literal"
    if kind.inner:
        # array<any> / set<any> normalize to the bare container kind
        if (
            kind.name in ("array", "set")
            and len(kind.inner) == 1
            and isinstance(kind.inner[0], Kind)
            and kind.inner[0].name == "any"
            and kind.size is None
        ):
            return kind.name
        inner = ", ".join(
            kind_name(k) if isinstance(k, Kind) else str(k) for k in kind.inner
        )
        if kind.size is not None:
            inner += f", {kind.size}"
        return f"{kind.name}<{inner}>"
    return kind.name


def _type_name(v) -> str:
    if v is NONE:
        return "none"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float"
    if isinstance(v, Decimal):
        return "decimal"
    if isinstance(v, str):
        return "string"
    if isinstance(v, Duration):
        return "duration"
    if isinstance(v, Datetime):
        return "datetime"
    if isinstance(v, Uuid):
        return "uuid"
    from surrealdb_tpu.val import SSet as _SS

    if isinstance(v, _SS):
        return "set"
    if isinstance(v, list):
        return "array"
    if isinstance(v, dict):
        return "object"
    if isinstance(v, Geometry):
        sub = v.kind.lower()
        sub = {
            "geometrycollection": "collection",
            "linestring": "line",
            "multilinestring": "multiline",
        }.get(sub, sub)
        return f"geometry<{sub}>"
    if isinstance(v, (bytes, bytearray)):
        return "bytes"
    if isinstance(v, RecordId):
        return "record"
    if isinstance(v, Range):
        return "range"
    if isinstance(v, Regex):
        return "regex"
    if isinstance(v, File):
        return "file"
    if isinstance(v, Table):
        return "table"
    from surrealdb_tpu.val import Closure as _Clo

    if isinstance(v, _Clo):
        return "function"
    return type(v).__name__


def coerce_err(v, kind: Kind):
    # reference format: val/value/convert/coerce.rs CoerceError::InvalidKind
    return SdbError(
        f"Expected `{kind_name(kind)}` but found `{render(v)}`"
    )


def coerce(v, kind: Kind):
    """Coerce a value to a kind; raises SdbError on mismatch."""
    n = kind.name
    if n == "any":
        return v
    if n == "option":
        if v is NONE:
            return NONE
        if v is None:
            # NULL is NOT none: option<string> rejects it unless the
            # inner kind admits null (language/types/field_none_null)
            if kind.inner:
                try:
                    return coerce(v, kind.inner[0])
                except SdbError:
                    raise coerce_err(v, kind)
            raise coerce_err(v, kind)
        return coerce(v, kind.inner[0]) if kind.inner else v
    if n == "either":
        for k in kind.inner:
            try:
                return coerce(v, k)
            except SdbError:
                continue
        raise coerce_err(v, kind)
    if n == "literal":
        lit = kind.literal
        from surrealdb_tpu.expr.ast import ArrayExpr as _AE

        if isinstance(lit, _AE):
            # array-shaped literal kind: elements are kinds/literals
            if not isinstance(v, list) or len(v) != len(lit.items):
                raise coerce_err(v, kind)
            out = []
            for x, spec in zip(v, lit.items):
                out.append(coerce(x, _as_kind(spec)))
            return out
        from surrealdb_tpu.exec.static_eval import static_value_maybe

        litv = static_value_maybe(lit)
        if value_eq(v, litv):
            return v
        raise coerce_err(v, kind)
    if n == "null":
        if v is None:
            return v
        raise coerce_err(v, kind)
    if n == "none":
        if v is NONE:
            return v
        raise coerce_err(v, kind)
    if n == "bool":
        if isinstance(v, bool):
            return v
        raise coerce_err(v, kind)
    if n == "int":
        if isinstance(v, bool):
            raise coerce_err(v, kind)
        if isinstance(v, int):
            return v
        if isinstance(v, float) and v.is_integer():
            return int(v)
        if isinstance(v, Decimal) and v == v.to_integral_value():
            return int(v)
        raise coerce_err(v, kind)
    if n == "float":
        if isinstance(v, bool):
            raise coerce_err(v, kind)
        if isinstance(v, float):
            return v
        if isinstance(v, (int, Decimal)):
            return float(v)
        raise coerce_err(v, kind)
    if n == "decimal":
        if isinstance(v, bool):
            raise coerce_err(v, kind)
        if isinstance(v, Decimal):
            return v
        if isinstance(v, int):
            return Decimal(v)
        if isinstance(v, float):
            return Decimal(str(v))
        raise coerce_err(v, kind)
    if n == "number":
        if isinstance(v, bool):
            raise coerce_err(v, kind)
        if isinstance(v, (int, float, Decimal)):
            return v
        raise coerce_err(v, kind)
    if n == "string":
        if isinstance(v, str):
            return v
        if isinstance(v, Table):
            return v.name
        raise coerce_err(v, kind)
    if n == "duration":
        if isinstance(v, Duration):
            return v
        raise coerce_err(v, kind)
    if n == "datetime":
        if isinstance(v, Datetime):
            return v
        if isinstance(v, str):
            try:
                return Datetime.parse(v)
            except ValueError:
                pass
        raise coerce_err(v, kind)
    if n == "uuid":
        if isinstance(v, Uuid):
            return v
        if isinstance(v, str):
            try:
                return Uuid(v)
            except ValueError:
                pass
        raise coerce_err(v, kind)
    if n == "array":
        if not isinstance(v, list):
            raise coerce_err(v, kind)
        if kind.inner:
            v = [coerce(x, kind.inner[0]) for x in v]
        if kind.size is not None and len(v) != kind.size:
            # sized collections demand the exact length (reference
            # coerce.rs: array<T, N> is a fixed size, issue 5677)
            inner_n = kind_name(kind.inner[0]) if kind.inner else "any"
            raise SdbError(
                f"Expected `array<{inner_n},{kind.size}>` but found a "
                f"collection of length `{len(v)}`"
            )
        return v
    if n == "set":
        from surrealdb_tpu.val import SSet

        if isinstance(v, SSet):
            items = v.items
        elif isinstance(v, list):
            items = v
        else:
            raise coerce_err(v, kind)
        if kind.inner:
            items = [coerce(x, kind.inner[0]) for x in items]
        out = SSet(items)
        if kind.size is not None and len(out) != kind.size:
            inner_n = kind_name(kind.inner[0]) if kind.inner else "any"
            raise SdbError(
                f"Expected `set<{inner_n},{kind.size}>` but found a "
                f"collection of length `{len(out)}`"
            )
        return out
    if n == "object":
        if isinstance(v, dict):
            return v
        raise coerce_err(v, kind)
    if n == "array_literal":
        if not isinstance(v, list) or len(v) != len(kind.inner):
            raise coerce_err(v, kind)
        return [coerce(x, kk) for x, kk in zip(v, kind.inner)]
    if n == "object_literal":
        if not isinstance(v, dict):
            raise coerce_err(v, kind)
        declared = dict(kind.inner)
        out = {}
        for k in v:
            if k not in declared:
                raise coerce_err(v, kind)
        for k, kk in declared.items():
            try:
                sub = coerce(v.get(k, NONE), kk)
            except SdbError:
                # sub-field mismatches report at the object level, with the
                # full declared kind and the full offending value
                raise coerce_err(v, kind)
            if sub is not NONE:
                out[k] = sub
        return out
    if n == "record":
        if isinstance(v, RecordId):
            if kind.inner and v.tb not in kind.inner:
                raise coerce_err(v, kind)
            return v
        raise coerce_err(v, kind)
    if n == "geometry":
        if isinstance(v, Geometry):
            if kind.inner and v.kind.lower() not in [
                x.lower() for x in kind.inner
            ] and not (
                "collection" in kind.inner
                and v.kind == "GeometryCollection"
            ):
                raise coerce_err(v, kind)
            return v
        if isinstance(v, dict) and "type" in v and (
            "coordinates" in v or "geometries" in v
        ):
            g = object_to_geometry(v)
            if g is not None:
                return coerce(g, kind)
        raise coerce_err(v, kind)
    if n == "point":
        if isinstance(v, Geometry) and v.kind == "Point":
            return v
        raise coerce_err(v, kind)
    if n == "bytes":
        if isinstance(v, (bytes, bytearray)):
            return bytes(v)
        raise coerce_err(v, kind)
    if n == "regex":
        if isinstance(v, Regex):
            return v
        raise coerce_err(v, kind)
    if n == "range":
        if isinstance(v, Range):
            return v
        raise coerce_err(v, kind)
    if n == "function":
        from surrealdb_tpu.val import Closure

        if isinstance(v, Closure):
            return v
        raise coerce_err(v, kind)
    if n == "file":
        if isinstance(v, File):
            return v
        raise coerce_err(v, kind)
    if n == "table":
        if isinstance(v, Table):
            t = v
        elif isinstance(v, str):
            t = Table(v)
        else:
            raise coerce_err(v, kind)
        if kind.inner and t.name not in kind.inner:
            raise coerce_err(v, kind)
        return t
    if n == "references":
        # computed references fields — value is filled by the executor
        return v if isinstance(v, list) else []
    raise SdbError(f"unknown kind {n!r}")


def _as_kind(spec):
    """A literal-kind element: already a Kind, or a literal value/AST."""
    if isinstance(spec, Kind):
        return spec
    from surrealdb_tpu.expr.ast import Idiom as _Idiom, Literal as _Lit, PField as _PF

    if isinstance(spec, _Idiom) and len(spec.parts) == 1 and isinstance(
        spec.parts[0], _PF
    ) and spec.parts[0].name.lower() in (
        "any", "bool", "int", "float", "number", "string", "datetime",
        "duration", "uuid", "object", "array", "bytes", "decimal",
        "record", "geometry", "point", "set", "null", "none", "regex",
        "range", "table",
    ):
        return Kind(spec.parts[0].name.lower())
    return Kind("literal", literal=spec)


def object_to_geometry(v: dict):
    t = v.get("type")
    if t == "GeometryCollection":
        geoms = v.get("geometries")
        if isinstance(geoms, list):
            inner = [
                g if isinstance(g, Geometry) else object_to_geometry(g)
                for g in geoms
            ]
            if all(inner):
                return Geometry(t, inner)
        return None
    coords = v.get("coordinates")
    if t in ("Point", "LineString", "Polygon", "MultiPoint",
             "MultiLineString", "MultiPolygon") and coords is not None:
        tc = _tupled(coords)
        # polygon rings auto-close (reference geo semantics: the first
        # point is appended when the ring is open)
        if t == "Polygon":
            tc = tuple(_close_ring(r) for r in tc)
        elif t == "MultiPolygon":
            tc = tuple(
                tuple(_close_ring(r) for r in poly) for poly in tc
            )
        return Geometry(t, tc)
    return None


def _close_ring(ring):
    if isinstance(ring, tuple) and len(ring) >= 2 and ring[0] != ring[-1]:
        return ring + (ring[0],)
    return ring


def _tupled(c):
    if isinstance(c, list):
        return tuple(_tupled(x) for x in c)
    return float(c) if isinstance(c, (int, float, Decimal)) else c


def cast_err(v, kind: Kind):
    # reference format: "Could not cast into `k` using input `v`"
    return SdbError(
        f"Could not cast into `{kind_name(kind)}` using input `{render(v)}`"
    )


def cast(v, kind: Kind):
    """`<kind> value` — lenient conversion (reference expr/cast.rs)."""
    n = kind.name
    if n in ("set", "array") and kind.size is not None:
        # sized casts demand the EXACT length (type/set.surql:
        # <set<int,5>>[1,2,1] errors), unlike field coercion's upper bound
        pass
    else:
        try:
            return coerce(v, kind)
        except SdbError:
            pass
    if n == "int":
        if isinstance(v, str):
            try:
                return int(v)
            except ValueError:
                try:
                    f = float(v)
                    return int(f)
                except ValueError:
                    pass
        if isinstance(v, (float, Decimal)):
            if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
                raise SdbError(f"Cannot convert {render(v)} to an int")
            return int(v)
        if isinstance(v, bool):
            return 1 if v else 0
        if isinstance(v, Datetime):
            return v.epoch_ns() // 1_000_000_000
    elif n == "float":
        if isinstance(v, str):
            try:
                return float(v)
            except ValueError:
                pass
        if isinstance(v, (int, Decimal)):
            return float(v)
        if isinstance(v, bool):
            return 1.0 if v else 0.0
    elif n == "decimal":
        if isinstance(v, str):
            try:
                return Decimal(v)
            except Exception:
                pass
        if isinstance(v, (int, float)):
            return Decimal(str(v))
        if isinstance(v, bool):
            return Decimal(1 if v else 0)
    elif n == "number":
        if isinstance(v, str):
            try:
                return int(v)
            except ValueError:
                try:
                    return float(v)
                except ValueError:
                    pass
    elif n == "string":
        if isinstance(v, (bytes, bytearray)):
            return bytes(v).decode("utf-8", "replace")
        from surrealdb_tpu.exec.operators import to_string

        return to_string(v)  # <string> NONE renders "NONE" (reference)
    elif n == "bool":
        if isinstance(v, str):
            if v.lower() == "true":
                return True
            if v.lower() == "false":
                return False
    elif n == "datetime":
        if isinstance(v, str):
            try:
                return Datetime.parse(v)
            except ValueError:
                raise cast_err(v, kind)
        if isinstance(v, int):
            import datetime as _dt

            return Datetime(_dt.datetime.fromtimestamp(v, _dt.timezone.utc))
    elif n == "duration":
        if isinstance(v, str):
            return Duration.parse(v)
    elif n == "uuid":
        if isinstance(v, str):
            try:
                return Uuid(v)
            except ValueError:
                raise cast_err(v, kind)
    elif n == "record":
        if isinstance(v, str):
            from surrealdb_tpu.syn.parser import parse_record_literal
            from surrealdb_tpu.exec.static_eval import static_value

            try:
                rid2 = static_value(parse_record_literal(v))
            except Exception:
                raise cast_err(v, kind)
            if kind.inner and rid2.tb not in kind.inner:
                raise cast_err(v, kind)
            return rid2
    elif n == "array":
        from surrealdb_tpu.val import SSet as _SSet

        def _len_check(out):
            if kind.size is not None and len(out) != int(kind.size):
                inner_n = kind_name(kind.inner[0]) if kind.inner else "any"
                raise SdbError(
                    f"Expected `array<{inner_n},{kind.size}>` but found a "
                    f"collection of length `{len(out)}`"
                )
            return out

        if isinstance(v, list):
            return _len_check(
                [cast(x, kind.inner[0]) for x in v] if kind.inner else v
            )
        if isinstance(v, _SSet):
            items = list(v.items)
            return _len_check(
                [cast(x, kind.inner[0]) for x in items]
                if kind.inner else items
            )
        if isinstance(v, Range):
            try:
                items = list(v.iter_ints())
            except TypeError:
                raise cast_err(v, kind)
            return _len_check(
                [cast(x, kind.inner[0]) for x in items]
                if kind.inner else items
            )
        if isinstance(v, (bytes, bytearray)):
            return _len_check(
                [cast(x, kind.inner[0]) for x in list(v)]
                if kind.inner else list(v)
            )
        raise cast_err(v, kind)
    elif n == "set":
        from surrealdb_tpu.val import SSet

        if isinstance(v, SSet):
            base = v.items
        elif isinstance(v, list):
            base = v
        elif isinstance(v, (bytes, bytearray)):
            base = list(v)
        elif isinstance(v, Range):
            try:
                base = list(v.iter_ints())
            except TypeError:
                raise cast_err(v, Kind("array"))
        else:
            # set casts convert through array first: failures name `array`
            # (casting/decimal.surql)
            raise cast_err(v, Kind("array"))
        if kind.inner:
            base = [cast(x, kind.inner[0]) for x in base]
        out = SSet(base)
        if kind.size is not None and len(out.items) != int(kind.size):
            inner_n = kind_name(kind.inner[0]) if kind.inner else "any"
            raise SdbError(
                f"Expected `set<{inner_n},{kind.size}>` but found a "
                f"collection of length `{len(out.items)}`"
            )
        return out
    elif n == "bytes":
        if isinstance(v, str):
            return v.encode("utf-8")
        if isinstance(v, list) and all(
            isinstance(x, int) and not isinstance(x, bool) and 0 <= x < 256
            for x in v
        ):
            return bytes(v)
    elif n == "regex":
        if isinstance(v, str):
            return Regex(v)
    elif n == "geometry" or n == "point":
        g = None
        if isinstance(v, dict):
            g = object_to_geometry(v)
        elif isinstance(v, (list, tuple)) and len(v) == 2 and all(
            isinstance(x, (int, float, Decimal)) and not isinstance(x, bool)
            for x in v
        ):
            g = Geometry("Point", (float(v[0]), float(v[1])))
        if g is not None:
            try:
                return coerce(g, kind)
            except SdbError:
                raise cast_err(v, Kind("geometry"))
        # geometry cast failures always name the bare kind (reference
        # val/convert/cast.rs: the error drops the parameterization)
        raise cast_err(v, Kind("geometry"))
    raise cast_err(v, kind)
