"""Batched execution engine.

Single engine (no legacy/streaming duality like the reference — SURVEY.md §7
step 3): a statement loop over a transaction, per-statement operator pipelines
for SELECT, and a document write pipeline mirroring the reference's
core/src/doc/ stage order. Vector / graph hot paths dispatch to the TPU
engines in surrealdb_tpu.idx / surrealdb_tpu.graph.
"""
