"""Vectorized operator kernels over classified columns (exec/batch.py).

Reference: core/src/exec/ — the push executor evaluates predicates,
projections and aggregates over ValueBatch columns with one kernel call
per batch instead of one `evaluate()` per row.

Exactness contract (the golden-file conformance suite is the net):

- A compiled node either produces the bit-identical value the scalar
  evaluator would produce for a row, or marks that row EXOTIC; exotic
  rows are re-evaluated through the ordinary `evaluate()` path (same
  values, same errors, same short-circuit order).
- Compilation is conservative: any expression shape outside the known
  set returns None and the whole expression stays scalar ("per-
  expression fallback").
- Kernels never raise on data: every case where the scalar operators
  would raise (arithmetic on NONE, negating a string, >2^53 integers,
  NaN ordering, ...) is classified exotic instead, so the scalar
  fallback raises the exact error text at the exact row.

Aggregation: `group_sources` (streaming tier — per-group fallback via
the drained Source rows) and `columnar_group_select` (whole-table tier
over the version-keyed column store — bails to the streaming tier on
any wrinkle) share one grouping core. Float sums run through
`np.cumsum`, which accumulates strictly left-to-right — bit-identical
to the scalar fold (pairwise `np.sum`/`np.add.reduceat` are NOT and
are never used for float aggregates).
"""

from __future__ import annotations

import numpy as np

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.exec.batch import (
    RANK_BOOL,
    RANK_EXOTIC,
    RANK_NONE,
    RANK_NULL,
    RANK_NUM,
    RANK_STR,
    Column,
    _count,
)
from surrealdb_tpu.val import NONE, type_rank

_I53 = 1 << 53

_CMP_OPS = ("<", "<=", ">", ">=", "=", "==", "!=")
_ARITH_OPS = ("+", "-", "*", "/")


def _enabled() -> bool:
    from surrealdb_tpu import cnf

    return cnf.COLUMNAR != "off"


# ---------------------------------------------------------------------------
# compiled nodes
# ---------------------------------------------------------------------------


class _Field:
    __slots__ = ("parts",)

    def __init__(self, parts):
        self.parts = parts

    def paths(self, out):
        out.add(self.parts)

    def eval(self, colset, ctx):
        return colset.col(self.parts)


class _Const:
    """A query-constant operand, evaluated once per execution."""

    __slots__ = ("value", "crank", "cnum")

    def __init__(self, value):
        self.value = value
        self.crank = type_rank(value)
        self.cnum = None
        if self.crank == 3:
            # Decimal compares through float() (val._num_cmp); int/float
            # pass through — callers reject NaN / >2^53 ints at compile
            from decimal import Decimal

            self.cnum = float(value) if isinstance(value, Decimal) \
                else value
        elif self.crank == 2:
            self.cnum = 1.0 if self.value else 0.0


class _Cmp:
    __slots__ = ("op", "lhs", "rhs")

    def __init__(self, op, lhs, rhs):
        self.op = op
        self.lhs = lhs
        self.rhs = rhs

    def paths(self, out):
        for s in (self.lhs, self.rhs):
            if not isinstance(s, _Const):
                s.paths(out)

    def eval(self, colset, ctx):
        op = self.op
        if isinstance(self.rhs, _Const):
            l = self.lhs.eval(colset, ctx)
            if l is None:
                return None
            return _cmp_col_const(op, l, self.rhs)
        if isinstance(self.lhs, _Const):
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
            l = self.rhs.eval(colset, ctx)
            if l is None:
                return None
            return _cmp_col_const(flip.get(op, op), l, self.lhs)
        l = self.lhs.eval(colset, ctx)
        r = self.rhs.eval(colset, ctx)
        if l is None or r is None:
            return None
        return _cmp_col_col(op, l, r)


class _In:
    """lhs ∈ <const list> — an OR of per-element equality kernels."""

    __slots__ = ("lhs", "elems", "neg")

    def __init__(self, lhs, elems, neg):
        self.lhs = lhs
        self.elems = elems  # list[_Const]
        self.neg = neg

    def paths(self, out):
        self.lhs.paths(out)

    def eval(self, colset, ctx):
        l = self.lhs.eval(colset, ctx)
        if l is None:
            return None
        n = l.n
        mask = np.zeros(n, bool)
        for c in self.elems:
            r = _cmp_col_const("==", l, c)
            mask |= r.num != 0.0
        if self.neg:
            mask = ~mask & (l.rank != RANK_EXOTIC)
        return _bool_col(n, mask, l.rank == RANK_EXOTIC)


class _Logic:
    __slots__ = ("op", "lhs", "rhs")

    def __init__(self, op, lhs, rhs):
        self.op = op
        self.lhs = lhs
        self.rhs = rhs

    def paths(self, out):
        self.lhs.paths(out)
        self.rhs.paths(out)

    def eval(self, colset, ctx):
        l = self.lhs.eval(colset, ctx)
        if l is None:
            return None
        r = self.rhs.eval(colset, ctx)
        if r is None:
            return None
        tl, el = _truthy(l)
        tr, er = _truthy(r)
        if self.op == "&&":
            # short-circuit: a valid falsy lhs decides the row — an
            # exotic rhs there never runs on the scalar path either
            mask = tl & tr
            exo = el | (tl & ~el & er)
        else:
            mask = tl | tr
            exo = el | (~tl & ~el & er)
        return _bool_col(l.n, mask & ~exo, exo)


class _Not:
    __slots__ = ("inner",)

    def __init__(self, inner):
        self.inner = inner

    def paths(self, out):
        self.inner.paths(out)

    def eval(self, colset, ctx):
        c = self.inner.eval(colset, ctx)
        if c is None:
            return None
        t, e = _truthy(c)
        return _bool_col(c.n, ~t & ~e, e)


class _Neg:
    __slots__ = ("inner",)

    def __init__(self, inner):
        self.inner = inner

    def paths(self, out):
        self.inner.paths(out)

    def eval(self, colset, ctx):
        c = self.inner.eval(colset, ctx)
        if c is None:
            return None
        # negation is numeric-only (`neg` raises on everything else)
        exo = c.rank != RANK_NUM
        out = Column(c.n, np.where(exo, RANK_EXOTIC, RANK_NUM).astype(
            np.int8), -c.num, c.is_int.copy(), None)
        return out


class _Arith:
    __slots__ = ("op", "lhs", "rhs")

    def __init__(self, op, lhs, rhs):
        self.op = op
        self.lhs = lhs
        self.rhs = rhs

    def paths(self, out):
        for s in (self.lhs, self.rhs):
            if not isinstance(s, _Const):
                s.paths(out)

    def eval(self, colset, ctx):
        op = self.op
        l = self.lhs.eval(colset, ctx) if not isinstance(self.lhs, _Const) \
            else self.lhs
        r = self.rhs.eval(colset, ctx) if not isinstance(self.rhs, _Const) \
            else self.rhs
        if l is None or r is None:
            return None
        if isinstance(l, _Const):
            if l.crank != 3:
                return None
            n = r.n
            la = np.full(n, float(l.cnum))
            lint = np.full(n, isinstance(l.value, int)
                           and not isinstance(l.value, bool))
            lexo = np.zeros(n, bool)
        else:
            n = l.n
            la, lint = l.num, l.is_int
            lexo = l.rank != RANK_NUM
        if isinstance(r, _Const):
            if r.crank != 3:
                return None
            ra = np.full(n, float(r.cnum))
            rint = np.full(n, isinstance(r.value, int)
                           and not isinstance(r.value, bool))
            rexo = np.zeros(n, bool)
        else:
            ra, rint = r.num, r.is_int
            rexo = r.rank != RANK_NUM
        exo = lexo | rexo
        is_int = lint & rint
        with np.errstate(divide="ignore", invalid="ignore",
                         over="ignore"):
            if op == "+":
                out = la + ra
            elif op == "-":
                out = la - ra
            elif op == "*":
                out = la * ra
            else:
                # float division only; int/int keeps the exact truncating
                # scalar semantics, and a negative-zero divisor's infinity
                # sign diverges from the scalar branch — both exotic
                exo = exo | is_int | ((ra == 0.0) & np.signbit(ra))
                out = la / ra
                zero = ra == 0.0
                if zero.any():
                    # scalar div: 0/0 → NaN, a/0 → ±inf by sign of a
                    out = np.where(zero & (la == 0.0), np.nan, out)
                    out = np.where(zero & (la > 0.0), np.inf, out)
                    out = np.where(zero & (la < 0.0), -np.inf, out)
                is_int = np.zeros(n, bool)
        # rows whose exact integer result left the f64-exact window, and
        # NaN results (ordering diverges), re-run on the scalar path
        exo = exo | (is_int & (np.abs(out) >= _I53)) | np.isnan(out)
        rank = np.where(exo, RANK_EXOTIC, RANK_NUM).astype(np.int8)
        return Column(n, rank, np.where(exo, 0.0, out), is_int & ~exo,
                      None)


def _bool_col(n, mask, exotic):
    rank = np.where(exotic, RANK_EXOTIC, RANK_BOOL).astype(np.int8)
    return Column(n, rank, mask.astype(np.float64), np.zeros(n, bool),
                  None)


def _truthy(col):
    """(truthy, exotic) masks with exact `is_truthy` semantics per rank."""
    r = col.rank
    exo = r == RANK_EXOTIC
    t = np.zeros(col.n, bool)
    numish = (r == RANK_BOOL) | (r == RANK_NUM)
    t[numish] = col.num[numish] != 0.0
    smask = r == RANK_STR
    if smask.any():
        t[smask] = np.not_equal(col.strs[smask], "")
    return t, exo


def _cmp_col_const(op, l, c: _Const):
    n = l.n
    r = l.rank
    exo = r == RANK_EXOTIC
    crank = c.crank
    if op in ("=", "==", "!="):
        if crank == 16 and op == "=":
            return None  # `=` against a regex is a match, not equality
        eq = np.zeros(n, bool)
        if crank <= 1:
            eq = r == crank
        elif crank in (2, 3):
            eq = (r == crank) & (l.num == c.cnum)
        elif crank == 4:
            smask = r == RANK_STR
            if smask.any():
                eq[smask] = np.equal(l.strs[smask], c.value)
        # other const ranks never equal a vectorizable row value
        if op == "!=":
            eq = ~eq & ~exo
        return _bool_col(n, eq & ~exo, exo)
    # ordering: rank order first, then the typed comparator inside the
    # shared rank (val.value_cmp semantics)
    lt = r < crank
    gt = (r > crank) & ~exo
    if crank in (2, 3):
        same = r == crank
        lt = lt | (same & (l.num < c.cnum))
        gt = gt | (same & (l.num > c.cnum))
    elif crank == 4:
        smask = r == RANK_STR
        if smask.any():
            sl = np.zeros(n, bool)
            sg = np.zeros(n, bool)
            sl[smask] = np.less(l.strs[smask], c.value)
            sg[smask] = np.greater(l.strs[smask], c.value)
            lt = lt | sl
            gt = gt | sg
    elif crank <= 1:
        pass  # same-rank NONE/NULL compare equal
    if op == "<":
        mask = lt
    elif op == "<=":
        mask = ~gt
    elif op == ">":
        mask = gt
    else:
        mask = ~lt
    return _bool_col(n, mask & ~exo, exo)


def _cmp_col_col(op, l, r):
    n = l.n
    exo = (l.rank == RANK_EXOTIC) | (r.rank == RANK_EXOTIC)
    lr, rr = l.rank, r.rank
    ltr = lr < rr
    gtr = lr > rr
    same = (lr == rr) & ~exo
    lt = ltr.copy()
    gt = gtr.copy()
    eq = np.zeros(n, bool)
    eq[same & (lr <= 1)] = True
    numish = same & ((lr == RANK_BOOL) | (lr == RANK_NUM))
    if numish.any():
        eq[numish] = l.num[numish] == r.num[numish]
        lt[numish] = l.num[numish] < r.num[numish]
        gt[numish] = l.num[numish] > r.num[numish]
    smask = same & (lr == RANK_STR)
    if smask.any():
        ls, rs = l.strs[smask], r.strs[smask]
        eq[smask] = np.equal(ls, rs)
        lt[smask] = np.less(ls, rs)
        gt[smask] = np.greater(ls, rs)
    if op in ("=", "=="):
        mask = eq
    elif op == "!=":
        mask = ~eq
    elif op == "<":
        mask = lt
    elif op == "<=":
        mask = ~gt
    elif op == ">":
        mask = gt
    else:
        mask = ~lt
    return _bool_col(n, mask & ~exo, exo)


def col_value_at(col, i):
    """The exact Python value of a computed column row (derived results
    only carry rank/num; field columns keep their original values)."""
    if col.vals is not None:
        return col.vals[i]
    r = col.rank[i]
    if r == RANK_NONE:
        return NONE
    if r == RANK_NULL:
        return None
    if r == RANK_BOOL:
        return bool(col.num[i])
    if r == RANK_NUM:
        return int(col.num[i]) if col.is_int[i] else float(col.num[i])
    if r == RANK_STR:
        return col.strs[i]
    raise SdbError("exotic row has no vectorized value")  # pragma: no cover


# ---------------------------------------------------------------------------
# compilation
# ---------------------------------------------------------------------------


def _const_expr_value(e, ctx):
    """Evaluate a row-constant operand once; _MISS when `e` is not
    row-constant (it references the document)."""
    from surrealdb_tpu.expr.ast import (
        ArrayExpr, Constant, Literal, Param, Prefix,
    )

    if isinstance(e, Literal):
        return e.value
    if isinstance(e, (Param, Constant)):
        from surrealdb_tpu.exec.eval import evaluate

        return evaluate(e, ctx)
    if isinstance(e, ArrayExpr):
        out = []
        for x in e.items:
            v = _const_expr_value(x, ctx)
            if v is _MISS:
                return _MISS
            out.append(v)
        return out
    if isinstance(e, Prefix) and e.op == "-":
        v = _const_expr_value(e.expr, ctx)
        if v is _MISS:
            return _MISS
        from surrealdb_tpu.exec.operators import neg

        try:
            return neg(v)
        except SdbError:
            return _MISS
    return _MISS


_MISS = object()


def _field_node(e):
    from surrealdb_tpu.expr.ast import Idiom, PField

    if isinstance(e, Idiom) and e.parts and all(
        isinstance(p, PField) for p in e.parts
    ):
        return _Field(tuple(p.name for p in e.parts))
    return None


def _const_ok_for_cmp(v) -> bool:
    import math
    from decimal import Decimal

    if isinstance(v, float) and math.isnan(v):
        return False
    if isinstance(v, int) and not isinstance(v, bool) and abs(v) > _I53:
        return False
    if isinstance(v, Decimal):
        try:
            f = float(v)
        except (OverflowError, ValueError):
            return False
        if math.isnan(f):
            return False
    return True


def compile_expr(e, ctx):
    """Compile an expression into a vectorized node; None = unsupported
    (the caller keeps the whole expression on the scalar path)."""
    if not _enabled():
        return None
    from surrealdb_tpu.expr.ast import Binary, Prefix

    fn = _field_node(e)
    if fn is not None:
        return fn
    if isinstance(e, Prefix):
        inner = compile_expr(e.expr, ctx)
        if inner is None:
            return None
        if e.op == "!":
            return _Not(inner)
        if e.op == "-":
            return _Neg(inner)
        return None
    if not isinstance(e, Binary):
        return None
    op = e.op
    if op in ("&&", "||"):
        l = compile_expr(e.lhs, ctx)
        r = compile_expr(e.rhs, ctx)
        if l is None or r is None:
            return None
        return _Logic(op, l, r)
    if op in ("∈", "∉"):
        l = compile_expr(e.lhs, ctx)
        if l is None or isinstance(l, _Logic):
            # &&/|| VALUE semantics return the deciding operand, not a
            # bool — only their truthiness vectorizes, never their value
            return None
        v = _const_expr_value(e.rhs, ctx)
        if v is _MISS:
            return None
        from surrealdb_tpu.val import SSet

        if isinstance(v, SSet):
            v = list(v.items)
        if not isinstance(v, list):
            return None
        elems = []
        for x in v:
            if not _const_ok_for_cmp(x):
                return None
            elems.append(_Const(x))
        return _In(l, elems, op == "∉")
    if op in _CMP_OPS or op in _ARITH_OPS:
        from decimal import Decimal

        sides = []
        for s in (e.lhs, e.rhs):
            v = _const_expr_value(s, ctx)
            if v is not _MISS:
                if not _const_ok_for_cmp(v):
                    return None
                if op in _ARITH_OPS and isinstance(v, Decimal):
                    # scalar arithmetic stays in Decimal (value AND
                    # result type); the f64 kernel would not
                    return None
                sides.append(_Const(v))
                continue
            sub = compile_expr(s, ctx)
            if sub is None or isinstance(sub, _Logic):
                # &&/|| value semantics (see the IN branch above)
                return None
            sides.append(sub)
        l, r = sides
        if isinstance(l, _Const) and isinstance(r, _Const):
            return None  # constant folding is the static evaluator's job
        if op in _ARITH_OPS:
            return _Arith(op, l, r)
        return _Cmp(op, l, r)
    return None


class VecPred:
    """A compiled WHERE predicate: `masks(colset, ctx)` returns
    (pass_mask, fallback_mask) — fallback rows must re-run the full
    scalar predicate. None from the kernel (runtime bail) surfaces as
    an all-fallback answer."""

    __slots__ = ("node", "paths")

    def __init__(self, node):
        self.node = node
        p = set()
        node.paths(p)
        self.paths = p

    def masks(self, colset, ctx):
        col = self.node.eval(colset, ctx)
        if col is None:
            n = colset.n
            return np.zeros(n, bool), np.ones(n, bool)
        t, e = _truthy(col)
        return t & ~e, e


def compile_predicate(cond, ctx):
    """Compile a WHERE tree; None = keep the scalar row loop."""
    if cond is None:
        return None
    node = compile_expr(cond, ctx)
    if node is None:
        return None
    return VecPred(node)


# ---------------------------------------------------------------------------
# grouping core
# ---------------------------------------------------------------------------


class _View:
    """A masked, row-aligned view over a column set: numpy payloads are
    compressed eagerly (cheap), python values resolve through the index
    map only when touched."""

    __slots__ = ("col", "idx", "rank", "num", "is_int", "_strs", "n")

    def __init__(self, col, idx):
        self.col = col
        self.idx = idx
        self.rank = col.rank[idx] if idx is not None else col.rank
        self.num = col.num[idx] if idx is not None else col.num
        self.is_int = col.is_int[idx] if idx is not None else col.is_int
        self._strs = None
        self.n = len(self.rank)

    @property
    def strs(self):
        if self._strs is None:
            s = self.col.strs
            self._strs = s[self.idx] if self.idx is not None else s
        return self._strs

    def value_at(self, j):
        i = int(self.idx[j]) if self.idx is not None else int(j)
        return col_value_at(self.col, i)


def _factorize(view):
    """Grouping codes for one key column — two rows share a code iff
    `hashable(a) == hashable(b)` would put them in one legacy group
    (int 1 and float 1.0 share; True and 1 do not)."""
    r = view.rank
    n = view.n
    codes = np.zeros(n, np.int64)
    codes[r == RANK_NULL] = 1
    bm = r == RANK_BOOL
    if bm.any():
        codes[bm] = 2 + view.num[bm].astype(np.int64)
    base = 4
    nm = r == RANK_NUM
    if nm.any():
        _u, inv = np.unique(view.num[nm], return_inverse=True)
        codes[nm] = base + inv
        base += len(_u)
    sm = r == RANK_STR
    if sm.any():
        # dict factorization (exact Python string equality, O(n) hash
        # lookups) — np.unique over an object array would sort with
        # per-element Python comparisons
        seen: dict = {}
        sub = np.empty(int(sm.sum()), np.int64)
        for i, s in enumerate(view.strs[sm].tolist()):
            code = seen.get(s)
            if code is None:
                code = seen[s] = len(seen)
            sub[i] = code
        codes[sm] = base + sub
    return codes


def _combine_codes(code_list):
    combined = code_list[0]
    for c in code_list[1:]:
        m = int(c.max()) + 1 if len(c) else 1
        combined = combined * m + c
        _u, combined = np.unique(combined, return_inverse=True)
    u, first_idx, inv = np.unique(
        combined, return_index=True, return_inverse=True
    )
    return inv.astype(np.int64), first_idx, len(u)


class _Groups:
    __slots__ = ("inv", "first", "G", "order", "starts", "counts")

    def __init__(self, inv, first, G):
        self.inv = inv
        self.first = first
        self.G = G
        self.order = np.argsort(inv, kind="stable")
        self.counts = np.bincount(inv, minlength=G)
        ends = np.cumsum(self.counts)
        self.starts = ends - self.counts

    def seg(self, g):
        return self.order[self.starts[g]:self.starts[g] + self.counts[g]]


def _group_sum(view, seg, want_mean=False):
    """math::sum / the sum half of math::mean over one group segment,
    bit-identical to the scalar left-to-right fold."""
    r = view.rank[seg]
    nm = r == RANK_NUM
    cnt = int(nm.sum())
    if cnt == 0:
        return (0, 0) if want_mean else 0
    sub = seg[nm]
    ints = view.is_int[sub]
    vals = view.num[sub]
    if ints.all():
        if cnt * float(np.max(np.abs(vals))) < float(1 << 62):
            total = int(np.cumsum(vals.astype(np.int64))[-1])
        else:
            total = 0
            for v in vals.tolist():
                total += int(v)
    elif not ints.any():
        total = float(np.cumsum(vals)[-1])
    else:
        # mixed int/float: promotion points matter — exact scalar fold
        total = 0
        it = ints.tolist()
        for v, isi in zip(vals.tolist(), it):
            total = total + (int(v) if isi else v)
    return (total, cnt) if want_mean else total


def _agg_call_shape(expr):
    """(fname, arg_expr) for the directly-vectorizable aggregate calls;
    None otherwise (per-group scalar fallback)."""
    from surrealdb_tpu.expr.ast import FunctionCall

    if not isinstance(expr, FunctionCall):
        return None
    fname = expr.name.lower()
    if fname == "count" and not expr.args:
        return (fname, None)
    if fname in ("count", "math::sum", "math::min", "math::max",
                 "math::mean", "array::group") and len(expr.args) == 1:
        return (fname, expr.args[0])
    return None


class _GroupPlan:
    """Everything `group_core` computed: emission-ordered group list +
    per-group member segments + the views it grouped on."""

    __slots__ = ("groups", "emit", "views", "n")


def _build_groups(key_nodes, colset, ctx, mask_idx):
    views = []
    for node in key_nodes:
        col = node.eval(colset, ctx)
        if col is None:
            return None
        v = _View(col, mask_idx)
        if (v.rank == RANK_EXOTIC).any():
            return None  # exotic group keys: legacy dict grouping
        views.append(v)
    if not views:
        return None
    codes = [_factorize(v) for v in views]
    inv, first, G = _combine_codes(codes)
    return views, _Groups(inv, first, G)


def group_core(n_stmt, key_exprs, ctx, colset, mask_idx,
               sources_sorted_fn):
    """Shared vectorized GROUP BY core. `sources_sorted_fn(order)`
    returns member Source rows for per-group scalar fallback, or None
    when the caller cannot materialize rows (whole-table tier — any
    fallback need bails the tier instead).

    Returns the output rows (emission order = group keys sorted by the
    legacy comparator) or None when this statement can't be served
    vectorized."""
    from surrealdb_tpu.err import QueryCancelled, QueryTimeout
    from surrealdb_tpu.exec.statements import _set_out_field, expr_name
    from surrealdb_tpu.val import copy_value, sort_key

    key_nodes = []
    for g in key_exprs:
        node = compile_expr(g, ctx)
        if node is None:
            return None
        key_nodes.append(node)
    built = _build_groups(key_nodes, colset, ctx, mask_idx)
    if built is None:
        return None
    views, groups = built
    G = groups.G

    # emission order: representative key values, legacy comparator
    reps = []
    for g in range(G):
        f = groups.first[g]
        reps.append(tuple(v.value_at(f) for v in views))
    emit = sorted(range(G), key=lambda g: tuple(
        sort_key(v) for v in reps[g]
    ))

    # plan each output field once, then fill per group
    out_rows = [dict() for _ in range(G)]
    members_cache = [None]

    def members(g):
        if members_cache[0] is None:
            srcs = sources_sorted_fn(groups.order)
            if srcs is None:
                return None
            members_cache[0] = srcs
        s = int(groups.starts[g])
        return members_cache[0][s:s + int(groups.counts[g])]

    is_value = n_stmt.value is not None
    fields = []
    if is_value:
        fields.append((n_stmt.value, "__value__"))
    else:
        for expr, alias in n_stmt.exprs:
            if expr == "*":
                return None  # grouped `*` is a statement error upstream
            fields.append((expr, alias or expr_name(expr)))

    gb = key_exprs
    try:
        for expr, name in fields:
            ctx.check_deadline()
            vals_out = _agg_field(
                expr, n_stmt, ctx, colset, mask_idx, groups, views,
                gb, members, reps, is_value=is_value,
            )
            if vals_out is None:
                return None
            for g in range(G):
                v = vals_out[g]
                if isinstance(v, (list, dict)):
                    v = copy_value(v)
                if name == "__value__":
                    out_rows[g] = v
                else:
                    _set_out_field(out_rows[g], name, v)
    except (QueryTimeout, QueryCancelled):
        raise
    except SdbError:
        # a scalar fallback raised: bail so the legacy group loop
        # re-raises the exact error at the exact (sorted-group-order)
        # position — field-major fallback here could surface a
        # different group's error first
        return None
    _count(ctx.ds, "agg_groups", G)
    return [out_rows[g] for g in emit]


def _agg_field(expr, n_stmt, ctx, colset, mask_idx, groups, views,
               gb, members, reps, is_value=False):
    """Per-group values for one output field; None bails the tier."""
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.operators import float_div
    from surrealdb_tpu.exec.statements import _is_aggregate

    G = groups.G
    if _is_aggregate(expr):
        shape = _agg_call_shape(expr)
        if shape is not None:
            fname, arg = shape
            if fname == "count" and arg is None:
                return [int(groups.counts[g]) for g in range(G)]
            node = compile_expr(arg, ctx)
            view = None
            if node is not None:
                col = node.eval(colset, ctx)
                if col is not None:
                    view = _View(col, mask_idx)
            if view is None:
                return _per_group_fallback(expr, groups, members, ctx)
            exotic = view.rank == RANK_EXOTIC
            if fname == "count":
                if exotic.any():
                    return _per_group_fallback(expr, groups, members, ctx)
                t, _e = _truthy_view(view)
                w = np.bincount(groups.inv, weights=t.astype(np.float64),
                                minlength=G)
                return [int(w[g]) for g in range(G)]
            if fname == "array::group":
                if not isinstance(node, _Field):
                    return _per_group_fallback(expr, groups, members, ctx)
                out = []
                for g in range(G):
                    flat = []
                    for j in groups.seg(g):
                        v = view.col.vals[
                            int(view.idx[j]) if view.idx is not None
                            else int(j)
                        ]
                        if isinstance(v, list):
                            flat.extend(v)
                        else:
                            flat.append(v)
                    out.append(flat)
                return out
            if exotic.any():
                return _per_group_fallback(expr, groups, members, ctx)
            if fname == "math::sum":
                return [_group_sum(view, groups.seg(g)) for g in range(G)]
            if fname == "math::mean":
                out = []
                for g in range(G):
                    total, cnt = _group_sum(view, groups.seg(g),
                                            want_mean=True)
                    out.append(float("nan") if cnt == 0
                               else float_div(total, cnt))
                return out
            # math::min / math::max: any non-numeric member is the exact
            # scalar coercion error — per-group fallback raises it
            out = []
            for g in range(G):
                seg = groups.seg(g)
                r = view.rank[seg]
                if not (r == RANK_NUM).all():
                    return _per_group_fallback(expr, groups, members,
                                               ctx)
                vals = view.num[seg]
                j = int(np.argmin(vals)) if fname == "math::min" \
                    else int(np.argmax(vals))
                out.append(view.value_at(seg[j]))
            return out
        return _per_group_fallback(expr, groups, members, ctx)
    if any(expr == g for g in gb):
        ki = next(i for i, g in enumerate(gb) if expr == g)
        return [reps[g][ki] for g in range(G)]
    if is_value:
        # non-aggregate SELECT VALUE with GROUP: evaluate on the first
        # member of each group (legacy `_apply_group` semantics)
        node = compile_expr(expr, ctx)
        view = None
        if node is not None:
            col = node.eval(colset, ctx)
            if col is not None:
                view = _View(col, mask_idx)
        if view is not None and not (view.rank == RANK_EXOTIC).any():
            return [view.value_at(groups.first[g]) for g in range(G)]
        out = []
        for g in range(G):
            m = members(g)
            if m is None:
                return None
            first = m[0]
            d = first.doc if first.rid is not None else first.value
            out.append(evaluate(expr, ctx.with_doc(d, first.rid)))
        return out
    # implicit collect: the expression evaluates per member row
    node = compile_expr(expr, ctx)
    view = None
    if node is not None:
        col = node.eval(colset, ctx)
        if col is not None:
            view = _View(col, mask_idx)
    if view is None or (view.rank == RANK_EXOTIC).any():
        return _collect_fallback(expr, groups, members, ctx)
    return [
        [view.value_at(j) for j in groups.seg(g)] for g in range(G)
    ]


def _truthy_view(view):
    col = Column(view.n, view.rank, view.num, view.is_int, None)
    col._strs = view._strs if view._strs is not None else None
    if col._strs is None and (view.rank == RANK_STR).any():
        col._strs = view.strs
    return _truthy(col)


def _per_group_fallback(expr, groups, members, ctx):
    from surrealdb_tpu.exec.statements import _eval_aggregate

    out = []
    for g in range(groups.G):
        m = members(g)
        if m is None:
            return None
        out.append(_eval_aggregate(expr, m, ctx))
    return out


def _collect_fallback(expr, groups, members, ctx):
    from surrealdb_tpu.exec.eval import evaluate

    out = []
    for g in range(groups.G):
        m = members(g)
        if m is None:
            return None
        vals = []
        for src in m:
            d = src.doc if src.rid is not None else src.value
            vals.append(evaluate(expr, ctx.with_doc(d, src.rid)))
        out.append(vals)
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def group_sources(rows, n_stmt, ctx, aliases):
    """Streaming-tier vectorized GROUP BY over drained Source rows.
    Returns the grouped output rows, or None → legacy `_apply_group`."""
    if not _enabled() or not rows:
        return None
    from surrealdb_tpu.exec.batch import BatchCols
    from surrealdb_tpu.exec.statements import _resolve_alias

    gb = [_resolve_alias(g, aliases) for g in (n_stmt.group or [])]
    if not gb:
        return None
    colset = BatchCols(rows)

    def sources_sorted(order):
        return [rows[int(i)] for i in order]

    out = group_core(n_stmt, gb, ctx, colset, None, sources_sorted)
    if out is not None:
        _count(ctx.ds, "agg_streamed")
        _count(ctx.ds, "rows_vectorized", len(rows))
    return out


class _TableColset:
    __slots__ = ("tc", "n")

    def __init__(self, tc):
        self.tc = tc
        self.n = tc.n

    def col(self, parts):
        return self.tc.cols[parts]


def columnar_group_select(n_stmt, tb, ctx, aliases):
    """Whole-table tier: serve a grouped SELECT straight from the
    version-keyed column store — no Source materialization at all.
    Returns output rows (pre ORDER/START/LIMIT) or None to stream."""
    if not _enabled():
        return None
    from surrealdb_tpu.exec.batch import get_table_columns
    from surrealdb_tpu.exec.statements import _resolve_alias

    gb = [_resolve_alias(g, aliases) for g in (n_stmt.group or [])]
    if not gb:
        return None
    pred = None
    if n_stmt.cond is not None:
        pred = compile_predicate(n_stmt.cond, ctx)
        if pred is None:
            return None
    # collect every path the statement touches so ONE scan builds them
    paths = set()
    nodes = []
    for g in gb:
        node = compile_expr(g, ctx)
        if node is None:
            return None
        node.paths(paths)
        nodes.append(node)
    exprs = [n_stmt.value] if n_stmt.value is not None else [
        e for e, _a in n_stmt.exprs
    ]
    for e in exprs:
        if e == "*":
            return None
        for sub in _touched_subexprs(e):
            node = compile_expr(sub, ctx)
            if node is not None:
                node.paths(paths)
    if pred is not None:
        paths |= pred.paths
    tc = get_table_columns(ctx, tb, paths)
    if tc is None:
        return None
    colset = _TableColset(tc)
    if pred is not None:
        mask, fb = pred.masks(colset, ctx)
        if fb.any():
            return None  # scalar-fallback rows need real documents
        idx = np.flatnonzero(mask)
    else:
        idx = None
    out = group_core(n_stmt, gb, ctx, colset, idx, lambda order: None)
    if out is not None:
        _count(ctx.ds, "agg_columnar")
        _count(ctx.ds, "rows_vectorized", tc.n)
    return out


# ---------------------------------------------------------------------------
# vectorized ORDER BY (colstore-backed lexsort)
# ---------------------------------------------------------------------------


def _order_codes(col):
    """Dense per-row sort codes for one ORDER BY key column, exactly
    mirroring `value_cmp` over the vectorizable ranks: type rank first
    (NONE < NULL < bool < number < string), then the typed comparator
    inside the rank (numeric compare for bool/number — int 1 ties
    float 1.0; Python string order for strings). Equal-comparing rows
    share a code, so later keys and sort stability decide them —
    byte-identical to the scalar `_OrderKey` path."""
    n = col.n
    rank = col.rank.astype(np.int64)
    val = col.num.copy()
    smask = col.rank == RANK_STR
    if smask.any():
        sv = col.strs[smask].tolist()
        uniq = {s: i for i, s in enumerate(sorted(set(sv)))}
        val[np.flatnonzero(smask)] = [float(uniq[s]) for s in sv]
    order = np.lexsort((val, rank))
    sr = rank[order]
    svv = val[order]
    new = np.ones(n, bool)
    new[1:] = (sr[1:] != sr[:-1]) | (svv[1:] != svv[:-1])
    codes = np.empty(n, np.int64)
    codes[order] = np.cumsum(new) - 1
    return codes


def lexsort_sources(rows, items, ctx, keep=None):
    """Colstore-backed ORDER BY over drained Source rows: when every
    key is a clean scalar column (compilable expression, no exotic
    rows, no COLLATE/NUMERIC), sort via np.lexsort over dense codes
    instead of the row-at-a-time key extractor. Returns the reordered
    (and `keep`-bounded) row list, or None → the exact scalar path
    (same fallback rules as every kernel in this module: bail, never
    guess). `items` are `(resolved_expr, dir, collate, numeric)`.
    Small row sets stay scalar — below the floor the per-column setup
    costs more than the row loop it replaces."""
    if not _enabled() or len(rows) < 64:
        return None
    from surrealdb_tpu.exec.batch import BatchCols

    for _expr, _d, collate, numeric in items:
        if collate or numeric:
            return None  # collation/numeric string order: scalar path
    nodes = []
    for expr, _d, _c, _n in items:
        node = compile_expr(expr, ctx)
        if node is None:
            return None
        nodes.append(node)
    colset = BatchCols(rows)
    keys = []
    for node, (_e, d, _c, _n) in zip(nodes, items):
        col = node.eval(colset, ctx)
        if col is None or (col.rank == RANK_EXOTIC).any():
            # exotic rows (links, datetimes, NaN, >2^53 ints, nested
            # values, missing docs) need the scalar comparator
            return None
        codes = _order_codes(col)
        keys.append(codes if d == "asc" else -codes)
    # np.lexsort is stable and sorts by the LAST key first — reverse so
    # the first ORDER BY key is primary; equal full-keys keep original
    # row order, exactly like the stable scalar sort (and like
    # heapq.nsmallest on the keep-bounded path)
    order = np.lexsort(tuple(reversed(keys)))
    if keep is not None and keep < len(order):
        order = order[:keep]
    _count(ctx.ds, "order_lexsort")
    _count(ctx.ds, "rows_vectorized", len(rows))
    return [rows[int(i)] for i in order]


# ---------------------------------------------------------------------------
# fused filtered-KNN (hybrid vector + predicate queries)
# ---------------------------------------------------------------------------

# Cross-query batcher for fused (candidate mask, query vector, k)
# payloads: riders arriving together ride ONE scoring kernel per
# (matrix, mask) group — the PR-6 device/batcher.py discipline applied
# to hybrid brute-force KNN. Lazy: embedded datastores that never run
# a hybrid query pay nothing.
_FUSED_BATCHER = None


def _get_fused_batcher():
    global _FUSED_BATCHER
    if _FUSED_BATCHER is None:
        from surrealdb_tpu.device import DeviceOpError, DeviceUnavailable
        from surrealdb_tpu.device.batcher import DeviceBatcher

        _FUSED_BATCHER = DeviceBatcher(
            dispatch=_fused_dispatch,
            fallback=_fused_host_single,
            retryable=(DeviceUnavailable, DeviceOpError),
        )
    return _FUSED_BATCHER


def _fused_host_single(p):
    """Exact host scoring for one rider (the same `_host_distances`
    ladder the legacy brute path uses)."""
    from surrealdb_tpu.idx.vector import TpuVectorIndex

    xs = p["mat"][p["cand"]]
    tmp = TpuVectorIndex.__new__(TpuVectorIndex)
    tmp.vecs = xs
    tmp.metric = p["metric"]
    tmp.mink_p = p["p"]
    d = tmp._host_distances(p["q"])
    k = min(p["k"], xs.shape[0])
    idx = np.argpartition(d, k - 1)[:k] if k < xs.shape[0] else \
        np.arange(xs.shape[0])
    idx = idx[np.argsort(d[idx], kind="stable")]
    return [(int(p["cand"][int(i)]), float(d[i])) for i in idx]


def _fused_dispatch(payloads):
    """One coalesced dispatch: group riders by (matrix, candidate-mask)
    and run ONE batched scoring kernel per group — device when healthy
    and the candidate set is big enough, exact host ladder otherwise."""
    from surrealdb_tpu import cnf
    from surrealdb_tpu.device import get_supervisor

    groups = {}
    for i, p in enumerate(payloads):
        groups.setdefault(p["token"], []).append(i)
    results = [None] * len(payloads)
    sup = get_supervisor()
    for token, idxs in groups.items():
        p0 = payloads[idxs[0]]
        cand = p0["cand"]
        n = int(cand.shape[0])
        if n == 0:
            for i in idxs:
                results[i] = []
            continue
        use_device = n >= cnf.KNN_DEVICE_MIN_ROWS and sup.fast_path() \
            and len(idxs) > 0
        if use_device:
            xs = p0["mat"][cand]
            qs = np.stack([payloads[i]["q"] for i in idxs])
            kmax = min(max(payloads[i]["k"] for i in idxs), n)
            _t, _m, bufs = sup.call(
                "brute_knn",
                {"k": kmax, "metric": p0["metric"], "p": p0["p"]},
                [xs, qs.astype(np.float32)],
            )
            d, ind = bufs[0], bufs[1]
            for row, i in enumerate(idxs):
                k = min(payloads[i]["k"], n)
                results[i] = [
                    (int(cand[int(ii)]), float(dd))
                    for dd, ii in zip(d[row][:k], ind[row][:k])
                    if ii >= 0
                ]
        else:
            for i in idxs:
                results[i] = _fused_host_single(payloads[i])
    return results


def fused_brute_knn(tb, knn, qv, rest, ctx):
    """Serve a brute-force (possibly filtered) KNN from the column
    store: the residual predicate evaluates vectorized over the table
    columns, and only surviving rows ship — as (candidate mask, query
    vector, k) — through the cross-query batcher for scoring. Returns
    [(rid, dist)] or None → the legacy row-at-a-time scan."""
    if not _enabled():
        return None
    from surrealdb_tpu.exec.batch import _count, get_table_columns
    from surrealdb_tpu.expr.ast import Idiom, PField

    lhs = knn.lhs
    if not (isinstance(lhs, Idiom) and len(lhs.parts) == 1
            and isinstance(lhs.parts[0], PField)):
        return None
    field = lhs.parts[0].name
    if not (isinstance(qv, list) and qv and all(
        isinstance(x, (int, float)) and not isinstance(x, bool)
        for x in qv
    )):
        return None
    dim = len(qv)
    pred = None
    if rest is not None:
        pred = compile_predicate(rest, ctx)
        if pred is None:
            return None
    from surrealdb_tpu.col import get_vector_column

    col = get_vector_column(ctx, tb, field, dim)
    if col is None or col.bad_ids or col.ids_enc is None:
        # non-conforming rows: the legacy scan's first-row-dim /skip
        # semantics must decide, not the column store
        return None
    if pred is not None:
        tc = get_table_columns(ctx, tb, pred.paths)
        if tc is None or tc.version != col.version:
            return None
        mask, fb = pred.masks(_TableColset(tc), ctx)
        if fb.any():
            return None  # fallback rows need real documents
        pos = _vec_align(ctx.ds, tb, field, dim, tc, col)
        if pos is None:
            return None
        cand = np.flatnonzero(mask[pos])
    else:
        cand = np.arange(len(col.ids), dtype=np.int64)
    if len(cand) == 0:
        return []
    from surrealdb_tpu.ops.metrics import normalize_metric

    metric, p = normalize_metric(knn.dist or "euclidean")
    q = np.asarray(qv, dtype=np.float32)
    # exact mask bytes in the token — a hash collision between two
    # different candidate sets would score a rider against the wrong
    # rows, silently
    token = (id(col.mat), cand.tobytes(), metric, float(p))
    payload = {
        "mat": col.mat, "cand": cand, "q": q, "k": int(knn.k),
        "metric": metric, "p": float(p), "token": token,
    }
    _count(ctx.ds, "fused_knn_queries")
    out = _get_fused_batcher().submit(payload)
    rids = col.ids
    from surrealdb_tpu.val import RecordId

    return [(RecordId(tb, rids[vi]), dist) for vi, dist in out]


def _vec_align(ds, tb, field, dim, tc, col):
    """Row positions of the vector column inside the table column set
    (both are key-ordered scans of the same snapshot; the vector rows
    are a subsequence). Cached per write version."""
    cache = getattr(ds, "_fused_align", None)
    if cache is None:
        cache = ds._fused_align = {}
    key = (tb, field, dim)
    hit = cache.get(key)
    if hit is not None and hit[0] == tc.version and hit[1] == id(col):
        return hit[2]
    te = tc.ids_enc
    pos = np.empty(len(col.ids_enc), np.int64)
    j = 0
    for i, s in enumerate(col.ids_enc):
        while j < len(te) and te[j] != s:
            j += 1
        if j >= len(te):
            return None  # snapshots diverged: rebuild next query
        pos[i] = j
        j += 1
    cache[key] = (tc.version, id(col), pos)
    return pos


def _touched_subexprs(e):
    """Field-bearing argument expressions of an output field (for path
    pre-collection; over-approximation is fine — unneeded columns cost
    one vector each)."""
    from surrealdb_tpu.expr.ast import Binary, FunctionCall, Idiom, Prefix

    out = []

    def rec(x):
        if isinstance(x, Idiom):
            out.append(x)
        elif isinstance(x, FunctionCall):
            for a in x.args:
                rec(a)
        elif isinstance(x, Binary):
            rec(x.lhs)
            rec(x.rhs)
        elif isinstance(x, Prefix):
            rec(x.expr)

    rec(e)
    return out
