"""Statement execution (reference: core/src/dbs/executor.rs + exec/planner.rs
SELECT pipeline Scan→Filter→Split→Aggregate→Sort→Limit; write statements run
the document pipeline in exec/document.py)."""

from __future__ import annotations

import random as _random
import time

from surrealdb_tpu import key as K
from surrealdb_tpu.catalog import (
    AccessDef,
    AnalyzerDef,
    DatabaseDef,
    EventDef,
    FieldDef,
    FunctionDef,
    IndexDef,
    NamespaceDef,
    ParamDef,
    SequenceDef,
    SubscriptionDef,
    TableDef,
    UserDef,
)
from surrealdb_tpu.err import (
    BreakException,
    ContinueException,
    ReturnException,
    SdbError,
    ThrownError,
)
from surrealdb_tpu.exec.coerce import coerce
from surrealdb_tpu.exec.context import Ctx
from surrealdb_tpu.exec.eval import evaluate, fetch_record, walk
from surrealdb_tpu.expr.ast import *  # noqa: F401,F403
from surrealdb_tpu.val import (
    NONE,
    Range,
    RecordId,
    Table,
    Uuid,
    copy_value,
    is_truthy,
    render,
    sort_key,
    value_cmp,
)

# ---------------------------------------------------------------------------
# statement dispatch (expression position)
# ---------------------------------------------------------------------------


def eval_statement(node, ctx: Ctx):
    t = type(node)
    fn = _STMTS.get(t)
    if fn is not None:
        if isinstance(node, (DefineNamespace, DefineDatabase, DefineTable,
                             DefineField, DefineIndex, DefineEvent,
                             DefineAnalyzer, DefineUser, DefineAccess,
                             DefineModule,
                             DefineSequence, DefineConfig, DefineParam,
                             DefineFunction, RemoveStmt,
                             InfoStmt, RebuildIndex)):
            node = _ddl_resolve(node, ctx)
        return fn(node, ctx)
    return evaluate(node, ctx)


def _ddl_resolve(n, ctx: Ctx):
    """Materialize expression-valued DDL attributes — names, ON tables,
    comments, durations — at execution time. Reference: parameterized
    schema statements (language-tests/tests/language/parameterized/schema)
    compute each name/comment Expr in the DefineStatement itself."""
    import dataclasses

    changes = {}
    for a in ("name", "tb", "comment", "batch", "start", "target", "target2"):
        v = getattr(n, a, None)
        if not isinstance(v, Node):
            continue
        rv = evaluate(v, ctx)
        if a == "comment":
            changes[a] = None if rv is NONE else rv
        elif a in ("batch", "start"):
            if not isinstance(rv, int) or isinstance(rv, bool):
                raise SdbError(f"Expected an int but found {render(rv)}")
            changes[a] = rv
        else:
            if not isinstance(rv, str):
                raise SdbError(
                    f"Expected a string but found {render(rv)}"
                )
            changes[a] = rv
    dur = getattr(n, "duration", None)
    if isinstance(dur, dict) and any(isinstance(x, Node) for x in dur.values()):
        changes["duration"] = {
            k: (evaluate(x, ctx) if isinstance(x, Node) else x)
            for k, x in dur.items()
        }
    cfg = getattr(n, "config", None)
    if isinstance(cfg, dict):
        newcfg = {
            k: (evaluate(x, ctx) if isinstance(x, Node) and k in
                ("key", "name", "backend", "issuer_key", "path", "comment",
                 "namespace", "database")
                else x)
            for k, x in cfg.items()
        }
        if newcfg.get("comment") is NONE:
            newcfg["comment"] = None
        if newcfg != cfg:
            changes["config"] = newcfg
    if changes:
        n = dataclasses.replace(n, **changes)
    # a $param field name is a whole idiom string ("a.b") — parse it
    if (isinstance(n, DefineField) or
            (isinstance(n, RemoveStmt) and n.kind == "field")) and \
            isinstance(n.name, str):
        from surrealdb_tpu.syn.parser import Parser

        n = dataclasses.replace(n, name=Parser(n.name)._field_name_parts())
    return n


# ---------------------------------------------------------------------------
# simple statements
# ---------------------------------------------------------------------------


def _s_let(n: LetStmt, ctx):
    if n.name in ("access", "auth", "token", "session"):
        # reference cnf PROTECTED_PARAM_NAMES
        raise SdbError(
            f"'{n.name}' is a protected variable and cannot be set"
        )
    v = evaluate(n.what, ctx)
    if n.kind is not None:
        try:
            v = coerce(v, n.kind)
        except SdbError as e:
            raise SdbError(
                f"Tried to set `${n.name}`, but couldn't coerce value: {e}"
            )
    ctx.vars[n.name] = v
    return NONE


def _s_return(n: ReturnStmt, ctx):
    v = evaluate(n.what, ctx)
    if n.fetch:
        v = apply_fetch(v, n.fetch, ctx)
    raise ReturnException(v)


def _s_if(n: IfStmt, ctx):
    for cond, body in n.branches:
        if is_truthy(evaluate(cond, ctx)):
            return eval_statement(body, ctx)
    if n.otherwise is not None:
        return eval_statement(n.otherwise, ctx)
    return NONE


def _s_for(n: ForStmt, ctx):
    rng = evaluate(n.range, ctx)
    if isinstance(rng, Range):
        try:
            items = list(rng.iter_ints())
        except TypeError:
            raise SdbError("FOR range must have integer bounds")
    elif isinstance(rng, list):
        items = rng
    elif isinstance(rng, dict):
        items = list(rng.values())
    else:
        raise SdbError(f"Cannot iterate over {render(rng)} in a FOR loop")
    for item in items:
        c = ctx.child()
        c.vars[n.param] = item
        try:
            eval_statement(n.body, c)
        except BreakException:
            break
        except ContinueException:
            continue
    return NONE


def _s_break(n, ctx):
    raise BreakException()


def _s_continue(n, ctx):
    raise ContinueException()


def _s_throw(n: ThrowStmt, ctx):
    from surrealdb_tpu.exec.operators import to_string

    raise ThrownError(f"An error occurred: {to_string(evaluate(n.what, ctx))}")


def _s_sleep(n: SleepStmt, ctx):
    from surrealdb_tpu.val import Duration

    d = evaluate(n.duration, ctx)
    if isinstance(d, Duration):
        # sliced so KILL / deadline expiry interrupts within ~50ms
        # instead of parking the worker for the whole duration
        end = time.monotonic() + min(d.to_seconds(), 30)
        while True:
            ctx.check_deadline()
            left = end - time.monotonic()
            if left <= 0:
                break
            time.sleep(min(left, 0.05))
    return NONE


def _s_use(n: UseStmt, ctx):
    # empty-string namespaces/databases are legal (`USE NS ```)
    if n.ns is not None:
        ctx.session.ns = n.ns
        ctx.ns = n.ns
    if n.db is not None:
        ctx.session.db = n.db
        ctx.db = n.db
    return {
        "database": ctx.session.db if ctx.session.db is not None else NONE,
        "namespace": ctx.session.ns if ctx.session.ns is not None else NONE,
    }


def _s_option(n, ctx):
    if n.name.upper() == "IMPORT":
        # OPTION IMPORT: subsequent DEFINEs overwrite by default (import
        # streams re-define tables/fields; reference dbs/options.rs).
        # Scoped to THIS query run (the executor), not the session.
        if ctx.executor is not None:
            ctx.executor.import_mode = bool(n.value)
    return NONE


# ---------------------------------------------------------------------------
# target resolution — what a FROM/UPDATE/DELETE target yields
# ---------------------------------------------------------------------------


class Source:
    """One input row: a record (rid + doc) or a plain value. `_cols`
    holds per-row vectorized-expression values (exec/stream.py
    ColumnCache) — row-lifetime storage, so recycled object ids can't
    alias rows."""

    __slots__ = ("rid", "doc", "value", "_cols")

    def __init__(self, rid=None, doc=None, value=NONE):
        self.rid = rid
        self.doc = doc
        self.value = value
        self._cols = None


def _target_value(expr, ctx):
    """Evaluate a FROM target; bare idents become Tables."""
    if isinstance(expr, Idiom) and len(expr.parts) == 1 and isinstance(
        expr.parts[0], PField
    ):
        return Table(expr.parts[0].name)
    v = evaluate(expr, ctx)
    return v


def iterate_targets(what: list, ctx: Ctx, cond=None, stmt=None):
    """Yield Source objects for each target (reference dbs/iterator.rs
    Iterable collection)."""
    for expr in what:
        v = _target_value(expr, ctx)
        yield from _iterate_value(v, ctx, cond, stmt)


def _iterate_value(v, ctx, cond=None, stmt=None):
    ns, db = ctx.need_ns_db()
    if isinstance(v, Table):
        yield from _scan_table(v.name, ctx, cond, stmt)
    elif isinstance(v, RecordId):
        if isinstance(v.id, Range):
            yield from _scan_record_range(v, ctx)
        else:
            doc = fetch_record(ctx, v)
            yield Source(rid=v, doc=doc if doc is not NONE else NONE)
    elif isinstance(v, list):
        for x in v:
            yield from _iterate_value(x, ctx, cond, stmt)
    elif isinstance(v, dict):
        # objects are used as-is in SELECT; write statements resolve the id
        # themselves (reference prepare_computed: SELECT check happens first)
        yield Source(value=v)
    elif v is NONE or v is None:
        return
    else:
        yield Source(value=v)


def _scan_table(tb: str, ctx, cond=None, stmt=None):
    """Table scan — consults the index planner first (idx/planner.rs)."""
    from surrealdb_tpu.exec.eval import apply_computed_fields, computed_fields_of
    from surrealdb_tpu.idx.planner import plan_scan

    # the reference errors when scanning a table that was never defined
    # (language/statements/for/break_in_function.surql et al.)
    _ns0, _db0 = ctx.need_ns_db()
    if ctx.txn.get(K.tb_def(_ns0, _db0, tb)) is None:
        raise SdbError(f"The table '{tb}' does not exist")

    plan = plan_scan(tb, cond, ctx, stmt) if ctx.version is None else None
    if plan is not None:
        yield from plan
        return
    ns, db = ctx.need_ns_db()
    from surrealdb_tpu.kvs.api import deserialize

    has_computed = bool(computed_fields_of(tb, ctx))
    if ctx.version is not None:
        # as-of scan over the version history: last entry <= ts per id
        from surrealdb_tpu.exec.eval import version_ns

        ts = version_ns(ctx.version)
        hp = K.hist_prefix(ns, db, tb)
        cur_id = None
        best = None
        for k, raw in ctx.txn.scan(*K.prefix_range(hp)):
            ident = k[len(hp):-8]
            ets = int.from_bytes(k[-8:], "big")
            if ident != cur_id:
                if cur_id is not None and best:
                    yield _hist_source(tb, cur_id, best, has_computed, ctx)
                cur_id, best = ident, None
            if ets <= ts:
                best = raw
        if cur_id is not None and best:
            yield _hist_source(tb, cur_id, best, has_computed, ctx)
        return
    pre = K.record_prefix(ns, db, tb)
    beg, end = K.prefix_range(pre)
    plen = len(pre)
    for k, raw in ctx.txn.scan(beg, end):
        # the prefix pins (ns, db, tb): only the id needs decoding
        idv, _pos = K.dec_value(k, plen)
        rid = RecordId(tb, idv)
        doc = deserialize(raw)
        if has_computed:
            doc = apply_computed_fields(tb, doc, rid, ctx)
        yield Source(rid=rid, doc=doc)


def _hist_source(tb, ident_enc, raw, has_computed, ctx):
    from surrealdb_tpu.exec.eval import apply_computed_fields
    from surrealdb_tpu.kvs.api import deserialize

    doc = deserialize(raw)
    rid = doc.get("id") if isinstance(doc, dict) else None
    if not isinstance(rid, RecordId):
        from surrealdb_tpu.key import dec_value

        rid = RecordId(tb, dec_value(ident_enc)[0])
    if has_computed:
        doc = apply_computed_fields(tb, doc, rid, ctx)
    return Source(rid=rid, doc=doc)


def _scan_record_range(v: RecordId, ctx):
    ns, db = ctx.need_ns_db()
    rng: Range = v.id
    from surrealdb_tpu.kvs.api import deserialize

    if rng.beg is NONE:
        beg = K.record_prefix(ns, db, v.tb)
    else:
        beg = K.record(ns, db, v.tb, rng.beg)
        if not rng.beg_incl:
            beg += b"\x00"
    if rng.end is NONE:
        _, end = K.prefix_range(K.record_prefix(ns, db, v.tb))
    else:
        end = K.record(ns, db, v.tb, rng.end)
        if rng.end_incl:
            end += b"\xff"
    plen = len(K.record_prefix(ns, db, v.tb))
    for k, raw in ctx.txn.scan(beg, end):
        idv, _pos = K.dec_value(k, plen)
        yield Source(rid=RecordId(v.tb, idv), doc=deserialize(raw))


# ---------------------------------------------------------------------------
# permissions
# ---------------------------------------------------------------------------


def check_table_permission(tb: str, action: str, ctx: Ctx, doc=None, rid=None):
    """Row-level permission check (doc/check + scan operators). Returns
    truthy if the action is allowed for the session on this doc."""
    if ctx.session.is_owner or ctx.session.auth_level in ("editor",):
        return True
    if ctx._in_perm_check:
        # permission clauses evaluate with permissions disabled
        # (reference opt.new_with_perms(false)) — cyclic record links in
        # a predicate subquery must not recurse into more checks
        return True
    ns, db = ctx.need_ns_db()
    tdef = ctx.txn.get_val(K.tb_def(ns, db, tb))
    if tdef is None or tdef.permissions is None:
        return ctx.session.auth_level == "viewer" and action == "select"
    p = tdef.permissions.get(action, False)
    if p is True or p is False:
        return p
    c = ctx.with_doc(doc, rid)
    c._in_perm_check = True
    return is_truthy(evaluate(p, c))


# ---------------------------------------------------------------------------
# SELECT
# ---------------------------------------------------------------------------

_AGGREGATES = {
    "count", "math::sum", "math::mean", "math::min", "math::max",
    "math::stddev", "math::variance", "math::median", "math::mode",
    "math::product", "math::spread", "math::interquartile", "math::midhinge",
    "math::trimean", "math::bottom", "math::top", "math::percentile",
    "math::nearestrank", "time::min", "time::max", "array::group",
    "array::distinct", "array::flatten", "array::concat", "array::first",
    "array::last", "array::len", "array::max", "array::min", "array::sort",
    "array::join",
}


def _is_aggregate(expr) -> bool:
    if isinstance(expr, FunctionCall):
        if expr.name.lower() in _AGGREGATES:
            return True
        return any(_is_aggregate(a) for a in expr.args)
    if isinstance(expr, Binary):
        return _is_aggregate(expr.lhs) or _is_aggregate(expr.rhs)
    if isinstance(expr, Prefix):
        return _is_aggregate(expr.expr)
    return False


def expr_name(expr, sql=False) -> str:
    """Canonical output field name for an unaliased projection. sql=True
    renders for SQL output (reserved idents get backticks)."""
    if isinstance(expr, Idiom):
        from surrealdb_tpu.val import escape_ident as _esc

        out = []
        for p in expr.parts:
            if isinstance(p, tuple):
                out.append(expr_name(p[1], sql))
            elif isinstance(p, PField):
                # `@` is the repeat-subject marker, never escaped
                name = p.name if p.name == "@" else (
                    _esc(p.name) if sql else p.name
                )
                if out:
                    out.append("." + name)
                else:
                    out.append(name)
            elif isinstance(p, PRecurse):
                if p.min == p.max and p.min is not None:
                    rng = str(p.min)
                elif p.max is None:
                    rng = ".." if p.min in (None, 1) else f"{p.min}.."
                elif p.min in (None, 1):
                    rng = f"..{p.max}"
                else:
                    rng = f"{p.min}..{p.max}"
                ins = f"+{p.instruction}" if p.instruction else ""
                txt = ("." if out else "") + "{" + rng + ins + "}"
                inner = list(p.parts or [])
                if inner and all(
                    isinstance(x, PDestructure) for x in inner
                ):
                    txt += expr_name(Idiom(inner), sql)
                elif inner:
                    txt += "(" + expr_name(Idiom(inner), sql) + ")"
                out.append(txt)
            elif isinstance(p, PDestructure):
                fields = []
                for nm, wh in p.fields:
                    if wh is None:
                        fields.append(nm)
                    else:
                        sub_i = wh if isinstance(wh, Idiom) \
                            else Idiom(list(wh))
                        fields.append(f"{nm}: {expr_name(sub_i, sql)}")
                out.append(
                    ("." if out else "") + "{ " + ", ".join(fields) + " }"
                )
            elif isinstance(p, PAll):
                out.append(".*" if out else "*")
            elif isinstance(p, PIndex):
                out.append(f"[{expr_name(p.expr)}]")
            elif isinstance(p, PLast):
                out.append("[$]")
            elif isinstance(p, PGraph):
                arrow = {"out": "->", "in": "<-", "both": "<->", "ref": "<~"}[p.dir]
                if p.alias is not None:
                    aname = p.alias if isinstance(p.alias, str) \
                        else expr_name(p.alias, sql)
                    # ->(edge AS name): the step names the output field
                    out.append(("." if out else "") + aname)
                    continue
                if p.expr is not None:
                    from surrealdb_tpu.exec.render_def import _select_sql

                    out.append(f"{arrow}({_select_sql(p.expr)})")
                    continue
                names = ", ".join(w[0] for w in p.what) if p.what else "?"
                if len(p.what) <= 1:
                    out.append(f"{arrow}{names}")
                else:
                    out.append(f"{arrow}({names})")
            elif isinstance(p, PWhere):
                out.append("[WHERE]")
            elif isinstance(p, PMethod):
                out.append(f".{p.name}()")
            elif isinstance(p, PFlatten):
                out.append("…")
            else:
                out.append("")
        return "".join(out)
    if isinstance(expr, FunctionCall):
        return expr.name
    if isinstance(expr, Literal):
        return render(expr.value)
    if isinstance(expr, Param):
        return expr.name
    if isinstance(expr, Binary):
        # compound names render nested calls with their arguments
        # ("math::mean(v) + 1"), unlike bare top-level calls
        def sub(e):
            if isinstance(e, FunctionCall):
                from surrealdb_tpu.exec.render_def import _expr_sql

                return _expr_sql(e)
            return expr_name(e, sql)

        return f"{sub(expr.lhs)} {expr.op} {sub(expr.rhs)}"
    if isinstance(expr, Cast):
        return expr_name(expr.expr)
    if isinstance(expr, Subquery):
        return "subquery"
    if isinstance(expr, RecordIdLit):
        return expr.tb
    if isinstance(expr, Knn):
        return expr_name(expr.lhs)
    return "field"


def _ast_params(node, out, _depth=0, _in_sub=False):
    """Collect Param names referenced anywhere in an AST fragment. Inside a
    SELECT subquery $this refers to the subquery's own document, but
    $parent still points at the enclosing (grouped) document, so only
    `parent` is collected there; deeper subqueries re-bind it."""
    import dataclasses

    from surrealdb_tpu.expr.ast import Param as _Param, Subquery as _Sub

    if _depth > 40 or node is None:
        return
    if isinstance(node, _Param):
        if not _in_sub:
            out.add(node.name)
        elif node.name == "parent":
            out.add("parent")
        return
    if isinstance(node, _Sub) and isinstance(node.stmt, SelectStmt):
        if not _in_sub:
            _ast_params(node.stmt, out, _depth + 1, True)
        return
    if isinstance(node, (list, tuple)):
        for x in node:
            _ast_params(x, out, _depth + 1, _in_sub)
        return
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            _ast_params(getattr(node, f.name), out, _depth + 1, _in_sub)


def _check_group_params(n):
    """Grouped selects have no document for $this/$parent to refer to
    (reference catalog/aggregation.rs AggregateExprCollector)."""
    names: set = set()
    for expr, _a in n.exprs:
        if expr != "*":
            _ast_params(expr, names)
    if n.value is not None:
        _ast_params(n.value, names)
    if "this" in names or "self" in names:
        raise SdbError(
            "Invalid query: Found a `$this` parameter refering to the "
            "document of a group by select statement\n"
            "Select statements with a group by currently have no defined "
            "document to refer to"
        )
    if "parent" in names:
        raise SdbError(
            "Invalid query: Found a `$parent` parameter refering to the "
            "document of a GROUP select statement\n"
            "Select statements with a GROUP BY or GROUP ALL currently have "
            "no defined document to refer to"
        )


def _s_select(n: SelectStmt, ctx: Ctx):
    ctx.check_deadline()
    c = _timeout_ctx(n, ctx)
    if c is ctx:
        c = ctx.child()
    if n.group is not None:
        _check_group_params(n)
    if n.explain:
        return _explain_select(n, c)
    # VERSION clause
    if n.version is not None:
        from surrealdb_tpu.expr.ast import Subquery as _Subq

        if any(isinstance(w, _Subq) for w in n.what):
            raise SdbError(
                "Invalid query: VERSION clause cannot be used with a "
                "subquery source. Place the VERSION clause inside the "
                "subquery instead."
            )
        c.version = evaluate(n.version, ctx)
        from surrealdb_tpu.exec.eval import version_ns as _vns

        vts = _vns(c.version)
        for w in n.what:
            # only bare-ident targets name a table statically; anything
            # else must NOT be evaluated here (it runs again in
            # iterate_targets — double side effects)
            tbn = None
            if isinstance(w, Idiom) and len(w.parts) == 1 and \
                    isinstance(w.parts[0], PField):
                tbn = w.parts[0].name
            if tbn is not None:
                ns_v, db_v = c.need_ns_db()
                if c.txn.get_val_at(K.tb_def(ns_v, db_v, tbn), vts) is None:
                    raise SdbError(f"The table '{tbn}' does not exist")
    # streaming batched operator engine (execution engine A) for eligible
    # plain-scan shapes; everything else stays on the legacy recursive
    # path (reference plan_or_compute.rs legacy fallback)
    from surrealdb_tpu.exec.stream import _UNSUPPORTED, try_stream_select

    out = try_stream_select(n, c)
    if out is not _UNSUPPORTED:
        return out
    rows = []
    perms = not c.session.is_owner
    for src in iterate_targets(n.what, c, n.cond, n):
        c.check_deadline()
        if src.rid is not None and src.doc is NONE:
            # direct record fetch that doesn't exist -> no row
            continue
        if perms and src.rid is not None:
            if not check_table_permission(src.rid.tb, "select", c, src.doc, src.rid):
                continue
            from surrealdb_tpu.exec.document import reduce_fields

            if isinstance(src.doc, dict):
                src.doc = reduce_fields(src.rid.tb, src.doc, c)
        rows.append(src)
    # brute-force KNN over multiple FROM sources: each table contributed its
    # own top-k; the KnnTopK aggregate is global, so trim the union back to
    # the k nearest (top-k of a union ⊆ union of per-source top-ks)
    bk = getattr(c, "_brute_knn_k", None)
    if bk is not None and c.knn and len(rows) > bk:
        from surrealdb_tpu.idx.planner import hashable

        rows.sort(
            key=lambda s: c.knn.get(hashable(s.rid), float("inf"))
            if s.rid is not None else float("inf")
        )
        rows = rows[:bk]
    n = _expand_field_projections(n, c)
    return _select_pipeline(n, rows, c)


def select_over_sources(n: SelectStmt, sources, ctx: Ctx):
    """Run a SELECT over pre-resolved sources (graph/reference lookup
    subqueries: `->(SELECT ...)` / `<~(SELECT ...)`)."""
    c = ctx.child()
    c._cond_consumed = False
    rows = list(sources)
    if not c.session.is_owner:
        rows = [
            src
            for src in rows
            if src.rid is None
            or check_table_permission(src.rid.tb, "select", c, src.doc, src.rid)
        ]
    return _select_pipeline(n, rows, c)


def _eval_limits(n, ctx):
    """Evaluate LIMIT/START exactly once: (ok, keep, lim, off). keep is
    the top-k bound (LIMIT+START, both non-negative) or None; lim/off
    are the evaluated ints to slice with (only valid when ok). On an
    evaluation error ok=False — the slicing below re-evaluates and
    raises at the legacy position (after the sort). Volatile LIMIT
    expressions must not evaluate twice: the sliced values are the SAME
    ints the heap was bounded with."""
    try:
        lim = int(evaluate(n.limit, ctx)) if n.limit is not None else None
        off = int(evaluate(n.start, ctx)) if n.start is not None else None
    except Exception:
        return False, None, None, None
    keep = None
    if lim is not None and lim >= 0 and (off or 0) >= 0:
        # negative slices keep python slice semantics (no heap)
        keep = lim + (off or 0)
    return True, keep, lim, off


def _select_pipeline(n: SelectStmt, rows, c):
    # WHERE (if planner didn't consume it, re-filter — planner marks via attr)
    if n.cond is not None and not getattr(c, "_cond_consumed", False):
        kept = []
        for src in rows:
            doc = src.doc if src.rid is not None else src.value
            cc = c.with_doc(doc, src.rid)
            cc.knn = c.knn
            if is_truthy(evaluate(n.cond, cc)):
                kept.append(src)
        rows = kept
    # SPLIT
    for sp in n.split:
        rows = _apply_split(rows, sp, c)

    # alias map: ORDER BY / GROUP BY may reference projection aliases
    aliases = {}
    for expr, alias in n.exprs:
        if expr == "*":
            continue
        aliases[alias or expr_name(expr)] = expr
    if n.value is not None and getattr(n, "value_alias", None):
        aliases[n.value_alias] = n.value
    # GROUP BY
    if n.group is not None:
        if any(e == "*" for e, _a in n.exprs):
            raise SdbError(
                "Invalid query: Incorrect selector for aggregate "
                "selection, expression `*` within in selector cannot "
                "be aggregated in a group."
            )
        # GROUP ALL over zero cond-matched rows: the legacy engine emits
        # nothing; the streaming executor emits the count-0 row
        empty_row = n.cond is None or (
            getattr(c.session, "planner_strategy", None) == "all-ro"
        )
        if not rows and not c.session.is_owner and \
                c.session.auth_level != "editor":
            # a hard PERMISSIONS NONE table suppresses the GROUP ALL row
            for w in n.what:
                try:
                    v = _target_value(w, c)
                except SdbError:
                    continue
                tbn = v.name if isinstance(v, Table) else (
                    v.tb if isinstance(v, RecordId) else None)
                if tbn is None:
                    continue
                ns_, db_ = c.need_ns_db()
                tdef = c.txn.get_val(K.tb_def(ns_, db_, tbn))
                if tdef is not None and tdef.permissions is not None and                         tdef.permissions.get("select") is False:
                    empty_row = False
        out_rows = _apply_group(rows, n, c, aliases, empty_row)
        lok, keep, lim, off = _eval_limits(n, c)
        if n.order and n.order != "rand":
            out_rows = _apply_order(out_rows, n.order, c, keep=keep)
        elif n.order == "rand":
            _stmt_rng(c).shuffle(out_rows)
        if n.start is not None:
            out_rows = out_rows[
                off if lok else int(evaluate(n.start, c)) :]
        if n.limit is not None:
            out_rows = out_rows[
                : lim if lok else int(evaluate(n.limit, c))]
    else:
        # ORDER BY on the underlying rows (aliases resolve to their exprs)
        lok, keep, lim, off = _eval_limits(n, c)
        if n.order == "rand":
            _stmt_rng(c).shuffle(rows)
        elif n.order:
            rows = _apply_order_sources(rows, n.order, c, aliases,
                                        keep=keep)
        if n.start is not None:
            rows = rows[off if lok else int(evaluate(n.start, c)) :]
        if n.limit is not None:
            rows = rows[: lim if lok else int(evaluate(n.limit, c))]
        # VALUE selectors see omitted docs (the scalar output can't be
        # pruned later); ORDER BY above still saw the full documents
        if n.omit and n.value is not None:
            omits_v = _expand_omits(n.omit, c)
            for src in rows:
                doc = src.doc if src.rid is not None else src.value
                if isinstance(doc, dict):
                    doc = copy_value(doc)
                    for om in omits_v:
                        _omit_path(doc, om, c)
                    if src.rid is not None:
                        src.doc = doc
                    else:
                        src.value = doc
        out_rows = [_project(src, n, c) for src in rows]
    # OMIT applies to the OUTPUT records (reference pluck stage): after
    # grouping/projection, so omitted group keys still group and omitted
    # projected fields disappear entirely
    if n.omit and n.value is None:
        omits = _expand_omits(n.omit, c)
        pruned = []
        for r in out_rows:
            if isinstance(r, dict):
                r = copy_value(r)
                for om in omits:
                    _omit_path(r, om, c)
            pruned.append(r)
        out_rows = pruned
    # FETCH
    if n.fetch:
        out_rows = [apply_fetch(r, n.fetch, c) for r in out_rows]
    if n.only:
        # target-level check: FROM ONLY NONE / [] / [a, b] error outright —
        # but a LIMIT 1 caps the stream before the check (reference
        # select.rs); zero ROWS from a valid single target return NONE
        limited_to_one = (
            n.limit is not None and int(evaluate(n.limit, c)) == 1
        )
        if len(n.what) == 1:
            tv = _target_value(n.what[0], c)
            too_many = (
                isinstance(tv, list) and len(tv) > 1 and not limited_to_one
            )
            if tv is NONE or tv is None or too_many or (
                isinstance(tv, list) and len(tv) == 0
            ):
                raise SdbError(
                    "Expected a single result output when using the ONLY keyword"
                )
        if len(out_rows) == 1:
            return out_rows[0]
        if len(out_rows) == 0:
            return NONE
        raise SdbError(
            "Expected a single result output when using the ONLY keyword"
        )
    return out_rows


def _target_of(n, ctx):
    return None


def _expand_field_projections(n, ctx):
    """type::field()/type::fields() projections expand to the named
    idioms at execution (reference: functions/type/field suite)."""
    if n.value is not None or not n.exprs:
        return n
    hit = any(
        isinstance(e, FunctionCall)
        and e.name in ("type::field", "type::fields")
        for e, _a in n.exprs if e != "*"
    )
    if not hit:
        return n
    from surrealdb_tpu.syn.parser import Parser
    import copy as _copy

    out = []
    for e, a in n.exprs:
        if not (isinstance(e, FunctionCall)
                and e.name in ("type::field", "type::fields")):
            out.append((e, a))
            continue
        v = evaluate(e.args[0], ctx) if e.args else NONE
        names = v if e.name == "type::fields" else [v]
        if not isinstance(names, list):
            raise SdbError(
                f"Incorrect arguments for function {e.name}(). Argument 1 "
                f"was the wrong type. Expected `array` but found "
                f"`{render(names)}`"
            )
        for nm in names:
            if not isinstance(nm, str):
                raise SdbError(
                    f"Incorrect arguments for function {e.name}(). "
                    f"Argument 1 was the wrong type. Expected `string` "
                    f"but found `{render(nm)}`"
                )
            out.append((Idiom(Parser(nm)._field_name_parts()), a))
    n2 = _copy.copy(n)
    n2.exprs = out
    return n2


def _expand_omits(omit, ctx):
    """Evaluate type::field()/type::fields() OMIT entries into idioms
    once per statement (reference: parameterized/select.surql)."""
    out = []
    for om in omit:
        if isinstance(om, FunctionCall) and om.name in (
                "type::field", "type::fields"):
            from surrealdb_tpu.syn.parser import Parser

            v = evaluate(om.args[0], ctx) if om.args else NONE
            names = v if om.name == "type::fields" else [v]
            if not isinstance(names, list):
                continue
            for s in names:
                if isinstance(s, str):
                    out.append(Idiom(Parser(s)._field_name_parts()))
        else:
            out.append(om)
    return out


def _omit_path(doc, om, ctx=None):
    """Remove an OMIT path; `.{a, b}` destructure suffixes expand to the
    listed subpaths (reference idiom omit semantics)."""
    if not isinstance(om, Idiom):
        return
    _omit_parts(doc, om.parts)


def _omit_parts(doc, parts):
    if not parts:
        return
    part = parts[0]
    if isinstance(part, PField):
        if isinstance(doc, list):
            for item in doc:
                _omit_parts(item, parts)
            return
        if not isinstance(doc, dict):
            return
        if len(parts) == 1:
            doc.pop(part.name, None)
        else:
            _omit_parts(doc.get(part.name), parts[1:])
    elif isinstance(part, PDestructure):
        for name, sub in part.fields:
            if sub is None:
                _omit_parts(doc, [PField(name)])
            elif isinstance(sub, Idiom):
                subparts = [
                    p for p in sub.parts if not isinstance(p, tuple)
                ]
                _omit_parts(doc, [PField(name)] + subparts)
    elif isinstance(part, PAll):
        if len(parts) == 1:
            if isinstance(doc, (dict, list)):
                doc.clear()
            return
        if isinstance(doc, dict):
            for v in doc.values():
                _omit_parts(v, parts[1:])
        elif isinstance(doc, list):
            for item in doc:
                _omit_parts(item, parts[1:])


def _dynamic_field_key(expr, ctx):
    """Unaliased `type::field($p)` projections key by the RESOLVED field
    name (functions/type/field/..._variable_fields_projection)."""
    if isinstance(expr, FunctionCall) and expr.name == "type::field" \
            and expr.args:
        try:
            k = evaluate(expr.args[0], ctx)
        except SdbError:
            return None
        if isinstance(k, str):
            return k
    return None


def _project(src: Source, n: SelectStmt, ctx: Ctx):
    doc = src.doc if src.rid is not None else src.value
    c = ctx.with_doc(doc, src.rid)
    c.knn = ctx.knn
    if n.value is not None:
        try:
            return evaluate(n.value, c)
        except ReturnException as r:
            # a RETURN inside the projection expr yields that row's value
            # (reference catch_return at projection boundaries)
            return r.value
    out = {}
    star = False
    for expr, alias in n.exprs:
        if expr == "*":
            star = True
            if isinstance(doc, dict):
                for k, v in doc.items():
                    out[k] = copy_value(v)
            elif doc is not NONE and doc is not None and not isinstance(doc, dict):
                # SELECT * FROM scalar -> the scalar itself
                if len(n.exprs) == 1:
                    return copy_value(doc)
            continue
        v = evaluate(expr, c)
        if alias:
            _set_out_field(out, alias, v)
        else:
            dynk = _dynamic_field_key(expr, c)
            if dynk is not None:
                _set_out_field(out, dynk, v)
                continue
            segs = _idiom_segments(expr, c)
            if segs is not None:
                _set_nested_out(out, segs, v)
            else:
                _set_out_field(out, expr_name(expr), v)
    if not n.exprs and not star:
        return copy_value(doc)
    return out


def _idiom_segments(expr, ctx=None):
    """Nesting segments for an unaliased idiom projection (reference
    Value::set pluck semantics): field and graph parts nest; any other
    trailing part attaches at the last segment. None = not an idiom."""
    if not isinstance(expr, Idiom):
        return None
    segs = []
    for p in expr.parts:
        if isinstance(p, PField):
            segs.append(p.name)
        elif isinstance(p, PGraph):
            arrow = {"out": "->", "in": "<-", "both": "<->", "ref": "<~"}[p.dir]
            if getattr(p, "alias", None) is not None:
                # ->(edge AS name) names the output segment
                segs.append(p.alias if isinstance(p.alias, str)
                            else expr_name(p.alias))
                continue
            if getattr(p, "expr", None) is not None:
                from surrealdb_tpu.exec.render_def import _select_sql

                segs.append(f"{arrow}({_select_sql(p.expr)})")
                continue
            names = ", ".join(w[0] for w in p.what) if p.what else "?"
            if len(p.what) <= 1:
                segs.append(f"{arrow}{names}")
            else:
                segs.append(f"{arrow}({names})")
        # every other part kind (index, where, value, all, ...) is dropped
        # from the output name, later field parts still nest (reference
        # Idiom::simplify, expr/idiom/mod.rs:75 keeps Field/Start/Lookup)
    if not segs:
        return None
    return segs


def _set_nested_out(out, segs: list, v):
    """Set a value at a nested path; arrays distribute over their elements
    (the computed value replaces whatever the deeper levels held)."""
    cur = out
    for i, s in enumerate(segs[:-1]):
        if isinstance(cur, list):
            for item in cur:
                if isinstance(item, dict):
                    _set_nested_out(item, segs[i:], v)
            return
        if not isinstance(cur, dict):
            return
        nxt = cur.get(s)
        if not isinstance(nxt, (dict, list)):
            nxt = {}
            cur[s] = nxt
        cur = nxt
    if isinstance(cur, list):
        for item in cur:
            if isinstance(item, dict):
                item[segs[-1]] = copy_value(v)
        return
    if isinstance(cur, dict):
        cur[segs[-1]] = v


def _set_out_field(out: dict, name: str, v):
    # alias paths like a.b create nested objects
    if "." in name and not name.startswith("("):
        segs = name.split(".")
        cur = out
        for s in segs[:-1]:
            nxt = cur.get(s)
            if not isinstance(nxt, dict):
                nxt = {}
                cur[s] = nxt
            cur = nxt
        cur[segs[-1]] = v
    else:
        out[name] = v


def _apply_split(rows, sp, ctx):
    out = []
    name = expr_name(sp) if isinstance(sp, Idiom) else None
    for src in rows:
        doc = src.doc if src.rid is not None else src.value
        c = ctx.with_doc(doc, src.rid)
        v = evaluate(sp, c)
        from surrealdb_tpu.val import SSet as _SSet

        if isinstance(v, _SSet):
            v = list(v.items)
        if isinstance(v, list):
            for item in v:
                nd = copy_value(doc) if isinstance(doc, dict) else {}
                if name:
                    _set_path(nd, name.split("."), item)
                out.append(Source(rid=src.rid, doc=nd, value=nd))
        else:
            out.append(src)
    return out


def _set_path(doc, segs, v):
    cur = doc
    for s in segs[:-1]:
        nxt = cur.get(s)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[s] = nxt
        cur = nxt
    cur[segs[-1]] = v


def _drop_skipped(results):
    """Filter permission-skipped writes (document.SKIP sentinel)."""
    from surrealdb_tpu.exec.document import SKIP

    return [r for r in results if r is not SKIP]


def _count_only_stmt(n) -> bool:
    return bool(n.exprs) and all(
        _is_aggregate(e) for e, _a in n.exprs if e != "*"
    ) and any(e != "*" for e, _a in n.exprs)


def _apply_group(rows, n: SelectStmt, ctx, aliases=None, empty_row=True):
    from surrealdb_tpu.val import hashable

    if not rows and n.group == []:
        # GROUP ALL over no input: aggregates still emit one row
        # (count: 0) unless the table was hard-denied by permissions
        if empty_row and n.value is None and _count_only_stmt(n):
            row = {}
            for expr, alias in n.exprs:
                if expr == "*":
                    continue
                name = alias if alias else expr_name(expr)
                row[name] = _eval_aggregate(expr, [], ctx)
            return [row]
        return []

    groups: dict = {}
    order = []
    gb = [_resolve_alias(g, aliases) for g in (n.group or [])]
    keyvals: dict = {}
    for src in rows:
        doc = src.doc if src.rid is not None else src.value
        c = ctx.with_doc(doc, src.rid)
        vals = [evaluate(g, c) for g in gb] if gb else []
        key = tuple(hashable(v) for v in vals)
        if key not in groups:
            groups[key] = []
            keyvals[key] = vals
            order.append(key)
        groups[key].append(src)
    # groups emit in key order (the reference collects into an ordered map)
    order.sort(key=lambda k: tuple(sort_key(v) for v in keyvals[k]))
    out = []
    for key in order:
        members = groups[key]
        first = members[0]
        fdoc = first.doc if first.rid is not None else first.value
        fc = ctx.with_doc(fdoc, first.rid)
        if n.value is not None:
            if _is_aggregate(n.value):
                out.append(_eval_aggregate(n.value, members, ctx))
            else:
                out.append(evaluate(n.value, fc))
            continue
        row = {}
        for expr, alias in n.exprs:
            if expr == "*":
                if isinstance(fdoc, dict):
                    row.update(copy_value(fdoc))
                continue
            name = alias if alias else expr_name(expr)
            if _is_aggregate(expr):
                v = _eval_aggregate(expr, members, ctx)
            elif any(expr == g for g in gb):
                v = evaluate(expr, fc)
            else:
                # implicit array::group: the expression evaluates per
                # member row and the results collect into an array
                v = []
                for m in members:
                    d = m.doc if m.rid is not None else m.value
                    mc = ctx.with_doc(d, m.rid)
                    v.append(evaluate(expr, mc))
            _set_out_field(row, name, v)
        out.append(row)
    return out


# the reference's real streaming aggregates (catalog/aggregation.rs
# AggregateExprCollector); other _AGGREGATES entries are ordinary functions
# applied over an implicit Accumulate of their argument, so when their
# argument itself contains an aggregate they act as plain outer calls
_TRUE_AGGS = {
    "count", "math::sum", "math::mean", "math::min", "math::max",
    "math::stddev", "math::variance", "time::min", "time::max",
    "array::group",
}


def _eval_aggregate(expr, members, ctx):
    """Evaluate an aggregate expression over a group of source rows."""
    if (
        isinstance(expr, FunctionCall)
        and expr.name.lower() in _AGGREGATES
        and not (
            expr.name.lower() not in _TRUE_AGGS
            and any(_is_aggregate(a) for a in expr.args)
        )
    ):
        fname = expr.name.lower()
        from surrealdb_tpu.fnc import FUNCS

        if fname == "count" and not expr.args:
            return len(members)
        # collect per-row values of the first argument
        vals = []
        for src in members:
            doc = src.doc if src.rid is not None else src.value
            c = ctx.with_doc(doc, src.rid)
            vals.append(evaluate(expr.args[0], c) if expr.args else NONE)
        if fname == "count":
            return sum(1 for v in vals if is_truthy(v))
        if fname == "math::sum":
            from decimal import Decimal as _D

            from surrealdb_tpu.fnc import FUNCS as _F

            nums = [
                x for x in vals
                if isinstance(x, (int, float, _D))
                and not isinstance(x, bool)
            ]
            if not nums:
                return 0
            return _F["math::sum"]([nums], ctx)
        extra = []
        for a in expr.args[1:]:
            extra.append(evaluate(a, ctx))
        if fname == "array::group":
            # the grouped aggregate collects + flattens WITHOUT dedup
            # (reference Accumulate; array::distinct dedups explicitly)
            flat = []
            for v in vals:
                if isinstance(v, list):
                    flat.extend(v)
                else:
                    flat.append(v)
            return flat
        if fname in ("array::concat", "array::flatten"):
            flat = []
            for v in vals:
                if isinstance(v, list):
                    flat.extend(v)
                else:
                    flat.append(v)
            return flat
        if fname == "array::first":
            return vals[0] if vals else NONE
        if fname == "array::last":
            return vals[-1] if vals else NONE
        if fname == "array::len":
            return len(vals)
        if fname in ("math::stddev", "math::variance") and len([
            x for x in vals if not isinstance(x, bool)
            and isinstance(x, (int, float))
        ]) <= 1:
            # the grouped aggregate reports 0 for a single-member group
            # (reference catalog/aggregation.rs create_field_document),
            # unlike the plain math:: function which yields NaN
            return 0.0
        return FUNCS[fname]([vals] + extra, ctx)
    if isinstance(expr, Binary):
        return _binary_aggregate(expr, members, ctx)
    if isinstance(expr, Prefix):
        from surrealdb_tpu.exec.operators import neg

        v = _eval_aggregate(expr.expr, members, ctx)
        if expr.op == "-":
            return neg(v)
        return v
    if isinstance(expr, FunctionCall):
        from surrealdb_tpu.fnc import FUNCS

        args = [_eval_aggregate(a, members, ctx) for a in expr.args]
        fn = FUNCS.get(expr.name.lower())
        if fn is None:
            raise SdbError(f"The function '{expr.name}' does not exist")
        return fn(args, ctx)
    # non-aggregate: evaluate on first member
    first = members[0]
    doc = first.doc if first.rid is not None else first.value
    return evaluate(expr, ctx.with_doc(doc, first.rid))


def _binary_aggregate(expr, members, ctx):
    from surrealdb_tpu.exec.operators import binary_op

    lhs = _eval_aggregate(expr.lhs, members, ctx)
    rhs = _eval_aggregate(expr.rhs, members, ctx)
    return binary_op(expr.op, lhs, rhs)


def _resolve_alias(expr, aliases):
    """A field-path ORDER/GROUP item naming a projection alias (including
    nested aliases like `AS b.c`) resolves to the aliased expression."""
    if not aliases:
        return expr
    if isinstance(expr, Idiom) and expr.parts and all(
        isinstance(p, PField) for p in expr.parts
    ):
        name = ".".join(p.name for p in expr.parts)
        if name in aliases and aliases[name] is not expr:
            return aliases[name]
    return expr


def _stmt_rng(ctx):
    """Statement-level RNG (ORDER BY RAND): datastore-scoped and
    optionally seeded (SURREAL_RAND_SEED) so deterministic-sim and
    bench runs stay reproducible — never the process-global `random`
    instance another subsystem might be consuming."""
    rng = getattr(ctx.ds, "rng", None)
    if rng is None:
        from surrealdb_tpu import cnf

        rng = _random.Random(cnf.RAND_SEED or None)
        try:
            ctx.ds.rng = rng
        except AttributeError:
            pass
    return rng


def _apply_order_sources(rows, order, ctx, aliases=None, keep=None):
    """ORDER BY over source rows (pre-projection): aliases resolve to their
    expressions, everything else evaluates against the source doc.
    `keep` (LIMIT+START known non-negative) bounds the sort to a top-k
    heap instead of sorting every row."""
    items = []
    for expr, d, collate, numeric in order:
        resolved = _resolve_alias(expr, aliases)
        # ORDER keys mirror evaluation against the projected output: an
        # alias re-computes its projection (traversal and all); a raw
        # idiom walks the output row value-only — record links stay
        # un-traversed (reference select/fetch/order_by.surql)
        items.append((resolved, d, collate, numeric, resolved is not expr))
    # colstore-backed sort: clean scalar key columns go through one
    # np.lexsort instead of the row-at-a-time key extractor; any
    # exotic row / uncompilable key / COLLATE|NUMERIC flag bails to
    # the exact scalar path below (exec/vops.py fallback rules)
    from surrealdb_tpu.exec.vops import lexsort_sources

    fast = lexsort_sources(
        rows, [(e, d, c, nu) for e, d, c, nu, _a in items], ctx,
        keep=keep,
    )
    if fast is not None:
        return fast
    keyed = []
    for src in rows:
        doc = src.doc if src.rid is not None else src.value
        cc = ctx.with_doc(doc, src.rid)
        cc.knn = ctx.knn
        keys = []
        for expr, d, collate, numeric, was_alias in items:
            cc._no_link_fetch = not was_alias
            try:
                keys.append((evaluate(expr, cc), d, collate, numeric))
            finally:
                cc._no_link_fetch = False
        keyed.append((_OrderKey(keys), src))
    if keep is not None and keep < len(keyed):
        import heapq

        # nsmallest is stable (documented equivalent of sorted()[:n])
        keyed = heapq.nsmallest(keep, keyed, key=lambda kr: kr[0])
        return [r for _k, r in keyed]
    keyed.sort(key=lambda kr: kr[0])
    return [r for _k, r in keyed]


def _order_cmp(v, w, collate, numeric):
    if collate and isinstance(v, str) and isinstance(w, str):
        from surrealdb_tpu.utils.translit import lexical_cmp

        return lexical_cmp(v, w, numeric=numeric)
    if numeric and isinstance(v, str) and isinstance(w, str):
        import re

        def splitnum(s):
            return [
                int(p) if p.isdigit() else p
                for p in re.split(r"(\d+)", s)
                if p
            ]

        a, b = splitnum(v), splitnum(w)
        for x, y in zip(a, b):
            if type(x) is not type(y):
                x, y = str(x), str(y)
            if x != y:
                return -1 if x < y else 1
        return (len(a) > len(b)) - (len(a) < len(b))
    return value_cmp(v, w)


class _OrderKey:
    __slots__ = ("keys",)

    def __init__(self, keys):
        self.keys = keys

    def __lt__(self, other):
        for (v, d, collate, numeric), (w, _, _, _) in zip(
            self.keys, other.keys
        ):
            c = _order_cmp(v, w, collate, numeric)
            if c:
                return (c < 0) if d == "asc" else (c > 0)
        return False

    def __eq__(self, other):
        # heapq.nsmallest decorates with (key, index) tuples: without a
        # real __eq__, tied keys never fall through to the index and
        # tie order becomes heap-arbitrary — diverging from the stable
        # sorted()[:n] this class promises (and from the vectorized
        # lexsort path, which is stable by construction)
        for (v, _d, collate, numeric), (w, _, _, _) in zip(
            self.keys, other.keys
        ):
            if _order_cmp(v, w, collate, numeric):
                return False
        return True


def _apply_order(rows, order, ctx, keep=None):
    keyed = []
    for r in rows:
        c = ctx.with_doc(r, None)
        keys = []
        for item in order:
            expr, d, collate, numeric = item
            keys.append((evaluate(expr, c), d, collate, numeric))
        keyed.append((_OrderKey(keys), r))
    if keep is not None and keep < len(keyed):
        import heapq

        # bounded top-k: LIMIT (+START) keeps keep rows — an O(n log k)
        # heap instead of the full O(n log n) sort-then-slice
        keyed = heapq.nsmallest(keep, keyed, key=lambda kr: kr[0])
        return [r for _k, r in keyed]
    keyed.sort(key=lambda kr: kr[0])
    return [r for _k, r in keyed]


def apply_fetch(v, fetch_paths, ctx):
    """FETCH: inline record links at given paths. Params and
    type::field/type::fields calls resolve to path strings first
    (reference expr/fetch.rs compute)."""
    for p in fetch_paths:
        for parts in _fetch_parts(p, ctx):
            v = _fetch_path(v, parts, ctx)
    return v


def _fetch_parts(p, ctx):
    """One FETCH item -> list of part-lists (type::fields yields many)."""
    if isinstance(p, Idiom):
        # a bare single-field idiom naming a string/array param resolves
        # dynamically; plain idioms fetch statically
        if len(p.parts) == 1 and isinstance(p.parts[0], tuple) and \
                p.parts[0][0] == "start":
            return _fetch_parts_value(evaluate(p.parts[0][1], ctx))
        return [list(p.parts)]
    if isinstance(p, Param):
        return _fetch_parts_value(evaluate(p, ctx))
    if isinstance(p, FunctionCall) and p.name in ("type::field",
                                                  "type::fields"):
        # the reference evaluates the ARGUMENTS (strings), then parses
        # them as idioms — not the call itself (expr/fetch.rs:105-150)
        arg = evaluate(p.args[0], ctx) if p.args else NONE
        return _fetch_parts_value(arg)
    if isinstance(p, Literal) and isinstance(p.value, str):
        return _fetch_parts_value(p.value)
    return _fetch_parts_value(evaluate(p, ctx))


def _fetch_parts_value(val):
    from surrealdb_tpu.val import render as _r

    if isinstance(val, str):
        from surrealdb_tpu.syn.parser import Parser

        try:
            idm = Parser(val).parse_expr()
        except Exception:
            idm = None
        if not isinstance(idm, Idiom):
            raise SdbError(
                f"Found {_r(val)} on FETCH CLAUSE, but FETCH expects an "
                f"idiom, a string or fields"
            )
        return [list(idm.parts)]
    if isinstance(val, list):
        out = []
        for x in val:
            out.extend(_fetch_parts_value(x))
        return out
    if isinstance(val, Idiom):
        return [list(val.parts)]
    raise SdbError(
        f"Found {_r(val)} on FETCH CLAUSE, but FETCH expects an idiom, "
        f"a string or fields"
    )


def _fetch_path(v, parts, ctx):
    if not parts:
        return _fetch_value(v, ctx)
    if isinstance(v, list):
        return [_fetch_path(x, parts, ctx) for x in v]
    part = parts[0]
    if isinstance(part, PField) and isinstance(v, dict):
        name = part.name
        if name in v:
            nv = dict(v)
            nv[name] = _fetch_path(v[name], parts[1:], ctx)
            return nv
        return v
    if isinstance(part, PAll):
        return _fetch_path(v, parts[1:], ctx)
    if isinstance(v, RecordId):
        doc = fetch_record(ctx, v)
        if doc is NONE:
            return v
        return _fetch_path(doc, parts, ctx)
    return v


def _fetch_value(v, ctx):
    if isinstance(v, RecordId):
        doc = fetch_record(ctx, v)
        return copy_value(doc) if doc is not NONE else v
    if isinstance(v, list):
        return [_fetch_value(x, ctx) for x in v]
    return v


def _explain_streaming(n: SelectStmt, ctx) -> str:
    """Streaming-executor EXPLAIN string (reference exec/ operator tree
    pretty-print, used under planner-strategy all-ro). EXPLAIN ANALYZE
    executes and annotates {rows: N} per operator + a Total rows line."""
    from surrealdb_tpu.exec.render_def import _expr_sql
    from surrealdb_tpu.idx.planner import (
        _choose_index,
        _classify_preds,
        _find_knn,
        _find_matches,
        _remove_node,
        get_indexes_for,
    )

    analyze = n.explain in ("analyze", "analyze-json", "postfix-full")
    json_fmt = n.explain in (
        "json", "analyze-json", "postfix", "postfix-full"
    )
    orig_n = n
    if (
        analyze
        and not json_fmt
        and not getattr(
            ctx.session, "redact_volatile_explain_attrs", False
        )
    ):
        # stream-eligible statements ANALYZE through the real operator
        # tree: measured rows/batches/elapsed per operator (reference
        # exec/operators/explain.rs AnalyzePlan). The redacted
        # (deterministic) form below serves the language-test harness.
        from surrealdb_tpu.exec.stream import try_stream_analyze

        real = try_stream_analyze(n, ctx)
        if real is not None:
            return real

    # ORDER BY id is the natural scan order (reversed for DESC): the
    # sort is elided and LIMIT/START push into the scan — only when the
    # plan is a plain table scan (no predicate can pick an index)
    scan_dir = "Forward"
    single_target = len(n.what) == 1
    if (
        n.order
        and n.order != "rand"
        and len(n.order) == 1
        and expr_name(n.order[0][0]) == "id"
        and n.cond is None
        and single_target
    ):
        # only a TABLE scan can absorb id-order into scan direction;
        # RecordIdScan ranges keep the SortTopKByKey (reference
        # reverse_iterator_range_new_executor)
        try:
            _tv = _target_value(n.what[0], ctx)
        except SdbError:
            _tv = None
        if isinstance(_tv, Table):
            if n.order[0][1] == "desc":
                scan_dir = "Backward"
            n = _strip_order(n)

    # resolve scan children (one per FROM target)
    scans = []  # (label_fn, scan_rows)
    rid_range_scan = False
    total_scan_rows = 0
    residual = n.cond
    # KNN in the WHERE tree: KnnScan (HNSW access path) or KnnTopK (the
    # pipeline-breaking brute-force aggregate, exec/operators/knn_topk.rs)
    knn = _find_knn(n.cond) if n.cond is not None else None
    knn_residual = _remove_node(n.cond, knn) if knn is not None else None
    knn_brute = None
    for expr in n.what:
        # subquery FROM sources nest their own full sub-plan (reference
        # streaming planner: the inner SELECT is an operator subtree)
        sub_sel = None
        se = _unwrap_start(expr)
        if isinstance(se, Subquery) and isinstance(se.stmt, SelectStmt):
            sub_sel = se.stmt
        if sub_sel is not None:
            import copy as _copy

            sub = _copy.copy(sub_sel)
            # the sub-plan always renders as text (the outer call alone
            # JSON-encodes); keep only the analyze dimension
            sub.explain = "analyze" if analyze else "explain"
            txt = _explain_streaming(sub, ctx.child())
            sub_lines = [
                l for l in txt.split("\n")
                if l.strip() and not l.startswith("Total rows")
            ]
            rows = (
                len(list(_iterate_value(_target_value(expr, ctx), ctx)))
                if analyze else 0
            )
            scans.append(("__raw__", rows, sub_lines))
            total_scan_rows += rows
            continue
        v = _target_value(expr, ctx)
        if isinstance(v, RecordId):
            rows = len(list(_iterate_value(v, ctx))) if analyze else 0
            if isinstance(v.id, Range):
                rid_range_scan = True
                rg = v.id
                rid_s = (
                    f"{v.tb}:{render(rg.beg)}"
                    + ("..=" if rg.end_incl else "..")
                    + render(rg.end)
                )
            else:
                rid_s = v.render()
            scans.append(
                (f"RecordIdScan [ctx: Db] [record_id: {rid_s}]", rows)
            )
            total_scan_rows += rows
            continue
        if not isinstance(v, Table):
            rows = len(list(_iterate_value(v, ctx))) if analyze else 0
            from surrealdb_tpu.expr.ast import Cast as _Cst, \
                RangeExpr as _Rng

            src_e = _unwrap_start(expr)
            if isinstance(src_e, _Cst) and isinstance(src_e.expr, _Rng):
                # `..` is a binary operator in the reference grammar, so
                # a cast-of-range renders `<array>  0 .. 5`
                from surrealdb_tpu.exec.coerce import kind_name as _kn2

                rg = src_e.expr
                beg = _expr_sql(rg.beg) if rg.beg is not None else ""
                end = _expr_sql(rg.end) if rg.end is not None else ""
                src = f"<{_kn2(src_e.kind)}>  {beg} .. {end}"
            else:
                src = _expr_sql(src_e)
            scans.append(
                (f"SourceExpr [ctx: Db] [expr: {src}]", rows)
            )
            total_scan_rows += rows
            continue
        tb = v.name
        pushed_limit = pushed_offset = None
        indexes = get_indexes_for(tb, ctx)
        if n.with_index:
            indexes = [i for i in indexes if i.name in n.with_index]
        noindex = n.with_index == []
        label = None
        if knn is not None:
            qv = evaluate(knn.rhs, ctx)
            dim = len(qv) if isinstance(qv, list) else 0
            idef_h = None
            if not noindex and knn.dist is None:
                from surrealdb_tpu.idx.planner import _field_path as _fpk

                kpath = _fpk(knn.lhs)
                idef_h = next(
                    (d for d in indexes
                     if d.hnsw is not None and d.cols_str
                     and d.cols_str[0] == kpath),
                    None,
                )
            if idef_h is not None:
                rows = 0
                if analyze:
                    from surrealdb_tpu.idx.planner import plan_scan

                    plan = plan_scan(tb, n.cond, ctx.child(), n)
                    rows = sum(1 for _ in plan) if plan is not None else 0
                label = (
                    f"KnnScan [ctx: Db] [index: {idef_h.name}, k: {knn.k}, "
                    f"ef: {knn.ef or 40}, dimension: {dim}]"
                )
                residual = knn_residual  # rendered as a Filter above
                scans.append((label, rows))
                total_scan_rows += rows
                continue
            knn_brute = (knn, dim)
            if single_target and knn_residual is not None:
                rows = 0
                if analyze:
                    for src in _iterate_value(v, ctx, None, None):
                        doc = src.doc if src.rid is not None else src.value
                        cc = ctx.with_doc(doc, src.rid)
                        if is_truthy(evaluate(knn_residual, cc)):
                            rows += 1
                label = (
                    f"TableScan [ctx: Db] [table: {tb}, direction: Forward, "
                    f"predicate: {_expr_sql(knn_residual)}]"
                )
            else:
                rows = (
                    len(list(_iterate_value(v, ctx, None, None)))
                    if analyze else 0
                )
                label = (
                    f"TableScan [ctx: Db] [table: {tb}, direction: Forward]"
                )
            residual = None
            scans.append((label, rows))
            total_scan_rows += rows
            continue
        # a MATCHES candidate scores 800 (exec/index/analysis.rs:1281):
        # it loses to a unique full-equality access (1000) but beats
        # non-unique eq (500) and ranges — defer the choice until the
        # eq/range candidates are scored below
        mts = _find_matches(n.cond) if n.cond is not None and not noindex else []
        ft_cand = None
        if mts:
            mt = mts[0]
            idef = next((d for d in indexes if d.fulltext is not None), None)
            if idef is not None:
                ft_cand = (mt, idef)
        if label is None and n.cond is not None and not noindex:
            from surrealdb_tpu.idx.planner import (
                _array_like_paths,
                _ft_branch_scan,
                or_union_branches,
                union_branch_scan,
            )

            # plan-time `type::field($param)` resolution applies to the
            # union analysis too (schemaless parameterized scans)
            orb = or_union_branches(
                tb, _resolve_type_fields(n.cond, ctx), indexes, ctx,
                value_idioms=False,
            )
            if orb is not None:
                from surrealdb_tpu.val import hashable

                branch_lines = []
                seen_u = set()
                for br in orb:
                    brows = 0
                    if br["kind"] == "ft":
                        q = evaluate(br["mt"].rhs, ctx)
                        bl = (
                            f"FullTextScan [ctx: Db] "
                            f"[index: {br['idef'].name}, query: {q}]"
                        )
                    elif br["kind"] == "range":
                        acc = " ".join(
                            f"{op}{render(evaluate(vx, ctx))}"
                            for op, vx in sorted(
                                br["tail"][1],
                                key=lambda t: t[0] in ("<", "<="),
                            )
                        )
                        bl = (
                            f"IndexScan [ctx: Db] [index: {br['idef'].name}, "
                            f"access: {acc}, direction: Forward]"
                        )
                    elif br["kind"] == "in":
                        iv = evaluate(br["tail"][1], ctx)
                        iv = iv if isinstance(iv, list) else [iv]
                        acc = (
                            f"= {render(iv[0])}" if len(iv) == 1
                            else f"IN {render(iv)}"
                        )
                        bl = (
                            f"IndexScan [ctx: Db] [index: {br['idef'].name}, "
                            f"access: {acc}, direction: Forward]"
                        )
                    else:
                        idef_b = br["idef"]
                        eq_vals = [
                            evaluate(br["eqs"][c], ctx)
                            for c in idef_b.cols_str[:br["nmatch"]]
                        ]
                        acc = (
                            f"= {render(eq_vals[0])}"
                            if len(eq_vals) == 1 and br["tail"] is None
                            and len(idef_b.cols_str) == 1
                            else "[" + ", ".join(
                                render(x) for x in eq_vals) + "]"
                        )
                        bl = (
                            f"IndexScan [ctx: Db] [index: {idef_b.name}, "
                            f"access: {acc}, direction: Forward]"
                        )
                    if analyze:
                        srcs = list(union_branch_scan(tb, br, ctx.child()))
                        brows = len(srcs)
                        for s in srcs:
                            if s.rid is not None:
                                seen_u.add(hashable(s.rid))
                    branch_lines.append((bl, brows))
                urows = len(seen_u) if analyze else 0
                scans.append((
                    f"UnionIndexScan [ctx: Db] [table: {tb}, "
                    f"branches: {len(orb)}]",
                    urows, branch_lines,
                ))
                total_scan_rows += urows
                residual = n.cond
                continue

            cond_plan = _resolve_type_fields(n.cond, ctx)
            eqs, ins, rngs = _classify_preds(
                cond_plan, _array_like_paths(tb, ctx), value_idioms=False
            )
            chosen = _choose_index(indexes, eqs, ins, rngs) if (
                eqs or ins or rngs
            ) else None
            union_branches = None
            if chosen is not None:
                idef, nmatch, tail, chosen_score = chosen
                if tail is not None and tail[0] == "in" and nmatch == 0:
                    iv = evaluate(tail[1], ctx)
                    iv = iv if isinstance(iv, list) else [iv]
                    if len(iv) > 32:
                        # large IN arrays fall back to a table scan
                        # (reference: in_operator_large_array_fallback)
                        chosen = None
                    else:
                        union_branches = (idef, iv)
            if ft_cand is not None and (
                chosen is None or chosen[3] <= 800
            ):
                # the MATCHES access (800) outranks everything but a
                # unique full-equality candidate
                mt, idef_ft = ft_cand
                q = evaluate(mt.rhs, ctx)
                label = (
                    f"FullTextScan [ctx: Db] [index: {idef_ft.name}, "
                    f"query: {q}]"
                )
                residual = _remove_node(residual, mt)
                # the scan line reports the raw full-text hit count; the
                # residual Filter above it shows the post-filter rows
                rows = 0
                if analyze:
                    rows = len(list(_ft_branch_scan(
                        tb, {"mt": mt, "idef": idef_ft}, ctx.child()
                    )))
                scans.append((label, rows))
                total_scan_rows += rows
                continue
            if union_branches is not None and len(union_branches[1]) == 1:
                idef, iv = union_branches
                bv = iv[0]
                label = (
                    f"IndexScan [ctx: Db] [index: {idef.name}, "
                    f"access: = {render(bv)}, direction: Forward]"
                )
                rows = (
                    len(list(_iterate_value(v, ctx, n.cond, n)))
                    if analyze else 0
                )
                scans.append((label, rows))
                total_scan_rows += rows
                continue
            if union_branches is not None:
                idef, iv = union_branches
                branches = []
                col = idef.cols_str[0]
                base_path = col.replace("….", "").replace("…", "")
                for bv in iv:
                    brows = 0
                    if analyze:
                        from surrealdb_tpu.syn.parser import Parser as _P

                        parts = _P(base_path)._field_name_parts()
                        for src in _iterate_value(v, ctx):
                            doc = src.doc if src.rid is not None else src.value
                            cc = ctx.with_doc(doc, src.rid)
                            cv = evaluate(Idiom(parts), cc)
                            if isinstance(cv, list):
                                flat = []
                                for x in cv:
                                    flat.extend(x if isinstance(x, list) else [x])
                                if any(value_cmp(x, bv) == 0 for x in flat):
                                    brows += 1
                            elif value_cmp(cv, bv) == 0:
                                brows += 1
                    bacc = (
                        f"[{render(bv)}]" if len(idef.cols_str) > 1
                        else f"= {render(bv)}"
                    )
                    branches.append((
                        f"IndexScan [ctx: Db] [index: {idef.name}, "
                        f"access: {bacc}, direction: Forward]",
                        brows,
                    ))
                urows = 0
                if analyze:
                    from surrealdb_tpu.syn.parser import Parser as _P

                    parts = _P(base_path)._field_name_parts()
                    for src in _iterate_value(v, ctx):
                        doc = src.doc if src.rid is not None else src.value
                        cc = ctx.with_doc(doc, src.rid)
                        cv = evaluate(Idiom(parts), cc)
                        flat = []
                        if isinstance(cv, list):
                            for x in cv:
                                flat.extend(x if isinstance(x, list) else [x])
                        else:
                            flat = [cv]
                        if any(
                            value_cmp(x, bv) == 0 for bv in iv for x in flat
                        ):
                            urows += 1
                scans.append((
                    f"UnionIndexScan [ctx: Db] [table: {tb}, "
                    f"branches: {len(branches)}]",
                    urows, branches,
                ))
                total_scan_rows += urows
                continue
            if chosen is not None:
                vals = [evaluate(eqs[c], ctx) for c in idef.cols_str[:nmatch]]
                if nmatch == 0 and tail is not None and tail[0] == "range":
                    # single-column range: compact ">2000 <2020" form
                    acc = " ".join(
                        f"{op}{render(evaluate(vx, ctx))}"
                        for op, vx in sorted(
                            tail[1], key=lambda t: t[0] in ("<", "<=")
                        )
                    )
                    tail = ("rng_done", tail[1])
                elif len(idef.cols_str) > 1 or tail is not None:
                    acc = "[" + ", ".join(render(x) for x in vals) + "]"
                else:
                    acc = f"= {render(vals[0])}" if vals else "[]"
                # composite tails: only the FIRST range bound rides the
                # index access; later bounds — and any IN tail after an
                # eq prefix — drop to a residual Filter (the reference's
                # streaming executor pushes a single compound range)
                extra_bound_vxs = []
                in_tail_residual = False
                if tail is not None and tail[0] == "range":
                    # composite access pushes exactly ONE bound (cond
                    # order); every other bound filters above the scan
                    opmap = {">": "MoreThan", ">=": "MoreThanEqual",
                             "<": "LessThan", "<=": "LessThanEqual"}
                    op, vx = tail[1][0]
                    acc += f" {opmap.get(op, op)} {render(evaluate(vx, ctx))}"
                    extra_bound_vxs = [vx2 for _o2, vx2 in tail[1][1:]]
                elif tail is not None and tail[0] == "in":
                    if nmatch:
                        in_tail_residual = True
                    else:
                        acc += f" IN {render(evaluate(tail[1], ctx))}"
                direction = "Forward"
                if (
                    n.order
                    and n.order != "rand"
                    and len(n.order) == 1
                    and tail is not None
                    and tail[0] in ("range", "rng_done")
                ):
                    oexpr, odir, _oc, _on = n.order[0]
                    from surrealdb_tpu.idx.planner import _field_path as _fp

                    if _fp(oexpr) == idef.cols_str[nmatch] \
                            and single_target:
                        if odir == "desc":
                            direction = "Backward"
                        n = _strip_order(n)
                if (
                    idef.unique
                    and nmatch == len(idef.cols_str)
                    and tail is None
                    and n.order
                    and n.order != "rand"
                ):
                    # a UNIQUE full-equality access yields at most one row:
                    # the streaming planner elides the sort entirely
                    n = _strip_order(n)
                limattr = ""
                if (
                    n.limit is not None
                    and n.group is None
                    and (not n.order or n.order == [])
                    and single_target
                ):
                    pushed_limit = int(evaluate(n.limit, ctx))
                    limattr = f", limit: {pushed_limit}"
                    n = _strip_limit(n)
                    if n.start is not None:
                        # START pushes with LIMIT (reference limit/offset
                        # pushdown into the index scan)
                        limattr += f", offset: {int(evaluate(n.start, ctx))}"
                        n = _strip_start(n)
                label = (
                    f"IndexScan [ctx: Db] [index: {idef.name}, access: {acc}, "
                    f"direction: {direction}{limattr}]"
                )
                # residual: predicates not covered by the index
                covered = set(idef.cols_str[:nmatch])
                if tail is not None and not in_tail_residual:
                    covered.add(idef.cols_str[nmatch])
                preds = []
                from surrealdb_tpu.idx.planner import _split_ands, _field_path

                _split_ands(n.cond, preds)
                keep = []
                for pred in preds:
                    from surrealdb_tpu.expr.ast import Binary as _B

                    pth = None
                    enforceable = False
                    is_extra_bound = False
                    if isinstance(pred, _B):
                        lp0 = _field_path(pred.lhs)
                        pth = lp0 or _field_path(pred.rhs)
                        # containment accesses (value INSIDE field, field
                        # CONTAINS v) scan candidate elements — the
                        # predicate always re-filters above the scan
                        enforceable = pred.op in (
                            "=", "==", "<", "<=", ">", ">="
                        ) or (pred.op == "∈" and lp0 is not None)
                        # later range bounds on the tail column dropped
                        # out of the access string — they filter above
                        is_extra_bound = any(
                            pred.rhs is vx or pred.lhs is vx
                            for vx in extra_bound_vxs
                        )
                    if pth is None or pth not in covered or not enforceable \
                            or is_extra_bound:
                        keep.append(pred)
                residual = None
                for pred in keep:
                    from surrealdb_tpu.expr.ast import Binary as _B

                    residual = (
                        pred if residual is None
                        else _B("&&", residual, pred)
                    )
        if (
            label is None
            and n.cond is None
            and n.order
            and n.order != "rand"
            and len(n.order) == 1
            and n.group is None
            and n.start is None
            and not noindex
            and single_target
        ):
            # ORDER BY an indexed column: scan the index in order and
            # push the limit into the scan (reference limit pushdown)
            oexpr, odir, _oc, _on2 = n.order[0]
            opath = expr_name(oexpr)
            idef2 = next(
                (d for d in indexes
                 if d.cols_str and d.cols_str[0] == opath
                 and d.fulltext is None and d.hnsw is None),
                None,
            )
            if idef2 is not None:
                direction = "Backward" if odir == "desc" else "Forward"
                limattr = ""
                if n.limit is not None:
                    pushed_limit = int(evaluate(n.limit, ctx))
                    limattr = f", limit: {pushed_limit}"
                label = (
                    f"IndexScan [ctx: Db] [index: {idef2.name}, access: "
                    f", direction: {direction}{limattr}]"
                )
                n = _strip_limit(_strip_order(n))
        if label is None and n.cond is not None and single_target:
            # point lookup: a conjunct `id = <record>` scans one record
            # (reference RecordIdScan)
            prid = _id_eq_rid(n.cond, tb)
            if prid is not None:
                from surrealdb_tpu.exec.stream import _inline_params

                pred_s = _expr_sql(
                    _elide_count_args(_inline_params(n.cond, ctx))
                )
                label = (
                    f"RecordIdScan [ctx: Db] [record_id: {prid.render()}, "
                    f"predicate: {pred_s}]"
                )
                residual = None
        if label is None and ctx.doc is not None and single_target:
            # scans inside a per-document context (computed fields, field
            # clauses) re-plan per evaluation: the reference labels them
            # DynamicScan with params UN-inlined (they're row-dynamic)
            extra = ""
            if n.cond is not None:
                extra += f", predicate: {_expr_sql(n.cond)}"
                residual = None
            if n.limit is not None and not n.order and n.group is None:
                extra += f", limit: {int(evaluate(n.limit, ctx))}"
                if n.start is not None:
                    extra += f", offset: {int(evaluate(n.start, ctx))}"
            label = f"DynamicScan [ctx: Db] [source: {tb}{extra}]"
        if label is None:
            extra = ""
            if n.cond is not None and single_target:
                # a single table scan absorbs the predicate; multi-source
                # and subquery plans keep a Filter node above (reference
                # explain/complex.surql). Params render inlined: physical
                # exprs hold evaluated constants.
                from surrealdb_tpu.exec.stream import _inline_params
                extra += f", predicate: {_expr_sql(_elide_count_args(_inline_params(n.cond, ctx)))}"
                residual = None
            if (
                n.limit is not None
                and not n.order
                and n.group is None
            ):
                pushed_limit = int(evaluate(n.limit, ctx))
                extra += f", limit: {pushed_limit}"
                if n.start is not None:
                    pushed_offset = int(evaluate(n.start, ctx))
                    extra += f", offset: {pushed_offset}"
            label = (
                f"TableScan [ctx: Db] [table: {tb}, "
                f"direction: {scan_dir}{extra}]"
            )
        if analyze:
            # scans report their own emitted rows (pre-residual-filter);
            # table scans with inlined predicates report post-filter
            if label.startswith("TableScan") and n.cond is not None:
                kept = 0
                for src in _iterate_value(v, ctx, None, None):
                    doc = src.doc if src.rid is not None else src.value
                    cc = ctx.with_doc(doc, src.rid)
                    if is_truthy(evaluate(n.cond, cc)):
                        kept += 1
                rows = kept
            else:
                rows = len(list(_iterate_value(v, ctx, n.cond, n)))
            # a limit pushed into the scan caps the rows it emits
            if pushed_limit is not None:
                off = pushed_offset or 0
                rows = max(0, min(pushed_limit, rows - off))
        else:
            rows = 0
        scans.append((label, rows))
        total_scan_rows += rows

    # assemble the tree bottom-up
    mid_lines = []
    # run the select for row counts of upper operators
    out_rows_n = 0
    if analyze:
        saved = orig_n.explain
        orig_n.explain = None
        try:
            result = _s_select(orig_n, ctx.child())
        finally:
            orig_n.explain = saved
        out_rows_n = len(result) if isinstance(result, list) else 1

    root_lines = []
    lookup_lines = []  # raw pre-indented graph field.lookup sub-trees
    scan_lines = []  # (reldepth, text, rows)

    def _emit_scan(depth, entry):
        if entry[0] == "__raw__":
            # a nested sub-plan: pre-rendered lines, re-indented at
            # assembly relative to this slot
            for line in entry[2]:
                scan_lines.append((("raw", depth), line, 0))
            return
        scan_lines.append((depth, entry[0], entry[1]))
        if len(entry) > 2 and entry[2]:
            for bl, br in entry[2]:
                scan_lines.append((depth + 1, bl, br))

    if len(scans) > 1:
        scan_lines.append((0, "Union [ctx: Db]", total_scan_rows))
        for entry in scans:
            _emit_scan(1, entry)
    else:
        _emit_scan(0, scans[0])
    if knn_brute is not None:
        knn_o, dim_o = knn_brute
        dist_name = (knn_o.dist or "EUCLIDEAN").capitalize()
        filt_line = None
        if len(scans) > 1 and knn_residual is not None:
            filt_rows = 0
            if analyze:
                for expr in n.what:
                    vv = _target_value(expr, ctx)
                    for src in _iterate_value(vv, ctx, None, None):
                        doc = src.doc if src.rid is not None else src.value
                        cc = ctx.with_doc(doc, src.rid)
                        if is_truthy(evaluate(knn_residual, cc)):
                            filt_rows += 1
            filt_line = (
                f"Filter [ctx: Db] [predicate: {_expr_sql(knn_residual)}]",
                filt_rows,
            )
        else:
            filt_rows = scans[0][1] if scans else 0
        ktop_rows = min(knn_o.k, filt_rows) if analyze else 0
        wrapped = [(
            0,
            f"KnnTopK [ctx: Db] [field: {expr_name(knn_o.lhs)}, "
            f"k: {knn_o.k}, distance: {dist_name}, dimension: {dim_o}]",
            ktop_rows,
        )]
        shift = 1
        if filt_line is not None:
            wrapped.append((1, filt_line[0], filt_line[1]))
            shift = 2
        scan_lines = wrapped + [(_shift_depth(d, shift), t, r) for d, t, r in scan_lines]
    if not single_target and n.cond is not None and knn_brute is None:
        # multi-source plans always filter above the Union — a per-branch
        # index access can't cover the other branches (explain/complex)
        residual = n.cond
    if residual is not None:
        # rows THROUGH the filter: equals the final row count except under
        # grouping, where the aggregate collapses them (5581_select_count)
        filt_rows = out_rows_n
        if analyze and n.group is not None and single_target:
            try:
                v0 = _target_value(n.what[0], ctx)
                cctx = ctx.child()
                filt_rows = 0
                for src in _iterate_value(v0, cctx, n.cond, n):
                    doc = src.doc if src.rid is not None else src.value
                    if n.cond is None or cctx._cond_consumed or is_truthy(
                        evaluate(n.cond, cctx.with_doc(doc, src.rid))
                    ):
                        filt_rows += 1
            except SdbError:
                filt_rows = out_rows_n
        scan_lines = [
            (0, "Filter [ctx: Db] [predicate: "
             f"{_expr_sql(_label_cond(residual, ctx))}]",
             filt_rows)
        ] + [(_shift_depth(d, 1), t, r) for d, t, r in scan_lines]
    if n.split:
        names = ", ".join(expr_name(sp) for sp in n.split)
        scan_lines = [
            (0, f"Split [ctx: Db] [on: {names}]", out_rows_n)
        ] + [(_shift_depth(d, 1), t, r) for d, t, r in scan_lines]
    # aggregation / projection root
    if n.group is not None:
        if n.group:
            by = ", ".join(expr_name(g) for g in n.group) or ", ".join(
                (a or expr_name(e))
                for e, a in n.exprs
                if e != "*" and not _is_aggregate(e)
            )
            root_lines.append((f"Aggregate [ctx: Db] [by: {by}]", out_rows_n))
        else:
            # count-only GROUP ALL uses the dedicated count scans
            only_count = (
                len(n.exprs) == 1
                and isinstance(n.exprs[0][0], FunctionCall)
                and n.exprs[0][0].name.lower() == "count"
                and not n.exprs[0][0].args
            )
            if only_count and len(n.what) == 1 and len(scans) == 1:
                label, rows = scans[0][0], scans[0][1]
                tbname = label.split("table: ")[1].split(",")[0].rstrip(
                    "]"
                ) if "table: " in label else None
                tv = _target_value(n.what[0], ctx)
                if isinstance(tv, RecordId) and isinstance(tv.id, Range) \
                        and n.cond is None:
                    rg = tv.id
                    rsrc = (
                        f"{tv.tb}:{render(rg.beg)}"
                        + ("..=" if rg.end_incl else "..")
                        + render(rg.end)
                    )
                    text = f"CountScan [ctx: Db] [source: {rsrc}]"
                    return _render_tree([(0, text, 1 if analyze else 0)],
                                        analyze, 1)
                if label.startswith("TableScan") and n.cond is None:
                    from surrealdb_tpu.val import escape_ident as _esc2

                    text = (
                        f"CountScan [ctx: Db] [source: {_esc2(tbname)}]"
                    )
                    return _render_tree([(0, text, 1 if analyze else 0)],
                                        analyze, 1)
                if label.startswith("IndexScan") and residual is None:
                    # a count scan needs the index to cover the WHOLE
                    # predicate; residuals require real documents
                    tbn = _target_value(n.what[0], ctx).name
                    cond_s = _expr_sql(n.cond) if n.cond is not None else ""
                    text = (
                        f"IndexCountScan [ctx: Db] [source: {tbn}, "
                        f"condition: {cond_s}]"
                    )
                    return _render_tree([(0, text, 1 if analyze else 0)],
                                        analyze, 1)
            root_lines.append(
                ("Aggregate [ctx: Db] [mode: GROUP ALL]",
                 max(out_rows_n, 1))
            )
    else:
        if n.value is not None:
            root_lines.append(
                (f"ProjectValue [ctx: Db] [expr: {_expr_sql(n.value)}]",
                 out_rows_n)
            )
            if isinstance(n.value, Idiom):
                prec = next(
                    (p for p in n.value.parts if isinstance(p, PRecurse)),
                    None,
                )
                if prec is not None:
                    pi = n.value.parts.index(prec)
                    lookup_lines.append((
                        "expr.recurse",
                        _recurse_flat(prec, n.value.parts[pi + 1:]),
                    ))
        else:
            # bare `Project` is the pass-through root over RecordIdScans
            # (point lookups, keys-only counts); once an ORDER/LIMIT
            # pipeline sits above the scan the reference renders the full
            # SelectProject (explain/select_basic, count_range_keys_only
            # vs reverse_iterator_range)
            only_rid_scans = scans and all(
                entry[0].startswith("RecordIdScan")
                and "predicate:" not in entry[0] for entry in scans
            ) and not (n.order and n.order != "rand") and n.limit is None
            graph_projs = bool(n.exprs) and all(
                e != "*" and isinstance(e, Idiom)
                and any(isinstance(p, PGraph) for p in e.parts)
                for e, _a in n.exprs
            )
            if graph_projs:
                # graph-lookup projections: bare Project root with one
                # `field.lookup:` sub-tree per projection
                root_lines.append(("Project [ctx: Db]", out_rows_n))
                for e, _a in n.exprs:
                    flat = _graph_hops_flat(e.parts)
                    if flat:
                        lookup_lines.append(("field.lookup", flat))
            elif only_rid_scans:
                root_lines.append(("Project [ctx: Db]", out_rows_n))
            else:
                def _proj_name(e, a):
                    if a:
                        return a
                    # destructure projections list the BASE field; the
                    # destructure itself runs in a Compute node
                    if isinstance(e, Idiom):
                        cut = next(
                            (ix for ix, p in enumerate(e.parts)
                             if isinstance(p, PDestructure)), None)
                        if cut:
                            return expr_name(Idiom(list(e.parts[:cut])))
                    return expr_name(e)

                projs = ", ".join(
                    "*" if e == "*" else _proj_name(e, a) for e, a in n.exprs
                )
                root_lines.append(
                    (f"SelectProject [ctx: Db] [projections: {projs}]",
                     out_rows_n)
                )
                # function-call fields render with elided args (reference
                # operator pretty-print: `vector::distance::knn(...)`)
                computed = [
                    f"{a or expr_name(e)} = " + (
                        f"{e.name}(...)" if isinstance(e, FunctionCall)
                        else _expr_sql(e)
                    )
                    for e, a in n.exprs
                    if e != "*" and not isinstance(e, Idiom)
                ]
                for e, a in n.exprs:
                    if e == "*" or not isinstance(e, Idiom):
                        continue
                    if any(isinstance(p, PDestructure) for p in e.parts) \
                            and not any(
                                isinstance(p, PRecurse) for p in e.parts
                            ):
                        computed.append(
                            f"{_proj_name(e, a)} = "
                            f"{expr_name(e, sql=True)}"
                        )
                # recursion idioms compute through a Recurse sub-plan
                for e, a in n.exprs:
                    if e == "*" or not isinstance(e, Idiom):
                        continue
                    prec = next(
                        (p for p in e.parts if isinstance(p, PRecurse)),
                        None,
                    )
                    if prec is None:
                        continue
                    nm = a or expr_name(e)
                    computed.append(f"{nm} = {expr_name(e, sql=True)}")
                    pi = e.parts.index(prec)
                    lookup_lines.append((
                        f"{nm}.recurse",
                        _recurse_flat(prec, e.parts[pi + 1:]),
                    ))
                if computed:
                    mid_lines.insert(
                        0,
                        (f"Compute [ctx: Db] [fields: {', '.join(computed)}]",
                         out_rows_n),
                    )
    # order / limit layers: grouped sorts sit ABOVE the Aggregate; plain
    # sorts sit under the projection
    if n.order and n.order != "rand":
        keys = ", ".join(
            f"{expr_name(e)} {'DESC' if d == 'desc' else 'ASC'}"
            for e, d, _c, _n2 in n.order
        )
        if n.group is not None:
            if n.limit is not None:
                lim = int(evaluate(n.limit, ctx))
                root_lines.insert(
                    0,
                    (f"SortTopK [ctx: Db] [order_by: {keys}, limit: {lim}]",
                     out_rows_n),
                )
            else:
                root_lines.insert(
                    0, (f"Sort [ctx: Db] [order_by: {keys}]", out_rows_n)
                )
        elif n.limit is not None:
            lim = int(evaluate(n.limit, ctx))
            off = int(evaluate(n.start, ctx)) if n.start is not None else 0
            # sorts sit directly under the projection, above Compute; the
            # top-k keeps limit+offset rows, the Limit node drops the skip
            mid_lines.insert(
                0,
                (f"SortTopKByKey [ctx: Db] [sort_keys: {keys}, "
                 f"limit: {lim + off}]",
                 out_rows_n)
            )
            limattr2 = f"limit: {lim}, offset: {off}" \
                if n.start is not None else f"limit: {lim}"
            mid_lines.insert(
                0, (f"Limit [ctx: Db] [{limattr2}]", out_rows_n)
            )
        else:
            # ORDER BY id ASC over a single forward table scan streams in
            # key order already — the sort is elided (iterator order)
            id_asc = (
                len(n.order) == 1
                and n.order[0][1] != "desc"
                and expr_name(n.order[0][0]) == "id"
                and len(scans) == 1
                and scans[0][0].startswith("TableScan")
                and "direction: Forward" in scans[0][0]
            )
            if not id_asc:
                mid_lines.insert(
                    0,
                    (f"SortByKey [ctx: Db] [sort_keys: {keys}]", out_rows_n)
                )
    if n.limit is not None and n.group is not None:
        lim = int(evaluate(n.limit, ctx))
        root_lines.insert(0, (f"Limit [ctx: Db] [limit: {lim}]", out_rows_n))
    if n.fetch:
        fields = ", ".join(expr_name(f) for f in n.fetch)
        root_lines.insert(
            0, (f"Fetch [ctx: Db] [fields: {fields}]", out_rows_n)
        )
    stacked = [(i, t, r) for i, (t, r) in enumerate(root_lines + mid_lines)]
    base = len(stacked)
    raw = []
    for label, flat in lookup_lines:
        for line in _lookup_raw_lines(label, flat, max(base - 1, 0)):
            raw.append((None, line, 0))
    shifted = []
    for d, t, r in scan_lines:
        if isinstance(d, tuple):
            shifted.append((None, "    " * (base + d[1]) + t, 0))
        else:
            shifted.append((base + d, t, r))
    ordered = stacked + raw + shifted
    if json_fmt:
        return _tree_to_json(ordered, analyze, out_rows_n)
    return _render_tree(ordered, analyze, out_rows_n)


def _id_eq_rid(cond, tb):
    """A top-level AND conjunct `id = <record>` / `<record> = id` (or ==)
    naming the scanned table -> the RecordId, else None (RecordIdScan)."""
    from surrealdb_tpu.expr.ast import Binary as _B, Literal as _L

    preds = []
    from surrealdb_tpu.idx.planner import _split_ands

    _split_ands(cond, preds)
    for p in preds:
        if not (isinstance(p, _B) and p.op in ("=", "==")):
            continue
        for lhs, rhs in ((p.lhs, p.rhs), (p.rhs, p.lhs)):
            if isinstance(lhs, Idiom) and len(lhs.parts) == 1 and \
                    isinstance(lhs.parts[0], PField) and \
                    lhs.parts[0].name == "id":
                v = None
                if isinstance(rhs, _L) and isinstance(rhs.value, RecordId):
                    v = rhs.value
                else:
                    from surrealdb_tpu.expr.ast import RecordIdLit as _RL

                    if isinstance(rhs, _RL):
                        try:
                            from surrealdb_tpu.exec.static_eval import (
                                static_value,
                            )

                            v = static_value(rhs)
                        except Exception:
                            v = None
                if isinstance(v, RecordId) and v.tb == tb and \
                        not isinstance(v.id, Range):
                    return v
    return None


def _elide_count_args(node):
    """Predicate labels render count(->edge) as count(...) (reference
    count-exists rewriter plan text)."""
    import copy as _copy

    from surrealdb_tpu.expr.ast import Binary as _B, Constant as _C
    from surrealdb_tpu.expr.ast import FunctionCall as _FC

    if isinstance(node, _FC) and node.name.lower() == "count" and node.args:
        n2 = _copy.copy(node)
        n2.args = [_C("...")]
        return n2
    if isinstance(node, _B):
        n2 = _copy.copy(node)
        n2.lhs = _elide_count_args(node.lhs)
        n2.rhs = _elide_count_args(node.rhs)
        return n2
    return node


def _resolve_type_fields(node, ctx):
    """Plan-time rewrite: `type::field(<doc-free expr>)` becomes the named
    column idiom so access-path analysis can match indexes (reference
    resolves parameterized OData-style columns at plan time)."""
    import copy as _copy

    from surrealdb_tpu.expr.ast import Binary as _B
    from surrealdb_tpu.expr.ast import FunctionCall as _FC
    from surrealdb_tpu.idx.planner import _doc_free_idiom  # noqa: F401

    def const_str(e):
        from surrealdb_tpu.expr.ast import Literal as _L

        if isinstance(e, _L) and isinstance(e.value, str):
            return e.value
        if isinstance(e, Param):
            try:
                val = evaluate(e, ctx)
            except SdbError:
                return None
            return val if isinstance(val, str) else None
        return None

    def rec(e):
        if isinstance(e, _FC) and e.name.lower() == "type::field" \
                and len(e.args) == 1:
            s = const_str(e.args[0])
            if s:
                return Idiom([PField(p) for p in s.split(".")])
        if isinstance(e, _B):
            e2 = _copy.copy(e)
            e2.lhs = rec(e.lhs)
            e2.rhs = rec(e.rhs)
            return e2
        return e

    return rec(node)


def _label_cond(node, ctx):
    """Filter-label rendering: function args elide (count(...),
    type::field(...)) and doc-free IN/INSIDE arrays fold to their
    evaluated values."""
    import copy as _copy

    from surrealdb_tpu.expr.ast import ArrayExpr as _AE
    from surrealdb_tpu.expr.ast import Binary as _B, Constant as _C
    from surrealdb_tpu.expr.ast import FunctionCall as _FC
    from surrealdb_tpu.expr.ast import Literal as _L

    def rec(e):
        if isinstance(e, _FC) and e.args and e.name.lower() in (
            "count", "type::field", "type::fields"
        ):
            e2 = _copy.copy(e)
            e2.args = [_C("...")]
            return e2
        if isinstance(e, _B):
            e2 = _copy.copy(e)
            e2.lhs = rec(e.lhs)
            e2.rhs = rec(e.rhs)
            if e2.op in ("∈", "IN") and isinstance(e.rhs, _AE):
                try:
                    e2.rhs = _L(evaluate(e.rhs, ctx))
                except SdbError:
                    pass
            return e2
        return e

    return rec(node)


def _strip_order(n):
    import copy as _copy

    n2 = _copy.copy(n)
    n2.order = []
    return n2


def _strip_limit(n):
    import copy as _copy

    n2 = _copy.copy(n)
    n2.limit = None
    return n2


def _strip_start(n):
    import copy as _copy

    n2 = _copy.copy(n)
    n2.start = None
    return n2


import re as _re_mod


def _tree_to_json(entries, analyze, total):
    """Structured (FORMAT JSON) explain: {operator, context, attributes,
    children[, metrics, total_rows]} (reference exec explain JSON)."""
    # raw pre-indented lookup lines (depth None) carry no tree position;
    # recover depth from their indentation so the JSON nest stays sane
    fixed = []
    for d, t, r in entries:
        if d is None:
            stripped = t.lstrip(" ")
            d = max((len(t) - len(stripped)) // 4, 0)
            t = stripped
        fixed.append((d, t, r))
    entries = fixed
    rx = _re_mod.compile(
        r"^(?P<op>\w+) \[ctx: (?P<ctx>\w+)\](?: \[(?P<attrs>.*)\])?$"
    )

    def parse(text):
        m = rx.match(text)
        if m is None:
            return {"operator": text, "context": "Db", "attributes": {}}
        attrs = {}
        raw = m.group("attrs")
        if raw:
            for part in _re_mod.split(r", (?=[\w.]+: )", raw):
                k, _, v = part.partition(": ")
                attrs[k] = v
        out = {
            "operator": m.group("op"),
            "context": m.group("ctx"),
            "attributes": attrs,
        }
        if m.group("op") == "Filter" and "predicate" in attrs:
            # reference Filter nodes also carry an expressions list
            out["expressions"] = [
                {"role": "predicate", "sql": attrs["predicate"]}
            ]
        return out

    nodes = []
    stack = []  # (depth, node)
    root = None
    for depth, text, rows in entries:
        node = parse(text)
        node["children"] = []
        if analyze:
            node["metrics"] = {"output_rows": rows}
        while stack and stack[-1][0] >= depth:
            stack.pop()
        if stack:
            stack[-1][1]["children"].append(node)
        else:
            root = node
        stack.append((depth, node))
        nodes.append(node)
    if root is None:
        root = {"operator": "Empty", "context": "Db", "attributes": {},
                "children": []}
    def prune(nd):
        if not nd["children"]:
            nd.pop("children", None)
        else:
            for ch in nd["children"]:
                prune(ch)
    prune(root)
    if analyze:
        root["total_rows"] = total
    return root


def _unwrap_start(e):
    """Unwrap a single-part start-tuple idiom to its inner expression."""
    if isinstance(e, Idiom) and len(e.parts) == 1 and \
            isinstance(e.parts[0], tuple) and e.parts[0][0] == "start":
        return e.parts[0][1]
    return e


def _shift_depth(d, k):
    """Shift a scan-line depth by k; raw sub-plan lines carry tuple depths."""
    if isinstance(d, tuple):
        return (d[0], d[1] + k)
    return d + k


def _render_tree(entries, analyze, total):
    out = []
    for depth, text, rows in entries:
        if depth is None:
            # raw pre-indented line (graph lookup sub-trees)
            out.append(text)
            continue
        line = ("    " * depth) + text
        if analyze:
            line += f" {{rows: {rows}}}"
        out.append(line)
    s = "\n".join(out) + "\n"
    if analyze:
        s += f"\nTotal rows: {total}"
    return s


def _graph_hops_flat(parts):
    """Top-down node labels for a graph-lookup chain: hops render
    outermost-last-hop-first, ending at CurrentValueSource (reference
    exec/operators/scan/graph.rs GraphEdgeScan explain). Subquery hops
    render their SELECT plan over a FullEdge-output scan."""
    from surrealdb_tpu.exec.render_def import _expr_sql
    from surrealdb_tpu.expr.ast import PGraph

    arrows = {"out": "->", "in": "<-", "both": "<->", "ref": "<~"}
    hops = [p for p in parts if isinstance(p, PGraph)]
    if not hops:
        return None
    flat = []
    for g in reversed(hops):
        if getattr(g, "expr", None) is not None:
            sel = g.expr
            tbls = ", ".join(expr_name(w) for w in sel.what)
            if sel.group:
                by = ", ".join(expr_name(x) for x in sel.group)
                flat.append(f"Aggregate [ctx: Db] [by: {by}]")
            else:
                projs = ", ".join(
                    "*" if e == "*" else (a or expr_name(e))
                    for e, a in sel.exprs
                ) or "*"
                flat.append(
                    f"SelectProject [ctx: Db] [projections: {projs}]"
                )
            if sel.cond is not None:
                flat.append(
                    f"Filter [ctx: Db] [predicate: {_expr_sql(sel.cond)}]"
                )
            flat.append(
                f"GraphEdgeScan [ctx: Db] [direction: {arrows[g.dir]}, "
                f"tables: {tbls}, output: FullEdge]"
            )
        else:
            tbls = ", ".join(w[0] for w in g.what) if g.what else "?"
            flat.append(
                f"GraphEdgeScan [ctx: Db] [direction: {arrows[g.dir]}, "
                f"tables: {tbls}, output: TargetId]"
            )
    flat.append("CurrentValueSource [ctx: Rt]")
    return flat


def _recurse_flat(prec, following=()):
    """Node labels for a `.{n}` recursion: a Recurse head, then the
    repeated path's hop chain. A destructure body (inside the braces or
    as the following part) is `pattern: tree` with no hop chain."""
    from surrealdb_tpu.expr.ast import PDestructure as _PD

    if prec.min == prec.max and prec.min is not None:
        depth_s = str(prec.min)
    elif prec.max is None:
        depth_s = f"{1 if prec.min is None else prec.min}.."
    else:
        depth_s = f"{1 if prec.min is None else prec.min}..{prec.max}"
    attrs = (
        f"depth: {depth_s}, instruction: {prec.instruction or 'default'}"
    )
    inner = list(prec.parts or [])
    nxt = following[0] if following else None
    if any(isinstance(x, _PD) for x in inner) or (
        not inner and isinstance(nxt, _PD)
    ):
        attrs += ", pattern: tree"
        return [f"Recurse [ctx: Db] [{attrs}]"]
    head = [f"Recurse [ctx: Db] [{attrs}]"]
    hops = _graph_hops_flat(inner)
    return head + (hops if hops else ["CurrentValueSource [ctx: Rt]"])


def _lookup_raw_lines(label, flat, parent_depth):
    """Render a `{label}: <tree>` block: the label line sits 2 spaces past
    the parent's indent, nested nodes 4 more each."""
    base = "    " * parent_depth + "  "
    lines = [f"{base}{label}: {flat[0]}"]
    for i, lab in enumerate(flat[1:], 1):
        lines.append(base + "    " * i + lab)
    return lines


def _graph_lookup_lines(parts, label, parent_depth=0):
    flat = _graph_hops_flat(parts)
    if flat is None:
        return None
    return _lookup_raw_lines(label, flat, parent_depth)


def _s_explain_generic(n: ExplainStmt, ctx: Ctx):
    """EXPLAIN of non-select statements: AST pretty-print (Rt context)."""
    from surrealdb_tpu.exec.render_def import _expr_sql

    lines = []

    def walk_node(node, depth):
        from surrealdb_tpu.expr.ast import (
            BreakStmt as _Br,
            ContinueStmt as _Co,
            ForStmt as _For,
            IfElse as _If,
            LetStmt as _Let,
            ReturnStmt as _Ret,
            Subquery as _Sub,
            ThrowStmt as _Th,
        )

        if isinstance(node, _Ret):
            lines.append((depth, "Return [ctx: Rt]"))
            walk_node(node.what, depth + 1)
        elif isinstance(node, _Th):
            lines.append(
                (depth, f"Expr [ctx: Rt] [expr: THROW {_expr_sql(node.what)}]")
            )
        elif isinstance(node, _Br):
            lines.append((depth, "Expr [ctx: Rt] [expr: BREAK]"))
        elif isinstance(node, _Co):
            lines.append((depth, "Expr [ctx: Rt] [expr: CONTINUE]"))
        elif isinstance(node, _Let):
            lines.append((depth, f"Let [ctx: Rt] [param: ${node.name}]"))
            walk_node(node.what, depth + 1)
        elif isinstance(node, _For):
            from surrealdb_tpu.expr.ast import BlockExpr as _Blk

            nstmts = (
                len(node.body.stmts) if isinstance(node.body, _Blk) else 1
            )
            lines.append((
                depth,
                f"Foreach [ctx: Rt] [param: {node.param}, statements: {nstmts}]",
            ))
        elif isinstance(node, _If):
            attrs = f"branches: {len(node.branches)}"
            if node.otherwise is not None:
                attrs += ", has_else: true"
            lines.append((depth, f"IfElse [ctx: Rt] [{attrs}]"))
        elif isinstance(node, _Sub):
            walk_node(node.stmt, depth)
        elif isinstance(node, SleepStmt):
            dur = evaluate(node.duration, ctx)
            lines.append((
                depth,
                f"Sleep [ctx: Rt] [duration: {render(dur)}]",
            ))
        elif isinstance(node, Idiom) and any(
            isinstance(p, PGraph) for p in node.parts
        ):
            # graph-lookup idiom: the Expr line plus a nested lookup tree
            from surrealdb_tpu.exec.render_def import _select_sql

            arrows = {"out": "->", "in": "<-", "both": "<->", "ref": "<~"}
            pieces = []
            for p in node.parts:
                if isinstance(p, tuple) and p[0] == "start":
                    pieces.append(f"({_expr_sql(p[1])})")
                elif isinstance(p, PGraph):
                    if getattr(p, "expr", None) is not None:
                        pieces.append(
                            f"{arrows[p.dir]}({_select_sql(p.expr)})"
                        )
                    else:
                        nm = ", ".join(w[0] for w in p.what) \
                            if p.what else "?"
                        pieces.append(f"{arrows[p.dir]}{nm}")
                elif isinstance(p, PField):
                    pieces.append(f".{p.name}")
            lines.append(
                (depth, f"Expr [ctx: Db] [expr: {''.join(pieces)}]")
            )
            for raw in _graph_lookup_lines(node.parts, "expr.lookup"):
                lines.append((None, raw))
        else:
            lines.append((depth, f"Expr [ctx: Rt] [expr: {_expr_sql(node)}]"))

    walk_node(n.stmt, 0)
    out = []
    rows_suffix = " {rows: 0}" if n.analyze else ""
    for depth, text in lines:
        if depth is None:
            out.append(text)
            continue
        out.append(("    " * depth) + text + rows_suffix)
    s_out = "\n".join(out) + "\n"
    if n.analyze:
        # bare expressions report one row; control-flow statements zero
        is_bare = lines and lines[0][1].startswith("Expr ")
        total = 1 if is_bare else 0
        s_out += f"\nTotal rows: {total}"
    return s_out


def _explain_select(n: SelectStmt, ctx):
    """EXPLAIN — report the plan the iterator would use (dbs/plan.rs).
    EXPLAIN FULL also executes and reports fetch counts."""
    if ctx.session.planner_strategy == "all-ro":
        return _explain_streaming(n, ctx)
    from surrealdb_tpu.idx.planner import explain_plan

    out = []
    range_target = False
    for expr in n.what:
        v = _target_value(expr, ctx)
        if isinstance(v, Table):
            plan_e = explain_plan(v.name, n.cond, ctx, n)
            out.extend(plan_e if isinstance(plan_e, list) else [plan_e])
            if n.with_index == []:
                out.append(
                    {
                        "detail": {"reason": "WITH NOINDEX"},
                        "operation": "Fallback",
                    }
                )
        elif isinstance(v, RecordId) and isinstance(v.id, Range):
            rg = v.id
            direction = "forward"
            if (
                n.order
                and n.order != "rand"
                and len(n.order) == 1
                and n.order[0][1] == "desc"
                and expr_name(n.order[0][0]) == "id"
            ):
                direction = "backward"
            rs = rg
            range_target = True
            count_only_rng = (
                n.cond is None
                and not n.order
                and len(n.exprs) == 1
                and isinstance(n.exprs[0][0], FunctionCall)
                and n.exprs[0][0].name.lower() == "count"
                and not n.exprs[0][0].args
            )
            if count_only_rng and n.group == []:
                rng_op = "Iterate Range Count"
            elif count_only_rng and n.group is None:
                rng_op = "Iterate Range Keys"
            else:
                rng_op = "Iterate Range"
            out.append(
                {
                    "detail": {
                        "direction": direction,
                        "range": rs,
                        "table": v.tb,
                    },
                    "operation": rng_op,
                }
            )
        else:
            out.append(
                {
                    "detail": {"type": "Value"},
                    "operation": "Iterate Value",
                }
            )
    # an index range scan that consumed the ORDER BY (in-order / backward
    # iteration) behaves order-free for the start/limit strategy
    # (iterator.rs can_cancel_on_limit); the marker is internal-only
    order_consumed = any([
        o.get("detail", {}).pop("_order_consumed", False)
        for o in out
        if isinstance(o.get("detail"), dict)
    ])  # list-comp: pop the marker from EVERY entry before any() looks
    out.append(_collector_detail(n, ctx))
    if n.explain in ("full", "postfix-full"):
        out.append(
            {
                "detail": {"type": "KeysAndValues"},
                "operation": "RecordStrategy",
            }
        )
        if (n.start is not None or n.limit is not None) \
                and not range_target:
            # mirrors iterator.rs can_start_skip / can_cancel_on_limit:
            # START pushes to storage only for a single unfiltered iterator
            # (or an index that applies the WHERE itself) with no ORDER BY;
            # LIMIT cancels early unless GROUP BY or un-indexed ORDER BY
            index_backed = bool(out) and str(
                out[0].get("operation", "")
            ).startswith("Iterate Index")
            can_skip = (
                not n.group
                and len(n.what) == 1
                and (n.cond is None or index_backed)
                and (not n.order or order_consumed)
            )
            can_cancel = not n.group and (not n.order or order_consumed)
            detail = {}
            if n.limit is not None and can_cancel:
                detail["CancelOnLimit"] = int(evaluate(n.limit, ctx))
            if n.start is not None and can_skip:
                sv = int(evaluate(n.start, ctx))
                if sv:
                    detail["SkipStart"] = sv
            if detail:
                out.append(
                    {"detail": detail, "operation": "StartLimitStrategy"}
                )
        count = 0
        for expr in n.what:
            v = _target_value(expr, ctx)
            cctx = ctx.child()
            for src in _iterate_value(v, cctx, n.cond, n):
                # the fetch stage counts rows that reach the collector:
                # post-WHERE (scan access paths may over-approximate)
                if n.cond is not None and not cctx._cond_consumed:
                    doc = src.doc if src.rid is not None else src.value
                    cc = cctx.with_doc(doc, src.rid)
                    if not is_truthy(evaluate(n.cond, cc)):
                        continue
                count += 1
        if n.start is not None:
            count = max(count - int(evaluate(n.start, ctx)), 0)
        if n.limit is not None:
            count = min(count, int(evaluate(n.limit, ctx)))
        # an in-order (range-plan) index scan cancelled on limit streams
        # straight from the index: the fetch stage reports 0
        if any(
            o.get("operation") == "StartLimitStrategy"
            and "CancelOnLimit" in o.get("detail", {})
            for o in out
        ) and any(
            o.get("operation") == "Iterate Index"
            and isinstance(o.get("detail", {}).get("plan"), dict)
            and "from" in o["detail"]["plan"]
            for o in out
        ):
            count = 0
        # a top-k collector (MemoryOrderedLimit) holds full rows — the
        # fetch stage never re-reads records (reference: count always 0)
        if any(
            o.get("operation") == "Collector"
            and o.get("detail", {}).get("type") == "MemoryOrderedLimit"
            for o in out
        ):
            count = 0
        out.append({"detail": {"count": count}, "operation": "Fetch"})
    return out


# ---------------------------------------------------------------------------
# write statements -> document pipeline
# ---------------------------------------------------------------------------


def _explain_write(n, ctx):
    from surrealdb_tpu.idx.planner import explain_plan

    # UPSERT defers record creation (Iterable::Defer); other writes on a
    # direct record id iterate the record (dbs/iterator.rs)
    defer = type(n).__name__ == "UpsertStmt"
    out = []
    for expr in n.what:
        v = _target_value(expr, ctx)
        if isinstance(v, Table):
            if defer and n.cond is None:
                # bare-table UPSERT yields one new record — it never
                # scans the table (Iterable::Yield)
                out.append({
                    "detail": {"table": v.name},
                    "operation": "Iterate Yield",
                })
                continue
            plan_e = explain_plan(v.name, n.cond, ctx, n)
            out.extend(plan_e if isinstance(plan_e, list) else [plan_e])
        elif isinstance(v, RecordId) and not isinstance(v.id, Range):
            out.append({
                "detail": {"record": v},
                "operation": "Iterate Defer" if defer else "Iterate Record",
            })
        else:
            out.append({"detail": {"type": "Value"}, "operation": "Iterate Value"})
    out.append({"detail": {"type": "Memory"}, "operation": "Collector"})
    return out


def threading_active() -> int:
    import threading

    return threading.active_count()


def _collector_detail(n: SelectStmt, ctx=None):
    """Collector explain entry; GROUP queries report their aggregation
    slots (reference Group collector: _aN aggregations over exprN argument
    slots, _gN group expressions)."""
    if n.group is None:
        if n.order and n.order != "rand" and n.limit is not None                 and ctx is not None:
            # ordered + limited: the collector keeps start+limit rows
            lim = int(evaluate(n.limit, ctx))
            if n.start is not None:
                lim += int(evaluate(n.start, ctx))
            return {
                "detail": {"limit": lim, "type": "MemoryOrderedLimit"},
                "operation": "Collector",
            }
        ctype = "MemoryOrdered" if n.order else "Memory"
        return {"detail": {"type": ctype}, "operation": "Collector"}
    _AGG_NAMES = {
        "count": "Count", "math::sum": "Sum", "math::mean": "Mean",
        "__count_value__": "CountValue",
        "math::min": "Min", "math::max": "Max", "time::min": "DatetimeMin",
        "time::max": "DatetimeMax", "math::stddev": "StdDev",
        "math::variance": "Variance",
    }
    from surrealdb_tpu.exec.render_def import _expr_sql

    aggs = {}
    sel = {}
    group_exprs = {}
    agg_exprs = {}
    expr_slots: dict = {}  # arg text -> exprN
    ai = 0
    # group slots are numbered in GROUP BY clause order (catalog
    # aggregation planner walks the GROUP BY list, not the projection)
    group_slots: dict = {}  # select-field name -> _gN
    non_agg: dict = {}  # select-field name -> expr
    for expr, alias in n.exprs:
        if expr == "*":
            continue
        if not (
            isinstance(expr, FunctionCall) and expr.name.lower() in _AGG_NAMES
        ):
            non_agg[alias or expr_name(expr)] = expr
    if isinstance(n.group, list):
        for g in n.group:
            gname = expr_name(g)
            gkey = f"_g{len(group_slots)}"
            group_slots[gname] = gkey
            src = non_agg.get(gname, g)
            group_exprs[gkey] = _expr_sql(src)
    for expr, alias in n.exprs:
        if expr == "*":
            continue
        name = alias or expr_name(expr)
        if isinstance(expr, FunctionCall) and expr.name.lower() in _AGG_NAMES:
            key = f"_a{ai}"
            ai += 1
            base = _AGG_NAMES[expr.name.lower()]
            if expr.args:
                if expr.name.lower() == "count":
                    base = "CountValue"
                argtext = expr_name(expr.args[0])
                slot = expr_slots.get(argtext)
                if slot is None:
                    slot = f"expr{len(expr_slots)}"
                    expr_slots[argtext] = slot
                    agg_exprs[slot] = argtext
                aggs[key] = f"{base}({slot})"
            else:
                aggs[key] = base
            sel[name] = key
        else:
            gkey = group_slots.get(name)
            if gkey is None:
                gkey = f"_g{len(group_slots)}"
                group_slots[name] = gkey
                group_exprs[gkey] = _expr_sql(expr)
            sel[name] = gkey
    return {
        "detail": {
            "Aggregate expressions": agg_exprs,
            "Aggregations": aggs,
            "Group expressions": group_exprs,
            "Select expression": sel,
            "type": "Group",
        },
        "operation": "Collector",
    }


def _only_wrap(results, only):
    if not only:
        return results
    if len(results) == 1:
        return results[0]
    if len(results) == 0:
        return NONE
    raise SdbError("Expected a single result output when using the ONLY keyword")


def _timeout_ctx(n, ctx: Ctx) -> Ctx:
    """Child ctx with a deadline when the statement has TIMEOUT (expression-
    valued; reference: parameterized/timeout.surql). Without one, the
    global ALTER SYSTEM QUERY_TIMEOUT applies."""
    from surrealdb_tpu.val import Duration

    if getattr(n, "timeout", None) is None:
        if ctx.deadline is None:
            try:
                cfg = ctx.txn.get_val(K.sys_cfg()) or {}
            except Exception:
                cfg = {}
            d = cfg.get("QUERY_TIMEOUT")
            if isinstance(d, Duration):
                c = ctx.child()
                c.deadline = time.monotonic() + d.to_seconds()
                c.timeout_dur = d
                return c
        return ctx

    d = evaluate(n.timeout, ctx)
    if not isinstance(d, Duration):
        raise SdbError(f"Expected a duration but found {render(d)}")
    c = ctx.child()
    # a statement TIMEOUT can only SHRINK the budget: the edge deadline
    # (X-Surreal-Timeout / server default) stays binding underneath it
    stmt_dl = time.monotonic() + d.to_seconds()
    if ctx.deadline is not None and ctx.deadline < stmt_dl:
        return c
    c.deadline = stmt_dl
    c.timeout_dur = d
    return c


def _s_create(n: CreateStmt, ctx: Ctx):
    from surrealdb_tpu.exec.document import create_one
    ctx = _timeout_ctx(n, ctx)
    ctx.check_deadline()
    if getattr(n, "version", None) is not None:
        from surrealdb_tpu.exec.eval import version_ns

        ctx = ctx.child()
        ctx.write_version = version_ns(evaluate(n.version, ctx))

    results = []
    for expr in n.what:
        v = _target_value(expr, ctx)
        targets = v if isinstance(v, list) else [v]
        for t in targets:
            ctx.check_deadline()
            results.append(create_one(t, n.data, n.output, ctx))
    results = _drop_skipped(results)
    results = [r for r in results if r is not NONE or n.output is not None]
    if n.output is not None and n.output.kind == "none":
        return _only_wrap([], n.only) if n.only else []
    return _only_wrap(results, n.only)


def _s_insert(n: InsertStmt, ctx: Ctx):
    ctx = _timeout_ctx(n, ctx)
    ctx.check_deadline()
    if getattr(n, "version", None) is not None:
        from surrealdb_tpu.exec.eval import version_ns

        ctx = ctx.child()
        ctx.write_version = version_ns(evaluate(n.version, ctx))
    from surrealdb_tpu.exec.document import insert_one, relate_insert_one

    into = None
    if n.into is not None:
        v = _target_value(n.into, ctx)
        if isinstance(v, Table):
            into = v.name
        elif isinstance(v, str):
            into = v
        elif isinstance(v, RecordId):
            into = v.tb
    results = []
    if isinstance(n.data, InsertRows):
        names = [expr_name(f) for f in n.data.fields]
        for row in n.data.rows:
            doc = {}
            for name, ex in zip(names, row):
                _set_path(doc, name.split("."), evaluate(ex, ctx))
            results.append(
                insert_one(into, doc, n.ignore, n.update, n.output, ctx)
            )
    else:
        data = evaluate(n.data, ctx)
        items = data if isinstance(data, list) else [data]
        for item in items:
            ctx.check_deadline()
            if not isinstance(item, dict):
                raise SdbError(f"Cannot INSERT {render(item)}")
            if n.relation:
                results.append(
                    relate_insert_one(into, item, n.ignore, n.output, ctx)
                )
            else:
                results.append(
                    insert_one(into, item, n.ignore, n.update, n.output, ctx)
                )
    results = _drop_skipped(results)
    if n.output is not None and n.output.kind == "none":
        return []
    return results


def _resolve_write_source(src, ctx):
    """Writes resolve object values carrying a record id to that record."""
    if src.rid is None and isinstance(src.value, dict):
        rid = src.value.get("id")
        if isinstance(rid, RecordId):
            return Source(rid=rid, doc=fetch_record(ctx, rid))
    return src


def _s_update(n: UpdateStmt, ctx: Ctx):
    ctx = _timeout_ctx(n, ctx)
    ctx.check_deadline()
    from surrealdb_tpu.exec.document import update_one

    if n.explain:
        return _explain_write(n, ctx)
    results = []
    for src in iterate_targets(n.what, ctx, None, None):
        ctx.check_deadline()
        src = _resolve_write_source(src, ctx)
        if src.rid is None:
            raise SdbError(f"Cannot UPDATE {render(src.value)}")
        if src.doc is NONE:
            continue  # UPDATE only touches existing records
        if n.cond is not None:
            c = ctx.with_doc(src.doc, src.rid)
            if not is_truthy(evaluate(n.cond, c)):
                continue
        results.append(update_one(src.rid, src.doc, n.data, n.output, ctx))
    results = _drop_skipped(results)
    results = [r for r in results if r is not NONE or n.output is None]
    if n.output is not None and n.output.kind == "none":
        return _only_wrap([], False) if not n.only else NONE
    return _only_wrap(results, n.only)


def _s_upsert(n: UpsertStmt, ctx: Ctx):
    ctx = _timeout_ctx(n, ctx)
    ctx.check_deadline()
    from surrealdb_tpu.exec.document import create_one, update_one

    if n.explain:
        return _explain_write(n, ctx)
    results = []
    for expr in n.what:
        v = _target_value(expr, ctx)
        targets = v if isinstance(v, list) else [v]
        for t in targets:
            ctx.check_deadline()
            if isinstance(t, RecordId) and not isinstance(t.id, Range):
                doc = fetch_record(ctx, t)
                if doc is NONE:
                    # a missing record is created regardless of WHERE
                    results.append(create_one(t, n.data, n.output, ctx, upsert=True))
                else:
                    if n.cond is not None:
                        c = ctx.with_doc(doc, t)
                        if not is_truthy(evaluate(n.cond, c)):
                            continue
                    results.append(update_one(t, doc, n.data, n.output, ctx))
            elif isinstance(t, Table) and n.cond is None:
                # bare-table UPSERT is a Yield (reference Iterable::Yield):
                # create ONE new record — unless a unique index already
                # holds the new row's values, which redirects the write to
                # that record (explicit-id UPSERT still errors instead)
                from surrealdb_tpu.exec.document import (
                    _find_unique_conflict,
                    apply_data,
                )

                probe = apply_data({}, n.data, ctx.child(), None,
                                   this_doc=NONE)
                pid = probe.get("id")
                if pid is not None and pid is not NONE:
                    # data carries an explicit id: upsert THAT record
                    from surrealdb_tpu.exec.document import record_id_key

                    prid = pid if isinstance(pid, RecordId) \
                        else RecordId(t.name, record_id_key(pid))
                    doc = fetch_record(ctx, prid)
                    if doc is NONE:
                        results.append(create_one(
                            prid, n.data, n.output, ctx, upsert=True
                        ))
                    else:
                        results.append(
                            update_one(prid, doc, n.data, n.output, ctx)
                        )
                    continue
                existing_rid = _find_unique_conflict(t.name, probe, None, ctx)
                if existing_rid is not None:
                    doc = fetch_record(ctx, existing_rid)
                    results.append(
                        update_one(existing_rid, doc, n.data, n.output, ctx)
                    )
                else:
                    results.append(
                        create_one(t, n.data, n.output, ctx, upsert=True)
                    )
            elif isinstance(t, Table):
                # UPSERT table WHERE: update matching, create if none —
                # an undefined table simply has no matches (no error)
                matched = False
                ns0, db0 = ctx.need_ns_db()
                srcs = (
                    _scan_table(t.name, ctx)
                    if ctx.txn.get(K.tb_def(ns0, db0, t.name)) is not None
                    else []
                )
                for src in srcs:
                    if n.cond is not None:
                        c = ctx.with_doc(src.doc, src.rid)
                        if not is_truthy(evaluate(n.cond, c)):
                            continue
                    matched = True
                    results.append(
                        update_one(src.rid, src.doc, n.data, n.output, ctx)
                    )
                if not matched:
                    results.append(
                        create_one(t, n.data, n.output, ctx, upsert=True)
                    )
            else:
                yield_src = list(_iterate_value(t, ctx))
                for src in yield_src:
                    src = _resolve_write_source(src, ctx)
                    if src.rid is None:
                        raise SdbError(f"Cannot UPSERT {render(src.value)}")
                    if src.doc is NONE:
                        results.append(
                            create_one(src.rid, n.data, n.output, ctx, upsert=True)
                        )
                    else:
                        results.append(
                            update_one(src.rid, src.doc, n.data, n.output, ctx)
                        )
    results = _drop_skipped(results)
    results = [r for r in results if r is not NONE or n.output is None]
    if n.output is not None and n.output.kind == "none":
        return []
    return _only_wrap(results, n.only)


def _s_delete(n: DeleteStmt, ctx: Ctx):
    ctx = _timeout_ctx(n, ctx)
    ctx.check_deadline()
    from surrealdb_tpu.exec.document import delete_one

    if n.explain:
        return _explain_write(n, ctx)
    results = []
    for src in iterate_targets(n.what, ctx, None, None):
        ctx.check_deadline()
        src = _resolve_write_source(src, ctx)
        if src.rid is None:
            raise SdbError(f"Cannot DELETE {render(src.value)}")
        if src.doc is NONE:
            continue
        if n.cond is not None:
            c = ctx.with_doc(src.doc, src.rid)
            if not is_truthy(evaluate(n.cond, c)):
                continue
        r = delete_one(src.rid, src.doc, n.output, ctx)
        if n.output is not None and n.output.kind != "none":
            # permission-skipped rows and select-gated outputs drop out;
            # a legitimately-NONE RETURN VALUE stays
            results.append(r)
    results = _drop_skipped(results)
    return _only_wrap(results, n.only) if n.only else results


def _s_relate(n: RelateStmt, ctx: Ctx):
    ctx = _timeout_ctx(n, ctx)
    ctx.check_deadline()
    from surrealdb_tpu.exec.document import relate_one

    kind_v = _target_value(n.kind, ctx)
    froms = evaluate(n.from_, ctx) if not isinstance(n.from_, Idiom) or not (
        len(n.from_.parts) == 1 and isinstance(n.from_.parts[0], PField)
    ) else _target_value(n.from_, ctx)
    tos = evaluate(n.to, ctx) if not isinstance(n.to, Idiom) or not (
        len(n.to.parts) == 1 and isinstance(n.to.parts[0], PField)
    ) else _target_value(n.to, ctx)
    froms = froms if isinstance(froms, list) else [froms]
    tos = tos if isinstance(tos, list) else [tos]
    results = []
    for f in froms:
        ctx.check_deadline()
        for t in tos:
            fr = _as_rid(f, "in")
            to = _as_rid(t, "id")
            results.append(
                relate_one(kind_v, fr, to, n.data, n.output, ctx, n.uniq)
            )
    if n.output is not None and n.output.kind == "none":
        return []
    if n.output is None:
        results = [r for r in results if r is not NONE]
    results = _drop_skipped(results)
    return _only_wrap(results, n.only)


def _as_rid(v, prop="in"):
    if isinstance(v, RecordId):
        return v
    if isinstance(v, dict) and isinstance(v.get("id"), RecordId):
        return v["id"]
    raise SdbError(
        f"Cannot execute RELATE statement where property '{prop}' "
        f"is: {render(v)}"
    )


# ---------------------------------------------------------------------------
# DEFINE / REMOVE / INFO / etc.
# ---------------------------------------------------------------------------


def _ensure_ns_db(ctx: Ctx):
    """Auto-create namespace/database definitions on first use."""
    ns, db = ctx.need_ns_db()
    if ctx.txn.get(K.ns_def(ns)) is None:
        ctx.txn.set_val(K.ns_def(ns), NamespaceDef(ns))
    if ctx.txn.get(K.db_def(ns, db)) is None:
        ctx.txn.set_val(K.db_def(ns, db), DatabaseDef(db))


def _exists_guard(ctx, key, name, kind, if_not_exists, overwrite,
                  msg=None):
    if ctx.txn.get(key) is not None:
        if if_not_exists:
            return True  # skip silently
        if not overwrite and not getattr(ctx.executor, "import_mode", False):
            raise SdbError(
                msg or f"The {kind} '{name}' already exists"
            )
    return False


def _base_phrase(base, ctx):
    if base == "root":
        return "in the root"
    if base == "ns":
        return f"in the namespace '{ctx.session.ns}'"
    return f"in the database '{ctx.session.db}'"


def _s_define_ns(n: DefineNamespace, ctx):
    if _exists_guard(ctx, K.ns_def(n.name), n.name, "namespace",
                     n.if_not_exists, n.overwrite):
        return NONE
    ctx.txn.set_val(K.ns_def(n.name), NamespaceDef(n.name, n.comment))
    return NONE


def _s_define_db(n: DefineDatabase, ctx):
    ns = ctx.session.ns
    if not ns:
        raise SdbError("Specify a namespace to use")
    if ctx.txn.get(K.ns_def(ns)) is None:
        ctx.txn.set_val(K.ns_def(ns), NamespaceDef(ns))
    if _exists_guard(ctx, K.db_def(ns, n.name), n.name, "database",
                     n.if_not_exists, n.overwrite):
        return NONE
    cf = None
    if n.changefeed is not None:
        from surrealdb_tpu.val import Duration

        d = evaluate(n.changefeed, ctx)
        cf = d.ns if isinstance(d, Duration) else int(d)
    ctx.txn.set_val(
        K.db_def(ns, n.name),
        DatabaseDef(n.name, n.comment, cf, strict=getattr(n, "strict", False)),
    )
    return NONE


def _s_define_table(n: DefineTable, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    if _exists_guard(ctx, K.tb_def(ns, db, n.name), n.name, "table",
                     n.if_not_exists, n.overwrite):
        return NONE
    cf = None
    if n.changefeed is not None:
        from surrealdb_tpu.val import Duration

        d = evaluate(n.changefeed, ctx)
        cf = d.ns if isinstance(d, Duration) else int(d)
    # TYPE defaults: SCHEMAFULL implies NORMAL, otherwise ANY
    # (reference DefineTableStatement); explicit TYPE always wins
    if n.kind is None:
        kind = "normal" if n.full else "any"
    else:
        kind = n.kind
    # catalog table ids allocate monotonically per database (the
    # reference's TableId; surfaced by INFO ... STRUCTURE) — REMOVEd
    # tables never free their id
    _idk = K.tb_idseq(ns, db)
    existing = ctx.txn.get_val(K.tb_def(ns, db, n.name))
    if existing is not None:
        next_id = getattr(existing, "table_id", 0)  # redefinition keeps id
    else:
        next_id = ctx.txn.get_val(_idk) or 0
        ctx.txn.set_val(_idk, next_id + 1)
    tdef = TableDef(
        name=n.name,
        table_id=next_id,
        drop=n.drop,
        full=n.full,
        kind=kind,
        relation_from=n.relation_from,
        relation_to=n.relation_to,
        enforced=n.enforced,
        view=n.view,
        permissions=n.permissions,
        changefeed=cf,
        comment=n.comment,
    )
    ctx.txn.set_val(K.tb_def(ns, db, n.name), tdef)
    if kind == "relation":
        # relation tables implicitly define typed in/out fields
        from surrealdb_tpu.catalog import FieldDef
        from surrealdb_tpu.expr.ast import Kind as _Kind

        for fname, tbs in (("in", n.relation_from), ("out", n.relation_to)):
            fk = K.fd_def(ns, db, n.name, fname)
            if ctx.txn.get(fk) is None or n.overwrite:
                kk = _Kind("record", list(tbs) if tbs else [])
                ctx.txn.set_val(
                    fk,
                    FieldDef(
                        name=[PField(fname)], name_str=fname, kind=kk
                    ),
                )
    if n.view is not None:
        _materialize_view(tdef, ctx)
    return NONE


def _materialize_view(tdef: TableDef, ctx):
    """Populate a `DEFINE TABLE ... AS SELECT` view at definition time by
    feeding every existing source record through the incremental engine
    (reference doc/table.rs model — leaves per-group aggregation stats in
    place for later writes). Build errors don't fail the DEFINE."""
    from surrealdb_tpu.exec import views as V
    from surrealdb_tpu.exec.document import rebuild_view, view_source_tables
    from surrealdb_tpu.kvs.api import deserialize

    try:
        analysis = V.analyze_view(tdef.view)
    except V.Unsupported:
        analysis = None
    if analysis is None:
        try:
            rebuild_view(tdef, ctx)
        except SdbError:
            pass
        return
    ns, db = ctx.need_ns_db()
    # clear any stale rows + stats for a redefinition
    ctx.txn.delete_range(*K.prefix_range(K.record_prefix(ns, db, tdef.name)))
    ctx.txn.delete_range(*K.prefix_range(K.view_meta(ns, db, tdef.name)))
    try:
        for src in view_source_tables(tdef.view):
            beg, end = K.prefix_range(K.record_prefix(ns, db, src))
            for k, raw in list(ctx.txn.scan(beg, end)):
                doc = deserialize(raw)
                rid = doc.get("id") if isinstance(doc, dict) else None
                if not isinstance(rid, RecordId):
                    _ns2, _db2, _tb2, idv = K.decode_record_id(k)
                    rid = RecordId(src, idv)
                V.process_view(tdef, analysis, rid, NONE, doc, "CREATE", ctx)
    except SdbError:
        pass


def _kind_all_records(kind) -> bool:
    """True when every leaf of the type is a record (REFERENCE is only
    valid on record-typed fields; wrappers option/array/set pass through,
    unions need every branch to be records)."""
    if kind is None:
        return False
    nm = kind.name
    if nm == "record":
        return True
    if nm in ("option", "array", "set"):
        return all(
            _kind_all_records(i) for i in (kind.inner or [])
        ) and bool(kind.inner)
    if nm == "either":
        return all(_kind_all_records(i) for i in (kind.inner or []))
    return False


def _s_define_field(n: DefineField, ctx):
    if getattr(n, "flex", False):
        ns0 = ctx.session.ns
        db0 = ctx.session.db
        if ns0 and db0:
            td0 = ctx.txn.get_val(K.tb_def(ns0, db0, n.tb))
            if td0 is not None and not td0.full:
                raise SdbError(
                    "An error occurred: FLEXIBLE can only be used in "
                    "SCHEMAFULL tables"
                )
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    if ctx.txn.get(K.tb_def(ns, db, n.tb)) is None:
        ctx.txn.set_val(K.tb_def(ns, db, n.tb), TableDef(name=n.tb))
    name_str = _field_name_str(n.name)
    _check_computed_field(n, name_str, ns, db, ctx)
    if getattr(n, "reference", None) is not None:
        # reference define/field.rs REFERENCE validations
        if "." in name_str or "[" in name_str:
            raise SdbError(
                f"Cannot use the `REFERENCE` keyword on nested field "
                f"`{name_str}`. Specify a referencing field at the root "
                f"level instead."
            )
        if n.kind is not None and not _kind_all_records(n.kind):
            from surrealdb_tpu.exec.coerce import kind_name as _kn

            raise SdbError(
                f"Cannot use the `REFERENCE` keyword with "
                f"`TYPE {_kn(n.kind)}`. Specify only a `record` type, or "
                f"a type containing only records, instead."
            )
    if name_str == "id":
        # reference define/field.rs validate_id_restrictions
        for kw, present in (
            ("VALUE", n.value is not None),
            ("REFERENCE", getattr(n, "reference", None) is not None),
            ("DEFAULT", n.default is not None),
        ):
            if present:
                raise SdbError(
                    f"Cannot use the `{kw}` keyword on the `id` field."
                )
        if n.kind is not None and not _id_kind_supported(n.kind):
            from surrealdb_tpu.exec.coerce import kind_name as _kn

            raise SdbError(
                f"Cannot use the `{_kn(n.kind)}` type on the `id` field, "
                f"as that's not a valid record id key."
            )
    _check_nested_kind(n, name_str, ns, db, ctx)
    kdef = K.fd_def(ns, db, n.tb, name_str)
    if _exists_guard(ctx, kdef, name_str, "field", n.if_not_exists, n.overwrite):
        return NONE
    fd = FieldDef(
        name=n.name,
        name_str=name_str,
        flex=n.flex,
        kind=n.kind,
        readonly=n.readonly,
        value=n.value,
        assert_=n.assert_,
        default=n.default,
        default_always=n.default_always,
        computed=n.computed,
        permissions=n.permissions,
        reference=n.reference,
        comment=n.comment,
    )
    ctx.txn.set_val(kdef, fd)
    _process_recursive_definitions(n, fd, ns, db, ctx)
    # on a relation table, the `in`/`out` field kinds ARE the relation's
    # endpoint constraint — keep the table def's IN/OUT union in sync so
    # INFO renders the live constraint (reference derives TYPE RELATION
    # IN/OUT from the in/out field definitions)
    if name_str in ("in", "out") and n.kind is not None:
        td = ctx.txn.get_val(K.tb_def(ns, db, n.tb))
        if td is not None and td.kind == "relation":
            tbs = _record_kind_tables(n.kind)
            if tbs is not None:
                import copy as _copy

                td = _copy.copy(td)
                if name_str == "in":
                    td.relation_from = tbs
                else:
                    td.relation_to = tbs
                ctx.txn.set_val(K.tb_def(ns, db, n.tb), td)
    return NONE


def _id_kind_supported(k) -> bool:
    """Kinds usable as a record-id key (reference record_id/key.rs
    kind_supported): any/number/int/string/uuid/array/set/object,
    int/string/array/object literals, and eithers of those."""
    nm = k.name
    if nm in ("any", "number", "int", "string", "uuid", "array", "set",
              "object"):
        return True
    if nm in ("array_literal", "object_literal"):
        return True
    if nm == "literal":
        return isinstance(k.literal, (int, str)) and \
            not isinstance(k.literal, bool)
    if nm == "either":
        return all(_id_kind_supported(b) for b in k.inner)
    return False


def _kind_inner_sub(k):
    """Kind of a container's elements (reference Kind::inner_kind):
    array/set expose their element kind; eithers union their branches'
    element kinds (flattened); everything else has no subtype."""
    from surrealdb_tpu.expr.ast import Kind

    if not isinstance(k, Kind):
        return None
    if k.name in ("array", "set"):
        return k.inner[0] if k.inner else Kind("any")
    if k.name == "option":
        # reference models option<T> as none | T — subtypes pass through
        return _kind_inner_sub(k.inner[0]) if k.inner else None
    if k.name == "either":
        subs = [s for s in (_kind_inner_sub(b) for b in k.inner)
                if s is not None]
        if not subs:
            return None
        flat = []
        for s in subs:
            flat.extend(s.inner if s.name == "either" else [s])
        return flat[0] if len(flat) == 1 else Kind("either", flat)
    return None


def _process_recursive_definitions(n, fd, ns, db, ctx):
    """DEFINE FIELD f TYPE array<K> implicitly defines f.* TYPE K (and so
    on down through nested containers); an existing subtype def keeps its
    other clauses and gets its TYPE replaced. Reference:
    define/field.rs process_recursive_definitions."""
    from surrealdb_tpu.expr.ast import Kind, PAll

    cur = _kind_inner_sub(fd.kind)
    name_parts = list(fd.name)
    depth = 0
    while cur is not None and depth < 16:
        if cur.name == "any":
            # `array` with no element type already implies `.* TYPE any`
            break
        name_parts = name_parts + [PAll()]
        nstr = _field_name_str(name_parts)
        key = K.fd_def(ns, db, n.tb, nstr)
        existing = ctx.txn.get_val(key)
        if existing is not None:
            import copy as _copy

            sub = _copy.copy(existing)
            sub.kind = cur
        else:
            sub = FieldDef(name=list(name_parts), name_str=nstr, kind=cur)
        ctx.txn.set_val(key, sub)
        cur = _kind_inner_sub(cur)
        depth += 1


def _record_kind_tables(kind):
    """For record / record<a | b> kinds, the endpoint table list (empty =
    any record); None when the kind isn't record-shaped."""
    from surrealdb_tpu.expr.ast import Kind

    if not isinstance(kind, Kind):
        return None
    if kind.name == "record":
        # parser stores record<...> endpoint tables as plain ident strings
        return [str(t) for t in (kind.inner or [])]
    if kind.name == "either":
        out = []
        for b in kind.inner or []:
            sub = _record_kind_tables(b)
            if sub is None:
                return None
            out.extend(sub)
        return out
    return None


def _check_nested_kind(n, name_str, ns, db, ctx):
    """A nested field's TYPE must equal the kind its parent projects at
    that segment (reference define/field.rs type-mismatch check)."""
    from surrealdb_tpu.exec.coerce import kind_name
    from surrealdb_tpu.expr.ast import Kind, PIndex as _PIdx

    if n.kind is None or len(n.name) < 2:
        return
    pfd = None
    split = None
    for i in range(len(n.name) - 1, 0, -1):
        cand = _field_name_str(n.name[:i])
        fd = ctx.txn.get_val(K.fd_def(ns, db, n.tb, cand))
        if fd is not None:
            pfd, parent_str, split = fd, cand, i
            break
    if pfd is None or pfd.kind is None:
        return

    def as_seg(p):
        if isinstance(p, PField):
            return ("key", p.name)
        if isinstance(p, PAll):
            return ("all", None)
        if isinstance(p, _PIdx):
            return ("idx", p.expr.value
                    if isinstance(p.expr, Literal) else None)
        return None

    segs = [as_seg(p) for p in n.name[split:]]
    if any(x is None for x in segs):
        return

    ALLOW = object()
    MISMATCH = object()

    def proj(k, seg):
        nm = k.name
        if nm == "option":
            return proj(k.inner[0], seg) if k.inner else ALLOW
        if nm == "either":
            outs = []
            for b in k.inner:
                r = proj(b, seg)
                if r is MISMATCH:
                    return MISMATCH
                if r is ALLOW:
                    continue
                outs.extend(r if isinstance(r, list) else [r])
            return outs or ALLOW
        if nm == "any":
            return ALLOW
        if nm == "object" and not getattr(k, "inner", None):
            # plain objects have keyed children only
            return ALLOW if seg[0] in ("key", "all") else MISMATCH
        if nm in ("array", "set"):
            if seg[0] not in ("all", "idx"):
                return MISMATCH
            if seg[0] == "idx" and getattr(k, "size", None) is not None \
                    and isinstance(seg[1], int) and seg[1] >= k.size:
                return MISMATCH  # index beyond the declared array size
            if not k.inner:
                return ALLOW
            return [k.inner[0]]
        if nm == "array_literal":
            if seg[0] == "idx":
                i = seg[1]
                if isinstance(i, int) and 0 <= i < len(k.inner):
                    return [k.inner[i]]
                return MISMATCH
            if seg[0] == "all":
                return list(k.inner)
            return MISMATCH
        if nm == "object_literal":
            if seg[0] == "key":
                for kk, kv in k.inner:
                    if kk == seg[1]:
                        return [kv]
                return MISMATCH
            if seg[0] == "all":
                return [kv for _kk, kv in k.inner]
            return MISMATCH
        return ALLOW

    if n.kind.name == "any":
        return  # `any` children are always compatible
    kinds = [pfd.kind]
    r = None
    for seg in segs:
        outs = []
        for k in kinds:
            rr = proj(k, seg)
            if rr is MISMATCH:
                outs = MISMATCH
                break
            if rr is ALLOW:
                outs = ALLOW
                break
            outs.extend(rr)
        r = outs
        if r is ALLOW or r is MISMATCH:
            break
        kinds = r
    if r is ALLOW:
        return
    if r is not MISMATCH:
        # canonical union of projected kinds must equal the declared kind;
        # option<K> and nested eithers flatten into the union
        def leaves(k):
            if k.name == "option" and k.inner:
                yield from leaves(k.inner[0])
            elif k.name == "either":
                for b in k.inner:
                    yield from leaves(b)
            else:
                yield kind_name(k)

        names = list(dict.fromkeys(x for k in r for x in leaves(k)))
        if "any" in names:
            return  # parent projects `any` at this segment
        want = " | ".join(names)
        have = " | ".join(
            dict.fromkeys(x for x in leaves(n.kind))
        )
        if want == have:
            return
    raise SdbError(
        f"Cannot set field `{name_str}` with type `{kind_name(n.kind)}` "
        f"as it mismatched with field `{parent_str}` with type "
        f"`{kind_name(pfd.kind)}`"
    )


def _check_computed_field(n, name_str, ns, db, ctx):
    """COMPUTED field validation (reference expr/statements/define/field.rs):
    clause exclusions, top-level-only, no indexes, and cycle detection."""
    existing = {
        fd.name_str: fd
        for _k, fd in ctx.txn.scan_vals(
            *K.prefix_range(K.fd_prefix(ns, db, n.tb))
        )
    }
    if n.computed is None:
        # defining a nested field under a computed parent is an error
        if "." in name_str:
            parent = name_str.split(".")[0]
            pfd = existing.get(parent)
            if pfd is not None and pfd.computed is not None:
                raise SdbError(
                    f"Cannot define nested field `{name_str}` as parent "
                    f"field `{parent}` is a `COMPUTED` field."
                )
        return
    if name_str == "id":
        raise SdbError("Cannot use the `COMPUTED` keyword on the `id` field.")
    for attr, kw in (("value", "VALUE"), ("assert_", "ASSERT"),
                     ("default", "DEFAULT"), ("reference", "REFERENCE"),
                     ("readonly", "READONLY")):
        if getattr(n, attr, None):
            raise SdbError(f"Cannot use the `{kw}` keyword with `COMPUTED`.")
    if len(n.name) > 1:
        raise SdbError(
            f"Cannot define field `{name_str}` as `COMPUTED` fields must "
            "be top-level."
        )
    for other in existing:
        if other.startswith(name_str + ".") or other.startswith(
                name_str + "["):
            raise SdbError(
                f"Cannot define field `{name_str}` as `COMPUTED` since a "
                f"nested field `{other}` already exists."
            )
    # computed fields cannot be indexed
    for _k, idef in ctx.txn.scan_vals(
            *K.prefix_range(K.ix_prefix(ns, db, n.tb))):
        for col in idef.cols_str:
            if col == name_str or col.startswith(name_str + "."):
                raise SdbError(
                    f"Computed fields cannot be indexed. Index: "
                    f"'{idef.name}' - Field: '{name_str}'"
                )
    # cycle detection over the computed-field dependency graph
    deps = {
        fname: sorted(_computed_deps(fd.computed))
        for fname, fd in existing.items()
        if fd.computed is not None and fname != name_str
    }
    deps[name_str] = sorted(_computed_deps(n.computed))

    def dfs(cur, path, seen):
        for d in deps.get(cur, []):
            if d == name_str:
                # canonical cycle: rotate to start at the smallest name
                i = path.index(min(path))
                cyc = path[i:] + path[:i]
                raise SdbError(
                    "Cyclic dependency detected among computed fields: "
                    + " -> ".join(cyc + [cyc[0]])
                )
            if d in deps and d not in seen:
                seen.add(d)
                dfs(d, path + [d], seen)

    dfs(name_str, [name_str], {name_str})


def _computed_deps(expr) -> set:
    """Field names referenced by a computed expression: bare idioms,
    `this.x` / `$this.x`, and `this['x']` bracket access."""
    out = set()

    def visit(node):
        if isinstance(node, Idiom) and node.parts:
            p0 = node.parts[0]
            if isinstance(p0, PField):
                out.add(p0.name)
            elif isinstance(p0, tuple) and len(p0) == 2 and p0[0] == "start":
                base = p0[1]
                if isinstance(base, Param) and base.name in ("this", "self"):
                    rest = node.parts[1:]
                    if rest:
                        r0 = rest[0]
                        if isinstance(r0, PField):
                            out.add(r0.name)
                        elif isinstance(r0, PIndex) and isinstance(
                                r0.expr, Literal) and isinstance(
                                r0.expr.value, str):
                            out.add(r0.expr.value)
            # bracket access on a bare field: a['b'] has PField head,
            # already collected above
        for f in getattr(node, "__dataclass_fields__", {}):
            v = getattr(node, f)
            if isinstance(v, Node):
                visit(v)
            elif isinstance(v, list):
                for x in v:
                    if isinstance(x, Node):
                        visit(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, Node):
                                visit(y)

    if expr is not None:
        visit(expr)
    return out


def _field_name_str(parts) -> str:
    out = []
    for p in parts:
        if isinstance(p, PField):
            out.append(("." if out else "") + p.name)
        elif isinstance(p, PAll):
            out.append(".*" if out else "*")
        elif isinstance(p, PIndex):
            from surrealdb_tpu.expr.ast import Literal as _L

            if isinstance(p.expr, _L):
                out.append(f"[{p.expr.value}]")
        elif isinstance(p, PFlatten):
            out.append("\u2026")  # `field...` renders with an ellipsis
    return "".join(out)


def _s_define_index(n: DefineIndex, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    if ctx.txn.get(K.tb_def(ns, db, n.tb)) is None:
        ctx.txn.set_val(K.tb_def(ns, db, n.tb), TableDef(name=n.tb))
    kdef = K.ix_def(ns, db, n.tb, n.name)
    if _exists_guard(ctx, kdef, n.name, "index", n.if_not_exists, n.overwrite):
        return NONE
    if n.overwrite and ctx.txn.get(kdef) is not None:
        _remove_index_data(ns, db, n.tb, n.name, ctx)
    # computed fields cannot be indexed
    computed_names = {
        fd.name_str
        for _k, fd in ctx.txn.scan_vals(
            *K.prefix_range(K.fd_prefix(ns, db, n.tb)))
        if fd.computed is not None
    }
    cols = []
    for c in n.cols:
        # type::field($f) / type::fields($fs) expand to idioms at define
        # time (reference: parameterized/schema/index.surql)
        if isinstance(c, FunctionCall) and c.name in (
                "type::field", "type::fields"):
            from surrealdb_tpu.syn.parser import Parser

            v = evaluate(c.args[0], ctx) if c.args else NONE
            names = v if c.name == "type::fields" else [v]
            if not isinstance(names, list):
                raise SdbError(
                    f"Expected an array but found {render(names)}")
            for s in names:
                if not isinstance(s, str):
                    raise SdbError(
                        f"Expected a string but found {render(s)}")
                cols.append(Idiom(Parser(s)._field_name_parts()))
        else:
            cols.append(c)
    for c in cols:
        cname = expr_name(c)
        head = cname.split(".")[0].split("[")[0]
        if head in computed_names:
            raise SdbError(
                f"Computed fields cannot be indexed. Index: '{n.name}' - "
                f"Field: '{head}'"
            )
    td = ctx.txn.get_val(K.tb_def(ns, db, n.tb))
    if td is not None and td.full:
        # SCHEMAFULL: every indexed column must resolve to a defined
        # field (or a path its parent's kind can contain)
        for c in cols:
            _check_index_field_exists(c, n.tb, ns, db, ctx)
    idef = IndexDef(
        name=n.name,
        tb=n.tb,
        cols=cols,
        cols_str=[expr_name(c) for c in cols],
        unique=n.unique,
        hnsw=n.hnsw,
        fulltext=n.fulltext,
        count=n.count,
        count_cond=getattr(n, "count_cond", None),
        comment=n.comment,
    )
    ctx.txn.set_val(kdef, idef)
    from surrealdb_tpu.exec.document import build_index

    if getattr(n, "concurrently", False):
        # background build (reference kvs/index.rs IndexBuilder): status
        # moves started -> indexing -> ready, visible via INFO FOR INDEX
        _spawn_index_build(ctx.ds, ns, db, idef)
        return NONE
    build_index(idef, ctx)
    return NONE


def _check_index_field_exists(col, tb, ns, db, ctx):
    """On SCHEMAFULL tables an index column must name a defined field, or
    have a defined top-level parent whose kind permits sub-field access
    (object/any/array/set/object-or-array literals, eithers of those, or
    no declared type). Reference: define/index.rs + kind.rs
    allows_sub_fields."""
    if not isinstance(col, Idiom):
        return
    path = expr_name(col)
    if path == "id":
        return
    if ctx.txn.get_val(K.fd_def(ns, db, tb, path)) is not None:
        return
    head = col.parts[0] if col.parts else None
    if isinstance(head, PField):
        pfd = ctx.txn.get_val(K.fd_def(ns, db, tb, head.name))
        if pfd is not None and (
            pfd.kind is None or _kind_allows_sub_fields(pfd.kind)
        ):
            return
    raise SdbError(f"The field '{path}' does not exist")


def _kind_allows_sub_fields(k) -> bool:
    nm = k.name
    if nm in ("any", "object", "array", "set", "object_literal",
              "array_literal"):
        return True
    if nm == "literal":
        return isinstance(k.literal, (list, dict))
    if nm == "option":
        return all(_kind_allows_sub_fields(b) for b in k.inner) if k.inner \
            else True
    if nm == "either":
        return all(
            b.name == "none" or _kind_allows_sub_fields(b) for b in k.inner
        )
    return False


def _spawn_index_build(ds, ns, db, idef):
    import threading

    from surrealdb_tpu.exec.context import Ctx as _Ctx
    from surrealdb_tpu.kvs.ds import Session as _Session

    key = (ns, db, idef.tb, idef.name)
    ds.index_builds[key] = {
        "status": "started", "initial": 0, "pending": 0, "updated": 0,
    }

    def run():
        from surrealdb_tpu.exec.document import build_index

        for _attempt in range(5):
            txn = ds.transaction(write=True)
            c = _Ctx(ds, _Session(ns=ns, db=db, auth_level="owner"), txn)
            try:
                build_index(idef, c)
                txn.commit()
                return
            except SdbError as e:
                txn.cancel()
                if "conflict" not in str(e):
                    ds.index_builds[key] = {
                        "status": "error", "error": str(e),
                    }
                    return
        ds.index_builds[key] = {
            "status": "error", "error": "too many conflicts",
        }

    threading.Thread(target=run, daemon=True).start()


def _remove_index_data(ns, db, tb, ix, ctx):
    ctx.txn.delete_range(*K.prefix_range(K.index_prefix(ns, db, tb, ix)))
    ctx.txn.delete_range(*K.prefix_range(K.index_unique_prefix(ns, db, tb, ix)))
    ctx.txn.delete_range(*K.prefix_range(K.ix_state(ns, db, tb, ix, b"")))
    ctx.ds.vector_indexes.pop((ns, db, tb, ix), None)
    ctx.ds.ft_indexes.pop((ns, db, tb, ix), None)


def _s_define_event(n: DefineEvent, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    if ctx.txn.get(K.tb_def(ns, db, n.tb)) is None:
        ctx.txn.set_val(K.tb_def(ns, db, n.tb), TableDef(name=n.tb))
    kdef = K.ev_def(ns, db, n.tb, n.name)
    if _exists_guard(ctx, kdef, n.name, "event", n.if_not_exists, n.overwrite):
        return NONE
    ctx.txn.set_val(kdef, EventDef(
        n.name, n.when, n.then, n.comment,
        getattr(n, "async_", False), getattr(n, "retry", None),
        getattr(n, "maxdepth", None),
    ))
    return NONE


def _s_define_param(n: DefineParam, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    kdef = K.pa_def(ns, db, n.name)
    if _exists_guard(ctx, kdef, f"${n.name}", "param", n.if_not_exists, n.overwrite):
        return NONE
    v = evaluate(n.value, ctx)
    ctx.txn.set_val(kdef, ParamDef(n.name, v, n.permissions, n.comment))
    return NONE


def _s_define_function(n: DefineFunction, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    kdef = K.fc_def(ns, db, n.name)
    if _exists_guard(ctx, kdef, n.name, "function", n.if_not_exists,
                     n.overwrite,
                     msg=f"The function 'fn::{n.name}' already exists"):
        return NONE
    ctx.txn.set_val(
        kdef,
        FunctionDef(n.name, n.args, n.block, n.returns, n.permissions, n.comment),
    )
    return NONE


def _s_define_analyzer(n: DefineAnalyzer, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    kdef = K.az_def(ns, db, n.name)
    if _exists_guard(ctx, kdef, n.name, "analyzer", n.if_not_exists, n.overwrite):
        return NONE
    ctx.txn.set_val(
        kdef, AnalyzerDef(n.name, n.tokenizers, n.filters, n.function, n.comment)
    )
    return NONE


_BASE_RANK = {"root": 0, "ns": 1, "db": 2}


def _s_define_user(n: DefineUser, ctx):
    from surrealdb_tpu.fnc.misc_fns import password_hash

    base = n.base
    # a principal can only manage users at or below its own base
    # (reference Options::is_allowed level check / fn auth_limit)
    sess_base = getattr(ctx.session, "auth_base", "root")
    if _BASE_RANK.get(base, 2) < _BASE_RANK.get(sess_base, 0):
        raise SdbError(
            "IAM error: Not enough permissions to perform this action"
        )
    if base in ("ns", "db") and not ctx.session.ns:
        raise SdbError("Specify a namespace to use")
    if base == "db" and not ctx.session.db:
        raise SdbError("Specify a database to use")
    ns = ctx.session.ns if base in ("ns", "db") else None
    db = ctx.session.db if base == "db" else None
    kdef = K.us_def(base, ns, db, n.name)
    ulabel = {"root": "root user", "ns": "namespace user",
              "db": "database user"}[base]
    if _exists_guard(ctx, kdef, n.name, ulabel, n.if_not_exists, n.overwrite):
        return NONE
    ph = n.passhash or (password_hash(n.password) if n.password else "")
    ctx.txn.set_val(
        kdef, UserDef(n.name, base, ph, n.roles, n.duration, n.comment)
    )
    return NONE


def _s_define_module(n, ctx):
    from surrealdb_tpu.surrealism import define_module

    _ensure_ns_db(ctx)
    data = evaluate(n.executable, ctx)
    if isinstance(data, str):
        try:
            data = data.encode("latin-1")
        except UnicodeEncodeError:
            raise SdbError(
                "DEFINE MODULE expects the module bytes — pass a <bytes> "
                "value (the string form cannot carry binary payloads)"
            )
    if not isinstance(data, (bytes, bytearray)):
        raise SdbError(
            "DEFINE MODULE expects the module bytes (a <bytes> value)"
        )
    name = n.name
    if name is None:
        from surrealdb_tpu.surrealism import SurliModule

        name = SurliModule.from_bytes(bytes(data)).header.get("name")
        if not name:
            raise SdbError("DEFINE MODULE requires a name (mod::name AS ...)")
    define_module(name, bytes(data), ctx, comment=n.comment,
                  if_not_exists=n.if_not_exists, overwrite=n.overwrite)
    return NONE


def _s_define_access(n: DefineAccess, ctx):
    base = n.base
    ns = ctx.session.ns if base in ("ns", "db") else None
    db = ctx.session.db if base == "db" else None
    # materialize expression-valued config (KEY $key etc.) and validate
    # the algorithm surface (reference access_type.rs)
    cfg = dict(n.config)
    for a in ("key", "issuer_key", "url"):
        v = cfg.get(a)
        if isinstance(v, Node):
            rv = evaluate(v, ctx)
            cfg[a] = None if rv is NONE else rv
    kdef = K.ac_def(base, ns, db, n.name)
    if _exists_guard(
        ctx, kdef, n.name, "access", n.if_not_exists, n.overwrite,
        msg=(f"The access method '{n.name}' already exists "
             f"{_base_phrase(base, ctx)}"),
    ):
        # IF NOT EXISTS short-circuits before algorithm validation
        return NONE
    alg = (cfg.get("alg") or "").upper()
    ialg = (cfg.get("issuer_alg") or "").upper()
    if "ES512" in (alg, ialg):
        raise SdbError(
            "The ES512 algorithm is not currently supported. "
            "Please use ES384 or another supported algorithm"
        )
    if alg.startswith("HS") and cfg.get("issuer_key") is not None \
            and cfg.get("key") is not None \
            and cfg["issuer_key"] != cfg["key"]:
        raise SdbError(
            f"Invalid query: Symmetric algorithm {alg} requires the same "
            "key for signing and verification. Use the same key value for "
            "both KEY and WITH ISSUER KEY clauses, or omit WITH ISSUER KEY."
        )
    ctx.txn.set_val(
        kdef, AccessDef(n.name, base, n.kind, cfg, n.duration, n.comment)
    )
    return NONE


def _s_define_sequence(n: DefineSequence, ctx):
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    kdef = K.seq_state(ns, db, n.name)
    if ctx.txn.get(kdef) is not None:
        if n.if_not_exists:
            return NONE
        if not n.overwrite:
            raise SdbError(f"The sequence '{n.name}' already exists")
    tmo = None
    if n.timeout is not None:
        from surrealdb_tpu.val import Duration

        tmo = evaluate(n.timeout, ctx)
        if not isinstance(tmo, Duration):
            raise SdbError(f"Expected a duration but found {render(tmo)}")
    sd = SequenceDef(n.name, n.batch, n.start, tmo)
    ctx.txn.set_val(kdef, (sd, n.start))
    ctx.ds.sequences.pop((ns, db, n.name), None)  # drop stale local batch
    return NONE


def _s_define_config(n: DefineConfig, ctx):
    from surrealdb_tpu.catalog import (
        ApiActionDef,
        ApiDef,
        BucketDef,
        ConfigDef,
    )

    if n.what == "DEFAULT":
        # KV-level default session namespace/database (INFO FOR KV .defaults)
        key = K.cfg_def("", "", "DEFAULT")
        if _exists_guard(ctx, key, "DEFAULT", "config", n.if_not_exists,
                         n.overwrite):
            return NONE
        ctx.txn.set_val(key, dict(n.config))
        return NONE
    _ensure_ns_db(ctx)
    ns, db = ctx.need_ns_db()
    if n.what == "API_DEF":
        from surrealdb_tpu.api import validate_define_path

        cfg = n.config
        validate_define_path(str(cfg["path"]))
        key = K.api_def(ns, db, cfg["path"])
        if _exists_guard(ctx, key, cfg["path"], "api", n.if_not_exists,
                         n.overwrite):
            return NONE
        # middleware args are computed at define time (reference:
        # parameterized/schema/api.surql renders fn::middleware('auth'))
        def _mw(mw):
            return [
                (name, [Literal(evaluate(a, ctx)) for a in args])
                for name, args in mw
            ]

        actions = [
            ApiActionDef(a["methods"], _mw(a["middleware"]), a["permissions"],
                         a["then"])
            for a in cfg["actions"]
        ]
        comment = cfg.get("comment")
        if comment is not None and not isinstance(comment, str):
            comment = evaluate(comment, ctx)
            if comment is NONE:
                comment = None
        ctx.txn.set_val(key, ApiDef(cfg["path"], actions, None, comment))
        return NONE
    if n.what == "BUCKET":
        cfg = n.config
        key = K.bucket_def(ns, db, cfg["name"])
        if _exists_guard(ctx, key, cfg["name"], "bucket", n.if_not_exists,
                         n.overwrite):
            return NONE
        comment = cfg.get("comment")
        if comment is not None and not isinstance(comment, str):
            comment = evaluate(comment, ctx)
            if comment is NONE:
                comment = None
        from surrealdb_tpu.buc import check_backend_allowed

        backend = cfg.get("backend")
        if backend is not None and not isinstance(backend, str):
            backend = evaluate(backend, ctx)
        check_backend_allowed(backend)
        ctx.txn.set_val(
            key,
            BucketDef(cfg["name"], backend,
                      cfg.get("readonly", False),
                      cfg.get("permissions", True), comment),
        )
        return NONE
    key = K.cfg_def(ns, db, n.what)
    if _exists_guard(ctx, key, n.what, "config", n.if_not_exists, n.overwrite,
                     msg=f"The config for {n.what.lower()} already exists"):
        return NONE
    cd = ConfigDef(n.what)
    cfg = n.config
    if "middleware" in cfg:
        cd.middleware = cfg["middleware"]
    if "permissions" in cfg:
        cd.permissions = cfg["permissions"]
    if "tables" in cfg:
        cd.tables = cfg["tables"]
    if "functions" in cfg:
        cd.functions = cfg["functions"]
    if "depth" in cfg:
        cd.depth = cfg["depth"]
    if "complexity" in cfg:
        cd.complexity = cfg["complexity"]
    if "introspection" in cfg:
        cd.introspection = cfg["introspection"]
    ctx.txn.set_val(key, cd)
    return NONE


def _s_remove(n: RemoveStmt, ctx: Ctx):
    ns = ctx.session.ns
    db = ctx.session.db
    kind = n.kind

    def _guard(key, label):
        if ctx.txn.get(key) is None:
            if n.if_exists:
                return True
            raise SdbError(f"The {kind} '{label}' does not exist")
        return False

    if kind == "namespace":
        key = K.ns_def(n.name)
        if _guard(key, n.name):
            return NONE
        ctx.txn.delete(key)
        ctx.txn.delete_range(*K.prefix_range(K.db_prefix(n.name)))
        ctx.txn.delete_range(*K.prefix_range(b"/*" + K.enc_str(n.name)))
        return NONE
    if kind == "database":
        key = K.db_def(ns, n.name)
        if _guard(key, n.name):
            return NONE
        ctx.txn.delete(key)
        ctx.txn.delete_range(*K.prefix_range(K.tb_prefix(ns, n.name)))
        ctx.txn.delete_range(
            *K.prefix_range(b"/*" + K.enc_str(ns) + b"*" + K.enc_str(n.name))
        )
        return NONE
    if kind == "table":
        key = K.tb_def(ns, db, n.name)
        if _guard(key, n.name):
            return NONE
        # a table with dependent views cannot be removed (reference
        # catalog guard; view/removed.surql, view/delete_view.surql)
        from surrealdb_tpu.exec.document import view_source_tables

        dependents = [
            d.name
            for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.tb_prefix(ns, db)))
            if d.view is not None and d.name != n.name
            and n.name in view_source_tables(d.view)
        ]
        if dependents:
            raise SdbError(
                f"Invalid query: Cannot delete table `{n.name}` on which a "
                f"view is defined, table(s) `{'`, `'.join(dependents)}` are "
                f"defined as a view on this table."
            )
        ctx.txn.delete(key)
        for kk in (K.fd_prefix, K.ix_prefix, K.ev_prefix, K.lq_prefix):
            ctx.txn.delete_range(*K.prefix_range(kk(ns, db, n.name)))
        base = K._tb(ns, db, n.name)
        ctx.txn.delete_range(*K.prefix_range(base))
        for ixkey in list(ctx.ds.vector_indexes):
            if ixkey[:3] == (ns, db, n.name):
                ctx.ds.vector_indexes.pop(ixkey, None)
        for ixkey in list(ctx.ds.ft_indexes):
            if ixkey[:3] == (ns, db, n.name):
                ctx.ds.ft_indexes.pop(ixkey, None)
        gk = (ns, db, n.name)
        from surrealdb_tpu.exec.document import _bump_graph_version

        _bump_graph_version(ctx, gk)
        if ctx.ds.graph_engine:
            for ck in list(ctx.ds.graph_engine):
                if ck[2] == n.name or ck[3] == n.name:
                    ctx.ds.graph_engine.pop(ck, None)
        return NONE
    if kind == "field":
        name_str = _field_name_str(n.name) if isinstance(n.name, list) else n.name
        key = K.fd_def(ns, db, n.tb, name_str)
        if _guard(key, name_str):
            return NONE
        ctx.txn.delete(key)
        return NONE
    if kind == "index":
        key = K.ix_def(ns, db, n.tb, n.name)
        if _guard(key, n.name):
            return NONE
        ctx.txn.delete(key)
        _remove_index_data(ns, db, n.tb, n.name, ctx)
        return NONE
    if kind == "event":
        key = K.ev_def(ns, db, n.tb, n.name)
        if _guard(key, n.name):
            return NONE
        ctx.txn.delete(key)
        return NONE
    if kind == "param":
        key = K.pa_def(ns, db, n.name)
        if ctx.txn.get(key) is None:
            if n.if_exists:
                return NONE
            raise SdbError(f"The param '${n.name}' does not exist")
        ctx.txn.delete(key)
        return NONE
    if kind == "function":
        key = K.fc_def(ns, db, n.name)
        if _guard(key, f"fn::{n.name}"):
            return NONE
        ctx.txn.delete(key)
        return NONE
    if kind == "analyzer":
        key = K.az_def(ns, db, n.name)
        if _guard(key, n.name):
            return NONE
        ctx.txn.delete(key)
        return NONE
    if kind == "user":
        base = n.base or "root"
        ulabel = {"root": "root user", "ns": "namespace user",
                  "db": "database user"}[base]
        key = K.us_def(base, ns if base in ("ns", "db") else None,
                       db if base == "db" else None, n.name)
        if ctx.txn.get(key) is None:
            if n.if_exists:
                return NONE
            raise SdbError(f"The {ulabel} '{n.name}' does not exist")
        ctx.txn.delete(key)
        return NONE
    if kind == "access":
        base = n.base or "db"
        key = K.ac_def(base, ns if base in ("ns", "db") else None,
                       db if base == "db" else None, n.name)
        if ctx.txn.get(key) is None:
            if n.if_exists:
                return NONE
            raise SdbError(
                f"The access method '{n.name}' does not exist "
                f"{_base_phrase(base, ctx)}"
            )
        ctx.txn.delete(key)
        return NONE
    if kind == "sequence":
        key = K.seq_state(ns, db, n.name)
        if _guard(key, n.name):
            return NONE
        ctx.txn.delete(key)
        ctx.ds.sequences.pop((ns, db, n.name), None)
        return NONE
    if kind in ("config", "api", "bucket"):
        keyf = {"config": K.cfg_def, "api": K.api_def,
                "bucket": K.bucket_def}[kind]
        nm = n.name.upper() if kind == "config" else n.name
        if kind == "config" and nm == "DEFAULT":
            # DEFINE stores DEFAULT at root level; REMOVE checks there even
            # when ALTER upserted a DB-level copy (reference behaviour)
            key = K.cfg_def("", "", "DEFAULT")
        else:
            key = keyf(ns, db, nm)
        if ctx.txn.get(key) is None:
            if n.if_exists:
                return NONE
            if kind == "config":
                raise SdbError(
                    f"The config for {n.name.lower()} does not exist"
                )
            raise SdbError(f"The {kind} '{nm}' does not exist")
        ctx.txn.delete(key)
        return NONE
    if kind == "module":
        from surrealdb_tpu.surrealism import remove_module

        nm = n.name
        if nm.startswith("mod::"):
            nm = nm[5:]
        remove_module(nm, ctx, if_exists=n.if_exists)
        return NONE
    raise SdbError(f"unknown REMOVE kind {kind}")


def _supports_compaction(ctx) -> bool:
    return hasattr(ctx.ds.backend, "compact")


def _s_alter(n: AlterTable, ctx: Ctx):
    ns, db = ctx.need_ns_db()
    key = K.tb_def(ns, db, n.name)
    tdef = ctx.txn.take_val(key)
    if tdef is None:
        if n.if_exists:
            return NONE
        raise SdbError(f"The table '{n.name}' does not exist")
    if getattr(n, "compact", False) and not _supports_compaction(ctx):
        raise SdbError(
            "The storage layer does not support compaction requests."
        )
    if n.full is not None:
        tdef.full = n.full
    if n.drop is not None:
        tdef.drop = n.drop
    if n.kind is not None:
        tdef.kind = n.kind
    if n.relation_from is not None:
        tdef.relation_from = n.relation_from
    if n.relation_to is not None:
        tdef.relation_to = n.relation_to
    if n.permissions is not None:
        tdef.permissions = n.permissions
    if n.comment is not None:
        if n.comment == "__drop__":
            tdef.comment = None
        else:
            c = n.comment
            if isinstance(c, Node):
                c = evaluate(c, ctx)
            tdef.comment = None if c is NONE else c
    if n.changefeed is not None:
        if n.changefeed == "__drop__":
            tdef.changefeed = None
        else:
            from surrealdb_tpu.val import Duration

            d = evaluate(n.changefeed, ctx)
            tdef.changefeed = d.ns if isinstance(d, Duration) else int(d)
    ctx.txn.set_val(key, tdef)
    return NONE


def _s_alter_other(n: AlterStmt, ctx: Ctx):
    """ALTER for non-table definitions: load, apply clause edits, store."""
    ns = ctx.session.ns
    db = ctx.session.db
    kind = n.kind
    labels = {
        "field": "field", "index": "index", "event": "event",
        "param": "param", "function": "function", "analyzer": "analyzer",
        "user": "user", "access": "access", "sequence": "sequence",
        "api": "api", "bucket": "bucket", "config": "config",
    }
    if kind == "database":
        if n.name is not None and ctx.txn.get(K.db_def(ns, n.name)) is None:
            if n.if_exists:
                return NONE
            raise SdbError(f"The database '{n.name}' does not exist")
        if ("compact", True) in (n.changes or []) and not _supports_compaction(ctx):
            raise SdbError(
                "The storage layer does not support compaction requests."
            )
        return NONE  # COMPACT is a maintenance hint elsewhere
    if kind == "config":
        spec = dict(n.changes).get("config_spec") or {}
        what = n.name.upper()
        if what == "DEFAULT":
            # upsert behaviour, stored at DB level (unlike DEFINE, which
            # stores at root — REMOVE CONFIG DEFAULT checks root and errors)
            from surrealdb_tpu.catalog import ConfigDef

            key = K.cfg_def(ns, db, "DEFAULT")
            d = ctx.txn.take_val(key)
            if not isinstance(d, ConfigDef):
                d = ConfigDef("DEFAULT")
            for k2 in ("namespace", "database"):
                if k2 in spec:
                    v = spec[k2]
                    setattr(d, k2, v if isinstance(v, str) else evaluate(v, ctx))
            ctx.txn.set_val(key, d)
            return NONE
        key = K.cfg_def(ns, db, what)
        d = ctx.txn.take_val(key)
        if d is None:
            if n.if_exists:
                return NONE
            raise SdbError(f"The config for {what.lower()} does not exist")
        for k2 in ("middleware", "permissions", "tables", "functions",
                   "depth", "complexity", "introspection"):
            if k2 in spec:
                setattr(d, k2, spec[k2])
        ctx.txn.set_val(key, d)
        return NONE
    if kind in ("system", "model", "module"):
        if kind == "system":
            from surrealdb_tpu.val import Duration as _Dur

            for clause, value in (n.changes or []):
                if clause == "compact" and not _supports_compaction(ctx):
                    raise SdbError(
                        "The storage layer does not support compaction "
                        "requests."
                    )
                if clause == "query_timeout":
                    skey = K.sys_cfg()
                    cfg = ctx.txn.take_val(skey) or {}
                    if value == "__drop__":
                        cfg.pop("QUERY_TIMEOUT", None)
                    else:
                        v = evaluate(value, ctx)
                        if not isinstance(v, _Dur):
                            raise SdbError(
                                f"Expected a duration but found {render(v)}"
                            )
                        cfg["QUERY_TIMEOUT"] = v
                    if cfg:
                        ctx.txn.set_val(skey, cfg)
                    else:
                        ctx.txn.delete(skey)
        return NONE
    if kind in ("api", "bucket"):
        keyf = K.api_def if kind == "api" else K.bucket_def
        key = keyf(ns, db, n.name)
        d = ctx.txn.take_val(key)
        if d is None:
            if n.if_exists:
                return NONE
            raise SdbError(f"The {kind} '{n.name}' does not exist")
        for clause, value in n.changes:
            if value == "__drop__":
                if clause == "comment":
                    d.comment = None
                elif clause == "readonly":
                    d.readonly = False
                continue
            if clause == "comment":
                v = value
                if not isinstance(v, (str, type(None))):
                    v = evaluate(v, ctx)
                    if v is NONE:
                        v = None
                d.comment = v
            elif clause == "api_then":
                methods, body = value
                from surrealdb_tpu.catalog import ApiActionDef

                if methods == ["any"]:
                    # the fallback updates in place (it renders first)
                    for a in d.actions:
                        if "any" in a.methods:
                            a.then = body
                            break
                    else:
                        d.actions.append(ApiActionDef(["any"], [], True, body))
                else:
                    # an updated method handler moves to the END of the
                    # action list (the reference removes + re-pushes)
                    for a in list(d.actions):
                        if set(a.methods) == set(methods):
                            d.actions.remove(a)
                            a.then = body
                            d.actions.append(a)
                            break
                    else:
                        d.actions.append(ApiActionDef(methods, [], True, body))
            elif clause == "api_drop_then":
                methods = value
                for a in list(d.actions):
                    if not any(m in a.methods for m in methods):
                        continue
                    # selective drop: surviving methods of a multi-method
                    # group keep the handler under the remaining methods
                    a.methods = [m for m in a.methods if m not in methods]
                    if not a.methods:
                        a.then = None
                        if not a.middleware:
                            d.actions.remove(a)
            elif hasattr(d, clause):
                setattr(d, clause, value)
        ctx.txn.set_val(key, d)
        return NONE
    keymap = {
        "field": lambda: K.fd_def(ns, db, n.tb, n.name if isinstance(n.name, str) else _field_name_str(n.name)),
        "index": lambda: K.ix_def(ns, db, n.tb, n.name),
        "event": lambda: K.ev_def(ns, db, n.tb, n.name),
        "param": lambda: K.pa_def(ns, db, n.name),
        "function": lambda: K.fc_def(ns, db, n.name),
        "analyzer": lambda: K.az_def(ns, db, n.name),
        "user": lambda: K.us_def(
            n.base or "root",
            ns if (n.base or "root") in ("ns", "db") else None,
            db if (n.base or "root") == "db" else None,
            n.name,
        ),
        "access": lambda: K.ac_def(
            n.base or "db",
            ns if (n.base or "db") in ("ns", "db") else None,
            db if (n.base or "db") == "db" else None,
            n.name,
        ),
        "sequence": lambda: K.seq_state(ns, db, n.name),
    }
    key = keymap[kind]()
    stored = ctx.txn.take_val(key)
    if stored is None:
        if n.if_exists:
            return NONE
        disp = n.name
        if kind == "function":
            disp = f"fn::{disp}"
        elif kind == "param":
            disp = f"${disp}"
        if kind == "access":
            raise SdbError(
                f"The access method '{disp}' does not exist "
                f"{_base_phrase(n.base or 'db', ctx)}"
            )
        if kind == "user":
            raise SdbError(
                f"The user '{disp}' does not exist "
                f"{_base_phrase(n.base or 'root', ctx)}"
            )
        raise SdbError(
            f"The {labels.get(kind, kind)} '{disp}' does not exist"
        )
    d = stored[0] if kind == "sequence" else stored
    if kind == "sequence":
        from surrealdb_tpu.val import Duration as _Dur

        for i2, (clause, value) in enumerate(list(n.changes)):
            if clause == "timeout" and value != "__drop__" and not isinstance(
                value, _Dur
            ):
                v2 = evaluate(value, ctx)
                if v2 is NONE or v2 is None:
                    n.changes[i2] = (clause, "__drop__")
                    continue
                if not isinstance(v2, _Dur):
                    raise SdbError(
                        f"Expected a duration but found {render(v2)}"
                    )
                n.changes[i2] = (clause, v2)
    for clause, value in n.changes:
        if value == "__drop__":
            if clause == "comment":
                d.comment = None
            elif clause in ("value", "default", "when"):
                setattr(d, "default" if clause == "default" else clause, None)
            elif clause == "assert":
                d.assert_ = None
            elif clause == "type":
                d.kind = None
            elif clause == "async":
                d.async_ = False
                d.retry = None
                d.maxdepth = None
            elif clause == "readonly":
                d.readonly = False
            elif clause == "flexible":
                d.flex = False
            elif clause in ("tokenizers", "filters", "roles"):
                setattr(d, clause, [])
            elif clause == "duration":
                d.duration = None
            elif clause == "timeout":
                d.timeout = None
            elif clause == "reference":
                d.reference = None
            continue
        if clause == "password":
            from surrealdb_tpu.fnc.misc_fns import password_hash

            d.passhash = password_hash(value)
            continue
        if clause == "value" and kind == "param":
            d.value = evaluate(value, ctx)
            continue
        if hasattr(d, clause):
            v = value
            if clause in ("comment",) and not isinstance(v, (str, type(None))):
                v = evaluate(v, ctx)
                if v is NONE:
                    v = None
            setattr(d, clause, v)
    if kind == "sequence":
        ctx.txn.set_val(key, (d, stored[1]))
    else:
        ctx.txn.set_val(key, d)
    return NONE


def _s_rebuild(n: RebuildIndex, ctx: Ctx):
    ns, db = ctx.need_ns_db()
    idef = ctx.txn.get_val(K.ix_def(ns, db, n.tb, n.name))
    if idef is None:
        if n.if_exists:
            return NONE
        raise SdbError(f"The index '{n.name}' does not exist")
    _remove_index_data(ns, db, n.tb, n.name, ctx)
    from surrealdb_tpu.exec.document import build_index

    build_index(idef, ctx)
    return NONE


# ---------------------------------------------------------------------------
# INFO
# ---------------------------------------------------------------------------


class _AtTxn:
    """Read adapter serving catalog definitions as of a timestamp."""

    def __init__(self, txn, ts: int):
        self._txn = txn
        self._ts = ts

    def get_val(self, key):
        return self._txn.get_val_at(key, self._ts)

    def get(self, key):
        v = self._txn.get_val_at(key, self._ts)
        return None if v is None else b"\x01"

    def scan_vals(self, beg, end, limit=None, reverse=False):
        yield from self._txn.scan_vals_at(beg, end, self._ts)

    def __getattr__(self, name):
        return getattr(self._txn, name)


def _s_info(n: InfoStmt, ctx: Ctx):
    from surrealdb_tpu.exec.render_def import (
        render_access,
        render_analyzer,
        render_db,
        render_event,
        render_field,
        render_function,
        render_index,
        render_ns,
        render_param,
        render_sequence,
        render_table,
        render_user,
    )

    if getattr(n, "version", None) is not None:
        from surrealdb_tpu.exec.eval import version_ns

        ts = version_ns(evaluate(n.version, ctx))
        ctx = ctx.child()
        ctx.txn = _AtTxn(ctx.txn, ts)
    if n.level == "system":
        import os as _os

        mem_kb = 0
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS"):
                        mem_kb = int(line.split()[1])
                        break
        except OSError:
            pass
        # device state from the supervisor — never `import jax` on a
        # query thread (check_robustness rule 5): the runner subprocess
        # owns the backend, INFO reads its health snapshot
        from surrealdb_tpu.device import get_supervisor
        from surrealdb_tpu.telemetry import (
            stage_snapshot as _stage_snapshot,
        )

        def _mem_snapshot():
            from surrealdb_tpu.resource import get_accountant

            return get_accountant().snapshot()

        def _columnar_snapshot(ds):
            from surrealdb_tpu.exec.batch import counters, store_nbytes

            out = dict(counters(ds))
            out["colstore_bytes"] = store_nbytes(ds)
            out["colstore_tables"] = len(
                getattr(ds, "_table_columns", {})
            )
            return out

        dev = get_supervisor().status()

        # shard topology (kvs/shard.py): ranges, epochs, primaries —
        # None/absent on unsharded stores. topology() serves the
        # last-known map without network I/O, so this can't stall INFO;
        # SdbError only covers the never-initialised-map edge.
        from surrealdb_tpu.err import (
            QueryCancelled as _QC, QueryTimeout as _QT,
        )

        try:
            shard_topo = ctx.ds.backend.topology()
        except (_QC, _QT):
            raise  # cancellation must never be absorbed by INFO
        except SdbError:
            shard_topo = None
        # follower-read serving state (kvs/remote.py closed-timestamp
        # protocol): per-group closed_ts/lag/era observations plus the
        # session floor and the served/rejected/fallback counters —
        # cache-only, same no-network discipline as topology()
        repl_info = None
        repl_fn = getattr(ctx.ds.backend, "replication_info", None)
        if repl_fn is not None:
            try:
                repl_info = {
                    "groups": repl_fn(),
                    "counters": {
                        k: ctx.ds.telemetry.get(k) for k in (
                            "follower_reads_served",
                            "follower_read_fallbacks",
                        )
                    },
                    "closed_ts_lag_s":
                        ctx.ds.backend.replication_lag_s(),
                }
            except (_QC, _QT):
                raise
            except SdbError:
                repl_info = None
        out = {
            "available_parallelism": _os.cpu_count() or 1,
            "cpu_usage": 0.0,
            "load_average": list(_os.getloadavg()),
            "memory_allocated": mem_kb * 1024,
            "memory_usage": mem_kb * 1024,
            "physical_cores": _os.cpu_count() or 1,
            "threads": threading_active(),
            "tpu_devices": (dev.get("device_count", 0)
                            if dev.get("state") == "ready" else 0),
            # device supervisor health: state (cold/probing/ready/
            # degraded), restart/timeout counters, last error, resident
            # block-cache counts — the serving-side view of the runner
            "device": dev,
            "metrics": dict(ctx.ds.metrics),
            # slow-query log ring (kvs/slowlog.rs; threshold via
            # SURREAL_SLOW_QUERY_THRESHOLD_MS)
            "slow_queries": [
                {"ms": ms, "statement": label}
                for ms, label in ctx.ds.slow_log[-50:]
            ],
            # in-flight (non-LIVE) query registry: each id is a valid
            # KILL <query-id> target (inflight.py)
            "queries": ctx.ds.inflight.snapshot(),
            # per-stage query timing (PR-6 overhead strip) — the same
            # table tools/profile_query.py prints and /metrics exports
            "stages": _stage_snapshot(),
            # live-query fan-out spine health (server/fanout.py):
            # sessions, dispatch backlog, overflow/drop tallies
            "live": dict(ctx.ds.fanout.stats(),
                         subscriptions=len(ctx.ds.live_queries)),
            # node-wide resource governance (resource.py): accounted
            # derived-state bytes vs the soft/hard watermarks, the
            # per-kind breakdown, and eviction/shed/throttle counters
            "mem": _mem_snapshot(),
            # columnar executor health (exec/batch.py + exec/vops.py):
            # vectorized vs fallback rows, aggregate tier hits, column
            # store builds/hits/bytes, fused-KNN and pushdown tallies
            "columnar": _columnar_snapshot(ctx.ds),
        }
        if shard_topo is not None:
            out["shards"] = shard_topo
        if repl_info is not None:
            out["replication"] = repl_info
        # vector index residency — rows, host bytes, ANN state, sync
        # version, mesh width (device_sharded, device/mesh.py), and for
        # shard-partitioned serving (idx/shardvec.py) the per-shard
        # slices + replica addresses — so an operator can see which
        # slice of which index is serving where
        knn_status = []
        for ixkey, eng in list(ctx.ds.vector_indexes.items()):
            ent = {"index": ".".join(str(x) for x in ixkey)}
            status_fn = getattr(eng, "shards_status", None)
            if status_fn is not None:
                ent["shards"] = status_fn()
            else:
                res_fn = getattr(eng, "residency", None)
                if res_fn is None:
                    continue
                ent["residency"] = res_fn()
            knn_status.append(ent)
        if knn_status:
            out["knn"] = knn_status
        return out
    if n.level == "root":
        out = {"accesses": {}, "namespaces": {}, "nodes": {}, "system": {},
               "users": {}}
        syscfg = ctx.txn.get_val(K.sys_cfg())
        if syscfg:
            out["config"] = {k: v for k, v in sorted(syscfg.items())}
        dflt = ctx.txn.get_val(K.cfg_def("", "", "DEFAULT"))
        # always present: {} when no DEFAULT config (remove/config/default)
        out["defaults"] = (
            {k: v for k, v in sorted(dflt.items())} if dflt is not None
            else {}
        )
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ns_prefix())):
            out["namespaces"][d.name] = render_ns(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.us_prefix("root"))):
            out["users"][d.name] = render_user(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ac_prefix("root"))):
            out["accesses"][d.name] = render_access(d)
        return out
    if n.level == "ns":
        ns = ctx.session.ns
        out = {"accesses": {}, "databases": {}, "users": {}}
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.db_prefix(ns))):
            out["databases"][d.name] = render_db(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.us_prefix("ns", ns))):
            out["users"][d.name] = render_user(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ac_prefix("ns", ns))):
            out["accesses"][d.name] = render_access(d)
        return out
    if n.level == "db":
        ns, db = ctx.need_ns_db()
        out = {
            "accesses": {}, "analyzers": {}, "apis": {}, "buckets": {},
            "configs": {}, "functions": {}, "models": {}, "modules": {},
            "params": {}, "sequences": {}, "tables": {}, "users": {},
        }
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.tb_prefix(ns, db))):
            out["tables"][d.name] = render_table(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.pa_prefix(ns, db))):
            out["params"][d.name] = render_param(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.fc_prefix(ns, db))):
            out["functions"][d.name] = render_function(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.mod_prefix(ns, db))):
            txt = f"DEFINE MODULE mod::{d.name} AS <module>"
            if d.comment:
                txt += f" COMMENT '{d.comment}'"
            txt += " PERMISSIONS FULL"
            out["modules"][d.name] = txt
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ml_prefix(ns, db))):
            label = f"{d.name}<{d.version}>"
            txt = f"DEFINE MODEL ml::{d.name}<{d.version}>"
            if d.comment:
                txt += f" COMMENT '{d.comment}'"
            txt += " PERMISSIONS FULL"
            out["models"][label] = txt
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.az_prefix(ns, db))):
            out["analyzers"][d.name] = render_analyzer(d)
        for _k, d in ctx.txn.scan_vals(
            *K.prefix_range(K.us_prefix("db", ns, db))
        ):
            out["users"][d.name] = render_user(d)
        for _k, d in ctx.txn.scan_vals(
            *K.prefix_range(K.ac_prefix("db", ns, db))
        ):
            out["accesses"][d.name] = render_access(d)
        for _k, st in ctx.txn.scan_vals(
            *K.prefix_range(b"/!sq" + K.enc_str(ns) + K.enc_str(db))
        ):
            sd = st[0]
            out["sequences"][sd.name] = render_sequence(sd)
        from surrealdb_tpu.exec.render_def import (
            render_api,
            render_bucket,
            render_config,
        )

        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.api_prefix(ns, db))):
            out["apis"][d.path] = render_api(d)
        for _k, d in ctx.txn.scan_vals(
            *K.prefix_range(K.bucket_prefix(ns, db))
        ):
            out["buckets"][d.name] = render_bucket(d)
        _cfg_names = {"GRAPHQL": "GraphQL", "API": "API", "DEFAULT": "Default"}
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.cfg_prefix(ns, db))):
            out["configs"][_cfg_names.get(d.what, d.what)] = render_config(d)
        if n.structure:
            from surrealdb_tpu.exec.render_def import (
                config_structure,
                table_structure,
            )

            out["configs"] = [
                config_structure(d)
                for _k, d in ctx.txn.scan_vals(
                    *K.prefix_range(K.cfg_prefix(ns, db))
                )
            ]
            # STRUCTURE mode lists structured defs instead of SQL strings
            out["tables"] = [
                table_structure(d)
                for _k, d in ctx.txn.scan_vals(
                    *K.prefix_range(K.tb_prefix(ns, db))
                )
            ]
            seqs = []
            for _k, st in ctx.txn.scan_vals(
                *K.prefix_range(b"/!sq" + K.enc_str(ns) + K.enc_str(db))
            ):
                sd = st[0]
                seqs.append({
                    "name": sd.name,
                    "batch": str(sd.batch),
                    "start": str(sd.start),
                    "timeout": sd.timeout if sd.timeout is not None else NONE,
                })
            out["sequences"] = seqs
            for k2 in ("accesses", "analyzers", "apis", "buckets",
                       "functions", "models", "modules", "params", "users"):
                if isinstance(out.get(k2), dict):
                    out[k2] = list(out[k2].values())
        return out
    if n.level == "table":
        from surrealdb_tpu.exec.render_def import (
            event_structure,
            field_structure,
            index_structure,
        )

        ns, db = ctx.need_ns_db()
        tb = n.target
        if ctx.txn.get(K.tb_def(ns, db, tb)) is None:
            raise SdbError(f"The table '{tb}' does not exist")
        if n.structure:
            out = {"events": [], "fields": [], "indexes": [], "lives": [],
                   "tables": []}
            for _k, d in ctx.txn.scan_vals(
                *K.prefix_range(K.fd_prefix(ns, db, tb))
            ):
                out["fields"].append(field_structure(d, tb))
            for _k, d in ctx.txn.scan_vals(
                *K.prefix_range(K.ix_prefix(ns, db, tb))
            ):
                out["indexes"].append(index_structure(d))
            for _k, d in ctx.txn.scan_vals(
                *K.prefix_range(K.ev_prefix(ns, db, tb))
            ):
                out["events"].append(event_structure(d, tb))
            return out
        out = {"events": {}, "fields": {}, "indexes": {}, "lives": {},
               "tables": {}}
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.fd_prefix(ns, db, tb))):
            from surrealdb_tpu.exec.render_def import field_name_key

            out["fields"][field_name_key(d.name_str)] = render_field(d, tb)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ix_prefix(ns, db, tb))):
            out["indexes"][d.name] = render_index(d)
        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.ev_prefix(ns, db, tb))):
            out["events"][d.name] = render_event(d, tb)
        # views (foreign tables) whose FROM sources this table are listed
        # under `tables` (reference catalog: table definitions carry their
        # source link; INFO FOR TABLE shows dependent views)
        from surrealdb_tpu.exec.document import view_source_tables

        for _k, d in ctx.txn.scan_vals(*K.prefix_range(K.tb_prefix(ns, db))):
            if d.view is not None and tb in view_source_tables(d.view):
                out["tables"][d.name] = render_table(d)
        return out
    if n.level == "index":
        ns, db = ctx.need_ns_db()
        idef = ctx.txn.get_val(K.ix_def(ns, db, n.target2, n.target))
        if idef is None:
            raise SdbError(f"The index '{n.target}' does not exist")
        st = ctx.ds.index_builds.get((ns, db, n.target2, n.target))
        if st is None:
            st = {"status": "ready", "initial": 0, "pending": 0,
                  "updated": 0}
        return {"building": dict(st)}
    if n.level == "user":
        explicit = None
        if n.target2:
            t2 = n.target2.lower()
            explicit = {"db": "db", "database": "db", "ns": "ns",
                        "namespace": "ns", "root": "root"}.get(t2)
        bases = (explicit,) if explicit else ("db", "ns", "root")
        key = None
        for b in bases:
            key_try = K.us_def(
                b,
                ctx.session.ns if b in ("ns", "db") else None,
                ctx.session.db if b == "db" else None,
                n.target,
            )
            if ctx.txn.get(key_try) is not None:
                key = key_try
                break
        if key is None:
            if explicit and explicit != "root":
                raise SdbError(
                    f"The user '{n.target}' does not exist "
                    f"{_base_phrase(explicit, ctx)}"
                )
            raise SdbError(f"The root user '{n.target}' does not exist")
        from surrealdb_tpu.exec.render_def import render_user

        return render_user(ctx.txn.get_val(key))
    raise SdbError(f"unknown INFO level {n.level}")


# ---------------------------------------------------------------------------
# LIVE / KILL / SHOW
# ---------------------------------------------------------------------------


def _s_live(n: LiveStmt, ctx: Ctx):
    ns, db = ctx.need_ns_db()
    what = _target_value(n.what, ctx)
    if not isinstance(what, Table):
        raise SdbError("LIVE SELECT requires a table")
    lid = Uuid.new_v4()
    sub = SubscriptionDef(
        id=str(lid.u),
        ns=ns,
        db=db,
        tb=what.name,
        expr=n.expr,
        cond=n.cond,
        fetch=n.fetch,
        session_vars=dict(ctx.vars),
        auth_level=ctx.session.auth_level,
        rid=ctx.session.rid,
        node=ctx.ds.node_id,
    )
    ctx.txn.set_val(K.lq_def(ns, db, what.name, str(lid.u)), sub)
    ctx.ds.live_queries[str(lid.u)] = sub
    # route to the session's outbox IN THE SAME STEP as registration:
    # binding later (rpc layer, after the statement returns) leaves a
    # window where a dispatch worker matches the sub but finds no
    # route and silently drops the notification
    ob = getattr(ctx.session, "live_outbox", None)
    if ob is not None:
        ctx.ds.fanout.bind(str(lid.u), ob)
    return lid


def _s_kill(n: KillStmt, ctx: Ctx):
    v = evaluate(n.id, ctx)
    if isinstance(v, str):
        lid = v
    elif isinstance(v, Uuid):
        lid = str(v.u)
    else:
        raise SdbError("KILL requires a live query uuid")
    sub = ctx.ds.live_queries.pop(lid, None)
    if sub is not None:
        # stop routing BEFORE deleting the row: a dispatch worker that
        # already matched this lid may still hold a notification, but
        # nothing new is enqueued to the session after KILL returns
        ctx.ds.fanout.unbind(lid)
    if sub is None:
        # not a LIVE query: try the in-flight (normal) query registry —
        # KILL <query-id> sets the cooperative cancel flag and the
        # target fails with "The query was cancelled" at its next
        # check_deadline site
        if ctx.ds.inflight.kill(lid):
            return NONE
        raise SdbError(
            f"Can not execute KILL statement using id '{render(v)}'"
        )
    ctx.txn.delete(K.lq_def(sub.ns, sub.db, sub.tb, lid))
    return NONE


def _s_show(n: ShowStmt, ctx: Ctx):
    from surrealdb_tpu.cf import read_changes

    return read_changes(n, ctx)


_GRANT_POOL = (
    "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
)


def _access_level(n, ctx):
    """Resolve the statement's base (explicit ON, else the session's
    selected base — reference Options::selected_base)."""
    base = n.base
    if base is None:
        base = ("db" if ctx.session.db
                else "ns" if ctx.session.ns else "root")
    ns = ctx.session.ns if base in ("ns", "db") else None
    db = ctx.session.db if base == "db" else None
    if base == "db" and (not ns or not db):
        ctx.need_ns_db()
    if base == "ns" and not ns:
        raise SdbError("Specify a namespace to use")
    return base, ns, db


def _access_nf(base, ctx, name):
    if base == "root":
        return f"The root access method '{name}' does not exist"
    if base == "ns":
        return (f"The access method '{name}' does not exist in the "
                f"namespace '{ctx.session.ns}'")
    return (f"The access method '{name}' does not exist in the "
            f"database '{ctx.session.db}'")


def _user_nf(base, ctx, name):
    if base == "root":
        return f"The root user '{name}' does not exist"
    if base == "ns":
        return (f"The user '{name}' does not exist in the "
                f"namespace '{ctx.session.ns}'")
    return (f"The user '{name}' does not exist in the "
            f"database '{ctx.session.db}'")


def _grant_object(g: dict, redact: bool) -> dict:
    """SurrealQL object for an access grant (reference
    expr/statements/access.rs access_object_from_grant)."""
    grant = dict(g["grant"])
    if redact and "key" in grant:
        grant["key"] = "[REDACTED]"
    return {
        "id": g["id"],
        "ac": g["ac"],
        "type": g["type"],
        "creation": g["creation"],
        "expiration": g.get("expiration", NONE),
        "revocation": g.get("revocation", NONE),
        "subject": dict(g["subject"]),
        "grant": grant,
    }


def _s_access(n, ctx):
    from surrealdb_tpu.val import Datetime, Duration

    if n.op == "alter_sequence":
        ns, db = ctx.need_ns_db()
        if ctx.txn.get(K.seq_state(ns, db, n.name)) is None and not n.subject:
            raise SdbError(f"The sequence '{n.name}' does not exist")
        return NONE
    base, ns, db = _access_level(n, ctx)
    adef = ctx.txn.get_val(K.ac_def(base, ns, db, n.name))
    if adef is None:
        raise SdbError(_access_nf(base, ctx, n.name))

    if n.op == "grant":
        if adef.kind != "bearer":
            raise SdbError(
                f"The functionality 'Grants for {adef.kind.upper()}' is "
                f"not implemented"
            )
        kind, sv = n.subject
        bearer_for = (adef.config or {}).get("for", "user")
        if kind == "user":
            if bearer_for != "user":
                raise SdbError(
                    "The access method cannot issue grants to the "
                    "provided subject"
                )
            if ctx.txn.get(K.us_def(base, ns, db, sv)) is None:
                raise SdbError(_user_nf(base, ctx, sv))
            subject = {"user": sv}
        else:
            if bearer_for != "record":
                raise SdbError(
                    "The access method cannot issue grants to the "
                    "provided subject"
                )
            rid = evaluate(sv, ctx)
            subject = {"record": rid}
        rng = _random.SystemRandom()
        gid = rng.choice(_GRANT_POOL[10:]) + "".join(
            rng.choice(_GRANT_POOL) for _ in range(11)
        )
        secret = "".join(rng.choice(_GRANT_POOL) for _ in range(24))
        creation = Datetime.now()
        dur = (adef.duration or {}).get("grant", Duration.parse("30d"))
        if isinstance(dur, Duration):
            import datetime as _dt

            expiration = Datetime(
                creation.dt + _dt.timedelta(seconds=dur.to_seconds()),
                creation.ns_frac, creation.year_shift,
            )
        else:
            expiration = NONE
        g = {
            "id": gid,
            "ac": n.name,
            "type": "bearer",
            "creation": creation,
            "expiration": expiration,
            "revocation": NONE,
            "subject": subject,
            "grant": {"id": gid, "key": f"surreal-bearer-{gid}-{secret}"},
        }
        ctx.txn.set_val(K.ac_grant(base, ns, db, n.name, gid), g)
        # the ONE place the real key is returned (reference: grants are
        # redacted everywhere after creation)
        return _grant_object(g, redact=False)

    beg, end = K.prefix_range(K.ac_grant_prefix(base, ns, db, n.name))

    def _matching():
        sel_kind, operand = n.selector or ("all", None)
        for k, g in ctx.txn.scan_vals(beg, end):
            if sel_kind == "grant" and g["id"] != operand:
                continue
            if sel_kind == "where":
                doc = _grant_object(g, redact=True)
                if not is_truthy(evaluate(operand, ctx.with_doc(doc, None))):
                    continue
            yield k, g

    if n.op == "show":
        return [_grant_object(g, redact=True) for _k, g in _matching()]

    if n.op == "revoke":
        out = []
        now = Datetime.now()
        for k, g in _matching():
            if g.get("revocation") not in (None, NONE):
                continue
            g = dict(g)
            g["revocation"] = now
            ctx.txn.set_val(k, g)
            out.append(_grant_object(g, redact=True))
        return out

    if n.op == "purge":
        kinds, grace_e = n.purge or (set(), None)
        grace = 0.0
        if grace_e is not None:
            gv = evaluate(grace_e, ctx)
            if isinstance(gv, Duration):
                grace = gv.to_seconds()
        now = Datetime.now()
        out = []
        for k, g in _matching():
            exp = g.get("expiration")
            rev = g.get("revocation")
            dead = False
            gns = int(grace * 1e9)
            if "expired" in kinds and isinstance(exp, Datetime):
                dead = dead or now.epoch_ns() - exp.epoch_ns() >= gns
            if "revoked" in kinds and isinstance(rev, Datetime):
                dead = dead or now.epoch_ns() - rev.epoch_ns() >= gns
            if dead:
                ctx.txn.delete(k)
                out.append(_grant_object(g, redact=True))
        return out

    raise SdbError(f"unknown ACCESS operation '{n.op}'")


_STMTS = {
    LetStmt: _s_let,
    ReturnStmt: _s_return,
    IfStmt: _s_if,
    ForStmt: _s_for,
    BreakStmt: _s_break,
    ContinueStmt: _s_continue,
    ThrowStmt: _s_throw,
    SleepStmt: _s_sleep,
    UseStmt: _s_use,
    OptionStmt: _s_option,
    SelectStmt: _s_select,
    CreateStmt: _s_create,
    InsertStmt: _s_insert,
    UpdateStmt: _s_update,
    UpsertStmt: _s_upsert,
    DeleteStmt: _s_delete,
    RelateStmt: _s_relate,
    DefineNamespace: _s_define_ns,
    DefineDatabase: _s_define_db,
    DefineTable: _s_define_table,
    DefineField: _s_define_field,
    DefineIndex: _s_define_index,
    DefineEvent: _s_define_event,
    DefineParam: _s_define_param,
    DefineFunction: _s_define_function,
    DefineAnalyzer: _s_define_analyzer,
    DefineUser: _s_define_user,
    DefineAccess: _s_define_access,
    DefineModule: _s_define_module,
    DefineSequence: _s_define_sequence,
    DefineConfig: _s_define_config,
    RemoveStmt: _s_remove,
    AlterTable: _s_alter,
    AlterStmt: _s_alter_other,
    ExplainStmt: _s_explain_generic,
    RebuildIndex: _s_rebuild,
    InfoStmt: _s_info,
    LiveStmt: _s_live,
    KillStmt: _s_kill,
    ShowStmt: _s_show,
    AccessStmt: _s_access,
}


def _import_silences(fn):
    """OPTION IMPORT: data statements run fully (indexes populate) but
    report NONE, matching import-stream behavior (statements/option)."""

    def wrapped(n, ctx):
        out = fn(n, ctx)
        if getattr(ctx.executor, "import_mode", False):
            # the statement's natural empty shape: ONLY -> NONE, else []
            return NONE if getattr(n, "only", False) else []
        return out

    return wrapped


for _t in (CreateStmt, InsertStmt, UpdateStmt, UpsertStmt, DeleteStmt,
           RelateStmt):
    _STMTS[_t] = _import_silences(_STMTS[_t])

