"""Streaming batched operator engine (execution engine A).

Reference: core/src/exec/mod.rs:1-35 — push-based batched operator DAG
(`ValueBatch` streams, no recursive compute()) with per-operator metrics
(core/src/exec/metrics.rs:50-60) surfaced through EXPLAIN ANALYZE.

Design notes (TPU-first host engine):
- Operators are generator pipelines over row batches (`list[Source]`,
  BATCH_SIZE rows). SurrealQL rows are ragged/heterogeneous, so batches
  stay row-major; rectangular NUMERIC columns (vector fields) are
  extracted per batch and evaluated vectorized — one numpy/device call
  per batch instead of one `evaluate()` per row. That columnar fast path
  is where the batched engine beats the row-at-a-time legacy executor
  (the reference gets the same effect from its columnar ValueBatch).
- Every operator owns an OpMetrics (rows/batches/elapsed-ns). Metrics
  are recorded only when enabled (EXPLAIN ANALYZE) — zero overhead on
  the normal path, like the reference's `monitor_stream`.
- Statements outside the supported shape fall back to the legacy
  recursive executor (`plan_or_compute.rs:69` legacy_compute analog) —
  the reference ships exactly this dual-engine split.
"""

from __future__ import annotations

import heapq
import time

import numpy as np

from surrealdb_tpu import cnf
from surrealdb_tpu.err import SdbError
from surrealdb_tpu.val import NONE, Table, is_truthy

BATCH_SIZE = cnf.OPERATOR_BUFFER_SIZE

_UNSUPPORTED = object()


class OpMetrics:
    __slots__ = ("rows", "batches", "ns", "enabled", "vrows", "frows")

    def __init__(self):
        self.rows = 0
        self.batches = 0
        self.ns = 0
        self.enabled = False
        # columnar accounting: rows served by the vectorized kernels vs
        # rows that took the scalar-fallback path (EXPLAIN ANALYZE shows
        # both so a fallback regression is visible per operator)
        self.vrows = 0
        self.frows = 0


def _fmt_elapsed(ns: int) -> str:
    if ns >= 1_000_000_000:
        return f"{ns / 1e9:.2f}s"
    if ns >= 1_000_000:
        return f"{ns / 1e6:.2f}ms"
    if ns >= 1_000:
        return f"{ns / 1e3:.2f}µs"
    return f"{ns}ns"


class Operator:
    """Base operator: `execute(ctx)` yields row batches; `lines()` yields
    (depth, label, metrics) rows for EXPLAIN ANALYZE rendering."""

    label = "Op [ctx: Db]"

    def __init__(self, *children):
        self.children = list(children)
        self.metrics = OpMetrics()

    def enable_metrics(self):
        self.metrics.enabled = True
        for c in self.children:
            c.enable_metrics()

    def execute(self, ctx):
        gen = self._execute(ctx)
        if not self.metrics.enabled:
            return gen
        m = self.metrics

        def monitored():
            while True:
                t0 = time.perf_counter_ns()
                try:
                    b = next(gen)
                except StopIteration:
                    m.ns += time.perf_counter_ns() - t0
                    return
                m.ns += time.perf_counter_ns() - t0
                m.rows += len(b)
                m.batches += 1
                yield b

        return monitored()

    def _execute(self, ctx):  # pragma: no cover — abstract
        raise NotImplementedError

    def lines(self, depth=0):
        out = [(depth, self.label, self.metrics)]
        for c in self.children:
            out.extend(c.lines(depth + 1))
        return out


# ---------------------------------------------------------------------------
# scan
# ---------------------------------------------------------------------------


# NOTE: the old `_vector_pred` numeric-AND-tree compiler grew into the
# general columnar expression compiler in exec/vops.py (comparison /
# boolean / arithmetic / IN over classified typed columns with per-row
# exotic fallback); TableScanOp routes every predicate through it.


class TableScanOp(Operator):
    """Batched table scan with the predicate inlined (single-target scans
    absorb the WHERE — reference operators/scan/table.rs) and optional
    limit/offset pushdown. Emits post-filter rows."""

    def __init__(self, tb: str, cond, pushed_limit, pushed_offset,
                 direction: str, label: str, cols=None):
        super().__init__()
        self.tb = tb
        self.cond = cond
        self.pushed_limit = pushed_limit
        self.pushed_offset = pushed_offset
        self.direction = direction
        self.label = label
        self.cols = cols  # ColumnCache for vectorized predicates (later)

    def _execute(self, ctx):
        from surrealdb_tpu import key as K
        from surrealdb_tpu.exec.eval import (
            apply_computed_fields, computed_fields_of, evaluate,
        )
        from surrealdb_tpu.kvs.api import deserialize
        from surrealdb_tpu.val import RecordId

        ns, db = ctx.need_ns_db()
        if ctx.txn.get(K.tb_def(ns, db, self.tb)) is None:
            raise SdbError(f"The table '{self.tb}' does not exist")
        has_computed = bool(computed_fields_of(self.tb, ctx))
        pre = K.record_prefix(ns, db, self.tb)
        beg, end = K.prefix_range(pre)
        plen = len(pre)
        reverse = self.direction == "Backward"
        skip = self.pushed_offset or 0
        remaining = self.pushed_limit
        from surrealdb_tpu.exec.statements import Source

        vec = None
        if self.cond is not None and not has_computed:
            from surrealdb_tpu.exec import vops

            vec = vops.compile_predicate(self.cond, ctx)

        def row_pass(src):
            cc = ctx.with_doc(src.doc, src.rid)
            return is_truthy(evaluate(self.cond, cc))

        if vec is not None:
            # columnar filter: evaluate whole pending batches through
            # the vops kernels; rows the kernels classify exotic fall
            # back row-wise (bit-identical values, identical errors)
            from surrealdb_tpu.exec.batch import BatchCols, _count

            pend: list = []
            batch = []

            def flush():
                nonlocal pend, skip, remaining, batch
                mask, fb = vec.masks(BatchCols(pend), ctx)
                nfb = int(fb.sum())
                m = self.metrics
                m.vrows += len(pend) - nfb
                m.frows += nfb
                _count(ctx.ds, "batches_vectorized")
                _count(ctx.ds, "rows_vectorized", len(pend) - nfb)
                if nfb:
                    _count(ctx.ds, "rows_fallback", nfb)
                passing = [
                    s_ for s_, ok, f in zip(pend, mask, fb)
                    if (row_pass(s_) if f else ok)
                ]
                pend = []
                for src in passing:
                    if skip > 0:
                        skip -= 1
                        continue
                    batch.append(src)
                    if remaining is not None:
                        remaining -= 1
                        if remaining <= 0:
                            return True
                return False

            done = False
            for k, raw in ctx.txn.scan(beg, end, reverse=reverse):
                ctx.check_deadline()
                # the scan prefix pins (ns, db, tb): only the id decodes
                idv, _pos = K.dec_value(k, plen)
                doc = deserialize(raw)
                pend.append(Source(rid=RecordId(self.tb, idv), doc=doc))
                if len(pend) >= BATCH_SIZE:
                    done = flush()
                    if batch:
                        yield batch
                        batch = []
                    if done:
                        break
            if pend and not done:
                flush()
            if batch:
                yield batch
            return

        batch = []
        for k, raw in ctx.txn.scan(beg, end, reverse=reverse):
            ctx.check_deadline()
            idv, _pos = K.dec_value(k, plen)
            rid = RecordId(self.tb, idv)
            doc = deserialize(raw)
            if has_computed:
                doc = apply_computed_fields(self.tb, doc, rid, ctx)
            src = Source(rid=rid, doc=doc)
            if self.cond is not None:
                cc = ctx.with_doc(doc, rid)
                if not is_truthy(evaluate(self.cond, cc)):
                    continue
            if skip > 0:
                skip -= 1
                continue
            batch.append(src)
            if remaining is not None:
                remaining -= 1
                if remaining <= 0:
                    break
            if len(batch) >= BATCH_SIZE:
                yield batch
                batch = []
        if batch:
            yield batch


# ---------------------------------------------------------------------------
# sort / limit
# ---------------------------------------------------------------------------


def _order_key_fn(order, ctx, aliases, cols):
    """Row→sort-key function with EXACT legacy semantics (reuses the
    comparator machinery from exec/statements._apply_order_sources)."""
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.exec.statements import _OrderKey, _resolve_alias

    resolved = []
    for e, d, c, num in order:
        r = _resolve_alias(e, aliases)
        # aliases re-compute their projection (traversal allowed); raw
        # idioms sort value-only without record-link fetches
        resolved.append((r, d, c, num, r is not e))

    def key(src):
        doc = src.doc if src.rid is not None else src.value
        cc = ctx.with_doc(doc, src.rid)
        cc.knn = ctx.knn
        keys = []
        for e, d, collate, numeric, was_alias in resolved:
            v = cols.get_row(e, src)
            if v is _COL_MISS:
                cc._no_link_fetch = not was_alias
                try:
                    v = evaluate(e, cc)
                finally:
                    cc._no_link_fetch = False
            keys.append((v, d, collate, numeric))
        return _OrderKey(keys)

    return key


def _lexsort_try(rows, order, aliases, ctx, keep=None):
    """Colstore-backed sort for the streaming operators: clean scalar
    key columns go through one np.lexsort (exec/vops.py) instead of
    the row-at-a-time key extractor; None → the exact scalar sort
    (exotic rows, uncompilable keys, COLLATE/NUMERIC, tiny inputs)."""
    from surrealdb_tpu.exec.statements import _resolve_alias
    from surrealdb_tpu.exec.vops import lexsort_sources

    items = [
        (_resolve_alias(e, aliases), d, c, num)
        for e, d, c, num in order
    ]
    return lexsort_sources(rows, items, ctx, keep=keep)


class SortOp(Operator):
    """Pipeline-breaking full sort (SortByKey)."""

    def __init__(self, child, order, aliases, cols, label):
        super().__init__(child)
        self.order = order
        self.aliases = aliases
        self.cols = cols
        self.label = label

    def _execute(self, ctx):
        rows = []
        for b in self.children[0].execute(ctx):
            self.cols.prime(b, ctx)
            rows.extend(b)
        fast = _lexsort_try(rows, self.order, self.aliases, ctx)
        if fast is not None:
            rows = fast
        else:
            rows.sort(
                key=_order_key_fn(self.order, ctx, self.aliases,
                                  self.cols)
            )
        for s in range(0, len(rows), BATCH_SIZE):
            yield rows[s:s + BATCH_SIZE]


class VecTopKScanOp(Operator):
    """Columnar brute-force vector top-k: ORDER BY a recognized vector
    expression with LIMIT over a full table scan rides the persistent
    column store (col.py + the native C++ extraction kernel) — score the
    whole table in one numpy call, then materialize ONLY the winning
    rows. The winners' projected scores recompute per-row in f64 from
    the fetched documents, so output values are bit-identical to the
    row-at-a-time engine; only the ranking runs on the f32 column.
    Reference role: exec/operators/knn_topk.rs (KnnTopK scan operator)."""

    def __init__(self, tb, spec, keep, skip, desc, label):
        super().__init__()
        self.tb = tb
        self.spec = spec  # (kind, parts, qvec, expr)
        self.keep = keep
        self.skip = skip
        self.desc = desc
        self.label = label

    def _execute(self, ctx):
        from surrealdb_tpu import key as K
        from surrealdb_tpu.col import get_vector_column
        from surrealdb_tpu.exec.eval import fetch_record
        from surrealdb_tpu.exec.statements import Source
        from surrealdb_tpu.val import RecordId

        ns, db = ctx.need_ns_db()
        if ctx.txn.get(K.tb_def(ns, db, self.tb)) is None:
            raise SdbError(f"The table '{self.tb}' does not exist")
        kind, parts, qv, _expr = self.spec
        col = get_vector_column(ctx, self.tb, parts[0], qv.shape[0])
        if col is None or col.bad_ids:
            # dirty overlay or non-conforming rows: the planner guards
            # against engaging here, but races resolve to the safe path
            raise _FallbackToLegacy()
        m = col.mat
        qf = qv.astype(np.float32)
        if kind == "cos_sim":
            dots = m @ qf
            denom = col.norms() * np.linalg.norm(qf)
            with np.errstate(divide="ignore", invalid="ignore"):
                scores = dots / denom
        elif kind == "eucl":
            scores = np.linalg.norm(m - qf[None, :], axis=1)
        elif kind == "manh":
            scores = np.abs(m - qf[None, :]).sum(axis=1)
        else:  # dot
            scores = m @ qf
        n_rows = scores.shape[0]
        k = min(self.keep, n_rows)
        key = -scores if self.desc else scores
        if k < n_rows:
            part = np.argpartition(key, k - 1)[:k]
            order = part[np.argsort(key[part], kind="stable")]
        else:
            order = np.argsort(key, kind="stable")
        order = order[self.skip:]
        batch = []
        for i in order:
            ctx.check_deadline()
            rid = RecordId(self.tb, col.ids[int(i)])
            doc = fetch_record(ctx, rid)
            if doc is NONE:
                continue
            batch.append(Source(rid=rid, doc=doc))
            if len(batch) >= BATCH_SIZE:
                yield batch
                batch = []
        if batch:
            yield batch


class _FallbackToLegacy(Exception):
    """Raised mid-plan when a columnar fast path can't serve the txn."""


class SortTopKOp(Operator):
    """Order + limit as a bounded top-k (SortTopKByKey + Limit): keeps
    limit+offset rows via a heap instead of sorting the whole input —
    the reference's sort/topk.rs pipeline-breaking aggregate."""

    def __init__(self, child, order, aliases, cols, keep: int, skip: int,
                 label: str, limit_label: str):
        super().__init__(child)
        self.order = order
        self.aliases = aliases
        self.cols = cols
        self.keep = keep
        self.skip = skip
        self.label = label
        self.limit_label = limit_label
        self.limit_metrics = OpMetrics()

    def enable_metrics(self):
        super().enable_metrics()
        self.limit_metrics.enabled = True

    def _execute(self, ctx):
        rows = []
        for b in self.children[0].execute(ctx):
            self.cols.prime(b, ctx)
            rows.extend(b)
        top = _lexsort_try(rows, self.order, self.aliases, ctx,
                           keep=self.keep)
        if top is None:
            key = _order_key_fn(self.order, ctx, self.aliases,
                                self.cols)
            top = heapq.nsmallest(self.keep, rows, key=key)
        out = top[self.skip:]
        # the Limit node above the top-k drops the offset rows
        self.limit_metrics.rows += len(out)
        self.limit_metrics.batches += 1
        for s in range(0, len(out), BATCH_SIZE):
            yield out[s:s + BATCH_SIZE]

    def lines(self, depth=0):
        out = [
            (depth, self.limit_label, self.limit_metrics),
            (depth, self.label, self.metrics),
        ]
        for c in self.children:
            out.extend(c.lines(depth + 1))
        return out


class LimitOp(Operator):
    """START/LIMIT slicing when a sort sits below (not pushed into scan)."""

    def __init__(self, child, skip: int, limit, label):
        super().__init__(child)
        self.skip = skip
        self.limit = limit
        self.label = label

    def _execute(self, ctx):
        skip = self.skip
        remaining = self.limit
        for b in self.children[0].execute(ctx):
            if skip > 0:
                if skip >= len(b):
                    skip -= len(b)
                    continue
                b = b[skip:]
                skip = 0
            if remaining is not None:
                if remaining <= 0:
                    return
                b = b[:remaining]
                remaining -= len(b)
            if b:
                yield b


# ---------------------------------------------------------------------------
# vectorized column cache
# ---------------------------------------------------------------------------

_COL_MISS = object()

# vector functions with a (field, query-constant) shape that vectorize to
# one numpy call per batch; math mirrors fnc/vector_fns.py (f64)
_VEC_FNS = {
    "vector::similarity::cosine": "cos_sim",
    "vector::distance::euclidean": "eucl",
    "vector::distance::manhattan": "manh",
    "vector::dot": "dot",
}


class ColumnCache:
    """Per-query cache of vectorized expression columns.

    For recognized exprs (vector fn over a plain field + query-constant
    vector), `prime(batch)` computes the whole batch in ONE numpy call;
    `get_row` serves individual rows (sort keys, projection) from the
    cached column. Rows whose field is missing/ragged fall back to the
    row-at-a-time evaluator — semantics are identical, only the schedule
    changes (SURVEY.md §7: batched operator DAG from day one)."""

    MISS = _COL_MISS

    def __init__(self):
        self.specs = {}  # id(expr) -> (kind, field_parts, qvec, expr)
        self.vspecs = {}  # id(expr) -> (vops node, expr) scalar kernels
        # computed values live ON each Source (src._cols[id(expr)]): their
        # lifetime is the row's lifetime — a persistent {id(src): value}
        # map would serve stale values when CPython recycles a freed
        # Source's address for a later batch's row

    def register(self, expr, ctx):
        from surrealdb_tpu.expr.ast import Binary, FunctionCall, Idiom, \
            Param, PField
        from surrealdb_tpu.exec.eval import evaluate

        if id(expr) in self.specs or id(expr) in self.vspecs:
            return True
        if not isinstance(expr, FunctionCall):
            # scalar projection kernels: arithmetic / comparison / IN
            # trees whose VALUE (not just truthiness) is exact — one
            # vops call per batch serves projections and sort keys
            # (logic ops return operand values, so roots stay scalar)
            from surrealdb_tpu.exec import vops

            if isinstance(expr, Binary) and (
                expr.op in vops._CMP_OPS or expr.op in vops._ARITH_OPS
                or expr.op in ("∈", "∉")
            ):
                node = vops.compile_expr(expr, ctx)
                if node is not None and not isinstance(node, vops._Field):
                    self.vspecs[id(expr)] = (node, expr)
                    return True
            return False
        kind = _VEC_FNS.get(expr.name.lower())
        if kind is None or len(expr.args) != 2:
            return False
        fe, qe = expr.args
        if not (isinstance(fe, Idiom)
                and all(isinstance(p, PField) for p in fe.parts)):
            return False
        # the second arg must be query-constant (param / literal): evaluate
        # once up front
        if not isinstance(qe, (Param, list)):
            from surrealdb_tpu.expr.ast import Literal
            if not isinstance(qe, Literal):
                return False
        try:
            qv = evaluate(qe, ctx)
        except SdbError:
            return False
        if not (isinstance(qv, list) and qv
                and all(isinstance(x, (int, float)) for x in qv)):
            return False
        self.specs[id(expr)] = (
            kind, [p.name for p in fe.parts], np.asarray(qv, np.float64),
            expr,
        )
        return True

    def prime(self, batch, ctx):
        if self.vspecs:
            from surrealdb_tpu.exec import vops
            from surrealdb_tpu.exec.batch import RANK_EXOTIC, BatchCols

            for sid, (node, _expr) in self.vspecs.items():
                todo = [
                    src for src in batch
                    if getattr(src, "_cols", None) is None
                    or sid not in src._cols
                ]
                if not todo:
                    continue
                col = node.eval(BatchCols(todo), ctx)
                if col is None:
                    continue  # runtime bail: rows evaluate row-wise
                for i, src in enumerate(todo):
                    if col.rank[i] == RANK_EXOTIC:
                        continue  # scalar fallback (exact error/value)
                    cols = getattr(src, "_cols", None)
                    if cols is None:
                        cols = src._cols = {}
                    cols[sid] = vops.col_value_at(col, i)
        if not self.specs:
            return
        for sid, (kind, parts, qv, expr) in self.specs.items():
            idxs = []
            mats = []
            dim = qv.shape[0]
            for src in batch:
                cols = getattr(src, "_cols", None)
                if cols is not None and sid in cols:
                    continue
                doc = src.doc if src.rid is not None else src.value
                v = doc
                for p in parts:
                    v = v.get(p) if isinstance(v, dict) else None
                if isinstance(v, list) and len(v) == dim:
                    # numeric-dtype check via numpy (int/float kinds only;
                    # bools/objects reject) — far cheaper than a
                    # per-element isinstance loop
                    try:
                        arr = np.asarray(v)
                    except (TypeError, ValueError):
                        continue
                    if arr.dtype.kind in ("i", "f"):
                        idxs.append(src)
                        mats.append(arr.astype(np.float64, copy=False))
                # else: row falls back to evaluate() (exact same errors)
            if not mats:
                continue
            m = np.asarray(mats, np.float64)
            if kind == "cos_sim":
                dots = m @ qv
                denom = np.linalg.norm(m, axis=1) * np.linalg.norm(qv)
                with np.errstate(divide="ignore", invalid="ignore"):
                    vals = dots / denom
            elif kind == "eucl":
                vals = np.linalg.norm(m - qv[None, :], axis=1)
            elif kind == "manh":
                vals = np.abs(m - qv[None, :]).sum(axis=1)
            else:  # dot
                vals = m @ qv
            for src, val in zip(idxs, vals):
                cols = getattr(src, "_cols", None)
                if cols is None:
                    cols = src._cols = {}
                cols[sid] = float(val)

    def get_row(self, expr, src):
        cols = getattr(src, "_cols", None)
        if cols is None:
            return _COL_MISS
        return cols.get(id(expr), _COL_MISS)


# ---------------------------------------------------------------------------
# projection
# ---------------------------------------------------------------------------


class ProjectOp(Operator):
    """SelectProject / ProjectValue — row projection with the vectorized
    column cache consulted for recognized exprs."""

    def __init__(self, child, stmt, cols, label, compute_label=None):
        super().__init__(child)
        self.stmt = stmt
        self.cols = cols
        self.label = label
        self.compute_label = compute_label
        self.compute_metrics = OpMetrics()

    def enable_metrics(self):
        super().enable_metrics()
        self.compute_metrics.enabled = True

    def _execute(self, ctx):
        from surrealdb_tpu.exec.statements import _project

        n = self.stmt
        for b in self.children[0].execute(ctx):
            self.cols.prime(b, ctx)
            out = []
            for src in b:
                ctx._stream_cols = (self.cols, src)
                try:
                    out.append(_project(src, n, ctx))
                finally:
                    ctx._stream_cols = None
            if self.compute_label is not None:
                self.compute_metrics.rows += len(out)
                self.compute_metrics.batches += 1
            yield out

    def lines(self, depth=0):
        out = [(depth, self.label, self.metrics)]
        d = depth + 1
        if self.compute_label is not None:
            out.append((d, self.compute_label, self.compute_metrics))
            d += 1
        # children render under the deepest mid line (the plan tree is a
        # straight spine of root + mid lines)
        for c in self.children:
            out.extend(c.lines(d))
        return out


class AggregateOp(Operator):
    """GROUP BY / GROUP ALL over the scanned rows (reference
    exec/operators/aggregate.rs). A barrier by nature: drains the child,
    groups via the shared grouping engine, then emits the final grouped
    rows (ORDER/START/LIMIT apply to the grouped output)."""

    def __init__(self, child, stmt, aliases, label):
        super().__init__(child)
        self.stmt = stmt
        self.aliases = aliases
        self.label = label

    def _execute(self, ctx):
        from surrealdb_tpu.exec import vops
        from surrealdb_tpu.exec.eval import evaluate
        from surrealdb_tpu.exec.statements import (
            _apply_group, _apply_order, _stmt_rng,
        )

        n = self.stmt
        out = None
        scan = self.children[0]
        if (
            not self.metrics.enabled
            and isinstance(scan, TableScanOp)
            and scan.pushed_limit is None
            and not scan.pushed_offset
            and scan.direction == "Forward"
        ):
            # whole-table tier: filter + group + aggregate straight off
            # the version-keyed column store — no Source rows at all.
            # (EXPLAIN ANALYZE keeps the streaming tier so per-operator
            # row counts stay real.)
            out = vops.columnar_group_select(n, scan.tb, ctx,
                                             self.aliases)
        if out is None:
            rows = []
            for b in scan.execute(ctx):
                ctx.check_deadline()
                rows.extend(b)
            self.metrics.vrows += len(rows)
            out = vops.group_sources(rows, n, ctx, self.aliases)
            if out is None:
                self.metrics.vrows = 0
                self.metrics.frows += len(rows)
                empty_row = n.cond is None or (
                    getattr(ctx.session, "planner_strategy", None)
                    == "all-ro"
                )
                out = _apply_group(rows, n, ctx, self.aliases, empty_row)
        from surrealdb_tpu.exec.statements import _eval_limits

        # LIMIT/START evaluate ONCE: the heap bound and the slice must
        # see the same ints (volatile LIMIT expressions)
        lok, keep, lim, off = _eval_limits(n, ctx)
        if n.order == "rand":
            _stmt_rng(ctx).shuffle(out)
        elif n.order:
            out = _apply_order(out, n.order, ctx, keep=keep)
        if n.start is not None:
            out = out[off if lok else int(evaluate(n.start, ctx)):]
        if n.limit is not None:
            out = out[:lim if lok else int(evaluate(n.limit, ctx))]
        for i in range(0, len(out), BATCH_SIZE):
            yield out[i:i + BATCH_SIZE]
        if not out:
            yield []


# ---------------------------------------------------------------------------
# plan building / routing
# ---------------------------------------------------------------------------


def _inline_params(e, ctx):
    """Deep-copy an expression with $params replaced by their bound values
    — the reference's streaming explain renders physical exprs, which hold
    the evaluated constants, not the param names."""
    import dataclasses

    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.expr.ast import Literal, Param

    if isinstance(e, Param):
        try:
            return Literal(evaluate(e, ctx))
        except SdbError:
            return e
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            nv = _inline_params(v, ctx)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e
    if isinstance(e, list):
        out = [_inline_params(x, ctx) for x in e]
        return out if any(a is not b for a, b in zip(out, e)) else e
    if isinstance(e, tuple):
        out = tuple(_inline_params(x, ctx) for x in e)
        return out if any(a is not b for a, b in zip(out, e)) else e
    return e


def build_select_plan(n, ctx):
    """Build the streaming operator tree for an eligible SELECT; returns
    None when the statement needs the legacy engine (index access paths,
    grouping, permissions, multi-source, graph/recursion projections —
    the reference's PlannerUnsupported fallback, exec/planner.rs:309)."""
    from surrealdb_tpu.exec.statements import (
        _expand_field_projections, _target_value, expr_name,
    )
    from surrealdb_tpu.exec.render_def import _expr_sql
    from surrealdb_tpu.expr.ast import FunctionCall, Idiom, PRecurse
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.idx.planner import _find_knn, _find_matches, plan_scan

    if getattr(ctx.session, "planner_strategy", None) == "compute-only":
        return None
    if (
        n.version is not None or ctx.version is not None
        or n.split or n.fetch or n.omit or n.only
        or n.order == "rand" or len(n.what) != 1
        or not ctx.session.is_owner or ctx.perms_enabled
    ):
        return None
    if n.group is not None and any(e == "*" for e, _a in n.exprs):
        return None  # `*` in a grouped selection errors on the legacy path
    try:
        v = _target_value(n.what[0], ctx)
    except SdbError:
        return None
    if not isinstance(v, Table):
        return None
    tb = v.name
    if n.cond is not None:
        if _find_knn(n.cond) is not None or _find_matches(n.cond):
            return None
        if plan_scan(tb, n.cond, ctx, n) is not None:
            return None  # an index access path applies — legacy engine
    n = _expand_field_projections(n, ctx)
    # recursion idioms need the legacy Recurse machinery's explain shape;
    # execution-wise evaluate() handles them, so only exclude from plans
    # when they appear (keeps analyze labels honest)
    for e, _a in n.exprs:
        if isinstance(e, Idiom) and any(
            isinstance(p, PRecurse) for p in e.parts
        ):
            return None
    if isinstance(n.value, Idiom) and any(
        isinstance(p, PRecurse) for p in n.value.parts
    ):
        return None

    cols = ColumnCache()
    for e, _a in n.exprs:
        if e != "*":
            cols.register(e, ctx)
    if n.value is not None:
        cols.register(n.value, ctx)

    aliases = {}
    for expr, alias in n.exprs:
        if expr != "*":
            aliases[alias or expr_name(expr)] = expr
    if n.value is not None and getattr(n, "value_alias", None):
        aliases[n.value_alias] = n.value

    if n.group is not None:
        if not n.group:
            # GROUP ALL rides the legacy count/aggregate fast paths
            # (key-only count scans beat draining every row here)
            return None
        extra = ""
        if n.cond is not None:
            from surrealdb_tpu.exec.statements import _elide_count_args

            extra += (
                ", predicate: "
                + _expr_sql(_elide_count_args(_inline_params(n.cond, ctx)))
            )
        scan = TableScanOp(
            tb, n.cond, None, None, "Forward",
            f"TableScan [ctx: Db] [table: {tb}, direction: Forward{extra}]",
            cols,
        )
        by = ", ".join(expr_name(g) for g in n.group) or ", ".join(
            (a or expr_name(e)) for e, a in n.exprs if e != "*"
        )
        return AggregateOp(
            scan, n, aliases, f"Aggregate [ctx: Db] [by: {by}]"
        )

    order = list(n.order) if n.order and n.order != "rand" else []
    # ORDER BY id over a plain scan streams in key order already (the
    # order-preserving key codec IS id order): elide the sort — Backward
    # scan for DESC. COLLATE/NUMERIC id sorts — and projections that
    # alias some other expression AS id — keep the real sort.
    scan_dir = "Forward"
    if (
        order
        and len(order) == 1
        and expr_name(order[0][0]) == "id"
        and "id" not in aliases
        and order[0][2] is None
        and not order[0][3]
    ):
        if order[0][1] != "desc":
            order = []
        elif n.cond is None:
            scan_dir = "Backward"
            order = []

    lim = int(evaluate(n.limit, ctx)) if n.limit is not None else None
    off = int(evaluate(n.start, ctx)) if n.start is not None else 0
    if (lim is not None and lim < 0) or off < 0:
        # Legacy applies Python slice semantics to negative START/LIMIT;
        # keep one behavior by routing those (rare) shapes to legacy.
        return None

    pushed_limit = pushed_offset = None
    extra = ""
    if n.cond is not None:
        from surrealdb_tpu.exec.statements import _elide_count_args

        extra += f", predicate: {_expr_sql(_elide_count_args(_inline_params(n.cond, ctx)))}"
    if not order and (lim is not None or off):
        pushed_limit = lim
        if lim is not None:
            extra += f", limit: {lim}"
        if off:
            pushed_offset = off
            extra += f", offset: {off}"
    # columnar vector top-k: ORDER BY <vec-fn alias> LIMIT k over a bare
    # scan scores the whole table from the column store in one shot
    node = None
    if (
        n.cond is None
        and lim is not None
        and len(order) == 1
        and not order[0][2]  # no COLLATE
        and not order[0][3]  # no NUMERIC
    ):
        from surrealdb_tpu.exec.statements import _resolve_alias

        oexpr = _resolve_alias(order[0][0], aliases)
        spec = cols.specs.get(id(oexpr))
        if spec is not None and len(spec[1]) == 1:
            from surrealdb_tpu.col import get_vector_column

            col = get_vector_column(ctx, tb, spec[1][0], spec[2].shape[0])
            if col is not None and not col.bad_ids:
                desc = order[0][1] == "desc"
                node = VecTopKScanOp(
                    tb, spec, lim + off, off, desc,
                    f"VecTopKScan [ctx: Db] [table: {tb}, "
                    f"expr: {spec[0]}, limit: {lim + off}]",
                )
                order = []

    if node is None:
        scan_label = (
            f"TableScan [ctx: Db] [table: {tb}, direction: "
            f"{scan_dir}{extra}]"
        )
        node = TableScanOp(tb, n.cond, pushed_limit, pushed_offset,
                           scan_dir, scan_label, cols)

    if order:
        keys = ", ".join(
            f"{expr_name(e)} {'DESC' if d == 'desc' else 'ASC'}"
            for e, d, _c, _n2 in order
        )
        if lim is not None:
            limattr = (
                f"limit: {lim}, offset: {off}" if off else f"limit: {lim}"
            )
            node = SortTopKOp(
                node, order, aliases, cols, lim + off, off,
                f"SortTopKByKey [ctx: Db] [sort_keys: {keys}, "
                f"limit: {lim + off}]",
                f"Limit [ctx: Db] [{limattr}]",
            )
        else:
            node = SortOp(
                node, order, aliases, cols,
                f"SortByKey [ctx: Db] [sort_keys: {keys}]",
            )
            if off:
                node = LimitOp(
                    node, off, None, f"Start [ctx: Db] [offset: {off}]"
                )
    if n.value is not None:
        label = f"ProjectValue [ctx: Db] [expr: {_expr_sql(n.value)}]"
        compute_label = None
    else:
        projs = ", ".join(
            "*" if e == "*" else (a or expr_name(e)) for e, a in n.exprs
        )
        label = f"SelectProject [ctx: Db] [projections: {projs}]"
        computed = [
            f"{a or expr_name(e)} = " + (
                f"{e.name}(...)" if isinstance(e, FunctionCall)
                else _expr_sql(e)
            )
            for e, a in n.exprs
            if e != "*" and not isinstance(e, Idiom)
        ]
        compute_label = (
            f"Compute [ctx: Db] [fields: {', '.join(computed)}]"
            if computed else None
        )
    return ProjectOp(node, n, cols, label, compute_label)


def try_stream_select(n, ctx):
    """Execute via the streaming engine; _UNSUPPORTED → legacy fallback."""
    plan = build_select_plan(n, ctx)
    if plan is None:
        return _UNSUPPORTED
    out = []
    try:
        for b in plan.execute(ctx):
            out.extend(b)
    except _FallbackToLegacy:
        # a columnar fast path couldn't serve this txn after all (raised
        # before any batch is emitted)
        return _UNSUPPORTED
    return out


def try_stream_analyze(n, ctx):
    """EXPLAIN ANALYZE through the real operator tree: executes, drains,
    and renders per-operator measured rows/batches/elapsed (reference
    exec/operators/explain.rs AnalyzePlan + metrics.rs). Returns None when
    the statement isn't stream-eligible (cosmetic renderer handles it)."""
    import copy as _copy

    n2 = _copy.copy(n)
    n2.explain = None
    plan = build_select_plan(n2, ctx)
    if plan is None:
        return None
    plan.enable_metrics()
    total = 0
    for b in plan.execute(ctx):
        total += len(b)
    lines = []
    for depth, label, m in plan.lines():
        extra = ""
        if m.vrows or m.frows:
            # columnar accounting: rows the vectorized kernels served
            # vs rows that took the scalar fallback (exec/vops.py)
            extra = f"vectorized: {m.vrows}, fallback: {m.frows}, "
        lines.append(
            "    " * depth + label
            + f" {{rows: {m.rows}, batches: {m.batches}, "
            + extra
            + f"elapsed: {_fmt_elapsed(m.ns)}}}"
        )
    return "\n".join(lines) + f"\n\nTotal rows: {total}"
