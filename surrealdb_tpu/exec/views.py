"""Incremental materialized-view maintenance.

Mirrors the reference's aggregation framework (catalog/aggregation.rs:
Aggregation/AggregationStat/add_to_aggregation_stats/create_field_document)
and per-document view processing (doc/table.rs process_view*): every source
write updates the view's per-group aggregation stats in place — no source
rescan — so views stay correct even over DROP tables, cascade to
views-on-views, and fire events on the view rows they write.

Unsupported shapes (accumulating aggregates like array::group/math::median,
VALUE selectors) raise Unsupported and fall back to the scan-based rebuild
in exec/document.py.
"""

from __future__ import annotations

import math as _math
from dataclasses import dataclass, field, replace

from surrealdb_tpu import key as K
from surrealdb_tpu.err import SdbError
from surrealdb_tpu.expr.ast import (
    Binary,
    FunctionCall,
    Idiom,
    PField,
    Prefix,
)
from surrealdb_tpu.val import NONE, Datetime, RecordId, copy_value, is_truthy, render

# aggregate function -> (stat kind, expected arg type label)
_AGG_KINDS = {
    "count": ("countv", "number"),
    "math::max": ("nmax", "number"),
    "math::min": ("nmin", "number"),
    "math::sum": ("sum", "number"),
    "math::mean": ("mean", "number"),
    "math::stddev": ("stddev", "number"),
    "math::variance": ("variance", "number"),
    "time::max": ("tmax", "datetime"),
    "time::min": ("tmin", "datetime"),
}

_FN_NAME = {
    "countv": "count", "nmax": "math::max", "nmin": "math::min",
    "sum": "math::sum", "mean": "math::mean", "stddev": "math::stddev",
    "variance": "math::variance", "tmax": "time::max", "tmin": "time::min",
}


class Unsupported(Exception):
    """View shape the incremental engine can't maintain — caller falls
    back to the scan-based rebuild."""


@dataclass
class ViewAnalysis:
    kind: str  # "aggregate" | "plain"
    cond: object = None
    group_exprs: list = field(default_factory=list)
    aggregations: list = field(default_factory=list)  # (stat kind, argidx)
    arg_exprs: list = field(default_factory=list)
    fields: list = field(default_factory=list)  # (name, rewritten expr)


def _is_field_idiom(e, name=None):
    return (
        isinstance(e, Idiom)
        and len(e.parts) == 1
        and isinstance(e.parts[0], PField)
        and (name is None or e.parts[0].name == name)
    )


def analyze_view(sel) -> ViewAnalysis:
    """Reference AggregationAnalysis::analyze_fields_groups (materialized)."""
    group = getattr(sel, "group", None)
    cond = getattr(sel, "cond", None)
    exprs = getattr(sel, "exprs", None)
    if getattr(sel, "value", None) is not None or exprs is None:
        raise Unsupported("VALUE selectors are not supported on views")
    if getattr(sel, "split", None):
        raise Unsupported("SPLIT on a view")
    if group is None:
        return ViewAnalysis(kind="plain", cond=cond)

    a = ViewAnalysis(kind="aggregate", cond=cond)
    a.group_exprs = list(group)
    arg_map: dict = {}  # rendered arg expr -> index

    def arg_index(expr):
        key = repr(expr)
        idx = arg_map.get(key)
        if idx is None:
            idx = len(a.arg_exprs)
            arg_map[key] = idx
            a.arg_exprs.append(expr)
        return idx

    def rewrite(e, in_agg_arg=False):
        if isinstance(e, FunctionCall):
            fname = e.name.lower()
            if fname == "count" and not e.args:
                a.aggregations.append(("count", None))
                return Idiom([PField(f"_a{len(a.aggregations) - 1}")])
            if fname in _AGG_KINDS:
                if in_agg_arg:
                    raise Unsupported("nested aggregate")
                if len(e.args) != 1:
                    raise Unsupported("aggregate arity")
                kindname, _ = _AGG_KINDS[fname]
                idx = arg_index(e.args[0])
                a.aggregations.append((kindname, idx))
                return Idiom([PField(f"_a{len(a.aggregations) - 1}")])
            from surrealdb_tpu.exec.statements import _is_aggregate

            if any(_is_aggregate(x) for x in e.args):
                new_args = [rewrite(x, in_agg_arg) for x in e.args]
                return replace(e, args=new_args)
            return e
        if isinstance(e, Idiom) and not in_agg_arg:
            for gi, g in enumerate(a.group_exprs):
                if e == g:
                    return Idiom([PField(f"_g{gi}")])
            return e
        if isinstance(e, Binary):
            return replace(
                e, lhs=rewrite(e.lhs, in_agg_arg), rhs=rewrite(e.rhs, in_agg_arg)
            )
        if isinstance(e, Prefix):
            return replace(e, expr=rewrite(e.expr, in_agg_arg))
        from surrealdb_tpu.exec.statements import _is_aggregate

        if _is_aggregate(e):
            raise Unsupported("aggregate in unsupported expression shape")
        return e

    from surrealdb_tpu.exec.statements import _is_aggregate, expr_name

    # aliases used in GROUP BY refer to their expressions
    for gi, g in enumerate(a.group_exprs):
        if _is_field_idiom(g):
            gname = g.parts[0].name
            for expr, alias in exprs:
                if expr == "*":
                    continue
                if alias == gname:
                    a.group_exprs[gi] = expr
                    break

    for expr, alias in exprs:
        if expr == "*":
            raise Unsupported("* selector on an aggregate view")
        name = alias or expr_name(expr)
        # group expression (by alias or directly)?
        matched = False
        for gi, g in enumerate(a.group_exprs):
            if expr == g or (alias and _is_field_idiom(g, alias)):
                a.fields.append((name, Idiom([PField(f"_g{gi}")])))
                matched = True
                break
        if matched:
            continue
        if _is_aggregate(expr):
            a.fields.append((name, rewrite(expr)))
        else:
            # non-aggregate, non-group selectors would accumulate values
            # (Aggregation::Accumulate) — unsupported on views
            raise Unsupported(f"accumulating selector {name}")

    # ensure a per-group record count exists (drives row deletion)
    if not any(k in ("count", "countv", "mean", "stddev", "variance")
               for k, _ in a.aggregations):
        a.aggregations.append(("count", None))
    return a


# ---------------------------------------------------------------------------
# aggregation stats
# ---------------------------------------------------------------------------


def new_stats(aggregations) -> list:
    out = []
    for kind, arg in aggregations:
        if kind == "count":
            out.append({"k": "count", "count": 0})
        elif kind == "countv":
            out.append({"k": "countv", "arg": arg, "count": 0})
        elif kind == "nmax":
            out.append({"k": "nmax", "arg": arg, "max": float("-inf")})
        elif kind == "nmin":
            out.append({"k": "nmin", "arg": arg, "min": float("inf")})
        elif kind == "sum":
            out.append({"k": "sum", "arg": arg, "sum": 0.0})
        elif kind == "mean":
            out.append({"k": "mean", "arg": arg, "sum": 0.0, "count": 0})
        elif kind in ("stddev", "variance"):
            out.append({"k": kind, "arg": arg, "sum": 0.0, "sumsq": 0.0,
                        "count": 0})
        elif kind == "tmax":
            out.append({"k": "tmax", "arg": arg, "max": None})
        elif kind == "tmin":
            out.append({"k": "tmin", "arg": arg, "min": None})
    return out


def _num(v, kind):
    from decimal import Decimal

    if isinstance(v, bool) or not isinstance(v, (int, float, Decimal)):
        raise SdbError(
            f"Incorrect arguments for function {_FN_NAME[kind]}(). "
            f"Argument 1 was the wrong type. Expected `number` but found "
            f"`{render(v)}`"
        )
    return v


def _dt(v, kind):
    if not isinstance(v, Datetime):
        raise SdbError(
            f"Incorrect arguments for function {_FN_NAME[kind]}(). "
            f"Argument 1 was the wrong type. Expected `datetime` but found "
            f"`{render(v)}`"
        )
    return v


def stats_add(stats, args):
    """reference add_to_aggregation_stats."""
    from surrealdb_tpu.exec.operators import add, mul

    for s in stats:
        k = s["k"]
        if k == "count":
            s["count"] += 1
        elif k == "countv":
            if is_truthy(args[s["arg"]]):
                s["count"] += 1
        elif k == "nmax":
            n = _num(args[s["arg"]], k)
            if s["max"] < n:
                s["max"] = n
        elif k == "nmin":
            n = _num(args[s["arg"]], k)
            if s["min"] > n:
                s["min"] = n
        elif k == "sum":
            s["sum"] = add(s["sum"], _num(args[s["arg"]], k))
        elif k == "mean":
            s["sum"] = add(s["sum"], _num(args[s["arg"]], k))
            s["count"] += 1
        elif k in ("stddev", "variance"):
            n = _num(args[s["arg"]], k)
            s["sum"] = add(s["sum"], n)
            s["sumsq"] = add(s["sumsq"], mul(n, n))
            s["count"] += 1
        elif k == "tmax":
            d = _dt(args[s["arg"]], k)
            if s["max"] is None or s["max"] < d:
                s["max"] = d
        elif k == "tmin":
            d = _dt(args[s["arg"]], k)
            if s["min"] is None or s["min"] > d:
                s["min"] = d


def stats_remove(stats, args) -> list:
    """Downdate on record removal; returns stat indexes needing a
    recalculation (min/max losing their extremum)."""
    from surrealdb_tpu.exec.operators import mul, sub

    recalc = []
    for i, s in enumerate(stats):
        k = s["k"]
        if k == "count":
            s["count"] -= 1
        elif k == "countv":
            if is_truthy(args[s["arg"]]):
                s["count"] -= 1
        elif k == "nmax":
            if args[s["arg"]] == s["max"]:
                recalc.append(i)
        elif k == "nmin":
            if args[s["arg"]] == s["min"]:
                recalc.append(i)
        elif k == "sum":
            s["sum"] = sub(s["sum"], _num(args[s["arg"]], k))
        elif k == "mean":
            s["sum"] = sub(s["sum"], _num(args[s["arg"]], k))
            s["count"] -= 1
        elif k in ("stddev", "variance"):
            n = _num(args[s["arg"]], k)
            s["sum"] = sub(s["sum"], n)
            s["sumsq"] = sub(s["sumsq"], mul(n, n))
            s["count"] -= 1
        elif k == "tmax":
            if args[s["arg"]] == s["max"]:
                recalc.append(i)
        elif k == "tmin":
            if args[s["arg"]] == s["min"]:
                recalc.append(i)
    return recalc


def stats_update(stats, before_args, after_args) -> list:
    """Same-group update; returns stat indexes needing recalculation."""
    from surrealdb_tpu.exec.operators import add, mul, sub

    recalc = []
    for i, s in enumerate(stats):
        k = s["k"]
        if k == "count":
            pass
        elif k == "countv":
            if is_truthy(before_args[s["arg"]]):
                s["count"] -= 1
            if is_truthy(after_args[s["arg"]]):
                s["count"] += 1
        elif k == "nmax":
            after = _num(after_args[s["arg"]], k)
            before = before_args[s["arg"]]
            if after >= s["max"]:
                s["max"] = after
            elif before == s["max"]:
                recalc.append(i)
        elif k == "nmin":
            after = _num(after_args[s["arg"]], k)
            before = before_args[s["arg"]]
            if after <= s["min"]:
                s["min"] = after
            elif before == s["min"]:
                recalc.append(i)
        elif k == "sum":
            s["sum"] = add(sub(s["sum"], _num(before_args[s["arg"]], k)),
                           _num(after_args[s["arg"]], k))
        elif k == "mean":
            s["sum"] = add(sub(s["sum"], _num(before_args[s["arg"]], k)),
                           _num(after_args[s["arg"]], k))
        elif k in ("stddev", "variance"):
            b = _num(before_args[s["arg"]], k)
            n = _num(after_args[s["arg"]], k)
            s["sum"] = add(sub(s["sum"], b), n)
            s["sumsq"] = add(sub(s["sumsq"], mul(b, b)), mul(n, n))
        elif k == "tmax":
            after = _dt(after_args[s["arg"]], k)
            before = before_args[s["arg"]]
            if s["max"] is None or after >= s["max"]:
                s["max"] = after
            elif before == s["max"]:
                recalc.append(i)
        elif k == "tmin":
            after = _dt(after_args[s["arg"]], k)
            before = before_args[s["arg"]]
            if s["min"] is None or after <= s["min"]:
                s["min"] = after
            elif before == s["min"]:
                recalc.append(i)
    return recalc


def stats_count(stats):
    for s in stats:
        if s["k"] in ("count", "countv", "mean", "stddev", "variance"):
            return s["count"]
    return None


def field_document(group_vals, stats) -> dict:
    """reference create_field_document: {_aN: value, _gN: group value}."""
    from surrealdb_tpu.exec.operators import div, float_div, mul, sub

    doc = {}
    for i, s in enumerate(stats):
        k = s["k"]
        if k in ("count", "countv"):
            v = s["count"]
        elif k == "nmax":
            v = s["max"]
        elif k == "nmin":
            v = s["min"]
        elif k == "sum":
            v = s["sum"]
        elif k == "mean":
            v = (float_div(s["sum"], s["count"]) if s["count"]
                 else float("nan"))
        elif k in ("stddev", "variance"):
            if s["count"] <= 1:
                v = 0.0
            else:
                mean = div(s["sum"], s["count"])
                var = div(sub(s["sumsq"], mul(s["sum"], mean)),
                          s["count"] - 1)
                if var == 0.0:
                    var = 0.0
                v = var if k == "variance" else (
                    _math.sqrt(float(var)) if float(var) > 0 else 0.0
                )
        elif k in ("tmax",):
            v = s["max"] if s["max"] is not None else NONE
        else:
            v = s["min"] if s["min"] is not None else NONE
        doc[f"_a{i}"] = v
    for gi, gv in enumerate(group_vals):
        doc[f"_g{gi}"] = gv
    return doc


# ---------------------------------------------------------------------------
# per-document view processing (reference doc/table.rs)
# ---------------------------------------------------------------------------


def _eval(expr, doc, ctx):
    from surrealdb_tpu.exec.eval import evaluate

    c = ctx.with_doc(doc, None)
    return evaluate(expr, c)


def _compute_args(analysis, doc, ctx):
    return [_eval(e, doc, ctx) for e in analysis.arg_exprs]


def _compute_group(analysis, doc, ctx):
    return [_eval(g, doc, ctx) for g in analysis.group_exprs]


def _cond_ok(analysis, doc, ctx) -> bool:
    if analysis.cond is None:
        return True
    return is_truthy(_eval(analysis.cond, doc, ctx))


def process_view(view_tdef, analysis, rid, before, after, action, ctx):
    """Dispatch one source-document mutation into the view (reference
    doc/table.rs process_view / process_aggregate_view)."""
    if analysis.kind == "plain":
        _process_plain(view_tdef, analysis, rid, before, after, action, ctx)
        return
    if action == "CREATE":
        if not _cond_ok(analysis, after, ctx):
            return
        group = _compute_group(analysis, after, ctx)
        _view_create(view_tdef, analysis, group, after, ctx)
    elif action == "DELETE":
        if not _cond_ok(analysis, before, ctx):
            return
        group = _compute_group(analysis, before, ctx)
        _view_delete(view_tdef, analysis, group, before, ctx)
    else:  # UPDATE
        gb = (_compute_group(analysis, before, ctx)
              if _cond_ok(analysis, before, ctx) else None)
        ga = (_compute_group(analysis, after, ctx)
              if _cond_ok(analysis, after, ctx) else None)
        if gb is None and ga is None:
            return
        if gb is not None and ga is not None:
            from surrealdb_tpu.val import value_eq

            same = len(gb) == len(ga) and all(
                value_eq(x, y) for x, y in zip(gb, ga)
            )
            if same:
                _view_update(view_tdef, analysis, gb, before, after, ctx)
            else:
                _view_delete(view_tdef, analysis, gb, before, ctx)
                _view_create(view_tdef, analysis, ga, after, ctx)
        elif gb is not None:
            _view_delete(view_tdef, analysis, gb, before, ctx)
        else:
            _view_create(view_tdef, analysis, ga, after, ctx)


def _process_plain(view_tdef, analysis, rid, before, after, action, ctx):
    """Non-aggregated materialized view: one view row per source row,
    same record key (reference ViewDefinition::Materialized)."""
    from surrealdb_tpu.exec.statements import expr_name

    ns, db = ctx.need_ns_db()
    vrid = RecordId(view_tdef.name, rid.id)
    if analysis.cond is not None:
        doc = after if action != "DELETE" else before
        store = action != "DELETE" and is_truthy(_eval(analysis.cond, after, ctx))
    else:
        store = action != "DELETE"
    vkey = K.record(ns, db, view_tdef.name, rid.id)
    old = ctx.txn.get(vkey)
    from surrealdb_tpu.kvs.api import deserialize, serialize

    old_doc = deserialize(old) if old is not None else NONE
    if store:
        row = {}
        from surrealdb_tpu.exec.eval import evaluate

        sel = view_tdef.view
        c = ctx.with_doc(after, rid)
        for expr, alias in sel.exprs:
            if expr == "*":
                if isinstance(after, dict):
                    row.update(copy_value(after))
                continue
            row[alias or expr_name(expr)] = evaluate(expr, c)
        row["id"] = vrid
        ctx.txn.set(vkey, serialize(row))
        ctx.record_cache.pop((view_tdef.name, K.enc_value(rid.id)), None)
        _fire_triggers(
            vrid, old_doc, row,
            "UPDATE" if old is not None else "CREATE", ctx,
        )
    elif old is not None:
        ctx.txn.delete(vkey)
        ctx.record_cache.pop((view_tdef.name, K.enc_value(rid.id)), None)
        _fire_triggers(vrid, old_doc, NONE, "DELETE", ctx)


def _row_keys(view_tdef, group, ctx):
    ns, db = ctx.need_ns_db()
    gid = list(group)
    kb = K.enc_value(gid)
    return (
        RecordId(view_tdef.name, gid),
        K.record(ns, db, view_tdef.name, gid),
        K.view_meta(ns, db, view_tdef.name, kb),
    )


def _write_view_row(view_tdef, analysis, group, stats, before_doc, action, ctx):
    """Materialize the row from stats + run triggers (reference
    run_triggers: index + cascading views + events)."""
    from surrealdb_tpu.exec.eval import evaluate
    from surrealdb_tpu.kvs.api import serialize

    vrid, vkey, mkey = _row_keys(view_tdef, group, ctx)
    fdoc = field_document(group, stats)
    row = {}
    c = ctx.with_doc(fdoc, vrid)
    for name, expr in analysis.fields:
        v = evaluate(expr, c)
        if v is not NONE:
            row[name] = v
    row["id"] = vrid
    ctx.txn.set(vkey, serialize(row))
    ctx.txn.set_val(mkey, stats)
    ctx.record_cache.pop((view_tdef.name, K.enc_value(vrid.id)), None)
    _fire_triggers(vrid, before_doc, row, action, ctx)


def _fire_triggers(vrid, before_doc, after_doc, action, ctx):
    """Index + cascade + events on a view-row write (reference
    doc/table.rs run_triggers)."""
    from surrealdb_tpu.exec.document import (
        index_update,
        run_events,
        update_views,
    )

    if ctx.depth > 24:
        raise SdbError("Max computation depth exceeded")
    c = ctx.child()
    index_update(vrid, before_doc, after_doc, c)
    update_views(vrid, before_doc, after_doc, action, c)
    # view-row events see the record DATA (no id field) — the reference's
    # run_triggers builds the cursor from Record.data, where the id lives
    # in the key, not the value
    def _strip(d):
        if isinstance(d, dict) and "id" in d:
            d = {k: v for k, v in d.items() if k != "id"}
        return d

    run_events(vrid, _strip(before_doc), _strip(after_doc), action, c)


def _get_row_state(view_tdef, analysis, group, ctx):
    from surrealdb_tpu.kvs.api import deserialize

    vrid, vkey, mkey = _row_keys(view_tdef, group, ctx)
    raw = ctx.txn.get(vkey)
    row = deserialize(raw) if raw is not None else None
    stats = ctx.txn.get_val(mkey)
    return vrid, row, stats


def _view_create(view_tdef, analysis, group, doc, ctx):
    vrid, row, stats = _get_row_state(view_tdef, analysis, group, ctx)
    action = "UPDATE" if row is not None else "CREATE"
    if stats is None:
        stats = new_stats(analysis.aggregations)
    args = _compute_args(analysis, doc, ctx)
    stats_add(stats, args)
    _write_view_row(view_tdef, analysis, group, stats,
                    row if row is not None else NONE, action, ctx)


def _view_delete(view_tdef, analysis, group, doc, ctx):
    vrid, row, stats = _get_row_state(view_tdef, analysis, group, ctx)
    if row is None or stats is None:
        return
    count = stats_count(stats)
    if count is not None and count <= 1:
        ns, db = ctx.need_ns_db()
        _vrid, vkey, mkey = _row_keys(view_tdef, group, ctx)
        ctx.txn.delete(vkey)
        ctx.txn.delete(mkey)
        ctx.record_cache.pop((view_tdef.name, K.enc_value(vrid.id)), None)
        _fire_triggers(vrid, row, NONE, "DELETE", ctx)
        return
    args = _compute_args(analysis, doc, ctx)
    recalc = stats_remove(stats, args)
    _recalculate(view_tdef, analysis, group, stats, recalc, ctx)
    _write_view_row(view_tdef, analysis, group, stats, row, "UPDATE", ctx)


def _view_update(view_tdef, analysis, group, before, after, ctx):
    vrid, row, stats = _get_row_state(view_tdef, analysis, group, ctx)
    if row is None or stats is None:
        # first sighting of this group (e.g. view defined before writes)
        _view_create(view_tdef, analysis, group, after, ctx)
        return
    bargs = _compute_args(analysis, before, ctx)
    aargs = _compute_args(analysis, after, ctx)
    recalc = stats_update(stats, bargs, aargs)
    _recalculate(view_tdef, analysis, group, stats, recalc, ctx)
    _write_view_row(view_tdef, analysis, group, stats, row, "UPDATE", ctx)


def _recalculate(view_tdef, analysis, group, stats, recalc, ctx):
    """Re-derive min/max stats by scanning the group's source rows
    (reference builds a SELECT over the source with the group condition)."""
    if not recalc:
        return
    from surrealdb_tpu.exec.document import view_source_tables
    from surrealdb_tpu.kvs.api import deserialize
    from surrealdb_tpu.val import value_eq

    ns, db = ctx.need_ns_db()
    values_per_stat: dict = {i: [] for i in recalc}
    for src in view_source_tables(view_tdef.view):
        beg, end = K.prefix_range(K.record_prefix(ns, db, src))
        for _k, raw in ctx.txn.scan(beg, end):
            doc = deserialize(raw)
            if not _cond_ok(analysis, doc, ctx):
                continue
            g = _compute_group(analysis, doc, ctx)
            if len(g) != len(group) or not all(
                value_eq(x, y) for x, y in zip(g, group)
            ):
                continue
            args = _compute_args(analysis, doc, ctx)
            for i in recalc:
                values_per_stat[i].append(args[stats[i]["arg"]])
    for i in recalc:
        vals = [v for v in values_per_stat[i] if v is not NONE]
        s = stats[i]
        if not vals:
            continue  # source unavailable (DROP) — keep the old extremum
        if s["k"] in ("nmax", "tmax"):
            s["max"] = max(vals)
        else:
            s["min"] = min(vals)
