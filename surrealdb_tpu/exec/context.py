"""Execution context (reference: core/src/ctx/ + dbs/options.rs).

A lightweight chain: each scope (statement, document, closure) gets a child
context sharing the datastore/transaction handles, with its own variable
bindings and current-document pointer.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from surrealdb_tpu.err import SdbError


class Ctx:
    __slots__ = (
        "ds", "session", "txn", "vars", "doc", "doc_id", "parent_doc",
        "executor", "ns", "db", "knn", "record_cache", "deadline",
        "timeout_dur", "write_version", "depth",
        "perms_enabled", "version", "_cond_consumed", "_cf_seq", "_in_perm_check",
        "_brute_knn_k", "_strict_readonly", "_stream_cols", "_no_link_fetch", "_script_depth",
        "cancel", "inflight",
    )

    def __init__(self, ds, session, txn, executor=None):
        self.ds = ds
        self.session = session
        self.txn = txn
        self.executor = executor
        self.vars: dict[str, Any] = {}
        self.doc = None  # current document value ($this)
        self.doc_id = None  # RecordId of current document
        self.parent_doc = None
        self.ns = session.ns
        self.db = session.db
        self.knn: Optional[dict] = None  # record-key -> distance (KnnContext)
        self.record_cache: dict = {}
        self.deadline: Optional[float] = None
        self.timeout_dur = None
        self.write_version = None  # CREATE/INSERT ... VERSION (epoch ns)
        self.depth = 0
        self.perms_enabled = False  # row-level permissions active
        self._in_perm_check = False  # evaluating a PERMISSIONS clause
        self.version = None  # VERSION clause timestamp
        self._cond_consumed = False  # planner handled the WHERE clause
        self._cf_seq = 0
        self._brute_knn_k = None  # brute KNN global k (multi-source trim)
        self._strict_readonly = False  # REPLACE: dropped readonly errors
        self._stream_cols = None  # (ColumnCache, src) — exec/stream.py
        # ORDER BY keys evaluate pre-FETCH with no record-link traversal
        # (reference: sort compares computed values without db access)
        self._no_link_fetch = False
        self._script_depth = 0  # nested script frames (budget: 15)
        # cooperative cancellation: a threading.Event set by KILL
        # <query-id>, client disconnect, or server drain; checked at
        # every check_deadline() site alongside the deadline itself
        self.cancel = None
        self.inflight = None  # the owning QueryHandle (inflight.py)

    def child(self) -> "Ctx":
        c = Ctx.__new__(Ctx)
        c.ds = self.ds
        c.session = self.session
        c.txn = self.txn
        c.executor = self.executor
        c.vars = dict(self.vars)
        c.doc = self.doc
        c.doc_id = self.doc_id
        c.parent_doc = self.parent_doc
        c.ns = self.ns
        c.db = self.db
        c.knn = self.knn
        c.record_cache = self.record_cache
        c.deadline = self.deadline
        c.timeout_dur = self.timeout_dur
        c.write_version = self.write_version
        c.depth = self.depth + 1
        c.perms_enabled = self.perms_enabled
        c.version = self.version
        c._cond_consumed = False
        c._cf_seq = 0
        c._brute_knn_k = self._brute_knn_k
        c._strict_readonly = self._strict_readonly
        c._in_perm_check = self._in_perm_check
        c._stream_cols = self._stream_cols
        c._no_link_fetch = self._no_link_fetch
        c._script_depth = self._script_depth
        c.cancel = self.cancel
        c.inflight = self.inflight
        from surrealdb_tpu import cnf

        if c.depth > cnf.MAX_COMPUTATION_DEPTH:
            raise SdbError("Max computation depth exceeded")
        return c

    def with_doc(self, doc, doc_id=None) -> "Ctx":
        c = self.child()
        # $parent = the enclosing context's (possibly pinned) $this —
        # fixed at the time the enclosing statement started, like $this
        pin = self.vars.get("this", self.doc)
        c.parent_doc = pin
        c.doc = doc
        c.doc_id = doc_id
        c.vars["parent"] = pin
        c.vars["this"] = doc
        return c

    def check_deadline(self):
        from surrealdb_tpu import cnf as _cnf

        if _cnf.MEMORY_THRESHOLD:
            from surrealdb_tpu.mem import check_threshold

            check_threshold()
        if self.cancel is not None and self.cancel.is_set():
            from surrealdb_tpu.err import QueryCancelled

            if self.inflight is not None:
                self.inflight.mark_cancelled()
            raise QueryCancelled("The query was cancelled")
        if self.deadline is not None and time.monotonic() > self.deadline:
            from surrealdb_tpu.err import QueryTimeout

            suffix = (
                f": {self.timeout_dur.render()}"
                if self.timeout_dur is not None else ""
            )
            if self.inflight is not None:
                self.inflight.mark_timed_out()
            raise QueryTimeout(
                "The query was not executed because it exceeded the "
                f"timeout{suffix}"
            )

    def need_ns_db(self):
        # empty-string names are legal (`USE NS ```) — only None is unset
        if self.ns is None or self.db is None:
            raise SdbError(
                "Specify a namespace and database to use"
            )
        return self.ns, self.db
