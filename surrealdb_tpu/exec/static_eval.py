"""Static evaluation of literal-only expressions (no datastore needed).

Used by the test harness (parsing expected values) and literal kinds.
"""

from __future__ import annotations

from surrealdb_tpu.err import SdbError
from surrealdb_tpu.expr.ast import (
    ArrayExpr,
    SetExpr,
    Binary,
    Idiom,
    Literal,
    ObjectExpr,
    PField,
    Prefix,
    RangeExpr,
    RecordIdLit,
    RegexLit,
)
from surrealdb_tpu.val import NONE, Range, RecordId, Regex


def static_value(node):
    if isinstance(node, Literal):
        return node.value
    if isinstance(node, ArrayExpr):
        return [static_value(x) for x in node.items]
    if isinstance(node, ObjectExpr):
        out = {k: static_value(v) for k, v in node.items}
        if len(out) == 2 and "type" in out and (
            "coordinates" in out or "geometries" in out
        ):
            from surrealdb_tpu.exec.coerce import object_to_geometry

            g = object_to_geometry(out)
            if g is not None:
                return g
        return out
    if isinstance(node, SetExpr):
        from surrealdb_tpu.val import SSet

        return SSet([static_value(x) for x in node.items])
    if isinstance(node, RecordIdLit):
        idv = node.id
        if isinstance(idv, RangeExpr):
            return RecordId(node.tb, static_value_range(idv))
        return RecordId(node.tb, static_value(idv))
    if isinstance(node, RangeExpr):
        return static_value_range(node)
    if isinstance(node, Prefix) and node.op == "-":
        v = static_value(node.expr)
        return -v
    if isinstance(node, Prefix) and node.op == "+":
        return static_value(node.expr)
    if isinstance(node, RegexLit):
        return Regex(node.pattern)
    if isinstance(node, Idiom) and len(node.parts) == 1 and isinstance(
        node.parts[0], PField
    ):
        # bare word in a static context = string-ish identity (rare)
        return node.parts[0].name
    if isinstance(node, Binary):
        from surrealdb_tpu.exec.operators import binary_op

        return binary_op(node.op, static_value(node.lhs), static_value(node.rhs))
    from surrealdb_tpu.expr.ast import FunctionCall as _FC

    if isinstance(node, _FC) and node.name == "__point__":
        from surrealdb_tpu.val import Geometry

        return Geometry(
            "Point",
            (float(static_value(node.args[0])), float(static_value(node.args[1]))),
        )
    raise SdbError(f"not a static value: {node!r}")


def static_value_range(node: RangeExpr):
    beg = static_value(node.beg) if node.beg is not None else NONE
    end = static_value(node.end) if node.end is not None else NONE
    return Range(beg, end, node.beg_incl, node.end_incl)


def static_value_maybe(v):
    """Kind.literal payloads may be raw values or AST nodes."""
    from surrealdb_tpu.expr.ast import Node

    if isinstance(v, Node):
        return static_value(v)
    return v
